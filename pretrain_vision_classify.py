"""Vision (ViT) classification pretraining entry point.

Parity with /root/reference/pretrain_vision_classify.py: ViT backbone +
classification head on image/label batches (synthetic stream unless a
loader is wired in).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.config.arguments import build_parser, configs_from_args, parse_args
from megatronapp_tpu.models.vision import (
    VitSpec, init_vit_params, vit_classification_loss, vit_config,
)
from megatronapp_tpu.parallel.mesh import build_mesh
from megatronapp_tpu.training.optimizer import get_optimizer
from megatronapp_tpu.training.train import reshape_global_batch
from megatronapp_tpu.training.train_state import setup_train_state
from megatronapp_tpu.training.train_step import make_train_step


def main(argv=None):
    ap = build_parser("pretrain_vision_classify (megatronapp-tpu)")
    ap.add_argument("--img-size", type=int, default=224)
    ap.add_argument("--patch-dim", type=int, default=16)
    ap.add_argument("--num-classes", type=int, default=1000)
    args = parse_args(ap, argv)
    gpt_cfg, parallel, training, opt_cfg = configs_from_args(args)
    spec = VitSpec(image_size=args.img_size, patch_size=args.patch_dim,
                   num_classes=args.num_classes)
    import dataclasses
    cfg = vit_config(**{f.name: getattr(gpt_cfg, f.name)
                        for f in dataclasses.fields(gpt_cfg)
                        if f.name not in ("position_embedding",
                                          "attn_mask_type",
                                          "add_qkv_bias",
                                          "max_position_embeddings")},
                     max_position_embeddings=1 + spec.num_patches)

    ctx = build_mesh(parallel)
    optimizer = get_optimizer(opt_cfg, training.train_iters)
    state, shardings, _ = setup_train_state(
        jax.random.PRNGKey(training.seed),
        lambda k: init_vit_params(k, cfg, spec), optimizer, ctx)

    def loss_fn(p, micro):
        return vit_classification_loss(p, micro["images"],
                                       micro["labels"], cfg, spec, ctx=ctx)

    step_fn = make_train_step(loss_fn, optimizer, opt_cfg, ctx, shardings,
                              training.train_iters)
    num_micro = training.num_microbatches(ctx.dp * ctx.ep)

    batch_iter = None
    if args.data_path:
        from megatronapp_tpu.data.image_folder import (
            ClassificationTransform, image_batches, load_folder,
        )
        ds = load_folder(args.data_path)
        if len(ds.classes) > spec.num_classes:
            # Out-of-range labels would be silently clamped by the CE
            # gather under jit — fail loudly instead.
            raise SystemExit(
                f"--num-classes {spec.num_classes} < {len(ds.classes)} "
                f"class directories in {args.data_path}")
        batch_iter = image_batches(
            ds, training.global_batch_size,
            ClassificationTransform(spec.image_size, train=True,
                                    seed=training.seed),
            seed=training.seed)

    rng = np.random.default_rng(training.seed)
    losses = []
    t0 = time.perf_counter()
    with ctx.mesh:
        for it in range(training.train_iters):
            if batch_iter is not None:
                batch = next(batch_iter)
            else:
                batch = {
                    "images": rng.normal(size=(
                        training.global_batch_size, spec.image_size,
                        spec.image_size, spec.num_channels)
                    ).astype(np.float32),
                    "labels": rng.integers(
                        0, spec.num_classes,
                        training.global_batch_size).astype(np.int32),
                }
            batch = reshape_global_batch(batch, num_micro)
            state, metrics = step_fn(state, batch)
            if (it + 1) % training.log_interval == 0 or \
                    it + 1 == training.train_iters:
                metrics = jax.device_get(metrics)
                losses.append(float(metrics["loss"]))
                print(f"iter {it+1:6d}/{training.train_iters} | "
                      f"loss {float(metrics['loss']):.4f} | "
                      f"acc {float(metrics['accuracy']):.3f}")
    dt = time.perf_counter() - t0
    print(f"done: final loss {losses[-1]:.4f}, "
          f"{training.train_iters * training.global_batch_size / dt:.1f} "
          f"img/s")


if __name__ == "__main__":
    main()
