"""ICT (Inverse Cloze Task) biencoder pretraining entry point.

Parity with /root/reference/pretrain_ict.py: BERT-style query/context
towers trained with an in-batch retrieval softmax (diagonal labels) over
blocks built by the native build_blocks_mapping. --data-path must point at
a sentence-split corpus (tools/preprocess_data.py --split-sentences) and
--titles-data-path at a one-title-per-document companion; without them a
synthetic lexical-overlap stream is used.
"""

import time

import jax

from megatronapp_tpu.config.arguments import build_parser, configs_from_args, parse_args
from megatronapp_tpu.models.bert import bert_config
from megatronapp_tpu.models.biencoder import ict_loss, init_biencoder_params
from megatronapp_tpu.parallel.mesh import build_mesh
from megatronapp_tpu.training.optimizer import get_optimizer
from megatronapp_tpu.training.train import reshape_global_batch
from megatronapp_tpu.training.train_state import setup_train_state
from megatronapp_tpu.training.train_step import make_train_step


def main(argv=None):
    ap = build_parser("pretrain_ict (megatronapp-tpu)")
    ap.add_argument("--titles-data-path", type=str, default=None)
    ap.add_argument("--query-in-block-prob", type=float, default=0.1)
    ap.add_argument("--use-one-sent-docs", action="store_true")
    ap.add_argument("--retriever-score-scaling", action="store_true")
    ap.add_argument("--biencoder-shared-query-context-model",
                    action="store_true")
    args = parse_args(ap, argv)
    gpt_cfg, parallel, training, opt_cfg = configs_from_args(args)
    import dataclasses
    cfg = bert_config(**{f.name: getattr(gpt_cfg, f.name)
                         for f in dataclasses.fields(gpt_cfg)
                         if f.name not in ("position_embedding",
                                           "attn_mask_type",
                                           "add_qkv_bias")})

    ctx = build_mesh(parallel)
    optimizer = get_optimizer(opt_cfg, training.train_iters)
    state, shardings, _ = setup_train_state(
        jax.random.PRNGKey(training.seed),
        lambda k: init_biencoder_params(
            k, cfg, shared=args.biencoder_shared_query_context_model),
        optimizer, ctx)

    def loss_fn(params, micro):
        return ict_loss(params, micro, cfg, ctx=ctx,
                        score_scaling=args.retriever_score_scaling)

    step_fn = make_train_step(loss_fn, optimizer, opt_cfg, ctx, shardings,
                              training.train_iters)
    num_micro = training.num_microbatches(ctx.dp * ctx.ep)

    batch_iter = None
    if args.data_path:
        if not args.titles_data_path:
            raise SystemExit("--titles-data-path is required with "
                             "--data-path (one title per document)")
        from megatronapp_tpu.data.ict_dataset import ICTDataset, ict_batches
        from megatronapp_tpu.data.indexed_dataset import IndexedDataset
        dataset = ICTDataset(
            IndexedDataset(args.data_path),
            IndexedDataset(args.titles_data_path),
            seq_length=training.seq_length,
            num_epochs=max(1, training.train_iters *
                           training.global_batch_size // 1000 + 1),
            query_in_block_prob=args.query_in_block_prob,
            seed=training.seed,
            use_one_sent_blocks=args.use_one_sent_docs)
        batch_iter = ict_batches(dataset, training.global_batch_size)
        print(f"ICT corpus: {len(dataset)} blocks from {args.data_path}")

    t0 = time.perf_counter()
    last = None
    with ctx.mesh:
        for it in range(training.train_iters):
            if batch_iter is not None:
                batch = next(batch_iter)
            else:
                from megatronapp_tpu.data.ict_dataset import mock_ict_batch
                batch = mock_ict_batch(it, training.global_batch_size,
                                       training.seq_length, cfg.vocab_size)
            batch = reshape_global_batch(batch, num_micro)
            state, metrics = step_fn(state, batch)
            if (it + 1) % training.log_interval == 0 or \
                    it + 1 == training.train_iters:
                metrics = jax.device_get(metrics)
                last = metrics
                print(f"iter {it+1:6d}/{training.train_iters} | "
                      f"loss {float(metrics['loss']):.4f} | "
                      f"top1 {float(metrics.get('top1_acc', 0)):.1f}%")
    dt = time.perf_counter() - t0
    print(f"done: final loss {float(last['loss']):.4f} in {dt:.1f}s")


if __name__ == "__main__":
    main()
