"""Benchmark: GPT-2 125M-class training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric of record (BASELINE.md): tokens/sec/chip; vs_baseline is MFU relative
to the 40% MFU north-star target (reference publishes no absolute numbers —
BASELINE.json published: {}).

Robustness (round-1 postmortem): the tunneled axon TPU backend can hang
indefinitely (even tiny matmuls never return), which round 1 turned into a
whole-round rc=1 with no perf artifact.  The benchmark therefore runs in a
watchdog structure:

  parent (no jax import)  --spawns-->  probe child (tiny matmul, hard timeout)
                          --spawns-->  bench child (the real measurement)

Each child is retried with backoff on timeout/crash; if everything fails the
parent still exits 0 with a diagnostic JSON line so the driver records
*something* actionable instead of a traceback.
"""

import json
import os
import subprocess
import sys
import time

PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "90"))
BENCH_TIMEOUT_S = 600   # two attention impls = two compiles + windows
LOCAL_TIMEOUT_S = 300   # CPU micro-bench fallback (tiny model, compiles)
ATTEMPTS = int(os.environ.get("BENCH_ATTEMPTS", "4"))
BACKOFF_S = (20, 60, 180)

# Fail-fast (round-5 postmortem: 4 x 90 s probe hangs produced no usable
# record): a probe TIMEOUT means the tunnel is in its multi-hour hang mode
# — retrying with backoff never helps within a round, so bail to the CPU
# fallback after the first one. Probe CRASHES (rc != 0) still retry.
CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                  + " --xla_force_host_platform_device_count=8").strip(),
}

# Every successful measurement is persisted here (and committed), so a
# tunnel hang at end-of-round reports the last real number (stale-flagged)
# instead of 0.0 — round-2 postmortem: three 90s probe timeouts produced an
# official record of zero while PERF.md held a real 82k tok/s measurement.
LAST_GOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "bench_last_good.json")


def _save_last_good(res: dict):
    rec = dict(res)
    rec.setdefault("extra", {})["measured_at"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        with open(LAST_GOOD_PATH, "w") as f:
            json.dump(rec, f, indent=1)
    except OSError:
        pass
    return rec


def _load_last_good():
    try:
        with open(LAST_GOOD_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _run_child(mode: str, timeout_s: int, extra_env=None):
    """Run this file in a subprocess; return parsed JSON from its last
    stdout line, or an error dict."""
    env = dict(os.environ, **(extra_env or {}))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), mode],
            capture_output=True, text=True, timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        # `timeout: True` is the structured fail-fast signal — don't key
        # behavior off the human-readable message.
        return {"ok": False, "timeout": True,
                "error": f"{mode} timed out after {timeout_s}s "
                         "(tunnel hang)"}
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    if proc.returncode != 0 or not lines:
        tail = (proc.stderr or "").strip().splitlines()[-8:]
        return {"ok": False,
                "error": f"{mode} rc={proc.returncode}: " + " | ".join(tail)}
    try:
        return json.loads(lines[-1])
    except json.JSONDecodeError:
        return {"ok": False, "error": f"{mode} emitted non-JSON: {lines[-1][:200]}"}


def _tp_overlap_hook():
    """Overlapped-vs-GSPMD A/B (tools/tp_overlap_benchmark.py) on the CPU
    mesh — cheap, attached to every round's record so the tp-overlap step
    time is tracked alongside the headline metric."""
    if os.environ.get("BENCH_TP_OVERLAP", "1") != "1":
        return None
    r = _run_child("--tp-overlap", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("fwd") else None


def _cp_a2a_hook():
    """Ring-attention + MoE chunked-a2a A/B (tools/cp_a2a_benchmark.py) on
    the CPU mesh — attached to every round's record like the tp-overlap
    hook so the cp/ep overlap paths are tracked round over round."""
    if os.environ.get("BENCH_CP_A2A", "1") != "1":
        return None
    r = _run_child("--cp-a2a", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("ring_attention") else None


def _paged_kv_hook():
    """Paged-vs-dense serving A/B (tools/paged_kv_benchmark.py) on the
    CPU backend — decode throughput, memory footprint, and prefix-cache
    hit rate tracked round over round like the other hooks."""
    if os.environ.get("BENCH_PAGED_KV", "1") != "1":
        return None
    r = _run_child("--paged-kv", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("decode") else None


def _spec_decode_hook():
    """Speculative-vs-plain serving A/B (tools/spec_decode_benchmark.py)
    on the CPU backend — acceptance rate and tokens/step per proposer
    tracked round over round like the other hooks."""
    if os.environ.get("BENCH_SPEC_DECODE", "1") != "1":
        return None
    r = _run_child("--spec-decode", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("ngram") else None


def _kv_quant_hook():
    """int8-vs-bf16 KV-pool serving A/B (tools/kv_quant_benchmark.py)
    on the CPU backend — resident pool bytes, sessions-at-capacity,
    tokens/s, logits parity, and spec-decode acceptance delta tracked
    round over round like the other hooks."""
    if os.environ.get("BENCH_KV_QUANT", "1") != "1":
        return None
    r = _run_child("--kv-quant", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("memory_decode") else None


def _kv_spill_hook():
    """KV capacity tiers A/B (tools/kv_spill_benchmark.py) on the CPU
    backend — resident sessions at a fixed HBM block budget with vs
    without the host-RAM spill tier (gate >= 2x, token-exact resume),
    and the fleet-global prefix store's hit-rate/chunks-avoided vs
    the storeless baseline, tracked round over round like the other
    hooks."""
    if os.environ.get("BENCH_KV_SPILL", "1") != "1":
        return None
    r = _run_child("--kv-spill", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("capacity") else None


def _megakernel_hook():
    """Megakernel decode + dispatch levers A/B
    (tools/megakernel_benchmark.py) on the CPU backend — decode
    dispatch-count ratio (plain vs fused, bf16 + int8), stream parity,
    and the head-fold + scan-unroll fwd+bwd wall ratio tracked round
    over round like the other hooks."""
    if os.environ.get("BENCH_MEGAKERNEL", "1") != "1":
        return None
    r = _run_child("--megakernel", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("decode") else None


def _disagg_hook():
    """Colocated-vs-disaggregated serving A/B
    (tools/disagg_benchmark.py) on the CPU sub-meshes — decode p99
    token-interval under a long in-flight prefill, tokens/s ratio, and
    the stream-parity pin tracked round over round like the other
    hooks."""
    if os.environ.get("BENCH_DISAGG", "1") != "1":
        return None
    r = _run_child("--disagg", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("disagg") else None


def _telemetry_hook():
    """Telemetry-overhead A/B (tools/telemetry_benchmark.py) on the CPU
    backend — driver-soak tokens/s with the metrics registry + request
    tracer on vs off (gate >= 0.95) and the disabled-path ns/call
    microbench tracked round over round like the other hooks."""
    if os.environ.get("BENCH_TELEMETRY", "1") != "1":
        return None
    r = _run_child("--telemetry", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("telemetry") else None


def _pp_tp_hook():
    """tp-sharded-vs-replicated pipeline stage body A/B
    (tools/pp_tp_benchmark.py) on the CPU mesh — fwd/fwd+bwd speedup and
    the parity pins tracked round over round like the other hooks."""
    if os.environ.get("BENCH_PP_TP", "1") != "1":
        return None
    r = _run_child("--pp-tp", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("fwd") else None


def _dist_opt_hook():
    """ZeRO-1 distributed optimizer A/B (tools/dist_opt_benchmark.py) on
    a dp2 CPU mesh — per-rank m/v state bytes, step-time ratio vs the
    replicated baseline, and fp32/bf16-moments loss parity tracked round
    over round like the other hooks."""
    if os.environ.get("BENCH_DIST_OPT", "1") != "1":
        return None
    r = _run_child("--dist-opt", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("memory") else None


def _fleet_hook():
    """Affinity-router-vs-round-robin fleet A/B
    (tools/fleet_benchmark.py) on the CPU backend — fleet prefix-cache
    hit rate, decode p99, live-migration stream parity tracked round
    over round like the other hooks."""
    if os.environ.get("BENCH_FLEET", "1") != "1":
        return None
    r = _run_child("--fleet", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("affinity") else None


def _fleet_proc_hook():
    """Cross-process fleet A/B (tools/fleet_proc_benchmark.py) on the
    CPU backend — stream parity vs the in-process fleet on the same
    seeded loadgen trace, exact RPC frame/byte accounting, forced
    cross-process migration parity, histogram-backed SLO attainment,
    and the merged multi-process Chrome trace gate tracked round over
    round like the other hooks."""
    if os.environ.get("BENCH_FLEET_PROC", "1") != "1":
        return None
    r = _run_child("--fleet-proc", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("cross_process") else None


def _lora_hook():
    """Multi-tenant batched-LoRA serving A/B (tools/lora_benchmark.py)
    on the CPU backend — batched-vs-serial tokens/s at 8 distinct
    adapters (gate >= 1.5x with token-exact streams), the rank-exact
    HBM bank byte pin, and the zero-B bitwise parity gate tracked
    round over round like the other hooks."""
    if os.environ.get("BENCH_LORA", "1") != "1":
        return None
    r = _run_child("--lora", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("batched") else None


def _pipeline_hook():
    """Zero-bubble-vs-1F1B pipeline schedule A/B
    (tools/pipeline_benchmark.py) on the CPU mesh — the simulated-
    timeline bubble-fraction gate (zb strictly below 1F1B at the bench
    shapes incl. the 2x-slow stage), the 2-step pp2 train loss-parity
    pin, and the pp2 x cp2 x tp2 compiled FLOPs ratio tracked round
    over round like the other hooks."""
    if os.environ.get("BENCH_PIPELINE", "1") != "1":
        return None
    r = _run_child("--pipeline", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("bubble") else None


def _fp8_hook():
    """fp8 end-to-end A/B (tools/fp8_benchmark.py) on the CPU backend —
    fp8-vs-bf16 training loss parity on the tp2 rings, the compiled
    collective-permute byte ratio, and the fp8 KV-pool byte/parity
    gates tracked round over round like the other hooks."""
    if os.environ.get("BENCH_FP8", "1") != "1":
        return None
    r = _run_child("--fp8", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    return r if r.get("train") else None


def _attach_overlap_hooks(res):
    """Attach the tp-overlap, cp/a2a, pp×tp, dist-opt, paged-kv, and
    spec-decode A/B results to a round record."""
    tpo = _tp_overlap_hook()
    if tpo:
        res.setdefault("extra", {})["tp_overlap"] = tpo
    cpa = _cp_a2a_hook()
    if cpa:
        res.setdefault("extra", {})["cp_a2a"] = cpa
    ppt = _pp_tp_hook()
    if ppt:
        res.setdefault("extra", {})["pp_tp_overlap"] = ppt
    dop = _dist_opt_hook()
    if dop:
        res.setdefault("extra", {})["dist_opt"] = dop
    pkv = _paged_kv_hook()
    if pkv:
        res.setdefault("extra", {})["paged_kv"] = pkv
    spd = _spec_decode_hook()
    if spd:
        res.setdefault("extra", {})["spec_decode"] = spd
    dsg = _disagg_hook()
    if dsg:
        res.setdefault("extra", {})["disagg"] = dsg
    kvq = _kv_quant_hook()
    if kvq:
        res.setdefault("extra", {})["kv_quant"] = kvq
    kvs = _kv_spill_hook()
    if kvs:
        res.setdefault("extra", {})["kv_spill"] = kvs
    mkd = _megakernel_hook()
    if mkd:
        res.setdefault("extra", {})["megakernel"] = mkd
    tel = _telemetry_hook()
    if tel:
        res.setdefault("extra", {})["telemetry"] = tel
    f8 = _fp8_hook()
    if f8:
        res.setdefault("extra", {})["fp8"] = f8
    flt = _fleet_hook()
    if flt:
        res.setdefault("extra", {})["fleet"] = flt
    fpr = _fleet_proc_hook()
    if fpr:
        res.setdefault("extra", {})["fleet_proc"] = fpr
    ppl = _pipeline_hook()
    if ppl:
        res.setdefault("extra", {})["pipeline"] = ppl
    lra = _lora_hook()
    if lra:
        res.setdefault("extra", {})["lora"] = lra
    return res


def _cpu_fallback_record(history):
    """Real measurement on the CPU backend (tiny GPT) so a dead tunnel
    round still emits a nonzero metric instead of value: 0.0."""
    r = _run_child("--local-bench", LOCAL_TIMEOUT_S, extra_env=CPU_ENV)
    if r.get("value"):
        r["environment"] = "cpu-fallback"
        r.setdefault("extra", {})["environment"] = "cpu-fallback"
        r["extra"]["history"] = history
    return r if r.get("value") else None


def parent_main(local_only: bool = False):
    history = []
    if local_only:
        res = _cpu_fallback_record(["--local requested"])
        if res is None:
            res = {"metric": "gpt_tiny_tokens_per_sec_cpu", "value": 0.0,
                   "unit": "tokens/s", "vs_baseline": 0.0,
                   "extra": {"error": "local CPU bench failed"}}
        res = _attach_overlap_hooks(res)
        print(json.dumps(res))
        return
    for attempt in range(ATTEMPTS):
        if attempt:
            time.sleep(BACKOFF_S[min(attempt - 1, len(BACKOFF_S) - 1)])
        probe = _run_child("--probe", PROBE_TIMEOUT_S)
        if not probe.get("ok"):
            history.append(f"attempt {attempt+1} probe: {probe.get('error')}")
            if probe.get("timeout"):
                # Tunnel hang mode: no amount of backoff heals it within a
                # round — fail fast to the fallback chain.
                history.append("probe timeout -> fail-fast to fallback")
                break
            continue
        # Each attention impl runs as its OWN watchdogged child: a hang
        # in one cannot destroy the other's measurement (the tunnel
        # hangs rather than raising), and each gets the full budget.
        by_impl = {}
        for impl in ("auto", "pallas"):
            r = _run_child("--bench", BENCH_TIMEOUT_S,
                           extra_env={"BENCH_ATTENTION_IMPL": impl})
            if r.get("metric") and r.get("value") is not None:
                by_impl[impl] = r
            else:
                history.append(
                    f"attempt {attempt+1} bench[{impl}]: {r.get('error')}")
        if by_impl:
            best = max(by_impl, key=lambda k: by_impl[k]["value"])
            res = by_impl[best]
            res.setdefault("extra", {})["probe_s"] = probe.get("elapsed")
            res["extra"]["attention_impl"] = best
            res["extra"]["tok_s_by_impl"] = {
                k: v["value"] for k, v in by_impl.items()}
            res = _save_last_good(res)
            res = _attach_overlap_hooks(res)
            print(json.dumps(res))
            return
    # All attempts failed (tunnel hang or crash): report the persisted
    # last-good measurement, flagged stale, instead of 0.0.  `history`
    # carries the per-attempt errors for diagnosis; a fresh CPU
    # micro-bench rides along so the round still has a live signal.
    cpu = _cpu_fallback_record(history)
    tpo = _tp_overlap_hook()
    cpa = _cp_a2a_hook()
    ppt = _pp_tp_hook()
    dop = _dist_opt_hook()
    pkv = _paged_kv_hook()
    spd = _spec_decode_hook()
    kvq = _kv_quant_hook()
    mkd = _megakernel_hook()
    tel = _telemetry_hook()
    f8 = _fp8_hook()
    flt = _fleet_hook()
    fpr = _fleet_proc_hook()
    ppl = _pipeline_hook()
    last = _load_last_good()
    if last is not None:
        # Top-level `stale` so the consumer can verifiably distinguish this
        # from a live measurement (the value itself is the persisted
        # last-good number, kept at top level per the driver contract).
        last["stale"] = True
        last.setdefault("extra", {})["stale"] = True
        last["extra"]["stale_reason"] = ("live benchmark could not run this "
                                         "invocation; value is the persisted "
                                         "last-good measurement from "
                                         "extra.measured_at")
        last["extra"]["history"] = history
        if cpu:
            last["extra"]["cpu_fallback"] = {
                "metric": cpu["metric"], "value": cpu["value"],
                "unit": cpu["unit"], "extra": cpu.get("extra", {})}
        if tpo:
            last["extra"]["tp_overlap"] = tpo
        if cpa:
            last["extra"]["cp_a2a"] = cpa
        if ppt:
            last["extra"]["pp_tp_overlap"] = ppt
        if dop:
            last["extra"]["dist_opt"] = dop
        if pkv:
            last["extra"]["paged_kv"] = pkv
        if spd:
            last["extra"]["spec_decode"] = spd
        if kvq:
            last["extra"]["kv_quant"] = kvq
        if mkd:
            last["extra"]["megakernel"] = mkd
        if tel:
            last["extra"]["telemetry"] = tel
        if f8:
            last["extra"]["fp8"] = f8
        if flt:
            last["extra"]["fleet"] = flt
        if fpr:
            last["extra"]["fleet_proc"] = fpr
        if ppl:
            last["extra"]["pipeline"] = ppl
        print(json.dumps(last))
        return
    if cpu:
        # No last-good chip number exists: the CPU micro-bench IS the
        # round's metric — real and nonzero, tagged so consumers never
        # compare it against chip rounds.
        if tpo:
            cpu.setdefault("extra", {})["tp_overlap"] = tpo
        if cpa:
            cpu.setdefault("extra", {})["cp_a2a"] = cpa
        if ppt:
            cpu.setdefault("extra", {})["pp_tp_overlap"] = ppt
        if dop:
            cpu.setdefault("extra", {})["dist_opt"] = dop
        if pkv:
            cpu.setdefault("extra", {})["paged_kv"] = pkv
        if spd:
            cpu.setdefault("extra", {})["spec_decode"] = spd
        if kvq:
            cpu.setdefault("extra", {})["kv_quant"] = kvq
        if mkd:
            cpu.setdefault("extra", {})["megakernel"] = mkd
        if tel:
            cpu.setdefault("extra", {})["telemetry"] = tel
        if f8:
            cpu.setdefault("extra", {})["fp8"] = f8
        if flt:
            cpu.setdefault("extra", {})["fleet"] = flt
        if fpr:
            cpu.setdefault("extra", {})["fleet_proc"] = fpr
        if ppl:
            cpu.setdefault("extra", {})["pipeline"] = ppl
        print(json.dumps(cpu))
        return
    print(json.dumps({
        "metric": "gpt2_125m_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "extra": {"error": "benchmark could not run and no last-good record "
                           "exists; see history and PERF.md",
                  "history": history},
    }))


def local_bench_main():
    """CPU micro-bench (fallback child; JAX_PLATFORMS=cpu set by the
    parent BEFORE this process imports jax). Tiny GPT, differential
    timing — seconds, not minutes, and always a real nonzero number."""
    import jax
    import numpy as np

    from megatronapp_tpu.config.parallel_config import ParallelConfig
    from megatronapp_tpu.config.training_config import OptimizerConfig
    from megatronapp_tpu.config.transformer_config import TransformerConfig
    from megatronapp_tpu.models.gpt import gpt_loss, init_gpt_params
    from megatronapp_tpu.parallel.mesh import build_mesh
    from megatronapp_tpu.training.optimizer import get_optimizer
    from megatronapp_tpu.training.train_state import setup_train_state
    from megatronapp_tpu.training.train_step import make_train_step

    cfg = TransformerConfig(
        num_layers=2, hidden_size=128, num_attention_heads=4,
        vocab_size=2048, max_position_embeddings=256,
        remat_policy="selective")
    seq, micro_bs = 128, 2
    ctx = build_mesh(ParallelConfig(), devices=jax.devices()[:1])
    opt_cfg = OptimizerConfig(lr=1e-4)
    optimizer = get_optimizer(opt_cfg, 100)
    state, shardings, _ = setup_train_state(
        jax.random.PRNGKey(0), lambda k: init_gpt_params(k, cfg),
        optimizer, ctx)

    def loss_fn(params, micro):
        return gpt_loss(params, micro["tokens"], micro["labels"],
                        micro["loss_mask"], cfg)

    step_fn = make_train_step(loss_fn, optimizer, opt_cfg, ctx, shardings,
                              100, check_nan=False)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          (1, micro_bs, seq)).astype(np.int32)
    batch = {
        "tokens": tokens,
        "labels": np.roll(tokens, -1, axis=-1),
        "loss_mask": np.ones_like(tokens, dtype=np.float32),
        "position_ids": np.tile(np.arange(seq, dtype=np.int32),
                                (1, micro_bs, 1)),
    }
    with ctx.mesh:
        state, metrics = step_fn(state, batch)  # compile + warmup
        _ = jax.device_get(metrics["loss"])
        times = {}
        for n_steps in (2, 6):
            t0 = time.perf_counter()
            for _ in range(n_steps):
                state, metrics = step_fn(state, batch)
            _ = jax.device_get(metrics["loss"])
            times[n_steps] = time.perf_counter() - t0
        dt = times[6] - times[2]
    tok_per_sec = micro_bs * seq * 4 / dt
    print(json.dumps({
        "metric": "gpt_tiny_tokens_per_sec_cpu",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "environment": "cpu-fallback",
        "extra": {"environment": "cpu-fallback",
                  "device": str(jax.devices()[0]),
                  "step_ms": round(dt / 4 * 1e3, 2),
                  "model": "gpt-tiny 2L/128H", "seq": seq,
                  "note": "chip unreachable this round; tiny-GPT CPU "
                          "measurement so the round has a live nonzero "
                          "signal (NOT comparable to chip tokens/s)"},
    }))


def tp_overlap_main():
    """tp-comm-overlap A/B child (CPU mesh env set by the parent)."""
    from tools.tp_overlap_benchmark import run
    print(json.dumps(run(tp=4, batch=2, seq=256, hidden=128, ffn=512,
                         iters=5, warmup=1)))


def cp_a2a_main():
    """cp ring + moe a2a overlap A/B child (CPU mesh env set by parent)."""
    from tools.cp_a2a_benchmark import run
    print(json.dumps(run(cp=4, ep=4, batch=2, seq=256, heads=8, kv_heads=4,
                         head_dim=32, iters=5, warmup=1)))


def pp_tp_main():
    """tp-sharded pipeline stage body A/B child (CPU mesh env set by the
    parent)."""
    from tools.pp_tp_benchmark import run
    print(json.dumps(run(tp=2, pp=2, batch=2, seq=64, hidden=128,
                         layers=4, microbatches=4, iters=9, warmup=2)))


def pipeline_main():
    """Zero-bubble schedule + pp x cp x tp composition A/B child (CPU
    mesh env set by the parent)."""
    from tools.pipeline_benchmark import run
    print(json.dumps(run(steps=2)))


def dist_opt_main():
    """ZeRO-1 distributed-optimizer A/B child (CPU mesh env set by the
    parent). hidden 256 / seq 32: the weight update is the subsystem
    under test — keep its share of the step large enough that the
    sharded-vs-replicated ratio is signal, not scheduler noise."""
    from tools.dist_opt_benchmark import run
    print(json.dumps(run(dp=2, batch=2, seq=32, hidden=256, layers=2,
                         iters=9, warmup=2, train_steps=6)))


def paged_kv_main():
    """paged-vs-dense serving A/B child (CPU env set by the parent)."""
    from tools.paged_kv_benchmark import run
    print(json.dumps(run(max_batch=4, block_size=8, max_new=6,
                         n_requests=6, prefix_len=48)))


def spec_decode_main():
    """speculative-vs-plain serving A/B child (CPU env set by parent)."""
    from tools.spec_decode_benchmark import run
    print(json.dumps(run(n_requests=4, motif_len=12, repeats=4,
                         max_new=24, spec_k=4)))


def kv_quant_main():
    """int8-vs-bf16 KV pool A/B child (CPU env set by the parent)."""
    from tools.kv_quant_benchmark import run
    print(json.dumps(run(max_batch=4, block_size=8, max_new=6,
                         spec_k=4)))


def kv_spill_main():
    """KV capacity tiers A/B child (CPU env set by the parent)."""
    from tools.kv_spill_benchmark import run
    print(json.dumps(run(num_blocks=8, sessions=6, spill_mb=4.0,
                         dtypes=("bf16",))))


def megakernel_main():
    """megakernel decode + dispatch levers A/B child (CPU env set by
    the parent)."""
    from tools.megakernel_benchmark import run
    print(json.dumps(run(max_new=6, scan_unroll=2, iters=6)))


def telemetry_main():
    """telemetry on-vs-off driver-soak A/B child (CPU env set by the
    parent)."""
    from tools.telemetry_benchmark import run
    print(json.dumps(run(n_requests=6, prompt_len=16, max_new=24,
                         repeats=3)))


def fp8_main():
    """fp8 training + KV A/B child (CPU env set by the parent)."""
    from tools.fp8_benchmark import run
    print(json.dumps(run(iters=6, max_new=6)))


def fleet_main():
    """affinity-router-vs-round-robin fleet A/B child (CPU env set by
    the parent)."""
    from tools.fleet_benchmark import run
    print(json.dumps(run(n_replicas=2, groups=4, followers=3,
                         prefix_len=32, max_new=8)))


def fleet_proc_main():
    """cross-process fleet A/B child (CPU env set by the parent):
    2 real replica worker processes replay the seeded loadgen trace
    against the in-process fleet baseline."""
    from tools.fleet_proc_benchmark import run
    print(json.dumps(run(n_replicas=2, requests=10, tenants=2,
                         max_new=8)))


def lora_main():
    """batched-LoRA serving A/B child (CPU env set by the parent)."""
    from tools.lora_benchmark import run
    print(json.dumps(run(n_adapters=8, rank=8, max_new=8)))


def disagg_main():
    """colocated-vs-disaggregated serving A/B child (CPU env set by the
    parent; virtual sub-mesh devices set here, pre-jax-import)."""
    from tools.disagg_benchmark import _ensure_devices, run
    _ensure_devices(8)
    print(json.dumps(run(n_short=3, short_new=48, long_len=192,
                         prefill_chunk=16)))


def probe_main():
    """Tiny device op to verify the backend is alive."""
    t0 = time.time()
    import jax
    import jax.numpy as jnp
    d = jax.devices()[0]
    x = jnp.ones((128, 128))
    s = float(jax.device_get(jnp.dot(x, x)).sum())
    assert s == 128.0 * 128 * 128
    print(json.dumps({"ok": True, "device": str(d),
                      "elapsed": round(time.time() - t0, 1)}))


def _measure_impl(attention_impl: str):
    """tokens/s for one attention implementation (differential timing)."""
    import jax
    import numpy as np

    from megatronapp_tpu.config.parallel_config import ParallelConfig
    from megatronapp_tpu.config.training_config import OptimizerConfig
    from megatronapp_tpu.config.transformer_config import TransformerConfig
    from megatronapp_tpu.models.gpt import gpt_loss, init_gpt_params
    from megatronapp_tpu.parallel.mesh import build_mesh
    from megatronapp_tpu.training.optimizer import get_optimizer
    from megatronapp_tpu.training.train_state import setup_train_state
    from megatronapp_tpu.training.train_step import make_train_step

    # GPT-2 125M (reference run_single_gpt.sh class model).
    cfg = TransformerConfig(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=50304, max_position_embeddings=1024,
        remat_policy="selective", attention_impl=attention_impl,
    )
    seq, micro_bs, n_micro = 1024, 4, 1
    par = ParallelConfig()
    ctx = build_mesh(par, devices=jax.devices()[:1])

    opt_cfg = OptimizerConfig(lr=1e-4)
    optimizer = get_optimizer(opt_cfg, 100)
    state, shardings, _ = setup_train_state(
        jax.random.PRNGKey(0), lambda k: init_gpt_params(k, cfg),
        optimizer, ctx)

    def loss_fn(params, micro):
        loss, m = gpt_loss(params, micro["tokens"], micro["labels"],
                           micro["loss_mask"], cfg)
        return loss, m

    step_fn = make_train_step(loss_fn, optimizer, opt_cfg, ctx, shardings,
                              100, check_nan=False)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size,
                          (n_micro, micro_bs, seq)).astype(np.int32)
    batch = {
        "tokens": tokens,
        "labels": np.roll(tokens, -1, axis=-1),
        "loss_mask": np.ones_like(tokens, dtype=np.float32),
        "position_ids": np.tile(np.arange(seq, dtype=np.int32),
                                (n_micro, micro_bs, 1)),
    }

    with ctx.mesh:
        # Differential timing: the tunneled platform's block_until_ready does
        # not wait, and a device_get round-trip has fixed latency; timing two
        # windows and differencing cancels the constant.
        state, metrics = step_fn(state, batch)  # compile + warmup
        _ = jax.device_get(metrics["loss"])
        times = {}
        for n_steps in (5, 25):
            t0 = time.perf_counter()
            for _ in range(n_steps):
                state, metrics = step_fn(state, batch)
            _ = jax.device_get(metrics["loss"])
            times[n_steps] = time.perf_counter() - t0
        n_steps = 25 - 5
        dt = times[25] - times[5]

    tokens_per_step = micro_bs * n_micro * seq
    return cfg, seq, tokens_per_step * n_steps / dt, dt / n_steps


def bench_main():
    """One attention impl per invocation (BENCH_ATTENTION_IMPL env; the
    parent runs one watchdogged child per impl and picks the faster —
    the flash/dense crossover at this shape was set from one noisy
    round-2 sample, so the bench self-selects)."""
    import jax

    from megatronapp_tpu.utils.flops import TPU_PEAK_FLOPS, flops_per_token

    impl = os.environ.get("BENCH_ATTENTION_IMPL", "auto")
    cfg, seq, tok_per_sec, step_s = _measure_impl(impl)

    platform = jax.devices()[0].platform
    kind = getattr(jax.devices()[0], "device_kind", platform).lower()
    peak = next((v for k, v in TPU_PEAK_FLOPS.items() if k in kind),
                TPU_PEAK_FLOPS.get(platform, 1e12))
    mfu = tok_per_sec * flops_per_token(cfg, seq) / peak

    print(json.dumps({
        "metric": "gpt2_125m_tokens_per_sec_per_chip",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "device": kind,
                  "step_ms": round(step_s * 1e3, 2),
                  "attention_impl": impl},
    }))


if __name__ == "__main__":
    if "--probe" in sys.argv:
        probe_main()
    elif "--bench" in sys.argv:
        bench_main()
    elif "--local-bench" in sys.argv:
        local_bench_main()
    elif "--tp-overlap" in sys.argv:
        tp_overlap_main()
    elif "--cp-a2a" in sys.argv:
        cp_a2a_main()
    elif "--pp-tp" in sys.argv:
        pp_tp_main()
    elif "--pipeline" in sys.argv:
        pipeline_main()
    elif "--dist-opt" in sys.argv:
        dist_opt_main()
    elif "--paged-kv" in sys.argv:
        paged_kv_main()
    elif "--spec-decode" in sys.argv:
        spec_decode_main()
    elif "--kv-quant" in sys.argv:
        kv_quant_main()
    elif "--kv-spill" in sys.argv:
        kv_spill_main()
    elif "--disagg" in sys.argv:
        disagg_main()
    elif "--megakernel" in sys.argv:
        megakernel_main()
    elif "--telemetry" in sys.argv:
        telemetry_main()
    elif "--fp8" in sys.argv:
        fp8_main()
    elif "--fleet-proc" in sys.argv:
        fleet_proc_main()
    elif "--lora" in sys.argv:
        lora_main()
    elif "--fleet" in sys.argv:
        fleet_main()
    else:
        parent_main(local_only="--local" in sys.argv)
