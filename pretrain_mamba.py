"""Mamba / hybrid-SSM pretraining entry point.

Parity with /root/reference/pretrain_mamba.py (MambaModel provider :44,
GPT-style get_batch/loss_func over the same .bin/.idx data). Model is
megatronapp_tpu/models/mamba.py: associative-scan selective SSM with
optional hybrid attention layers (--hybrid-pattern 'MMM*'), trained with
the shared microbatch-accumulating train step.
"""

import time

import jax
import numpy as np

from megatronapp_tpu.config.arguments import (
    parse_args,
    build_parser, configs_from_args, make_batch_iter_factory,
)
from megatronapp_tpu.models.mamba import (
    MambaConfig, init_mamba_params, mamba_loss,
)
from megatronapp_tpu.parallel.mesh import build_mesh
from megatronapp_tpu.training.optimizer import get_optimizer
from megatronapp_tpu.data.mock import mock_batches
from megatronapp_tpu.training.train import reshape_global_batch
from megatronapp_tpu.training.train_state import setup_train_state
from megatronapp_tpu.training.train_step import make_train_step


def main(argv=None):
    ap = build_parser("pretrain_mamba (megatronapp-tpu)")
    # Reference mamba flags (arguments.py --mamba-state-dim etc.).
    ap.add_argument("--mamba-state-dim", type=int, default=16)
    ap.add_argument("--mamba-conv-kernel", type=int, default=4)
    ap.add_argument("--mamba-expand", type=int, default=2)
    ap.add_argument("--hybrid-pattern", type=str, default=None,
                    help="per-layer allocation, e.g. 'MMM*' (M=mamba, "
                         "*=attention); default all-M")
    args = parse_args(ap, argv)
    cfg, parallel, training, opt_cfg = configs_from_args(args)
    mcfg = MambaConfig(state_dim=args.mamba_state_dim,
                       conv_kernel=args.mamba_conv_kernel,
                       expand=args.mamba_expand,
                       hybrid_pattern=args.hybrid_pattern)

    ctx = build_mesh(parallel)
    optimizer = get_optimizer(opt_cfg, training.train_iters)
    state, shardings, _ = setup_train_state(
        jax.random.PRNGKey(training.seed),
        lambda k: init_mamba_params(k, cfg, mcfg), optimizer, ctx)

    def loss_fn(params, micro):
        return mamba_loss(params, micro["tokens"], micro["labels"],
                          micro["loss_mask"], cfg, mcfg, ctx=ctx)

    step_fn = make_train_step(loss_fn, optimizer, opt_cfg, ctx, shardings,
                              training.train_iters)
    num_micro = training.num_microbatches(ctx.dp * ctx.ep)

    factory = make_batch_iter_factory(args, training, cfg)
    batch_iter = factory(0) if factory is not None else mock_batches(
        training.seq_length, cfg.vocab_size, training.global_batch_size,
        seed=training.seed)

    losses = []
    t0 = time.perf_counter()
    with ctx.mesh:
        for it in range(training.train_iters):
            batch = reshape_global_batch(next(batch_iter), num_micro)
            state, metrics = step_fn(state, batch)
            if (it + 1) % training.log_interval == 0 or \
                    it + 1 == training.train_iters:
                metrics = jax.device_get(metrics)
                losses.append(float(metrics["loss"]))
                print(f"iter {it+1:6d}/{training.train_iters} | "
                      f"loss {float(metrics['loss']):.4f} | "
                      f"grad_norm {float(metrics['grad_norm']):.3f}")
    dt = time.perf_counter() - t0
    tokens = training.train_iters * training.global_batch_size * \
        training.seq_length
    print(f"done: final loss {losses[-1]:.4f}, {tokens/dt:,.0f} tok/s")
    return losses


if __name__ == "__main__":
    main()
