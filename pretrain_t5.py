"""T5 pretraining entry point (span-corruption objective).

Parity with /root/reference/pretrain_t5.py: encoder/decoder LM trained on
span-corrupted text. Data comes from a synthetic stream unless --data-path
points at a sentence-split tokenized corpus (tools/preprocess_data.py
--split-sentences), in which case samples are built by
data/t5_dataset.py (sentinel span corruption).
"""

import time

import jax

from megatronapp_tpu.config.arguments import build_parser, configs_from_args, parse_args
from megatronapp_tpu.models.t5 import (
    init_t5_params, mock_t5_batch, t5_config, t5_loss,
)
from megatronapp_tpu.parallel.mesh import build_mesh
from megatronapp_tpu.training.optimizer import get_optimizer
from megatronapp_tpu.training.train import reshape_global_batch
from megatronapp_tpu.training.train_state import setup_train_state
from megatronapp_tpu.training.train_step import make_train_step


def main(argv=None):
    ap = build_parser("pretrain_t5 (megatronapp-tpu)")
    ap.add_argument("--mask-prob", type=float, default=0.15)
    ap.add_argument("--short-seq-prob", type=float, default=0.1)
    ap.add_argument("--decoder-seq-length", type=int, default=None)
    args = parse_args(ap, argv)
    gpt_cfg, parallel, training, opt_cfg = configs_from_args(args)
    import dataclasses
    cfg = t5_config(**{f.name: getattr(gpt_cfg, f.name)
                       for f in dataclasses.fields(gpt_cfg)
                       if f.name not in ("position_embedding",
                                         "attn_mask_type")})
    dec_len = args.decoder_seq_length or max(training.seq_length // 4, 16)

    ctx = build_mesh(parallel)
    optimizer = get_optimizer(opt_cfg, training.train_iters)
    state, shardings, _ = setup_train_state(
        jax.random.PRNGKey(training.seed),
        lambda k: init_t5_params(k, cfg), optimizer, ctx)

    step_fn = make_train_step(
        lambda p, micro: t5_loss(p, micro, cfg, ctx=ctx),
        optimizer, opt_cfg, ctx, shardings, training.train_iters)
    num_micro = training.num_microbatches(ctx.dp * ctx.ep)

    batch_iter = None
    if args.data_path:
        from megatronapp_tpu.data.indexed_dataset import IndexedDataset
        from megatronapp_tpu.data.t5_dataset import (
            T5Dataset, T5TokenIds, t5_batches,
        )
        from megatronapp_tpu.data.tokenizers import build_tokenizer
        tok = build_tokenizer(args.tokenizer_type,
                              args.tokenizer_name_or_path,
                              getattr(args, "vocab_size", None))
        # Sentinel ids must not collide with real corpus tokens. Prefer
        # the padded vocab region above the tokenizer's true vocab (those
        # ids are never produced by tokenization); fall back to the top of
        # the vocab with a warning (T5 tokenizers reserve <extra_id_*>
        # there, but arbitrary tokenizers do not).
        true_v = cfg.true_vocab_size or getattr(tok, "vocab_size", None)
        if true_v and cfg.vocab_size > true_v:
            sentinels = list(range(true_v, cfg.vocab_size))[:100]
        else:
            n_sent = min(100, max(cfg.vocab_size // 50, 1))
            sentinels = list(range(cfg.vocab_size - n_sent, cfg.vocab_size))
            print(f"warning: no padded vocab region; using top "
                  f"{n_sent} vocab ids as sentinels (may collide with "
                  f"real tokens)")
        ids = T5TokenIds(
            bos=getattr(tok, "bos", 1), eos=getattr(tok, "eod", 2) or 2,
            pad=getattr(tok, "pad", 0), sentinels=sentinels)
        dataset = T5Dataset(
            IndexedDataset(args.data_path),
            enc_seq_length=training.seq_length, dec_seq_length=dec_len,
            vocab_size=cfg.vocab_size, token_ids=ids,
            num_samples=training.train_iters * training.global_batch_size,
            seed=training.seed, masked_lm_prob=args.mask_prob,
            short_seq_prob=args.short_seq_prob)
        batch_iter = t5_batches(dataset, training.global_batch_size)
        print(f"T5 corpus: {len(dataset)} samples from {args.data_path}")

    losses = []
    t0 = time.perf_counter()
    with ctx.mesh:
        for it in range(training.train_iters):
            if batch_iter is not None:
                batch = next(batch_iter)
                batch.pop("dec_mask", None)
            else:
                batch = mock_t5_batch(it, training.global_batch_size,
                                      training.seq_length, dec_len,
                                      cfg.vocab_size)
            batch = reshape_global_batch(batch, num_micro)
            state, metrics = step_fn(state, batch)
            if (it + 1) % training.log_interval == 0 or \
                    it + 1 == training.train_iters:
                metrics = jax.device_get(metrics)
                losses.append(float(metrics["loss"]))
                print(f"iter {it+1:6d}/{training.train_iters} | "
                      f"loss {float(metrics['loss']):.4f}")
    dt = time.perf_counter() - t0
    tokens = training.train_iters * training.global_batch_size * \
        training.seq_length
    print(f"done: final loss {losses[-1]:.4f}, {tokens/dt:,.0f} tok/s")


if __name__ == "__main__":
    main()
