"""ViT inpainting pretraining entry point.

Parity with /root/reference/pretrain_vision_inpaint.py (VitInpaintingModel
+ masked-MSE loss + PSNR/SSIM metrics). Synthetic image stream with
patch-aligned random hole masks unless an image loader is wired in.
"""

import dataclasses
import time

import jax
import numpy as np

from megatronapp_tpu.config.arguments import build_parser, configs_from_args, parse_args
from megatronapp_tpu.models.inpaint import init_inpaint_params, inpaint_loss
from megatronapp_tpu.models.vision import VitSpec, vit_config
from megatronapp_tpu.parallel.mesh import build_mesh
from megatronapp_tpu.training.optimizer import get_optimizer
from megatronapp_tpu.training.train import reshape_global_batch
from megatronapp_tpu.training.train_state import setup_train_state
from megatronapp_tpu.training.train_step import make_train_step


def main(argv=None):
    ap = build_parser("pretrain_vision_inpaint (megatronapp-tpu)")
    ap.add_argument("--img-size", type=int, default=224)
    ap.add_argument("--patch-dim", type=int, default=16)
    ap.add_argument("--mask-factor", type=float, default=0.25,
                    help="fraction of patches masked per image")
    args = parse_args(ap, argv)
    gpt_cfg, parallel, training, opt_cfg = configs_from_args(args)
    spec = VitSpec(image_size=args.img_size, patch_size=args.patch_dim)
    cfg = vit_config(**{f.name: getattr(gpt_cfg, f.name)
                        for f in dataclasses.fields(gpt_cfg)
                        if f.name not in ("position_embedding",
                                          "attn_mask_type",
                                          "add_qkv_bias",
                                          "max_position_embeddings")},
                     max_position_embeddings=1 + spec.num_patches)

    ctx = build_mesh(parallel)
    optimizer = get_optimizer(opt_cfg, training.train_iters)
    state, shardings, _ = setup_train_state(
        jax.random.PRNGKey(training.seed),
        lambda k: init_inpaint_params(k, cfg, spec), optimizer, ctx)

    def loss_fn(p, micro):
        return inpaint_loss(p, micro["images"], micro["masks"], cfg, spec,
                            ctx=ctx)

    step_fn = make_train_step(loss_fn, optimizer, opt_cfg, ctx, shardings,
                              training.train_iters)
    num_micro = training.num_microbatches(ctx.dp * ctx.ep)

    batch_iter = None
    if args.data_path:
        from megatronapp_tpu.data.image_folder import (
            ClassificationTransform, image_batches, load_folder,
        )
        batch_iter = image_batches(
            load_folder(args.data_path), training.global_batch_size,
            ClassificationTransform(spec.image_size, train=True,
                                    seed=training.seed),
            seed=training.seed)

    rng = np.random.default_rng(training.seed)
    g = spec.image_size // spec.patch_size
    losses = []
    t0 = time.perf_counter()
    with ctx.mesh:
        for it in range(training.train_iters):
            bits = (rng.random((training.global_batch_size, g, g)) <
                    args.mask_factor).astype(np.float32)
            masks = np.repeat(np.repeat(bits, spec.patch_size, axis=1),
                              spec.patch_size, axis=2)[..., None]
            if batch_iter is not None:
                images = next(batch_iter)["images"]
            else:
                images = rng.normal(size=(
                    training.global_batch_size, spec.image_size,
                    spec.image_size, spec.num_channels)
                ).astype(np.float32)
            batch = reshape_global_batch({
                "images": images,
                "masks": masks,
            }, num_micro)
            state, metrics = step_fn(state, batch)
            if (it + 1) % training.log_interval == 0 or \
                    it + 1 == training.train_iters:
                metrics = jax.device_get(metrics)
                losses.append(float(metrics["loss"]))
                print(f"iter {it+1:6d}/{training.train_iters} | "
                      f"loss {float(metrics['loss']):.4f} | "
                      f"psnr {float(metrics['psnr']):.2f} | "
                      f"ssim {float(metrics['ssim']):.3f}")
    dt = time.perf_counter() - t0
    print(f"done: final loss {losses[-1]:.4f}, "
          f"{training.train_iters * training.global_batch_size / dt:.1f} "
          f"img/s")
    return losses


if __name__ == "__main__":
    main()
