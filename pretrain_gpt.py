"""GPT pretraining entry point.

Parity with /root/reference/pretrain_gpt.py (model_provider :47, get_batch
:139, loss_func :159, forward_step :227) — flags follow the reference's
arguments.py names, so e.g. the reference's test config translates directly:

  python pretrain_gpt.py \\
      --num-layers 16 --hidden-size 2048 --num-attention-heads 32 \\
      --seq-length 2048 --micro-batch-size 2 --global-batch-size 16 \\
      --tensor-model-parallel-size 2 --pipeline-model-parallel-size 2 \\
      --num-layers-per-virtual-pipeline-stage 4 \\
      --train-iters 100 --lr 1e-4 --trace --trace-interval 5
"""

import sys

from megatronapp_tpu.config.arguments import (
    build_parser, configs_from_args, make_batch_iter_factory, parse_args,
    save_resolved_args,
)
from megatronapp_tpu.training.train import pretrain_gpt


def main(argv=None):
    args = parse_args(build_parser("pretrain_gpt (megatronapp-tpu)"), argv)
    model, parallel, training, optimizer = configs_from_args(args)
    if args.save:
        save_resolved_args(args, args.save)
    factory = make_batch_iter_factory(args, training, model)
    result = pretrain_gpt(model, parallel, training, optimizer,
                          batch_iter_factory=factory)
    print(f"done: final loss {result.losses[-1]:.4f}, "
          f"{result.tokens_per_sec:,.0f} tok/s")
    return result


if __name__ == "__main__":
    main()
