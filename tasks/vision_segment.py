"""Semantic segmentation finetune + mIoU evaluation (SETR-style head).

Parity with /root/reference/tasks/vision/segmentation/ (seg_heads.py
SetrSegmentationHead: per-patch features → class logits → upsample to
pixel resolution; metrics.py mean_iou over a class confusion matrix;
finetune_setr.py epoch loop). Data interface: .npz with `images`
[N,H,W,C] float and `masks` [N,H,W] int class ids (255 = ignore), the
cityscapes loading of the reference reduced to arrays.

Usage:
  python tasks/vision_segment.py --train-data train.npz \
      --valid-data val.npz --num-classes 19 --img-size 128 --patch-dim 16
"""

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/tasks/", 1)[0])

import numpy as np
from megatronapp_tpu.config.arguments import parse_args

IGNORE_INDEX = 255


def init_seg_head(rng, cfg, num_classes):
    """Linear per-patch classifier (SetrSegmentationHead's conv1x1 on
    patch features is exactly a per-patch linear)."""
    import jax
    import jax.numpy as jnp
    std = cfg.init_method_std
    return {
        "kernel": jax.random.normal(
            rng, (cfg.hidden_size, num_classes), jnp.float32) * std,
        "bias": jnp.zeros((num_classes,), jnp.float32),
    }


def segment_logits(params, images, cfg, spec, num_classes, ctx=None):
    """[B,H,W,C] → per-pixel class logits [B,H,W,num_classes]: backbone
    patch tokens (CLS dropped) → per-patch linear → bilinear upsample
    (seg_heads.py to_2D + interpolate)."""
    import jax
    import jax.numpy as jnp

    from megatronapp_tpu.models.vision import vit_backbone
    b, h, w, _ = images.shape
    grid = spec.image_size // spec.patch_size
    enc = vit_backbone(params, images, cfg, spec, ctx=ctx)[:, 1:]
    sh = params["seg_head"]
    logits = enc.astype(jnp.float32) @ sh["kernel"] + sh["bias"]
    logits = logits.reshape(b, grid, grid, num_classes)
    return jax.image.resize(logits, (b, h, w, num_classes), "bilinear")


def segmentation_loss(params, images, masks, cfg, spec, num_classes,
                      ctx=None):
    """Per-pixel CE with ignore-index masking + pixel accuracy."""
    import jax.numpy as jnp

    from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
    logits = segment_logits(params, images, cfg, spec, num_classes,
                            ctx=ctx)
    b, h, w, c = logits.shape
    valid = (masks != IGNORE_INDEX).astype(jnp.float32)
    safe = jnp.where(masks == IGNORE_INDEX, 0, masks)
    loss, _ = cross_entropy_loss(
        logits.reshape(b, h * w, c), safe.reshape(b, h * w),
        valid.reshape(b, h * w))
    pred = jnp.argmax(logits, -1)
    acc = jnp.sum((pred == masks) * valid) / jnp.maximum(valid.sum(), 1.0)
    return loss, {"lm_loss": loss, "pixel_accuracy": acc}


def confusion_matrix(pred: np.ndarray, target: np.ndarray,
                     num_classes: int) -> np.ndarray:
    """[num_classes, num_classes] counts (rows = target, cols = pred),
    ignore-index pixels dropped (metrics.py hist semantics)."""
    valid = target != IGNORE_INDEX
    t = target[valid].astype(np.int64)
    p = pred[valid].astype(np.int64)
    idx = t * num_classes + p
    return np.bincount(idx, minlength=num_classes ** 2).reshape(
        num_classes, num_classes)


def mean_iou(conf: np.ndarray):
    """(mIoU over classes present, per-class IoU array with NaN for
    absent classes) — reference mean_iou."""
    inter = np.diag(conf).astype(np.float64)
    union = conf.sum(1) + conf.sum(0) - inter
    with np.errstate(divide="ignore", invalid="ignore"):
        iou = inter / union
    return float(np.nanmean(np.where(union > 0, iou, np.nan))), iou


def make_segment_fwd(cfg, spec, num_classes):
    """Jit the eval forward ONCE; pass to evaluate_miou from loops."""
    import jax
    return jax.jit(lambda p, x: segment_logits(p, x, cfg, spec,
                                               num_classes))


def evaluate_miou(params, cfg, spec, images, masks, num_classes,
                  batch_size=16, fwd=None):
    from tasks.common import padded_batches
    fwd = fwd or make_segment_fwd(cfg, spec, num_classes)
    conf = np.zeros((num_classes, num_classes), np.int64)
    done = 0
    for (chunk,), real in padded_batches([images], batch_size):
        pred = np.asarray(fwd(params, chunk)).argmax(-1)[:real]
        conf += confusion_matrix(pred, masks[done: done + real],
                                 num_classes)
        done += real
    return mean_iou(conf)


def finetune_segmentation(train_images, train_masks, valid_images,
                          valid_masks, cfg, spec, num_classes, *,
                          epochs=3, batch_size=16, lr=1e-3, seed=0,
                          pretrained_params=None, log_fn=print):
    """Epoch loop; returns (params, best mIoU)."""
    import jax
    import optax

    from megatronapp_tpu.models.vision import init_vit_params

    params, _ = init_vit_params(jax.random.PRNGKey(seed), cfg, spec)
    if pretrained_params is not None:
        for key in pretrained_params:
            if key in params and key != "seg_head":
                params[key] = pretrained_params[key]
    params["seg_head"] = init_seg_head(jax.random.PRNGKey(seed + 1), cfg,
                                       num_classes)

    opt = optax.adam(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, images, masks):
        (loss, metrics), g = jax.value_and_grad(
            lambda p: segmentation_loss(p, images, masks, cfg, spec,
                                        num_classes), has_aux=True)(params)
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    eval_fwd = make_segment_fwd(cfg, spec, num_classes)
    rng = np.random.default_rng(seed)
    steps_per_epoch = max(len(train_images) // batch_size, 1)
    best = 0.0
    for epoch in range(epochs):
        order = rng.permutation(len(train_images))
        loss = None
        for s in range(steps_per_epoch):
            idx = order[s * batch_size: (s + 1) * batch_size]
            params, opt_state, loss = step(
                params, opt_state, train_images[idx], train_masks[idx])
        miou, _ = evaluate_miou(params, cfg, spec, valid_images,
                                valid_masks, num_classes, batch_size,
                                fwd=eval_fwd)
        best = max(best, miou)
        log_fn(f"epoch {epoch+1}/{epochs} | train loss "
               f"{float(loss):.4f} | mIoU {miou:.4f}")
    return params, best


def main(argv=None):
    from megatronapp_tpu.models.vision import VitSpec, vit_config

    ap = argparse.ArgumentParser()
    ap.add_argument("--train-data", required=True,
                    help=".npz with images/masks")
    ap.add_argument("--valid-data", required=True)
    ap.add_argument("--num-classes", type=int, required=True)
    ap.add_argument("--img-size", type=int, default=128)
    ap.add_argument("--patch-dim", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--num-layers", type=int, default=12)
    ap.add_argument("--hidden-size", type=int, default=768)
    ap.add_argument("--num-attention-heads", type=int, default=12)
    ap.add_argument("--load-dir", default=None)
    args = parse_args(ap, argv)

    cfg = vit_config(num_layers=args.num_layers,
                     hidden_size=args.hidden_size,
                     num_attention_heads=args.num_attention_heads,
                     max_position_embeddings=(args.img_size //
                                              args.patch_dim) ** 2 + 1)
    spec = VitSpec(image_size=args.img_size, patch_size=args.patch_dim,
                   num_classes=args.num_classes)
    train = np.load(args.train_data)
    valid = np.load(args.valid_data)
    pretrained = None
    if args.load_dir:
        import jax

        from megatronapp_tpu.models.vision import init_vit_params
        from tasks.common import restore_params
        tmpl, _ = init_vit_params(jax.random.PRNGKey(0), cfg, spec)
        pretrained = restore_params(args.load_dir, tmpl)

    _, best = finetune_segmentation(
        np.asarray(train["images"], np.float32),
        np.asarray(train["masks"], np.int32),
        np.asarray(valid["images"], np.float32),
        np.asarray(valid["masks"], np.int32),
        cfg, spec, args.num_classes, epochs=args.epochs,
        batch_size=args.batch_size, lr=args.lr,
        pretrained_params=pretrained)
    print(f"best mIoU: {best:.4f}")


if __name__ == "__main__":
    main()
