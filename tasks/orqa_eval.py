"""Open-retrieval QA (ORQA-style) retrieval evaluation.

Parity with /root/reference/tasks/orqa/evaluate_orqa.py +
orqa/unsupervised/qa_utils (NQ-style eval): embed every evidence block
with the biencoder's context tower and each question with the query
tower, retrieve top-k blocks by inner product, and score a hit when a
retrieved block contains the answer (token-subsequence containment — the
reference matches answer strings in block text).

Inputs: the ICT corpus layout (sentence-split blocks .bin/.idx + titles
companion, data/ict_dataset.py) and a queries JSONL of
{"question": "...", "answers": ["...", ...]}.

Usage:
  python tasks/orqa_eval.py --data-path blocks --titles-data-path titles \
      --queries qa.jsonl --load-dir ckpt_biencoder --seq-length 128
"""

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/tasks/", 1)[0])

import numpy as np
from megatronapp_tpu.config.arguments import parse_args


def _pad_batch(seqs, seq_length, pad):
    tokens = np.full((len(seqs), seq_length), pad, np.int32)
    mask = np.zeros((len(seqs), seq_length), np.float32)
    for i, s in enumerate(seqs):
        s = s[:seq_length]
        tokens[i, : len(s)] = s
        mask[i, : len(s)] = 1.0
    return tokens, mask


def _contains_subseq(haystack: np.ndarray, needle) -> bool:
    n = len(needle)
    if n == 0 or n > len(haystack):
        return False
    needle = np.asarray(needle)
    # all windows of length n
    windows = np.lib.stride_tricks.sliding_window_view(haystack, n)
    return bool((windows == needle).all(axis=1).any())


def evaluate_retrieval(params, cfg, block_ds, titles_ds, queries, *,
                       tokenizer, ids, seq_length=128, batch_size=32,
                       topk=(1, 5, 20), log_fn=print):
    """queries: [{'question': str, 'answers': [str]}]. Returns
    {f'top{k}_acc': float} over the evidence blocks built exactly like
    ICT context blocks (one block per build_blocks_mapping span)."""
    import jax

    from megatronapp_tpu.data.ict_dataset import ICTDataset, IctTokenIds
    from megatronapp_tpu.models.biencoder import biencoder_embed

    if not queries:
        raise ValueError("no queries to evaluate")
    ict = ICTDataset(block_ds, titles_ds, seq_length=seq_length,
                     token_ids=IctTokenIds(cls=ids.cls, sep=ids.sep,
                                           pad=ids.pad),
                     num_epochs=1, query_in_block_prob=1.0)
    n_blocks = len(ict)
    if n_blocks == 0:
        raise ValueError("no evidence blocks (corpus too small)")

    embed_ctx = jax.jit(lambda t, m: biencoder_embed(
        params, t, cfg, kind="context", padding_mask=m))
    embed_q = jax.jit(lambda t, m: biencoder_embed(
        params, t, cfg, kind="query", padding_mask=m))

    # Evidence embeddings + raw block token streams for answer matching.
    ctx_emb = []
    block_tokens = []
    for s in range(0, n_blocks, batch_size):
        rows = [ict[i] for i in range(s, min(s + batch_size, n_blocks))]
        t = np.stack([r["context_tokens"] for r in rows])
        m = np.stack([r["context_pad_mask"] for r in rows])
        ctx_emb.append(np.asarray(embed_ctx(t, m.astype(np.float32))))
        for r in rows:
            start, end, doc, _ = r["block_data"]
            block_tokens.append(np.concatenate(
                [np.asarray(block_ds[i]) for i in range(start, end)]))
    ctx_emb = np.concatenate(ctx_emb)
    log_fn(f"embedded {n_blocks} evidence blocks")

    hits = {k: 0 for k in topk}
    kmax = max(topk)
    for s in range(0, len(queries), batch_size):
        chunk = queries[s: s + batch_size]
        # Match the ICT training query format exactly:
        # [CLS] q[:seq_length-2] [SEP] (ict_dataset.py _pad) — blunt
        # truncation after the fact would drop the SEP on long questions.
        seqs = [[ids.cls,
                 *tokenizer.tokenize(q["question"])[:seq_length - 2],
                 ids.sep]
                for q in chunk]
        t, m = _pad_batch(seqs, seq_length, ids.pad)
        q_emb = np.asarray(embed_q(t, m))
        scores = q_emb @ ctx_emb.T            # [B, n_blocks]
        # argpartition (O(n)) then sort only the kmax candidates — a full
        # argsort is O(n log n) per batch over the whole corpus.
        kk = min(kmax, scores.shape[1])
        cand = np.argpartition(-scores, kk - 1, axis=1)[:, :kk]
        order = np.take_along_axis(
            cand, np.argsort(-np.take_along_axis(scores, cand, axis=1),
                             axis=1), axis=1)
        for qi, q in enumerate(chunk):
            answers = [tokenizer.tokenize(a) for a in q["answers"]]
            rank_hit = None
            for rank, bi in enumerate(order[qi]):
                if any(_contains_subseq(block_tokens[bi], a)
                       for a in answers):
                    rank_hit = rank
                    break
            for k in topk:
                if rank_hit is not None and rank_hit < k:
                    hits[k] += 1
    n = len(queries)
    accs = {f"top{k}_acc": hits[k] / n for k in topk}
    log_fn(" | ".join(f"top-{k}: {hits[k]/n:.4f}" for k in topk) +
           f"  ({n} questions, {n_blocks} blocks)")
    return accs


def main(argv=None):
    from megatronapp_tpu.data.indexed_dataset import IndexedDataset
    from megatronapp_tpu.models.bert import bert_config
    from tasks.common import build_tok_and_ids, restore_params

    ap = argparse.ArgumentParser()
    ap.add_argument("--data-path", required=True)
    ap.add_argument("--titles-data-path", required=True)
    ap.add_argument("--queries", required=True,
                    help="JSONL {'question','answers'}")
    ap.add_argument("--load-dir", default=None)
    ap.add_argument("--seq-length", type=int, default=128)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-layers", type=int, default=12)
    ap.add_argument("--hidden-size", type=int, default=768)
    ap.add_argument("--num-attention-heads", type=int, default=12)
    ap.add_argument("--vocab-size", type=int, default=30592)
    ap.add_argument("--tokenizer-type", default="BertWordPieceTokenizer")
    ap.add_argument("--tokenizer-name-or-path", default=None)
    ap.add_argument("--report-topk-accuracies", type=int, nargs="+",
                    default=[1, 5, 20])
    args = parse_args(ap, argv)

    import jax

    from megatronapp_tpu.models.biencoder import init_biencoder_params

    tok, ids = build_tok_and_ids(args.tokenizer_type,
                                 args.tokenizer_name_or_path,
                                 args.vocab_size)
    cfg = bert_config(num_layers=args.num_layers,
                      hidden_size=args.hidden_size,
                      num_attention_heads=args.num_attention_heads,
                      vocab_size=args.vocab_size,
                      max_position_embeddings=args.seq_length)
    params, _ = init_biencoder_params(jax.random.PRNGKey(0), cfg)
    params = restore_params(args.load_dir, params) or params

    queries = [json.loads(l) for l in open(args.queries) if l.strip()]
    evaluate_retrieval(
        params, cfg, IndexedDataset(args.data_path),
        IndexedDataset(args.titles_data_path), queries, tokenizer=tok,
        ids=ids, seq_length=args.seq_length, batch_size=args.batch_size,
        topk=tuple(args.report_topk_accuracies))


if __name__ == "__main__":
    main()
