"""Ensemble classification predictions from multiple finetune runs.

Parity with /root/reference/tasks/ensemble_classifier.py: load per-run
prediction files, sum the class scores per example (uid-aligned), argmax
the ensemble, report per-dataset and overall accuracy.

Prediction file format: .npz with `logits` [N, C], `labels` [N],
`uid` [N] (written by tasks/finetune.py --save-predictions).

Usage:
  python tasks/ensemble_classifier.py run1/preds.npz run2/preds.npz ...
"""

import argparse
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/tasks/", 1)[0])


def ensemble(paths):
    """Sum uid-aligned scores across runs → (pred [N], labels [N])."""
    total = None
    labels = None
    uid = None
    for path in paths:
        data = np.load(path)
        if total is None:
            total = np.asarray(data["logits"], np.float64).copy()
            labels = np.asarray(data["labels"])
            uid = np.asarray(data["uid"])
        else:
            if not np.array_equal(uid, data["uid"]):
                raise ValueError(f"{path}: uid mismatch with the first "
                                 "run — predictions are not aligned")
            if not np.array_equal(labels, data["labels"]):
                raise ValueError(f"{path}: labels disagree with the "
                                 "first run on the same uids")
            total += np.asarray(data["logits"], np.float64)
    if total is None:
        raise ValueError("no prediction files")
    return total.argmax(axis=1), labels


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+", help="prediction .npz files")
    args = ap.parse_args(argv)
    pred, labels = ensemble(args.paths)
    acc = float((pred == labels).mean())
    print(f"ensemble of {len(args.paths)} runs: accuracy {acc:.4f} "
          f"({len(pred)} examples)")
    return acc


if __name__ == "__main__":
    main()
