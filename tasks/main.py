"""Unified downstream-task dispatcher.

Parity with /root/reference/tasks/main.py (one entry, --task routes to
the family-specific harness). Task names follow the reference's
(RACE, MNLI/QQP-style classify, WIKITEXT103, LAMBADA) plus the
families this build adds explicit entries for.

  python tasks/main.py --task RACE --train-data r.jsonl --valid-data d.jsonl ...
  python tasks/main.py --task CLASSIFY --num-classes 2 ...
  python tasks/main.py --task WIKITEXT103 --data-path wiki.txt ...
  python tasks/main.py --task LAMBADA --data-path lambada.jsonl ...
  python tasks/main.py --task ORQA --data-path blocks --queries q.jsonl ...
  python tasks/main.py --task MSDP-EVAL --guess-file g --answer-file a
  python tasks/main.py --task VISION-CLASSIFY --train-data t.npz ...
  python tasks/main.py --task VISION-SEGMENT --train-data t.npz ...
  python tasks/main.py --task ENSEMBLE run1/p.npz run2/p.npz
"""

import sys

sys.path.insert(0, __file__.rsplit("/tasks/", 1)[0])


def main():
    if "--task" not in sys.argv:
        raise SystemExit(__doc__)
    i = sys.argv.index("--task")
    task = sys.argv[i + 1].upper()
    rest = sys.argv[1:i] + sys.argv[i + 2:]

    if task in ("RACE", "MULTICHOICE"):
        from tasks.finetune import main as m
        m(["--task", "multichoice", *rest])
    elif task in ("CLASSIFY", "MNLI", "QQP"):
        from tasks.finetune import main as m
        m(["--task", "classify", *rest])
    elif task in ("WIKITEXT103", "WIKITEXT"):
        from tasks.zeroshot_gpt import main as m
        m(["--task", "wikitext", *rest])
    elif task == "LAMBADA":
        from tasks.zeroshot_gpt import main as m
        m(["--task", "lambada", *rest])
    elif task == "ORQA":
        from tasks.orqa_eval import main as m
        m(rest)
    elif task in ("MSDP-EVAL", "MSDP"):
        from tasks.msdp import main as m
        m(rest)
    elif task == "VISION-CLASSIFY":
        from tasks.vision_classify import main as m
        m(rest)
    elif task == "VISION-SEGMENT":
        from tasks.vision_segment import main as m
        m(rest)
    elif task == "ENSEMBLE":
        from tasks.ensemble_classifier import main as m
        m(rest)
    else:
        raise SystemExit(
            f"unknown --task {task}; known: RACE, CLASSIFY (MNLI/QQP), "
            "WIKITEXT103, LAMBADA, ORQA, MSDP-EVAL, VISION-CLASSIFY, "
            "VISION-SEGMENT, ENSEMBLE")


if __name__ == "__main__":
    main()
