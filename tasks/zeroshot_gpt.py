"""Zero-shot GPT evaluation: LM perplexity + LAMBADA-style cloze accuracy.

Parity with /root/reference/tasks/zeroshot_gpt/evaluate.py (+ datasets.py):
- WikiText-style perplexity: the token stream is chunked into overlapping
  windows (`--overlapping-eval` stride); each window scores only its new
  tokens, and PPL = exp(total_nll / total_tokens).
- LAMBADA cloze: accuracy of greedily predicting the final word's tokens
  given the context.

Runs against a live params pytree or a converted checkpoint; doubles as a
whole-stack correctness check — on an HF-converted model the perplexity
must match the HF implementation's (tests/test_tasks_eval.py).

Usage:
  python tasks/zeroshot_gpt.py --task wikitext --data-path corpus.txt \
      --load-dir /ckpts/gpt2 --preset gpt2-125m \
      --tokenizer-type GPT2BPETokenizer [--seq-length 1024]
"""

import argparse
import json
import math
import sys

sys.path.insert(0, __file__.rsplit("/tasks/", 1)[0])

import numpy as np
from megatronapp_tpu.config.arguments import parse_args


def lm_nll(params, cfg, token_ids: np.ndarray, seq_length: int,
           overlapping_eval: int = 0, batch_size: int = 8, ctx=None):
    """Total negative log-likelihood of a token stream.

    Returns (total_nll, total_predicted_tokens). Windows of seq_length
    tokens advance by `overlapping_eval` (default: non-overlapping =
    seq_length); in overlapping mode only the last `stride` tokens of each
    window are scored — the reference's --overlapping-eval semantics.
    """
    import jax
    import jax.numpy as jnp

    from megatronapp_tpu.models.gpt import gpt_forward

    stride = overlapping_eval or seq_length
    n = len(token_ids)

    @jax.jit
    def window_nll(tokens, targets, mask):
        logits, _ = gpt_forward(params, tokens, cfg, ctx=ctx)
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        return jnp.sum((logz - tgt) * mask)

    total_nll = 0.0
    total_tokens = 0
    batch_tokens, batch_targets, batch_masks = [], [], []

    def flush():
        nonlocal total_nll
        if not batch_tokens:
            return
        t = np.stack(batch_tokens)
        g = np.stack(batch_targets)
        m = np.stack(batch_masks)
        total_nll_arr = window_nll(jnp.asarray(t), jnp.asarray(g),
                                   jnp.asarray(m))
        total_nll += float(jax.device_get(total_nll_arr))
        batch_tokens.clear(); batch_targets.clear(); batch_masks.clear()

    start = 0
    prev_end = 1  # first not-yet-scored target position
    while prev_end < n:
        end = min(start + seq_length + 1, n)
        window = token_ids[start:end]
        tokens = window[:-1]
        targets = window[1:]
        # Score only positions not covered by a previous window (exactly
        # once per token, including the final partial window).
        new = end - prev_end
        mask = np.zeros(len(targets), np.float32)
        mask[len(targets) - new:] = 1.0
        pad = seq_length - len(tokens)
        if pad > 0:
            tokens = np.pad(tokens, (0, pad))
            targets = np.pad(targets, (0, pad))
            mask = np.pad(mask, (0, pad))
        batch_tokens.append(tokens.astype(np.int32))
        batch_targets.append(targets.astype(np.int32))
        batch_masks.append(mask)
        total_tokens += new
        if len(batch_tokens) == batch_size:
            flush()
        prev_end = end
        if end == n:
            break
        start = start + stride if stride < seq_length else end - 1
    flush()
    return total_nll, total_tokens


def evaluate_wikitext(params, cfg, token_ids, seq_length,
                      overlapping_eval=0, ctx=None):
    """→ {'nll', 'tokens', 'ppl', 'adjusted_ppl' omitted (no detok ratio)}"""
    nll, count = lm_nll(params, cfg, np.asarray(token_ids), seq_length,
                        overlapping_eval, ctx=ctx)
    return {"nll": nll, "tokens": count,
            "ppl": math.exp(nll / max(count, 1))}


def evaluate_lambada(params, cfg, examples, seq_length, ctx=None):
    """Cloze accuracy: `examples` is a list of (context_ids, target_ids);
    correct iff EVERY target token is the greedy argmax given the prefix
    (reference lambada strict match)."""
    import jax
    import jax.numpy as jnp

    from megatronapp_tpu.models.gpt import gpt_forward

    @jax.jit
    def window_argmax(tokens):
        logits, _ = gpt_forward(params, tokens, cfg, ctx=ctx)
        return jnp.argmax(logits, axis=-1)

    correct = 0
    for context, target in examples:
        ids = list(context) + list(target)
        if len(ids) > seq_length:
            ids = ids[-seq_length:]
        tokens = np.asarray(ids[:-1], np.int32)[None]
        pad = seq_length - tokens.shape[1]
        if pad > 0:
            tokens = np.pad(tokens, ((0, 0), (0, pad)))
        pred = np.asarray(jax.device_get(window_argmax(jnp.asarray(tokens))))
        k = len(target)
        pos = len(ids) - 1 - k  # predictions for the k target tokens
        if np.array_equal(pred[0, pos: pos + k], np.asarray(target)):
            correct += 1
    return {"accuracy": correct / max(len(examples), 1),
            "correct": correct, "total": len(examples)}


def main(argv=None):
    from megatronapp_tpu.data.tokenizers import build_tokenizer
    from megatronapp_tpu.models.presets import PRESETS

    ap = argparse.ArgumentParser()
    ap.add_argument("--task", choices=["wikitext", "lambada"],
                    default="wikitext")
    ap.add_argument("--data-path", required=True,
                    help="txt (wikitext) or jsonl with 'text' (lambada)")
    ap.add_argument("--load-dir", required=True)
    ap.add_argument("--preset", default="gpt2-125m")
    ap.add_argument("--tokenizer-type", default="GPT2BPETokenizer")
    ap.add_argument("--tokenizer-name-or-path", default=None)
    ap.add_argument("--seq-length", type=int, default=1024)
    ap.add_argument("--overlapping-eval", type=int, default=0)
    args = parse_args(ap, argv)

    import jax

    from megatronapp_tpu.models.gpt import init_gpt_params
    from megatronapp_tpu.training.checkpointing import CheckpointManager

    cfg = PRESETS[args.preset]()
    tok = build_tokenizer(args.tokenizer_type, args.tokenizer_name_or_path)
    params0, _ = init_gpt_params(jax.random.PRNGKey(0), cfg)
    mngr = CheckpointManager(args.load_dir)
    restored = mngr.restore({"step": 0, "params": params0, "opt_state": {}})
    mngr.close()
    if restored is None:
        raise FileNotFoundError(f"no checkpoint in {args.load_dir}")
    params = restored["params"]

    if args.task == "wikitext":
        with open(args.data_path) as f:
            ids = tok.tokenize(f.read())
        res = evaluate_wikitext(params, cfg, ids, args.seq_length,
                                args.overlapping_eval)
    else:
        examples = []
        with open(args.data_path) as f:
            for line in f:
                if not line.strip():
                    continue
                text = json.loads(line)["text"]
                ctx_text, target = text.rsplit(" ", 1)
                examples.append((tok.tokenize(ctx_text),
                                 tok.tokenize(" " + target)))
        res = evaluate_lambada(params, cfg, examples, args.seq_length)
    print(json.dumps({"task": args.task, **res}))


if __name__ == "__main__":
    main()
