"""Shared helpers for the downstream-task CLIs (tasks/*.py).

One canonical version of the tokenizer/token-id assembly and the
checkpoint-restore boilerplate that every task entry needs (reference
tasks/main.py + finetune_utils share the analogous setup)."""

import sys

sys.path.insert(0, __file__.rsplit("/tasks/", 1)[0])


def build_tok_and_ids(tokenizer_type, tokenizer_name_or_path, vocab_size):
    """(tokenizer, BertTokenIds) with conventional low-id fallbacks for
    tokenizers without BERT specials (e.g. NullTokenizer)."""
    from megatronapp_tpu.data.bert_dataset import BertTokenIds
    from megatronapp_tpu.data.tokenizers import build_tokenizer

    tok = build_tokenizer(tokenizer_type, tokenizer_name_or_path,
                          vocab_size)

    def special(name, default):
        v = getattr(tok, name, None)
        return default if v is None else v

    ids = BertTokenIds(cls=special("cls", 1), sep=special("sep", 2),
                       mask=special("mask", 3), pad=special("pad", 0))
    return tok, ids


def padded_batches(arrays, batch_size):
    """Yield (padded_chunk_tuple, real_count): fixed-size batches over
    parallel arrays with the ragged tail zero-padded — keeps one
    compiled shape for jitted eval loops."""
    import numpy as np
    n = len(arrays[0])
    for s in range(0, n, batch_size):
        chunks = [a[s: s + batch_size] for a in arrays]
        real = len(chunks[0])
        if real < batch_size:
            chunks = [np.concatenate(
                [c, np.zeros_like(c[:1]).repeat(batch_size - real,
                                                axis=0)])
                for c in chunks]
        yield tuple(chunks), real


def restore_params(load_dir, template_params, log_fn=print):
    """Orbax-restore `params` from a training checkpoint dir, or None."""
    if not load_dir:
        return None
    from megatronapp_tpu.training.checkpointing import CheckpointManager
    mngr = CheckpointManager(load_dir)
    restored = mngr.restore({"step": 0, "params": template_params,
                             "opt_state": {}})
    mngr.close()
    if restored is None:
        return None
    log_fn(f"loaded checkpoint step {restored['step']} from {load_dir}")
    return restored["params"]
