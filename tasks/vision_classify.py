"""Vision classification finetune + evaluation.

Parity with /root/reference/tasks/vision/classification/ (finetune a
pretrained ViT backbone with a fresh classification head, epoch loop
with top-1 dev accuracy; eval_utils accuracy_func_provider). Data comes
from .npz files with `images` [N,H,W,C] float and `labels` [N] int —
the torchvision ImageFolder loading of the reference reduces to this
array interface on TPU (host-side numpy feed).

Usage:
  python tasks/vision_classify.py --train-data train.npz \
      --valid-data val.npz --num-classes 10 --img-size 32 --patch-dim 4 \
      [--load-dir ckpt] --epochs 3
"""

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/tasks/", 1)[0])

import numpy as np
from megatronapp_tpu.config.arguments import parse_args


def make_classify_fwd(cfg, spec):
    """Jit the eval forward ONCE; pass the result to evaluate_accuracy
    from loops (a fresh jit per call would recompile every epoch)."""
    import jax

    from megatronapp_tpu.models.vision import vit_classify
    return jax.jit(lambda p, x: vit_classify(p, x, cfg, spec))


def evaluate_accuracy(params, cfg, spec, images, labels,
                      batch_size=64, fwd=None):
    """Top-1 accuracy over an array dataset (reference
    accuracy_func_provider/calculate_correct_answers)."""
    from tasks.common import padded_batches

    fwd = fwd or make_classify_fwd(cfg, spec)
    correct = 0
    done = 0
    for (chunk,), real in padded_batches([images], batch_size):
        logits = np.asarray(fwd(params, chunk))
        pred = logits.argmax(-1)[:real]
        correct += int((pred == labels[done: done + real]).sum())
        done += real
    return correct / max(len(images), 1)


def finetune_vision(train_images, train_labels, valid_images,
                    valid_labels, cfg, spec, *, epochs=3,
                    batch_size=64, lr=1e-3, seed=0,
                    pretrained_params=None, log_fn=print):
    """Epoch loop; returns (params, best_dev_accuracy)."""
    import jax
    import optax

    from megatronapp_tpu.models.vision import (
        init_vit_params, vit_classification_loss,
    )

    params, _ = init_vit_params(jax.random.PRNGKey(seed), cfg, spec)
    if pretrained_params is not None:
        # Graft the pretrained backbone; keep the fresh head (reference
        # finetune_utils: head reinitialized for the downstream label
        # space).
        for key in pretrained_params:
            if key in params and key != "head":
                params[key] = pretrained_params[key]

    opt = optax.adam(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, images, labels):
        (loss, metrics), g = jax.value_and_grad(
            lambda p: vit_classification_loss(p, images, labels, cfg,
                                              spec),
            has_aux=True)(params)
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    eval_fwd = make_classify_fwd(cfg, spec)
    rng = np.random.default_rng(seed)
    steps_per_epoch = max(len(train_images) // batch_size, 1)
    best = 0.0
    for epoch in range(epochs):
        order = rng.permutation(len(train_images))
        loss = None
        for s in range(steps_per_epoch):
            idx = order[s * batch_size: (s + 1) * batch_size]
            params, opt_state, loss = step(
                params, opt_state, train_images[idx], train_labels[idx])
        acc = evaluate_accuracy(params, cfg, spec, valid_images,
                                valid_labels, batch_size, fwd=eval_fwd)
        best = max(best, acc)
        log_fn(f"epoch {epoch+1}/{epochs} | train loss "
               f"{float(loss):.4f} | dev acc {acc:.4f}")
    return params, best


def main(argv=None):
    from megatronapp_tpu.models.vision import VitSpec, vit_config

    ap = argparse.ArgumentParser()
    ap.add_argument("--train-data", required=True, help=".npz images/labels")
    ap.add_argument("--valid-data", required=True)
    ap.add_argument("--num-classes", type=int, required=True)
    ap.add_argument("--img-size", type=int, default=224)
    ap.add_argument("--patch-dim", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--num-layers", type=int, default=12)
    ap.add_argument("--hidden-size", type=int, default=768)
    ap.add_argument("--num-attention-heads", type=int, default=12)
    ap.add_argument("--load-dir", default=None)
    args = parse_args(ap, argv)

    cfg = vit_config(num_layers=args.num_layers,
                     hidden_size=args.hidden_size,
                     num_attention_heads=args.num_attention_heads,
                     max_position_embeddings=(args.img_size //
                                              args.patch_dim) ** 2 + 1)
    spec = VitSpec(image_size=args.img_size, patch_size=args.patch_dim,
                   num_classes=args.num_classes)

    train = np.load(args.train_data)
    valid = np.load(args.valid_data)
    pretrained = None
    if args.load_dir:
        import jax

        from megatronapp_tpu.models.vision import init_vit_params
        from tasks.common import restore_params
        tmpl, _ = init_vit_params(jax.random.PRNGKey(0), cfg, spec)
        pretrained = restore_params(args.load_dir, tmpl)

    _, best = finetune_vision(
        np.asarray(train["images"], np.float32), np.asarray(
            train["labels"], np.int32),
        np.asarray(valid["images"], np.float32), np.asarray(
            valid["labels"], np.int32),
        cfg, spec, epochs=args.epochs,
        batch_size=args.batch_size, lr=args.lr,
        pretrained_params=pretrained)
    print(f"best dev accuracy: {best:.4f}")


if __name__ == "__main__":
    main()
