"""Sequence-classification finetuning (GLUE/RACE-style).

Parity with /root/reference/tasks/finetune_utils.py + tasks/glue/
(finetune a pretrained BERT encoder with a classification head over
labeled sentence pairs; epoch loop with dev-set accuracy). Data format:
TSV with `label<TAB>text_a[<TAB>text_b]` (the GLUE processors reduce to
this shape).

Usage:
  python tasks/finetune.py --task classify --train-data train.tsv \
      --valid-data dev.tsv --num-classes 2 \
      --load-dir /ckpts/bert --tokenizer-type BertWordPieceTokenizer \
      --epochs 3 --seq-length 128 ...
"""

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/tasks/", 1)[0])

import numpy as np
from megatronapp_tpu.config.arguments import parse_args


def read_tsv(path):
    """[(label:int, text_a, text_b|None)] from label<TAB>a[<TAB>b] lines."""
    rows = []
    with open(path) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) < 2 or not parts[0].strip():
                continue
            rows.append((int(parts[0]), parts[1],
                         parts[2] if len(parts) > 2 else None))
    return rows


def build_classification_batch(rows, tokenizer, ids, seq_length):
    """BERT-style [CLS] a [SEP] b [SEP] batches with tokentype ids."""
    tokens = np.full((len(rows), seq_length), ids.pad, np.int32)
    types = np.zeros((len(rows), seq_length), np.int32)
    mask = np.zeros((len(rows), seq_length), np.float32)
    labels = np.zeros((len(rows),), np.int32)
    for i, (label, a, b) in enumerate(rows):
        ta = tokenizer.tokenize(a)
        tb = tokenizer.tokenize(b) if b else []
        # Truncate the longer side first (reference clean_text/truncation
        # policy); budget = seq_length minus specials ([CLS] a [SEP] for
        # singles, [CLS] a [SEP] b [SEP] for pairs).
        budget = seq_length - (3 if tb else 2)
        while len(ta) + len(tb) > budget:
            (ta if len(ta) >= len(tb) else tb).pop()
        seq = [ids.cls, *ta, ids.sep]
        tt = [0] * len(seq)
        if tb:
            seq += [*tb, ids.sep]
            tt += [1] * (len(tb) + 1)
        tokens[i, : len(seq)] = seq
        types[i, : len(seq)] = tt
        mask[i, : len(seq)] = 1.0
        labels[i] = label
    return {"tokens": tokens, "tokentype_ids": types,
            "padding_mask": mask, "labels": labels}


def _pooled_logits(params, batch, cfg, ctx=None):
    """Shared scoring path: encoder → tanh pooler over [CLS] →
    classifier dense. [B', num_classes] fp32."""
    import jax.numpy as jnp

    from megatronapp_tpu.models.bert import bert_encode
    h = bert_encode(params, batch["tokens"], cfg,
                    padding_mask=batch["padding_mask"],
                    tokentype_ids=batch["tokentype_ids"], ctx=ctx)
    ch = params["classifier"]
    pooled = jnp.tanh(h[:, 0].astype(jnp.float32)
                      @ ch["pooler"].astype(jnp.float32)
                      + ch["pooler_bias"].astype(jnp.float32))
    return pooled @ ch["dense"].astype(jnp.float32) \
        + ch["dense_bias"].astype(jnp.float32)


def classification_loss(params, batch, cfg, num_classes, ctx=None):
    """CLS-pooled classification CE + accuracy (reference finetune_utils
    _cross_entropy_forward_step): BERT embeddings → encoder → tanh pooler
    over [CLS] → classifier dense (the LM head is bypassed)."""
    import jax.numpy as jnp

    from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
    cls_logits = _pooled_logits(params, batch, cfg, ctx=ctx)
    loss, _ = cross_entropy_loss(cls_logits[:, None],
                                 batch["labels"][:, None])
    acc = jnp.mean((jnp.argmax(cls_logits, -1)
                    == batch["labels"]).astype(jnp.float32))
    return loss, {"lm_loss": loss, "accuracy": acc}


def init_classifier_head(rng, cfg, num_classes):
    import jax
    import jax.numpy as jnp
    h = cfg.hidden_size
    k1, k2 = jax.random.split(rng)
    std = cfg.init_method_std
    return {
        "pooler": jax.random.normal(k1, (h, h), cfg.params_dtype) * std,
        "pooler_bias": jnp.zeros((h,), cfg.params_dtype),
        "dense": jax.random.normal(k2, (h, num_classes),
                                   cfg.params_dtype) * std,
        "dense_bias": jnp.zeros((num_classes,), cfg.params_dtype),
    }, {
        "pooler": ("embed", "embed"), "pooler_bias": ("embed",),
        "dense": ("embed", None), "dense_bias": (None,),
    }


def read_multichoice_jsonl(path):
    """[(label:int, context, question, [options])] from JSONL rows
    {"context","question","options","label"} (RACE articles reduce to
    this shape; reference tasks/race/data.py builds the same per-choice
    sequences)."""
    import json
    rows = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            d = json.loads(line)
            rows.append((int(d["label"]), d["context"], d["question"],
                         list(d["options"])))
    return rows


def build_multichoice_batch(rows, tokenizer, ids, seq_length,
                            max_qa_length=128):
    """RACE-style per-choice sequences: each question expands to
    NUM_CHOICES rows [CLS] context [SEP] question option [SEP] that
    collapse into the batch dim (reference RaceDataset.sample_multiplier,
    tasks/race/data.py:42-44). Returns batch with tokens [B*C, S] and
    labels [B]."""
    n_choices = len(rows[0][3])
    if any(len(r[3]) != n_choices for r in rows):
        raise ValueError(
            "multichoice rows disagree on option count: "
            f"{sorted({len(r[3]) for r in rows})} — labels would "
            "misalign with choice scores")
    bad = [r[0] for r in rows if not 0 <= r[0] < n_choices]
    if bad:
        raise ValueError(
            f"multichoice labels out of range [0,{n_choices}): {bad[:5]} "
            "— take_along_axis would silently clamp them")
    expanded = []
    for label, context, question, options in rows:
        tc_full = tokenizer.tokenize(context)  # once per row, not per opt
        for opt in options:
            qa = tokenizer.tokenize(f"{question} {opt}")
            # QA capped so [CLS] + ≥1 context token + [SEP] qa [SEP]
            # always fits (reference truncates the QA to max_qa_length
            # and the context to the remainder).
            qa = qa[:min(max_qa_length, seq_length - 4)]
            expanded.append((tc_full, qa))
    tokens = np.full((len(expanded), seq_length), ids.pad, np.int32)
    types = np.zeros((len(expanded), seq_length), np.int32)
    mask = np.zeros((len(expanded), seq_length), np.float32)
    for i, (tc_full, qa_tokens) in enumerate(expanded):
        budget = seq_length - 3 - len(qa_tokens)
        tc = tc_full[:max(budget, 1)]
        seq = [ids.cls, *tc, ids.sep, *qa_tokens, ids.sep]
        tt = [0] * (len(tc) + 2) + [1] * (len(qa_tokens) + 1)
        tokens[i, : len(seq)] = seq
        types[i, : len(seq)] = tt
        mask[i, : len(seq)] = 1.0
    labels = np.asarray([r[0] for r in rows], np.int32)
    return {"tokens": tokens, "tokentype_ids": types,
            "padding_mask": mask, "labels": labels,
            "num_choices": n_choices}


def multichoice_loss(params, batch, cfg, num_choices, ctx=None):
    """Score each choice-sequence with the 1-logit head, softmax over the
    choices (reference RACE: classification head num_classes=1 with the
    sample multiplier collapsing into batch)."""
    import jax.numpy as jnp

    from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
    scores = _pooled_logits(params, batch, cfg, ctx=ctx)  # [B*C, 1]
    scores = scores.reshape(-1, num_choices)              # [B, C]
    loss, _ = cross_entropy_loss(scores[:, None],
                                 batch["labels"][:, None])
    acc = jnp.mean((jnp.argmax(scores, -1)
                    == batch["labels"]).astype(jnp.float32))
    return loss, {"lm_loss": loss, "accuracy": acc}


def finetune_classification(train_rows, valid_rows, tokenizer, ids, cfg,
                            num_classes, *, epochs=3, batch_size=16,
                            lr=2e-5, seq_length=128, seed=0,
                            pretrained_params=None, log_fn=print,
                            multichoice=False, save_predictions=None):
    """Epoch loop (reference finetune_utils.finetune): train on train_rows,
    report dev accuracy each epoch. Returns (params, best_accuracy).

    multichoice=True switches to RACE semantics: rows are
    (label, context, question, options), the head has 1 logit, and
    softmax runs over the expanded choice sequences."""
    import jax
    import jax.numpy as jnp

    from megatronapp_tpu.config.training_config import OptimizerConfig
    from megatronapp_tpu.models.bert import init_bert_params
    from megatronapp_tpu.training.optimizer import get_optimizer

    rng = jax.random.PRNGKey(seed)
    params, _ = init_bert_params(rng, cfg, add_binary_head=False)
    if pretrained_params is not None:
        # Graft the pretrained encoder; keep the fresh classifier.
        for key in pretrained_params:
            if key in params:
                params[key] = pretrained_params[key]
    if multichoice:
        num_classes = 1
        num_choices = len(train_rows[0][3])
        vbad = {len(r[3]) for r in valid_rows} - {num_choices}
        if vbad:
            raise ValueError(
                f"valid set has option counts {sorted(vbad)} but the "
                f"train set has {num_choices} — scores would be "
                "misgrouped at the reshape")
    params["classifier"], _ = init_classifier_head(rng, cfg, num_classes)

    def build(rows):
        if multichoice:
            b = build_multichoice_batch(rows, tokenizer, ids, seq_length)
            b.pop("num_choices")  # static; closed over in loss_for
            return b
        return build_classification_batch(rows, tokenizer, ids,
                                          seq_length)

    def loss_for(p, batch):
        if multichoice:
            return multichoice_loss(p, batch, cfg, num_choices)
        return classification_loss(p, batch, cfg, num_classes)

    steps_per_epoch = max(len(train_rows) // batch_size, 1)
    # min_lr must sit below the finetune LR (2e-5 default is smaller than
    # OptimizerConfig's pretrain-scale min_lr) or "decay" would raise it.
    optimizer = get_optimizer(
        OptimizerConfig(lr=lr, min_lr=0.0, lr_warmup_iters=0),
        epochs * steps_per_epoch)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state, batch, step_i):
        del step_i
        (loss, metrics), g = jax.value_and_grad(
            lambda p: loss_for(p, batch), has_aux=True)(params)
        updates, opt_state = optimizer.update(g, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u.astype(p.dtype), params,
                              updates)
        return params, opt_state, loss, metrics

    @jax.jit
    def evaluate(params, batch):
        return loss_for(params, batch)[1]

    rng_np = np.random.default_rng(seed)
    best = 0.0
    for epoch in range(epochs):
        order = rng_np.permutation(len(train_rows))
        for s in range(steps_per_epoch):
            idx = order[s * batch_size: (s + 1) * batch_size]
            rows = [train_rows[i] for i in idx]
            params, opt_state, loss, metrics = step(
                params, opt_state, build(rows), s)
        # Dev accuracy (single padded batch per eval chunk).
        correct = total = 0
        for s in range(0, len(valid_rows), batch_size):
            rows = valid_rows[s: s + batch_size]
            m = evaluate(params, build(rows))
            correct += float(m["accuracy"]) * len(rows)
            total += len(rows)
        acc = correct / max(total, 1)
        best = max(best, acc)
        log_fn(f"epoch {epoch+1}/{epochs} | train loss "
               f"{float(loss):.4f} | dev acc {acc:.4f}")
    if save_predictions:
        # Final dev-set class scores for tasks/ensemble_classifier.py
        # (reference finetune_utils saves (predictions, labels, uid)).
        import hashlib
        logits_fn = jax.jit(lambda p, b: _pooled_logits(p, b, cfg))
        rows_logits = []
        for s in range(0, len(valid_rows), batch_size):
            rows = valid_rows[s: s + batch_size]
            scores = np.asarray(logits_fn(params, build(rows)))
            if multichoice:
                scores = scores.reshape(-1, num_choices)
            rows_logits.append(scores)
        # Content-derived uid: runs over DIFFERENT dev files must not
        # pass the ensemble's alignment check by length coincidence.
        uid = np.asarray([
            int.from_bytes(hashlib.sha1(
                repr(r[1:]).encode()).digest()[:8], "little")
            for r in valid_rows], np.uint64)
        np.savez(save_predictions,
                 logits=np.concatenate(rows_logits),
                 labels=np.asarray([r[0] for r in valid_rows], np.int32),
                 uid=uid)
        log_fn(f"predictions → {save_predictions}")
    return params, best


def main(argv=None):
    from megatronapp_tpu.models.bert import bert_config

    ap = argparse.ArgumentParser()
    ap.add_argument("--task", default="classify",
                    choices=["classify", "multichoice"],
                    help="classify = GLUE-style TSV pairs; multichoice = "
                         "RACE-style JSONL (context/question/options)")
    ap.add_argument("--train-data", required=True)
    ap.add_argument("--valid-data", required=True)
    ap.add_argument("--num-classes", type=int, default=None,
                    help="required for --task classify; ignored for "
                         "multichoice (1-logit head over choices)")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-5)
    ap.add_argument("--seq-length", type=int, default=128)
    ap.add_argument("--num-layers", type=int, default=12)
    ap.add_argument("--hidden-size", type=int, default=768)
    ap.add_argument("--num-attention-heads", type=int, default=12)
    ap.add_argument("--vocab-size", type=int, default=30592)
    ap.add_argument("--tokenizer-type", default="BertWordPieceTokenizer")
    ap.add_argument("--tokenizer-name-or-path", default=None)
    ap.add_argument("--load-dir", default=None)
    ap.add_argument("--save-predictions", default=None,
                    help=".npz of final dev-set scores for "
                         "tasks/ensemble_classifier.py")
    args = parse_args(ap, argv)

    from tasks.common import build_tok_and_ids, restore_params
    tok, ids = build_tok_and_ids(args.tokenizer_type,
                                 args.tokenizer_name_or_path,
                                 args.vocab_size)
    cfg = bert_config(num_layers=args.num_layers,
                      hidden_size=args.hidden_size,
                      num_attention_heads=args.num_attention_heads,
                      vocab_size=args.vocab_size,
                      max_position_embeddings=args.seq_length)
    pretrained = None
    if args.load_dir:
        import jax

        from megatronapp_tpu.models.bert import init_bert_params
        tmpl, _ = init_bert_params(jax.random.PRNGKey(0), cfg)
        pretrained = restore_params(args.load_dir, tmpl)

    if args.task == "classify" and args.num_classes is None:
        ap.error("--num-classes is required for --task classify")
    reader = (read_multichoice_jsonl if args.task == "multichoice"
              else read_tsv)
    _, best = finetune_classification(
        reader(args.train_data), reader(args.valid_data), tok, ids,
        cfg, args.num_classes, epochs=args.epochs,
        batch_size=args.batch_size, lr=args.lr,
        seq_length=args.seq_length, pretrained_params=pretrained,
        multichoice=args.task == "multichoice",
        save_predictions=args.save_predictions)
    print(f"best dev accuracy: {best:.4f}")


if __name__ == "__main__":
    main()
