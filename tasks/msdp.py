"""Knowledge-grounded dialogue (MSDP) prompting + evaluation metrics.

Parity with /root/reference/tasks/msdp/ (multi-stage dialogue prompting:
metrics.py token-F1 over normalized text, evaluate.py F1 scoring of
generated responses vs ground truth, prompt.py few-shot prompt assembly
served through the generation engine). The KILT/WoW data prep of
preprocessing.py reduces to the same line-per-example text interface.

Library surface:
  normalize_answer, f1_score, corpus_f1  — response-vs-gold scoring
  distinct_n                              — generation diversity
  build_knowledge_prompt, build_response_prompt — few-shot assembly
  evaluate_file                           — CLI: guesses vs answers files
"""

import argparse
import re
import sys
from collections import Counter
from typing import List, Sequence, Tuple

sys.path.insert(0, __file__.rsplit("/tasks/", 1)[0])

_ARTICLES = re.compile(r"\b(a|an|the)\b")
_PUNCT = re.compile(r"[!\"#$%&()*+,\-./:;<=>?@\[\]\\^`{|}~_']")


def normalize_answer(s: str) -> str:
    """Lowercase, strip punctuation/articles/extra whitespace (the
    standard SQuAD/ParlAI normalization the reference uses)."""
    s = _PUNCT.sub(" ", s.lower())
    s = _ARTICLES.sub(" ", s)
    return " ".join(s.split())


def f1_score(guess: str, answer: str) -> Tuple[float, float, float]:
    """(precision, recall, f1) over normalized token multisets."""
    pred = normalize_answer(guess).split()
    gold = normalize_answer(answer).split()
    common = Counter(pred) & Counter(gold)
    overlap = sum(common.values())
    if overlap == 0:
        return 0.0, 0.0, 0.0
    p = overlap / len(pred)
    r = overlap / len(gold)
    return p, r, 2 * p * r / (p + r)


def corpus_f1(guesses: Sequence[str], answers: Sequence[str]
              ) -> Tuple[float, float, float]:
    """Mean (p, r, f1) over pairs (reference F1Metric.compute_all_pairs
    semantics)."""
    if len(guesses) != len(answers):
        raise ValueError(f"{len(guesses)} guesses vs {len(answers)} "
                         "answers")
    if not guesses:
        raise ValueError("nothing to score")
    triples = [f1_score(g, a) for g, a in zip(guesses, answers)]
    n = len(triples)
    return (sum(t[0] for t in triples) / n,
            sum(t[1] for t in triples) / n,
            sum(t[2] for t in triples) / n)


def distinct_n(texts: Sequence[str], n: int = 2) -> float:
    """Fraction of distinct n-grams across generations (diversity
    metric reported alongside F1 in dialogue eval)."""
    grams = Counter()
    for t in texts:
        toks = normalize_answer(t).split()
        for i in range(len(toks) - n + 1):
            grams[tuple(toks[i: i + n])] += 1
    total = sum(grams.values())
    return len(grams) / total if total else 0.0


def build_knowledge_prompt(examples: List[dict], topic: str,
                           dialogue: List[str]) -> str:
    """Stage-1 prompt (knowledge generation): few-shot examples of
    (topic, last turn → knowledge), then the query (reference
    prompt.py knowledge-generation stage)."""
    parts = []
    for ex in examples:
        parts.append(f"( {ex['topic']} ) {ex['turn']} => {ex['knowledge']}")
    parts.append(f"( {topic} ) {dialogue[-1]} =>")
    return "\n".join(parts)


def build_response_prompt(examples: List[dict], topic: str,
                          dialogue: List[str], knowledge: str) -> str:
    """Stage-2 prompt (response generation): few-shot examples of
    (turn + knowledge → response)."""
    parts = []
    for ex in examples:
        parts.append(f"Topic: {ex['topic']}. User says: {ex['turn']} "
                     f"We know that: {ex['knowledge']} "
                     f"System replies: {ex['response']}")
    parts.append(f"Topic: {topic}. User says: {dialogue[-1]} "
                 f"We know that: {knowledge} System replies:")
    return "\n".join(parts)


def evaluate_file(guess_path: str, answer_path: str, log_fn=print):
    """Line-aligned generation file vs ground-truth file → metrics
    (reference evaluate.py evaluate_f1)."""
    # Keep line alignment: blank generations are legitimate (scored 0),
    # so only the trailing newline's empty element is dropped — dropping
    # interior blanks independently on each side would silently mis-pair
    # every line after them.
    def read_lines(path):
        with open(path) as f:
            lines = [l.rstrip("\n") for l in f]
        if lines and lines[-1] == "":
            lines.pop()
        return lines

    guesses = read_lines(guess_path)
    answers = read_lines(answer_path)
    p, r, f1 = corpus_f1(guesses, answers)
    d1, d2 = distinct_n(guesses, 1), distinct_n(guesses, 2)
    log_fn(f"precision {p:.4f} | recall {r:.4f} | F1 {f1:.4f} | "
           f"distinct-1 {d1:.4f} | distinct-2 {d2:.4f} "
           f"({len(guesses)} pairs)")
    return {"precision": p, "recall": r, "f1": f1,
            "distinct_1": d1, "distinct_2": d2}


def main(argv=None):
    ap = argparse.ArgumentParser(__doc__)
    ap.add_argument("--guess-file", required=True)
    ap.add_argument("--answer-file", required=True)
    args = ap.parse_args(argv)
    evaluate_file(args.guess_file, args.answer_file)


if __name__ == "__main__":
    main()
