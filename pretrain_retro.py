"""Retro (retrieval-augmented) pretraining entry point.

Parity with /root/reference/pretrain_retro.py: decoder with chunked
cross-attention to retrieved neighbors (synthetic token/neighbor stream
unless a retrieval database is wired in — reference tools/retro builds
one offline).
"""

import time

import jax
import numpy as np

from megatronapp_tpu.config.arguments import build_parser, configs_from_args, parse_args
from megatronapp_tpu.models.retro import (
    RetroSpec, init_retro_params, retro_loss,
)
from megatronapp_tpu.parallel.mesh import build_mesh
from megatronapp_tpu.training.optimizer import get_optimizer
from megatronapp_tpu.training.train import reshape_global_batch
from megatronapp_tpu.training.train_state import setup_train_state
from megatronapp_tpu.training.train_step import make_train_step


def main(argv=None):
    ap = build_parser("pretrain_retro (megatronapp-tpu)")
    ap.add_argument("--retro-chunk-length", type=int, default=64)
    ap.add_argument("--retro-num-neighbors", type=int, default=2)
    ap.add_argument("--retro-retrieved-length", type=int, default=128)
    ap.add_argument("--retro-encoder-layers", type=int, default=2)
    ap.add_argument("--retro-data", type=str, default=None,
                    help=".npz from tools/retro_preprocess.py "
                         "(samples + neighbors); synthetic stream if "
                         "absent")
    args = parse_args(ap, argv)
    cfg, parallel, training, opt_cfg = configs_from_args(args)
    spec = RetroSpec(chunk_length=args.retro_chunk_length,
                     num_neighbors=args.retro_num_neighbors,
                     retrieved_length=args.retro_retrieved_length,
                     cca_layers=tuple(
                         range(1, cfg.num_layers, 3)) or (1,))
    import dataclasses

    from megatronapp_tpu.config.transformer_config import AttnMaskType
    enc_cfg = dataclasses.replace(
        cfg, num_layers=args.retro_encoder_layers,
        attn_mask_type=AttnMaskType.bidirectional)

    ctx = build_mesh(parallel)
    optimizer = get_optimizer(opt_cfg, training.train_iters)
    state, shardings, _ = setup_train_state(
        jax.random.PRNGKey(training.seed),
        lambda k: init_retro_params(k, cfg, enc_cfg, spec), optimizer,
        ctx)

    def loss_fn(p, micro):
        return retro_loss(p, micro["tokens"], micro["neighbors"],
                          micro["labels"], micro["loss_mask"], cfg,
                          enc_cfg, spec, ctx=ctx)

    step_fn = make_train_step(loss_fn, optimizer, opt_cfg, ctx, shardings,
                              training.train_iters)
    num_micro = training.num_microbatches(ctx.dp * ctx.ep)
    n_chunks = training.seq_length // spec.chunk_length

    retro_data = None
    if args.retro_data:
        retro_data = np.load(args.retro_data)
        samples, neigh = retro_data["samples"], retro_data["neighbors"]
        sample_mask = (retro_data["mask"] if "mask" in retro_data.files
                       else None)
        if len(samples) == 0:
            raise SystemExit(f"--retro-data {args.retro_data} contains "
                             "no samples")
        if samples.shape[1] != training.seq_length:
            raise SystemExit(
                f"--retro-data samples are length {samples.shape[1]} but "
                f"--seq-length is {training.seq_length}")
        if neigh.shape[1:] != (n_chunks, spec.num_neighbors,
                               spec.retrieved_length):
            raise SystemExit(
                f"--retro-data neighbors {neigh.shape[1:]} mismatch the "
                f"retro spec {(n_chunks, spec.num_neighbors, spec.retrieved_length)}")
        print(f"retro corpus: {len(samples)} samples from "
              f"{args.retro_data}")

    rng = np.random.default_rng(training.seed)
    losses = []
    t0 = time.perf_counter()
    with ctx.mesh:
        for it in range(training.train_iters):
            if retro_data is not None:
                idx = (np.arange(training.global_batch_size)
                       + it * training.global_batch_size) % len(samples)
                toks = samples[idx]
                nb = neigh[idx]
                mask_rows = (sample_mask[idx] if sample_mask is not None
                             else None)
            else:
                mask_rows = None
                toks = rng.integers(0, cfg.vocab_size, (
                    training.global_batch_size, training.seq_length)
                ).astype(np.int32)
                nb = rng.integers(0, cfg.vocab_size, (
                    training.global_batch_size, n_chunks,
                    spec.num_neighbors, spec.retrieved_length)
                ).astype(np.int32)
            # The rolled label at the final position wraps to the
            # sample's own first token — mask it out (harmless on the
            # synthetic stream, a wrong signal on real corpus samples).
            # Real data also masks document-tail chunk padding: the label
            # at position t is toks[t+1], so drop positions whose TARGET
            # is padding (shifted mask) as well as padded positions.
            loss_mask = np.ones_like(toks, np.float32)
            if mask_rows is not None:
                loss_mask = mask_rows * np.roll(mask_rows, -1, axis=1)
            loss_mask[:, -1] = 0.0
            batch = reshape_global_batch({
                "tokens": toks,
                "neighbors": nb,
                "labels": np.roll(toks, -1, axis=1),
                "loss_mask": loss_mask,
            }, num_micro)
            state, metrics = step_fn(state, batch)
            if (it + 1) % training.log_interval == 0 or \
                    it + 1 == training.train_iters:
                metrics = jax.device_get(metrics)
                losses.append(float(metrics["loss"]))
                print(f"iter {it+1:6d}/{training.train_iters} | "
                      f"loss {float(metrics['loss']):.4f}")
    dt = time.perf_counter() - t0
    tokens = training.train_iters * training.global_batch_size * \
        training.seq_length
    print(f"done: final loss {losses[-1]:.4f}, {tokens/dt:,.0f} tok/s")


if __name__ == "__main__":
    main()
