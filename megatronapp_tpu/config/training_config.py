"""Training/optimizer configuration.

Mirrors the reference argument groups (/root/reference/megatron/training/
arguments.py — _add_training_args, _add_learning_rate_args,
_add_regularization_args, _add_checkpointing_args) and
OptimizerConfig (/root/reference/megatron/core/optimizer/optimizer_config.py),
reduced to the knobs that matter on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class OptimizerConfig:
    optimizer: str = "adam"          # 'adam' | 'sgd'
    lr: float = 3e-4
    min_lr: float = 3e-5
    lr_decay_style: str = "cosine"   # 'cosine' | 'linear' | 'constant'
    lr_warmup_iters: int = 0
    lr_decay_iters: Optional[int] = None  # default: train_iters
    weight_decay: float = 0.01
    adam_beta1: float = 0.9
    adam_beta2: float = 0.95
    adam_eps: float = 1e-8
    sgd_momentum: float = 0.9
    clip_grad: float = 1.0
    # bf16 grad all-reduce (reference --accumulate-allreduce-grads-in-fp32
    # inverse); we accumulate in fp32 by default.
    grad_reduce_in_fp32: bool = True


@dataclasses.dataclass
class TrainingConfig:
    micro_batch_size: int = 1
    global_batch_size: int = 8
    seq_length: int = 512
    train_iters: int = 100
    seed: int = 1234
    log_interval: int = 10
    eval_interval: Optional[int] = None
    eval_iters: int = 10
    save_interval: Optional[int] = None
    save_dir: Optional[str] = None
    load_dir: Optional[str] = None
    exit_interval: Optional[int] = None
    # Linear batch-size rampup (reference --rampup-batch-size
    # "<start> <increment> <samples>"): grow the global batch from start to
    # global_batch_size over the first `samples` consumed samples.
    rampup_batch_size: Optional[tuple] = None
    # Direct-to-shards state init (--sharded-init): params/optimizer
    # state never materialize unsharded — for giant-model runs whose
    # replicated init would OOM a device. Off by default: the two-stage
    # replicated-then-reshard init is the one whose seeded values are
    # provably mesh-independent on this jax build (train_state.py).
    sharded_init: bool = False
    # NaN/spike guard (reference rerun_state_machine result validation).
    check_for_nan_in_loss: bool = True
    loss_spike_factor: float = 10.0
    # Rerun state machine (reference --rerun-mode / --error-injection-rate,
    # arguments.py:1795-1812): 'disabled' | 'validate_results'.
    rerun_mode: str = "validate_results"
    error_injection_rate: float = 0.0
    # Host-side straggler detector (reference --log-straggler).
    log_straggler: bool = False
    # Workload-inspector HTTP server (reference
    # --run-workload-inspector-server): /status, /straggler/*, /probe.
    run_workload_inspector_server: bool = False
    workload_inspector_port: int = 0
    # Metrics sinks (reference --tensorboard-dir / wandb analogues).
    metrics_jsonl: Optional[str] = None
    tensorboard_dir: Optional[str] = None
    # MegaScan tracing (reference --trace / --trace-interval /
    # --continuous-trace-iterations, arguments.py:2705ff).
    trace: bool = False
    trace_interval: int = 5
    continuous_trace_iterations: int = 2
    trace_dir: str = "trace"
    trace_granularity: str = "full"

    def num_microbatches(self, data_parallel: int) -> int:
        denom = self.micro_batch_size * data_parallel
        if self.global_batch_size % denom != 0:
            raise ValueError(
                f"global_batch_size={self.global_batch_size} not divisible by "
                f"micro_batch_size*dp={denom}")
        return self.global_batch_size // denom
