"""Training/optimizer configuration.

Mirrors the reference argument groups (/root/reference/megatron/training/
arguments.py — _add_training_args, _add_learning_rate_args,
_add_regularization_args, _add_checkpointing_args) and
OptimizerConfig (/root/reference/megatron/core/optimizer/optimizer_config.py),
reduced to the knobs that matter on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class OptimizerConfig:
    optimizer: str = "adam"          # 'adam' | 'sgd'
    lr: float = 3e-4
    min_lr: float = 3e-5
    lr_decay_style: str = "cosine"   # 'cosine' | 'linear' | 'constant'
    lr_warmup_iters: int = 0
    lr_decay_iters: Optional[int] = None  # default: train_iters
    weight_decay: float = 0.01
    adam_beta1: float = 0.9
    adam_beta2: float = 0.95
    adam_eps: float = 1e-8
    sgd_momentum: float = 0.9
    clip_grad: float = 1.0
    # bf16 grad all-reduce (reference --accumulate-allreduce-grads-in-fp32
    # inverse); we accumulate in fp32 by default.
    grad_reduce_in_fp32: bool = True
    # ZeRO-1 distributed-optimizer mixed precision (reference
    # --main-params-dtype / --exp-avg-dtype / --exp-avg-sq-dtype,
    # precision-aware DistributedOptimizer): dtype of the fp32
    # master-weight shard (kept only when params are lower precision)
    # and of the stored Adam moments — update math stays fp32.
    # 'fp32' | 'bf16' (and the long spellings); validated at parse time.
    main_params_dtype: str = "fp32"
    exp_avg_dtype: str = "fp32"
    exp_avg_sq_dtype: str = "fp32"
    # Collectives of the ZeRO-1 weight update: 'gspmd' lets XLA insert
    # the grad slice / param all-gather from the dp-sharded state layout
    # (arXiv 2004.13336); 'ring' runs the update full-manual with the
    # latency-hiding ring all-gather from parallel/overlap.py; 'bulk'
    # full-manual with one tiled all-gather (the A/B baseline ring is
    # measured against).
    dist_opt_comm: str = "gspmd"


@dataclasses.dataclass
class TrainingConfig:
    micro_batch_size: int = 1
    global_batch_size: int = 8
    seq_length: int = 512
    train_iters: int = 100
    seed: int = 1234
    log_interval: int = 10
    eval_interval: Optional[int] = None
    eval_iters: int = 10
    save_interval: Optional[int] = None
    save_dir: Optional[str] = None
    load_dir: Optional[str] = None
    exit_interval: Optional[int] = None
    # Linear batch-size rampup (reference --rampup-batch-size
    # "<start> <increment> <samples>"): grow the global batch from start to
    # global_batch_size over the first `samples` consumed samples.
    rampup_batch_size: Optional[tuple] = None
    # Direct-to-shards state init (--sharded-init): params/optimizer
    # state never materialize unsharded — for giant-model runs whose
    # replicated init would OOM a device. Off by default: the two-stage
    # replicated-then-reshard init is the one whose seeded values are
    # provably mesh-independent on this jax build (train_state.py).
    sharded_init: bool = False
    # NaN/spike guard (reference rerun_state_machine result validation).
    check_for_nan_in_loss: bool = True
    loss_spike_factor: float = 10.0
    # Rerun state machine (reference --rerun-mode / --error-injection-rate,
    # arguments.py:1795-1812): 'disabled' | 'validate_results'.
    rerun_mode: str = "validate_results"
    error_injection_rate: float = 0.0
    # Graceful-exit signal handler (reference --exit-signal-handler /
    # dist_signal_handler.py): SIGTERM finishes the in-flight step,
    # force-saves an emergency checkpoint + side state, and exits
    # cleanly; the exit decision is agreed across processes
    # (training/signals.py should_exit). sigint additionally catches ^C.
    exit_signal_handler: bool = False
    exit_signal_handler_sigint: bool = False
    # Heartbeat monitor with section timeouts (reference ft_integration:
    # --heartbeat-dir writes heartbeat.json for an external supervisor;
    # ft_timeouts = (setup, step, checkpointing) seconds for the
    # in-process watchdog). Enabled when either is set.
    heartbeat_dir: Optional[str] = None
    ft_timeouts: Optional[tuple] = None
    # FT drill fault: ("hang"|"exit", delay_s) — reference
    # maybe_setup_simulated_fault. 'exit' hard-kills the process after
    # delay; 'hang' wedges the train loop (the heartbeat watchdog and
    # the external supervisor must catch it).
    simulated_fault: Optional[tuple] = None
    # Fast non-persistent local checkpoints (reference
    # --non-persistent-save-interval / --non-persistent-ckpt-dir,
    # LocalCheckpointManager): latest-only .npz every N steps for fast
    # preemption restarts, independent of the durable Orbax saves.
    # Restore prefers the freshest of (local, durable).
    non_persistent_save_interval: Optional[int] = None
    non_persistent_ckpt_dir: Optional[str] = None

    def resolved_non_persistent_dir(self) -> Optional[str]:
        """Where the local checkpoints live: the explicit dir, else
        <save_dir>/non_persistent when local saves are enabled, else
        None. The ONE home of the default-location policy (parse-time
        validation in config/arguments.py and the train loop both use
        it)."""
        if self.non_persistent_ckpt_dir:
            return self.non_persistent_ckpt_dir
        if self.non_persistent_save_interval and self.save_dir:
            import os
            return os.path.join(self.save_dir, "non_persistent")
        return None

    # Host-side straggler detector (reference --log-straggler).
    log_straggler: bool = False
    # Workload-inspector HTTP server (reference
    # --run-workload-inspector-server): /status, /straggler/*, /probe.
    run_workload_inspector_server: bool = False
    workload_inspector_port: int = 0
    # Metrics sinks (reference --tensorboard-dir / wandb analogues).
    metrics_jsonl: Optional[str] = None
    tensorboard_dir: Optional[str] = None
    # MegaScan tracing (reference --trace / --trace-interval /
    # --continuous-trace-iterations, arguments.py:2705ff).
    trace: bool = False
    trace_interval: int = 5
    continuous_trace_iterations: int = 2
    trace_dir: str = "trace"
    trace_granularity: str = "full"

    def num_microbatches(self, data_parallel: int) -> int:
        denom = self.micro_batch_size * data_parallel
        if self.global_batch_size % denom != 0:
            raise ValueError(
                f"global_batch_size={self.global_batch_size} not divisible by "
                f"micro_batch_size*dp={denom}")
        return self.global_batch_size // denom
