"""Argument system: reference-compatible flags → config dataclasses.

Parity with /root/reference/megatron/training/arguments.py (2719 LoC, ~28
_add_*_args groups :1059-2656 + validate_args): the flag NAMES follow the
reference so launch scripts translate 1:1; values land in our
TransformerConfig / ParallelConfig / TrainingConfig / OptimizerConfig
dataclasses instead of a global args namespace.
"""

from __future__ import annotations

import argparse
from typing import Optional, Tuple

import jax.numpy as jnp

from megatronapp_tpu.config.parallel_config import ParallelConfig
from megatronapp_tpu.config.training_config import (
    OptimizerConfig, TrainingConfig,
)
from megatronapp_tpu.config.transformer_config import (
    ActivationKind, NormKind, PositionEmbeddingKind, TransformerConfig,
)


def add_serving_args(ap: argparse.ArgumentParser):
    """Serving / paged-KV flags (ISSUE 3) — single source of truth shared
    by the main parser (so config-YAML runs and --use-checkpoint-args
    carry them) and tools/run_text_generation_server.py, which consumes
    them to assemble the engine."""
    g = ap.add_argument_group("serving")
    g.add_argument("--engine", choices=["static", "dynamic", "mamba"],
                   default="static",
                   help="dynamic = continuous batching (connections "
                        "share one decode batch through the server's "
                        "stepper thread, inference/dynamic_engine.py); "
                        "mamba = recurrent-state decode for pure-M "
                        "presets (reference mamba server tool)")
    g.add_argument("--max-batch", type=int, default=4,
                   help="dynamic engine: concurrent decode slots")
    g.add_argument("--paged-kv-cache", action="store_true",
                   help="with --engine dynamic: block-pool paged KV "
                        "cache + ragged paged-attention decode "
                        "(inference/paged_cache.py, "
                        "ops/pallas/paged_attention.py) — per-block "
                        "admission, prefix caching, preemption")
    g.add_argument("--kv-block-size", type=int, default=16,
                   help="tokens per KV block")
    g.add_argument("--num-kv-blocks", type=int, default=None,
                   help="pool size (default: dense capacity max_batch * "
                        "ceil(max_seq_len/block_size); size down to run "
                        "oversubscribed with preemption)")
    g.add_argument("--no-prefix-caching", action="store_false",
                   dest="prefix_caching",
                   help="disable refcounted shared-prefix block reuse")
    # Quantized serving (ISSUE 10 int8, ISSUE 13 fp8). Choices AND help
    # derive from the shared KV_CACHE_DTYPES registry
    # (inference/paged_cache.py) — the flag, the server validation, and
    # the pool check cannot drift apart.
    from megatronapp_tpu.inference.paged_cache import (
        KV_CACHE_DTYPES, kv_cache_dtype_help,
    )
    g.add_argument("--kv-cache-dtype", choices=sorted(KV_CACHE_DTYPES),
                   default="bf16",
                   help="paged KV-pool storage dtype — "
                        + kv_cache_dtype_help()
                        + " (quantized dtypes need --paged-kv-cache; "
                        "MLA latent/pe pools quantize with per-row "
                        "scalar scales; quantized pools cost "
                        "~(D+4)/2D of the bf16 bytes)")
    g.add_argument("--megakernel-decode", action="store_true",
                   help="fused (megakernel) decode step (ISSUE 11/16, "
                        "ops/pallas/kernel_gen.py): the per-token layer "
                        "body runs as fat Pallas kernels around the "
                        "paged-attention kernel instead of the "
                        "~15-fusion unfused tail (needs --engine "
                        "dynamic --paged-kv-cache; streams stay "
                        "token-exact). Large H/FFN shapes grid-tile "
                        "their weight columns to fit "
                        "--megakernel-vmem-budget; resident "
                        "--quantized-weights dequantize in-register; "
                        "speculative verify and chunked prefill run "
                        "the fused ragged step; composes with "
                        "--serve-disagg and --serve-fleet; MLA runs "
                        "the fused latent prologue + absorbed-q latent "
                        "kernel. Ineligible configs (MoE, --serve-tp>1, "
                        "MegaScope hooks) keep the unfused step with a "
                        "logged reason")
    g.add_argument("--megakernel-vmem-budget", type=int, default=None,
                   metavar="BYTES",
                   help="per-kernel operand budget (bytes) for the "
                        "fused decode kernels — tile counts are chosen "
                        "as the smallest grid that fits it (default: "
                        "MEGAKERNEL_VMEM_BUDGET env or 12 MiB; values "
                        "above ~16 MiB/core exceed real TPU VMEM and "
                        "are warned). The fallback log names this flag "
                        "when even the finest tiling cannot fit")
    g.add_argument("--scan-unroll", type=int, default=1,
                   help="lax.scan unroll factor for the layer stack "
                        "(PERF.md lever #3): unrolls the training "
                        "layer scan AND the serving decode/multi-query "
                        "step scans — pairs with --megakernel-decode")
    g.add_argument("--quantized-weights", action="store_true",
                   help="serve from int8 weights kept RESIDENT (per-"
                        "channel dequant fused at matmul entry, param "
                        "HBM ~halved) instead of dequantize-on-load; "
                        "pairs with --load-quantized, otherwise the "
                        "loaded/initialized params are PTQ-quantized at "
                        "startup")
    g.add_argument("--spec-method", default="none",
                   choices=["none", "draft", "mtp", "ngram"],
                   help="speculative decoding over the paged engine "
                        "(inference/speculative.py; needs --engine "
                        "dynamic --paged-kv-cache): draft = small draft "
                        "model (--draft-model), mtp = self-draft through "
                        "the model's MTP heads, ngram = model-free "
                        "prompt lookup. Greedy output is bit-identical "
                        "to plain decode; sampling preserves the target "
                        "distribution exactly")
    g.add_argument("--spec-k", type=int, default=4,
                   help="max draft tokens verified per round (the "
                        "verify step runs K+1 ragged queries through "
                        "the multi-query paged-attention kernel)")
    g.add_argument("--draft-model", default=None,
                   help="models/presets.py preset for --spec-method "
                        "draft (must share the target vocab/tokenizer)")
    g.add_argument("--draft-load-dir", default=None,
                   help="checkpoint dir for the draft model (otherwise "
                        "randomly initialized — only useful for "
                        "plumbing tests)")
    # Disaggregated serving (ISSUE 9, inference/disagg.py).
    g.add_argument("--serve-disagg", action="store_true",
                   help="prefill/decode disaggregation: split the "
                        "devices into a prefill sub-mesh and a decode "
                        "sub-mesh (2*serve_tp devices total) with KV "
                        "handoff through the shared block pool — decode "
                        "token intervals stop being hostage to long "
                        "prefills (needs --engine dynamic "
                        "--paged-kv-cache)")
    g.add_argument("--serve-tp", type=int, default=1,
                   help="tensor-parallel degree of the serving mesh: "
                        "the ragged paged-attention kernels run "
                        "head-sharded over a tp mesh with per-shard KV "
                        "pools (with --serve-disagg, EACH sub-mesh is "
                        "this wide)")
    g.add_argument("--prefill-chunk", type=int, default=32,
                   help="chunked-prefill chunk size — with "
                        "--serve-disagg also the prefill-side "
                        "scheduling quantum (chunks defer when the "
                        "decode SLO is at risk)")
    g.add_argument("--disagg-prefill-slots", type=int, default=2,
                   help="staging page-table rows for in-flight/parked "
                        "prefills on the prefill sub-mesh")
    g.add_argument("--decode-slo-ms", type=float, default=None,
                   help="decode token-interval SLO budget: prefill "
                        "chunks are preempted when the next chunk "
                        "would push the interval past this; /stats "
                        "and /healthz report attainment")
    # Fleet serving (ISSUE 14, inference/fleet.py).
    g.add_argument("--serve-fleet", type=int, default=1, metavar="N",
                   help="run N engine replicas behind the KV-affinity "
                        "fleet router (inference/fleet.py): admission "
                        "scores prefix-cache affinity + queue depth + "
                        "pool pressure + SLO attainment per replica; "
                        "replica death fails sessions over losslessly; "
                        "reloads roll one replica at a time. N=1 keeps "
                        "the single-engine path (needs --engine dynamic "
                        "--paged-kv-cache for N>1; with --serve-disagg "
                        "each replica is its own prefill/decode "
                        "sub-mesh pair)")
    g.add_argument("--fleet-migrate", action="store_true",
                   help="live session migration between fleet replicas "
                        "(PagedKVCache.export_slot/import_slot — "
                        "quantized KV rows + scales ship verbatim, "
                        "streams stay token-exact): overloaded replicas "
                        "hand running sessions to underloaded ones, and "
                        "rolling reloads drain by migration instead of "
                        "waiting for completion")
    g.add_argument("--fleet-autoscale", action="store_true",
                   help="EWMA-attainment-driven autoscaling of each "
                        "disagg replica's prefill/decode mesh split "
                        "(fleet.MeshSplitAutoscaler): low decode-SLO "
                        "attainment shrinks the prefill sub-mesh, "
                        "persistent prefill-queue depth grows it; "
                        "applied by drain + rebuild (needs "
                        "--serve-disagg)")
    # Cross-process fleet (ISSUE 18, inference/fleet_rpc.py).
    g.add_argument("--fleet-procs", type=int, default=0, metavar="N",
                   help="promote the fleet to N replica WORKER "
                        "PROCESSES behind the process router "
                        "(inference/fleet_rpc.py): each replica is a "
                        "spawned `python -m megatronapp_tpu.inference"
                        ".fleet_rpc` worker serving its engine over a "
                        "length-prefixed socket RPC; the router keeps "
                        "the same rid space, affinity admission, and "
                        "token-exact migration across the process "
                        "boundary. 0 keeps fleet serving in-process "
                        "(mutually exclusive with --serve-fleet N>1)")
    g.add_argument("--replica-rpc-port", type=int, default=0,
                   metavar="PORT",
                   help="base TCP port for replica workers (replica i "
                        "binds PORT+i on 127.0.0.1); 0 = ephemeral "
                        "ports published via each replica's addr.json")
    g.add_argument("--supervisor", choices=("off", "thread", "process"),
                   default="off",
                   help="replica supervisor mode (inference/"
                        "supervisor.py): 'thread' polls worker "
                        "heartbeats from a router thread, 'process' "
                        "runs `python -m megatronapp_tpu.inference"
                        ".supervisor` as its own OS process — either "
                        "detects a wedged/killed worker, SIGKILLs and "
                        "relaunches it, and the router fails sessions "
                        "over losslessly (needs --fleet-procs)")
    # Multi-tenant batched-LoRA serving (ISSUE 19, inference/lora.py).
    g.add_argument("--lora-dir", type=str, default=None, metavar="DIR",
                   help="serve per-request LoRA adapters from DIR "
                        "(<DIR>/<adapter_id>.npz, LoraAdapter.save "
                        "format): requests submit with an adapter_id, "
                        "the engine pins it into the HBM adapter cache "
                        "(inference/lora.py AdapterCache — refcount/"
                        "LRU-evict, PagedKVCache discipline), and every "
                        "decode step applies the per-row low-rank "
                        "deltas via the segmented batched-LoRA kernel "
                        "(needs --engine dynamic --paged-kv-cache; "
                        "incompatible with --multi-latent-attention: "
                        "MLA has no q/kv projection leaves to adapt)")
    g.add_argument("--lora-rank", type=int, default=8, metavar="R",
                   help="adapter rank the HBM banks are sized for "
                        "(every served adapter must match; DISTINCT "
                        "from the MLA latent dims --q-lora-rank/"
                        "--kv-lora-rank)")
    g.add_argument("--max-resident-adapters", type=int, default=8,
                   metavar="N",
                   help="HBM adapter cache capacity: N adapters resident "
                        "at once (plus the permanent all-zero NULL "
                        "slot); misses load from --lora-dir, evicting "
                        "the LRU unpinned resident — admission waits "
                        "when all N are pinned by in-flight requests")
    # KV capacity tiers (ISSUE 20, inference/paged_cache.py).
    g.add_argument("--kv-spill-host-mb", type=float, default=0.0,
                   metavar="MB",
                   help="host-RAM KV spill tier byte budget (0 = off): "
                        "idle/low-priority sessions PARK — their pool "
                        "blocks export to host memory (export_slot "
                        "payloads, exact serialized bytes) and the "
                        "blocks free — then resume token-exact through "
                        "import_slot on the next token. Under pressure "
                        "the engine prefers parking over preemption "
                        "(a park costs an import, a preemption a "
                        "re-prefill); needs --engine dynamic "
                        "--paged-kv-cache")
    g.add_argument("--kv-spill-watermark-blocks", type=int, default=0,
                   metavar="N",
                   help="park sessions whenever the pool's free+"
                        "evictable block count drops below N (0 = park "
                        "only under admission/decode pressure); parked "
                        "sessions auto-resume FIFO once capacity "
                        "recovers above the watermark (needs "
                        "--kv-spill-host-mb)")
    g.add_argument("--fleet-prefix-store-mb", type=float, default=0.0,
                   metavar="MB",
                   help="fleet-global prefix store capacity (0 = off): "
                        "prefix blocks inserted by ANY replica are "
                        "exported once into a shared host-RAM store "
                        "(keyed by the same rolling block hashes as "
                        "the prefix cache), and a replica admitting a "
                        "prompt it misses locally imports the blocks "
                        "instead of recomputing the prefill — hot "
                        "prefixes cost once per fleet, not once per "
                        "replica (LRU-bounded; needs --serve-fleet "
                        "N>=2 or --fleet-procs N>=2)")
    # Telemetry spine (ISSUE 12).
    g.add_argument("--serving-metrics", action="store_true",
                   help="enable the telemetry registry "
                        "(utils/metrics.py): counters + log-bucket "
                        "latency histograms from the engines, "
                        "allocator, and driver, exported as Prometheus "
                        "text at GET /metrics (env equivalent: "
                        "MEGATRON_METRICS=1). Off by default — the "
                        "disabled path is one dict check per site")
    g.add_argument("--request-trace", action="store_true",
                   help="enable the always-on bounded request-lifecycle "
                        "tracer (trace/request_trace.py): B/E spans per "
                        "request id (admit/queue/prefill/handoff/adopt/"
                        "decode/retire) in a ring buffer, served as one "
                        "merged Chrome trace at GET /trace (env "
                        "equivalent: MEGATRON_REQUEST_TRACE=1)")
    g.add_argument("--request-trace-capacity", type=int, default=16384,
                   help="ring-buffer record capacity for "
                        "--request-trace (old records fall off; memory "
                        "stays bounded under production load)")
    return g


def validate_serving_args(args, multi_latent_attention: bool = False):
    """Parse-time validation of the serving flag combinations (single
    source of truth for every entry point consuming add_serving_args) —
    reject impossible configs with an actionable message instead of a
    deep stack trace at engine construction."""
    # kv_cache_dtype validation shares the pool's registry messages
    # (inference/paged_cache.py validate_kv_cache_dtype), so the flag
    # help, this parse-time check, and the pool constructor agree by
    # construction (ISSUE 13 satellite).
    from megatronapp_tpu.inference.paged_cache import (
        validate_kv_cache_dtype,
    )
    try:
        validate_kv_cache_dtype(
            getattr(args, "kv_cache_dtype", "bf16"),
            paged=getattr(args, "paged_kv_cache", False),
            mla=multi_latent_attention)
    except ValueError as e:
        raise SystemExit(str(e))
    if getattr(args, "megakernel_decode", False):
        if getattr(args, "engine", "static") != "dynamic":
            raise SystemExit(
                "--megakernel-decode requires --engine dynamic (the "
                "fused step is the dynamic engine's decode body)")
        if not getattr(args, "paged_kv_cache", False):
            raise SystemExit(
                "--megakernel-decode requires --paged-kv-cache (the "
                "fused step is built around the paged-attention "
                "kernel)")
    budget = getattr(args, "megakernel_vmem_budget", None)
    if budget is not None and budget <= 0:
        raise SystemExit(
            f"--megakernel-vmem-budget must be a positive byte count "
            f"(got {budget}); the tiling planner divides weight "
            "columns until each kernel's operands fit it")
    # Fleet serving (ISSUE 14): parse-time validation in the usual
    # first-failed-predicate style — each impossible combination gets
    # its own actionable message.
    fleet = getattr(args, "serve_fleet", 1)
    if fleet < 1:
        raise SystemExit(
            f"--serve-fleet must be >= 1 (got {fleet}); 1 = the "
            "single-engine path, N > 1 = N replicas behind the fleet "
            "router")
    if fleet > 1:
        if getattr(args, "engine", "static") != "dynamic":
            raise SystemExit(
                "--serve-fleet N>1 requires --engine dynamic (the "
                "router drives replica step loops through the "
                "continuous-batching driver)")
        if not getattr(args, "paged_kv_cache", False):
            raise SystemExit(
                "--serve-fleet N>1 requires --paged-kv-cache (affinity "
                "scoring rides the pool's rolling block hashes and "
                "migration ships pool blocks)")
    if getattr(args, "fleet_migrate", False) and fleet < 2:
        raise SystemExit(
            "--fleet-migrate needs --serve-fleet >= 2 (live session "
            "migration moves KV between REPLICA pools; with one "
            "replica there is nowhere to migrate to)")
    if getattr(args, "fleet_autoscale", False):
        if not getattr(args, "serve_disagg", False):
            raise SystemExit(
                "--fleet-autoscale needs --serve-disagg (the "
                "autoscaler's knob is each replica's prefill/decode "
                "mesh split — a colocated engine has no split to "
                "resize)")
        if getattr(args, "engine", "static") != "dynamic":
            raise SystemExit(
                "--fleet-autoscale needs --engine dynamic (it is a "
                "fleet-router policy)")
    # Cross-process fleet (ISSUE 18): same first-failed-predicate style.
    procs = getattr(args, "fleet_procs", 0)
    if procs < 0:
        raise SystemExit(
            f"--fleet-procs must be >= 0 (got {procs}); 0 = in-process "
            "serving, N > 0 = N replica worker processes")
    if procs > 0:
        if fleet > 1:
            raise SystemExit(
                "--fleet-procs and --serve-fleet N>1 are mutually "
                "exclusive: the process router OWNS its replica "
                "workers (one fleet, one router — pick in-process OR "
                "cross-process)")
        if getattr(args, "engine", "static") != "dynamic":
            raise SystemExit(
                "--fleet-procs requires --engine dynamic (replica "
                "workers serve DynamicInferenceEngine step loops)")
        if not getattr(args, "paged_kv_cache", False):
            raise SystemExit(
                "--fleet-procs requires --paged-kv-cache (cross-"
                "process migration ships pool blocks; affinity rides "
                "the pool's rolling block hashes)")
    port = getattr(args, "replica_rpc_port", 0)
    if port and not procs:
        raise SystemExit(
            "--replica-rpc-port needs --fleet-procs (it is the replica "
            "workers' base port; in-process replicas have no sockets)")
    if port and not (1024 <= port <= 65535 - max(procs, 1)):
        raise SystemExit(
            f"--replica-rpc-port {port} out of range: need 1024 <= "
            f"PORT and PORT+{procs} <= 65535 (replica i binds PORT+i), "
            "or 0 for ephemeral ports")
    if getattr(args, "supervisor", "off") != "off" and not procs:
        raise SystemExit(
            "--supervisor needs --fleet-procs (it watches worker "
            "heartbeats and relaunches worker PROCESSES; the in-process "
            "fleet's kill/revive drills already route through the same "
            "supervisor code path internally)")
    # Multi-tenant LoRA serving (ISSUE 19): same first-failed-predicate
    # style — the adapter banks ride the dynamic paged decode step.
    if getattr(args, "lora_dir", None):
        if getattr(args, "engine", "static") != "dynamic":
            raise SystemExit(
                "--lora-dir requires --engine dynamic (the adapter "
                "banks join the dynamic engine's decode scan; the "
                "static engine has no per-row adapter plumbing)")
        if not getattr(args, "paged_kv_cache", False):
            raise SystemExit(
                "--lora-dir requires --paged-kv-cache (the segmented "
                "LoRA delta rides the paged decode/multi-query steps)")
        if multi_latent_attention:
            raise SystemExit(
                "--lora-dir is incompatible with "
                "--multi-latent-attention: MLA factors attention "
                "through latent kernels with no q_kernel/kv_kernel "
                "leaves to adapt — serve MLA models without LoRA")
        if getattr(args, "serve_disagg", False):
            raise SystemExit(
                "--lora-dir does not compose with --serve-disagg yet: "
                "the adapter banks join the unified dynamic engine's "
                "decode scan; the disagg facade's split prefill/decode "
                "meshes would need per-mesh bank replicas (serve LoRA "
                "from the colocated dynamic engine or a fleet of them)")
    rank = getattr(args, "lora_rank", 8)
    if rank < 1:
        raise SystemExit(
            f"--lora-rank must be >= 1 (got {rank}); the HBM banks "
            "are sized A[L, slots, din, R] / B[L, slots, R, dout]")
    max_res = getattr(args, "max_resident_adapters", 8)
    if max_res < 1:
        raise SystemExit(
            f"--max-resident-adapters must be >= 1 (got {max_res}); "
            "slot 0 is the reserved NULL adapter, so at least one "
            "managed slot is needed to serve any adapter at all")
    # KV capacity tiers (ISSUE 20): same first-failed-predicate style.
    spill_mb = getattr(args, "kv_spill_host_mb", 0.0)
    if spill_mb < 0:
        raise SystemExit(
            f"--kv-spill-host-mb must be >= 0 (got {spill_mb}); it is "
            "the spill tier's host byte budget (0 disables it)")
    if spill_mb:
        if getattr(args, "engine", "static") != "dynamic":
            raise SystemExit(
                "--kv-spill-host-mb requires --engine dynamic (park/"
                "unpark is the dynamic engine's slot machinery)")
        if not getattr(args, "paged_kv_cache", False):
            raise SystemExit(
                "--kv-spill-host-mb requires --paged-kv-cache (the "
                "spill tier parks pool blocks via export_slot/"
                "import_slot)")
        if getattr(args, "serve_disagg", False):
            raise SystemExit(
                "--kv-spill-host-mb does not compose with "
                "--serve-disagg yet: parking lives in the unified "
                "engine's slot machinery; the disagg facade stages "
                "prefills in a separate pool (serve the spill tier "
                "from colocated dynamic engines or a fleet of them)")
    watermark = getattr(args, "kv_spill_watermark_blocks", 0)
    if watermark < 0:
        raise SystemExit(
            f"--kv-spill-watermark-blocks must be >= 0 (got "
            f"{watermark}); it is a free-block low-water mark")
    if watermark and not spill_mb:
        raise SystemExit(
            "--kv-spill-watermark-blocks needs --kv-spill-host-mb "
            "(the watermark decides WHEN to park; the budget is WHERE "
            "the parked bytes go — without a budget nothing can park)")
    store_mb = getattr(args, "fleet_prefix_store_mb", 0.0)
    if store_mb < 0:
        raise SystemExit(
            f"--fleet-prefix-store-mb must be >= 0 (got {store_mb}); "
            "it is the store's host capacity (0 disables it)")
    if store_mb and fleet < 2 and procs < 2:
        raise SystemExit(
            "--fleet-prefix-store-mb needs a fleet of >= 2 replicas "
            "(--serve-fleet N>=2 or --fleet-procs N>=2): with one "
            "replica the pool's own prefix cache already holds every "
            "inserted block — a fleet-global store would only "
            "duplicate it")
    if (getattr(args, "quantized_weights", False)
            and getattr(args, "engine", "static") == "mamba"):
        raise SystemExit(
            "--quantized-weights supports the gpt engines only: "
            "mamba_forward does not resolve resident int8 kernels "
            "(drop the flag, or serve the artifact without it to "
            "dequantize on load)")


def build_parser(title: str = "megatronapp-tpu") -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description=title, allow_abbrev=False,
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)

    g = ap.add_argument_group("model")  # _add_network_size_args parity
    g.add_argument("--num-layers", type=int, default=12)
    g.add_argument("--hidden-size", type=int, default=768)
    g.add_argument("--num-attention-heads", type=int, default=12)
    g.add_argument("--num-query-groups", type=int, default=None)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--heterogeneous-layers-config-path", type=str,
                   default=None)
    g.add_argument("--heterogeneous-layers-config-encoded-json", type=str,
                   default=None)
    g.add_argument("--vocab-size", type=int, default=50304)
    g.add_argument("--max-position-embeddings", type=int, default=2048)
    g.add_argument("--position-embedding-type", default="rope",
                   choices=[k.value for k in PositionEmbeddingKind])
    g.add_argument("--rotary-base", type=float, default=10000.0)
    g.add_argument("--rotary-percent", type=float, default=1.0)
    g.add_argument("--normalization", default="LayerNorm",
                   choices=[k.value for k in NormKind])
    g.add_argument("--swiglu", action="store_true")
    g.add_argument("--squared-relu", action="store_true")
    g.add_argument("--disable-bias-linear", action="store_true")
    g.add_argument("--add-qkv-bias", action="store_true")
    g.add_argument("--qk-layernorm", action="store_true")
    g.add_argument("--untie-embeddings-and-output-weights",
                   action="store_true")
    g.add_argument("--init-method-std", type=float, default=0.02)
    g.add_argument("--preset", default=None,
                   help="named model preset (models/presets.py); flags "
                        "override preset fields they explicitly set")

    g = ap.add_argument_group("mtp")  # multi_token_prediction.py parity
    g.add_argument("--mtp-num-layers", type=int, default=None)
    g.add_argument("--mtp-loss-scaling-factor", type=float, default=0.1)

    g = ap.add_argument_group("mla")  # MLATransformerConfig parity
    g.add_argument("--multi-latent-attention", action="store_true")
    g.add_argument("--q-lora-rank", type=int, default=None)
    g.add_argument("--kv-lora-rank", type=int, default=512)
    g.add_argument("--qk-head-dim", type=int, default=128)
    g.add_argument("--qk-pos-emb-head-dim", type=int, default=64)
    g.add_argument("--v-head-dim", type=int, default=128)

    g = ap.add_argument_group("moe")  # _add_moe_args parity
    g.add_argument("--num-experts", type=int, default=None)
    g.add_argument("--moe-router-topk", type=int, default=2)
    g.add_argument("--moe-ffn-hidden-size", type=int, default=None)
    g.add_argument("--moe-aux-loss-coeff", type=float, default=0.0)
    g.add_argument("--moe-z-loss-coeff", type=float, default=0.0)
    g.add_argument("--moe-expert-capacity-factor", type=float, default=None)
    g.add_argument("--moe-layer-freq", type=int, default=1)
    g.add_argument("--moe-shared-expert-intermediate-size", type=int,
                   default=None)

    g = ap.add_argument_group("distributed")  # _add_distributed_args parity
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--context-parallel-size", type=int, default=1)
    g.add_argument("--hierarchical-context-parallel-sizes", nargs=2,
                   type=int, default=None, metavar=("A2A", "RING"),
                   help="inner a2a x outer ring sizes for "
                        "cp-comm-type a2a+p2p (reference flag)")
    g.add_argument("--expert-model-parallel-size", type=int, default=1)
    g.add_argument("--num-layers-per-virtual-pipeline-stage", type=int,
                   default=None)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--tp-comm-overlap", action="store_true",
                   help="overlap tensor-parallel collectives with the "
                        "dependent GEMMs via manual ring all-gather / "
                        "reduce-scatter matmuls (parallel/overlap.py)")
    g.add_argument("--no-tp-sharded-stage", action="store_false",
                   dest="tp_sharded_stage",
                   help="disable the tp-SHARDED pipeline stage body "
                        "(parallel/pipeline.py tp_shard) and fall back "
                        "to tp-replicated stage compute — the A/B "
                        "baseline; only meaningful with pp>1 x tp>1")
    g.add_argument("--sharded-init", action="store_true",
                   help="initialize the train state direct-to-shards "
                        "(params never materialize unsharded — for "
                        "giant-model runs whose replicated init would "
                        "OOM a device); the default two-stage "
                        "replicated-then-reshard init is the one whose "
                        "seeded values are mesh-independent "
                        "(training/train_state.py)")
    g.add_argument("--no-cp-comm-overlap", action="store_false",
                   dest="cp_comm_overlap",
                   help="disable the latency-hiding ring-attention path "
                        "(pre-issued KV hops + fused custom_vjp reverse "
                        "ring, ops/context_parallel.py); falls back to "
                        "the plain unrolled ring")
    g.add_argument("--no-moe-comm-overlap", action="store_false",
                   dest="moe_comm_overlap",
                   help="disable the chunked latency-hiding MoE "
                        "all-to-all (transformer/moe.py); falls back to "
                        "the bulk two-collective dispatch")
    g.add_argument("--use-distributed-optimizer", action="store_true",
                   default=True,
                   help="ZeRO-1 distributed optimizer (default on): "
                        "Adam m/v (and the fp32 master shard for "
                        "low-precision params) live sharded over the "
                        "data-parallel axis; grads enter the update "
                        "reduce-scattered and updated params return via "
                        "all-gather (training/distributed_optimizer.py)")
    g.add_argument("--no-use-distributed-optimizer", action="store_false",
                   dest="use_distributed_optimizer",
                   help="replicate optimizer state on every dp rank "
                        "(the A/B baseline for bench extra.dist_opt)")
    g.add_argument("--main-params-dtype", default="fp32",
                   help="dtype of the ZeRO-1 master-weight shard (kept "
                        "only when params are lower precision); fp32 is "
                        "the supported accumulation dtype")
    g.add_argument("--exp-avg-dtype", default="fp32",
                   help="storage dtype of the Adam first moment "
                        "(exp_avg): fp32 | bf16 — update math stays "
                        "fp32; bf16 halves per-rank m bytes and "
                        "requires --use-distributed-optimizer")
    g.add_argument("--exp-avg-sq-dtype", default="fp32",
                   help="storage dtype of the Adam second moment "
                        "(exp_avg_sq): fp32 | bf16; requires "
                        "--use-distributed-optimizer")
    g.add_argument("--dist-opt-comm", default="gspmd",
                   choices=["gspmd", "ring", "bulk"],
                   help="collectives of the ZeRO-1 weight update: gspmd "
                        "= XLA inserts grad slice / param all-gather "
                        "from the dp-sharded state layout (arXiv "
                        "2004.13336); ring = full-manual update with "
                        "the latency-hiding ring all-gather "
                        "(parallel/overlap.py); bulk = full-manual "
                        "with one tiled all-gather")
    g.add_argument("--cp-comm-type", default="p2p",
                   choices=["p2p", "a2a", "allgather", "a2a+p2p"])
    # MegaFBD / MegaDPP flags (reference arguments.py:2197-2205).
    g.add_argument("--forward-backward-disaggregating", action="store_true")
    g.add_argument("--use-dpp", action="store_true",
                   help="breadth-first-chunk pipeline order (MegaDPP)")
    # Pipeline schedule programs + the trace-driven planner (ISSUE 15,
    # parallel/schedule.py). Choices derive from the schedule layer's
    # canonical list so a new schedule is one edit, not three.
    from megatronapp_tpu.parallel.schedule import SCHEDULES
    g.add_argument("--pp-schedule", default="1f1b",
                   choices=list(SCHEDULES),
                   help="pipeline schedule program executed by the "
                        "manual region (parallel/schedule.py): 1f1b "
                        "(interleaved automatically when vpp > 1), vpp "
                        "(alias requiring "
                        "--num-layers-per-virtual-pipeline-stage), or "
                        "zero-bubble (backward split into B=dgrad / "
                        "W=wgrad; W deferred into bubble slots, the "
                        "weight update fenced on all W done — grads "
                        "identical to the fused backward)")
    g.add_argument("--pp-plan-from-trace", action="store_true",
                   help="let the trace-driven planner "
                        "(parallel/schedule.Planner) retune the "
                        "schedule from per-stage step-time EWMAs "
                        "(MegaScan ring-hop spans + the straggler "
                        "signal + the heterogeneous stage table); "
                        "re-plans log loudly and rebuild the train "
                        "step")
    # Multi-host runtime (reference torchrun MASTER_ADDR/RANK/WORLD_SIZE →
    # jax.distributed; auto-detected on TPU pods).
    g.add_argument("--multi-host", action="store_true",
                   help="join the jax.distributed multi-host runtime "
                        "before building the mesh (auto-detects "
                        "coordinator on TPU pods)")
    g.add_argument("--coordinator-address", default=None,
                   help="host:port of process 0 (manual launches)")
    g.add_argument("--num-processes", type=int, default=None)
    g.add_argument("--process-id", type=int, default=None)

    g = ap.add_argument_group("training")  # _add_training_args parity
    g.add_argument("--micro-batch-size", type=int, default=1)
    g.add_argument("--global-batch-size", type=int, default=8)
    g.add_argument("--rampup-batch-size", nargs=3, type=int, default=None,
                   metavar=("START", "INCR", "SAMPLES"),
                   help="linear global-batch rampup (reference "
                        "--rampup-batch-size)")
    g.add_argument("--seq-length", type=int, default=1024)
    g.add_argument("--train-iters", type=int, default=100)
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--log-interval", type=int, default=10)
    g.add_argument("--eval-interval", type=int, default=None)
    g.add_argument("--eval-iters", type=int, default=10)
    g.add_argument("--exit-interval", type=int, default=None)
    g.add_argument("--recompute-activations", action="store_true",
                   help="selective recompute (default policy already "
                        "selective; use --recompute-granularity)")
    g.add_argument("--recompute-granularity", default="selective",
                   choices=["none", "selective", "selective_attn", "full"])
    g.add_argument("--attention-impl", default="auto",
                   choices=["auto", "pallas", "reference"],
                   help="auto = flash above --flash-min-seq, dense below")
    g.add_argument("--flash-min-seq", type=int, default=2048,
                   help="flash/dense crossover sequence length (PERF.md)")
    # --scan-unroll lives in add_serving_args (single source of truth
    # for both the training layer scan and the serving step scans).
    g.add_argument("--flash-head-fold", action="store_true",
                   help="fold q-head pairs into the trailing block dim "
                        "of the flash BACKWARD kernels (D=64 -> 128 "
                        "lanes, PERF.md lever #1); ineligible layouts "
                        "keep the standard kernels")
    g.add_argument("--bf16", action="store_true", default=True)
    g.add_argument("--fp32", action="store_true",
                   help="disable bf16 compute")
    # fp8 training GEMMs (ISSUE 13, training/fp8.py).
    g.add_argument("--fp8", action="store_true",
                   help="fp8 (e4m3) GEMMs with delayed-scaling amax "
                        "history inside the tp-overlap ring matmuls "
                        "(fwd + bwd; parallel/overlap.py). Requires "
                        "--tp-comm-overlap with tp > 1 on a pp==1, "
                        "cp==1, dense non-MLA/non-MoE layout; the amax/"
                        "scale state rides the train state, so "
                        "checkpoints resume bitwise")
    g.add_argument("--fp8-margin", type=int, default=0,
                   help="delayed-scaling margin: scale = FP8_MAX / "
                        "(amax * 2**margin) — headroom against "
                        "inter-step amax growth (TE --fp8-margin)")
    g.add_argument("--fp8-amax-history-len", type=int, default=16,
                   help="amax history window per (layer, site, tensor); "
                        "the scale follows the max over the window "
                        "(TE --fp8-amax-history-len)")

    g = ap.add_argument_group("learning-rate")  # _add_learning_rate_args
    g.add_argument("--lr", type=float, default=3e-4)
    g.add_argument("--min-lr", type=float, default=3e-5)
    g.add_argument("--lr-decay-style", default="cosine",
                   choices=["cosine", "linear", "constant"])
    g.add_argument("--lr-warmup-iters", type=int, default=0)
    g.add_argument("--lr-decay-iters", type=int, default=None)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.95)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--optimizer", default="adam", choices=["adam", "sgd"])

    g = ap.add_argument_group("checkpointing")  # _add_checkpointing_args
    g.add_argument("--save", default=None, metavar="DIR")
    g.add_argument("--load", default=None, metavar="DIR")
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--use-checkpoint-args", action="store_true",
                   help="apply args.json stored with the --load checkpoint "
                        "as defaults (explicit flags still override; "
                        "reference --use-checkpoint-args)")
    g.add_argument("--config-yaml", default=None, metavar="FILE",
                   help="YAML of flag values applied as defaults "
                        "(reference yaml_arguments.py alternative)")

    g = ap.add_argument_group("data")  # _add_data_args parity
    g.add_argument("--data-path", default=None,
                   help=".bin/.idx prefix; omit for the mock dataset")
    g.add_argument("--tokenizer-type", default="NullTokenizer")
    g.add_argument("--tokenizer-name-or-path", default=None)

    g = ap.add_argument_group("logging")  # _add_logging_args parity
    g.add_argument("--tensorboard-dir", default=None)
    g.add_argument("--metrics-jsonl", default=None,
                   help="append per-log-step scalars to this JSONL file")

    g = ap.add_argument_group("fault-tolerance")  # _add_rerun args parity
    g.add_argument("--rerun-mode", default="validate_results",
                   choices=["disabled", "validate_results"])
    g.add_argument("--error-injection-rate", type=float, default=0.0)
    g.add_argument("--log-straggler", action="store_true")
    g.add_argument("--run-workload-inspector-server", action="store_true")
    g.add_argument("--workload-inspector-port", type=int, default=0)
    # Graceful exit + heartbeat + local checkpoints (ISSUE 6; reference
    # --exit-signal-handler / ft_integration / non_persistent ckpts).
    g.add_argument("--exit-signal-handler", action="store_true",
                   help="SIGTERM finishes the in-flight step, force-"
                        "saves an emergency checkpoint (durable + local "
                        "when configured) with resumable side state, "
                        "and exits cleanly; the exit decision is agreed "
                        "across processes")
    g.add_argument("--exit-signal-handler-sigint", action="store_true",
                   help="additionally catch SIGINT (^C) — implies "
                        "--exit-signal-handler")
    g.add_argument("--heartbeat-dir", default=None, metavar="DIR",
                   help="write heartbeat.json (section + timestamp, "
                        "atomic) for an external supervisor "
                        "(ft_integration.read_heartbeat); also enables "
                        "the in-process section-timeout watchdog")
    g.add_argument("--ft-timeouts", default=None,
                   metavar="SETUP,STEP,CKPT",
                   help="heartbeat section timeouts in seconds (three "
                        "comma-separated positive numbers, e.g. "
                        "'600,180,600'); enables the watchdog even "
                        "without --heartbeat-dir")
    g.add_argument("--simulated-fault", default=None, metavar="KIND:DELAY",
                   help="FT drill: schedule a simulated fault after "
                        "DELAY seconds — 'hang' wedges the train loop "
                        "(watchdog/supervisor must catch it), 'exit' "
                        "hard-kills the process (exit code 42)")
    g.add_argument("--non-persistent-save-interval", type=int,
                   default=None, metavar="N",
                   help="fast latest-only local checkpoint every N "
                        "steps (LocalCheckpointManager .npz, atomic "
                        "rename) — cheap enough for small N; restore "
                        "prefers the freshest of (local, durable)")
    g.add_argument("--non-persistent-ckpt-dir", default=None,
                   metavar="DIR",
                   help="directory for the local checkpoints (default: "
                        "<--save>/non_persistent)")

    add_serving_args(ap)   # paged KV serving flags (ISSUE 3)

    g = ap.add_argument_group("megascan")  # reference arguments.py:2705ff
    g.add_argument("--trace", action="store_true")
    g.add_argument("--trace-interval", type=int, default=5)
    g.add_argument("--continuous-trace-iterations", type=int, default=2)
    g.add_argument("--trace-dir", default="trace")
    g.add_argument("--trace-granularity", default="full",
                   choices=["full", "schedule", "collective"])
    return ap


def parse_args(ap: argparse.ArgumentParser, argv=None):
    """Parse with YAML-config and checkpoint-args defaults applied.

    Resolution order (lowest → highest precedence): parser defaults →
    --config-yaml values → --use-checkpoint-args stored values → explicit
    CLI flags. Use this instead of ap.parse_args in entry points."""
    import os
    import sys

    # Honor JAX_PLATFORMS explicitly: some site configurations (e.g. the
    # tunneled-TPU image) programmatically force jax_platforms AFTER env
    # processing, which silently overrides the operator's choice and can
    # hang every entry point when the tunnel is down. Applying the env var
    # through jax.config restores the standard JAX contract.
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    argv = list(sys.argv[1:] if argv is None else argv)
    pre, _ = ap.parse_known_args(argv)
    defaults = {}
    if getattr(pre, "config_yaml", None):
        defaults.update(_flags_from_yaml(pre.config_yaml))
    if getattr(pre, "use_checkpoint_args", False):
        if not pre.load:
            raise ValueError("--use-checkpoint-args requires --load")
        stored = load_saved_args(pre.load) or {}
        # Restore ARCHITECTURE/hyperparameter args only — run-control args
        # (where to save, how long to run, IO paths) stay with the new
        # invocation (reference --use-checkpoint-args skips the same set).
        defaults.update({k: v for k, v in stored.items()
                         if k not in _RUN_CONTROL_ARGS})
    if defaults:
        valid = {a.dest for a in ap._actions}
        unknown = sorted(set(defaults) - valid)
        if unknown:
            raise ValueError(f"unknown config keys: {unknown}")
        ap.set_defaults(**defaults)
    args = ap.parse_args(argv)
    if getattr(args, "multi_host", False):
        # Join the multi-host runtime before anything touches the backend
        # (parse_args itself never does). Checked on the FINAL namespace so
        # --multi-host works from the CLI, --config-yaml, and
        # --use-checkpoint-args restores alike.
        from megatronapp_tpu.parallel.mesh import initialize_multi_host
        initialize_multi_host(args.coordinator_address,
                              args.num_processes, args.process_id)
    return args


def _flags_from_yaml(path: str) -> dict:
    """{flag: value} from a YAML file; keys may use dashes or
    underscores."""
    import yaml
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: expected a mapping of flag: value")
    return {k.replace("-", "_"): v for k, v in raw.items()}


_ARGS_FILE = "resolved_args.json"

# Args --use-checkpoint-args must NOT resurrect from a stored run.
_RUN_CONTROL_ARGS = frozenset({
    "save", "load", "save_interval", "train_iters", "exit_interval",
    "use_checkpoint_args", "config_yaml", "data_path", "metrics_jsonl",
    "tensorboard_dir", "trace", "trace_dir", "log_interval",
    "eval_interval", "eval_iters",
})


def save_resolved_args(args, save_dir: str):
    """Persist the resolved flag namespace next to the checkpoint
    (reference stores args inside the ckpt; a sidecar JSON keeps ours
    format-agnostic)."""
    import json
    import os
    os.makedirs(save_dir, exist_ok=True)
    payload = {k: v for k, v in vars(args).items()
               if isinstance(v, (int, float, str, bool, list, tuple,
                                 type(None)))}
    with open(os.path.join(save_dir, _ARGS_FILE), "w") as f:
        json.dump(payload, f, indent=1)


def load_saved_args(load_dir: str) -> Optional[dict]:
    import json
    import os
    path = os.path.join(load_dir, _ARGS_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _hetero_json(args):
    """--heterogeneous-layers-config-{path,encoded-json} → encoded JSON
    (reference arguments.py _add_heterogeneous_args; the path is read once
    and carried as the encoded string, heterogeneous_config.py:196-205)."""
    encoded = getattr(args, "heterogeneous_layers_config_encoded_json",
                      None)
    path = getattr(args, "heterogeneous_layers_config_path", None)
    if encoded:
        return encoded
    if path:
        with open(path) as f:
            return f.read()
    return None


def _parse_ft_timeouts(s: Optional[str]) -> Optional[tuple]:
    """--ft-timeouts 'SETUP,STEP,CKPT' → (float, float, float), each > 0."""
    if s is None:
        return None
    parts = str(s).split(",")
    try:
        vals = tuple(float(p) for p in parts)
    except ValueError:
        vals = ()
    if len(vals) != 3 or any(v <= 0 for v in vals):
        raise ValueError(
            f"--ft-timeouts expects three positive comma-separated "
            f"seconds 'SETUP,STEP,CKPT' (e.g. '600,180,600'), got {s!r}")
    return vals


def _parse_simulated_fault(s: Optional[str]) -> Optional[tuple]:
    """--simulated-fault 'KIND:DELAY' → (kind, float delay >= 0)."""
    if s is None:
        return None
    kind, sep, delay_s = str(s).partition(":")
    try:
        delay = float(delay_s) if sep else -1.0
    except ValueError:
        delay = -1.0
    if kind not in ("hang", "exit") or delay < 0:
        raise ValueError(
            f"--simulated-fault expects 'KIND:DELAY' with KIND in "
            f"(hang, exit) and DELAY >= 0 seconds, got {s!r}")
    return kind, delay


def _validate_dist_opt_args(args) -> dict:
    """Parse + validate the ZeRO-1 mixed-precision knobs; returns the
    OptimizerConfig field values (clear errors at startup — a bad state
    dtype must not surface as a jit trace failure mid-setup)."""
    from megatronapp_tpu.training.distributed_optimizer import (
        STATE_DTYPES, resolve_state_dtype,
    )
    import jax.numpy as _jnp
    for flag, val in (("--main-params-dtype", args.main_params_dtype),
                      ("--exp-avg-dtype", args.exp_avg_dtype),
                      ("--exp-avg-sq-dtype", args.exp_avg_sq_dtype)):
        if str(val).lower() not in STATE_DTYPES:
            raise ValueError(
                f"{flag} expects one of {sorted(set(STATE_DTYPES))}, "
                f"got {val!r}")
    if resolve_state_dtype(args.main_params_dtype) != _jnp.float32:
        raise ValueError(
            "--main-params-dtype: only fp32 master weights are "
            "supported — the master shard is the fp32 accumulation "
            "domain (low-precision params get one automatically)")
    low_moments = any(
        resolve_state_dtype(v) != _jnp.float32
        for v in (args.exp_avg_dtype, args.exp_avg_sq_dtype))
    if low_moments and not args.use_distributed_optimizer:
        raise ValueError(
            "--exp-avg-dtype/--exp-avg-sq-dtype bf16 require "
            "--use-distributed-optimizer: low-precision moments are "
            "only supported on the ZeRO-1 state layout (the replicated "
            "optax chain stores fp32)")
    if low_moments and getattr(args, "forward_backward_disaggregating",
                               False):
        # The FBD executor path builds the plain chain (the ZeRO-1
        # wrapper is not wired there yet — ROADMAP follow-up); reject at
        # parse time with the real reason instead of the plain chain's
        # guard firing after mesh build.
        raise ValueError(
            "--exp-avg-dtype/--exp-avg-sq-dtype bf16 are not supported "
            "with --forward-backward-disaggregating: the FBD path runs "
            "the replicated optax chain (ZeRO-1 wiring is a ROADMAP "
            "follow-up)")
    return dict(
        main_params_dtype=args.main_params_dtype,
        exp_avg_dtype=args.exp_avg_dtype,
        exp_avg_sq_dtype=args.exp_avg_sq_dtype,
        dist_opt_comm=args.dist_opt_comm,
    )


def _validate_ft_args(args) -> dict:
    """Parse + validate the fault-tolerance flags; returns the
    TrainingConfig field values (clear errors at startup, not a stack
    trace hours into a run)."""
    ft_timeouts = _parse_ft_timeouts(args.ft_timeouts)
    simulated_fault = _parse_simulated_fault(args.simulated_fault)
    npsi = args.non_persistent_save_interval
    if npsi is not None and npsi <= 0:
        raise ValueError(
            f"--non-persistent-save-interval must be a positive step "
            f"count, got {npsi}")
    # The default-location policy (<--save>/non_persistent) lives in
    # TrainingConfig.resolved_non_persistent_dir — here we only reject
    # configs it cannot resolve, at parse time.
    if npsi and not (args.non_persistent_ckpt_dir or args.save):
        raise ValueError(
            "--non-persistent-save-interval needs a directory: pass "
            "--non-persistent-ckpt-dir or --save (the default is "
            "<--save>/non_persistent)")
    return dict(
        exit_signal_handler=(args.exit_signal_handler
                             or args.exit_signal_handler_sigint),
        exit_signal_handler_sigint=args.exit_signal_handler_sigint,
        heartbeat_dir=args.heartbeat_dir,
        ft_timeouts=ft_timeouts,
        simulated_fault=simulated_fault,
        non_persistent_save_interval=npsi,
        non_persistent_ckpt_dir=args.non_persistent_ckpt_dir,
    )


def configs_from_args(args) -> Tuple[TransformerConfig, ParallelConfig,
                                     TrainingConfig, OptimizerConfig]:
    """Build + cross-validate the four configs (validate_args parity)."""
    if args.preset:
        import dataclasses as _dc
        from megatronapp_tpu.models.presets import PRESETS
        model = PRESETS[args.preset]()
        # Explicitly-passed flags override preset fields. Detect "explicit"
        # by re-parsing with defaults suppressed.
        sentinel = build_parser().parse_args([])
        overrides = {}
        flag_to_field = {
            "num_layers": "num_layers", "hidden_size": "hidden_size",
            "num_attention_heads": "num_attention_heads",
            "num_query_groups": "num_query_groups",
            "ffn_hidden_size": "ffn_hidden_size",
            "vocab_size": "vocab_size",
            "max_position_embeddings": "max_position_embeddings",
            "init_method_std": "init_method_std",
            "tp_comm_overlap": "tp_comm_overlap",
            "tp_sharded_stage": "tp_sharded_stage",
        }
        for flag, field in flag_to_field.items():
            val = getattr(args, flag)
            if val != getattr(sentinel, flag):
                overrides[field] = val
        if overrides:
            model = _dc.replace(model, **overrides)
    else:
        activation = ActivationKind.gelu
        if args.swiglu:
            activation = ActivationKind.swiglu
        elif args.squared_relu:
            activation = ActivationKind.squared_relu
        model = TransformerConfig(
            num_layers=args.num_layers,
            hidden_size=args.hidden_size,
            num_attention_heads=args.num_attention_heads,
            num_query_groups=args.num_query_groups,
            ffn_hidden_size=args.ffn_hidden_size,
            kv_channels=args.kv_channels,
            vocab_size=args.vocab_size,
            max_position_embeddings=args.max_position_embeddings,
            position_embedding=PositionEmbeddingKind(
                args.position_embedding_type),
            rotary_base=args.rotary_base,
            rotary_percent=args.rotary_percent,
            normalization=NormKind(args.normalization),
            activation=activation,
            add_bias_linear=not args.disable_bias_linear,
            add_qkv_bias=args.add_qkv_bias,
            qk_layernorm=args.qk_layernorm,
            untie_embeddings_and_output_weights=(
                args.untie_embeddings_and_output_weights),
            init_method_std=args.init_method_std,
            num_moe_experts=args.num_experts,
            moe_router_topk=args.moe_router_topk,
            moe_ffn_hidden_size=args.moe_ffn_hidden_size,
            moe_aux_loss_coeff=args.moe_aux_loss_coeff,
            moe_z_loss_coeff=args.moe_z_loss_coeff,
            moe_capacity_factor=args.moe_expert_capacity_factor,
            moe_layer_freq=args.moe_layer_freq,
            moe_shared_expert_intermediate_size=(
                args.moe_shared_expert_intermediate_size),
            mtp_num_layers=args.mtp_num_layers,
            mtp_loss_scaling_factor=args.mtp_loss_scaling_factor,
            multi_latent_attention=args.multi_latent_attention,
            q_lora_rank=args.q_lora_rank,
            kv_lora_rank=args.kv_lora_rank,
            qk_head_dim=args.qk_head_dim,
            qk_pos_emb_head_dim=args.qk_pos_emb_head_dim,
            v_head_dim=args.v_head_dim,
            cp_comm_type=args.cp_comm_type,
            hierarchical_cp_a2a_size=(
                args.hierarchical_context_parallel_sizes[0]
                if args.hierarchical_context_parallel_sizes else 2),
            remat_policy=args.recompute_granularity,
            tp_comm_overlap=args.tp_comm_overlap,
            tp_sharded_stage=args.tp_sharded_stage,
            cp_comm_overlap=args.cp_comm_overlap,
            moe_comm_overlap=args.moe_comm_overlap,
            attention_impl=args.attention_impl,
            flash_min_seq=args.flash_min_seq,
            scan_unroll=args.scan_unroll,
            flash_head_fold=args.flash_head_fold,
            compute_dtype=jnp.float32 if args.fp32 else jnp.bfloat16,
            heterogeneous_layers_config_json=_hetero_json(args),
        )

    if getattr(args, "fp8", False):
        import dataclasses as _dc_fp8
        if args.fp8_amax_history_len < 1:
            raise ValueError(
                f"--fp8-amax-history-len must be >= 1, got "
                f"{args.fp8_amax_history_len}")
        model = _dc_fp8.replace(
            model, fp8=True, fp8_margin=args.fp8_margin,
            fp8_amax_history_len=args.fp8_amax_history_len)

    vpp = 1
    if args.num_layers_per_virtual_pipeline_stage:
        per_stage = (model.num_layers //
                     args.pipeline_model_parallel_size)
        if per_stage % args.num_layers_per_virtual_pipeline_stage != 0:
            raise ValueError(
                "--num-layers-per-virtual-pipeline-stage must divide "
                "layers-per-stage")
        vpp = per_stage // args.num_layers_per_virtual_pipeline_stage

    parallel = ParallelConfig(
        tensor_parallel=args.tensor_model_parallel_size,
        pipeline_parallel=args.pipeline_model_parallel_size,
        context_parallel=args.context_parallel_size,
        expert_parallel=args.expert_model_parallel_size,
        virtual_pipeline_parallel=vpp,
        sequence_parallel=args.sequence_parallel,
        distributed_optimizer=args.use_distributed_optimizer,
        forward_backward_disaggregating=args.forward_backward_disaggregating,
        pipeline_order_policy="bfc" if args.use_dpp else "dfc",
        use_dpp=args.use_dpp,
        pp_schedule=args.pp_schedule,
        pp_plan_from_trace=args.pp_plan_from_trace,
    )

    # Schedule-flag cross-validation (ISSUE 15): the host-driven MegaDPP
    # runtime executes its own dynamic order — a non-default
    # --pp-schedule there would be silently ignored, which is worse
    # than an error.
    if args.use_dpp and args.pp_schedule != "1f1b":
        raise ValueError(
            f"--pp-schedule {args.pp_schedule} does not compose with "
            "--use-dpp (the host-driven MegaDPP runtime schedules "
            "dynamically); drop one of the flags")
    if args.use_dpp and args.pp_plan_from_trace:
        raise ValueError(
            "--pp-plan-from-trace does not compose with --use-dpp (the "
            "host runtime already schedules dynamically); drop one")
    # Same policy for the FBD executor (it runs its own legacy
    # schedule; train.py re-checks for programmatic callers).
    if args.forward_backward_disaggregating and (
            args.pp_schedule != "1f1b" or args.pp_plan_from_trace):
        raise ValueError(
            "--pp-schedule/--pp-plan-from-trace do not compose with "
            "--forward-backward-disaggregating (the FBD executor runs "
            "its own schedule); drop one")

    # fp8 eligibility (ISSUE 13): reject impossible layouts at parse
    # time with the predicate that failed (training/fp8.py names it) —
    # a silent no-op fp8 run would be worse than an error.
    if model.fp8:
        from megatronapp_tpu.training.fp8 import fp8_ineligible_reason
        reason = fp8_ineligible_reason(model, parallel)
        if reason is not None:
            raise ValueError(reason)

    # Cross-validation (reference validate_args: seq/cp divisibility :695).
    if args.seq_length % (args.context_parallel_size or 1) != 0:
        raise ValueError("--seq-length must be divisible by "
                         "--context-parallel-size")
    if args.hierarchical_context_parallel_sizes:
        a2a_sz, ring_sz = args.hierarchical_context_parallel_sizes
        if a2a_sz * ring_sz != args.context_parallel_size:
            raise ValueError(
                f"--hierarchical-context-parallel-sizes {a2a_sz} {ring_sz} "
                f"must multiply to --context-parallel-size "
                f"({args.context_parallel_size})")
        if args.cp_comm_type != "a2a+p2p":
            raise ValueError(
                "--hierarchical-context-parallel-sizes requires "
                "--cp-comm-type a2a+p2p")
    if args.seq_length > model.max_position_embeddings:
        raise ValueError("--seq-length exceeds --max-position-embeddings")

    # --tp-comm-overlap divisibility (fail at parse time with a clear
    # message instead of a shard_map trace failure / silent GSPMD
    # fallback deep inside the first step): the ring primitives shard the
    # projection output/input dims — and, inside a pp>1 manual pipeline,
    # whole heads and the sequence — evenly over tp.
    tp = args.tensor_model_parallel_size
    if model.tp_comm_overlap and tp > 1:
        def _reject(what, dim):
            raise ValueError(
                f"--tp-comm-overlap: {what} ({dim}) is not divisible by "
                f"--tensor-model-parallel-size ({tp}); pick divisible "
                "sizes or drop the flag")
        if model.hidden_size % tp:
            _reject("--hidden-size", model.hidden_size)
        if not model.is_moe or model.moe_layer_freq > 1:
            if model.ffn_hidden_size % tp:
                _reject("--ffn-hidden-size (fc1/fc2 shard dim)",
                        model.ffn_hidden_size)
        # The tp-sharded stage body runs when pp>1 and the kill switch
        # is off (tp_stage_eligible) — INCLUDING cp>1 since the
        # pp x cp x tp composition (ISSUE 15), where the residual
        # stream shards the sequence over (cp, tp) jointly on the
        # contiguous p2p cp ring. Layouts the composition excludes
        # (MLA, MoE, a2a-family cp comms) keep the tp-replicated body,
        # so the stricter whole-head / sequence divisibility rules must
        # not reject those configs.
        from megatronapp_tpu.parallel.overlap import (
            tp_stage_cp_excluded_reason,
        )
        cp = args.context_parallel_size or 1
        tp_stage_candidate = (args.pipeline_model_parallel_size > 1
                              and model.tp_sharded_stage
                              and (cp <= 1
                                   or tp_stage_cp_excluded_reason(
                                       model, cp) is None))
        seq_shard = tp * (cp if cp > 1 else 1)
        if tp_stage_candidate and args.seq_length % seq_shard:
            what = (f"tp ({tp})" if cp <= 1
                    else f"cp*tp ({seq_shard})")
            raise ValueError(
                "--tp-comm-overlap with pp>1 runs the tp-SHARDED "
                "pipeline stage body, which shards the sequence over "
                f"{'tp' if cp <= 1 else '(cp, tp) jointly'}: "
                f"--seq-length ({args.seq_length}) must divide by "
                f"{what} — or pass --no-tp-sharded-stage for the "
                "replicated baseline")
        if model.multi_latent_attention:
            # Dense MLA never routes through the GSPMD overlap rings
            # (only its MLP does — covered by the ffn check above); only
            # the pp>1 tp-SHARDED stage body slices whole MLA heads.
            if tp_stage_candidate and model.num_attention_heads % tp:
                raise ValueError(
                    "--tp-comm-overlap with pp>1 runs the tp-SHARDED "
                    "pipeline stage body, which slices WHOLE MLA heads: "
                    f"--num-attention-heads ({model.num_attention_heads})"
                    f" must divide by tp ({tp}) — or pass "
                    "--no-tp-sharded-stage for the replicated baseline")
        else:
            d = model.head_dim
            if (model.num_attention_heads * d) % tp:
                _reject("QKV projection dim (heads*head_dim)",
                        model.num_attention_heads * d)
            if (2 * model.num_query_groups * d) % tp:
                _reject("KV projection dim (2*num-query-groups*head_dim)",
                        2 * model.num_query_groups * d)
            if tp_stage_candidate and (model.num_attention_heads % tp
                                       or model.num_query_groups % tp):
                raise ValueError(
                    "--tp-comm-overlap with pp>1 runs the tp-SHARDED "
                    "pipeline stage body, which slices WHOLE heads: "
                    f"--num-attention-heads ({model.num_attention_heads}) "
                    f"and --num-query-groups ({model.num_query_groups}) "
                    f"must both divide by tp ({tp}) — or pass "
                    "--no-tp-sharded-stage for the replicated baseline")

    training = TrainingConfig(
        rampup_batch_size=(tuple(args.rampup_batch_size)
                           if args.rampup_batch_size else None),
        sharded_init=args.sharded_init,
        **_validate_ft_args(args),
        metrics_jsonl=args.metrics_jsonl,
        tensorboard_dir=args.tensorboard_dir,
        rerun_mode=args.rerun_mode,
        error_injection_rate=args.error_injection_rate,
        log_straggler=args.log_straggler,
        run_workload_inspector_server=args.run_workload_inspector_server,
        workload_inspector_port=args.workload_inspector_port,
        micro_batch_size=args.micro_batch_size,
        global_batch_size=args.global_batch_size,
        seq_length=args.seq_length,
        train_iters=args.train_iters,
        seed=args.seed,
        log_interval=args.log_interval,
        eval_interval=args.eval_interval,
        eval_iters=args.eval_iters,
        exit_interval=args.exit_interval,
        save_dir=args.save,
        load_dir=args.load,
        save_interval=args.save_interval,
        trace=args.trace,
        trace_interval=args.trace_interval,
        continuous_trace_iterations=args.continuous_trace_iterations,
        trace_dir=args.trace_dir,
        trace_granularity=args.trace_granularity,
    )

    optimizer = OptimizerConfig(
        optimizer=args.optimizer,
        **_validate_dist_opt_args(args),
        lr=args.lr, min_lr=args.min_lr,
        lr_decay_style=args.lr_decay_style,
        lr_warmup_iters=args.lr_warmup_iters,
        lr_decay_iters=args.lr_decay_iters,
        weight_decay=args.weight_decay,
        adam_beta1=args.adam_beta1, adam_beta2=args.adam_beta2,
        adam_eps=args.adam_eps,
        clip_grad=args.clip_grad,
    )
    return model, parallel, training, optimizer


def make_batch_iter_factory(args, training: TrainingConfig,
                            model: TransformerConfig):
    """Data-iterator FACTORY from --data-path (.bin/.idx): called with the
    resume sample offset so checkpoint restarts skip already-consumed data
    (reference consumed_train_samples semantics). Returns None for the
    mock-data fallback (pretrain_gpt builds its own resume-aware stream)."""
    if not args.data_path:
        return None
    from megatronapp_tpu.data.gpt_dataset import GPTDataset, gpt_batches
    from megatronapp_tpu.data.indexed_dataset import IndexedDataset
    indexed = IndexedDataset(args.data_path)
    num_samples = (training.train_iters * training.global_batch_size)
    ds = GPTDataset(indexed, training.seq_length, num_samples,
                    seed=training.seed)

    def factory(start_sample_idx: int = 0):
        return gpt_batches(ds, training.global_batch_size,
                           start_idx=start_sample_idx)

    return factory
