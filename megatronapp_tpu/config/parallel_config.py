"""Parallelism configuration for the TPU device mesh.

TPU-native replacement for the reference's process-group bookkeeping in
``parallel_state.py`` (/root/reference/megatron/core/parallel_state.py:1272
``initialize_model_parallel``). Instead of building NCCL process groups from
global ranks, we describe a ``jax.sharding.Mesh`` factorization; XLA emits the
collectives over ICI/DCN from sharding annotations.

Axis order follows the reference RankGenerator order ``tp-cp-ep-dp-pp``
(parallel_state.py: RankGenerator) so that TP is innermost (fastest-varying,
mapped to the tightest ICI neighborhood) and PP is outermost (can ride DCN
across slices).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


# Canonical mesh axis names, outermost → innermost.
PP_AXIS = "pp"
DP_AXIS = "dp"
EP_AXIS = "ep"
CP_AXIS = "cp"
TP_AXIS = "tp"

MESH_AXES: Tuple[str, ...] = (PP_AXIS, DP_AXIS, EP_AXIS, CP_AXIS, TP_AXIS)


@dataclasses.dataclass
class ParallelConfig:
    """Degrees for every parallel dimension.

    Mirrors the argument semantics of the reference
    (--tensor-model-parallel-size, --pipeline-model-parallel-size,
    --context-parallel-size, --expert-model-parallel-size,
    --num-layers-per-virtual-pipeline-stage, --sequence-parallel;
    arguments.py distributed group :2045ff).
    Data parallel degree is inferred from the device count.
    """

    tensor_parallel: int = 1
    pipeline_parallel: int = 1
    context_parallel: int = 1
    expert_parallel: int = 1
    # Virtual pipeline (interleaved 1F1B): number of model chunks per pp stage.
    virtual_pipeline_parallel: int = 1
    # Korthikanti-style sequence parallelism for LN/dropout regions: on TPU this
    # is an activation-sharding choice (seq dim sharded over tp outside
    # attention/MLP); XLA inserts the all-gather/reduce-scatter pairs.
    sequence_parallel: bool = False
    # Explicit data-parallel degree; None = infer from num_devices.
    data_parallel: Optional[int] = None
    # ZeRO-1/3 style sharding of optimizer state / params over dp
    # (reference --use-distributed-optimizer / custom_fsdp).
    distributed_optimizer: bool = True
    fsdp: bool = False
    # Number of pipeline microbatches per global step.
    num_microbatches: int = 1
    # MegaFBD analogue: run forward and backward on disjoint sub-meshes.
    forward_backward_disaggregating: bool = False
    # MegaDPP analogue: chunk/microbatch traversal policy for the pipeline
    # schedule ('dfc' depth-first-chunk = interleaved, 'bfc'
    # breadth-first-chunk = sequential chunk passes; reference paper §5.2).
    pipeline_order_policy: str = "dfc"
    # MegaDPP dynamic runtime: drive pp execution through the host
    # readiness-driven scheduler (runtime/dpp_train.py) when the layout
    # allows (pure pp); otherwise the policy above orders the SPMD
    # schedule statically.
    use_dpp: bool = False
    # Pipeline schedule program (parallel/schedule.py, ISSUE 15):
    # '1f1b' (interleaved automatically when vpp > 1), 'vpp' (alias that
    # requires vpp > 1), or 'zero-bubble' (backward split into B=dgrad /
    # W=wgrad steps; W deferred into bubble slots, weight update fenced
    # on all W done — grads identical to the fused backward).
    pp_schedule: str = "1f1b"
    # Trace-driven dynamic planning: let parallel/schedule.Planner
    # choose/retune the schedule from per-stage step-time EWMAs
    # (MegaScan spans + straggler signal + the heterogeneous stage
    # table). Re-plans log loudly and rebuild the train step.
    pp_plan_from_trace: bool = False

    def __post_init__(self):
        for name in ("tensor_parallel", "pipeline_parallel", "context_parallel",
                     "expert_parallel", "virtual_pipeline_parallel"):
            v = getattr(self, name)
            if v < 1:
                raise ValueError(f"{name} must be >= 1, got {v}")
        if self.sequence_parallel and self.tensor_parallel == 1:
            # Harmless no-op; keep parity with reference which warns+disables.
            self.sequence_parallel = False
        if self.pipeline_order_policy not in ("dfc", "bfc"):
            raise ValueError(
                f"pipeline_order_policy must be 'dfc' or 'bfc', got "
                f"{self.pipeline_order_policy!r}")
        # Canonical name list lives with the schedule layer (lazy import
        # — config must stay import-light).
        from megatronapp_tpu.parallel.schedule import SCHEDULES
        if self.pp_schedule not in SCHEDULES:
            raise ValueError(
                f"pp_schedule must be one of {SCHEDULES}, "
                f"got {self.pp_schedule!r}")
        if self.pp_schedule == "vpp" and self.virtual_pipeline_parallel <= 1:
            raise ValueError(
                "pp_schedule 'vpp' requires virtual_pipeline_parallel > 1 "
                "(--num-layers-per-virtual-pipeline-stage); plain 1F1B "
                "is pp_schedule '1f1b'")

    @property
    def model_parallel_size(self) -> int:
        return (self.tensor_parallel * self.pipeline_parallel *
                self.context_parallel)

    def infer_data_parallel(self, num_devices: int) -> int:
        denom = (self.tensor_parallel * self.pipeline_parallel *
                 self.context_parallel * self.expert_parallel)
        if num_devices % denom != 0:
            raise ValueError(
                f"num_devices={num_devices} not divisible by "
                f"tp*pp*cp*ep={denom}")
        dp = num_devices // denom
        if self.data_parallel is not None and self.data_parallel != dp:
            raise ValueError(
                f"explicit data_parallel={self.data_parallel} inconsistent with "
                f"num_devices={num_devices} (inferred {dp})")
        return dp

    def mesh_shape(self, num_devices: int) -> Tuple[int, ...]:
        dp = self.infer_data_parallel(num_devices)
        return (self.pipeline_parallel, dp, self.expert_parallel,
                self.context_parallel, self.tensor_parallel)
