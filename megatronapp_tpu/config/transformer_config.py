"""Transformer architecture configuration.

TPU-native analogue of the reference's ``TransformerConfig`` dataclass
(/root/reference/megatron/core/transformer/transformer_config.py:18) and
``ModelParallelConfig`` (/root/reference/megatron/core/model_parallel_config.py).
The reference couples these to CUDA-era concerns (TE, fp8 recipes, CUDA graphs);
here the config describes the *math* of the model plus TPU-relevant choices
(dtype policy, remat policy, kernel implementation selection).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax.numpy as jnp


class AttnMaskType(enum.Enum):
    causal = "causal"
    padding = "padding"
    bidirectional = "bidirectional"


class ActivationKind(enum.Enum):
    gelu = "gelu"
    swiglu = "swiglu"
    geglu = "geglu"
    relu = "relu"
    squared_relu = "squared_relu"


class NormKind(enum.Enum):
    layernorm = "LayerNorm"
    rmsnorm = "RMSNorm"


class PositionEmbeddingKind(enum.Enum):
    rope = "rope"
    learned_absolute = "learned_absolute"
    yarn = "yarn"
    none = "none"


@dataclasses.dataclass
class TransformerConfig:
    """Architecture hyperparameters.

    Field semantics follow the reference TransformerConfig
    (transformer_config.py:18) — num_layers/hidden_size/num_attention_heads/
    num_query_groups/ffn_hidden_size/kv_channels etc. — expressed TPU-first.
    """

    num_layers: int = 2
    hidden_size: int = 128
    num_attention_heads: int = 8
    # GQA: number of KV heads (reference: num_query_groups).
    num_query_groups: Optional[int] = None
    ffn_hidden_size: Optional[int] = None
    kv_channels: Optional[int] = None
    vocab_size: int = 50304
    # Tokenizer's true vocab when vocab_size is padded to a TP-friendly
    # multiple (reference --make-vocab-size-divisible-by): inference masks
    # logits for padded ids so sampling cannot emit out-of-vocab tokens.
    true_vocab_size: Optional[int] = None
    max_position_embeddings: int = 2048

    # Normalization / activation / position embedding.
    normalization: NormKind = NormKind.layernorm
    layernorm_epsilon: float = 1e-5
    activation: ActivationKind = ActivationKind.gelu
    position_embedding: PositionEmbeddingKind = PositionEmbeddingKind.rope
    rotary_base: float = 10000.0
    rotary_percent: float = 1.0
    # YaRN context extension (position_embedding=yarn; reference
    # yarn_rotary_pos_embedding.py): trained-context multiplier and the
    # original pretraining context length.
    rope_scaling_factor: float = 1.0
    yarn_original_max_position: int = 4096
    yarn_beta_fast: float = 32.0
    yarn_beta_slow: float = 1.0
    yarn_mscale_coeff: float = 0.1
    add_qkv_bias: bool = False
    add_bias_linear: bool = True
    qk_layernorm: bool = False
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    untie_embeddings_and_output_weights: bool = False

    # Dropout (structural parity; usually 0 for LLM pretraining).
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0

    # Initialization.
    init_method_std: float = 0.02

    # Softmax / logits details (reference: apply_query_key_layer_scaling etc.).
    attention_softmax_in_fp32: bool = True
    apply_query_key_layer_scaling: bool = False

    # MoE (reference: transformer_config.py moe_* fields; moe/ directory).
    num_moe_experts: Optional[int] = None
    moe_router_topk: int = 2
    moe_ffn_hidden_size: Optional[int] = None
    moe_aux_loss_coeff: float = 0.0
    moe_z_loss_coeff: float = 0.0
    moe_shared_expert_intermediate_size: Optional[int] = None
    moe_capacity_factor: Optional[float] = None
    # Layer frequency: 1 = every layer is MoE; k = every k-th layer.
    moe_layer_freq: int = 1

    # Multi-token prediction (DeepSeek-V3; reference
    # multi_token_prediction.py + transformer_config mtp_num_layers /
    # mtp_loss_scaling_factor).
    mtp_num_layers: Optional[int] = None
    mtp_loss_scaling_factor: float = 0.1

    # Multi-latent attention (DeepSeek-style MLA; reference multi_latent_attention.py:44).
    multi_latent_attention: bool = False
    q_lora_rank: Optional[int] = None
    kv_lora_rank: int = 512
    qk_head_dim: int = 128
    qk_pos_emb_head_dim: int = 64
    v_head_dim: int = 128

    # dtype policy: params kept in fp32, compute in bf16 (TPU-native mixed precision).
    params_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16

    # Rematerialization policy for the layer scan:
    # 'none' | 'full' | 'selective' | 'selective_attn'.
    # 'selective' checkpoints only attention internals (reference
    # --recompute-activations semantics, arguments.py recompute group);
    # 'selective_attn' additionally saves the attention outputs so the
    # flash kernel forward is not re-executed in the backward pass.
    remat_policy: str = "selective"

    # Context-parallel attention mode (reference cp_comm_type,
    # transformer_config.py:458-462): 'p2p' ring / 'a2a' Ulysses /
    # 'allgather'.
    cp_comm_type: str = "p2p"
    # Inner all-to-all group size for cp_comm_type='a2a+p2p' (reference
    # --hierarchical-context-parallel-sizes inner dimension).
    hierarchical_cp_a2a_size: int = 2
    # Causal 'p2p' ring uses the load-balanced zigzag layout (rank i holds
    # chunks i and 2cp-1-i — the reference's TE ring behavior). Disable to
    # force the contiguous-layout ring (debug/oracle comparisons).
    cp_zigzag: bool = True
    # Latency-hiding contiguous ring attention (ops/context_parallel.py):
    # every KV-block ppermute hop is issued before the block compute it
    # feeds, and the p2p ring carries a custom_vjp whose backward runs the
    # symmetric reverse ring fused with the dK/dV accumulation (one pass,
    # accumulators travel with their blocks). Disable to fall back to the
    # plain unrolled ring differentiated by autodiff (debug/A-B baselines).
    cp_comm_overlap: bool = True
    # Latency-hiding MoE expert dispatch (transformer/moe.py
    # _chunked_a2a_ffn): the ep token exchange is decomposed into per-peer
    # ppermute hops, each issued before the expert GEMMs on the
    # previously-arrived chunk (results return the same way). Disable for
    # the bulk two-all_to_all dispatch (debug/A-B baselines).
    moe_comm_overlap: bool = True

    # Kernel implementation selection (spec_utils.py ModuleSpec analogue):
    # 'reference' = pure jnp; 'pallas' = fused Pallas flash attention;
    # 'auto' = on TPU, pallas for sequences >= flash_min_seq and the
    # XLA dense path below it, reference elsewhere.
    attention_impl: str = "auto"

    # Latency-hiding tensor-parallel matmuls (reference --tp-comm-overlap;
    # parallel/overlap.py): replace the GSPMD column/row-parallel
    # projections in attention/MLP with manual ring all-gather-matmul /
    # matmul-reduce-scatter so the tp collective hops ride under the
    # dependent GEMM chunks. Chunk count auto-derives from the tp degree.
    # Defaults off; ineligible layouts (tp=1, cp>1, inside a manual pp
    # region, indivisible projection dims) silently keep the GSPMD path.
    tp_comm_overlap: bool = False

    # tp-SHARDED stage bodies inside the full-manual pp pipeline
    # (parallel/pipeline.py tp_shard + overlap.py tp_stage_eligible):
    # activations shard over tp along the sequence between stages and the
    # stage projections run the manual ring primitives on per-shard weight
    # slices — tp× fewer stage FLOPs and tp× smaller pp hops than the
    # tp-replicated body. On by default wherever eligible (cp == 1,
    # divisible S/heads/ffn); this is the A/B kill-switch
    # (--no-tp-sharded-stage) forcing the replicated baseline.
    # tp_comm_overlap picks ring (True) vs bulk (False) collectives
    # INSIDE the sharded body.
    tp_sharded_stage: bool = True

    # Flash/dense crossover for 'auto' (PERF.md lever #2): at short
    # sequences the O(S^2) dense backward is FASTER on this chip than
    # the flash backward kernels at D=64 (measured 8x at S=1024 —
    # half-empty MXU lanes + recompute overhead dominate below the
    # memory-capacity regime flash exists for). 'pallas' forces flash
    # regardless.
    flash_min_seq: int = 2048

    # Fused dot-product attention blockwise kernel sizes (Pallas).
    flash_block_q: int = 512
    flash_block_kv: int = 512

    # lax.scan unroll factor for the layer stack (PERF.md lever #3:
    # unrolling lets XLA software-pipeline across layer boundaries at
    # the cost of code size/compile time). Must divide num_layers.
    # Honored by training (block_forward) AND the serving decode /
    # multi-query step scans (ISSUE 11) — unrolling the decode layer
    # loop removes its while-iteration dispatch overhead.
    scan_unroll: int = 1

    # Head-fold flash BACKWARD kernels (PERF.md lever #1, ISSUE 11,
    # --flash-head-fold): fold q-head pairs into the trailing block dim
    # (D=64 → full 128-lane vreg rows for every q/do load and gradient
    # accumulator, half the grid's head extent). Opt-in A/B knob until
    # the on-chip numbers land; ineligible layouts (2D > 128, odd head
    # counts, packed segments) silently keep the standard kernels.
    flash_head_fold: bool = False

    # fp8 (e4m3) training GEMMs with delayed-scaling amax history
    # (ISSUE 13, --fp8): the tp-overlap ring matmuls quantize both
    # operands to fp8 with per-(layer, site, tensor) scales derived
    # from an amax history threaded through the train state
    # (training/fp8.py). Requires tp_comm_overlap on a tp>1, pp==1,
    # cp==1, dense non-MLA/non-MoE layout (fp8_ineligible_reason names
    # the first failed predicate). fp8_margin: scale = FP8_MAX /
    # (amax * 2**margin) — headroom against inter-step amax growth.
    # fp8_amax_history_len: history window H (TE-default-ish 16; the
    # scale follows max over the window).
    fp8: bool = False
    fp8_margin: int = 0
    fp8_amax_history_len: int = 16

    # Heterogeneous per-layer structure (reference
    # heterogeneous_config.py HeterogeneousTransformerConfig): the HF
    # Nemotron "block_configs" JSON (encoded string). When set, layers
    # follow their individual specs (no-op / linear-replacement /
    # per-layer GQA + FFN sizes) and the block unrolls instead of
    # scanning.
    heterogeneous_layers_config_json: Optional[str] = None

    def __post_init__(self):
        self.hetero_block_specs = None
        if self.heterogeneous_layers_config_json:
            from megatronapp_tpu.transformer.heterogeneous import (
                parse_block_configs,
            )
            self.hetero_block_specs = parse_block_configs(
                self.heterogeneous_layers_config_json,
                num_attention_heads=self.num_attention_heads,
                hidden_size=self.hidden_size)
        if self.ffn_hidden_size is None:
            if self.activation in (ActivationKind.swiglu, ActivationKind.geglu):
                self.ffn_hidden_size = int(4 * self.hidden_size * 2 / 3)
            else:
                self.ffn_hidden_size = 4 * self.hidden_size
        if self.kv_channels is None:
            self.kv_channels = self.hidden_size // self.num_attention_heads
        if self.num_query_groups is None:
            self.num_query_groups = self.num_attention_heads
        if self.num_attention_heads % self.num_query_groups != 0:
            raise ValueError(
                f"num_attention_heads ({self.num_attention_heads}) must be divisible by "
                f"num_query_groups ({self.num_query_groups})"
            )
        if self.num_moe_experts is not None and self.moe_ffn_hidden_size is None:
            self.moe_ffn_hidden_size = self.ffn_hidden_size
        from megatronapp_tpu.ops.context_parallel import CP_COMM_TYPES
        if self.cp_comm_type not in CP_COMM_TYPES:
            raise ValueError(
                f"cp_comm_type must be one of {sorted(CP_COMM_TYPES)} "
                f"('p2p' = ring, 'a2a' = Ulysses), got "
                f"{self.cp_comm_type!r}")

    @property
    def is_moe(self) -> bool:
        return self.num_moe_experts is not None

    @property
    def head_dim(self) -> int:
        return self.kv_channels

    def num_parameters(self) -> int:
        """Approximate parameter count (embedding + blocks + final norm)."""
        h = self.hidden_size
        v = self.vocab_size
        n_kv = self.num_query_groups
        d = self.head_dim
        per_layer = (
            h * (self.num_attention_heads * d)  # Q
            + 2 * h * (n_kv * d)  # K,V
            + (self.num_attention_heads * d) * h  # out proj
            + 2 * h  # ln
        )
        if self.activation in (ActivationKind.swiglu, ActivationKind.geglu):
            per_layer += 3 * h * self.ffn_hidden_size
        else:
            per_layer += 2 * h * self.ffn_hidden_size
        per_layer += 2 * h  # second ln
        total = v * h + per_layer * self.num_layers + 2 * h
        if self.position_embedding == PositionEmbeddingKind.learned_absolute:
            total += self.max_position_embeddings * h
        if self.untie_embeddings_and_output_weights:
            total += v * h
        return total
