"""DINO self-distillation pretraining (student/teacher ViT).

Parity with /root/reference/megatron/legacy/model/vision/dino.py
(DINOLoss :23, DINOHead :82, MultiCropWrapper :118, DINOPretrainModel :219,
cosine_scheduler :159) and pretrain_vision_dino.py. TPU-first re-design:
the reference's stateful torch modules (EMA teacher, center buffer,
momentum/temp schedules indexed by epoch) become one pure jitted train
step over an explicit state pytree {student params, opt state, teacher
params, center} — the EMA update, the center momentum update, and the
last-layer gradient freeze are all traced-in `lax`-friendly arithmetic,
and the cross-replica center mean falls out of jnp.mean over the
dp-sharded batch axis (the reference's hand-written all_reduce,
dino.py:73-80).

Multi-crop: 2 global + N local views. Local crops run the same backbone
with the patch-grid position table bilinearly resized (the reference
interpolates pos embeddings inside VitBackbone for mismatched input
sizes); both resolutions batch over the leading axis so the MXU sees two
large matmul streams instead of ncrops small ones.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.models.vision import (
    VitSpec, init_vit_params, vit_backbone,
)


@dataclasses.dataclass
class DinoSpec:
    """DINO hyperparameters (reference args: --dino-* flags,
    arguments.py _add_vision_args)."""
    out_dim: int = 65536              # prototype count (dino.py out_dim)
    head_hidden: int = 2048           # --dino-head-hidden-size
    bottleneck: int = 256             # --dino-bottleneck-size
    head_nlayers: int = 3
    norm_last_layer: bool = True      # --dino-norm-last-layer
    n_local_crops: int = 2            # --dino-local-crops-number
    local_crop_size: int = 96         # --dino-local-img-size
    student_temp: float = 0.1
    warmup_teacher_temp: float = 0.04  # --dino-warmup-teacher-temp
    teacher_temp: float = 0.07         # --dino-teacher-temp
    warmup_teacher_temp_iters: int = 0
    center_momentum: float = 0.9
    momentum_teacher: float = 0.996    # --dino-momentum-teacher
    freeze_last_layer_iters: int = 0   # --dino-freeze-last-layer (in iters)


# ---------------------------------------------------------------------------
# Parameters


def init_dino_head_params(rng, in_dim: int, spec: DinoSpec, std: float):
    """MLP (nlayers, GELU) → L2-normalize → weight-normed linear
    (reference DINOHead, dino.py:82-116)."""
    n = max(spec.head_nlayers, 1)
    keys = jax.random.split(rng, n + 1)
    p: Dict[str, Any] = {}
    ax: Dict[str, Any] = {}
    dims = ([in_dim, spec.bottleneck] if n == 1 else
            [in_dim] + [spec.head_hidden] * (n - 1) + [spec.bottleneck])
    for i in range(n):
        p[f"mlp{i}_kernel"] = jax.random.normal(
            keys[i], (dims[i], dims[i + 1]), jnp.float32) * std
        p[f"mlp{i}_bias"] = jnp.zeros((dims[i + 1],), jnp.float32)
        ax[f"mlp{i}_kernel"] = (None, None)
        ax[f"mlp{i}_bias"] = (None,)
    # Weight-norm direction v; magnitude g is fixed at 1 when
    # norm_last_layer (reference weight_g.requires_grad=False).
    p["last_v"] = jax.random.normal(
        keys[n], (spec.bottleneck, spec.out_dim), jnp.float32) * std
    ax["last_v"] = (None, None)
    if not spec.norm_last_layer:
        p["last_g"] = jnp.ones((spec.out_dim,), jnp.float32)
        ax["last_g"] = (None,)
    return p, ax


def dino_head_forward(p, x: jnp.ndarray, spec: DinoSpec) -> jnp.ndarray:
    """[B, H] features → [B, out_dim] prototype scores."""
    x = x.astype(jnp.float32)
    n = max(spec.head_nlayers, 1)
    for i in range(n):
        x = x @ p[f"mlp{i}_kernel"] + p[f"mlp{i}_bias"]
        if i < n - 1:
            x = jax.nn.gelu(x)
    x = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-12)
    v = p["last_v"]
    w = v / (jnp.linalg.norm(v, axis=0, keepdims=True) + 1e-12)
    if "last_g" in p:
        w = w * p["last_g"][None, :]
    return x @ w


def init_dino_params(rng, cfg: TransformerConfig, vit_spec: VitSpec,
                     spec: DinoSpec):
    """Student params + logical axes. The teacher is a structural copy
    made by the caller (same pytree), never differentiated."""
    kb, kh = jax.random.split(rng)
    backbone, bb_ax = init_vit_params(kb, cfg, vit_spec, with_head=False)
    head, head_ax = init_dino_head_params(kh, cfg.hidden_size, spec,
                                          cfg.init_method_std)
    return ({"backbone": backbone, "head": head},
            {"backbone": bb_ax, "head": head_ax})


# ---------------------------------------------------------------------------
# Multi-crop forward


def _adapt_pos(pos: jnp.ndarray, from_grid: int, to_grid: int) -> jnp.ndarray:
    """Bilinearly resize the patch-grid part of a [1+P, H] position table
    to a different crop resolution (reference VitBackbone interpolates for
    mismatched img sizes; DINO local crops are smaller than global)."""
    if from_grid == to_grid:
        return pos
    cls_pos, grid = pos[:1], pos[1:]
    h = grid.shape[-1]
    grid = grid.reshape(from_grid, from_grid, h)
    grid = jax.image.resize(grid, (to_grid, to_grid, h), method="bilinear")
    return jnp.concatenate([cls_pos, grid.reshape(to_grid * to_grid, h)], 0)


def dino_branch_forward(p, images: jnp.ndarray, cfg: TransformerConfig,
                        vit_spec: VitSpec, spec: DinoSpec,
                        ctx=None) -> jnp.ndarray:
    """One branch (student or teacher) over a stack of same-size crops:
    [B, S, S, C] → [B, out_dim]. Handles local-crop sizes by resizing the
    position table to the crop's patch grid."""
    crop = images.shape[1]
    from_grid = vit_spec.image_size // vit_spec.patch_size
    to_grid = crop // vit_spec.patch_size
    bb = p["backbone"]
    if to_grid != from_grid:
        bb = dict(bb, pos=_adapt_pos(bb["pos"], from_grid, to_grid))
    local_spec = dataclasses.replace(vit_spec, image_size=crop)
    enc = vit_backbone(bb, images, cfg, local_spec, ctx=ctx)
    return dino_head_forward(p["head"], enc[:, 0], spec)


def dino_forward(student, teacher, global_crops: jnp.ndarray,
                 local_crops: Optional[jnp.ndarray],
                 cfg: TransformerConfig, vit_spec: VitSpec, spec: DinoSpec,
                 ctx=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """global_crops [B, 2, S, S, C]; local_crops [B, n, s, s, C] or None.

    Returns (student_out [(2+n)*B, out_dim] view-major, teacher_out
    [2*B, out_dim]) — the reference MultiCropWrapper's chunk layout."""
    b = global_crops.shape[0]
    # View-major ordering: crops of one view are contiguous (torch.chunk
    # semantics in DINOLoss), so [B,2,...] must transpose to [2,B,...].
    flat_g = global_crops.transpose(1, 0, 2, 3, 4).reshape(
        (2 * b,) + global_crops.shape[2:])
    s_global = dino_branch_forward(student, flat_g, cfg, vit_spec, spec,
                                   ctx=ctx)
    t_out = dino_branch_forward(teacher, flat_g, cfg, vit_spec, spec,
                                ctx=ctx)
    if local_crops is not None and local_crops.shape[1] > 0:
        n = local_crops.shape[1]
        flat_l = local_crops.transpose(1, 0, 2, 3, 4).reshape(
            (n * b,) + local_crops.shape[2:])
        s_local = dino_branch_forward(student, flat_l, cfg, vit_spec, spec,
                                      ctx=ctx)
        s_out = jnp.concatenate([s_global, s_local], axis=0)
    else:
        s_out = s_global
    return s_out, jax.lax.stop_gradient(t_out)


# ---------------------------------------------------------------------------
# Loss + schedules


def teacher_temp_at(step, spec: DinoSpec):
    """Linear warmup warmup_teacher_temp → teacher_temp (reference
    teacher_temp_schedule, dino.py:34-39, per-iter instead of per-epoch)."""
    w = max(spec.warmup_teacher_temp_iters, 1)
    frac = jnp.clip(step.astype(jnp.float32) / w, 0.0, 1.0)
    warm = spec.warmup_teacher_temp + frac * (
        spec.teacher_temp - spec.warmup_teacher_temp)
    return jnp.where(step >= spec.warmup_teacher_temp_iters,
                     spec.teacher_temp, warm)


def teacher_momentum_at(step, train_iters: int, spec: DinoSpec):
    """Cosine ramp momentum_teacher → 1.0 (reference cosine_scheduler,
    dino.py:159, applied to the EMA momentum in update_momentum :286)."""
    frac = jnp.clip(step.astype(jnp.float32) / max(train_iters, 1), 0., 1.)
    return 1.0 - (1.0 - spec.momentum_teacher) * (
        jnp.cos(jnp.pi * frac) + 1.0) / 2.0


def dino_loss(student_out: jnp.ndarray, teacher_out: jnp.ndarray,
              center: jnp.ndarray, step, spec: DinoSpec,
              batch_size: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Cross-entropy between teacher and student softmaxes across views,
    skipping same-view pairs (reference DINOLoss.forward, dino.py:41-71).

    Returns (loss, new_center). The center update (momentum mean of
    teacher outputs, dino.py:73-80) is global across data-parallel
    replicas for free: under jit the batch axis is dp-sharded and
    jnp.mean reduces globally.
    """
    temp = teacher_temp_at(step, spec)
    t = jax.nn.softmax((teacher_out - center) / temp, axis=-1)
    t = jax.lax.stop_gradient(t)
    s_views = student_out.reshape(-1, batch_size, spec.out_dim)
    t_views = t.reshape(2, batch_size, spec.out_dim)
    s_logp = jax.nn.log_softmax(s_views / spec.student_temp, axis=-1)

    total = jnp.zeros((), jnp.float32)
    n_terms = 0
    for iq in range(2):
        for v in range(s_views.shape[0]):
            if v == iq:
                continue  # skip same-view pairs (dino.py:63)
            total += jnp.mean(jnp.sum(-t_views[iq] * s_logp[v], axis=-1))
            n_terms += 1
    loss = total / max(n_terms, 1)

    batch_center = jnp.mean(teacher_out, axis=0, keepdims=True)
    new_center = (center * spec.center_momentum +
                  batch_center * (1.0 - spec.center_momentum))
    return loss, jax.lax.stop_gradient(new_center)


# ---------------------------------------------------------------------------
# Train step (student grads → optimizer → EMA teacher → center)


def setup_dino_train_state(rng, cfg: TransformerConfig, vit_spec: VitSpec,
                           spec: DinoSpec, optimizer, ctx):
    """State pytree {'step','params','opt_state','teacher','center'},
    jit-initialized into shardings (teacher mirrors the student's axes;
    the reference clones the student into the teacher at startup,
    dino.py:242-252)."""
    from megatronapp_tpu.parallel.sharding import tree_logical_to_sharding
    from megatronapp_tpu.training.train_state import (
        pick_rules, state_logical_axes,
    )

    captured = {}

    def _shapes_only(r):
        p, ax = init_dino_params(r, cfg, vit_spec, spec)
        captured["axes"] = ax
        return p

    jax.eval_shape(_shapes_only, rng)
    params_axes = captured["axes"]

    def _init(r):
        params, _ = init_dino_params(r, cfg, vit_spec, spec)
        return {"step": jnp.zeros((), jnp.int32), "params": params,
                "opt_state": optimizer.init(params),
                "teacher": jax.tree.map(jnp.copy, params),
                "center": jnp.zeros((1, spec.out_dim), jnp.float32)}

    struct = jax.eval_shape(_init, rng)
    axes = state_logical_axes(params_axes, struct["opt_state"])
    axes["teacher"] = params_axes
    axes["center"] = (None, None)
    shardings = tree_logical_to_sharding(axes, ctx.mesh, pick_rules(ctx))
    with ctx.mesh:
        state = jax.jit(_init, out_shardings=shardings)(rng)
    return state, shardings


def make_dino_train_step(cfg: TransformerConfig, vit_spec: VitSpec,
                         spec: DinoSpec, optimizer, opt_cfg, ctx,
                         state_shardings, train_iters: int):
    """One jitted step: student grad + update, teacher EMA, center update
    (reference pretrain loop: loss_func + update_momentum +
    cancel_gradients_last_layer, dino.py:266-293)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatronapp_tpu.training.optimizer import (
        global_grad_norm, lr_schedule,
    )
    sched = lr_schedule(opt_cfg, train_iters)

    def step(state, batch):
        params, teacher = state["params"], state["teacher"]
        b = batch["global_crops"].shape[0]

        def loss_fn(p):
            s_out, t_out = dino_forward(
                p, teacher, batch["global_crops"],
                batch.get("local_crops"), cfg, vit_spec, spec, ctx=ctx)
            loss, new_center = dino_loss(s_out, t_out, state["center"],
                                         state["step"], spec, b)
            return loss, (new_center, t_out)

        (loss, (new_center, t_out)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)

        # Freeze the last prototype layer for the first K iters
        # (reference cancel_gradients_last_layer, dino.py:278-284).
        if spec.freeze_last_layer_iters > 0:
            gate = (state["step"] >=
                    spec.freeze_last_layer_iters).astype(jnp.float32)
            grads["head"]["last_v"] = grads["head"]["last_v"] * gate
            if "last_g" in grads["head"]:
                grads["head"]["last_g"] = grads["head"]["last_g"] * gate

        grad_norm = global_grad_norm(grads)
        updates, new_opt = optimizer.update(grads, state["opt_state"],
                                            params)
        new_params = jax.tree.map(lambda p, u: p + u.astype(p.dtype),
                                  params, updates)

        m = teacher_momentum_at(state["step"], train_iters, spec)
        new_teacher = jax.tree.map(
            lambda t, s: t * m + s.astype(t.dtype) * (1.0 - m),
            teacher, new_params)

        new_state = {"step": state["step"] + 1, "params": new_params,
                     "opt_state": new_opt, "teacher": new_teacher,
                     "center": new_center}
        metrics = {"loss": loss, "grad_norm": grad_norm,
                   "lr": sched(state["step"]), "teacher_momentum": m}
        return new_state, metrics

    b_sh = NamedSharding(ctx.mesh, P(ctx.batch_spec()[0]))
    return jax.jit(step, in_shardings=(state_shardings, b_sh),
                   out_shardings=(state_shardings, None),
                   donate_argnums=(0,))


# ---------------------------------------------------------------------------
# KNN monitor (reference knn_monitor.py knn_predict / feature bank)


def knn_predict(feature: jnp.ndarray, feature_bank: jnp.ndarray,
                feature_labels: jnp.ndarray, classes: int, knn_k: int,
                knn_t: float) -> jnp.ndarray:
    """Weighted-KNN class prediction (reference knn_monitor.knn_predict:
    cosine sim → top-k → exp(sim/T) weights → one-hot score sum).

    feature [B, D] (L2-normalized), feature_bank [D, N],
    feature_labels [N] → predicted labels [B, classes-ranked]."""
    sim = feature @ feature_bank                       # [B, N]
    sim_w, idx = jax.lax.top_k(sim, knn_k)             # [B, K]
    labels = feature_labels[idx]                       # [B, K]
    w = jnp.exp(sim_w / knn_t)
    one_hot = jax.nn.one_hot(labels, classes, dtype=w.dtype)  # [B, K, C]
    scores = jnp.sum(one_hot * w[..., None], axis=1)   # [B, C]
    return jnp.argsort(-scores, axis=-1)


def compute_features(teacher, images: jnp.ndarray, cfg: TransformerConfig,
                     vit_spec: VitSpec, ctx=None) -> jnp.ndarray:
    """L2-normalized teacher CLS features for the bank
    (knn_monitor.compute_feature_bank)."""
    enc = vit_backbone(teacher["backbone"], images, cfg, vit_spec, ctx=ctx)
    f = enc[:, 0].astype(jnp.float32)
    return f / (jnp.linalg.norm(f, axis=-1, keepdims=True) + 1e-12)
