"""Mamba (selective state-space) model.

Parity with /root/reference/megatron/core/ssm/ (MambaMixer/MambaBlock,
1.6k LoC; hybrid mamba/attention layer allocation in mamba_hybrid_layer_
allocation.py). The reference leans on Triton kernels for the selective
scan; TPU-first this is a ``lax.associative_scan`` — the first-order
recurrence h_t = a_t h_{t-1} + b_t is associative, so XLA lowers it to a
log-depth parallel scan that maps well onto the VPU, no custom kernel
needed.

Mixer structure (Mamba-1): in_proj → (x, z); causal depthwise conv1d;
silu; data-dependent Δ, B, C; selective scan over diagonal A; gate by
silu(z); out_proj.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    NormKind, TransformerConfig,
)
from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
from megatronapp_tpu.ops.normalization import apply_norm, rms_norm
from megatronapp_tpu.parallel.sharding import is_logical_axes
from megatronapp_tpu.transformer.block import (
    _remat_wrap, init_layer_params, layer_forward,
)


@dataclasses.dataclass
class MambaConfig:
    """SSM hyperparameters (reference MambaMixer defaults)."""
    state_dim: int = 16        # N
    conv_kernel: int = 4
    expand: int = 2            # E = expand * hidden
    dt_rank: Optional[int] = None  # defaults to ceil(hidden/16)
    # 'M' = mamba layer, '*' = attention layer (reference hybrid allocation
    # string, e.g. 'MMM*MMM*' — ssm/mamba_hybrid_layer_allocation.py).
    hybrid_pattern: Optional[str] = None


def init_mamba_mixer_params(rng, cfg: TransformerConfig, mcfg: MambaConfig):
    h = cfg.hidden_size
    e = mcfg.expand * h
    n = mcfg.state_dim
    dt_rank = mcfg.dt_rank or max(h // 16, 1)
    keys = jax.random.split(rng, 6)
    std = cfg.init_method_std
    p = {
        "in_kernel": jax.random.normal(keys[0], (h, 2 * e),
                                       cfg.params_dtype) * std,
        "conv_kernel": jax.random.normal(
            keys[1], (mcfg.conv_kernel, e), cfg.params_dtype) * std,
        "conv_bias": jnp.zeros((e,), cfg.params_dtype),
        # x → (Δ_rank, B, C)
        "x_proj": jax.random.normal(keys[2], (e, dt_rank + 2 * n),
                                    cfg.params_dtype) * std,
        "dt_proj": jax.random.normal(keys[3], (dt_rank, e),
                                     cfg.params_dtype) * std,
        # softplus(dt_bias) initialized in [1e-3, 1e-1] (reference dt init).
        "dt_bias": jnp.log(jnp.expm1(jnp.exp(jax.random.uniform(
            keys[4], (e,), jnp.float32,
            jnp.log(1e-3), jnp.log(1e-1))))).astype(cfg.params_dtype),
        # A negative-real diagonal, initialized -[1..N] per channel.
        "A_log": jnp.log(jnp.tile(jnp.arange(1, n + 1, dtype=jnp.float32),
                                  (e, 1))).astype(cfg.params_dtype),
        "D": jnp.ones((e,), cfg.params_dtype),
        "out_kernel": jax.random.normal(
            keys[5], (e, h), cfg.params_dtype) * (
                std / jnp.sqrt(2.0 * cfg.num_layers)),
    }
    ax = {
        "in_kernel": ("embed", "mlp"), "conv_kernel": (None, "mlp"),
        "conv_bias": ("mlp",), "x_proj": ("mlp", None),
        "dt_proj": (None, "mlp"), "dt_bias": ("mlp",),
        "A_log": ("mlp", None), "D": ("mlp",),
        "out_kernel": ("mlp", "embed"),
    }
    return p, ax


def _selective_scan(u, dt, A, B, C, D, return_h: bool = False):
    """u,dt [B,S,E]; A [E,N]; B,C [B,S,N]; D [E] → y [B,S,E].

    h_t = exp(dt_t A) h_{t-1} + dt_t B_t u_t ;  y_t = C_t · h_t + D u_t.
    Runs as a parallel associative scan over the sequence axis.
    return_h also yields the final state h_S [B,E,N] (decode prefill).
    """
    # Discretize: a [B,S,E,N], b [B,S,E,N].
    a = jnp.exp(dt[..., None] * A[None, None])            # [B,S,E,N]
    b = dt[..., None] * B[:, :, None, :] * u[..., None]   # [B,S,E,N]

    def combine(left, right):
        a_l, b_l = left
        a_r, b_r = right
        return a_l * a_r, a_r * b_l + b_r

    _, h = jax.lax.associative_scan(combine, (a, b), axis=1)
    y = jnp.einsum("bsen,bsn->bse", h, C)
    y = y + u * D[None, None]
    return (y, h[:, -1]) if return_h else y


def mamba_mixer_forward(p, x, cfg: TransformerConfig, mcfg: MambaConfig,
                        return_state: bool = False):
    """x [B,S,H] → [B,S,H] (+ (conv_tail [B,k-1,E], h_last [B,E,N]) when
    return_state — the decode cache seeded by prefill)."""
    b, s, h = x.shape
    e = mcfg.expand * h
    n = mcfg.state_dim
    dt_rank = mcfg.dt_rank or max(h // 16, 1)
    dt_f32 = jnp.float32
    xz = x.astype(cfg.compute_dtype) @ p["in_kernel"].astype(
        cfg.compute_dtype)
    u_raw, z = jnp.split(xz, 2, axis=-1)

    # Causal depthwise conv along seq.
    k = mcfg.conv_kernel
    u_pad = jnp.pad(u_raw, ((0, 0), (k - 1, 0), (0, 0)))
    windows = jnp.stack([u_pad[:, i:i + s] for i in range(k)], axis=0)
    u = jnp.einsum("kbse,ke->bse", windows,
                   p["conv_kernel"].astype(u_raw.dtype))
    u = u + p["conv_bias"].astype(u.dtype)
    u = jax.nn.silu(u)

    proj = u @ p["x_proj"].astype(u.dtype)  # [B,S,dt_rank+2N]
    dt_r, B_, C_ = jnp.split(proj, [dt_rank, dt_rank + n], axis=-1)
    dt = jax.nn.softplus(
        dt_r.astype(dt_f32) @ p["dt_proj"].astype(dt_f32)
        + p["dt_bias"].astype(dt_f32))
    A = -jnp.exp(p["A_log"].astype(dt_f32))
    y = _selective_scan(u.astype(dt_f32), dt, A, B_.astype(dt_f32),
                        C_.astype(dt_f32), p["D"].astype(dt_f32),
                        return_h=return_state)
    if return_state:
        y, h_last = y
    y = y.astype(cfg.compute_dtype) * jax.nn.silu(z)
    out = y @ p["out_kernel"].astype(cfg.compute_dtype)
    if not return_state:
        return out
    # conv cache = last k-1 PRE-conv inputs (pad with zeros for short
    # prompts, matching the forward's zero padding).
    conv_tail = u_pad[:, s: s + k - 1]
    return out, (conv_tail, h_last)


def mamba_mixer_step(p, conv_buf, ssm_h, x, cfg: TransformerConfig,
                     mcfg: MambaConfig):
    """One-token recurrent mixer step (the reference decodes Mamba with
    Triton selective_state_update; here plain jnp — the per-token work is
    a handful of small matmuls).

    conv_buf [B,k-1,E] (pre-conv inputs), ssm_h [B,E,N], x [B,H] →
    (y [B,H], (conv_buf', ssm_h')).
    """
    h = x.shape[-1]
    n = mcfg.state_dim
    dt_rank = mcfg.dt_rank or max(h // 16, 1)
    dt_f32 = jnp.float32
    xz = x.astype(cfg.compute_dtype) @ p["in_kernel"].astype(
        cfg.compute_dtype)
    u_raw, z = jnp.split(xz, 2, axis=-1)              # [B,E]

    window = jnp.concatenate([conv_buf, u_raw[:, None]], axis=1)  # [B,k,E]
    u = jnp.einsum("bke,ke->be", window,
                   p["conv_kernel"].astype(u_raw.dtype))
    u = jax.nn.silu(u + p["conv_bias"].astype(u.dtype))

    proj = u @ p["x_proj"].astype(u.dtype)
    dt_r, B_, C_ = jnp.split(proj, [dt_rank, dt_rank + n], axis=-1)
    dt = jax.nn.softplus(
        dt_r.astype(dt_f32) @ p["dt_proj"].astype(dt_f32)
        + p["dt_bias"].astype(dt_f32))                # [B,E]
    A = -jnp.exp(p["A_log"].astype(dt_f32))           # [E,N]
    a = jnp.exp(dt[..., None] * A[None])              # [B,E,N]
    b = dt[..., None] * B_.astype(dt_f32)[:, None, :] \
        * u.astype(dt_f32)[..., None]
    ssm_h = a * ssm_h + b
    y = jnp.einsum("ben,bn->be", ssm_h, C_.astype(dt_f32))
    y = y + u.astype(dt_f32) * p["D"].astype(dt_f32)[None]
    y = y.astype(cfg.compute_dtype) * jax.nn.silu(z)
    out = y @ p["out_kernel"].astype(cfg.compute_dtype)
    return out, (window[:, 1:], ssm_h)


def init_mamba_params(rng, cfg: TransformerConfig, mcfg: MambaConfig):
    """Stacked mamba layers (+ optional interleaved attention via
    hybrid_pattern) + embedding + head."""
    pattern = mcfg.hybrid_pattern or "M" * cfg.num_layers
    if len(pattern) != cfg.num_layers:
        raise ValueError("hybrid_pattern length must equal num_layers")
    k_emb, k_layers, k_out = jax.random.split(rng, 3)
    std = cfg.init_method_std
    h = cfg.hidden_size
    p = {
        "embedding": {"word": jax.random.normal(
            k_emb, (cfg.vocab_size, h), cfg.params_dtype) * std},
        "final_ln_scale": jnp.ones((h,), cfg.params_dtype),
    }
    ax = {
        "embedding": {"word": ("vocab", "embed")},
        "final_ln_scale": ("embed",),
    }
    keys = jax.random.split(k_layers, cfg.num_layers)
    layers_p, layers_ax = [], None
    for i, kind in enumerate(pattern):
        if kind == "M":
            mp, max_ = init_mamba_mixer_params(keys[i], cfg, mcfg)
            lp = {"ln_scale": jnp.ones((h,), cfg.params_dtype),
                  "mixer": mp}
            lax_ = {"ln_scale": ("embed",), "mixer": max_}
        elif kind == "*":
            lp, lax_ = init_layer_params(keys[i], cfg)
        else:
            raise ValueError(f"unknown layer kind {kind!r}")
        layers_p.append((kind, lp, lax_))
    # Hybrid stacks are heterogeneous → store as a list (unrolled loop);
    # a pure-M stack is stacked for lax.scan.
    if set(pattern) == {"M"}:
        p["layers"] = jax.tree.map(lambda *xs: jnp.stack(xs),
                                   *[lp for _, lp, _ in layers_p])
        ax["layers"] = jax.tree.map(lambda axes: ("layers",) + axes,
                                    layers_p[0][2], is_leaf=is_logical_axes)
    else:
        p["layers"] = [lp for _, lp, _ in layers_p]
        ax["layers"] = [lax_ for _, _, lax_ in layers_p]
    return p, ax


def mamba_forward(p, tokens, cfg: TransformerConfig, mcfg: MambaConfig,
                  ctx=None):
    pattern = mcfg.hybrid_pattern or "M" * cfg.num_layers
    h = jnp.take(p["embedding"]["word"], tokens, axis=0).astype(
        cfg.compute_dtype)

    if set(pattern) == {"M"}:
        def body(carry, layer_p):
            x = carry
            y = rms_norm(x, layer_p["ln_scale"], cfg.layernorm_epsilon)
            x = x + mamba_mixer_forward(layer_p["mixer"], y, cfg,
                                        mcfg).astype(x.dtype)
            return x, None

        body = _remat_wrap(body, cfg.remat_policy)
        h, _ = jax.lax.scan(body, h, p["layers"])
    else:
        from megatronapp_tpu.models.gpt import gpt_rope_tables
        cos, sin = gpt_rope_tables(cfg, tokens.shape[1])
        for kind, layer_p in zip(pattern, p["layers"]):
            if kind == "M":
                y = rms_norm(h, layer_p["ln_scale"], cfg.layernorm_epsilon)
                h = h + mamba_mixer_forward(layer_p["mixer"], y, cfg,
                                            mcfg).astype(h.dtype)
            else:
                (h, _), _ = layer_forward(layer_p, h, cfg, cos, sin,
                                          ctx=ctx)

    h = rms_norm(h, p["final_ln_scale"], cfg.layernorm_epsilon)
    dt = cfg.compute_dtype
    logits = h.astype(dt) @ p["embedding"]["word"].T.astype(dt)
    return logits.astype(jnp.float32)


def mamba_loss(p, tokens, targets, loss_mask, cfg: TransformerConfig,
               mcfg: MambaConfig, ctx=None):
    """pretrain_mamba.py loss parity."""
    logits = mamba_forward(p, tokens, cfg, mcfg, ctx=ctx)
    loss, _ = cross_entropy_loss(logits, targets, loss_mask)
    return loss, {"lm_loss": loss}


# ---------------------------------------------------------------------------
# Recurrent generation (reference: core/inference mamba support +
# tools mamba text-generation server). Pure-M stacks carry stacked
# (conv_tail, ssm_h) states through a scan; hybrid stacks additionally
# carry a per-'*'-layer attention KV cache (reference hybrid allocation
# serves through the same inference context as attention models).

def mamba_prefill(p, tokens, cfg: TransformerConfig, mcfg: MambaConfig,
                  max_len: Optional[int] = None):
    """Parallel-scan prefill: logits for the prompt AND the per-layer
    decode caches. Pure-M stacks: states stacked [L, ...]. Hybrid stacks:
    a per-layer list of ('M' conv tail + SSM state) or ('*' K/V cache of
    length ``max_len``, which must cover prompt + generated tokens)."""
    pattern = mcfg.hybrid_pattern or "M" * cfg.num_layers
    h = jnp.take(p["embedding"]["word"], tokens, axis=0).astype(
        cfg.compute_dtype)

    if set(pattern) == {"M"}:
        def body(x, layer_p):
            y = rms_norm(x, layer_p["ln_scale"], cfg.layernorm_epsilon)
            out, state = mamba_mixer_forward(layer_p["mixer"], y, cfg, mcfg,
                                             return_state=True)
            return x + out.astype(x.dtype), state

        h, states = jax.lax.scan(body, h, p["layers"])
    else:
        from megatronapp_tpu.models.gpt import gpt_rope_tables
        b, s = tokens.shape
        max_len = max_len or s
        cos_full, sin_full = gpt_rope_tables(cfg, max_len)
        cos = None if cos_full is None else cos_full[:s]
        sin = None if sin_full is None else sin_full[:s]
        states = []
        for kind, layer_p in zip(pattern, p["layers"]):
            if kind == "M":
                y = rms_norm(h, layer_p["ln_scale"], cfg.layernorm_epsilon)
                out, state = mamba_mixer_forward(layer_p["mixer"], y, cfg,
                                                 mcfg, return_state=True)
                h = h + out.astype(h.dtype)
            else:
                kv = (jnp.zeros((b, max_len, cfg.num_query_groups,
                                 cfg.head_dim), cfg.compute_dtype),
                      jnp.zeros((b, max_len, cfg.num_query_groups,
                                 cfg.head_dim), cfg.compute_dtype))
                (h, state), _ = layer_forward(
                    layer_p, h, cfg, cos, sin, kv_cache=kv, cache_index=0)
            states.append(state)
    h = rms_norm(h, p["final_ln_scale"], cfg.layernorm_epsilon)
    dt = cfg.compute_dtype
    logits = h.astype(dt) @ p["embedding"]["word"].T.astype(dt)
    return logits.astype(jnp.float32), states


def mamba_decode_step(p, states, token, cfg: TransformerConfig,
                      mcfg: MambaConfig, cache_index=None):
    """token [B] + per-layer states → (logits [B,V], new states).

    ``cache_index`` (scalar int32, the absolute position of ``token``) is
    required for hybrid stacks — attention layers write their KV cache and
    select rope angles at that position; pure-M stacks ignore it."""
    pattern = mcfg.hybrid_pattern or "M" * cfg.num_layers
    x = jnp.take(p["embedding"]["word"], token, axis=0).astype(
        cfg.compute_dtype)

    if set(pattern) == {"M"}:
        def body(carry, inp):
            x = carry
            layer_p, (conv_buf, ssm_h) = inp
            y = rms_norm(x, layer_p["ln_scale"], cfg.layernorm_epsilon)
            out, new_state = mamba_mixer_step(layer_p["mixer"], conv_buf,
                                              ssm_h, y, cfg, mcfg)
            return x + out.astype(x.dtype), new_state

        x, new_states = jax.lax.scan(body, x, (p["layers"], states))
    else:
        from megatronapp_tpu.models.gpt import gpt_rope_tables
        if cache_index is None:
            raise ValueError("hybrid mamba decode requires cache_index")
        max_len = next(s[0].shape[1] for kind, s in zip(pattern, states)
                       if kind == "*")
        cos_full, sin_full = gpt_rope_tables(cfg, max_len)
        cos = None if cos_full is None else jax.lax.dynamic_slice_in_dim(
            cos_full, cache_index, 1)
        sin = None if sin_full is None else jax.lax.dynamic_slice_in_dim(
            sin_full, cache_index, 1)
        h = x[:, None]  # [B,1,H]
        new_states = []
        for kind, layer_p, state in zip(pattern, p["layers"], states):
            if kind == "M":
                y = rms_norm(h[:, 0], layer_p["ln_scale"],
                             cfg.layernorm_epsilon)
                out, new_state = mamba_mixer_step(
                    layer_p["mixer"], state[0], state[1], y, cfg, mcfg)
                h = h + out[:, None].astype(h.dtype)
            else:
                (h, new_state), _ = layer_forward(
                    layer_p, h, cfg, cos, sin, kv_cache=state,
                    cache_index=cache_index)
            new_states.append(new_state)
        x = h[:, 0]
    x = rms_norm(x, p["final_ln_scale"], cfg.layernorm_epsilon)
    dt = cfg.compute_dtype
    logits = x.astype(dt) @ p["embedding"]["word"].T.astype(dt)
    return logits.astype(jnp.float32), new_states


def mamba_generate(p, prompt_tokens, cfg: TransformerConfig,
                   mcfg: MambaConfig, *, max_new_tokens: int = 32,
                   greedy: bool = True, temperature: float = 1.0,
                   seed: int = 0, token_callback=None):
    """Convenience one-shot generation: parallel prefill then jitted
    recurrent decode (state donated). prompt_tokens [B,S] →
    [B, S+max_new]. For serving (sampling params, eod stop, compile
    caching) use inference.engine.MambaInferenceEngine."""
    import numpy as np

    from megatronapp_tpu.inference.engine import mask_padded_vocab

    prompt_len = prompt_tokens.shape[1]
    max_len = prompt_len + max_new_tokens
    prefill = jax.jit(
        lambda p, t: mamba_prefill(p, t, cfg, mcfg, max_len=max_len))
    step = jax.jit(
        lambda p, s, t, i: mamba_decode_step(p, s, t, cfg, mcfg,
                                             cache_index=i),
        donate_argnums=(1,))

    logits, states = prefill(p, prompt_tokens)
    out = [np.asarray(prompt_tokens)]
    rng = jax.random.PRNGKey(seed)
    next_logits = mask_padded_vocab(logits[:, -1], cfg)
    for i in range(max_new_tokens):
        if greedy:
            token = jnp.argmax(next_logits, axis=-1)
        else:
            rng, k = jax.random.split(rng)
            token = jax.random.categorical(
                k, next_logits / max(temperature, 1e-6), axis=-1)
        token = token.astype(prompt_tokens.dtype)
        out.append(np.asarray(token)[:, None])
        if token_callback is not None:
            token_callback(np.asarray(token))
        next_logits, states = step(p, states, token,
                                   jnp.int32(prompt_len + i))
        next_logits = mask_padded_vocab(next_logits, cfg)
    return np.concatenate(out, axis=1)
