"""T5 encoder-decoder model.

Parity with /root/reference/megatron/core/models/T5/t5_model.py (T5Model:
shared token embedding, bidirectional encoder block, decoder block with
causal self-attention + cross-attention over encoder output, tied LM head)
and pretrain_t5.py's span-corruption loss plumbing.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    AttnMaskType, NormKind, PositionEmbeddingKind, TransformerConfig,
)
from megatronapp_tpu.ops.attention import dot_product_attention
from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
from megatronapp_tpu.ops.normalization import apply_norm
from megatronapp_tpu.transformer.attention import (
    attention_forward, init_attention_params,
)
from megatronapp_tpu.transformer.block import (
    block_forward, init_block_params, init_layer_params, _remat_wrap,
)
from megatronapp_tpu.transformer.mlp import init_mlp_params, mlp_forward
from megatronapp_tpu.parallel.sharding import is_logical_axes


def t5_config(**kw) -> TransformerConfig:
    defaults = dict(position_embedding=PositionEmbeddingKind.learned_absolute,
                    add_qkv_bias=False, add_bias_linear=False,
                    normalization=NormKind.rmsnorm)
    defaults.update(kw)
    return TransformerConfig(**defaults)


def _init_cross_attention_params(rng, cfg: TransformerConfig, out_std):
    h, d = cfg.hidden_size, cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.num_query_groups
    k1, k2, k3 = jax.random.split(rng, 3)
    std = cfg.init_method_std
    p = {
        "q_kernel": jax.random.normal(k1, (h, nq * d), cfg.params_dtype) * std,
        "kv_kernel": jax.random.normal(k2, (h, 2 * nkv * d),
                                       cfg.params_dtype) * std,
        "out_kernel": jax.random.normal(k3, (nq * d, h),
                                        cfg.params_dtype) * out_std,
    }
    ax = {"q_kernel": ("embed", "qkv"), "kv_kernel": ("embed", "qkv"),
          "out_kernel": ("qkv", "embed")}
    return p, ax


def _cross_attention_forward(p, x, enc_out, cfg: TransformerConfig,
                             enc_mask: Optional[jnp.ndarray] = None):
    """x [B,Sd,H] attends over enc_out [B,Se,H]."""
    b, sd, _ = x.shape
    se = enc_out.shape[1]
    d = cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.num_query_groups
    dt = cfg.compute_dtype
    q = (x.astype(dt) @ p["q_kernel"].astype(dt)).reshape(b, sd, nq, d)
    kv = (enc_out.astype(dt) @ p["kv_kernel"].astype(dt))
    k, v = jnp.split(kv.reshape(b, se, 2 * nkv, d), 2, axis=2)
    mask = None
    if enc_mask is not None:
        mask = enc_mask[:, None, None, :].astype(bool)
    ctx_ = dot_product_attention(q, k, v,
                                 mask_type=AttnMaskType.bidirectional,
                                 attention_mask=mask)
    return ctx_.reshape(b, sd, nq * d) @ p["out_kernel"].astype(dt)


def init_t5_decoder_layer_params(rng, cfg: TransformerConfig):
    out_std = cfg.init_method_std / jnp.sqrt(2.0 * cfg.num_layers)
    k_self, k_cross, k_mlp = jax.random.split(rng, 3)
    self_p, self_ax = init_attention_params(k_self, cfg, out_std)
    cross_p, cross_ax = _init_cross_attention_params(k_cross, cfg, out_std)
    mlp_p, mlp_ax = init_mlp_params(k_mlp, cfg, out_std)
    h = cfg.hidden_size
    p = {"ln1_scale": jnp.ones((h,), cfg.params_dtype),
         "ln_cross_scale": jnp.ones((h,), cfg.params_dtype),
         "ln2_scale": jnp.ones((h,), cfg.params_dtype),
         "self_attention": self_p, "cross_attention": cross_p, "mlp": mlp_p}
    ax = {"ln1_scale": ("embed",), "ln_cross_scale": ("embed",),
          "ln2_scale": ("embed",),
          "self_attention": self_ax, "cross_attention": cross_ax,
          "mlp": mlp_ax}
    if cfg.normalization == NormKind.layernorm:
        for name in ("ln1", "ln_cross", "ln2"):
            p[f"{name}_bias"] = jnp.zeros((h,), cfg.params_dtype)
            ax[f"{name}_bias"] = ("embed",)
    return p, ax


def t5_decoder_layer_forward(p, x, enc_out, cfg: TransformerConfig,
                             enc_mask=None, ctx=None):
    residual = x
    h = apply_norm(cfg.normalization, x, p["ln1_scale"], p.get("ln1_bias"),
                   cfg.layernorm_epsilon)
    # Causal self-attention over the decoder stream.
    attn_out, _ = attention_forward(p["self_attention"], h, cfg,
                                    None, None, None, ctx=ctx)
    x = residual + attn_out.astype(residual.dtype)

    residual = x
    h = apply_norm(cfg.normalization, x, p["ln_cross_scale"],
                   p.get("ln_cross_bias"), cfg.layernorm_epsilon)
    cross_out = _cross_attention_forward(p["cross_attention"], h, enc_out,
                                         cfg, enc_mask)
    x = residual + cross_out.astype(residual.dtype)

    residual = x
    h = apply_norm(cfg.normalization, x, p["ln2_scale"], p.get("ln2_bias"),
                   cfg.layernorm_epsilon)
    x = residual + mlp_forward(p["mlp"], h, cfg).astype(residual.dtype)
    return x


def init_t5_params(rng, enc_cfg: TransformerConfig,
                   dec_cfg: Optional[TransformerConfig] = None,
                   pp: int = 1, vpp: int = 1):
    """Shared embedding + encoder block + stacked decoder layers + final
    norms. dec_cfg defaults to enc_cfg (with causal self-attention).

    pp > 1: BOTH stacks reshape to the pipeline layout [pp, vpp, Lc, ...]
    — the TPU-first answer to the reference's encoder/decoder split rank
    (parallel_state.py:62-64 --pipeline-model-parallel-split-rank): instead
    of dedicating disjoint rank ranges to encoder vs decoder, each phase
    pipelines over ALL pp stages in turn (t5_pipeline_loss), so no stage
    idles while the other phase runs."""
    dec_cfg = dec_cfg or dataclasses.replace(
        enc_cfg, attn_mask_type=AttnMaskType.causal)
    k_emb, k_pos, k_enc, k_dec = jax.random.split(rng, 4)
    std = enc_cfg.init_method_std
    h = enc_cfg.hidden_size
    p = {
        "embedding": {
            "word": jax.random.normal(
                k_emb, (enc_cfg.vocab_size, h), enc_cfg.params_dtype) * std,
            "pos": jax.random.normal(
                k_pos, (enc_cfg.max_position_embeddings, h),
                enc_cfg.params_dtype) * std,
        },
        "enc_final_ln_scale": jnp.ones((h,), enc_cfg.params_dtype),
        "dec_final_ln_scale": jnp.ones((h,), enc_cfg.params_dtype),
    }
    ax = {
        "embedding": {"word": ("vocab", "embed"), "pos": ("pos", "embed")},
        "enc_final_ln_scale": ("embed",),
        "dec_final_ln_scale": ("embed",),
    }
    p["encoder"], ax["encoder"] = init_block_params(k_enc, enc_cfg)
    keys = jax.random.split(k_dec, dec_cfg.num_layers)
    per_layer = [init_t5_decoder_layer_params(k, dec_cfg) for k in keys]
    p["decoder"] = jax.tree.map(lambda *xs: jnp.stack(xs),
                                *[q for q, _ in per_layer])
    ax["decoder"] = jax.tree.map(lambda axes: ("layers",) + axes,
                                 per_layer[0][1], is_leaf=is_logical_axes)
    if pp > 1:
        from megatronapp_tpu.parallel.pipeline import (
            reshape_params_for_pipeline,
        )
        for stack, cfg_ in (("encoder", enc_cfg), ("decoder", dec_cfg)):
            if cfg_.num_layers % (pp * vpp) != 0:
                raise ValueError(
                    f"{stack} num_layers={cfg_.num_layers} not divisible "
                    f"by pp*vpp={pp * vpp}")
            p[stack] = reshape_params_for_pipeline(p[stack], pp, vpp)
            ax[stack] = jax.tree.map(
                lambda axes: ("pp_stage", "vpp_chunk", "stage_layers")
                + axes[1:],
                ax[stack], is_leaf=is_logical_axes)
    return p, ax


def _embed(p, tokens, cfg):
    s = tokens.shape[1]
    h = jnp.take(p["embedding"]["word"], tokens, axis=0)
    h = h + jnp.take(p["embedding"]["pos"], jnp.arange(s), axis=0)
    return h.astype(cfg.compute_dtype)


def t5_forward(p, enc_tokens, dec_tokens, enc_cfg: TransformerConfig,
               dec_cfg: Optional[TransformerConfig] = None,
               enc_mask: Optional[jnp.ndarray] = None, ctx=None):
    """→ lm_logits [B, Sd, V] fp32."""
    dec_cfg = dec_cfg or dataclasses.replace(
        enc_cfg, attn_mask_type=AttnMaskType.causal)

    # Encoder (bidirectional; padding mask optional).
    h_enc = _embed(p, enc_tokens, enc_cfg)
    enc_run_cfg = dataclasses.replace(
        enc_cfg, attn_mask_type=AttnMaskType.bidirectional)
    attn_mask = (enc_mask[:, None, None, :].astype(bool)
                 if enc_mask is not None else None)
    enc_out, _ = block_forward(p["encoder"], h_enc, enc_run_cfg, None, None,
                               attn_mask, ctx=ctx)
    enc_out = apply_norm(enc_cfg.normalization, enc_out,
                         p["enc_final_ln_scale"], None,
                         enc_cfg.layernorm_epsilon)

    # Decoder scan over stacked layers.
    h_dec = _embed(p, dec_tokens, dec_cfg)

    def body(carry, layer_p):
        hh = t5_decoder_layer_forward(layer_p, carry, enc_out, dec_cfg,
                                      enc_mask, ctx=ctx)
        return hh, None

    body = _remat_wrap(body, dec_cfg.remat_policy)
    h_dec, _ = jax.lax.scan(body, h_dec, p["decoder"])
    h_dec = apply_norm(dec_cfg.normalization, h_dec,
                       p["dec_final_ln_scale"], None,
                       dec_cfg.layernorm_epsilon)
    dt = dec_cfg.compute_dtype
    logits = h_dec.astype(dt) @ p["embedding"]["word"].T.astype(dt)
    return logits.astype(jnp.float32)


def t5_loss(p, batch, enc_cfg: TransformerConfig, ctx=None):
    """pretrain_t5.py loss parity: CE over decoder targets with loss mask."""
    logits = t5_forward(p, batch["text_enc"], batch["text_dec"], enc_cfg,
                        enc_mask=batch.get("enc_mask"), ctx=ctx)
    loss, _ = cross_entropy_loss(logits, batch["labels"],
                                 batch.get("loss_mask"))
    return loss, {"lm_loss": loss}


def t5_pipeline_loss(p, batch_mb, enc_cfg: TransformerConfig, ctx,
                     vpp: int = 1, order_policy: str = "dfc",
                     schedule: str = "1f1b"):
    """Pipelined T5 loss over microbatched batches ({field: [M, mb, S]}).

    TPU-first redesign of the reference encoder/decoder PP split
    (--pipeline-model-parallel-split-rank, parallel_state.py:62-64): the
    reference dedicates rank ranges to encoder vs decoder because torch
    modules live on fixed GPUs; under SPMD both phases pipeline over ALL
    pp stages back to back — encoder chunks first, then decoder chunks
    with the (fp32) encoder memory riding each microbatch as a pipeline
    aux input consumed by every stage's cross-attention.
    """
    from megatronapp_tpu.parallel.pipeline import spmd_pipeline

    if ctx.cp > 1:
        raise NotImplementedError(
            "t5 pipeline with context parallelism needs a cp-aware "
            "cross-attention (encoder memory is consumed whole)")
    dec_cfg = dataclasses.replace(enc_cfg,
                                  attn_mask_type=AttnMaskType.causal)
    enc_run_cfg = dataclasses.replace(
        enc_cfg, attn_mask_type=AttnMaskType.bidirectional)
    enc_tokens = batch_mb["text_enc"]
    dec_tokens = batch_mb["text_dec"]
    m, mb, se = enc_tokens.shape
    sd = dec_tokens.shape[2]

    # --- phase 1: encoder over the pp axis -------------------------------
    h_enc = _embed(p, enc_tokens.reshape(m * mb, se), enc_cfg
                   ).astype(jnp.float32).reshape(m, mb, se, -1)
    enc_mask_mb = batch_mb.get("enc_mask")

    def enc_stage(chunk_params, x, layer_offset, aux_m=None):
        from megatronapp_tpu.transformer.block import block_forward
        attn_mask = None
        if aux_m is not None:
            # Padding mask per microbatch ([mb,Se] → [mb,1,1,Se]), same as
            # the non-pipelined t5_forward encoder.
            attn_mask = aux_m["enc_mask"][:, None, None, :].astype(bool)
        return block_forward(chunk_params, x, enc_run_cfg, None, None,
                             attn_mask, layer_offset=layer_offset, ctx=ctx)

    enc_out_mb, _ = spmd_pipeline(
        enc_stage, p["encoder"], h_enc, ctx, num_microbatches=m, vpp=vpp,
        compute_dtype=enc_cfg.compute_dtype, order_policy=order_policy,
        schedule=schedule,
        aux_mb=({"enc_mask": enc_mask_mb}
                if enc_mask_mb is not None else None))
    enc_out_mb = apply_norm(enc_cfg.normalization, enc_out_mb,
                            p["enc_final_ln_scale"], None,
                            enc_cfg.layernorm_epsilon).astype(jnp.float32)

    # --- phase 2: decoder over the pp axis, enc memory as aux ------------
    h_dec = _embed(p, dec_tokens.reshape(m * mb, sd), dec_cfg
                   ).astype(jnp.float32).reshape(m, mb, sd, -1)
    aux = {"enc_out": enc_out_mb}
    if "enc_mask" in batch_mb:
        aux["enc_mask"] = batch_mb["enc_mask"]

    def dec_stage(chunk_params, x, layer_offset, aux_m):
        enc_out = aux_m["enc_out"].astype(dec_cfg.compute_dtype)
        enc_mask = aux_m.get("enc_mask")

        def body(carry, layer_p):
            return t5_decoder_layer_forward(layer_p, carry, enc_out,
                                            dec_cfg, enc_mask,
                                            ctx=ctx), None

        body = _remat_wrap(body, dec_cfg.remat_policy)
        x, _ = jax.lax.scan(body, x, chunk_params)
        return x, jnp.zeros((), jnp.float32)

    out_mb, _ = spmd_pipeline(
        dec_stage, p["decoder"], h_dec, ctx, num_microbatches=m, vpp=vpp,
        compute_dtype=dec_cfg.compute_dtype, order_policy=order_policy,
        schedule=schedule, aux_mb=aux)

    out_mb = apply_norm(dec_cfg.normalization, out_mb,
                        p["dec_final_ln_scale"], None,
                        dec_cfg.layernorm_epsilon)
    dt = dec_cfg.compute_dtype
    logits = (out_mb.astype(dt)
              @ p["embedding"]["word"].T.astype(dt)).astype(jnp.float32)
    loss, _ = cross_entropy_loss(logits, batch_mb["labels"],
                                 batch_mb.get("loss_mask"))
    return loss, {"lm_loss": loss}


def mock_t5_batch(seed, batch_size, enc_len, dec_len, vocab_size):
    """Synthetic span-corruption-shaped batch (pretrain_t5.py mock
    stream; mirrors models/bert.py mock_bert_batch placement)."""
    import numpy as np
    r = np.random.default_rng(seed)
    enc = r.integers(3, vocab_size, size=(batch_size, enc_len))
    dec = r.integers(3, vocab_size, size=(batch_size, dec_len))
    labels = np.concatenate([dec[:, 1:], dec[:, :1]], axis=1)
    return {
        "text_enc": enc.astype(np.int32),
        "text_dec": dec.astype(np.int32),
        "labels": labels.astype(np.int32),
        "loss_mask": np.ones((batch_size, dec_len), np.float32),
        "enc_mask": np.ones((batch_size, enc_len), np.float32),
    }
