"""GPT model (decoder-only LM).

Parity with /root/reference/megatron/core/models/gpt/gpt_model.py:32
(GPTModel: LanguageModelEmbedding → TransformerBlock → output layer with
optionally tied word embeddings, vocab-parallel logits + CE). TPU-first:
functional params pytree, scan-over-layers block, logical-axis shardings.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    NormKind, PositionEmbeddingKind, TransformerConfig,
)
from megatronapp_tpu.ops import rotary
from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
from megatronapp_tpu.ops.normalization import apply_norm
from megatronapp_tpu.transformer.block import block_forward, init_block_params
from megatronapp_tpu.scope.hooks import scope_capture


def init_gpt_params(rng, cfg: TransformerConfig, pp: int = 1, vpp: int = 1):
    """Returns (params, logical_axes) pytrees.

    pp > 1: block params are stored in the pipeline layout
    [pp, vpp, L/(pp*vpp), ...] (sharded over the pp mesh axis) with the
    interleaved chunk→stage assignment — see parallel/pipeline.py.
    """
    k_emb, k_pos, k_block, k_out = jax.random.split(rng, 4)
    std = cfg.init_method_std
    p = {
        "embedding": {
            "word": jax.random.normal(
                k_emb, (cfg.vocab_size, cfg.hidden_size), cfg.params_dtype) * std,
        },
        "final_ln_scale": jnp.ones((cfg.hidden_size,), cfg.params_dtype),
    }
    ax = {
        "embedding": {"word": ("vocab", "embed")},
        "final_ln_scale": ("embed",),
    }
    if cfg.position_embedding == PositionEmbeddingKind.learned_absolute:
        p["embedding"]["pos"] = jax.random.normal(
            k_pos, (cfg.max_position_embeddings, cfg.hidden_size),
            cfg.params_dtype) * std
        ax["embedding"]["pos"] = ("pos", "embed")
    if cfg.normalization == NormKind.layernorm:
        p["final_ln_bias"] = jnp.zeros((cfg.hidden_size,), cfg.params_dtype)
        ax["final_ln_bias"] = ("embed",)
    p["block"], ax["block"] = init_block_params(k_block, cfg)
    if pp > 1:
        from megatronapp_tpu.parallel.pipeline import (
            reshape_params_for_pipeline,
        )
        # moe_layer_freq > 1 pipelines in GROUP units: the group-scan
        # layout {moe: [G,...], dense: [G, freq-1, ...]} reshapes its
        # leading G axis exactly like the uniform L axis (each pipeline
        # "layer" is one {1 moe + freq-1 dense} group).
        units = (cfg.num_layers // cfg.moe_layer_freq
                 if cfg.is_moe and cfg.moe_layer_freq > 1
                 else cfg.num_layers)
        if units % (pp * vpp) != 0:
            raise ValueError(
                f"{units} pipeline units (layers/groups) not divisible by "
                f"pp*vpp={pp * vpp}")
        p["block"] = reshape_params_for_pipeline(p["block"], pp, vpp)
        from megatronapp_tpu.parallel.sharding import is_logical_axes
        ax["block"] = jax.tree.map(
            lambda axes: ("pp_stage", "vpp_chunk", "stage_layers") + axes[1:],
            ax["block"], is_leaf=is_logical_axes)
    if cfg.untie_embeddings_and_output_weights:
        p["output"] = jax.random.normal(
            k_out, (cfg.hidden_size, cfg.vocab_size), cfg.params_dtype) * std
        ax["output"] = ("embed", "vocab")
    if cfg.mtp_num_layers:
        # MTP depth modules are NOT part of the pipelined stack: like the
        # embedding/head they run compiler-sharded on the last-stage
        # output (the reference places MTP on the last pp stage —
        # multi_token_prediction.py; here "outside the pipeline" is the
        # same placement expressed SPMD-style).
        from megatronapp_tpu.transformer.mtp import init_mtp_params
        p["mtp"], ax["mtp"] = init_mtp_params(k_out, cfg)
    return p, ax


def gpt_embed(p, tokens: jnp.ndarray, cfg: TransformerConfig,
              position_offset: int = 0, dtype=None,
              position_ids: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """tokens [B,S] → embeddings [B,S,H] (vocab axis tp-sharded: XLA handles
    the sharded gather; reference VocabParallelEmbedding layers.py:172).

    position_ids: optional explicit positions ([B,S] or [1,S]) — packed
    sequences reset positions per segment for learned-absolute embeddings
    too (reference resets the position_ids fed to the embedding)."""
    h = jnp.take(p["embedding"]["word"], tokens, axis=0)
    if "pos" in p["embedding"]:
        if position_ids is None:
            position_ids = jnp.arange(tokens.shape[1])[None, :]
        pos = position_ids + position_offset
        h = h + jnp.take(p["embedding"]["pos"], pos, axis=0)
    return h.astype(dtype or cfg.compute_dtype)


def rope_params(cfg: TransformerConfig):
    """(inv_freq, mscale) for the configured rope variant, or (None, 1.0).

    Single source of truth for the variant selection so the per-token
    packed-sequence tables inherit YaRN's NTK-by-parts interpolation and
    mscale exactly like the standard tables."""
    # MLA applies rope only on the decoupled position heads.
    rope_dim = (cfg.qk_pos_emb_head_dim if cfg.multi_latent_attention
                else cfg.head_dim)
    if cfg.position_embedding == PositionEmbeddingKind.rope:
        return rotary.rope_frequencies(rope_dim, cfg.rotary_base,
                                       cfg.rotary_percent), 1.0
    if cfg.position_embedding == PositionEmbeddingKind.yarn:
        inv_freq = rotary.yarn_frequencies(
            rope_dim, cfg.rotary_base,
            scaling_factor=cfg.rope_scaling_factor,
            original_max_position=cfg.yarn_original_max_position,
            beta_fast=cfg.yarn_beta_fast, beta_slow=cfg.yarn_beta_slow,
            rotary_percent=cfg.rotary_percent)
        m = rotary.yarn_mscale(cfg.rope_scaling_factor, cfg.yarn_mscale_coeff)
        return inv_freq, m
    return None, 1.0


def gpt_rope_tables(cfg: TransformerConfig, seq_len: int,
                    position_offset: int = 0,
                    positions: Optional[jnp.ndarray] = None):
    """Rope cos/sin tables for arange positions, or explicit per-token
    `positions` (packed sequences)."""
    inv_freq, m = rope_params(cfg)
    if inv_freq is None:
        return None, None
    if positions is None:
        positions = jnp.arange(seq_len)
    cos, sin = rotary.rope_cos_sin(positions + position_offset, inv_freq)
    if m != 1.0:
        cos, sin = cos * m, sin * m
    return cos, sin


def packed_attention_mask(segment_ids: jnp.ndarray) -> jnp.ndarray:
    """Block-diagonal mask for packed sequences: token i may attend j only
    within the same segment (causality comes from the standard causal mask
    on top). Parity with the reference packed/THD formats
    (core/packed_seq_params.py + --reset-attention-mask /
    --reset-position-ids semantics; positions reset per segment in
    packed_position_ids). Utility for mask-based consumers; the model
    path no longer densifies — the segment-aware flash kernel masks
    in-block and the cp impls thread segments through their collectives
    (transformer/attention.py).

    segment_ids [B,S] → bool mask [B,1,S,S] (True = may attend)."""
    same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
    return same


def packed_position_ids(segment_ids: jnp.ndarray) -> jnp.ndarray:
    """Per-segment position ids: positions restart at 0 at each segment
    boundary (reference --reset-position-ids). [B,S] → [B,S] int32."""
    b, s = segment_ids.shape
    idx = jnp.arange(s)[None, :]
    is_start = jnp.concatenate(
        [jnp.ones((b, 1), bool),
         segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1)
    seg_start = jax.lax.cummax(jnp.where(is_start, idx, 0), axis=1)
    return (idx - seg_start).astype(jnp.int32)


def gpt_forward(p, tokens: jnp.ndarray, cfg: TransformerConfig,
                attention_mask: Optional[jnp.ndarray] = None,
                position_offset: int = 0, ctx=None,
                segment_ids: Optional[jnp.ndarray] = None,
                zigzag_keep: bool = False, return_hidden: bool = False,
                fp8=None):
    """tokens [B,S] → (logits [B,S,V] fp32, moe_aux_loss) —
    (+ pre-head hidden states and rope tables when return_hidden, for the
    MTP depth modules).

    segment_ids: optional [B,S] packing map — attention is restricted to
    within-segment (packed sequences).

    Under causal ring context parallelism the sequence is transparently
    permuted into the load-balanced zigzag layout (ops/context_parallel.py
    zigzag_indices) and logits are unpermuted on return; `zigzag_keep=True`
    skips the unpermute (gpt_loss permutes the targets instead — cheaper
    than moving [B,S,V] logits across cp shards)."""
    from megatronapp_tpu.ops.context_parallel import (
        zigzag_active, zigzag_indices, zigzag_inverse_indices,
    )

    b, s = tokens.shape
    packed_pos = None
    if segment_ids is not None:
        # Positions restart per segment (reference --reset-position-ids) —
        # for BOTH the learned-absolute embedding and rope tables. The
        # segment mask itself is applied inside attention (flash in-block
        # masking / cp collectives), NOT as a dense [B,S,S] mask.
        packed_pos = packed_position_ids(segment_ids)
    positions = packed_pos
    zz = (zigzag_active(cfg, ctx) and segment_ids is None
          and attention_mask is None)
    if zz:
        idx = jnp.asarray(zigzag_indices(s, ctx.cp))
        tokens = jnp.take(tokens, idx, axis=1)
        positions = idx[None, :]
    h = gpt_embed(p, tokens, cfg, position_offset, position_ids=positions)
    cos, sin = gpt_rope_tables(cfg, s, position_offset,
                               positions=(positions[0] if zz else positions))
    h, aux = block_forward(p["block"], h, cfg, cos, sin, attention_mask,
                           ctx=ctx, zigzag=zz, segment_ids=segment_ids,
                           fp8=None if fp8 is None else fp8["block"])
    logits = gpt_head(p, h, cfg)
    if zz and not zigzag_keep:
        logits = jnp.take(logits, jnp.asarray(zigzag_inverse_indices(
            s, ctx.cp)), axis=1)
    if return_hidden:
        return logits, aux, h, (cos, sin)
    return logits, aux


def gpt_loss(p, tokens: jnp.ndarray, targets: jnp.ndarray,
             loss_mask: Optional[jnp.ndarray], cfg: TransformerConfig,
             ctx=None, segment_ids: Optional[jnp.ndarray] = None,
             fp8=None):
    """Training loss (CE + MoE aux). Mirrors pretrain_gpt.py loss_func
    (/root/reference/pretrain_gpt.py:159)."""
    from megatronapp_tpu.ops.context_parallel import (
        zigzag_active, zigzag_indices,
    )
    mtp_metrics = {}
    if cfg.mtp_num_layers:
        if segment_ids is not None:
            raise NotImplementedError(
                "multi token prediction + sequence packing is not "
                "supported (reference multi_token_prediction.py assert)")
        from megatronapp_tpu.transformer.mtp import mtp_loss as _mtp_loss
        logits, aux, hid, (cos, sin) = gpt_forward(
            p, tokens, cfg, ctx=ctx, zigzag_keep=True, return_hidden=True)
        if zigzag_active(cfg, ctx):
            # The depth modules' future-token rolls need contiguous
            # order: un-permute the main-stack output and run MTP with
            # plain rope tables — its attention then takes the contiguous
            # (non-zigzag) ring, which is correct under cp.
            from megatronapp_tpu.ops.context_parallel import (
                zigzag_inverse_indices,
            )
            inv = jnp.asarray(zigzag_inverse_indices(tokens.shape[1],
                                                     ctx.cp))
            hid = jnp.take(hid, inv, axis=1)
            cos, sin = gpt_rope_tables(cfg, tokens.shape[1])
        mtp_scaled, mtp_mean, mtp_layer_aux = _mtp_loss(
            p["mtp"], hid, lambda t: gpt_embed(p, t, cfg),
            lambda hh: gpt_head(p, hh, cfg), tokens, targets, loss_mask,
            cfg, cos, sin, ctx=ctx)
        # Keep 'moe_aux_loss' pure: the depth layers' router losses join
        # it (unscaled, like main-stack layers); the scaled MTP CE is
        # carried separately into the total.
        aux = aux + mtp_layer_aux
        mtp_metrics["mtp_loss"] = mtp_mean
        mtp_metrics["_mtp_scaled"] = mtp_scaled
    else:
        logits, aux = gpt_forward(p, tokens, cfg, ctx=ctx,
                                  segment_ids=segment_ids,
                                  zigzag_keep=True, fp8=fp8)
    if zigzag_active(cfg, ctx) and segment_ids is None:
        # Logits are in zigzag order — permute targets/mask to match (the
        # masked-mean CE is permutation-invariant).
        idx = jnp.asarray(zigzag_indices(tokens.shape[1], ctx.cp))
        targets = jnp.take(targets, idx, axis=1)
        if loss_mask is not None:
            loss_mask = jnp.take(loss_mask, idx, axis=1)
    loss, _ = cross_entropy_loss(logits, targets, loss_mask)
    mtp_scaled_term = mtp_metrics.pop("_mtp_scaled",
                                      jnp.zeros((), jnp.float32))
    return loss + aux + mtp_scaled_term, {"lm_loss": loss,
                                          "moe_aux_loss": aux,
                                          **mtp_metrics}


def gpt_head(p, h: jnp.ndarray, cfg: TransformerConfig) -> jnp.ndarray:
    """Final norm + vocab projection. h [..., S, H] → logits fp32."""
    h = apply_norm(cfg.normalization, h, p["final_ln_scale"],
                   p.get("final_ln_bias"), cfg.layernorm_epsilon)
    out_kernel = (p["output"] if "output" in p
                  else p["embedding"]["word"].T)
    logits = h.astype(cfg.compute_dtype) @ out_kernel.astype(cfg.compute_dtype)
    logits = scope_capture("result", logits)
    return logits.astype(jnp.float32)


def gpt_pipeline_loss(p, tokens_mb, targets_mb, loss_mask_mb,
                      cfg: TransformerConfig, ctx, vpp: int = 1,
                      order_policy: str = "dfc", segment_ids_mb=None,
                      schedule: str = "1f1b"):
    """Pipelined training loss over microbatched inputs [M, mb, S].

    Embedding and LM head run outside the pipeline body (compiler-sharded
    over dp/tp); the layer stack runs inside spmd_pipeline over the pp axis.
    The reference runs its schedules imperatively per rank
    (schedules.py:1918 1F1B); here the schedule is an instruction program
    executed by the jitted region (parallel/schedule.py) — `schedule`
    picks 1f1b/vpp or the zero-bubble B/W split (--pp-schedule).

    segment_ids_mb: optional [M, mb, S] packed map — segments and the
    per-token rope tables ride the pipeline as per-microbatch aux inputs
    (spmd_pipeline aux_mb).
    """
    from megatronapp_tpu.parallel.pipeline import spmd_pipeline

    from megatronapp_tpu.ops.context_parallel import (
        zigzag_active, zigzag_indices,
    )

    m, mb, s = tokens_mb.shape
    if segment_ids_mb is not None:
        if schedule == "zero-bubble":
            raise NotImplementedError(
                "--pp-schedule zero-bubble does not compose with packed "
                "sequences (per-microbatch aux inputs) yet — run the "
                "1f1b schedule there")
        return _gpt_pipeline_loss_packed(
            p, tokens_mb, targets_mb, loss_mask_mb, segment_ids_mb, cfg,
            ctx, vpp, order_policy)
    # tp-sharded stage body (parallel/overlap.py tp_stage_eligible —
    # decided BEFORE the zigzag layout: when both apply, the tp FLOPs
    # cut takes the contiguous cp ring over the zigzag load balance).
    from megatronapp_tpu.parallel.overlap import tp_stage_ineligible_reason
    _tp_reason = tp_stage_ineligible_reason(cfg, ctx, s)
    positions = None
    if zigzag_active(cfg, ctx) and _tp_reason is None:
        # pp x cp x tp composition (ISSUE 15): the seq-over-(cp, tp)
        # sharded stage body runs the CONTIGUOUS cp ring — the zigzag
        # permutation does not compose with the tp seq-sharding. The
        # tp-side FLOPs cut (tp x) dominates the zigzag load-balance
        # win; --no-tp-sharded-stage restores the zigzag layout.
        import logging
        logging.getLogger(__name__).info(
            "pp x cp x tp composition: tp-sharded stage bodies take the "
            "contiguous cp ring (zigzag layout does not compose with "
            "seq-over-tp sharding; --no-tp-sharded-stage restores "
            "zigzag)")
    elif zigzag_active(cfg, ctx):
        # Zigzag cp layout (see gpt_forward): permute the sequence so each
        # cp rank's contiguous block holds chunks (i, 2cp-1-i); rope tables
        # follow the permuted positions, and the in-pipeline cp-rank slicing
        # of cos/sin then picks each rank's zigzag positions. Targets are
        # permuted identically below, so the loss is unchanged.
        idx = jnp.asarray(zigzag_indices(s, ctx.cp))
        # jnp.take along the cp-SHARDED seq axis of the dp-sharded batch
        # arrays makes this build's SPMD partitioner emit an invalid
        # dynamic-slice (hlo verifier: "Slice dim size > dynamic slice
        # dimension" when mb is dp-sharded and seq cp-sharded). The
        # arrays are tiny ([M, mb, S] ints/mask), so replicate them for
        # the permutation — the embed/pipeline constraints re-shard
        # immediately downstream.
        rep = jax.sharding.NamedSharding(ctx.mesh,
                                         jax.sharding.PartitionSpec())
        tokens_mb, targets_mb, loss_mask_mb = (
            jnp.take(jax.lax.with_sharding_constraint(x, rep), idx, axis=2)
            for x in (tokens_mb, targets_mb, loss_mask_mb))
        positions = idx
    # fp32 across the shard_map boundary (spmd_pipeline casts to the compute
    # dtype at microbatch injection — see pipeline.py body notes).
    h = gpt_embed(p, tokens_mb.reshape(m * mb, s), cfg, dtype=jnp.float32,
                  position_ids=None if positions is None
                  else positions[None, :])
    h = h.reshape(m, mb, s, -1)
    cos, sin = gpt_rope_tables(cfg, s, positions=positions)

    # Pipeline offsets count scan units; with the moe group-scan each unit
    # is moe_layer_freq layers (layer ids feed scope captures/disturbance).
    unit_layers = (cfg.moe_layer_freq
                   if cfg.is_moe and cfg.moe_layer_freq > 1 else 1)

    # tp-sharded stage body (parallel/overlap.py tp_stage_eligible): the
    # manual pipeline region shards activations over tp along the seq dim
    # (jointly with cp under the pp x cp x tp composition) and the stage
    # body runs the ring-overlapped projections — tp× fewer stage FLOPs
    # instead of the tp-replicated redundant compute.
    tp_shard = positions is None and _tp_reason is None
    if (not tp_shard and ctx is not None and ctx.tp > 1 and ctx.pp > 1):
        # Trace-time log (fires once per compiled shape) naming the
        # SPECIFIC failed predicate instead of a generic ineligible
        # fallback (ISSUE 11 satellite).
        import logging
        logging.getLogger(__name__).info(
            "pipeline stage body runs tp-REPLICATED: %s",
            _tp_reason if positions is None
            else "inference path (positions given)")

    def stage_fn(chunk_params, x, layer_offset):
        layer_offset = layer_offset * unit_layers
        cos_l, sin_l = cos, sin
        from megatronapp_tpu.config.parallel_config import CP_AXIS
        from megatronapp_tpu.parallel.collectives import current_manual_axes
        if CP_AXIS in current_manual_axes() and cos is not None:
            # Inside the pipeline body the cp axis is manual: x carries
            # the local sequence block — slice the rope tables to this
            # cp rank's chunk. Under tp_shard the stream is additionally
            # tp-sharded ([.., S/(cp*tp), H]) and attention re-gathers
            # only the cp-LOCAL chunk through its tp rings, so the right
            # tables cover x.shape[1] * tp rows. With cp == 1 both
            # spellings slice the whole table at offset 0 (no-op). (In
            # the pp==1 fallback stage_fn runs outside any manual region
            # and x carries the full sequence — no slicing.)
            s_loc = x.shape[1] * (ctx.tp if tp_shard else 1)
            start = jax.lax.axis_index(CP_AXIS) * s_loc
            cos_l = jax.lax.dynamic_slice_in_dim(cos, start, s_loc)
            sin_l = jax.lax.dynamic_slice_in_dim(sin, start, s_loc)
        return block_forward(chunk_params, x, cfg, cos_l, sin_l, None,
                             layer_offset=layer_offset, ctx=ctx,
                             zigzag=positions is not None,
                             tp_sharded=tp_shard)

    out_mb, aux = spmd_pipeline(
        stage_fn, p["block"], h, ctx, num_microbatches=m, vpp=vpp,
        compute_dtype=cfg.compute_dtype, order_policy=order_policy,
        tp_shard=tp_shard, schedule=schedule)
    # Aux losses are summed over the M microbatches inside the pipeline;
    # normalize to per-microbatch scale to match the non-pipelined path.
    aux = aux / m

    mtp_metrics = {}
    mtp_scaled_term = jnp.zeros((), jnp.float32)
    if cfg.mtp_num_layers:
        # MTP runs on the last-stage output, outside the pp body, like the
        # head (reference last-stage placement, multi_token_prediction.py).
        if positions is not None:
            raise NotImplementedError(
                "multi token prediction + zigzag context parallelism is "
                "not supported (future-token rolls assume contiguous "
                "sequence order)")
        from megatronapp_tpu.transformer.mtp import mtp_loss as _mtp_loss
        mtp_scaled_term, mtp_mean, mtp_layer_aux = _mtp_loss(
            p["mtp"], out_mb.reshape(m * mb, s, -1),
            lambda t: gpt_embed(p, t, cfg),
            lambda hh: gpt_head(p, hh, cfg),
            tokens_mb.reshape(m * mb, s), targets_mb.reshape(m * mb, s),
            loss_mask_mb.reshape(m * mb, s), cfg, cos, sin, ctx=ctx)
        aux = aux + mtp_layer_aux
        mtp_metrics["mtp_loss"] = mtp_mean

    logits = gpt_head(p, out_mb, cfg)
    loss, _ = cross_entropy_loss(logits, targets_mb, loss_mask_mb)
    return loss + aux + mtp_scaled_term, {"lm_loss": loss,
                                          "moe_aux_loss": aux,
                                          **mtp_metrics}


def _gpt_pipeline_loss_packed(p, tokens_mb, targets_mb, loss_mask_mb,
                              segment_ids_mb, cfg: TransformerConfig, ctx,
                              vpp: int, order_policy: str):
    """Packed-sequence pipelined loss: per-token positions/rope tables and
    segment ids flow as spmd_pipeline aux inputs; attention applies the
    segment mask inside the pipeline body (reference packed/THD under pp)."""
    from megatronapp_tpu.parallel.pipeline import spmd_pipeline

    if cfg.mtp_num_layers:
        raise NotImplementedError(
            "multi token prediction + sequence packing is not "
            "supported (reference multi_token_prediction.py assert)")
    m, mb, s = tokens_mb.shape
    flat_segs = segment_ids_mb.reshape(m * mb, s)
    packed_pos = packed_position_ids(flat_segs)                # [M*mb, S]
    h = gpt_embed(p, tokens_mb.reshape(m * mb, s), cfg, dtype=jnp.float32,
                  position_ids=packed_pos)
    h = h.reshape(m, mb, s, -1)

    inv_freq, msc = rope_params(cfg)
    aux = {"segs": segment_ids_mb}
    if inv_freq is not None:
        cos, sin = rotary.rope_cos_sin(packed_pos.reshape(m, mb, s),
                                       inv_freq)              # [M,mb,S,half]
        if msc != 1.0:
            cos, sin = cos * msc, sin * msc
        aux["cos"], aux["sin"] = cos, sin

    def stage_fn(chunk_params, x, layer_offset, aux_m):
        return block_forward(chunk_params, x, cfg, aux_m.get("cos"),
                             aux_m.get("sin"), None,
                             layer_offset=layer_offset, ctx=ctx,
                             segment_ids=aux_m["segs"])

    out_mb, aux_loss = spmd_pipeline(
        stage_fn, p["block"], h, ctx, num_microbatches=m, vpp=vpp,
        compute_dtype=cfg.compute_dtype, order_policy=order_policy,
        aux_mb=aux)
    aux_loss = aux_loss / m

    logits = gpt_head(p, out_mb, cfg)
    loss, _ = cross_entropy_loss(logits, targets_mb, loss_mask_mb)
    return loss + aux_loss, {"lm_loss": loss, "moe_aux_loss": aux_loss}
