"""GPT model (decoder-only LM).

Parity with /root/reference/megatron/core/models/gpt/gpt_model.py:32
(GPTModel: LanguageModelEmbedding → TransformerBlock → output layer with
optionally tied word embeddings, vocab-parallel logits + CE). TPU-first:
functional params pytree, scan-over-layers block, logical-axis shardings.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    NormKind, PositionEmbeddingKind, TransformerConfig,
)
from megatronapp_tpu.ops import rotary
from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
from megatronapp_tpu.ops.normalization import apply_norm
from megatronapp_tpu.transformer.block import block_forward, init_block_params
from megatronapp_tpu.scope.hooks import scope_capture


def init_gpt_params(rng, cfg: TransformerConfig):
    """Returns (params, logical_axes) pytrees."""
    k_emb, k_pos, k_block, k_out = jax.random.split(rng, 4)
    std = cfg.init_method_std
    p = {
        "embedding": {
            "word": jax.random.normal(
                k_emb, (cfg.vocab_size, cfg.hidden_size), cfg.params_dtype) * std,
        },
        "final_ln_scale": jnp.ones((cfg.hidden_size,), cfg.params_dtype),
    }
    ax = {
        "embedding": {"word": ("vocab", "embed")},
        "final_ln_scale": ("embed",),
    }
    if cfg.position_embedding == PositionEmbeddingKind.learned_absolute:
        p["embedding"]["pos"] = jax.random.normal(
            k_pos, (cfg.max_position_embeddings, cfg.hidden_size),
            cfg.params_dtype) * std
        ax["embedding"]["pos"] = ("pos", "embed")
    if cfg.normalization == NormKind.layernorm:
        p["final_ln_bias"] = jnp.zeros((cfg.hidden_size,), cfg.params_dtype)
        ax["final_ln_bias"] = ("embed",)
    p["block"], ax["block"] = init_block_params(k_block, cfg)
    if cfg.untie_embeddings_and_output_weights:
        p["output"] = jax.random.normal(
            k_out, (cfg.hidden_size, cfg.vocab_size), cfg.params_dtype) * std
        ax["output"] = ("embed", "vocab")
    return p, ax


def gpt_embed(p, tokens: jnp.ndarray, cfg: TransformerConfig,
              position_offset: int = 0) -> jnp.ndarray:
    """tokens [B,S] → embeddings [B,S,H] (vocab axis tp-sharded: XLA handles
    the sharded gather; reference VocabParallelEmbedding layers.py:172)."""
    h = jnp.take(p["embedding"]["word"], tokens, axis=0)
    if "pos" in p["embedding"]:
        s = tokens.shape[1]
        pos = jnp.arange(s) + position_offset
        h = h + jnp.take(p["embedding"]["pos"], pos, axis=0)
    return h.astype(cfg.compute_dtype)


def gpt_rope_tables(cfg: TransformerConfig, seq_len: int,
                    position_offset: int = 0):
    if cfg.position_embedding == PositionEmbeddingKind.rope:
        inv_freq = rotary.rope_frequencies(cfg.head_dim, cfg.rotary_base,
                                           cfg.rotary_percent)
    elif cfg.position_embedding == PositionEmbeddingKind.yarn:
        inv_freq = rotary.yarn_frequencies(
            cfg.head_dim, cfg.rotary_base,
            scaling_factor=cfg.rope_scaling_factor,
            original_max_position=cfg.yarn_original_max_position,
            beta_fast=cfg.yarn_beta_fast, beta_slow=cfg.yarn_beta_slow,
            rotary_percent=cfg.rotary_percent)
    else:
        return None, None
    positions = jnp.arange(seq_len) + position_offset
    cos, sin = rotary.rope_cos_sin(positions, inv_freq)
    if cfg.position_embedding == PositionEmbeddingKind.yarn:
        m = rotary.yarn_mscale(cfg.rope_scaling_factor, cfg.yarn_mscale_coeff)
        cos, sin = cos * m, sin * m
    return cos, sin


def gpt_forward(p, tokens: jnp.ndarray, cfg: TransformerConfig,
                attention_mask: Optional[jnp.ndarray] = None,
                position_offset: int = 0):
    """tokens [B,S] → (logits [B,S,V] fp32, moe_aux_loss)."""
    b, s = tokens.shape
    h = gpt_embed(p, tokens, cfg, position_offset)
    cos, sin = gpt_rope_tables(cfg, s, position_offset)
    h, aux = block_forward(p["block"], h, cfg, cos, sin, attention_mask)
    h = apply_norm(cfg.normalization, h, p["final_ln_scale"],
                   p.get("final_ln_bias"), cfg.layernorm_epsilon)
    out_kernel = (p["output"] if "output" in p
                  else p["embedding"]["word"].T)
    logits = h.astype(cfg.compute_dtype) @ out_kernel.astype(cfg.compute_dtype)
    logits = scope_capture("result", logits)
    return logits.astype(jnp.float32), aux


def gpt_loss(p, tokens: jnp.ndarray, targets: jnp.ndarray,
             loss_mask: Optional[jnp.ndarray], cfg: TransformerConfig):
    """Training loss (CE + MoE aux). Mirrors pretrain_gpt.py loss_func
    (/root/reference/pretrain_gpt.py:159)."""
    logits, aux = gpt_forward(p, tokens, cfg)
    loss, _ = cross_entropy_loss(logits, targets, loss_mask)
    return loss + aux, {"lm_loss": loss, "moe_aux_loss": aux}
