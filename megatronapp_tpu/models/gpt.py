"""GPT model (decoder-only LM).

Parity with /root/reference/megatron/core/models/gpt/gpt_model.py:32
(GPTModel: LanguageModelEmbedding → TransformerBlock → output layer with
optionally tied word embeddings, vocab-parallel logits + CE). TPU-first:
functional params pytree, scan-over-layers block, logical-axis shardings.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    NormKind, PositionEmbeddingKind, TransformerConfig,
)
from megatronapp_tpu.ops import rotary
from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
from megatronapp_tpu.ops.normalization import apply_norm
from megatronapp_tpu.transformer.block import block_forward, init_block_params
from megatronapp_tpu.scope.hooks import scope_capture


def init_gpt_params(rng, cfg: TransformerConfig, pp: int = 1, vpp: int = 1):
    """Returns (params, logical_axes) pytrees.

    pp > 1: block params are stored in the pipeline layout
    [pp, vpp, L/(pp*vpp), ...] (sharded over the pp mesh axis) with the
    interleaved chunk→stage assignment — see parallel/pipeline.py.
    """
    k_emb, k_pos, k_block, k_out = jax.random.split(rng, 4)
    std = cfg.init_method_std
    p = {
        "embedding": {
            "word": jax.random.normal(
                k_emb, (cfg.vocab_size, cfg.hidden_size), cfg.params_dtype) * std,
        },
        "final_ln_scale": jnp.ones((cfg.hidden_size,), cfg.params_dtype),
    }
    ax = {
        "embedding": {"word": ("vocab", "embed")},
        "final_ln_scale": ("embed",),
    }
    if cfg.position_embedding == PositionEmbeddingKind.learned_absolute:
        p["embedding"]["pos"] = jax.random.normal(
            k_pos, (cfg.max_position_embeddings, cfg.hidden_size),
            cfg.params_dtype) * std
        ax["embedding"]["pos"] = ("pos", "embed")
    if cfg.normalization == NormKind.layernorm:
        p["final_ln_bias"] = jnp.zeros((cfg.hidden_size,), cfg.params_dtype)
        ax["final_ln_bias"] = ("embed",)
    p["block"], ax["block"] = init_block_params(k_block, cfg)
    if pp > 1:
        from megatronapp_tpu.parallel.pipeline import (
            reshape_params_for_pipeline,
        )
        if cfg.is_moe and cfg.moe_layer_freq > 1:
            raise NotImplementedError(
                "pipeline parallelism with moe_layer_freq > 1 group-scan "
                "layout is not supported yet")
        if cfg.num_layers % (pp * vpp) != 0:
            raise ValueError(
                f"num_layers={cfg.num_layers} not divisible by "
                f"pp*vpp={pp * vpp}")
        p["block"] = reshape_params_for_pipeline(p["block"], pp, vpp)
        from megatronapp_tpu.parallel.sharding import is_logical_axes
        ax["block"] = jax.tree.map(
            lambda axes: ("pp_stage", "vpp_chunk", "stage_layers") + axes[1:],
            ax["block"], is_leaf=is_logical_axes)
    if cfg.untie_embeddings_and_output_weights:
        p["output"] = jax.random.normal(
            k_out, (cfg.hidden_size, cfg.vocab_size), cfg.params_dtype) * std
        ax["output"] = ("embed", "vocab")
    return p, ax


def gpt_embed(p, tokens: jnp.ndarray, cfg: TransformerConfig,
              position_offset: int = 0, dtype=None) -> jnp.ndarray:
    """tokens [B,S] → embeddings [B,S,H] (vocab axis tp-sharded: XLA handles
    the sharded gather; reference VocabParallelEmbedding layers.py:172)."""
    h = jnp.take(p["embedding"]["word"], tokens, axis=0)
    if "pos" in p["embedding"]:
        s = tokens.shape[1]
        pos = jnp.arange(s) + position_offset
        h = h + jnp.take(p["embedding"]["pos"], pos, axis=0)
    return h.astype(dtype or cfg.compute_dtype)


def gpt_rope_tables(cfg: TransformerConfig, seq_len: int,
                    position_offset: int = 0):
    # MLA applies rope only on the decoupled position heads.
    rope_dim = (cfg.qk_pos_emb_head_dim if cfg.multi_latent_attention
                else cfg.head_dim)
    if cfg.position_embedding == PositionEmbeddingKind.rope:
        inv_freq = rotary.rope_frequencies(rope_dim, cfg.rotary_base,
                                           cfg.rotary_percent)
    elif cfg.position_embedding == PositionEmbeddingKind.yarn:
        inv_freq = rotary.yarn_frequencies(
            rope_dim, cfg.rotary_base,
            scaling_factor=cfg.rope_scaling_factor,
            original_max_position=cfg.yarn_original_max_position,
            beta_fast=cfg.yarn_beta_fast, beta_slow=cfg.yarn_beta_slow,
            rotary_percent=cfg.rotary_percent)
    else:
        return None, None
    positions = jnp.arange(seq_len) + position_offset
    cos, sin = rotary.rope_cos_sin(positions, inv_freq)
    if cfg.position_embedding == PositionEmbeddingKind.yarn:
        m = rotary.yarn_mscale(cfg.rope_scaling_factor, cfg.yarn_mscale_coeff)
        cos, sin = cos * m, sin * m
    return cos, sin


def packed_attention_mask(segment_ids: jnp.ndarray) -> jnp.ndarray:
    """Block-diagonal mask for packed sequences: token i may attend j only
    within the same segment (causality comes from the standard causal mask
    on top). Parity with the reference packed/THD formats
    (core/packed_seq_params.py + --reset-attention-mask /
    --reset-position-ids semantics; positions reset per segment in
    packed_position_ids). Note: an explicit mask routes attention through
    the reference impl (O(S²) scores), not the flash kernel — a
    segment-aware flash variant is future work.

    segment_ids [B,S] → bool mask [B,1,S,S] (True = may attend)."""
    same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
    return same


def packed_position_ids(segment_ids: jnp.ndarray) -> jnp.ndarray:
    """Per-segment position ids: positions restart at 0 at each segment
    boundary (reference --reset-position-ids). [B,S] → [B,S] int32."""
    b, s = segment_ids.shape
    idx = jnp.arange(s)[None, :]
    is_start = jnp.concatenate(
        [jnp.ones((b, 1), bool),
         segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1)
    seg_start = jax.lax.cummax(jnp.where(is_start, idx, 0), axis=1)
    return (idx - seg_start).astype(jnp.int32)


def gpt_forward(p, tokens: jnp.ndarray, cfg: TransformerConfig,
                attention_mask: Optional[jnp.ndarray] = None,
                position_offset: int = 0, ctx=None,
                segment_ids: Optional[jnp.ndarray] = None):
    """tokens [B,S] → (logits [B,S,V] fp32, moe_aux_loss).

    segment_ids: optional [B,S] packing map — attention is restricted to
    within-segment (packed sequences)."""
    b, s = tokens.shape
    h = gpt_embed(p, tokens, cfg, position_offset)
    cos, sin = gpt_rope_tables(cfg, s, position_offset)
    if segment_ids is not None:
        if ctx is not None and ctx.cp > 1:
            raise NotImplementedError(
                "packed sequences (segment_ids) are not supported under "
                "context parallelism yet")
        seg_mask = packed_attention_mask(segment_ids)
        attention_mask = (seg_mask if attention_mask is None
                          else attention_mask & seg_mask)
        if cos is not None:
            # Positions restart per segment (reference
            # --reset-position-ids): per-token rope tables [B,S,half].
            rel_pos = packed_position_ids(segment_ids) + position_offset
            from megatronapp_tpu.ops import rotary as _rot
            rope_dim = (cfg.qk_pos_emb_head_dim
                        if cfg.multi_latent_attention else cfg.head_dim)
            inv_freq = _rot.rope_frequencies(rope_dim, cfg.rotary_base,
                                             cfg.rotary_percent)
            cos, sin = _rot.rope_cos_sin(rel_pos, inv_freq)
    h, aux = block_forward(p["block"], h, cfg, cos, sin, attention_mask,
                           ctx=ctx)
    return gpt_head(p, h, cfg), aux


def gpt_loss(p, tokens: jnp.ndarray, targets: jnp.ndarray,
             loss_mask: Optional[jnp.ndarray], cfg: TransformerConfig,
             ctx=None, segment_ids: Optional[jnp.ndarray] = None):
    """Training loss (CE + MoE aux). Mirrors pretrain_gpt.py loss_func
    (/root/reference/pretrain_gpt.py:159)."""
    logits, aux = gpt_forward(p, tokens, cfg, ctx=ctx,
                              segment_ids=segment_ids)
    loss, _ = cross_entropy_loss(logits, targets, loss_mask)
    return loss + aux, {"lm_loss": loss, "moe_aux_loss": aux}


def gpt_head(p, h: jnp.ndarray, cfg: TransformerConfig) -> jnp.ndarray:
    """Final norm + vocab projection. h [..., S, H] → logits fp32."""
    h = apply_norm(cfg.normalization, h, p["final_ln_scale"],
                   p.get("final_ln_bias"), cfg.layernorm_epsilon)
    out_kernel = (p["output"] if "output" in p
                  else p["embedding"]["word"].T)
    logits = h.astype(cfg.compute_dtype) @ out_kernel.astype(cfg.compute_dtype)
    logits = scope_capture("result", logits)
    return logits.astype(jnp.float32)


def gpt_pipeline_loss(p, tokens_mb, targets_mb, loss_mask_mb,
                      cfg: TransformerConfig, ctx, vpp: int = 1,
                      order_policy: str = "dfc"):
    """Pipelined training loss over microbatched inputs [M, mb, S].

    Embedding and LM head run outside the pipeline body (compiler-sharded
    over dp/tp); the layer stack runs inside spmd_pipeline over the pp axis.
    The reference runs its schedules imperatively per rank
    (schedules.py:1918 1F1B); here the schedule is one jitted scan.
    """
    from megatronapp_tpu.parallel.pipeline import spmd_pipeline

    m, mb, s = tokens_mb.shape
    # fp32 across the shard_map boundary (spmd_pipeline casts to the compute
    # dtype at microbatch injection — see pipeline.py body notes).
    h = gpt_embed(p, tokens_mb.reshape(m * mb, s), cfg, dtype=jnp.float32)
    h = h.reshape(m, mb, s, -1)
    cos, sin = gpt_rope_tables(cfg, s)

    def stage_fn(chunk_params, x, layer_offset):
        cos_l, sin_l = cos, sin
        from megatronapp_tpu.config.parallel_config import CP_AXIS
        from megatronapp_tpu.parallel.collectives import current_manual_axes
        if CP_AXIS in current_manual_axes() and cos is not None:
            # Inside the pipeline body the cp axis is manual: x carries the
            # local S/cp sequence block — slice the rope tables to match.
            # (In the pp==1 fallback stage_fn runs outside any manual
            # region and x carries the full sequence — no slicing.)
            s_loc = x.shape[1]
            start = jax.lax.axis_index(CP_AXIS) * s_loc
            cos_l = jax.lax.dynamic_slice_in_dim(cos, start, s_loc)
            sin_l = jax.lax.dynamic_slice_in_dim(sin, start, s_loc)
        return block_forward(chunk_params, x, cfg, cos_l, sin_l, None,
                             layer_offset=layer_offset, ctx=ctx)

    out_mb, aux = spmd_pipeline(
        stage_fn, p["block"], h, ctx, num_microbatches=m, vpp=vpp,
        compute_dtype=cfg.compute_dtype, order_policy=order_policy)
    # Aux losses are summed over the M microbatches inside the pipeline;
    # normalize to per-microbatch scale to match the non-pipelined path.
    aux = aux / m

    logits = gpt_head(p, out_mb, cfg)
    loss, _ = cross_entropy_loss(logits, targets_mb, loss_mask_mb)
    return loss + aux, {"lm_loss": loss, "moe_aux_loss": aux}
