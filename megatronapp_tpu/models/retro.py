"""Retro: retrieval-augmented decoder with chunked cross-attention.

Parity with /root/reference/megatron/core/models/retro/ (decoder_spec.py,
decoder_attention.py RetroDecoderCrossAttention, encoder_spec.py) +
pretrain_retro.py: the input sequence splits into fixed-size chunks; each
chunk's retrieved neighbor texts are encoded by a small bidirectional
encoder; decoder layers at `retro_layer_numbers` cross-attend from each
chunk's tokens to the PREVIOUS chunk's neighbor encodings (chunked
cross-attention with the causal retrieval shift — chunk i's neighbors are
retrieved from its own content, so only later chunks may see them), other
layers are plain causal self-attention.

TPU-first: neighbors fold into the batch axis for the encoder
([B*C*K, R, H] one batched run) and the chunked cross-attention is a
batched dense attention over [B*C, chunk, K*R] — static shapes, MXU-sized
matmuls, no per-chunk Python loops.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    AttnMaskType, TransformerConfig,
)
from megatronapp_tpu.models.gpt import gpt_embed, gpt_head, gpt_rope_tables
from megatronapp_tpu.ops.attention import dot_product_attention
from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
from megatronapp_tpu.ops.normalization import apply_norm
from megatronapp_tpu.transformer.block import (
    init_block_params, init_layer_params, layer_forward,
)


@dataclasses.dataclass
class RetroSpec:
    """Chunking/retrieval geometry (reference RetroConfig:
    retro_chunk_length, retro_num_neighbors, retro_retrieved_length)."""
    chunk_length: int = 64
    num_neighbors: int = 2
    retrieved_length: int = 128
    # Decoder layers (0-based) that carry chunked cross-attention
    # (reference retro_layer_numbers, default [6, 9, 12...] 1-based).
    cca_layers: Tuple[int, ...] = (1,)


def init_retro_params(rng, cfg: TransformerConfig,
                      enc_cfg: TransformerConfig, spec: RetroSpec):
    """Decoder params + neighbor encoder + per-cca-layer cross attention."""
    k_dec, k_enc, k_cca = jax.random.split(rng, 3)
    std = cfg.init_method_std
    h = cfg.hidden_size
    p = {"embedding": {"word": jax.random.normal(
            k_dec, (cfg.vocab_size, h), cfg.params_dtype) * std},
         "final_ln_scale": jnp.ones((h,), cfg.params_dtype)}
    ax = {"embedding": {"word": ("vocab", "embed")},
          "final_ln_scale": ("embed",)}
    from megatronapp_tpu.config.transformer_config import NormKind
    if cfg.normalization == NormKind.layernorm:
        p["final_ln_bias"] = jnp.zeros((h,), cfg.params_dtype)
        ax["final_ln_bias"] = ("embed",)
    p["block"], ax["block"] = init_block_params(k_dec, cfg)
    p["encoder"], ax["encoder"] = init_block_params(k_enc, enc_cfg)
    # Cross-attention params per cca layer: q from decoder, kv from
    # neighbor encodings.
    cca = {}
    cca_ax = {}
    d = cfg.head_dim
    nq = cfg.num_attention_heads
    for i, lid in enumerate(spec.cca_layers):
        kq, kk, ko = jax.random.split(jax.random.fold_in(k_cca, i), 3)
        cca[str(lid)] = {
            "ln_scale": jnp.ones((h,), cfg.params_dtype),
            "q_kernel": jax.random.normal(kq, (h, nq * d),
                                          cfg.params_dtype) * std,
            "kv_kernel": jax.random.normal(kk, (h, 2 * nq * d),
                                           cfg.params_dtype) * std,
            "out_kernel": jax.random.normal(ko, (nq * d, h),
                                            cfg.params_dtype) * std,
        }
        cca_ax[str(lid)] = {
            "ln_scale": ("embed",),
            "q_kernel": ("embed", "qkv"), "kv_kernel": ("embed", "qkv"),
            "out_kernel": ("qkv", "embed"),
        }
    p["cca"] = cca
    ax["cca"] = cca_ax
    return p, ax


def _encode_neighbors(p, neighbors: jnp.ndarray,
                      enc_cfg: TransformerConfig, ctx=None) -> jnp.ndarray:
    """[B, C, K, R] neighbor token ids → [B, C, K*R, H] encodings (one
    batched bidirectional run; neighbors fold into the batch axis)."""
    b, c, k, r = neighbors.shape
    flat = neighbors.reshape(b * c * k, r)
    h = jnp.take(p["embedding"]["word"], flat, axis=0).astype(
        enc_cfg.compute_dtype)
    from megatronapp_tpu.transformer.block import block_forward
    enc, _ = block_forward(p["encoder"], h, enc_cfg, None, None, None,
                           ctx=ctx)
    return enc.reshape(b, c, k * r, -1)


def _chunked_cross_attention(cp, x: jnp.ndarray, enc: jnp.ndarray,
                             cfg: TransformerConfig,
                             spec: RetroSpec) -> jnp.ndarray:
    """x [B, S, H] decoder states; enc [B, C, K*R, H] neighbor encodings;
    each chunk attends its own neighbors (batched over B*C)."""
    b, s, h = x.shape
    c = s // spec.chunk_length
    d = cfg.head_dim
    nq = cfg.num_attention_heads
    dt = cfg.compute_dtype

    # Causal retrieval alignment (Retro paper / reference decoder_attention):
    # chunk i's neighbors are retrieved FROM chunk i's content, so its
    # tokens may only attend the neighbors of the PREVIOUS chunk — shift
    # the encodings one chunk right; chunk 0 sees zero keys/values (whose
    # attention output is exactly zero, leaving the residual unchanged).
    enc = jnp.concatenate([jnp.zeros_like(enc[:, :1]), enc[:, :-1]],
                          axis=1)
    y = apply_norm(cfg.normalization, x, cp["ln_scale"], None,
                   cfg.layernorm_epsilon).astype(dt)
    q = (y @ cp["q_kernel"].astype(dt)).reshape(b, s, nq, d)
    kv = (enc.astype(dt) @ cp["kv_kernel"].astype(dt))
    k_, v_ = jnp.split(kv.reshape(b, c, enc.shape[2], 2 * nq, d), 2,
                       axis=3)
    # Fold chunks into batch: q [B*C, chunk, nq, d] vs kv [B*C, K*R, nq, d].
    q = q.reshape(b * c, spec.chunk_length, nq, d)
    k_ = k_.reshape(b * c, enc.shape[2], nq, d)
    v_ = v_.reshape(b * c, enc.shape[2], nq, d)
    out = dot_product_attention(q, k_, v_,
                                mask_type=AttnMaskType.bidirectional)
    out = out.reshape(b, s, nq * d) @ cp["out_kernel"].astype(dt)
    return x + out.astype(x.dtype)


def retro_forward(p, tokens: jnp.ndarray, neighbors: jnp.ndarray,
                  cfg: TransformerConfig, enc_cfg: TransformerConfig,
                  spec: RetroSpec, ctx=None) -> jnp.ndarray:
    """tokens [B, S] + neighbors [B, S/chunk, K, R] → logits [B, S, V].

    The decoder runs layer-by-layer (unstacked indexing of the scanned
    params); cca layers insert chunked cross-attention after their
    self-attention sublayer (reference decoder_attention.py order).
    """
    b, s = tokens.shape
    assert s % spec.chunk_length == 0, (s, spec.chunk_length)
    h = gpt_embed(p, tokens, cfg)
    cos, sin = gpt_rope_tables(cfg, s)
    enc = _encode_neighbors(p, neighbors, enc_cfg, ctx=ctx)

    for lid in range(cfg.num_layers):
        layer_p = jax.tree.map(lambda x: x[lid], p["block"])
        (h, _), _ = layer_forward(layer_p, h, cfg, cos, sin, None,
                                  layer_id=lid, ctx=ctx)
        if lid in spec.cca_layers:
            h = _chunked_cross_attention(p["cca"][str(lid)], h, enc, cfg,
                                         spec)
    h = apply_norm(cfg.normalization, h, p["final_ln_scale"],
                   p.get("final_ln_bias"), cfg.layernorm_epsilon)
    logits = h.astype(cfg.compute_dtype) @ \
        p["embedding"]["word"].T.astype(cfg.compute_dtype)
    return logits.astype(jnp.float32)


def retro_loss(p, tokens, neighbors, targets, loss_mask,
               cfg: TransformerConfig, enc_cfg: TransformerConfig,
               spec: RetroSpec, ctx=None):
    """pretrain_retro.py loss parity."""
    logits = retro_forward(p, tokens, neighbors, cfg, enc_cfg, spec,
                           ctx=ctx)
    loss, _ = cross_entropy_loss(logits, targets, loss_mask)
    return loss, {"lm_loss": loss}
