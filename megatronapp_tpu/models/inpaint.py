"""ViT inpainting pretraining (masked-region reconstruction).

Parity with /root/reference/megatron/legacy/model/vision/inpainting.py
(VitInpaintingModel :19 — ViT backbone + zero-init linear patch decoder →
rearrange back to an image) and pretrain_vision_inpaint.py (masked-MSE
loss normalized by mask count + PSNR/SSIM metrics,
tasks/vision/segmentation/metrics.py:414-505). TPU-first: patch decode is
one [B,P,H]×[H,patch_dim] matmul and the un-patchify is a
reshape/transpose (inverse of models/vision.patchify — no einops/conv);
SSIM's per-channel gaussian filtering is a depthwise
lax.conv_general_dilated that XLA fuses.

Design note: the reference builds the backbone with class_token=False;
here the shared ViT keeps its CLS token and the decoder reads the patch
tokens enc[:, 1:] — same reconstruction capacity, one backbone
implementation for classify/DINO/inpaint.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.models.vision import (
    VitSpec, init_vit_params, vit_backbone,
)


def init_inpaint_params(rng, cfg: TransformerConfig, spec: VitSpec):
    kb, _ = jax.random.split(rng)
    p, ax = init_vit_params(kb, cfg, spec, with_head=False)
    # Zero-init decoder (reference get_linear_layer(..., init.zeros_),
    # inpainting.py:42-46).
    p["decoder_kernel"] = jnp.zeros((cfg.hidden_size, spec.patch_dim),
                                    jnp.float32)
    p["decoder_bias"] = jnp.zeros((spec.patch_dim,), jnp.float32)
    ax["decoder_kernel"] = ("embed", None)
    ax["decoder_bias"] = (None,)
    return p, ax


def unpatchify(patches: jnp.ndarray, patch: int, image_size: int,
               channels: int) -> jnp.ndarray:
    """[B, P, p*p*C] → [B, H, W, C] (inverse of vision.patchify; the
    reference's einops rearrange, inpainting.py:58-65)."""
    b = patches.shape[0]
    g = image_size // patch
    x = patches.reshape(b, g, g, patch, patch, channels)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, image_size, image_size, channels)


def inpaint_forward(p, images: jnp.ndarray, cfg: TransformerConfig,
                    spec: VitSpec, ctx=None) -> jnp.ndarray:
    """Masked image [B, H, W, C] → reconstruction [B, H, W, C]."""
    enc = vit_backbone(p, images, cfg, spec, ctx=ctx)
    decoded = enc[:, 1:].astype(jnp.float32) @ p["decoder_kernel"] \
        + p["decoder_bias"]
    return unpatchify(decoded, spec.patch_size, spec.image_size,
                      spec.num_channels)


def psnr(pred: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    """10·log10(1/mse) on [0,1]-range images (reference PSNR,
    metrics.py:414-432)."""
    mse = jnp.mean((pred - target) ** 2)
    return 10.0 * jnp.log10(1.0 / jnp.maximum(mse, 1e-10))


def _gaussian_window(size: int, sigma: float) -> jnp.ndarray:
    x = jnp.arange(size, dtype=jnp.float32) - size // 2
    g = jnp.exp(-(x ** 2) / (2.0 * sigma ** 2))
    g = g / jnp.sum(g)
    return jnp.outer(g, g)


def ssim(pred: jnp.ndarray, target: jnp.ndarray, window_size: int = 11,
         sigma: float = 1.5) -> jnp.ndarray:
    """Structural similarity on [B, H, W, C] images (reference SSIM,
    metrics.py:435-505: 11×11 gaussian σ=1.5, C1=0.01², C2=0.03²).
    Depthwise gaussian filtering via feature-grouped convolution."""
    c = pred.shape[-1]
    win = _gaussian_window(window_size, sigma)
    # [H, W, in_per_group=1, out=C] depthwise kernel.
    kernel = jnp.tile(win[:, :, None, None], (1, 1, 1, c))

    def filt(x):
        return jax.lax.conv_general_dilated(
            x.astype(jnp.float32), kernel, window_strides=(1, 1),
            padding="VALID", feature_group_count=c,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    mu_p, mu_t = filt(pred), filt(target)
    mu_pp, mu_tt, mu_pt = mu_p * mu_p, mu_t * mu_t, mu_p * mu_t
    sig_p = filt(pred * pred) - mu_pp
    sig_t = filt(target * target) - mu_tt
    sig_pt = filt(pred * target) - mu_pt
    c1, c2 = 0.01 ** 2, 0.03 ** 2
    ssim_map = ((2 * mu_pt + c1) * (2 * sig_pt + c2)) / (
        (mu_pp + mu_tt + c1) * (sig_p + sig_t + c2))
    return jnp.mean(ssim_map)


def inpaint_loss(p, images: jnp.ndarray, masks: jnp.ndarray,
                 cfg: TransformerConfig, spec: VitSpec,
                 ctx=None) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Masked-region MSE + PSNR/SSIM metrics (reference loss_func,
    pretrain_vision_inpaint.py:47-74: outputs and images both masked to
    the hole, sum-MSE / count_nonzero(mask)).

    images [B,H,W,C] original; masks [B,H,W,1] with 1 = hole to fill.
    The model sees the image with holes zeroed.
    """
    masked_input = images * (1.0 - masks)
    out = inpaint_forward(p, masked_input, cfg, spec, ctx=ctx)
    hole_out = out * masks
    hole_img = images.astype(jnp.float32) * masks
    mask_count = jnp.maximum(jnp.sum(masks) * spec.num_channels, 1.0)
    loss = jnp.sum((hole_out - hole_img) ** 2) / mask_count
    return loss, {"loss_mse": loss, "psnr": psnr(hole_out, hole_img),
                  "ssim": ssim(hole_out, hole_img)}


def random_patch_masks(rng: jnp.ndarray, batch: int, spec: VitSpec,
                       mask_ratio: float = 0.25) -> jnp.ndarray:
    """Patch-aligned random hole masks [B, H, W, 1] (the reference's
    RandomMaskingGenerator in the vit dataset transform): each patch is
    masked i.i.d. with probability mask_ratio."""
    g = spec.image_size // spec.patch_size
    bits = (jax.random.uniform(rng, (batch, g, g)) <
            mask_ratio).astype(jnp.float32)
    m = jnp.repeat(jnp.repeat(bits, spec.patch_size, axis=1),
                   spec.patch_size, axis=2)
    return m[..., None]
