"""Named model-family presets.

Parity targets from the reference example configs (examples/gpt3,
examples/mixtral/train_mixtral_8x7b_distributed.sh:51,85, run_single_gpt.sh,
BASELINE.md parity list).
"""

from __future__ import annotations

from megatronapp_tpu.config.transformer_config import (
    ActivationKind, NormKind, PositionEmbeddingKind, TransformerConfig,
)


def gpt2_125m(**kw) -> TransformerConfig:
    d = dict(num_layers=12, hidden_size=768, num_attention_heads=12,
             vocab_size=50304, true_vocab_size=50257,
             max_position_embeddings=1024,
             position_embedding=PositionEmbeddingKind.learned_absolute,
             add_qkv_bias=True)
    d.update(kw)
    return TransformerConfig(**d)


def gpt3_2p7b(**kw) -> TransformerConfig:
    """BASELINE.md north-star model (GPT-3 2.7B)."""
    d = dict(num_layers=32, hidden_size=2560, num_attention_heads=32,
             vocab_size=50304, max_position_embeddings=2048)
    d.update(kw)
    return TransformerConfig(**d)


def gpt_16l_2048h(**kw) -> TransformerConfig:
    """Reference DPP/FBD test model (test_train_gpt_single_dpp.sh:30-66:
    16L / h2048 / 32 heads / seq 2048)."""
    d = dict(num_layers=16, hidden_size=2048, num_attention_heads=32,
             vocab_size=50304, max_position_embeddings=2048)
    d.update(kw)
    return TransformerConfig(**d)


def llama3_8b(**kw) -> TransformerConfig:
    d = dict(num_layers=32, hidden_size=4096, num_attention_heads=32,
             num_query_groups=8, ffn_hidden_size=14336, vocab_size=128256,
             max_position_embeddings=8192, rotary_base=500000.0,
             activation=ActivationKind.swiglu,
             normalization=NormKind.rmsnorm, add_bias_linear=False,
             untie_embeddings_and_output_weights=True)
    d.update(kw)
    return TransformerConfig(**d)


def mixtral_8x7b(**kw) -> TransformerConfig:
    """examples/mixtral parity: 8 experts, top-2, GQA-8."""
    d = dict(num_layers=32, hidden_size=4096, num_attention_heads=32,
             num_query_groups=8, ffn_hidden_size=14336, vocab_size=32000,
             max_position_embeddings=32768, rotary_base=1e6,
             activation=ActivationKind.swiglu,
             normalization=NormKind.rmsnorm, add_bias_linear=False,
             untie_embeddings_and_output_weights=True,
             num_moe_experts=8, moe_router_topk=2,
             moe_ffn_hidden_size=14336, moe_aux_loss_coeff=0.02)
    d.update(kw)
    return TransformerConfig(**d)


def bert_base(**kw) -> TransformerConfig:
    from megatronapp_tpu.models.bert import bert_config
    d = dict(num_layers=12, hidden_size=768, num_attention_heads=12,
             vocab_size=30592, max_position_embeddings=512)
    d.update(kw)
    return bert_config(**d)


def t5_base(**kw) -> TransformerConfig:
    from megatronapp_tpu.models.t5 import t5_config
    d = dict(num_layers=12, hidden_size=768, num_attention_heads=12,
             vocab_size=32128, max_position_embeddings=512)
    d.update(kw)
    return t5_config(**d)


def mamba_130m(**kw) -> TransformerConfig:
    """state-spaces/mamba-130m-class dims (24 layers, d_model 768)."""
    d = dict(num_layers=24, hidden_size=768, num_attention_heads=12,
             vocab_size=50280, max_position_embeddings=2048,
             normalization=NormKind.rmsnorm)
    d.update(kw)
    return TransformerConfig(**d)


PRESETS = {
    "gpt2-125m": gpt2_125m,
    "gpt3-2.7b": gpt3_2p7b,
    "mamba-130m": mamba_130m,
    "gpt-16l-2048h": gpt_16l_2048h,
    "llama3-8b": llama3_8b,
    "mixtral-8x7b": mixtral_8x7b,
    "bert-base": bert_base,
    "t5-base": t5_base,
}
