"""BERT model (bidirectional encoder, MLM + NSP heads).

Parity with /root/reference/megatron/core/models/bert/bert_model.py
(BertModel: embeddings incl. tokentype, bidirectional TransformerBlock with
padding mask, BertLMHead dense+gelu+LN → tied-embedding logits, optional
binary NSP head) and pretrain_bert.py's loss (masked-LM CE + NSP CE).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    AttnMaskType, NormKind, PositionEmbeddingKind, TransformerConfig,
)
from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
from megatronapp_tpu.ops.normalization import apply_norm
from megatronapp_tpu.ops.activations import gelu
from megatronapp_tpu.transformer.block import block_forward, init_block_params


def bert_config(**kw) -> TransformerConfig:
    """BERT-flavored TransformerConfig defaults (learned positions,
    bidirectional+padding attention)."""
    defaults = dict(
        position_embedding=PositionEmbeddingKind.learned_absolute,
        attn_mask_type=AttnMaskType.padding,
        add_qkv_bias=True,
    )
    defaults.update(kw)
    return TransformerConfig(**defaults)


def init_bert_params(rng, cfg: TransformerConfig, num_tokentypes: int = 2,
                     add_binary_head: bool = True):
    k_emb, k_pos, k_tt, k_block, k_lm, k_bin = jax.random.split(rng, 6)
    std = cfg.init_method_std
    h = cfg.hidden_size
    p = {
        "embedding": {
            "word": jax.random.normal(
                k_emb, (cfg.vocab_size, h), cfg.params_dtype) * std,
            "pos": jax.random.normal(
                k_pos, (cfg.max_position_embeddings, h),
                cfg.params_dtype) * std,
            "tokentype": jax.random.normal(
                k_tt, (num_tokentypes, h), cfg.params_dtype) * std,
        },
        "emb_ln_scale": jnp.ones((h,), cfg.params_dtype),
        "emb_ln_bias": jnp.zeros((h,), cfg.params_dtype),
        # BertLMHead: dense + LN then tied-embedding projection.
        "lm_head": {
            "dense": jax.random.normal(k_lm, (h, h), cfg.params_dtype) * std,
            "dense_bias": jnp.zeros((h,), cfg.params_dtype),
            "ln_scale": jnp.ones((h,), cfg.params_dtype),
            "ln_bias": jnp.zeros((h,), cfg.params_dtype),
            "output_bias": jnp.zeros((cfg.vocab_size,), cfg.params_dtype),
        },
    }
    ax = {
        "embedding": {"word": ("vocab", "embed"), "pos": ("pos", "embed"),
                      "tokentype": (None, "embed")},
        "emb_ln_scale": ("embed",),
        "emb_ln_bias": ("embed",),
        "lm_head": {
            "dense": ("embed", "embed"), "dense_bias": ("embed",),
            "ln_scale": ("embed",), "ln_bias": ("embed",),
            "output_bias": ("vocab",),
        },
    }
    p["block"], ax["block"] = init_block_params(k_block, cfg)
    if add_binary_head:
        p["binary_head"] = {
            "pooler": jax.random.normal(k_bin, (h, h),
                                        cfg.params_dtype) * std,
            "pooler_bias": jnp.zeros((h,), cfg.params_dtype),
            "dense": jax.random.normal(k_bin, (h, 2),
                                       cfg.params_dtype) * std,
            "dense_bias": jnp.zeros((2,), cfg.params_dtype),
        }
        ax["binary_head"] = {
            "pooler": ("embed", "embed"), "pooler_bias": ("embed",),
            "dense": ("embed", None), "dense_bias": (None,),
        }
    return p, ax


def bert_encode(p, tokens, cfg: TransformerConfig,
                padding_mask: Optional[jnp.ndarray] = None,
                tokentype_ids: Optional[jnp.ndarray] = None,
                ctx=None) -> jnp.ndarray:
    """Shared BERT encoder trunk: word+pos+tokentype embeddings → embedding
    LN → bidirectional block with padding mask. tokens [B,S] → h [B,S,H].
    Reused by the LM model below, the classification finetune head
    (tasks/finetune.py), the embedding tool (tools/bert_embedding.py) and
    the biencoder towers (models/biencoder.py)."""
    b, s = tokens.shape
    emb = p["embedding"]
    h = jnp.take(emb["word"], tokens, axis=0)
    h = h + jnp.take(emb["pos"], jnp.arange(s), axis=0)
    if tokentype_ids is not None:
        h = h + jnp.take(emb["tokentype"], tokentype_ids, axis=0)
    else:
        h = h + emb["tokentype"][0]
    h = apply_norm(NormKind.layernorm, h, p["emb_ln_scale"],
                   p["emb_ln_bias"], cfg.layernorm_epsilon)
    h = h.astype(cfg.compute_dtype)

    attn_mask = None
    if padding_mask is not None:
        # [B,1,1,S] True=attend; bidirectional otherwise.
        attn_mask = padding_mask[:, None, None, :].astype(bool)
    h, _ = block_forward(p["block"], h, cfg, None, None, attn_mask, ctx=ctx)
    return h


def bert_forward(p, tokens, cfg: TransformerConfig,
                 padding_mask: Optional[jnp.ndarray] = None,
                 tokentype_ids: Optional[jnp.ndarray] = None, ctx=None):
    """tokens [B,S] (+ padding_mask [B,S] 1=real) →
    (lm_logits [B,S,V], binary_logits [B,2] | None)."""
    emb = p["embedding"]
    h = bert_encode(p, tokens, cfg, padding_mask=padding_mask,
                    tokentype_ids=tokentype_ids, ctx=ctx)

    # LM head (bert_lm_head: dense+gelu+LN then tied projection).
    lm = p["lm_head"]
    y = gelu(h.astype(jnp.float32) @ lm["dense"].astype(jnp.float32)
             + lm["dense_bias"].astype(jnp.float32))
    y = apply_norm(NormKind.layernorm, y, lm["ln_scale"], lm["ln_bias"],
                   cfg.layernorm_epsilon)
    logits = (y.astype(cfg.compute_dtype)
              @ emb["word"].T.astype(cfg.compute_dtype)).astype(jnp.float32)
    logits = logits + lm["output_bias"].astype(jnp.float32)

    binary_logits = None
    if "binary_head" in p:
        bh = p["binary_head"]
        pooled = jnp.tanh(h[:, 0].astype(jnp.float32)
                          @ bh["pooler"].astype(jnp.float32)
                          + bh["pooler_bias"].astype(jnp.float32))
        binary_logits = (pooled @ bh["dense"].astype(jnp.float32)
                         + bh["dense_bias"].astype(jnp.float32))
    return logits, binary_logits


def bert_loss(p, batch, cfg: TransformerConfig, ctx=None):
    """Masked-LM CE (over loss_mask positions) + NSP CE
    (pretrain_bert.py loss_func parity)."""
    logits, binary_logits = bert_forward(
        p, batch["tokens"], cfg, padding_mask=batch.get("padding_mask"),
        tokentype_ids=batch.get("tokentype_ids"), ctx=ctx)
    lm_loss, _ = cross_entropy_loss(logits, batch["labels"],
                                    batch["loss_mask"])
    total = lm_loss
    metrics = {"lm_loss": lm_loss}
    if binary_logits is not None and "is_random" in batch:
        nsp, _ = cross_entropy_loss(binary_logits[:, None, :],
                                    batch["is_random"][:, None])
        total = total + nsp
        metrics["sop_loss"] = nsp
    else:
        metrics["sop_loss"] = jnp.zeros((), jnp.float32)
    return total, metrics


def mock_bert_batch(rng, batch_size, seq_length, vocab_size,
                    mask_prob=0.15, mask_id=4):
    """Synthetic masked-LM batch (reference MockBertDataset semantics)."""
    import numpy as np
    r = np.random.default_rng(rng)
    tokens = r.integers(5, vocab_size, size=(batch_size, seq_length))
    labels = tokens.copy()
    mask = r.random((batch_size, seq_length)) < mask_prob
    tokens = np.where(mask, mask_id, tokens)
    return {
        "tokens": tokens.astype(np.int32),
        "labels": labels.astype(np.int32),
        "loss_mask": mask.astype(np.float32),
        "padding_mask": np.ones((batch_size, seq_length), np.float32),
        "tokentype_ids": np.zeros((batch_size, seq_length), np.int32),
        "is_random": r.integers(0, 2, size=(batch_size,)).astype(np.int32),
    }
