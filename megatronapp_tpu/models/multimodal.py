"""Multimodal (vision-language) model: LLaVA-style ViT → projector → GPT.

Parity with /root/reference/megatron/core/models/multimodal/
llava_model.py + pretrain_vlm.py: a vision encoder embeds the image into
a sequence of visual tokens; a 2-layer MLP projector maps them into the
language model's embedding space; the language model consumes
[visual tokens ‖ text embeddings] with causal attention, and the loss is
computed on text positions only (image positions masked out).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.models.gpt import (
    gpt_head, gpt_rope_tables, init_gpt_params,
)
from megatronapp_tpu.models.vision import (
    VitSpec, init_vit_params, vit_backbone,
)
from megatronapp_tpu.ops.activations import gelu
from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
from megatronapp_tpu.transformer.block import block_forward


def init_vlm_params(rng, lm_cfg: TransformerConfig,
                    vis_cfg: TransformerConfig, spec: VitSpec,
                    clip_tower: bool = False):
    """{'vision', 'projector', 'lm'} param tree + logical axes.

    clip_tower=True uses the CLIP-structured vision params (pre-LN, no
    final norm) matching converted HF LLaVA checkpoints."""
    k_vis, k_proj1, k_proj2, k_lm = jax.random.split(rng, 4)
    std = lm_cfg.init_method_std
    vis_p, vis_ax = init_vit_params(k_vis, vis_cfg, spec, with_head=False,
                                    clip_variant=clip_tower)
    lm_p, lm_ax = init_gpt_params(k_lm, lm_cfg)
    p = {
        "vision": vis_p,
        "projector": {
            "fc1": jax.random.normal(
                k_proj1, (vis_cfg.hidden_size, lm_cfg.hidden_size),
                lm_cfg.params_dtype) * std,
            "fc1_bias": jnp.zeros((lm_cfg.hidden_size,),
                                  lm_cfg.params_dtype),
            "fc2": jax.random.normal(
                k_proj2, (lm_cfg.hidden_size, lm_cfg.hidden_size),
                lm_cfg.params_dtype) * std,
            "fc2_bias": jnp.zeros((lm_cfg.hidden_size,),
                                  lm_cfg.params_dtype),
        },
        "lm": lm_p,
    }
    ax = {
        "vision": vis_ax,
        "projector": {"fc1": (None, "embed"), "fc1_bias": ("embed",),
                      "fc2": ("embed", "embed"), "fc2_bias": ("embed",)},
        "lm": lm_ax,
    }
    return p, ax


def project_visual(p, visual: jnp.ndarray, dt) -> jnp.ndarray:
    """2-layer MLP projector (reference llava mlp adapter)."""
    y = gelu(visual.astype(dt) @ p["fc1"].astype(dt)
             + p["fc1_bias"].astype(dt))
    return y @ p["fc2"].astype(dt) + p["fc2_bias"].astype(dt)


def vlm_forward(p, images: jnp.ndarray, tokens: jnp.ndarray,
                lm_cfg: TransformerConfig, vis_cfg: TransformerConfig,
                spec: VitSpec, ctx=None):
    """images [B,H,W,C] + tokens [B,S_text] → logits [B, V_img+S_text, V].

    Visual tokens prefix the text sequence (LLaVA layout); rope positions
    run over the CONCATENATED sequence.
    """
    dt = lm_cfg.compute_dtype
    b, s_text = tokens.shape
    visual = vit_backbone(p["vision"], images, vis_cfg, spec, ctx=ctx)
    # Drop CLS: the LM consumes the patch tokens (reference uses the
    # encoder grid features).
    visual = project_visual(p["projector"], visual[:, 1:], dt)
    n_vis = visual.shape[1]

    emb = p["lm"]["embedding"]
    text = jnp.take(emb["word"], tokens, axis=0).astype(dt)
    if "pos" in emb:
        text = text + jnp.take(
            emb["pos"], jnp.arange(n_vis, n_vis + s_text), axis=0
        ).astype(dt)
        visual = visual + jnp.take(
            emb["pos"], jnp.arange(n_vis), axis=0).astype(dt)
    h = jnp.concatenate([visual, text], axis=1)
    cos, sin = gpt_rope_tables(lm_cfg, n_vis + s_text)
    h, aux = block_forward(p["lm"]["block"], h, lm_cfg, cos, sin, None,
                           ctx=ctx)
    return gpt_head(p["lm"], h, lm_cfg), aux, n_vis


def vlm_loss(p, images, tokens, targets, loss_mask,
             lm_cfg: TransformerConfig, vis_cfg: TransformerConfig,
             spec: VitSpec, ctx=None):
    """CE on TEXT positions only (pretrain_vlm.py loss parity: image
    positions carry no labels)."""
    logits, aux, n_vis = vlm_forward(p, images, tokens, lm_cfg, vis_cfg,
                                     spec, ctx=ctx)
    text_logits = logits[:, n_vis:]
    loss, _ = cross_entropy_loss(text_logits, targets, loss_mask)
    return loss + aux, {"lm_loss": loss, "moe_aux_loss": aux}
