"""Biencoder retrieval model (ICT / REALM-style pretraining).

Parity with /root/reference/megatron/legacy/model/biencoder_model.py
(biencoder_model_provider: query tower + context tower, each a BERT encoder
with a pooled retrieval head) and /root/reference/pretrain_ict.py
(in-batch softmax over q·c^T scores, diagonal labels, optional
1/sqrt(hidden) score scaling, top-k retrieval accuracies).

TPU-first design notes: the reference all-gathers query/context embeddings
across the data-parallel group with a hand-written autograd function
(pretrain_ict.py:46-72 AllgatherFromDataParallelRegion). Here the loss is
computed over the *global* batch inside one jitted step; with dp-sharded
inputs XLA inserts the all-gather for the [B_global, B_global] score
matmul on its own, and the backward gather/scatter falls out of
differentiation.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.transformer.block import init_block_params


def _init_tower(rng, cfg: TransformerConfig, num_tokentypes: int):
    """One BERT-style encoder tower + linear retrieval head over pooled
    CLS (reference PretrainedBertModel + get_linear_layer head)."""
    k_emb, k_pos, k_tt, k_block, k_head = jax.random.split(rng, 5)
    std = cfg.init_method_std
    h = cfg.hidden_size
    p = {
        "embedding": {
            "word": jax.random.normal(
                k_emb, (cfg.vocab_size, h), cfg.params_dtype) * std,
            "pos": jax.random.normal(
                k_pos, (cfg.max_position_embeddings, h),
                cfg.params_dtype) * std,
            "tokentype": jax.random.normal(
                k_tt, (num_tokentypes, h), cfg.params_dtype) * std,
        },
        "emb_ln_scale": jnp.ones((h,), cfg.params_dtype),
        "emb_ln_bias": jnp.zeros((h,), cfg.params_dtype),
        "head": jax.random.normal(k_head, (h, h), cfg.params_dtype) * std,
        "head_bias": jnp.zeros((h,), cfg.params_dtype),
    }
    ax = {
        "embedding": {"word": ("vocab", "embed"), "pos": ("pos", "embed"),
                      "tokentype": (None, "embed")},
        "emb_ln_scale": ("embed",),
        "emb_ln_bias": ("embed",),
        "head": ("embed", "embed"),
        "head_bias": ("embed",),
    }
    p["block"], ax["block"] = init_block_params(k_block, cfg)
    return p, ax


def init_biencoder_params(rng, cfg: TransformerConfig,
                          num_tokentypes: int = 2, shared: bool = False):
    """(params, logical_axes). `shared` ties the two towers
    (--biencoder-shared-query-context-model)."""
    kq, kc = jax.random.split(rng)
    pq, axq = _init_tower(kq, cfg, num_tokentypes)
    if shared:
        return {"query": pq, "shared": True}, {"query": axq}
    pc, axc = _init_tower(kc, cfg, num_tokentypes)
    return ({"query": pq, "context": pc},
            {"query": axq, "context": axc})


def tower_embed(tower, tokens, cfg: TransformerConfig,
                padding_mask: Optional[jnp.ndarray] = None,
                tokentype_ids: Optional[jnp.ndarray] = None,
                ctx=None) -> jnp.ndarray:
    """tokens [B,S] → pooled retrieval embedding [B,H] (CLS position
    through the linear head)."""
    from megatronapp_tpu.models.bert import bert_encode
    h = bert_encode(tower, tokens, cfg, padding_mask=padding_mask,
                    tokentype_ids=tokentype_ids, ctx=ctx)
    pooled = h[:, 0].astype(jnp.float32)
    return pooled @ tower["head"].astype(jnp.float32) \
        + tower["head_bias"].astype(jnp.float32)


def biencoder_embed(p, tokens, cfg: TransformerConfig, *, kind: str,
                    padding_mask=None, ctx=None) -> jnp.ndarray:
    """kind = 'query' | 'context'; shared models route both through the
    query tower."""
    tower = p["query"] if (kind == "query" or p.get("shared")) \
        else p["context"]
    return tower_embed(tower, tokens, cfg, padding_mask=padding_mask,
                       ctx=ctx)


def ict_loss(p, batch, cfg: TransformerConfig, ctx=None,
             score_scaling: bool = False, report_topk=(1, 5)):
    """In-batch retrieval softmax (pretrain_ict.py loss_func): scores are
    q·c^T over the global batch, label i is context i."""
    q = biencoder_embed(p, batch["query_tokens"], cfg, kind="query",
                        padding_mask=batch.get("query_pad_mask"), ctx=ctx)
    c = biencoder_embed(p, batch["context_tokens"], cfg, kind="context",
                        padding_mask=batch.get("context_pad_mask"), ctx=ctx)
    scores = q @ c.T
    if score_scaling:
        scores = scores / jnp.sqrt(float(cfg.hidden_size))
    n = scores.shape[0]
    logp = jax.nn.log_softmax(scores, axis=-1)
    labels = jnp.arange(n)
    loss = -jnp.take_along_axis(logp, labels[:, None], axis=1).mean()
    metrics = {"loss": loss}
    # top-k retrieval accuracy (retriever_report_topk_accuracies).
    rank_of_true = (scores >= jnp.take_along_axis(
        scores, labels[:, None], axis=1)).sum(axis=1)
    for k in report_topk:
        metrics[f"top{k}_acc"] = (rank_of_true <= k).mean() * 100.0
    return loss, metrics
