"""Vision transformer (ViT) backbone + classification head.

Parity with /root/reference/megatron/core/models/vision/ (vit_backbone in
legacy/model/vision + core CLIP-style encoder used by multimodal) and
pretrain_vision_classify.py: patchify → linear patch embedding + [CLS]
token + learned positions → bidirectional transformer stack → head.
TPU-first: patch extraction is one reshape/transpose (no conv im2col), the
stack reuses the shared scan-over-layers block, and shapes keep the MXU
busy ([B, 1+P, H] with P = (img/patch)²).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    AttnMaskType, NormKind, PositionEmbeddingKind, TransformerConfig,
)
from megatronapp_tpu.ops.normalization import apply_norm
from megatronapp_tpu.transformer.block import block_forward, init_block_params


def vit_config(**kw) -> TransformerConfig:
    """ViT-flavored TransformerConfig (bidirectional, learned positions,
    no vocab)."""
    defaults = dict(
        position_embedding=PositionEmbeddingKind.learned_absolute,
        attn_mask_type=AttnMaskType.bidirectional,
        add_qkv_bias=True,
    )
    defaults.update(kw)
    return TransformerConfig(**defaults)


@dataclasses.dataclass
class VitSpec:
    """Image/patch geometry (reference vit args: --img-h/--img-w/
    --patch-dim) + head size."""
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    num_classes: int = 1000

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.num_channels * self.patch_size ** 2


def patchify(images: jnp.ndarray, patch: int) -> jnp.ndarray:
    """[B, H, W, C] → [B, (H/p)*(W/p), p*p*C] — one reshape/transpose
    (XLA-fusable; no convolution lowering needed)."""
    b, h, w, c = images.shape
    gh, gw = h // patch, w // patch
    x = images.reshape(b, gh, patch, gw, patch, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, gh * gw, patch * patch * c)


def init_vit_params(rng, cfg: TransformerConfig, spec: VitSpec,
                    with_head: bool = True, clip_variant: bool = False):
    """clip_variant=True produces the CLIP-tower structure (pre-encoder
    layernorm, no final norm) that tools/checkpoint/convert.py's LLaVA
    loader emits, so converted checkpoints restore against this init's
    pytree template."""
    keys = jax.random.split(rng, 5)
    std = cfg.init_method_std
    h = cfg.hidden_size
    p = {
        "patch_proj": jax.random.normal(
            keys[0], (spec.patch_dim, h), cfg.params_dtype) * std,
        "patch_bias": jnp.zeros((h,), cfg.params_dtype),
        "cls_token": jax.random.normal(
            keys[1], (1, 1, h), cfg.params_dtype) * std,
        "pos": jax.random.normal(
            keys[2], (1 + spec.num_patches, h), cfg.params_dtype) * std,
    }
    ax = {
        "patch_proj": (None, "embed"), "patch_bias": ("embed",),
        "cls_token": (None, None, "embed"), "pos": ("pos", "embed"),
    }
    if clip_variant:
        p["pre_ln_scale"] = jnp.ones((h,), cfg.params_dtype)
        p["pre_ln_bias"] = jnp.zeros((h,), cfg.params_dtype)
        ax["pre_ln_scale"] = ("embed",)
        ax["pre_ln_bias"] = ("embed",)
    else:
        p["final_ln_scale"] = jnp.ones((h,), cfg.params_dtype)
        p["final_ln_bias"] = jnp.zeros((h,), cfg.params_dtype)
        ax["final_ln_scale"] = ("embed",)
        ax["final_ln_bias"] = ("embed",)
    p["block"], ax["block"] = init_block_params(keys[3], cfg)
    if with_head:
        p["head_kernel"] = jax.random.normal(
            keys[4], (h, spec.num_classes), cfg.params_dtype) * std
        p["head_bias"] = jnp.zeros((spec.num_classes,), cfg.params_dtype)
        ax["head_kernel"] = ("embed", None)
        ax["head_bias"] = (None,)
    return p, ax


def vit_backbone(p, images: jnp.ndarray, cfg: TransformerConfig,
                 spec: VitSpec, ctx=None) -> jnp.ndarray:
    """[B, H, W, C] images → [B, 1+P, H] encoded tokens (CLS first).

    Optional param-presence-gated variants (used by converted CLIP towers,
    tools/checkpoint/convert.py llava path): a 'pre_ln_*' layernorm after
    the position add (CLIP pre_layrnorm), and omitting 'final_ln_scale'
    skips the output norm (LLaVA consumes an intermediate feature layer
    that is never post-normalized)."""
    b = images.shape[0]
    x = patchify(images.astype(cfg.compute_dtype), spec.patch_size)
    x = x @ p["patch_proj"].astype(cfg.compute_dtype) \
        + p["patch_bias"].astype(cfg.compute_dtype)
    cls = jnp.broadcast_to(p["cls_token"].astype(cfg.compute_dtype),
                           (b, 1, cfg.hidden_size))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + p["pos"].astype(cfg.compute_dtype)[None]
    if "pre_ln_scale" in p:
        x = apply_norm(NormKind.layernorm, x, p["pre_ln_scale"],
                       p.get("pre_ln_bias"), cfg.layernorm_epsilon)
    x, _ = block_forward(p["block"], x, cfg, None, None, None, ctx=ctx)
    if "final_ln_scale" not in p:
        return x
    return apply_norm(NormKind.layernorm, x, p["final_ln_scale"],
                      p["final_ln_bias"], cfg.layernorm_epsilon)


def vit_classify(p, images: jnp.ndarray, cfg: TransformerConfig,
                 spec: VitSpec, ctx=None) -> jnp.ndarray:
    """→ class logits [B, num_classes] from the CLS token."""
    enc = vit_backbone(p, images, cfg, spec, ctx=ctx)
    cls = enc[:, 0].astype(jnp.float32)
    return cls @ p["head_kernel"].astype(jnp.float32) \
        + p["head_bias"].astype(jnp.float32)


def vit_classification_loss(p, images, labels, cfg: TransformerConfig,
                            spec: VitSpec, ctx=None):
    """CE over classes (pretrain_vision_classify.py loss parity)."""
    from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
    logits = vit_classify(p, images, cfg, spec, ctx=ctx)
    loss, _ = cross_entropy_loss(logits[:, None], labels[:, None])
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"lm_loss": loss, "accuracy": acc}
