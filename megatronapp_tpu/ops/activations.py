"""Activation functions + gated-MLP helpers.

Parity with the reference fused bias-activation wrappers
(/root/reference/megatron/core/fusions/fused_bias_gelu.py,
fused_bias_swiglu.py, fused_bias_geglu.py). XLA fuses bias+activation into the
producing matmul on TPU, so these are expressed directly in jnp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import ActivationKind


def gelu(x):
    # tanh approximation — matches the reference bias_gelu fusion
    # (fused_bias_gelu.py uses the tanh form).
    return jax.nn.gelu(x, approximate=True)


def squared_relu(x):
    return jnp.square(jax.nn.relu(x))


def apply_activation(kind: ActivationKind, x, gate=None):
    """Apply activation; for gated kinds `x` is the value and `gate` the gate
    branch (reference fused_bias_swiglu.py: swiglu(y) = silu(y1) * y2)."""
    if kind == ActivationKind.swiglu:
        assert gate is not None
        return jax.nn.silu(gate) * x
    if kind == ActivationKind.geglu:
        assert gate is not None
        return gelu(gate) * x
    if kind == ActivationKind.gelu:
        return gelu(x)
    if kind == ActivationKind.relu:
        return jax.nn.relu(x)
    if kind == ActivationKind.squared_relu:
        return squared_relu(x)
    raise ValueError(f"unknown activation {kind}")


def is_gated(kind: ActivationKind) -> bool:
    return kind in (ActivationKind.swiglu, ActivationKind.geglu)
