"""Ragged paged-attention decode entry points (Pallas TPU) + page helpers.

vLLM-style paged KV serving ("Ragged Paged Attention", arXiv 2604.15464,
PAPERS.md): the decode cache lives in a shared block pool shaped
[num_blocks, block_size, Hkv, D]; each slot owns an ordered page table of
block ids, and one query token per active slot gathers K/V through its
table with an online softmax over VALID blocks only — no slot pays for
another slot's length, and admission is per-block instead of per-S_max
row (inference/paged_cache.py is the allocator).

The kernel BODIES live in ops/pallas/kernel_gen.py (ISSUE 11): one
dtype/shard/raggedness-parameterized generator emits the decode and
multi-query variants from a spec — the four hand-written bodies this
module used to carry (decode / multiquery × plain / tp, each × bf16 /
int8) are deleted; the public names below are thin dispatchers kept for
call-site compatibility (attention.py, dynamic_engine.py, disagg.py,
speculative.py, tests). The emitted bodies are bitwise-identical to the
legacy variants (pinned in tests/test_kernel_gen.py).

This module keeps what is NOT kernel-body generation: the jnp parity
oracles, the quantization helper (`quantize_kv_rows` — symmetric
per-(row, kv-head) int8, fused into the engine's write-path jits), the
page write/gather scatter helpers, and the tp eligibility predicate
(`tp_paged_eligible` / `tp_paged_ineligible_reason`).

TP sharding (ISSUE 9): GSPMD cannot partition a pallas_call, so the
tp-mesh serving path places the emitted kernels with a FULL-MANUAL
shard_map over KV heads (kernel_gen._tp_place): q heads and kv heads
slice contiguously together so each shard owns matched GQA groups, the
page table and kv lengths are replicated, and the K/V pools (plus int8
scale pools) shard on their Hkv dim — each device holds 1/tp of the
block pool and does 1/tp of the attention FLOPs/bytes.

Quantized KV (ISSUE 10, `k_scales`/`v_scales`): pools may be stored int8
with a per-(row, kv-head) fp32 scale pool [NB, bs, Hkv] alongside — rows
quantize independently on insert (`quantize_kv_rows`), so CoW copies,
rewind, and stale-row overwrites need no re-scaling. The scale blocks
ride the SAME scalar-prefetched page-table indirection as the KV blocks
and dequantize in-register; no bf16 pool is ever materialized.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.ops.pallas.kernel_gen import (  # noqa: F401 (re-export)
    _NEG_INF, _dequant_block, _interpret, paged_attention,
    paged_attention_latent,
)


def quantize_kv_rows(rows: jnp.ndarray, dtype=jnp.int8):
    """Symmetric per-(row, head) quantization of KV rows.

    rows [..., Hkv, D] → (quantized rows [..., Hkv, D], fp32 scales
    [..., Hkv]). Each (token, head) row quantizes independently over D —
    inserts never re-scale already-written rows, so partial blocks,
    copy-on-write copies, and speculative rewinds need no block-level
    bookkeeping. jit-able; fused into the engine's write-path jits.

    dtype selects the storage format (the page pool's dtype — callers
    pass ``pages.dtype`` so the write path follows the pool):
    - int8: round to [-127, 127] with scale = absmax / 127 (the PR-10
      path, bit-identical to before);
    - fp8 (e4m3fn): scale = absmax / 448 and SATURATE-cast — e4m3
      overflow is NaN, not inf, so the clip is load-bearing; the float
      cast rounds natively (no integer rounding step — the "drops the
      scale-pool rounding" half of the fp8 mode)."""
    from megatronapp_tpu.ops.pallas.kernel_gen import quant_qmax_of
    r32 = rows.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(r32), axis=-1)
    qmax = quant_qmax_of(dtype)
    if jnp.dtype(dtype) == jnp.dtype(jnp.int8):
        scales = jnp.maximum(absmax / qmax, 1e-12)
        q = jnp.clip(jnp.round(r32 / scales[..., None]), -qmax, qmax)
    else:
        scales = jnp.maximum(absmax / qmax, 1e-12)
        q = jnp.clip(r32 / scales[..., None], -qmax, qmax)
    return q.astype(dtype), scales.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Public kernel entry points — thin dispatchers over the generator
# ---------------------------------------------------------------------------


def paged_attention_decode(q: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray, page_table: jnp.ndarray,
                           kv_lens: jnp.ndarray,
                           softmax_scale: Optional[float] = None,
                           k_scales: Optional[jnp.ndarray] = None,
                           v_scales: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """One-token-per-slot ragged paged attention.

    q [B, Hq, D]; k_pages/v_pages [num_blocks, block_size, Hkv, D];
    page_table [B, max_blocks_per_seq] int32 (entries beyond a slot's
    allocation may be anything in range — they are masked, not read for
    math); kv_lens [B] int32 valid kv positions per slot (>= 1).
    k_scales/v_scales [num_blocks, block_size, Hkv] fp32: present iff the
    pools are int8 (quantize_kv_rows layout). Returns [B, Hq, D]."""
    return paged_attention(q, k_pages, v_pages, page_table, kv_lens,
                           softmax_scale=softmax_scale,
                           k_scales=k_scales, v_scales=v_scales)


def paged_attention_multiquery(q: jnp.ndarray, k_pages: jnp.ndarray,
                               v_pages: jnp.ndarray,
                               page_table: jnp.ndarray,
                               kv_lens: jnp.ndarray, q_lens: jnp.ndarray,
                               softmax_scale: Optional[float] = None,
                               k_scales: Optional[jnp.ndarray] = None,
                               v_scales: Optional[jnp.ndarray] = None
                               ) -> jnp.ndarray:
    """Ragged multi-query paged attention (speculative verify / chunked
    prefill).

    q [B, S_q, Hq, D] — per-request the first q_lens[b] rows are real
    queries at absolute positions kv_lens[b]-q_lens[b] .. kv_lens[b]-1
    (their K/V must already be written into the pages); the rest are
    padding whose outputs are garbage and must be discarded. kv_lens [B]
    counts ALL valid kv positions including the new tail (>= q_lens >=
    1). At q_len == 1 the emitted body reduces bitwise to the decode
    kernel. Returns [B, S_q, Hq, D]."""
    return paged_attention(q, k_pages, v_pages, page_table, kv_lens,
                           q_lens=q_lens, softmax_scale=softmax_scale,
                           k_scales=k_scales, v_scales=v_scales)


def paged_attention_decode_tp(q: jnp.ndarray, k_pages: jnp.ndarray,
                              v_pages: jnp.ndarray,
                              page_table: jnp.ndarray,
                              kv_lens: jnp.ndarray, mesh,
                              softmax_scale: Optional[float] = None,
                              k_scales: Optional[jnp.ndarray] = None,
                              v_scales: Optional[jnp.ndarray] = None
                              ) -> jnp.ndarray:
    """`paged_attention_decode` head-sharded over the tp axis of `mesh`
    (kernel_gen._tp_place: full-manual shard_map, pools + int8 scale
    pools sharded on Hkv, table/lens replicated). Output is [B, Hq, D]
    head-sharded (callers gather / constrain as needed)."""
    return paged_attention(q, k_pages, v_pages, page_table, kv_lens,
                           softmax_scale=softmax_scale,
                           k_scales=k_scales, v_scales=v_scales,
                           mesh=mesh)


def paged_attention_multiquery_tp(q: jnp.ndarray, k_pages: jnp.ndarray,
                                  v_pages: jnp.ndarray,
                                  page_table: jnp.ndarray,
                                  kv_lens: jnp.ndarray,
                                  q_lens: jnp.ndarray, mesh,
                                  softmax_scale: Optional[float] = None,
                                  k_scales: Optional[jnp.ndarray] = None,
                                  v_scales: Optional[jnp.ndarray] = None
                                  ) -> jnp.ndarray:
    """`paged_attention_multiquery` head-sharded over the tp axis of
    `mesh` (speculative verify / chunked prefill on a tp serving mesh).
    q [B, S_q, Hq, D] sharded on Hq; pools on Hkv (int8 pools: scale
    pools sharded alongside); table/lens/q_lens replicated."""
    return paged_attention(q, k_pages, v_pages, page_table, kv_lens,
                           q_lens=q_lens, softmax_scale=softmax_scale,
                           k_scales=k_scales, v_scales=v_scales,
                           mesh=mesh)


def dequantize_pages(pages: jnp.ndarray, scales: jnp.ndarray
                     ) -> jnp.ndarray:
    """Dense dequant of an int8 pool [..., bs, Hkv, D] with scales
    [..., bs, Hkv] → fp32 (references, prefix-hit gathers, A/B
    baselines — NOT the kernel path, which dequantizes per block)."""
    return pages.astype(jnp.float32) * scales[..., None]


def paged_attention_multiquery_reference(
        q: jnp.ndarray, k_pages: jnp.ndarray, v_pages: jnp.ndarray,
        page_table: jnp.ndarray, kv_lens: jnp.ndarray, q_lens: jnp.ndarray,
        softmax_scale: Optional[float] = None,
        k_scales: Optional[jnp.ndarray] = None,
        v_scales: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Pure-jnp oracle for the multi-query kernel (gathers dense,
    masks per-(query, kv) causally; int8 pools dequantize dense)."""
    b, s_q, hq, d = q.shape
    nb, bs, hkv, _ = k_pages.shape
    mb = page_table.shape[1]
    group = hq // hkv
    if k_scales is not None:
        k_pages = dequantize_pages(k_pages, k_scales)
        v_pages = dequantize_pages(v_pages, v_scales)
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    k = k_pages[page_table].reshape(b, mb * bs, hkv, d)
    v = v_pages[page_table].reshape(b, mb * bs, hkv, d)
    k = jnp.repeat(k, group, axis=2)
    v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * softmax_scale
    pos = jnp.arange(mb * bs)
    abs_q = (kv_lens - q_lens)[:, None] + jnp.arange(s_q)[None, :]  # [B,Sq]
    mask = ((pos[None, None, :] <= abs_q[:, :, None])
            & (pos[None, None, :] < kv_lens[:, None, None]))
    s = jnp.where(mask[:, :, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_attention_reference(q: jnp.ndarray, k_pages: jnp.ndarray,
                              v_pages: jnp.ndarray, page_table: jnp.ndarray,
                              kv_lens: jnp.ndarray,
                              softmax_scale: Optional[float] = None,
                              k_scales: Optional[jnp.ndarray] = None,
                              v_scales: Optional[jnp.ndarray] = None
                              ) -> jnp.ndarray:
    """Pure-jnp oracle with the same signature (gathers dense, masks;
    int8 pools dequantize dense)."""
    b, hq, d = q.shape
    nb, bs, hkv, _ = k_pages.shape
    mb = page_table.shape[1]
    group = hq // hkv
    if k_scales is not None:
        k_pages = dequantize_pages(k_pages, k_scales)
        v_pages = dequantize_pages(v_pages, v_scales)
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    k = k_pages[page_table].reshape(b, mb * bs, hkv, d)
    v = v_pages[page_table].reshape(b, mb * bs, hkv, d)
    k = jnp.repeat(k, group, axis=2)
    v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * softmax_scale
    pos = jnp.arange(mb * bs)
    s = jnp.where(pos[None, None, :] < kv_lens[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def dequantize_latent_pages(pages: jnp.ndarray, scales: jnp.ndarray
                            ) -> jnp.ndarray:
    """Dense dequant of a quantized LATENT pool [NB, bs, d] with per-row
    scalar scales [NB, bs] → fp32 (the latent row has no kv-head axis, so
    the scale is one scalar per (block, row) — `quantize_kv_rows` over a
    [..., d] row produces exactly this layout)."""
    return pages.astype(jnp.float32) * scales[..., None]


def paged_attention_latent_reference(
        q_lat: jnp.ndarray, q_pe: jnp.ndarray, lat_pages: jnp.ndarray,
        pe_pages: jnp.ndarray, page_table: jnp.ndarray,
        kv_lens: jnp.ndarray, w_v: jnp.ndarray,
        q_lens: Optional[jnp.ndarray] = None,
        softmax_scale: Optional[float] = None,
        lat_scales: Optional[jnp.ndarray] = None,
        pe_scales: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Pure-jnp oracle for the latent kernel: gathers the latent/pe runs
    DENSE through the page table (the pre-ISSUE-17 `mla_forward` decode
    path: `gather_pages_batched` + `kv_up` re-expansion), masks, and
    applies a plain softmax. Same signature and semantics as
    `paged_attention_latent` — q_lat is already ABSORBED through
    `kv_up`'s k_nope columns (and carries the YaRN mscale² if any), so
    scores are `q_lat·latᵀ + q_pe·peᵀ` and values re-expand dense as
    `lat @ w_v`. Quantized pools dequantize dense (per-row scalar
    scales)."""
    if softmax_scale is None:
        raise ValueError(
            "paged_attention_latent_reference requires softmax_scale — the "
            "MLA scale is 1/sqrt(dqk + dpe), which cannot be derived from "
            "the latent width")
    decode = q_lens is None
    if decode:
        q_lat = q_lat[:, None]
        q_pe = q_pe[:, None]
    b, s_q, nq, klat = q_lat.shape
    bs = lat_pages.shape[1]
    mb = page_table.shape[1]
    dv = w_v.shape[-1]
    if lat_scales is not None:
        lat_pages = dequantize_latent_pages(lat_pages, lat_scales)
        pe_pages = dequantize_latent_pages(pe_pages, pe_scales)
    lat = lat_pages[page_table].reshape(b, mb * bs, klat)
    pe = pe_pages[page_table].reshape(b, mb * bs, -1)
    s = (jnp.einsum("bqnk,bsk->bqns", q_lat.astype(jnp.float32),
                    lat.astype(jnp.float32))
         + jnp.einsum("bqnp,bsp->bqns", q_pe.astype(jnp.float32),
                      pe.astype(jnp.float32))) * softmax_scale
    pos = jnp.arange(mb * bs)
    if decode:
        mask = pos[None, None, :] < kv_lens[:, None, None]      # [B,1,S]
        mask = mask[:, :, None, :]
    else:
        abs_q = (kv_lens - q_lens)[:, None] + jnp.arange(s_q)[None, :]
        mask = ((pos[None, None, :] <= abs_q[:, :, None])
                & (pos[None, None, :] < kv_lens[:, None, None]))
        mask = mask[:, :, None, :]
    s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    v = jnp.einsum("bsk,knd->bsnd", lat.astype(jnp.float32),
                   w_v.astype(jnp.float32))
    out = jnp.einsum("bqns,bsnd->bqnd", p, v)
    out = out.astype(q_lat.dtype)
    return out[:, 0] if decode else out


# ---------------------------------------------------------------------------
# Page write / gather helpers (jit-able; `mode="drop"` keeps every invalid
# position out of the pool instead of clamping onto live blocks)
# ---------------------------------------------------------------------------


def write_prompt_pages(pages: jnp.ndarray, rows: jnp.ndarray,
                       table_row: jnp.ndarray, start, count) -> jnp.ndarray:
    """Scatter a prefill's new KV rows into the block pool.

    pages [L, num_blocks, block_size, ...]; rows [L, S_step, ...] where
    row i holds absolute sequence position start + i; table_row
    [max_blocks_per_seq]; count = number of valid rows (the rest are
    bucket padding and are dropped)."""
    nb, bs = pages.shape[1], pages.shape[2]
    s_step = rows.shape[1]
    pos = start + jnp.arange(s_step)
    blocks = jnp.take(table_row, pos // bs, mode="clip")
    blocks = jnp.where(jnp.arange(s_step) < count, blocks, nb)
    return pages.at[:, blocks, pos % bs].set(rows, mode="drop")


def append_token_pages(pages: jnp.ndarray, vals: jnp.ndarray,
                       page_table: jnp.ndarray, positions: jnp.ndarray,
                       active: jnp.ndarray) -> jnp.ndarray:
    """Write one decode token per slot at its own (block, offset).

    pages [num_blocks, block_size, ...]; vals [B, ...]; positions [B]
    (append position per slot); active [B] bool — inactive slots' page
    tables may reference freed blocks, so their writes are dropped, not
    clamped (the dense engine could write inactive rows harmlessly; a
    shared pool cannot)."""
    nb, bs = pages.shape[0], pages.shape[1]
    b = vals.shape[0]
    blocks = jnp.take_along_axis(page_table, (positions // bs)[:, None],
                                 axis=1)[:, 0]
    blocks = jnp.where(active, blocks, nb)
    return pages.at[blocks, positions % bs].set(vals, mode="drop")


def append_chunk_pages(pages: jnp.ndarray, vals: jnp.ndarray,
                       page_table: jnp.ndarray, starts: jnp.ndarray,
                       counts: jnp.ndarray, active: jnp.ndarray
                       ) -> jnp.ndarray:
    """Write a ragged multi-token run per slot (speculative verify /
    chunked prefill): row b's token i lands at absolute position
    starts[b] + i for i < counts[b]; padding rows and inactive slots are
    dropped, never clamped onto live blocks.

    pages [num_blocks, block_size, ...]; vals [B, S, ...]; starts/counts
    [B] int32; active [B] bool. counts[b] == 1 reduces to
    append_token_pages."""
    nb, bs = pages.shape[0], pages.shape[1]
    b, s = vals.shape[0], vals.shape[1]
    mb = page_table.shape[1]
    pos = starts[:, None] + jnp.arange(s)[None, :]           # [B, S]
    blocks = jnp.take_along_axis(
        page_table, jnp.clip(pos // bs, 0, mb - 1), axis=1)  # [B, S]
    valid = (jnp.arange(s)[None, :] < counts[:, None]) & active[:, None]
    blocks = jnp.where(valid, blocks, nb)
    flat = lambda x: x.reshape((b * s,) + x.shape[2:])  # noqa: E731
    return pages.at[flat(blocks), flat(pos % bs)].set(flat(vals),
                                                      mode="drop")


def gather_prefix_pages(pages: jnp.ndarray, table_row: jnp.ndarray,
                        num_blocks: int) -> jnp.ndarray:
    """Gather the first `num_blocks` (static) blocks of one slot into a
    contiguous run: pages [L, NB, bs, ...] → [L, num_blocks*bs, ...]
    (prefix-cache hits re-enter the dense bucketed prefill this way)."""
    sel = jnp.take(pages, table_row[:num_blocks], axis=1, mode="clip")
    return sel.reshape((pages.shape[0], num_blocks * pages.shape[2])
                       + pages.shape[3:])


def gather_pages_batched(pages: jnp.ndarray, page_table: jnp.ndarray
                         ) -> jnp.ndarray:
    """pages [NB, bs, ...] + table [B, MB] → [B, MB*bs, ...] (block order
    is sequence order; rows past a slot's length are garbage and must be
    masked by the caller). Used by the MLA paged decode, whose latent →
    kv_up reconstitution needs the contiguous latent run."""
    b, mb = page_table.shape
    bs = pages.shape[1]
    out = jnp.take(pages, page_table.reshape(-1), axis=0, mode="clip")
    return out.reshape((b, mb * bs) + pages.shape[2:])


# ---------------------------------------------------------------------------
# TP-shard eligibility (the placement itself lives in kernel_gen._tp_place)
# ---------------------------------------------------------------------------


def tp_paged_ineligible_reason(cfg, ctx) -> Optional[str]:
    """Why the paged kernels may NOT run sharded on ctx's tp axis —
    None when eligible, otherwise the FIRST failed predicate by name (so
    fallback logs say what to fix instead of a generic "ineligible").
    Standard layout: both head counts divide by tp so each shard owns
    whole, matched GQA groups (q head h reads kv head h // group —
    contiguous slicing of BOTH by tp preserves the grouping per shard,
    the same rule as the flash wrapper). MLA: the latent pool has no
    kv-head axis, so the shard axis is the latent COLUMN dim instead
    (kernel_gen._tp_place_latent) — eligibility is kv_lora_rank % tp."""
    if ctx is None:
        return "no mesh context (ctx is None)"
    if ctx.tp <= 1:
        return f"tp == {ctx.tp} (needs tp > 1 to shard)"
    if cfg.multi_latent_attention:
        if cfg.kv_lora_rank % ctx.tp:
            return (f"kv_lora_rank ({cfg.kv_lora_rank}) % tp ({ctx.tp}) "
                    f"!= 0 (the latent pool shards on latent columns)")
        return None
    if cfg.num_attention_heads % ctx.tp:
        return (f"num_attention_heads ({cfg.num_attention_heads}) % tp "
                f"({ctx.tp}) != 0")
    if cfg.num_query_groups % ctx.tp:
        return (f"num_query_groups ({cfg.num_query_groups}) % tp "
                f"({ctx.tp}) != 0 (shards must own whole GQA groups)")
    return None


def tp_paged_eligible(cfg, ctx) -> bool:
    """True when the paged kernels may run head-sharded on ctx's tp axis
    (see tp_paged_ineligible_reason for the predicate list — it names
    the specific failure for fallback logs)."""
    return tp_paged_ineligible_reason(cfg, ctx) is None
