"""Ragged paged-attention decode kernel (Pallas TPU) + page helpers.

vLLM-style paged KV serving ("Ragged Paged Attention", arXiv 2604.15464,
PAPERS.md): the decode cache lives in a shared block pool shaped
[num_blocks, block_size, Hkv, D]; each slot owns an ordered page table of
block ids, and one query token per active slot gathers K/V through its
table with an online softmax over VALID blocks only — no slot pays for
another slot's length, and admission is per-block instead of per-S_max
row (inference/paged_cache.py is the allocator).

Kernel shape choices mirror ops/pallas/flash_attention.py: fp32
accumulators, whole-block skip of out-of-length tiles, GQA via an
[Hkv, group, D] query reshape (q head h reads kv head h // group, the
same grouping attention.py uses), and `interpret=_interpret()` so the
kernel runs (and is tier-1 tested) on CPU. Page-table indirection uses
`pltpu.PrefetchScalarGridSpec`: the table and per-slot kv lengths are
scalar-prefetched so the BlockSpec index map can DMA block
`table[b, j]` directly from HBM — the kernel never materializes a
contiguous [B, S_max] cache.

A pure-jnp `paged_attention_reference` with the same signature is the
parity oracle for tests, and `write_prompt_pages` /
`append_token_pages` / `gather_pages*` are the jit-able scatter/gather
paths that replace the dense engine's host-side cache scatter.

TP sharding (ISSUE 9): GSPMD cannot partition a pallas_call, so — exactly
like the flash wrapper in transformer/attention.py — the tp-mesh serving
path places the kernels explicitly with a FULL-MANUAL shard_map over KV
heads: `paged_attention_decode_tp` / `paged_attention_multiquery_tp` run
the unmodified kernels on per-shard head slices (q heads and kv heads
slice contiguously together, so each shard owns matched GQA groups and
`group` is unchanged), with the page table and kv lengths replicated and
the K/V pools sharded on their Hkv dim — each device holds 1/tp of the
block pool and does 1/tp of the attention FLOPs/bytes. Eligibility is
`tp_paged_eligible` (heads divisible by tp, non-MLA pools).

Quantized KV (ISSUE 10, `k_scales`/`v_scales`): the pools may be stored
int8 with a per-(row, kv-head) fp32 scale pool [NB, bs, Hkv] living
alongside — rows quantize independently on insert (`quantize_kv_rows`),
so CoW copies, rewind, and stale-row overwrites need no re-scaling.
Every kernel grows a quantized path: the scale blocks ride the SAME
scalar-prefetched page-table indirection as the KV blocks (BlockSpec
index map `t[b, j]`), and each DMA'd int8 block dequantizes in-register
(one fp32 multiply per row×head) before the online-softmax update — no
bf16 pool is ever materialized. The jnp references take the same scales
and are the parity oracle; on CPU everything runs in interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def quantize_kv_rows(rows: jnp.ndarray):
    """Symmetric per-(row, head) int8 quantization of KV rows.

    rows [..., Hkv, D] → (int8 rows [..., Hkv, D], fp32 scales
    [..., Hkv]). Each (token, head) row quantizes independently over D —
    inserts never re-scale already-written rows, so partial blocks,
    copy-on-write copies, and speculative rewinds need no block-level
    bookkeeping. jit-able; fused into the engine's write-path jits."""
    r32 = rows.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(r32), axis=-1)
    scales = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(r32 / scales[..., None]), -127, 127)
    return q.astype(jnp.int8), scales.astype(jnp.float32)


def _dequant_block(k, ks):
    """[bs, Hkv, D] int8 block × [bs, Hkv] fp32 scales → fp32 block (the
    in-register dequant of one DMA'd page)."""
    return k.astype(jnp.float32) * ks[..., None]


# ---------------------------------------------------------------------------
# Decode kernel
# ---------------------------------------------------------------------------


def _decode_kernel(table_ref, lens_ref, q_ref, k_ref, v_ref, *rest,
                   scale, block_size, num_blocks_seq, hkv, group,
                   quantized=False):
    """Grid (B, max_blocks_per_seq); block j of slot b is DMA'd from page
    table_ref[b, j]. Online softmax over the ragged valid range
    [0, lens_ref[b]); fully-out-of-range blocks are skipped whole.

    quantized: k/v blocks arrive int8 with per-(row, head) fp32 scale
    blocks (ks_ref/vs_ref, fetched through the same page-table index
    map); dequant happens in-register on the fetched block."""
    if quantized:
        ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, acc, m_scr, l_scr = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    hq = hkv * group

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    kv_len = lens_ref[b]

    @pl.when(j * block_size < kv_len)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale          # [Hq, D]
        if quantized:
            k = _dequant_block(k_ref[0], ks_ref[0])       # [bs, Hkv, D]
            v = _dequant_block(v_ref[0], vs_ref[0])
        else:
            k = k_ref[0]                                  # [bs, Hkv, D]
            v = v_ref[0]
        d = q.shape[-1]
        q3 = q.reshape(hkv, group, d)
        k3 = jnp.swapaxes(k, 0, 1)                        # [Hkv, bs, D]
        v3 = jnp.swapaxes(v, 0, 1)
        s = jax.lax.dot_general(                          # [Hkv, g, bs]
            q3.astype(k3.dtype), k3,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)[0]
        valid = pos < kv_len                              # [bs]
        s = jnp.where(valid[None, None, :], s, _NEG_INF)
        s2 = s.reshape(hq, block_size)

        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1))
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(s2 - m_safe[:, None])
        p = jnp.where(valid[None, :], p, 0.0)
        corr = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        corr = jnp.where(m_prev <= _NEG_INF / 2, 0.0, corr)
        l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=1)
        p3 = p.reshape(hkv, group, block_size)
        pv = jax.lax.dot_general(                         # [Hkv, g, D]
            p3.astype(v3.dtype), v3,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr[:, None] + pv.reshape(hq, d)
        m_scr[:, 0] = m_new

    @pl.when(j == num_blocks_seq - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, 0], 1e-20)
        o_ref[0] = (acc[:] / l[:, None]).astype(o_ref.dtype)


def paged_attention_decode(q: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray, page_table: jnp.ndarray,
                           kv_lens: jnp.ndarray,
                           softmax_scale: Optional[float] = None,
                           k_scales: Optional[jnp.ndarray] = None,
                           v_scales: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """One-token-per-slot ragged paged attention.

    q [B, Hq, D]; k_pages/v_pages [num_blocks, block_size, Hkv, D];
    page_table [B, max_blocks_per_seq] int32 (entries beyond a slot's
    allocation may be anything in range — they are masked, not read for
    math); kv_lens [B] int32 valid kv positions per slot (>= 1).
    k_scales/v_scales [num_blocks, block_size, Hkv] fp32: present iff the
    pools are int8 (quantize_kv_rows layout) — the scale blocks ride the
    same page-table indirection and dequant runs in-kernel.
    Returns [B, Hq, D]."""
    b, hq, d = q.shape
    nb, bs, hkv, _ = k_pages.shape
    mb = page_table.shape[1]
    group = hq // hkv
    quantized = k_scales is not None
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)

    kernel = functools.partial(
        _decode_kernel, scale=float(softmax_scale), block_size=bs,
        num_blocks_seq=mb, hkv=hkv, group=group, quantized=quantized)

    kv_spec = pl.BlockSpec((1, bs, hkv, d),
                           lambda b_, j, t, l: (t[b_, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, hq, d), lambda b_, j, t, l: (b_, 0, 0)),
        kv_spec, kv_spec,
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        sc_spec = pl.BlockSpec((1, bs, hkv),
                               lambda b_, j, t, l: (t[b_, j], 0, 0))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hq, d), lambda b_, j, t, l: (b_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hq, d), jnp.float32),
            pltpu.VMEM((hq, 1), jnp.float32),
            pltpu.VMEM((hq, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, d), q.dtype),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32),
      *operands)


# ---------------------------------------------------------------------------
# Multi-query ragged kernel (speculative verify + chunked prefill)
# ---------------------------------------------------------------------------


def _multiquery_kernel(table_ref, lens_ref, qlens_ref, q_ref, k_ref, v_ref,
                       *rest, scale, block_size,
                       num_blocks_seq, hkv, group, s_q, quantized=False):
    """Grid (B, max_blocks_per_seq): per-request ragged q_len ∈ [1, S_q]
    queries against the page table — the multi-query generalization of
    `_decode_kernel` (arXiv 2604.15464's unified prefill/decode
    primitive). Local query i sits at absolute position
    kv_len - q_len + i and attends kv positions <= that (causal within
    the new tail, full attention to the context); padded query rows
    (i >= q_len) compute garbage over the valid range and are discarded
    by the caller. At q_len == 1 the math reduces to the decode kernel's
    exact block/accumulator order.

    quantized: int8 k/v blocks + per-(row, head) fp32 scale blocks
    (ks_ref/vs_ref), dequantized in-register like `_decode_kernel`."""
    if quantized:
        ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, acc, m_scr, l_scr = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    hq = hkv * group

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    kv_len = lens_ref[b]
    q_len = qlens_ref[b]
    q_start = kv_len - q_len          # absolute position of local query 0

    @pl.when(j * block_size < kv_len)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale      # [S_q, Hq, D]
        if quantized:
            k = _dequant_block(k_ref[0], ks_ref[0])   # [bs, Hkv, D]
            v = _dequant_block(v_ref[0], vs_ref[0])
        else:
            k = k_ref[0]                              # [bs, Hkv, D]
            v = v_ref[0]
        d = q.shape[-1]
        # [Hkv, S_q*group, D] with inner index i = s*group + g (so row
        # i's query position is i // group after unfolding back through
        # the [S_q, Hq] layout below).
        q3 = jnp.transpose(q.reshape(s_q, hkv, group, d),
                           (1, 0, 2, 3)).reshape(hkv, s_q * group, d)
        k3 = jnp.swapaxes(k, 0, 1)                    # [Hkv, bs, D]
        v3 = jnp.swapaxes(v, 0, 1)
        s = jax.lax.dot_general(                      # [Hkv, S_q*g, bs]
            q3.astype(k3.dtype), k3,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)[0]
        row_q = jax.lax.broadcasted_iota(
            jnp.int32, (s_q * group, 1), 0)[:, 0] // group
        abs_q = q_start + row_q                        # [S_q*group]
        valid = ((pos[None, :] <= abs_q[:, None])
                 & (pos[None, :] < kv_len))            # [S_q*g, bs]
        s = jnp.where(valid[None], s, _NEG_INF)
        # [S_q*Hq, bs] with row = s*hq + h (h = kvh*group + g).
        s2 = jnp.transpose(
            s.reshape(hkv, s_q, group, block_size),
            (1, 0, 2, 3)).reshape(s_q * hq, block_size)
        valid2 = jnp.transpose(
            jnp.broadcast_to(valid.reshape(1, s_q, group, block_size),
                             (hkv, s_q, group, block_size)),
            (1, 0, 2, 3)).reshape(s_q * hq, block_size)

        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1))
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(s2 - m_safe[:, None])
        p = jnp.where(valid2, p, 0.0)
        corr = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        corr = jnp.where(m_prev <= _NEG_INF / 2, 0.0, corr)
        l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=1)
        p3 = jnp.transpose(
            p.reshape(s_q, hkv, group, block_size),
            (1, 0, 2, 3)).reshape(hkv, s_q * group, block_size)
        pv = jax.lax.dot_general(                      # [Hkv, S_q*g, D]
            p3.astype(v3.dtype), v3,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        pv2 = jnp.transpose(
            pv.reshape(hkv, s_q, group, d),
            (1, 0, 2, 3)).reshape(s_q * hq, d)
        acc[:] = acc[:] * corr[:, None] + pv2
        m_scr[:, 0] = m_new

    @pl.when(j == num_blocks_seq - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, 0], 1e-20)
        a = acc[:]
        o_ref[0] = (a / l[:, None]).reshape(
            s_q, hq, a.shape[-1]).astype(o_ref.dtype)


def paged_attention_multiquery(q: jnp.ndarray, k_pages: jnp.ndarray,
                               v_pages: jnp.ndarray,
                               page_table: jnp.ndarray,
                               kv_lens: jnp.ndarray, q_lens: jnp.ndarray,
                               softmax_scale: Optional[float] = None,
                               k_scales: Optional[jnp.ndarray] = None,
                               v_scales: Optional[jnp.ndarray] = None
                               ) -> jnp.ndarray:
    """Ragged multi-query paged attention (speculative verify / chunked
    prefill).

    q [B, S_q, Hq, D] — per-request the first q_lens[b] rows are real
    queries at absolute positions kv_lens[b]-q_lens[b] .. kv_lens[b]-1
    (their K/V must already be written into the pages); the rest are
    padding whose outputs are garbage and must be discarded. kv_lens [B]
    counts ALL valid kv positions including the new tail (>= q_lens >=
    1). k_scales/v_scales [NB, bs, Hkv] fp32 mark int8 pools (see
    paged_attention_decode). Returns [B, S_q, Hq, D]."""
    b, s_q, hq, d = q.shape
    nb, bs, hkv, _ = k_pages.shape
    mb = page_table.shape[1]
    group = hq // hkv
    quantized = k_scales is not None
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)

    kernel = functools.partial(
        _multiquery_kernel, scale=float(softmax_scale), block_size=bs,
        num_blocks_seq=mb, hkv=hkv, group=group, s_q=s_q,
        quantized=quantized)

    kv_spec = pl.BlockSpec((1, bs, hkv, d),
                           lambda b_, j, t, l, ql: (t[b_, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, s_q, hq, d),
                     lambda b_, j, t, l, ql: (b_, 0, 0, 0)),
        kv_spec, kv_spec,
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        sc_spec = pl.BlockSpec((1, bs, hkv),
                               lambda b_, j, t, l, ql: (t[b_, j], 0, 0))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, s_q, hq, d),
                               lambda b_, j, t, l, ql: (b_, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((s_q * hq, d), jnp.float32),
            pltpu.VMEM((s_q * hq, 1), jnp.float32),
            pltpu.VMEM((s_q * hq, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s_q, hq, d), q.dtype),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32),
      q_lens.astype(jnp.int32), *operands)


def dequantize_pages(pages: jnp.ndarray, scales: jnp.ndarray
                     ) -> jnp.ndarray:
    """Dense dequant of an int8 pool [..., bs, Hkv, D] with scales
    [..., bs, Hkv] → fp32 (references, prefix-hit gathers, A/B
    baselines — NOT the kernel path, which dequantizes per block)."""
    return pages.astype(jnp.float32) * scales[..., None]


def paged_attention_multiquery_reference(
        q: jnp.ndarray, k_pages: jnp.ndarray, v_pages: jnp.ndarray,
        page_table: jnp.ndarray, kv_lens: jnp.ndarray, q_lens: jnp.ndarray,
        softmax_scale: Optional[float] = None,
        k_scales: Optional[jnp.ndarray] = None,
        v_scales: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Pure-jnp oracle for the multi-query kernel (gathers dense,
    masks per-(query, kv) causally; int8 pools dequantize dense)."""
    b, s_q, hq, d = q.shape
    nb, bs, hkv, _ = k_pages.shape
    mb = page_table.shape[1]
    group = hq // hkv
    if k_scales is not None:
        k_pages = dequantize_pages(k_pages, k_scales)
        v_pages = dequantize_pages(v_pages, v_scales)
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    k = k_pages[page_table].reshape(b, mb * bs, hkv, d)
    v = v_pages[page_table].reshape(b, mb * bs, hkv, d)
    k = jnp.repeat(k, group, axis=2)
    v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * softmax_scale
    pos = jnp.arange(mb * bs)
    abs_q = (kv_lens - q_lens)[:, None] + jnp.arange(s_q)[None, :]  # [B,Sq]
    mask = ((pos[None, None, :] <= abs_q[:, :, None])
            & (pos[None, None, :] < kv_lens[:, None, None]))
    s = jnp.where(mask[:, :, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_attention_reference(q: jnp.ndarray, k_pages: jnp.ndarray,
                              v_pages: jnp.ndarray, page_table: jnp.ndarray,
                              kv_lens: jnp.ndarray,
                              softmax_scale: Optional[float] = None,
                              k_scales: Optional[jnp.ndarray] = None,
                              v_scales: Optional[jnp.ndarray] = None
                              ) -> jnp.ndarray:
    """Pure-jnp oracle with the same signature (gathers dense, masks;
    int8 pools dequantize dense)."""
    b, hq, d = q.shape
    nb, bs, hkv, _ = k_pages.shape
    mb = page_table.shape[1]
    group = hq // hkv
    if k_scales is not None:
        k_pages = dequantize_pages(k_pages, k_scales)
        v_pages = dequantize_pages(v_pages, v_scales)
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    k = k_pages[page_table].reshape(b, mb * bs, hkv, d)
    v = v_pages[page_table].reshape(b, mb * bs, hkv, d)
    k = jnp.repeat(k, group, axis=2)
    v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * softmax_scale
    pos = jnp.arange(mb * bs)
    s = jnp.where(pos[None, None, :] < kv_lens[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Page write / gather helpers (jit-able; `mode="drop"` keeps every invalid
# position out of the pool instead of clamping onto live blocks)
# ---------------------------------------------------------------------------


def write_prompt_pages(pages: jnp.ndarray, rows: jnp.ndarray,
                       table_row: jnp.ndarray, start, count) -> jnp.ndarray:
    """Scatter a prefill's new KV rows into the block pool.

    pages [L, num_blocks, block_size, ...]; rows [L, S_step, ...] where
    row i holds absolute sequence position start + i; table_row
    [max_blocks_per_seq]; count = number of valid rows (the rest are
    bucket padding and are dropped)."""
    nb, bs = pages.shape[1], pages.shape[2]
    s_step = rows.shape[1]
    pos = start + jnp.arange(s_step)
    blocks = jnp.take(table_row, pos // bs, mode="clip")
    blocks = jnp.where(jnp.arange(s_step) < count, blocks, nb)
    return pages.at[:, blocks, pos % bs].set(rows, mode="drop")


def append_token_pages(pages: jnp.ndarray, vals: jnp.ndarray,
                       page_table: jnp.ndarray, positions: jnp.ndarray,
                       active: jnp.ndarray) -> jnp.ndarray:
    """Write one decode token per slot at its own (block, offset).

    pages [num_blocks, block_size, ...]; vals [B, ...]; positions [B]
    (append position per slot); active [B] bool — inactive slots' page
    tables may reference freed blocks, so their writes are dropped, not
    clamped (the dense engine could write inactive rows harmlessly; a
    shared pool cannot)."""
    nb, bs = pages.shape[0], pages.shape[1]
    b = vals.shape[0]
    blocks = jnp.take_along_axis(page_table, (positions // bs)[:, None],
                                 axis=1)[:, 0]
    blocks = jnp.where(active, blocks, nb)
    return pages.at[blocks, positions % bs].set(vals, mode="drop")


def append_chunk_pages(pages: jnp.ndarray, vals: jnp.ndarray,
                       page_table: jnp.ndarray, starts: jnp.ndarray,
                       counts: jnp.ndarray, active: jnp.ndarray
                       ) -> jnp.ndarray:
    """Write a ragged multi-token run per slot (speculative verify /
    chunked prefill): row b's token i lands at absolute position
    starts[b] + i for i < counts[b]; padding rows and inactive slots are
    dropped, never clamped onto live blocks.

    pages [num_blocks, block_size, ...]; vals [B, S, ...]; starts/counts
    [B] int32; active [B] bool. counts[b] == 1 reduces to
    append_token_pages."""
    nb, bs = pages.shape[0], pages.shape[1]
    b, s = vals.shape[0], vals.shape[1]
    mb = page_table.shape[1]
    pos = starts[:, None] + jnp.arange(s)[None, :]           # [B, S]
    blocks = jnp.take_along_axis(
        page_table, jnp.clip(pos // bs, 0, mb - 1), axis=1)  # [B, S]
    valid = (jnp.arange(s)[None, :] < counts[:, None]) & active[:, None]
    blocks = jnp.where(valid, blocks, nb)
    flat = lambda x: x.reshape((b * s,) + x.shape[2:])  # noqa: E731
    return pages.at[flat(blocks), flat(pos % bs)].set(flat(vals),
                                                      mode="drop")


def gather_prefix_pages(pages: jnp.ndarray, table_row: jnp.ndarray,
                        num_blocks: int) -> jnp.ndarray:
    """Gather the first `num_blocks` (static) blocks of one slot into a
    contiguous run: pages [L, NB, bs, ...] → [L, num_blocks*bs, ...]
    (prefix-cache hits re-enter the dense bucketed prefill this way)."""
    sel = jnp.take(pages, table_row[:num_blocks], axis=1, mode="clip")
    return sel.reshape((pages.shape[0], num_blocks * pages.shape[2])
                       + pages.shape[3:])


def gather_pages_batched(pages: jnp.ndarray, page_table: jnp.ndarray
                         ) -> jnp.ndarray:
    """pages [NB, bs, ...] + table [B, MB] → [B, MB*bs, ...] (block order
    is sequence order; rows past a slot's length are garbage and must be
    masked by the caller). Used by the MLA paged decode, whose latent →
    kv_up reconstitution needs the contiguous latent run."""
    b, mb = page_table.shape
    bs = pages.shape[1]
    out = jnp.take(pages, page_table.reshape(-1), axis=0, mode="clip")
    return out.reshape((b, mb * bs) + pages.shape[2:])


# ---------------------------------------------------------------------------
# TP-sharded kernel placement (full-manual shard_map over KV heads)
# ---------------------------------------------------------------------------


def tp_paged_eligible(cfg, ctx) -> bool:
    """True when the paged kernels may run head-sharded on ctx's tp axis:
    tp > 1, standard (non-MLA) paged layout, and both head counts divide
    by tp so each shard owns whole, matched GQA groups (q head h reads kv
    head h // group — contiguous slicing of BOTH by tp preserves the
    grouping per shard, the same eligibility rule as the flash
    wrapper)."""
    return (ctx is not None and ctx.tp > 1
            and not cfg.multi_latent_attention
            and cfg.num_attention_heads % ctx.tp == 0
            and cfg.num_query_groups % ctx.tp == 0)


def _tp_specs(mesh):
    from jax.sharding import PartitionSpec as P
    from megatronapp_tpu.config.parallel_config import TP_AXIS
    head = P(None, TP_AXIS, None)             # q/out [B, Hq, D]
    pages = P(None, None, TP_AXIS, None)      # pools [NB, bs, Hkv, D]
    scales = P(None, None, TP_AXIS)           # scale pools [NB, bs, Hkv]
    rep2, rep1 = P(None, None), P(None)
    return head, pages, scales, rep2, rep1


def paged_attention_decode_tp(q: jnp.ndarray, k_pages: jnp.ndarray,
                              v_pages: jnp.ndarray,
                              page_table: jnp.ndarray,
                              kv_lens: jnp.ndarray, mesh,
                              softmax_scale: Optional[float] = None,
                              k_scales: Optional[jnp.ndarray] = None,
                              v_scales: Optional[jnp.ndarray] = None
                              ) -> jnp.ndarray:
    """`paged_attention_decode` head-sharded over the tp axis of `mesh`.

    q [B, Hq, D] sharded on heads, pools [NB, bs, Hkv, D] sharded on
    Hkv, page table + kv lengths replicated; each shard runs the
    unmodified kernel on its own GQA groups against its 1/tp slice of
    the block pool. int8 pools shard their scale pools on Hkv alongside
    — a quantized shard owns exactly its heads' rows AND scales. Output
    is [B, Hq, D] head-sharded (callers gather / constrain as
    needed)."""
    from megatronapp_tpu.parallel.collectives import shard_map_compat
    head, pages, scales, rep2, rep1 = _tp_specs(mesh)
    if softmax_scale is None:
        softmax_scale = 1.0 / (q.shape[-1] ** 0.5)

    # Full-manual placement of the pallas decode kernel — purely local
    # per (head, pool) shard, no collectives; tp_paged_eligible callers
    # gate on no ambient manual axes.
    if k_scales is not None:
        def body_q(q_, k_, v_, t_, l_, ks_, vs_):
            return paged_attention_decode(q_, k_, v_, t_, l_,
                                          softmax_scale=softmax_scale,
                                          k_scales=ks_, v_scales=vs_)

        # manual-ok: full-manual kernel placement, see note above
        return shard_map_compat(
            body_q, mesh,
            in_specs=(head, pages, pages, rep2, rep1, scales, scales),
            out_specs=head)(q, k_pages, v_pages, page_table, kv_lens,
                            k_scales, v_scales)

    def body(q_, k_, v_, t_, l_):
        return paged_attention_decode(q_, k_, v_, t_, l_,
                                      softmax_scale=softmax_scale)

    # manual-ok: full-manual kernel placement, see note above
    return shard_map_compat(
        body, mesh, in_specs=(head, pages, pages, rep2, rep1),
        out_specs=head)(q, k_pages, v_pages, page_table, kv_lens)


def paged_attention_multiquery_tp(q: jnp.ndarray, k_pages: jnp.ndarray,
                                  v_pages: jnp.ndarray,
                                  page_table: jnp.ndarray,
                                  kv_lens: jnp.ndarray,
                                  q_lens: jnp.ndarray, mesh,
                                  softmax_scale: Optional[float] = None,
                                  k_scales: Optional[jnp.ndarray] = None,
                                  v_scales: Optional[jnp.ndarray] = None
                                  ) -> jnp.ndarray:
    """`paged_attention_multiquery` head-sharded over the tp axis of
    `mesh` (speculative verify / chunked prefill on a tp serving mesh).
    q [B, S_q, Hq, D] sharded on Hq; pools on Hkv (int8 pools: scale
    pools sharded alongside); table/lens/q_lens replicated."""
    from jax.sharding import PartitionSpec as P
    from megatronapp_tpu.config.parallel_config import TP_AXIS
    from megatronapp_tpu.parallel.collectives import shard_map_compat
    _, pages, scales, rep2, rep1 = _tp_specs(mesh)
    head4 = P(None, None, TP_AXIS, None)      # q/out [B, S_q, Hq, D]
    if softmax_scale is None:
        softmax_scale = 1.0 / (q.shape[-1] ** 0.5)

    # Full-manual placement of the pallas multi-query kernel — purely
    # local per (head, pool) shard, no collectives; tp_paged_eligible
    # callers gate on no ambient manual axes.
    if k_scales is not None:
        def body_q(q_, k_, v_, t_, l_, ql_, ks_, vs_):
            return paged_attention_multiquery(q_, k_, v_, t_, l_, ql_,
                                              softmax_scale=softmax_scale,
                                              k_scales=ks_, v_scales=vs_)

        # manual-ok: full-manual kernel placement, see note above
        return shard_map_compat(
            body_q, mesh,
            in_specs=(head4, pages, pages, rep2, rep1, rep1, scales,
                      scales),
            out_specs=head4)(q, k_pages, v_pages, page_table, kv_lens,
                             q_lens, k_scales, v_scales)

    def body(q_, k_, v_, t_, l_, ql_):
        return paged_attention_multiquery(q_, k_, v_, t_, l_, ql_,
                                          softmax_scale=softmax_scale)

    # manual-ok: full-manual kernel placement, see note above
    return shard_map_compat(
        body, mesh, in_specs=(head4, pages, pages, rep2, rep1, rep1),
        out_specs=head4)(q, k_pages, v_pages, page_table, kv_lens, q_lens)
