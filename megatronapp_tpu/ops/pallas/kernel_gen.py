"""Paged-attention kernel GENERATOR + fused (megakernel) decode kernels.

ISSUE 11 tentpole. Before this module, ops/pallas/paged_attention.py
hand-wrote four kernel variants (decode / multiquery × plain / tp) × two
KV dtypes (bf16, int8 dequant-in-register) — eight bodies that had to be
edited in lockstep. Every variant differed from the others along exactly
three axes, so the bodies are now EMITTED from a spec instead of copied:

  - ``ragged``     one query row per slot (decode) vs a per-request
                   ragged q_len ∈ [1, S_q] window (speculative verify /
                   chunked prefill) with the causal-tail mask and the
                   q_lens scalar-prefetch ref;
  - ``quantized``  bf16 pools vs int8 pools whose per-(row, kv-head)
                   fp32 scale blocks ride the SAME page-table BlockSpec
                   index map and dequantize in-register;
  - tp head-shard  plain single-device placement vs a FULL-MANUAL
                   shard_map over KV heads (``mesh=`` — each shard runs
                   the emitted kernel on its matched GQA groups against
                   its 1/tp slice of the pool).

``paged_attention`` is the one entry point; the legacy names in
paged_attention.py are thin wrappers over it. The emitted body is
op-for-op the legacy body (the ragged=False specialization collapses the
window transposes exactly the way the hand-written decode kernel did),
so generated kernels are BITWISE-identical to the variants they replace
— pinned in tests/test_kernel_gen.py against frozen copies of the old
bodies across {bf16, int8} × {tp1, tp2} × {q_len 1, ragged} ×
{GQA, MHA}. New variants (fp8 pools, MLA latent layouts, token-tree
masks) are parameters here, not new copies.

The second half of the module is the FUSED DECODE STEP (megakernel
direction, *Event Tensor* arXiv 2604.13327): at decode batch sizes the
per-token step is dispatch-dominated (PERF.md: 35.7% MFU full-step vs
63.6% one layer body), so the dispatch-heavy tail of the layer body is
folded into three fat Pallas kernels —

  - ``fused_qkv``      RMS/LayerNorm + QKV projection + (optional) QK
                       layernorm + rope, one kernel per layer entry;
  - ``fused_out_proj`` attention epilogue: GQA head-flatten + out
                       projection + bias + residual add;
  - ``fused_mlp``      pre-MLP norm + fc1 + activation (incl. gated) +
                       fc2 + bias + residual add.

``fused_layer_decode`` assembles them around the generated paged
attention kernel; transformer/block.py dispatches it for the s == 1
paged decode path when ``cfg.megakernel_decode`` is on
(DynamicInferenceEngine(fused_decode=True) / --megakernel-decode).
Greedy streams are pinned token-exact against the unfused engine; the
win is gated off the COMPILED module (utils/dispatch.py counts
executable fusions/custom-calls per decode step), not wall time — the
TPU tunnel is down, so on-chip wall numbers wait for the chip
(PERF.md round-15).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger(__name__)

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dequant_block(k, ks):
    """[bs, Hkv, D] int8 block × [bs, Hkv] fp32 scales → fp32 block (the
    in-register dequant of one DMA'd page)."""
    return k.astype(jnp.float32) * ks[..., None]


# ---------------------------------------------------------------------------
# The generator: one spec → one emitted ragged-paged-attention body
# ---------------------------------------------------------------------------


QUANT_DTYPES = {
    # THE canonical quantized-KV storage registry: quant_dtype axis of
    # PagedSpec → (page jnp dtype, TPU min tile (sublane, lane) for the
    # KV block windows, symmetric quantization range bound qmax). Both
    # 1-byte formats want the (32, 128) layout on-chip; bf16 pools tile
    # (16, 128). The tile is PARAMETERIZED (not hard-coded in the body)
    # so the fp8 (32, 128) layout can be flipped on and validated when
    # the chip returns — interpret mode (CPU) imposes no tiling, so the
    # same spec runs everywhere today. quantize_kv_rows derives its
    # range from qmax, and the serving-facing KV_CACHE_DTYPES registry
    # (inference/paged_cache.py) builds its quantized entries FROM this
    # map — one place to add a storage dtype end-to-end.
    "int8": (jnp.int8, (32, 128), 127.0),
    "fp8": (jnp.float8_e4m3fn, (32, 128), 448.0),
}


def quant_dtype_of(pages_dtype) -> Optional[str]:
    """Map a page pool's storage dtype to the PagedSpec quant_dtype axis
    (None = unquantized compute-dtype pool)."""
    for name, (dt, _, _) in QUANT_DTYPES.items():
        if jnp.dtype(pages_dtype) == jnp.dtype(dt):
            return name
    return None


def quant_qmax_of(pages_dtype) -> float:
    """Symmetric quantization range bound for a registered quantized
    page dtype (127 int8, 448 e4m3)."""
    name = quant_dtype_of(pages_dtype)
    if name is None:
        raise ValueError(
            f"{pages_dtype} is not a registered quantized KV storage "
            f"dtype ({sorted(QUANT_DTYPES)})")
    return QUANT_DTYPES[name][2]


def default_kv_tile(quant_dtype: Optional[str]):
    """Min TPU tile (sublane, lane) of the KV block windows for this
    storage dtype — the shape knob an on-chip tuning pass flips."""
    if quant_dtype is None:
        return (16, 128)
    return QUANT_DTYPES[quant_dtype][1]


@dataclasses.dataclass(frozen=True)
class PagedSpec:
    """Everything that selects a paged-attention kernel variant.

    ragged=False requires s_q == 1 (the decode shape); ragged=True adds
    the q_lens scalar-prefetch ref and the causal tail mask over the
    [1, S_q] window. quant_dtype ("int8" | "fp8" | None) adds the
    scale-block refs and the in-register dequant of each DMA'd block —
    the dequant body (cast to fp32 × per-(row, head) scale) is shared by
    both quantized formats, so a new storage dtype is a registry entry
    (QUANT_DTYPES), not a new body. kv_tile is the (sublane, lane) min
    tile of the KV block windows (dtype-dependent on TPU — fp8/int8 want
    (32, 128)); interpret mode ignores it, and paged_attention derives
    the per-dtype default, so it only needs touching for on-chip layout
    experiments. The tp head-shard axis is NOT part of the body spec —
    sharding is pure placement (``paged_attention(..., mesh=)`` wraps
    the same emitted kernel in a full-manual shard_map)."""

    ragged: bool
    quant_dtype: Optional[str]
    s_q: int
    block_size: int
    num_blocks_seq: int
    hkv: int
    group: int
    scale: float
    kv_tile: tuple = (16, 128)
    # MLA latent layout (ISSUE 17): pages hold [block, klat] latent +
    # [block, dpe] roped-key blocks with NO per-head axis; hkv carries
    # the QUERY head count (every head attends the one shared latent,
    # group == 1) and the kernel contracts q_lat · latent^T + q_pe ·
    # k_pe^T directly, re-expanding the value path per-tile through
    # kv_up's v columns ([klat, nq, dv] kernel operand).
    latent: bool = False
    klat: int = 0
    dpe: int = 0
    dv: int = 0

    @property
    def quantized(self) -> bool:
        return self.quant_dtype is not None

    def __post_init__(self):
        if not self.ragged and self.s_q != 1:
            raise ValueError(
                f"non-ragged (decode) kernels are single-query: s_q="
                f"{self.s_q} requires ragged=True (pass q_lens)")
        if self.quant_dtype is not None \
                and self.quant_dtype not in QUANT_DTYPES:
            raise ValueError(
                f"quant_dtype must be one of {sorted(QUANT_DTYPES)} or "
                f"None, got {self.quant_dtype!r}")
        if len(self.kv_tile) != 2 or self.kv_tile[1] % 128:
            raise ValueError(
                f"kv_tile must be (sublane, lane) with lane a multiple "
                f"of 128, got {self.kv_tile!r}")
        if self.latent:
            if self.klat <= 0 or self.dpe <= 0 or self.dv <= 0:
                raise ValueError(
                    f"latent specs need klat/dpe/dv > 0, got "
                    f"({self.klat}, {self.dpe}, {self.dv})")
            if self.group != 1:
                raise ValueError(
                    "latent specs have no GQA grouping (every query "
                    f"head shares the one latent row): group="
                    f"{self.group} must be 1, with hkv carrying the "
                    "query head count")


def emit_paged_kernel(spec: PagedSpec):
    """Emit the kernel body for `spec`.

    Grid (B, max_blocks_per_seq); block j of slot b is DMA'd from page
    table[b, j] (scalar-prefetched index map). Online softmax over the
    ragged valid range [0, lens[b]); fully-out-of-range blocks are
    skipped whole. Ragged kernels additionally mask each local query row
    i (absolute position kv_len - q_len + i) causally within the new
    tail; at q_len == 1 the math collapses to the decode body's exact
    block/accumulator order — the two legacy variants were the
    ragged=False / ragged=True points of this one template."""
    if spec.latent:
        return emit_latent_kernel(spec)
    bs = spec.block_size
    mbs = spec.num_blocks_seq
    hkv, group, s_q = spec.hkv, spec.group, spec.s_q
    hq = hkv * group
    ragged, quantized = spec.ragged, spec.quantized
    scale = spec.scale

    def kernel(*refs):
        if ragged:
            table_ref, lens_ref, qlens_ref = refs[:3]
            rest = refs[3:]
        else:
            table_ref, lens_ref = refs[:2]
            rest = refs[2:]
        del table_ref  # indirection is consumed by the BlockSpec index maps
        q_ref, k_ref, v_ref = rest[:3]
        rest = rest[3:]
        if quantized:
            ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
        else:
            o_ref, acc, m_scr, l_scr = rest
        b = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            acc[:] = jnp.zeros_like(acc)
            m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr)

        kv_len = lens_ref[b]
        if ragged:
            q_len = qlens_ref[b]
            q_start = kv_len - q_len   # absolute position of local query 0

        @pl.when(j * bs < kv_len)
        def _compute():
            q = q_ref[0].astype(jnp.float32) * scale
            if quantized:
                k = _dequant_block(k_ref[0], ks_ref[0])   # [bs, Hkv, D]
                v = _dequant_block(v_ref[0], vs_ref[0])
            else:
                k = k_ref[0]                              # [bs, Hkv, D]
                v = v_ref[0]
            d = q.shape[-1]
            if ragged:
                # [Hkv, S_q*group, D] with inner index i = s*group + g
                # (row i's query position is i // group after unfolding
                # back through the [S_q, Hq] layout below).
                q3 = jnp.transpose(q.reshape(s_q, hkv, group, d),
                                   (1, 0, 2, 3)).reshape(hkv, s_q * group,
                                                         d)
            else:
                q3 = q.reshape(hkv, group, d)
            k3 = jnp.swapaxes(k, 0, 1)                    # [Hkv, bs, D]
            v3 = jnp.swapaxes(v, 0, 1)
            s = jax.lax.dot_general(                      # [Hkv, rows, bs]
                q3.astype(k3.dtype), k3,
                (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            pos = j * bs + jax.lax.broadcasted_iota(
                jnp.int32, (1, bs), 1)[0]
            if ragged:
                row_q = jax.lax.broadcasted_iota(
                    jnp.int32, (s_q * group, 1), 0)[:, 0] // group
                abs_q = q_start + row_q                   # [S_q*group]
                valid = ((pos[None, :] <= abs_q[:, None])
                         & (pos[None, :] < kv_len))       # [S_q*g, bs]
                s = jnp.where(valid[None], s, _NEG_INF)
                # [S_q*Hq, bs] with row = s*hq + h (h = kvh*group + g).
                s2 = jnp.transpose(
                    s.reshape(hkv, s_q, group, bs),
                    (1, 0, 2, 3)).reshape(s_q * hq, bs)
                p_mask = jnp.transpose(
                    jnp.broadcast_to(valid.reshape(1, s_q, group, bs),
                                     (hkv, s_q, group, bs)),
                    (1, 0, 2, 3)).reshape(s_q * hq, bs)
            else:
                valid = pos < kv_len                      # [bs]
                s = jnp.where(valid[None, None, :], s, _NEG_INF)
                s2 = s.reshape(hq, bs)
                p_mask = valid[None, :]

            m_prev = m_scr[:, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1))
            m_safe = jnp.maximum(m_new, _NEG_INF / 2)
            p = jnp.exp(s2 - m_safe[:, None])
            p = jnp.where(p_mask, p, 0.0)
            corr = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
            corr = jnp.where(m_prev <= _NEG_INF / 2, 0.0, corr)
            l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=1)
            if ragged:
                p3 = jnp.transpose(
                    p.reshape(s_q, hkv, group, bs),
                    (1, 0, 2, 3)).reshape(hkv, s_q * group, bs)
            else:
                p3 = p.reshape(hkv, group, bs)
            pv = jax.lax.dot_general(                     # [Hkv, rows, D]
                p3.astype(v3.dtype), v3,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            if ragged:
                pv2 = jnp.transpose(
                    pv.reshape(hkv, s_q, group, d),
                    (1, 0, 2, 3)).reshape(s_q * hq, d)
            else:
                pv2 = pv.reshape(hq, d)
            acc[:] = acc[:] * corr[:, None] + pv2
            m_scr[:, 0] = m_new

        @pl.when(j == mbs - 1)
        def _finalize():
            l = jnp.maximum(l_scr[:, 0], 1e-20)
            if ragged:
                a = acc[:]
                o_ref[0] = (a / l[:, None]).reshape(
                    s_q, hq, a.shape[-1]).astype(o_ref.dtype)
            else:
                o_ref[0] = (acc[:] / l[:, None]).astype(o_ref.dtype)

    return kernel


def emit_latent_kernel(spec: PagedSpec):
    """Emit the MLA latent-space body for a latent `spec` (ISSUE 17).

    Same grid / online-softmax / causal-tail scaffolding as the dense
    template, but the pool blocks are the COMPRESSED run ([bs, klat]
    latent + [bs, dpe] roped shared key, no per-head axis) and the
    score contraction runs directly in latent space: the caller absorbs
    q_nope through kv_up's k_nope columns so block scores are
    q_lat · latent^T + q_pe · k_pe^T. The value path re-expands THIS
    tile's v rows in-register (dequantized latent block × kv_up's v
    columns) — the dense [B, S_kv, nq, dqk+dv] reconstitution the old
    mla_forward gather paid every step never materializes. Rows are
    s_q * nq with row = s*nq + h (group == 1: every head shares the
    latent row, so no GQA fold)."""
    bs = spec.block_size
    mbs = spec.num_blocks_seq
    nq, s_q = spec.hkv, spec.s_q
    klat, dpe, dv = spec.klat, spec.dpe, spec.dv
    rows = s_q * nq
    ragged, quantized = spec.ragged, spec.quantized
    scale = spec.scale

    def kernel(*refs):
        if ragged:
            table_ref, lens_ref, qlens_ref = refs[:3]
            rest = refs[3:]
        else:
            table_ref, lens_ref = refs[:2]
            rest = refs[2:]
        del table_ref  # indirection is consumed by the BlockSpec index maps
        ql_ref, qp_ref, lat_ref, pe_ref = rest[:4]
        rest = rest[4:]
        if quantized:
            ls_ref, ps_ref = rest[:2]
            rest = rest[2:]
        wv_ref, o_ref, acc, m_scr, l_scr = rest
        b = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            acc[:] = jnp.zeros_like(acc)
            m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr)

        kv_len = lens_ref[b]
        if ragged:
            q_len = qlens_ref[b]
            q_start = kv_len - q_len   # absolute position of local query 0

        @pl.when(j * bs < kv_len)
        def _compute():
            ql = ql_ref[0].astype(jnp.float32).reshape(rows, klat) * scale
            qp = qp_ref[0].astype(jnp.float32).reshape(rows, dpe) * scale
            if quantized:
                # Per-ROW scalar scales ([bs] fp32): the whole latent
                # row quantizes as one unit (quantize_kv_rows over the
                # trailing dim — no head axis to split on).
                lat = lat_ref[0].astype(jnp.float32) * ls_ref[0][:, None]
                pe = pe_ref[0].astype(jnp.float32) * ps_ref[0][:, None]
            else:
                lat = lat_ref[0]                          # [bs, klat]
                pe = pe_ref[0]                            # [bs, dpe]
            s2 = (jnp.dot(ql.astype(lat.dtype), lat.T,   # [rows, bs]
                          preferred_element_type=jnp.float32)
                  + jnp.dot(qp.astype(pe.dtype), pe.T,
                            preferred_element_type=jnp.float32))
            pos = j * bs + jax.lax.broadcasted_iota(
                jnp.int32, (1, bs), 1)[0]
            if ragged:
                row_q = jax.lax.broadcasted_iota(
                    jnp.int32, (rows, 1), 0)[:, 0] // nq
                abs_q = q_start + row_q                   # [rows]
                valid = ((pos[None, :] <= abs_q[:, None])
                         & (pos[None, :] < kv_len))       # [rows, bs]
            else:
                valid = jnp.broadcast_to(pos[None, :] < kv_len,
                                         (rows, bs))
            s2 = jnp.where(valid, s2, _NEG_INF)

            m_prev = m_scr[:, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1))
            m_safe = jnp.maximum(m_new, _NEG_INF / 2)
            p = jnp.exp(s2 - m_safe[:, None])
            p = jnp.where(valid, p, 0.0)
            corr = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
            corr = jnp.where(m_prev <= _NEG_INF / 2, 0.0, corr)
            l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=1)
            # Value path, re-expanded per-tile in-register: v rows of
            # THIS block from the (dequantized) latent block through
            # kv_up's v columns.
            wv = wv_ref[...]
            v_t = jax.lax.dot_general(                    # [bs, nq, dv]
                lat, wv.astype(lat.dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            v3 = jnp.swapaxes(v_t, 0, 1)                  # [nq, bs, dv]
            p3 = jnp.transpose(p.reshape(s_q, nq, bs), (1, 0, 2))
            pv = jax.lax.dot_general(                     # [nq, s_q, dv]
                p3.astype(v3.dtype), v3,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            pv2 = jnp.transpose(pv, (1, 0, 2)).reshape(rows, dv)
            acc[:] = acc[:] * corr[:, None] + pv2
            m_scr[:, 0] = m_new

        @pl.when(j == mbs - 1)
        def _finalize():
            l = jnp.maximum(l_scr[:, 0], 1e-20)
            a = acc[:] / l[:, None]
            if ragged:
                o_ref[0] = a.reshape(s_q, nq, dv).astype(o_ref.dtype)
            else:
                o_ref[0] = a.reshape(nq, dv).astype(o_ref.dtype)

    return kernel


def paged_attention_latent(q_lat: jnp.ndarray, q_pe: jnp.ndarray,
                           lat_pages: jnp.ndarray, pe_pages: jnp.ndarray,
                           page_table: jnp.ndarray, kv_lens: jnp.ndarray,
                           w_v: jnp.ndarray,
                           q_lens: Optional[jnp.ndarray] = None,
                           softmax_scale: Optional[float] = None,
                           lat_scales: Optional[jnp.ndarray] = None,
                           pe_scales: Optional[jnp.ndarray] = None,
                           mesh=None) -> jnp.ndarray:
    """MLA latent-space ragged paged attention with absorbed q weights
    (ISSUE 17 tentpole) — the latent-family entry point.

    q_lat [B, nq, klat] (decode) or [B, S_q, nq, klat] with q_lens [B]
    (ragged multi-query): the ABSORBED query — q_nope (× YaRN mscale²
    when active) contracted through kv_up's k_nope columns, so block
    scores form directly in latent space. q_pe [..., nq, dpe]: the
    roped decoupled heads. lat_pages [NB, bs, klat] / pe_pages
    [NB, bs, dpe]: the compressed pool (NO per-head axis). w_v
    [klat, nq, dv]: kv_up's v columns — the value path re-expands per
    DMA'd tile in-register. lat_scales/pe_scales [NB, bs] fp32 mark
    int8/fp8 pools (per-ROW scalar scales). softmax_scale is REQUIRED:
    the MLA scale 1/sqrt(dqk + dpe) is not derivable from the latent
    width. mesh: latent-COLUMN-shard over the tp axis (_tp_place_latent
    — MLA has no KV heads to split); callers gate on tp_paged_eligible.
    Returns [B(, S_q), nq, dv] in q_lat's dtype."""
    ragged = q_lens is not None
    if softmax_scale is None:
        raise ValueError(
            "paged_attention_latent requires softmax_scale: the MLA "
            "scale is 1/sqrt(qk_head_dim + qk_pos_emb_head_dim), which "
            "cannot be derived from the latent width")
    if mesh is not None:
        return _tp_place_latent(q_lat, q_pe, lat_pages, pe_pages,
                                page_table, kv_lens, w_v, q_lens,
                                softmax_scale, lat_scales, pe_scales,
                                mesh)
    if ragged:
        b, s_q, nq, klat = q_lat.shape
    else:
        b, nq, klat = q_lat.shape
        s_q = 1
    dpe = q_pe.shape[-1]
    dv = w_v.shape[-1]
    nb, bs, _ = lat_pages.shape
    mb = page_table.shape[1]
    quantized = lat_scales is not None
    quant_dtype = quant_dtype_of(lat_pages.dtype) if quantized else None
    if quantized and quant_dtype is None:
        raise ValueError(
            f"scales passed but latent page dtype {lat_pages.dtype} is "
            f"not a registered quantized storage format "
            f"({sorted(QUANT_DTYPES)})")
    spec = PagedSpec(ragged=ragged, quant_dtype=quant_dtype, s_q=s_q,
                     block_size=bs, num_blocks_seq=mb, hkv=nq, group=1,
                     scale=float(softmax_scale),
                     kv_tile=default_kv_tile(quant_dtype),
                     latent=True, klat=klat, dpe=dpe, dv=dv)
    kernel = emit_paged_kernel(spec)

    lat_spec = pl.BlockSpec((1, bs, klat),
                            lambda b_, j, t, *_: (t[b_, j], 0, 0))
    pe_spec = pl.BlockSpec((1, bs, dpe),
                           lambda b_, j, t, *_: (t[b_, j], 0, 0))
    if ragged:
        ql_spec = pl.BlockSpec((1, s_q, nq, klat),
                               lambda b_, j, *_: (b_, 0, 0, 0))
        qp_spec = pl.BlockSpec((1, s_q, nq, dpe),
                               lambda b_, j, *_: (b_, 0, 0, 0))
        o_spec = pl.BlockSpec((1, s_q, nq, dv),
                              lambda b_, j, *_: (b_, 0, 0, 0))
        out_shape = (b, s_q, nq, dv)
    else:
        ql_spec = pl.BlockSpec((1, nq, klat),
                               lambda b_, j, *_: (b_, 0, 0))
        qp_spec = pl.BlockSpec((1, nq, dpe),
                               lambda b_, j, *_: (b_, 0, 0))
        o_spec = pl.BlockSpec((1, nq, dv), lambda b_, j, *_: (b_, 0, 0))
        out_shape = (b, nq, dv)
    in_specs = [ql_spec, qp_spec, lat_spec, pe_spec]
    operands = [q_lat, q_pe, lat_pages, pe_pages]
    if quantized:
        sc_spec = pl.BlockSpec((1, bs),
                               lambda b_, j, t, *_: (t[b_, j], 0))
        in_specs += [sc_spec, sc_spec]
        operands += [lat_scales, pe_scales]
    in_specs.append(pl.BlockSpec(w_v.shape, lambda b_, j, *_: (0, 0, 0)))
    operands.append(w_v)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3 if ragged else 2,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=o_spec,
        scratch_shapes=[
            pltpu.VMEM((s_q * nq, dv), jnp.float32),
            pltpu.VMEM((s_q * nq, 1), jnp.float32),
            pltpu.VMEM((s_q * nq, 1), jnp.float32),
        ],
    )
    prefetch = [page_table.astype(jnp.int32), kv_lens.astype(jnp.int32)]
    if ragged:
        prefetch.append(q_lens.astype(jnp.int32))
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(out_shape, q_lat.dtype),
        interpret=_interpret(),
    )(*prefetch, *operands)


def _latent_block_scores(q, pages, page_table, kv_lens, scales=None):
    """Phase 1 of the latent-column tp path: ALL block scores
    q · pages^T over the page table — q [B, rows, d] × pages [NB, bs, d]
    → [B, rows, MB*bs] fp32, NO softmax. Out-of-range blocks write 0 so
    the cross-shard psum of klat-column partials stays finite; the
    caller masks before its fp32 softmax. scales [NB, bs] fp32 mark a
    quantized pool (per-row scalar scales compose multiplicatively with
    column shards, so per-shard dequant partials sum exactly)."""
    b, rows, d = q.shape
    nb, bs, _ = pages.shape
    mb = page_table.shape[1]
    quantized = scales is not None

    def kernel(*refs):
        table_ref, lens_ref, q_ref, kv_ref = refs[:4]
        rest = refs[4:]
        if quantized:
            sc_ref, o_ref = rest
        else:
            o_ref, = rest
        del table_ref
        b_ = pl.program_id(0)
        j = pl.program_id(1)
        kv_len = lens_ref[b_]

        @pl.when(j * bs < kv_len)
        def _compute():
            if quantized:
                kv = kv_ref[0].astype(jnp.float32) * sc_ref[0][:, None]
            else:
                kv = kv_ref[0]
            o_ref[0] = jnp.dot(q_ref[0].astype(kv.dtype), kv.T,
                               preferred_element_type=jnp.float32)

        @pl.when(j * bs >= kv_len)
        def _zero():
            o_ref[0] = jnp.zeros_like(o_ref)[0]

    kv_spec = pl.BlockSpec((1, bs, d),
                           lambda b_, j, t, *_: (t[b_, j], 0, 0))
    q_spec = pl.BlockSpec((1, rows, d), lambda b_, j, *_: (b_, 0, 0))
    in_specs = [q_spec, kv_spec]
    operands = [q, pages]
    if quantized:
        in_specs.append(pl.BlockSpec((1, bs),
                                     lambda b_, j, t, *_: (t[b_, j], 0)))
        operands.append(scales)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rows, bs), lambda b_, j, *_: (b_, 0, j)),
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rows, mb * bs), jnp.float32),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32), *operands)


def _latent_block_wsum(p, pages, page_table, kv_lens, w_v, scales=None):
    """Phase 2 of the latent-column tp path: probability-weighted value
    sum over the page table with the per-tile in-register re-expansion
    — p [B, rows, MB*bs] fp32 (masked softmax, zeros past each row's
    run) × pages [NB, bs, klat_local] through w_v [klat_local, nq, dv]
    → [B, rows, dv] fp32 partials (the caller psums over the klat
    shards)."""
    b, rows, _ = p.shape
    nb, bs, _ = pages.shape
    mb = page_table.shape[1]
    nq, dv = w_v.shape[1], w_v.shape[2]
    s_q = rows // nq
    quantized = scales is not None
    mbs_ = mb

    def kernel(*refs):
        table_ref, lens_ref, p_ref, kv_ref = refs[:4]
        rest = refs[4:]
        if quantized:
            sc_ref, wv_ref, o_ref, acc = rest
        else:
            wv_ref, o_ref, acc = rest
        del table_ref
        b_ = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            acc[:] = jnp.zeros_like(acc)

        kv_len = lens_ref[b_]

        @pl.when(j * bs < kv_len)
        def _compute():
            if quantized:
                lat = kv_ref[0].astype(jnp.float32) * sc_ref[0][:, None]
            else:
                lat = kv_ref[0]
            wv = wv_ref[...]
            v_t = jax.lax.dot_general(                    # [bs, nq, dv]
                lat, wv.astype(lat.dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            v3 = jnp.swapaxes(v_t, 0, 1)                  # [nq, bs, dv]
            p3 = jnp.transpose(p_ref[0].reshape(s_q, nq, bs), (1, 0, 2))
            pv = jax.lax.dot_general(                     # [nq, s_q, dv]
                p3.astype(v3.dtype), v3,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            acc[:] += jnp.transpose(pv, (1, 0, 2)).reshape(rows, dv)

        @pl.when(j == mbs_ - 1)
        def _finalize():
            o_ref[0] = acc[:]

    kv_spec = pl.BlockSpec((1, bs, pages.shape[-1]),
                           lambda b_, j, t, *_: (t[b_, j], 0, 0))
    p_spec = pl.BlockSpec((1, rows, bs), lambda b_, j, *_: (b_, 0, j))
    in_specs = [p_spec, kv_spec]
    operands = [p, pages]
    if quantized:
        in_specs.append(pl.BlockSpec((1, bs),
                                     lambda b_, j, t, *_: (t[b_, j], 0)))
        operands.append(scales)
    in_specs.append(pl.BlockSpec(w_v.shape, lambda b_, j, *_: (0, 0, 0)))
    operands.append(w_v)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rows, dv), lambda b_, j, *_: (b_, 0, 0)),
        scratch_shapes=[pltpu.VMEM((rows, dv), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rows, dv), jnp.float32),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32), *operands)


def _tp_place_latent(q_lat, q_pe, lat_pages, pe_pages, page_table,
                     kv_lens, w_v, q_lens, softmax_scale, lat_scales,
                     pe_scales, mesh):
    """Latent-COLUMN sharded placement of the MLA kernel family: MLA
    has no KV heads to split, so the tp axis shards the klat dim of the
    latent pool, the absorbed query, and kv_up's v rows (q_pe / pe
    pages / per-row scales / table / lens stay replicated — the rope
    head and the scalar scales have no latent columns). The softmax
    couples every latent column, so the body runs TWO emitted kernels
    around a replicated fp32 softmax: block scores (nope partials
    psum'd over shards + replicated pe scores) → host mask/softmax →
    weighted value sum (dv partials psum'd). The latent pool is read
    once per phase; the output is fully replicated (the psum), so the
    out-projection runs identically on every device and per-request
    streams stay engine-exact."""
    from jax.sharding import PartitionSpec as P

    from megatronapp_tpu.config.parallel_config import TP_AXIS
    from megatronapp_tpu.parallel.collectives import psum, shard_map_compat

    ragged = q_lens is not None
    if ragged:
        b, s_q, nq, klat = q_lat.shape
    else:
        b, nq, klat = q_lat.shape
        s_q = 1
    dv = w_v.shape[-1]
    rows = s_q * nq
    mb = page_table.shape[1]
    bs = lat_pages.shape[1]
    quantized = lat_scales is not None
    out_dtype = q_lat.dtype

    q_sh = (P(None, None, None, TP_AXIS) if ragged
            else P(None, None, TP_AXIS))
    q_rep = (P(None, None, None, None) if ragged else P(None, None, None))
    pool_sh = P(None, None, TP_AXIS)
    pool_rep = P(None, None, None)
    rep2, rep1 = P(None, None), P(None)
    out_sh = (P(None, None, None, None) if ragged else P(None, None, None))

    in_specs = [q_sh, q_rep, pool_sh, pool_rep, rep2, rep1,
                P(TP_AXIS, None, None)]
    operands = [q_lat, q_pe, lat_pages, pe_pages, page_table, kv_lens,
                w_v]
    if ragged:
        in_specs.append(rep1)
        operands.append(q_lens)
    if quantized:
        in_specs += [rep2, rep2]
        operands += [lat_scales, pe_scales]

    def body(*args):
        it = iter(args)
        ql_, qp_, lat_, pe_, t_, l_, wv_ = (next(it) for _ in range(7))
        qlens_ = next(it) if ragged else None
        ls_ = ps_ = None
        if quantized:
            ls_, ps_ = next(it), next(it)
        # fp32 flat rows with the softmax scale applied up front (the
        # shard partials must carry it identically).
        qlf = (ql_.astype(jnp.float32) * softmax_scale).reshape(
            b, rows, -1)
        qpf = (qp_.astype(jnp.float32) * softmax_scale).reshape(
            b, rows, -1)
        s_nope = _latent_block_scores(qlf, lat_, t_, l_, ls_)
        s_nope = psum(s_nope, TP_AXIS)
        # pe scores are replicated work (dpe is tiny) — identical on
        # every shard, no psum.
        s = s_nope + _latent_block_scores(qpf, pe_, t_, l_, ps_)
        pos = jnp.arange(mb * bs, dtype=jnp.int32)[None, None, :]
        if ragged:
            row_q = (jnp.arange(rows, dtype=jnp.int32)
                     // nq)[None, :, None]
            abs_q = (l_ - qlens_)[:, None, None] + row_q
        else:
            abs_q = (l_ - 1)[:, None, None]
        valid = (pos <= abs_q) & (pos < l_[:, None, None])
        s = jnp.where(valid, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        pr = jnp.exp(s - jnp.maximum(m, _NEG_INF / 2))
        pr = jnp.where(valid, pr, 0.0)
        pr = pr / jnp.maximum(jnp.sum(pr, axis=-1, keepdims=True), 1e-20)
        out = _latent_block_wsum(pr, lat_, t_, l_, wv_, ls_)
        out = psum(out, TP_AXIS).astype(out_dtype)
        return (out.reshape(b, s_q, nq, dv) if ragged
                else out.reshape(b, nq, dv))

    # manual-ok: full-manual kernel placement; the only collectives are
    # the two psums over the klat shards. tp_paged_eligible callers
    # gate on no ambient manual axes.
    return shard_map_compat(body, mesh, in_specs=tuple(in_specs),
                            out_specs=out_sh)(*operands)


def paged_attention(q: jnp.ndarray, k_pages: jnp.ndarray,
                    v_pages: jnp.ndarray, page_table: jnp.ndarray,
                    kv_lens: jnp.ndarray,
                    q_lens: Optional[jnp.ndarray] = None,
                    softmax_scale: Optional[float] = None,
                    k_scales: Optional[jnp.ndarray] = None,
                    v_scales: Optional[jnp.ndarray] = None,
                    mesh=None) -> jnp.ndarray:
    """Ragged paged attention — the single generator entry point.

    q [B, Hq, D] (decode) or [B, S_q, Hq, D] with q_lens [B] (ragged
    multi-query); k_pages/v_pages [NB, bs, Hkv, D]; page_table [B, MB]
    int32; kv_lens [B]. k_scales/v_scales [NB, bs, Hkv] fp32 mark int8
    pools (dequant rides the same page-table indirection, in-register).
    mesh: head-shard the emitted kernel over the tp axis of this mesh
    (full-manual shard_map — q on heads, pools + scale pools on Hkv,
    table/lens replicated); callers gate on tp_paged_eligible. Returns
    q's shape."""
    ragged = q_lens is not None
    if mesh is not None:
        return _tp_place(q, k_pages, v_pages, page_table, kv_lens, q_lens,
                         softmax_scale, k_scales, v_scales, mesh)
    if ragged:
        b, s_q, hq, d = q.shape
    else:
        b, hq, d = q.shape
        s_q = 1
    nb, bs, hkv, _ = k_pages.shape
    mb = page_table.shape[1]
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    quantized = k_scales is not None
    quant_dtype = quant_dtype_of(k_pages.dtype) if quantized else None
    if quantized and quant_dtype is None:
        raise ValueError(
            f"scales passed but page dtype {k_pages.dtype} is not a "
            f"registered quantized storage format "
            f"({sorted(QUANT_DTYPES)})")
    spec = PagedSpec(ragged=ragged, quant_dtype=quant_dtype, s_q=s_q,
                     block_size=bs, num_blocks_seq=mb, hkv=hkv,
                     group=hq // hkv, scale=float(softmax_scale),
                     kv_tile=default_kv_tile(quant_dtype))

    kernel = emit_paged_kernel(spec)

    # Page-table indirection: the table and per-slot lengths (and ragged
    # q_lens) are scalar-prefetched so the index maps can DMA block
    # t[b, j] straight from HBM — int8 scale blocks ride the same map.
    kv_spec = pl.BlockSpec((1, bs, hkv, d),
                           lambda b_, j, t, *_: (t[b_, j], 0, 0, 0))
    if ragged:
        q_spec = pl.BlockSpec((1, s_q, hq, d),
                              lambda b_, j, *_: (b_, 0, 0, 0))
    else:
        q_spec = pl.BlockSpec((1, hq, d), lambda b_, j, *_: (b_, 0, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [q, k_pages, v_pages]
    if quantized:
        sc_spec = pl.BlockSpec((1, bs, hkv),
                               lambda b_, j, t, *_: (t[b_, j], 0, 0))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3 if ragged else 2,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((s_q * hq, d), jnp.float32),
            pltpu.VMEM((s_q * hq, 1), jnp.float32),
            pltpu.VMEM((s_q * hq, 1), jnp.float32),
        ],
    )
    prefetch = [page_table.astype(jnp.int32), kv_lens.astype(jnp.int32)]
    if ragged:
        prefetch.append(q_lens.astype(jnp.int32))
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(*prefetch, *operands)


def _tp_place(q, k_pages, v_pages, page_table, kv_lens, q_lens,
              softmax_scale, k_scales, v_scales, mesh):
    """Head-sharded placement of the emitted kernel: a FULL-MANUAL
    shard_map over the tp axis — q sharded on heads, pools (and int8
    scale pools) on Hkv, page table / lengths / q_lens replicated. Each
    shard owns matched GQA groups (contiguous slicing of both head dims
    preserves h // group), so the per-shard body is the UNMODIFIED
    emitted kernel; no collectives run inside. tp_paged_eligible callers
    gate on no ambient manual axes."""
    from jax.sharding import PartitionSpec as P

    from megatronapp_tpu.config.parallel_config import TP_AXIS
    from megatronapp_tpu.parallel.collectives import shard_map_compat

    ragged = q_lens is not None
    if softmax_scale is None:
        softmax_scale = 1.0 / (q.shape[-1] ** 0.5)
    head = (P(None, None, TP_AXIS, None) if ragged
            else P(None, TP_AXIS, None))
    pages = P(None, None, TP_AXIS, None)      # pools [NB, bs, Hkv, D]
    scales = P(None, None, TP_AXIS)           # scale pools [NB, bs, Hkv]
    rep2, rep1 = P(None, None), P(None)

    in_specs = [head, pages, pages, rep2, rep1]
    operands = [q, k_pages, v_pages, page_table, kv_lens]
    if ragged:
        in_specs.append(rep1)
        operands.append(q_lens)
    if k_scales is not None:
        in_specs += [scales, scales]
        operands += [k_scales, v_scales]

    def body(*args):
        q_, k_, v_, t_, l_ = args[:5]
        rest = args[5:]
        ql_ = None
        if ragged:
            ql_, rest = rest[0], rest[1:]
        ks_ = vs_ = None
        if rest:
            ks_, vs_ = rest
        return paged_attention(q_, k_, v_, t_, l_, q_lens=ql_,
                               softmax_scale=softmax_scale,
                               k_scales=ks_, v_scales=vs_)

    # manual-ok: full-manual kernel placement, no collectives in body;
    # tp_paged_eligible callers gate on no ambient manual axes.
    return shard_map_compat(body, mesh, in_specs=tuple(in_specs),
                            out_specs=head)(*operands)


# ---------------------------------------------------------------------------
# Fused (megakernel) decode-layer kernels
#
# One decode token's layer body is ~15 small XLA fusions (two norms, two
# projection matmuls + biases, rope, GQA reshapes, out-proj, fc1/act/
# fc2, two residual adds) — each a separate dispatch inside the scan
# body. The kernels below fold that tail into fat single-program Pallas
# kernels around the generated paged-attention kernel. Math is op-for-op
# the unfused path's (same norm/rope/activation formulas, same
# dtypes/casts), so greedy streams stay token-exact — pinned in
# tests/test_kernel_gen.py. Shapes: decode x is [B, H] with B = a
# handful of slots, so whole-operand (no-grid) kernels are the small-
# shape fast path; when the operand set would blow the VMEM budget, the
# SAME kernels re-emit with a grid over OUTPUT COLUMNS (kv-head groups
# for QKV, H columns for out-proj/fc2, ffn columns for fc1). Column
# tiling keeps the contraction dimension whole per tile, so every tiled
# output column is BITWISE the no-grid one (an accumulator-carrying
# contraction split would reorder the fp32 sums and break the stream
# pins). Resident-quantized weights ({"qint8","qscale"} leaves) stay
# int8 kernel operands and dequantize in-register at matmul entry —
# exactly resolve_param's formula — so --quantized-weights and
# --megakernel-decode stack.
# ---------------------------------------------------------------------------

# Per-kernel operand budget for the fused kernels: tile counts are
# chosen as the smallest grid whose per-step operand blocks fit it.
# Real TPU VMEM is ~16 MB/core; interpret mode (CPU) has no limit but
# keeps the same gate so eligibility is platform-independent. The env
# var seeds the initial default; serving entry points override it at
# runtime via --megakernel-vmem-budget / set_megakernel_vmem_budget.
MEGAKERNEL_VMEM_BUDGET = int(os.environ.get(
    "MEGAKERNEL_VMEM_BUDGET", 12 * 1024 * 1024))

_vmem_budget = MEGAKERNEL_VMEM_BUDGET

# Above this, the per-kernel operand blocks cannot all be VMEM-resident
# on today's chips (~16 MiB/core) — allowed (useful on CPU engines),
# but warned, because on-chip the compiler would spill.
_VMEM_BUDGET_WARN = 16 * 1024 * 1024


def get_megakernel_vmem_budget() -> int:
    """The active per-kernel operand budget (bytes) for the fused
    decode kernels — tile planning and eligibility both read this."""
    return _vmem_budget


def set_megakernel_vmem_budget(nbytes) -> int:
    """Set the per-kernel operand budget (--megakernel-vmem-budget).
    Positive int; values above ~16 MiB/core exceed real TPU VMEM and
    are warned (fine for CPU/interpret engines). Returns the value."""
    global _vmem_budget
    n = int(nbytes)
    if n <= 0:
        raise ValueError(
            f"megakernel VMEM budget must be a positive byte count, "
            f"got {nbytes}")
    if n > _VMEM_BUDGET_WARN:
        logger.warning(
            "megakernel VMEM budget %d B exceeds ~16 MiB/core of real "
            "TPU VMEM — fused kernels planned against it will spill "
            "on-chip (harmless for CPU/interpret engines)", n)
    _vmem_budget = n
    return n


def _weight_itemsize(leaf) -> int:
    """Per-element bytes a weight operand costs in VMEM: resident-
    quantized leaves ship their int8 buffer (scales are counted
    separately by the tile planners)."""
    from megatronapp_tpu.inference.quantization import is_resident_leaf
    if is_resident_leaf(leaf):
        return 1
    return jnp.dtype(leaf.dtype).itemsize if hasattr(leaf, "dtype") \
        else jnp.dtype(jnp.float32).itemsize


def _weight_operands(leaf):
    """Kernel operand list for one weight leaf: [w] for a plain array,
    [qint8, qscale] for a resident-quantized pair (dequantized
    in-register by _dequant_weight)."""
    from megatronapp_tpu.inference.quantization import is_resident_leaf
    if is_resident_leaf(leaf):
        return [leaf["qint8"], leaf["qscale"]]
    return [leaf]


def _dequant_weight(w_ref, s_ref, cdt):
    """Matmul-entry weight read. Plain: cast to compute dtype (the
    no-grid kernels' original `w_ref[...].astype(cdt)`). Resident int8
    (or fp8) with per-output-channel fp32 scales: the exact
    resolve_param formula — int8 → fp32 × scale → compute dtype — so
    fused streams stay token-exact vs the resident unfused engine."""
    w = w_ref[...]
    if s_ref is None:
        return w.astype(cdt)
    return (w.astype(jnp.float32) * s_ref[...]).astype(cdt)


def _pick_grid(n_units, fixed_bytes, unit_bytes, budget, align=1):
    """Smallest divisor T of `n_units` such that one grid step's
    operands (fixed + per-unit × n_units/T) fit `budget`, preferring
    tile widths that stay `align`-divisible (128-lane layouts). 1 means
    the no-grid body fits; 0 means even one unit per tile does not."""
    first = 0
    for t in range(1, n_units + 1):
        if n_units % t:
            continue
        per = n_units // t
        if fixed_bytes + per * unit_bytes > budget:
            continue
        if not first:
            first = t
        if per % align == 0:
            return t
    return first


def _qkv_tiles(h, nq, nkv, d, rows, wq_item, wkv_item, act_item,
               q_scaled, kv_scaled, budget):
    """Tile count for _fused_qkv: the grid unit is one kv-head GROUP
    (its nq/nkv query heads + its K and V head), so GQA q/k/v column
    blocks stay aligned. Byte math is shared with
    megakernel_ineligible_reason — eligibility and emission cannot
    drift."""
    group = nq // nkv
    unit = (group * h * d * wq_item + 2 * h * d * wkv_item
            + (group + 2) * d * (4 + rows * act_item))
    if q_scaled:
        unit += group * d * 4
    if kv_scaled:
        unit += 2 * d * 4
    fixed = rows * h * (act_item + 4)
    return _pick_grid(nkv, fixed, unit, budget)


def _out_tiles(h, nqd, rows, w_item, act_item, scaled, budget):
    """Tile count for _fused_out_proj: the grid unit is one output (H)
    column — full-nqd contraction per tile."""
    unit = nqd * w_item + 2 * rows * act_item + 4 + (4 if scaled else 0)
    fixed = rows * nqd * act_item
    return _pick_grid(h, fixed, unit, budget, align=128)


def _mlp_tiles(h, ffn, gated, rows, w1_item, w2_item, act_item,
               s1, s2, budget):
    """MLP plan: None = the whole norm+fc1+act+fc2+residual body fits
    one no-grid kernel (the original fast path); otherwise (t1, t2) =
    tile counts for the two-kernel split (fc1+activation over ffn
    columns, then fc2+residual over H columns — the intermediate
    y [rows, ffn] lives in compute dtype, which apply_activation
    preserves, so the store/reload between the two kernels is lossless
    vs the single-kernel body). A 0 in the tuple means even one column
    per tile does not fit."""
    gm = 2 if gated else 1
    fc1_out = gm * ffn
    whole = (h * fc1_out * w1_item + ffn * h * w2_item
             + rows * (2 * h + fc1_out) * act_item)
    if s1:
        whole += fc1_out * 4
    if s2:
        whole += h * 4
    if whole <= budget:
        return None
    unit1 = gm * (h * w1_item + 4) + rows * act_item + (gm * 4 if s1
                                                        else 0)
    fixed1 = rows * h * (act_item + 4)
    t1 = _pick_grid(ffn, fixed1, unit1, budget)
    unit2 = ffn * w2_item + 2 * rows * act_item + 4 + (4 if s2 else 0)
    fixed2 = rows * ffn * act_item
    t2 = _pick_grid(h, fixed2, unit2, budget, align=128)
    return (t1, t2)


def _rope_rows(x, cos, sin):
    """Half-rotation RoPE on [B, H, D] rows with per-row tables
    [B, half] — elementwise-identical to ops.rotary.apply_rope on the
    [B, 1, H, D] decode shape (fp32 rotate, cast back)."""
    half = cos.shape[-1]
    rot = 2 * half
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    x1, x2 = x_rot[..., :half], x_rot[..., half:]
    c = cos[:, None, :]
    s = sin[:, None, :]
    out1 = x1.astype(jnp.float32) * c - x2.astype(jnp.float32) * s
    out2 = x2.astype(jnp.float32) * c + x1.astype(jnp.float32) * s
    out = jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
    if x_pass.shape[-1]:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out


def _full_spec(a):
    """BlockSpec mapping the WHOLE array into every grid step."""
    return pl.BlockSpec(a.shape, lambda i, _n=a.ndim: (0,) * _n)


def _lora_epilogue(xv, a, b, out_dtype):
    """In-kernel per-row LoRA delta (ISSUE 19 megakernel epilogue):
    xv [B*, din] (the SAME block the base matmul consumed), per-row
    gathered factors a [B*, din, rank] / b [B*, rank, dout] → the
    fp32 two-step product (x_b @ A_b) @ B_b cast to out_dtype. Row-wise
    by construction — batch composition cannot perturb a row's delta —
    and an all-zero B factor contributes an exact +0.0."""
    t = jnp.einsum("bi,bir->br", xv.astype(jnp.float32),
                   a.astype(jnp.float32))
    return jnp.einsum("br,bro->bo", t,
                      b.astype(jnp.float32)).astype(out_dtype)


def _fused_qkv(x, attn_p, cfg, cos, sin, tiles=None, lora=None):
    """Norm + QKV projection + (optional) QK-layernorm + rope in ONE
    kernel — the attention kernel's entry, fused.

    Small shapes run the original no-grid body; when the whole operand
    set would exceed get_megakernel_vmem_budget(), the kernel re-emits
    with a grid over kv-head GROUPS: each grid step reads the full x
    block plus 1/T of the Q/K/V weight columns (the packed KV weight is
    passed twice — K block at column-block t, V block at t + T, valid
    because nkv*D == T*(nkv_t*D)) and writes 1/T of the heads. The
    contraction stays whole per tile, and the norm recomputes from the
    full x block (row statistics are tile-independent), so tiled heads
    are BITWISE the no-grid ones. Resident-quantized weights dequantize
    in-register (_dequant_weight).

    x [B*, H] (residual dtype; B* = decode batch rows, or B·S flattened
    ragged rows for the fused multiquery step); returns (q, k, v) as
    [B*, nq, D] / [B*, nkv, D] in compute dtype, exactly as the unfused
    layer_forward → attention_forward prologue produces them. tiles:
    test/tuning override of the planned tile count (must divide nkv).
    lora: optional (aq, bq, akv, bkv) per-row adapter factors
    ([B*, H, rk], [B*, rk, nq·D], [B*, H, rk], [B*, rk, 2·nkv·D]) —
    the no-grid body grows a LoRA epilogue adding each row's delta to
    its projections between matmul and bias (the exact unfused
    placement); megakernel_ineligible_reason(lora_rank=) gates the
    tiled emission off."""
    from megatronapp_tpu.config.transformer_config import NormKind
    from megatronapp_tpu.inference.quantization import is_resident_leaf
    from megatronapp_tpu.ops.normalization import apply_norm, rms_norm

    b, h = x.shape
    nq, nkv, d = (cfg.num_attention_heads, cfg.num_query_groups,
                  cfg.head_dim)
    group = nq // nkv
    cdt = cfg.compute_dtype
    eps = cfg.layernorm_epsilon
    kind = cfg.normalization
    has_ln_bias = kind == NormKind.layernorm
    has_bias = "q_bias" in attn_p
    has_rope = cos is not None
    has_qk_ln = cfg.qk_layernorm

    wq_leaf, wkv_leaf = attn_p["q_kernel"], attn_p["kv_kernel"]
    q_res = is_resident_leaf(wq_leaf)
    kv_res = is_resident_leaf(wkv_leaf)
    t = tiles if tiles is not None else _qkv_tiles(
        h, nq, nkv, d, b, _weight_itemsize(wq_leaf),
        _weight_itemsize(wkv_leaf), jnp.dtype(cdt).itemsize,
        q_res, kv_res, get_megakernel_vmem_budget())
    if not t:
        raise ValueError(
            "fused QKV kernel exceeds the VMEM budget even at one "
            "kv-head group per tile — megakernel_ineligible_reason "
            "gates callers before tracing")
    assert nkv % t == 0, f"qkv tile count {t} must divide nkv={nkv}"
    has_lora = lora is not None
    assert not (has_lora and t != 1), (
        "LoRA epilogue rides the no-grid fused QKV body only — "
        "megakernel_ineligible_reason(lora_rank=) gates callers")

    if t == 1:
        operands = [x, attn_p["ln1_scale"]]
        if has_ln_bias:
            operands.append(attn_p["ln1_bias"])
        operands += _weight_operands(wq_leaf) + _weight_operands(wkv_leaf)
        if has_bias:
            operands += [attn_p["q_bias"], attn_p["kv_bias"]]
        if has_rope:
            operands += [cos, sin]
        if has_qk_ln:
            operands += [attn_p["q_ln_scale"], attn_p["k_ln_scale"]]
        if has_lora:
            operands += list(lora)

        def kernel(*refs):
            it = iter(refs)
            x_ref = next(it)
            ln_s = next(it)
            ln_b = next(it) if has_ln_bias else None
            wq_ref = next(it)
            wqs_ref = next(it) if q_res else None
            wkv_ref = next(it)
            wkvs_ref = next(it) if kv_res else None
            qb_ref = next(it) if has_bias else None
            kvb_ref = next(it) if has_bias else None
            cos_ref = next(it) if has_rope else None
            sin_ref = next(it) if has_rope else None
            qln_ref = next(it) if has_qk_ln else None
            kln_ref = next(it) if has_qk_ln else None
            if has_lora:
                aq_ref, bq_ref = next(it), next(it)
                akv_ref, bkv_ref = next(it), next(it)
            q_out, k_out, v_out = next(it), next(it), next(it)

            xn = apply_norm(kind, x_ref[...], ln_s[...],
                            ln_b[...] if ln_b is not None else None, eps)
            xn = xn.astype(cdt)
            q = xn @ _dequant_weight(wq_ref, wqs_ref, cdt)
            kv = xn @ _dequant_weight(wkv_ref, wkvs_ref, cdt)
            if has_lora:
                q = q + _lora_epilogue(xn, aq_ref[...], bq_ref[...], cdt)
                kv = kv + _lora_epilogue(xn, akv_ref[...], bkv_ref[...],
                                         cdt)
            if has_bias:
                q = q + qb_ref[...].astype(cdt)
                kv = kv + kvb_ref[...].astype(cdt)
            q = q.reshape(b, nq, d)
            k, v = jnp.split(kv.reshape(b, 2 * nkv, d), 2, axis=1)
            if has_qk_ln:
                q = rms_norm(q, qln_ref[...], eps)
                k = rms_norm(k, kln_ref[...], eps)
            if has_rope:
                q = _rope_rows(q, cos_ref[...], sin_ref[...])
                k = _rope_rows(k, cos_ref[...], sin_ref[...])
            q_out[...] = q
            k_out[...] = k
            v_out[...] = v

        return pl.pallas_call(
            kernel,
            out_shape=[jax.ShapeDtypeStruct((b, nq, d), cdt),
                       jax.ShapeDtypeStruct((b, nkv, d), cdt),
                       jax.ShapeDtypeStruct((b, nkv, d), cdt)],
            interpret=_interpret(),
        )(*operands)

    # ---- tiled emission: grid over kv-head groups --------------------
    nkv_t = nkv // t
    nq_t = group * nkv_t

    def col_w(width, off=0):
        return pl.BlockSpec((h, width), lambda i, _o=off: (0, _o + i))

    def col_s(width, off=0):
        return pl.BlockSpec((1, width), lambda i, _o=off: (0, _o + i))

    def col_b(width, off=0):
        return pl.BlockSpec((width,), lambda i, _o=off: (_o + i,))

    operands = [x, attn_p["ln1_scale"]]
    in_specs = [_full_spec(x), _full_spec(attn_p["ln1_scale"])]
    if has_ln_bias:
        operands.append(attn_p["ln1_bias"])
        in_specs.append(_full_spec(attn_p["ln1_bias"]))
    operands += _weight_operands(wq_leaf)
    in_specs.append(col_w(nq_t * d))
    if q_res:
        in_specs.append(col_s(nq_t * d))
    # KV weight columns are [K | V] packed: pass the leaf TWICE with
    # the V block offset by T column-blocks (nkv*D == T * nkv_t*D).
    kv_ops = _weight_operands(wkv_leaf)
    operands += kv_ops + kv_ops
    in_specs.append(col_w(nkv_t * d))
    if kv_res:
        in_specs.append(col_s(nkv_t * d))
    in_specs.append(col_w(nkv_t * d, off=t))
    if kv_res:
        in_specs.append(col_s(nkv_t * d, off=t))
    if has_bias:
        operands += [attn_p["q_bias"], attn_p["kv_bias"],
                     attn_p["kv_bias"]]
        in_specs += [col_b(nq_t * d), col_b(nkv_t * d),
                     col_b(nkv_t * d, off=t)]
    if has_rope:
        operands += [cos, sin]
        in_specs += [_full_spec(cos), _full_spec(sin)]
    if has_qk_ln:
        operands += [attn_p["q_ln_scale"], attn_p["k_ln_scale"]]
        in_specs += [_full_spec(attn_p["q_ln_scale"]),
                     _full_spec(attn_p["k_ln_scale"])]

    def tiled(*refs):
        it = iter(refs)
        x_ref = next(it)
        ln_s = next(it)
        ln_b = next(it) if has_ln_bias else None
        wq_ref = next(it)
        wqs_ref = next(it) if q_res else None
        wk_ref = next(it)
        wks_ref = next(it) if kv_res else None
        wv_ref = next(it)
        wvs_ref = next(it) if kv_res else None
        qb_ref = next(it) if has_bias else None
        kb_ref = next(it) if has_bias else None
        vb_ref = next(it) if has_bias else None
        cos_ref = next(it) if has_rope else None
        sin_ref = next(it) if has_rope else None
        qln_ref = next(it) if has_qk_ln else None
        kln_ref = next(it) if has_qk_ln else None
        q_out, k_out, v_out = next(it), next(it), next(it)

        xn = apply_norm(kind, x_ref[...], ln_s[...],
                        ln_b[...] if ln_b is not None else None, eps)
        xn = xn.astype(cdt)
        q = xn @ _dequant_weight(wq_ref, wqs_ref, cdt)
        k = xn @ _dequant_weight(wk_ref, wks_ref, cdt)
        v = xn @ _dequant_weight(wv_ref, wvs_ref, cdt)
        if has_bias:
            q = q + qb_ref[...].astype(cdt)
            k = k + kb_ref[...].astype(cdt)
            v = v + vb_ref[...].astype(cdt)
        q = q.reshape(b, nq_t, d)
        k = k.reshape(b, nkv_t, d)
        v = v.reshape(b, nkv_t, d)
        if has_qk_ln:
            q = rms_norm(q, qln_ref[...], eps)
            k = rms_norm(k, kln_ref[...], eps)
        if has_rope:
            q = _rope_rows(q, cos_ref[...], sin_ref[...])
            k = _rope_rows(k, cos_ref[...], sin_ref[...])
        q_out[...] = q
        k_out[...] = k
        v_out[...] = v

    return pl.pallas_call(
        tiled,
        grid=(t,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((b, nq_t, d), lambda i: (0, i, 0)),
                   pl.BlockSpec((b, nkv_t, d), lambda i: (0, i, 0)),
                   pl.BlockSpec((b, nkv_t, d), lambda i: (0, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, nq, d), cdt),
                   jax.ShapeDtypeStruct((b, nkv, d), cdt),
                   jax.ShapeDtypeStruct((b, nkv, d), cdt)],
        interpret=_interpret(),
    )(*operands)


def _mla_qkv_bytes(cfg, rows, w_item, act_item):
    """Operand bytes of the no-grid fused MLA QKV prologue — shared by
    _fused_mla_qkv's budget check and megakernel_ineligible_reason so
    eligibility and emission cannot drift. MLA prologue weights are
    never resident-quantized (quantization.RESIDENT_KERNELS is
    name-gated and carries none of q_down/q_up/q_proj/kv_down/kv_up),
    so one itemsize covers them all."""
    h = cfg.hidden_size
    nq = cfg.num_attention_heads
    dqk, dpe, dv = cfg.qk_head_dim, cfg.qk_pos_emb_head_dim, cfg.v_head_dim
    klat, qlr = cfg.kv_lora_rank, cfg.q_lora_rank
    if qlr:
        wb = h * qlr + qlr + qlr * nq * (dqk + dpe)
    else:
        wb = h * nq * (dqk + dpe)
    wb += h * (klat + dpe) + klat + klat * nq * (dqk + dv)
    ins = rows * h
    outs = rows * (nq * klat + nq * dpe + klat + dpe)
    rope = 2 * rows * (dpe // 2) * 4
    return wb * w_item + (ins + outs) * act_item + h * 4 + rope


def _fused_mla_qkv(x, attn_p, cfg, cos, sin):
    """The MLA megakernel prologue (ISSUE 17 carve-out c), ONE no-grid
    kernel: pre-attention norm → q path (q_proj, or q_down → rms →
    q_up) → split/rope the decoupled q_pe heads → ABSORB q_nope through
    kv_up's k_nope columns (× YaRN mscale² when active — the cached
    latent is unscaled, so the absorbed query carries both factors) →
    kv_down → split → rms-normed latent → roped shared k_pe row.

    x [B*, H] (residual dtype; B* = decode batch rows or B·S flattened
    ragged rows) with per-row rope tables [B*, dpe/2]; returns
    (q_lat [B*, nq, klat], q_pe [B*, nq, dpe], latent [B*, klat],
    k_pe [B*, dpe]) in compute dtype — exactly the operands
    paged_attention_latent and the append scatter consume. Math is
    op-for-op the unfused mla_forward paged prologue (same einsum
    absorption, same norm/rope formulas), so fused MLA streams stay
    token-exact. No grid: megakernel_ineligible_reason gates callers
    on _mla_qkv_bytes before tracing."""
    from megatronapp_tpu.config.transformer_config import (
        NormKind, PositionEmbeddingKind,
    )
    from megatronapp_tpu.ops import rotary
    from megatronapp_tpu.ops.normalization import apply_norm, rms_norm

    b, h = x.shape
    nq = cfg.num_attention_heads
    dqk, dpe, dv = cfg.qk_head_dim, cfg.qk_pos_emb_head_dim, cfg.v_head_dim
    klat = cfg.kv_lora_rank
    cdt = cfg.compute_dtype
    eps = cfg.layernorm_epsilon
    kind = cfg.normalization
    has_ln_bias = kind == NormKind.layernorm
    has_rope = cos is not None
    has_q_lora = "q_down" in attn_p
    m2 = 1.0
    if cfg.position_embedding == PositionEmbeddingKind.yarn:
        m = rotary.yarn_mscale(cfg.rope_scaling_factor,
                               cfg.yarn_mscale_coeff)
        m2 = m * m

    budget = get_megakernel_vmem_budget()
    need = _mla_qkv_bytes(cfg, b, jnp.dtype(cfg.params_dtype).itemsize,
                          jnp.dtype(cdt).itemsize)
    if need > budget:
        raise ValueError(
            "fused MLA QKV prologue exceeds the VMEM budget — "
            "megakernel_ineligible_reason gates callers before tracing")

    operands = [x, attn_p["ln1_scale"]]
    if has_ln_bias:
        operands.append(attn_p["ln1_bias"])
    if has_q_lora:
        operands += [attn_p["q_down"], attn_p["q_ln_scale"],
                     attn_p["q_up"]]
    else:
        operands.append(attn_p["q_proj"])
    operands += [attn_p["kv_down"], attn_p["kv_ln_scale"],
                 attn_p["kv_up"]]
    if has_rope:
        operands += [cos, sin]

    def kernel(*refs):
        it = iter(refs)
        x_ref = next(it)
        ln_s = next(it)
        ln_b = next(it) if has_ln_bias else None
        if has_q_lora:
            qd_ref, qln_ref, qu_ref = next(it), next(it), next(it)
        else:
            qp_ref = next(it)
        kvd_ref, kvln_ref, kvu_ref = next(it), next(it), next(it)
        cos_ref = next(it) if has_rope else None
        sin_ref = next(it) if has_rope else None
        ql_out, qpe_out, lat_out, pe_out = (next(it), next(it),
                                            next(it), next(it))

        xn = apply_norm(kind, x_ref[...], ln_s[...],
                        ln_b[...] if ln_b is not None else None, eps)
        xn = xn.astype(cdt)
        if has_q_lora:
            q0 = xn @ qd_ref[...].astype(cdt)
            q0 = rms_norm(q0, qln_ref[...], eps)
            qf = q0 @ qu_ref[...].astype(cdt)
        else:
            qf = xn @ qp_ref[...].astype(cdt)
        qf = qf.reshape(b, nq, dqk + dpe)
        q_nope, q_pe = qf[..., :dqk], qf[..., dqk:]
        if has_rope:
            q_pe = _rope_rows(q_pe, cos_ref[...], sin_ref[...])
        kv = xn @ kvd_ref[...].astype(cdt)
        lat_row, pe_row = kv[..., :klat], kv[..., klat:]
        lat_row = rms_norm(lat_row, kvln_ref[...], eps)
        if has_rope:
            pe_row = _rope_rows(pe_row[:, None, :], cos_ref[...],
                                sin_ref[...])[:, 0]
        wk = kvu_ref[...].astype(cdt).reshape(
            klat, nq, dqk + dv)[..., :dqk]
        q_abs = q_nope * m2 if m2 != 1.0 else q_nope
        ql_out[...] = jnp.einsum("bnd,knd->bnk", q_abs, wk)
        qpe_out[...] = q_pe
        lat_out[...] = lat_row
        pe_out[...] = pe_row

    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((b, nq, klat), cdt),
                   jax.ShapeDtypeStruct((b, nq, dpe), cdt),
                   jax.ShapeDtypeStruct((b, klat), cdt),
                   jax.ShapeDtypeStruct((b, dpe), cdt)],
        interpret=_interpret(),
    )(*operands)


def _fused_out_proj(attn_flat, attn_p, cfg, residual, tiles=None,
                    lora=None):
    """Attention epilogue in ONE kernel: out projection + bias +
    residual add (the paged-attention output arrives head-flat
    [B*, nq*D] — the GQA transpose/reshape is folded into the caller's
    free reshape). residual [B*, H] keeps its dtype; returns [B*, H].

    Large H re-emits the same body over a grid of H-column tiles: each
    step reads the full attn_flat block and 1/T of the weight columns
    (full contraction per tile — tiled columns bitwise the no-grid
    ones). Resident-quantized weights dequantize in-register. tiles:
    test/tuning override (must divide H). lora: optional (a, b)
    per-row factors ([B*, nq·D, rk], [B*, rk, H]) — the no-grid body
    adds each row's delta between matmul and bias."""
    from megatronapp_tpu.inference.quantization import is_resident_leaf

    b, h = residual.shape
    cdt = cfg.compute_dtype
    has_bias = "out_bias" in attn_p
    w_leaf = attn_p["out_kernel"]
    res = is_resident_leaf(w_leaf)
    nqd = attn_flat.shape[1]
    t = tiles if tiles is not None else _out_tiles(
        h, nqd, b, _weight_itemsize(w_leaf), jnp.dtype(cdt).itemsize,
        res, get_megakernel_vmem_budget())
    if not t:
        raise ValueError(
            "fused out-proj kernel exceeds the VMEM budget even at one "
            "output column per tile — megakernel_ineligible_reason "
            "gates callers before tracing")
    assert h % t == 0, f"out-proj tile count {t} must divide H={h}"
    has_lora = lora is not None
    assert not (has_lora and t != 1), (
        "LoRA epilogue rides the no-grid fused out-proj body only — "
        "megakernel_ineligible_reason(lora_rank=) gates callers")

    def kernel(*refs):
        it = iter(refs)
        a_ref = next(it)
        w_ref = next(it)
        ws_ref = next(it) if res else None
        r_ref = next(it)
        b_ref = next(it) if has_bias else None
        if has_lora:
            la_ref, lb_ref = next(it), next(it)
        o_ref = next(it)
        out = a_ref[...] @ _dequant_weight(w_ref, ws_ref, cdt)
        if has_lora:
            out = out + _lora_epilogue(a_ref[...], la_ref[...],
                                       lb_ref[...], cdt)
        if has_bias:
            out = out + b_ref[...].astype(cdt)
        r = r_ref[...]
        o_ref[...] = r + out.astype(r.dtype)

    operands = [attn_flat] + _weight_operands(w_leaf) + [residual]
    if has_bias:
        operands.append(attn_p["out_bias"])
    if has_lora:
        operands += list(lora)

    if t == 1:
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((b, h), residual.dtype),
            interpret=_interpret(),
        )(*operands)

    h_t = h // t
    in_specs = [_full_spec(attn_flat),
                pl.BlockSpec((nqd, h_t), lambda i: (0, i))]
    if res:
        in_specs.append(pl.BlockSpec((1, h_t), lambda i: (0, i)))
    in_specs.append(pl.BlockSpec((b, h_t), lambda i: (0, i)))
    if has_bias:
        in_specs.append(pl.BlockSpec((h_t,), lambda i: (i,)))
    return pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, h_t), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((b, h), residual.dtype),
        interpret=_interpret(),
    )(*operands)


def _fused_mlp(x, p, cfg, tiles=None, lora=None):
    """Pre-MLP norm + fc1 + activation (incl. gated) + fc2 + biases +
    residual add. x [B*, H] (residual dtype) → [B*, H].

    When the whole operand set fits the VMEM budget this is the
    original ONE no-grid kernel. Otherwise it splits into TWO tiled
    kernels: fc1+activation over ffn-column tiles producing y
    [B*, ffn] in compute dtype (apply_activation preserves its input
    dtype, so the store/reload is lossless), then fc2+bias+residual
    over H-column tiles with the full-ffn contraction — every output
    bitwise the single-kernel body's. tiles: test/tuning override —
    a (t1, t2) pair forces the split emission. lora: optional
    (a1, b1, a2, b2) per-row factors ([B*, H, rk], [B*, rk, fc1_out],
    [B*, ffn, rk], [B*, rk, H]) — the single-kernel body adds fc1's
    delta (from the normed input) and fc2's delta (from the ACTIVATED
    intermediate) between each matmul and its bias; the split emission
    does not carry it."""
    from megatronapp_tpu.config.transformer_config import NormKind
    from megatronapp_tpu.inference.quantization import is_resident_leaf
    from megatronapp_tpu.ops.activations import apply_activation, is_gated
    from megatronapp_tpu.ops.normalization import apply_norm

    b, h = x.shape
    cdt = cfg.compute_dtype
    eps = cfg.layernorm_epsilon
    kind = cfg.normalization
    act = cfg.activation
    gated = is_gated(act)
    has_ln_bias = kind == NormKind.layernorm
    mlp_p = p["mlp"]
    has_bias = "fc1_bias" in mlp_p
    w1_leaf, w2_leaf = mlp_p["fc1_kernel"], mlp_p["fc2_kernel"]
    r1 = is_resident_leaf(w1_leaf)
    r2 = is_resident_leaf(w2_leaf)
    plan = tiles if tiles is not None else _mlp_tiles(
        h, cfg.ffn_hidden_size, gated, b, _weight_itemsize(w1_leaf),
        _weight_itemsize(w2_leaf), jnp.dtype(cdt).itemsize, r1, r2,
        get_megakernel_vmem_budget())

    has_lora = lora is not None
    if plan is not None:
        assert not has_lora, (
            "LoRA epilogue rides the one-kernel fused MLP body only — "
            "megakernel_ineligible_reason(lora_rank=) gates callers")
        t1, t2 = plan
        if not t1 or not t2:
            raise ValueError(
                "fused MLP kernels exceed the VMEM budget even at one "
                "column per tile — megakernel_ineligible_reason gates "
                "callers before tracing")
        y = _fused_mlp_fc1(x, p, cfg, t1)
        return _fused_mlp_fc2(y, x, p, cfg, t2)

    operands = [x, p["ln2_scale"]]
    if has_ln_bias:
        operands.append(p["ln2_bias"])
    operands += _weight_operands(w1_leaf) + _weight_operands(w2_leaf)
    if has_bias:
        operands += [mlp_p["fc1_bias"], mlp_p["fc2_bias"]]
    if has_lora:
        operands += list(lora)

    def kernel(*refs):
        it = iter(refs)
        x_ref, ln_s = next(it), next(it)
        ln_b = next(it) if has_ln_bias else None
        w1_ref = next(it)
        w1s_ref = next(it) if r1 else None
        w2_ref = next(it)
        w2s_ref = next(it) if r2 else None
        b1_ref = next(it) if has_bias else None
        b2_ref = next(it) if has_bias else None
        if has_lora:
            a1_ref, b1l_ref = next(it), next(it)
            a2_ref, b2l_ref = next(it), next(it)
        o_ref = next(it)

        xn = apply_norm(kind, x_ref[...], ln_s[...],
                        ln_b[...] if ln_b is not None else None, eps)
        xn = xn.astype(cdt)
        y = xn @ _dequant_weight(w1_ref, w1s_ref, cdt)
        if has_lora:
            y = y + _lora_epilogue(xn, a1_ref[...], b1l_ref[...], cdt)
        if has_bias:
            y = y + b1_ref[...].astype(cdt)
        if gated:
            gate, val = jnp.split(y, 2, axis=-1)
            y = apply_activation(act, val, gate)
        else:
            y = apply_activation(act, y)
        out = y @ _dequant_weight(w2_ref, w2s_ref, cdt)
        if has_lora:
            out = out + _lora_epilogue(y, a2_ref[...], b2l_ref[...], cdt)
        if has_bias:
            out = out + b2_ref[...].astype(cdt)
        r = x_ref[...]
        o_ref[...] = r + out.astype(r.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h), x.dtype),
        interpret=_interpret(),
    )(*operands)


def _fused_mlp_fc1(x, p, cfg, t):
    """Kernel A of the tiled MLP split: pre-MLP norm + fc1 + bias +
    activation over a grid of ffn-column tiles. The gated variant reads
    the packed [gate | value] fc1 weight TWICE (value block offset by T
    column-blocks), so the activation sees exactly the columns the
    single-kernel split(y, 2) produces. Returns y [B*, ffn] in compute
    dtype."""
    from megatronapp_tpu.config.transformer_config import NormKind
    from megatronapp_tpu.inference.quantization import is_resident_leaf
    from megatronapp_tpu.ops.activations import apply_activation, is_gated
    from megatronapp_tpu.ops.normalization import apply_norm

    b, h = x.shape
    cdt = cfg.compute_dtype
    eps = cfg.layernorm_epsilon
    kind = cfg.normalization
    act = cfg.activation
    gated = is_gated(act)
    has_ln_bias = kind == NormKind.layernorm
    mlp_p = p["mlp"]
    has_bias = "fc1_bias" in mlp_p
    w1_leaf = mlp_p["fc1_kernel"]
    r1 = is_resident_leaf(w1_leaf)
    ffn = cfg.ffn_hidden_size
    assert ffn % t == 0, f"fc1 tile count {t} must divide ffn={ffn}"
    f_t = ffn // t

    def col_w(off=0):
        return pl.BlockSpec((h, f_t), lambda i, _o=off: (0, _o + i))

    def col_s(off=0):
        return pl.BlockSpec((1, f_t), lambda i, _o=off: (0, _o + i))

    def col_b(off=0):
        return pl.BlockSpec((f_t,), lambda i, _o=off: (_o + i,))

    operands = [x, p["ln2_scale"]]
    in_specs = [_full_spec(x), _full_spec(p["ln2_scale"])]
    if has_ln_bias:
        operands.append(p["ln2_bias"])
        in_specs.append(_full_spec(p["ln2_bias"]))
    w1_ops = _weight_operands(w1_leaf)
    operands += w1_ops
    in_specs.append(col_w())
    if r1:
        in_specs.append(col_s())
    if gated:
        operands += w1_ops
        in_specs.append(col_w(off=t))
        if r1:
            in_specs.append(col_s(off=t))
    if has_bias:
        operands.append(mlp_p["fc1_bias"])
        in_specs.append(col_b())
        if gated:
            operands.append(mlp_p["fc1_bias"])
            in_specs.append(col_b(off=t))

    def kernel(*refs):
        it = iter(refs)
        x_ref, ln_s = next(it), next(it)
        ln_b = next(it) if has_ln_bias else None
        wg_ref = next(it)
        wgs_ref = next(it) if r1 else None
        wv_ref = next(it) if gated else None
        wvs_ref = next(it) if (gated and r1) else None
        bg_ref = next(it) if has_bias else None
        bv_ref = next(it) if (has_bias and gated) else None
        y_out = next(it)

        xn = apply_norm(kind, x_ref[...], ln_s[...],
                        ln_b[...] if ln_b is not None else None, eps)
        xn = xn.astype(cdt)
        if gated:
            gate = xn @ _dequant_weight(wg_ref, wgs_ref, cdt)
            val = xn @ _dequant_weight(wv_ref, wvs_ref, cdt)
            if has_bias:
                gate = gate + bg_ref[...].astype(cdt)
                val = val + bv_ref[...].astype(cdt)
            y = apply_activation(act, val, gate)
        else:
            y = xn @ _dequant_weight(wg_ref, wgs_ref, cdt)
            if has_bias:
                y = y + bg_ref[...].astype(cdt)
            y = apply_activation(act, y)
        y_out[...] = y

    return pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, f_t), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((b, ffn), cdt),
        interpret=_interpret(),
    )(*operands)


def _fused_mlp_fc2(y, x, p, cfg, t):
    """Kernel B of the tiled MLP split: fc2 + bias + residual add over
    a grid of H-column tiles with the full-ffn contraction per tile.
    y [B*, ffn] (compute dtype, from _fused_mlp_fc1), x [B*, H] the
    pre-norm residual; returns [B*, H] in the residual dtype."""
    from megatronapp_tpu.inference.quantization import is_resident_leaf

    b, h = x.shape
    cdt = cfg.compute_dtype
    mlp_p = p["mlp"]
    has_bias = "fc2_bias" in mlp_p
    w2_leaf = mlp_p["fc2_kernel"]
    r2 = is_resident_leaf(w2_leaf)
    ffn = y.shape[1]
    assert h % t == 0, f"fc2 tile count {t} must divide H={h}"
    h_t = h // t

    operands = [y] + _weight_operands(w2_leaf) + [x]
    in_specs = [_full_spec(y),
                pl.BlockSpec((ffn, h_t), lambda i: (0, i))]
    if r2:
        in_specs.append(pl.BlockSpec((1, h_t), lambda i: (0, i)))
    in_specs.append(pl.BlockSpec((b, h_t), lambda i: (0, i)))
    if has_bias:
        operands.append(mlp_p["fc2_bias"])
        in_specs.append(pl.BlockSpec((h_t,), lambda i: (i,)))

    def kernel(*refs):
        it = iter(refs)
        y_ref = next(it)
        w2_ref = next(it)
        w2s_ref = next(it) if r2 else None
        x_ref = next(it)
        b2_ref = next(it) if has_bias else None
        o_ref = next(it)
        out = y_ref[...] @ _dequant_weight(w2_ref, w2s_ref, cdt)
        if has_bias:
            out = out + b2_ref[...].astype(cdt)
        r = x_ref[...]
        o_ref[...] = r + out.astype(r.dtype)

    return pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, h_t), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((b, h), x.dtype),
        interpret=_interpret(),
    )(*operands)


def _fused_mla_layer(p, x, cfg, rope_cos, rope_sin, kv_cache,
                     cache_positions, counts, page_table, active,
                     kv_scales=None):
    """One MLA layer as fused kernels (ISSUE 17 carve-out c): [fused
    MLA prologue — norm + q path + rope + absorption + latent/k_pe] →
    [compressed append scatter] → [generated latent-space paged kernel]
    → [fused out-proj + residual] → [fused norm+MLP + residual].

    Handles BOTH the s == 1 decode body (counts=None) and the ragged
    multiquery body (counts [B]) — the prologue is row-wise, so the
    B·S flattening is bitwise-safe exactly like fused_layer_multiquery.
    kv_scales: int8/fp8 latent pool scale pools ([L-sliced NB, bs] per
    pool) — the new rows quantize per-row right here (ONE fused jit
    covers prologue + quantize + scatter + attend) and new_cache
    carries four pools."""
    from megatronapp_tpu.ops.pallas.paged_attention import (
        append_chunk_pages, append_token_pages, quantize_kv_rows,
    )
    b, s, h = x.shape
    nq = cfg.num_attention_heads
    dqk, dpe, dv = cfg.qk_head_dim, cfg.qk_pos_emb_head_dim, cfg.v_head_dim
    klat = cfg.kv_lora_rank
    dt = cfg.compute_dtype
    attn_p = p["attention"]
    ragged = counts is not None
    xf = x.reshape(b * s, h)
    cos = rope_cos.reshape(b * s, -1) if rope_cos is not None else None
    sin = rope_sin.reshape(b * s, -1) if rope_sin is not None else None

    q_lat, q_pe, lat, pe = _fused_mla_qkv(
        xf, {**attn_p, "ln1_scale": p["ln1_scale"],
             **({"ln1_bias": p["ln1_bias"]} if "ln1_bias" in p else {})},
        cfg, cos, sin)
    w_v = attn_p["kv_up"].astype(dt).reshape(
        klat, nq, dqk + dv)[..., dqk:]

    c_lat, c_pe = kv_cache
    if active is None:
        active = jnp.ones((b,), bool)
    if ragged:
        lat_r, pe_r = lat.reshape(b, s, klat), pe.reshape(b, s, dpe)

        def _append(pool, rows_):
            return append_chunk_pages(pool, rows_, page_table,
                                      cache_positions, counts, active)
    else:
        lat_r, pe_r = lat[:, None], pe[:, None]

        def _append(pool, rows_):
            return append_token_pages(pool, rows_[:, 0], page_table,
                                      cache_positions, active)

    if kv_scales is not None:
        ls_p, ps_p = kv_scales
        lat_q, lat_s = quantize_kv_rows(lat_r, dtype=c_lat.dtype)
        pe_q, pe_s = quantize_kv_rows(pe_r, dtype=c_pe.dtype)
        c_lat = _append(c_lat, lat_q)
        c_pe = _append(c_pe, pe_q)
        ls_p = _append(ls_p, lat_s)
        ps_p = _append(ps_p, pe_s)
        new_cache = (c_lat, c_pe, ls_p, ps_p)
        sc_kw = {"lat_scales": ls_p, "pe_scales": ps_p}
    else:
        c_lat = _append(c_lat, lat_r.astype(c_lat.dtype))
        c_pe = _append(c_pe, pe_r.astype(c_pe.dtype))
        new_cache = (c_lat, c_pe)
        sc_kw = {}

    scale = 1.0 / float((dqk + dpe) ** 0.5)
    if ragged:
        attn = paged_attention_latent(
            q_lat.reshape(b, s, nq, klat), q_pe.reshape(b, s, nq, dpe),
            c_lat, c_pe, page_table, cache_positions + counts, w_v,
            q_lens=counts, softmax_scale=scale, **sc_kw)
    else:
        attn = paged_attention_latent(
            q_lat, q_pe, c_lat, c_pe, page_table, cache_positions + 1,
            w_v, softmax_scale=scale, **sc_kw)        # [B, nq, dv]
    x2 = _fused_out_proj(attn.reshape(b * s, nq * dv), attn_p, cfg, xf)
    x2 = _fused_mlp(x2, p, cfg)
    out = x2[:, None] if not ragged else x2.reshape(b, s, h)
    return (out, new_cache), None


def _lora_gathered(lora, s: int = 1):
    """Gather per-row adapter factors from one layer's bank slices:
    lora = {"row_adapter": [B] int32 bank slots, "banks":
    {target: (a [slots, din, rk], b [slots, rk, dout])}} → the fused
    bodies' operand tuples (qkv, out, mlp) with the batch's ids
    repeated over S for flattened ragged rows (every token row wears
    its slot's adapter). XLA gathers OUTSIDE the kernels; the bodies
    see dense [B*, …] factor operands."""
    ids = lora["row_adapter"]
    if s > 1:
        ids = jnp.repeat(ids, s)
    g = {t: (a[ids], b[ids]) for t, (a, b) in lora["banks"].items()}
    qkv = (g["q_kernel"][0], g["q_kernel"][1],
           g["kv_kernel"][0], g["kv_kernel"][1])
    out = g["out_kernel"]
    mlp = (g["fc1_kernel"][0], g["fc1_kernel"][1],
           g["fc2_kernel"][0], g["fc2_kernel"][1])
    return qkv, out, mlp


def fused_layer_decode(p, x, cfg, rope_cos, rope_sin, kv_cache,
                       cache_positions, page_table, active,
                       kv_scales=None, lora=None):
    """One decode layer as fused kernels: [fused norm+QKV+rope] →
    [append scatter] → [generated paged-attention kernel] → [fused
    out-proj + residual] → [fused norm+MLP + residual].

    Drop-in for transformer/block.layer_forward's s == 1 paged decode
    path (cfg.megakernel_decode; DynamicInferenceEngine(fused_decode=
    True)): same arguments, same ((out, new_cache), aux) return, greedy
    streams token-exact vs the unfused body. MegaScope capture /
    disturbance sites are NOT traced here — megakernel_ineligible_reason
    gates the fused path off while hooks are active."""
    from megatronapp_tpu.ops.pallas.paged_attention import (
        append_token_pages, quantize_kv_rows,
    )
    b = x.shape[0]
    assert x.shape[1] == 1, "fused_layer_decode is the s == 1 decode body"
    if cfg.multi_latent_attention:
        assert lora is None, (
            "LoRA targets the GQA projections — "
            "megakernel_ineligible_reason(lora_rank=) gates MLA off")
        return _fused_mla_layer(p, x, cfg, rope_cos, rope_sin, kv_cache,
                                cache_positions, None, page_table,
                                active, kv_scales=kv_scales)
    nq, d = cfg.num_attention_heads, cfg.head_dim
    attn_p = p["attention"]
    x2 = x[:, 0]
    cos = rope_cos[:, 0] if rope_cos is not None else None
    sin = rope_sin[:, 0] if rope_sin is not None else None
    qkv_lora = out_lora = mlp_lora = None
    if lora is not None:
        qkv_lora, out_lora, mlp_lora = _lora_gathered(lora)

    q, k, v = _fused_qkv(x2, {**attn_p, "ln1_scale": p["ln1_scale"],
                              **({"ln1_bias": p["ln1_bias"]}
                                 if "ln1_bias" in p else {})},
                         cfg, cos, sin, lora=qkv_lora)

    ck, cv = kv_cache
    if active is None:
        active = jnp.ones((b,), bool)
    if kv_scales is not None:
        cks, cvs = kv_scales
        k_q, k_s = quantize_kv_rows(k, dtype=ck.dtype)
        v_q, v_s = quantize_kv_rows(v, dtype=cv.dtype)
        ck = append_token_pages(ck, k_q, page_table, cache_positions,
                                active)
        cv = append_token_pages(cv, v_q, page_table, cache_positions,
                                active)
        cks = append_token_pages(cks, k_s, page_table, cache_positions,
                                 active)
        cvs = append_token_pages(cvs, v_s, page_table, cache_positions,
                                 active)
        new_cache = (ck, cv, cks, cvs)
        sc_kw = {"k_scales": cks, "v_scales": cvs}
    else:
        ck = append_token_pages(ck, k, page_table, cache_positions, active)
        cv = append_token_pages(cv, v, page_table, cache_positions, active)
        new_cache = (ck, cv)
        sc_kw = {}

    attn = paged_attention(q, ck, cv, page_table, cache_positions + 1,
                           **sc_kw)                       # [B, nq, D]
    x2 = _fused_out_proj(attn.reshape(b, nq * d), attn_p, cfg, x2,
                         lora=out_lora)
    x2 = _fused_mlp(x2, p, cfg, lora=mlp_lora)
    return (x2[:, None], new_cache), None


def fused_layer_multiquery(p, x, cfg, rope_cos, rope_sin, kv_cache,
                           cache_positions, counts, page_table, active,
                           kv_scales=None, lora=None):
    """One ragged multi-query layer (speculative verify rounds and
    chunked prefill) as the SAME fused kernels around the generated
    ragged paged-attention kernel: [fused norm+QKV+rope on the B·S
    flattened rows] → [chunk append scatter] → [ragged paged attention,
    q_lens scalar-prefetch path] → [fused out-proj + residual] →
    [fused norm+MLP + residual].

    Drop-in for transformer/block.layer_forward's chunk_counts paged
    path: x [B, S, H] with rope tables [B, S, half] and counts [B]
    (q_len ∈ [1, S] per row). Row-flattening is bitwise-safe — every
    fused op is row-wise (norms, rope, activations) or contracts the
    last dim only — so verify/prefill streams keep the PR 4 pins."""
    from megatronapp_tpu.ops.pallas.paged_attention import (
        append_chunk_pages, quantize_kv_rows,
    )
    b, s, h = x.shape
    if cfg.multi_latent_attention:
        assert lora is None, (
            "LoRA targets the GQA projections — "
            "megakernel_ineligible_reason(lora_rank=) gates MLA off")
        return _fused_mla_layer(p, x, cfg, rope_cos, rope_sin, kv_cache,
                                cache_positions, counts, page_table,
                                active, kv_scales=kv_scales)
    nq, nkv, d = (cfg.num_attention_heads, cfg.num_query_groups,
                  cfg.head_dim)
    attn_p = p["attention"]
    xf = x.reshape(b * s, h)
    cos = rope_cos.reshape(b * s, -1) if rope_cos is not None else None
    sin = rope_sin.reshape(b * s, -1) if rope_sin is not None else None
    qkv_lora = out_lora = mlp_lora = None
    if lora is not None:
        qkv_lora, out_lora, mlp_lora = _lora_gathered(lora, s)

    q, k, v = _fused_qkv(xf, {**attn_p, "ln1_scale": p["ln1_scale"],
                              **({"ln1_bias": p["ln1_bias"]}
                                 if "ln1_bias" in p else {})},
                         cfg, cos, sin, lora=qkv_lora)
    q = q.reshape(b, s, nq, d)
    k = k.reshape(b, s, nkv, d)
    v = v.reshape(b, s, nkv, d)

    ck, cv = kv_cache
    if active is None:
        active = jnp.ones((b,), bool)
    if kv_scales is not None:
        cks, cvs = kv_scales
        k_q, k_s = quantize_kv_rows(k, dtype=ck.dtype)
        v_q, v_s = quantize_kv_rows(v, dtype=cv.dtype)
        ck = append_chunk_pages(ck, k_q, page_table, cache_positions,
                                counts, active)
        cv = append_chunk_pages(cv, v_q, page_table, cache_positions,
                                counts, active)
        cks = append_chunk_pages(cks, k_s, page_table, cache_positions,
                                 counts, active)
        cvs = append_chunk_pages(cvs, v_s, page_table, cache_positions,
                                 counts, active)
        new_cache = (ck, cv, cks, cvs)
        sc_kw = {"k_scales": cks, "v_scales": cvs}
    else:
        ck = append_chunk_pages(ck, k, page_table, cache_positions,
                                counts, active)
        cv = append_chunk_pages(cv, v, page_table, cache_positions,
                                counts, active)
        new_cache = (ck, cv)
        sc_kw = {}

    attn = paged_attention(q, ck, cv, page_table,
                           cache_positions + counts, q_lens=counts,
                           **sc_kw)                    # [B, S, nq, D]
    x2 = _fused_out_proj(attn.reshape(b * s, nq * d), attn_p, cfg, xf,
                         lora=out_lora)
    x2 = _fused_mlp(x2, p, cfg, lora=mlp_lora)
    return (x2.reshape(b, s, h), new_cache), None


def megakernel_ineligible_reason(cfg, *, batch, tp_paged=False,
                                 paged=True, params=None,
                                 mq_rows=None,
                                 lora_rank=None) -> Optional[str]:
    """Why the fused (megakernel) decode step may NOT run — None when
    eligible, otherwise the FIRST failed predicate by name (same
    loud-fallback contract as tp_paged_ineligible_reason). params: the
    engine's param pytree when available — resident-quantized leaves
    change the weight-operand byte math (int8 blocks + fp32 scale rows
    enter the kernels and dequantize in-register; they are NOT a
    carve-out anymore). mq_rows: the widest flattened row count the
    fused multiquery step will see (prefill_chunk / max_batch·(K+1));
    tile plans are sized for the worse of batch and mq_rows. lora_rank:
    the serving adapter rank when an AdapterCache is attached — the
    LoRA epilogue rides only the NO-GRID fused bodies, so its
    predicates re-plan each body with the per-row factor bytes charged
    against the budget.

    Size no longer disqualifies a config outright: the fused kernels
    grid-tile their weight columns to fit the VMEM budget
    (get_megakernel_vmem_budget / --megakernel-vmem-budget), so the
    size predicates below fail only when even ONE column/kv-head-group
    per tile exceeds the budget. The same _qkv_tiles/_out_tiles/
    _mlp_tiles byte math drives kernel emission — eligibility and
    emission cannot drift."""
    if not paged:
        return "dense (non-paged) backend — the fused step is built " \
               "around the paged-attention kernel"
    if cfg.is_moe:
        return "MoE layers: expert dispatch is not fused yet"
    if getattr(cfg, "hetero_block_specs", None):
        return "heterogeneous per-layer configs unroll their own bodies"
    if tp_paged:
        return "tp head-sharded serving mesh: fused prologue/epilogue " \
               "kernels are single-device (the tp engine keeps the " \
               "unfused body)"
    from megatronapp_tpu.scope import hooks
    from megatronapp_tpu.scope.disturbance import get_disturbance
    cap_sites = ("qkv_q", "qkv_k", "qkv_v", "context", "mlp1", "mlp2",
                 "between_layers")
    if any(hooks.is_enabled(s) for s in cap_sites):
        return "MegaScope capture hooks active (fused kernels do not " \
               "trace capture sites)"
    dist = get_disturbance()
    if any(dist.active(s) for s in ("weight", "calculation", "system")):
        return "MegaScope disturbance sites active (fused kernels do " \
               "not trace perturbations)"
    # Size: plan the tile grids at the engine's worst row count; a 0
    # tile count means even the finest tiling cannot fit the budget.
    from megatronapp_tpu.inference.quantization import is_resident_leaf
    from megatronapp_tpu.ops.activations import is_gated
    blk = params.get("block", {}) if isinstance(params, dict) else {}
    attn = blk.get("attention", {}) if isinstance(blk, dict) else {}
    mlp = blk.get("mlp", {}) if isinstance(blk, dict) else {}
    h = cfg.hidden_size
    mla = cfg.multi_latent_attention
    nq = cfg.num_attention_heads
    rows = max(int(batch), int(mq_rows or 0))
    act_item = jnp.dtype(cfg.compute_dtype).itemsize
    default_item = jnp.dtype(cfg.params_dtype).itemsize

    def _wi(leaf):
        return 1 if is_resident_leaf(leaf) else default_item

    budget = get_megakernel_vmem_budget()
    flag = "raise --megakernel-vmem-budget to fuse anyway"
    if mla:
        # The MLA prologue (q path + absorption + latent projection +
        # rope, _fused_mla_qkv) has no column-tiling axis — the kv_up
        # absorption couples every head to the whole latent — so it
        # runs no-grid only and fails as one predicate.
        if _mla_qkv_bytes(cfg, rows, default_item, act_item) > budget:
            return (f"fused MLA QKV prologue (q path + kv_up absorption "
                    f"+ latent projection) exceeds the VMEM budget "
                    f"({budget} B) as one no-grid kernel — {flag}")
        nqd = nq * cfg.v_head_dim
    else:
        nkv, d = cfg.num_query_groups, cfg.head_dim
        if not _qkv_tiles(h, nq, nkv, d, rows, _wi(attn.get("q_kernel")),
                          _wi(attn.get("kv_kernel")), act_item,
                          is_resident_leaf(attn.get("q_kernel")),
                          is_resident_leaf(attn.get("kv_kernel")),
                          budget):
            return (f"fused QKV kernel: one kv-head group per tile "
                    f"still exceeds the VMEM budget ({budget} B) — "
                    f"{flag}")
        nqd = nq * d
    if not _out_tiles(h, nqd, rows, _wi(attn.get("out_kernel")),
                      act_item, is_resident_leaf(attn.get("out_kernel")),
                      budget):
        return (f"fused out-proj kernel: one output column per tile "
                f"still exceeds the VMEM budget ({budget} B) — {flag}")
    plan = _mlp_tiles(h, cfg.ffn_hidden_size, is_gated(cfg.activation),
                      rows, _wi(mlp.get("fc1_kernel")),
                      _wi(mlp.get("fc2_kernel")), act_item,
                      is_resident_leaf(mlp.get("fc1_kernel")),
                      is_resident_leaf(mlp.get("fc2_kernel")), budget)
    if plan is not None and (not plan[0] or not plan[1]):
        return (f"fused MLP kernels: one ffn/output column per tile "
                f"still exceeds the VMEM budget ({budget} B) — {flag}")
    if lora_rank:
        if mla:
            return ("LoRA serving targets the GQA projection kernels — "
                    "the MLA megakernel has no q_kernel/kv_kernel to "
                    "compose an adapter epilogue onto")
        # LoRA epilogue (ISSUE 19): the fused bodies add per-row
        # adapter factors as extra whole-array operands, which only the
        # NO-GRID emissions carry (the tiled emissions' column blocks
        # would have to split the B factor's dout dim in lockstep —
        # not built). Re-plan each body with the budget reduced by its
        # fp32 per-row factor bytes: still no-grid → base + LoRA fits.
        rk = int(lora_rank)
        d_qkv = nq * cfg.head_dim + 2 * cfg.num_query_groups * cfg.head_dim
        lb = rows * rk * (2 * h + d_qkv) * 4
        if _qkv_tiles(h, nq, cfg.num_query_groups, cfg.head_dim, rows,
                      _wi(attn.get("q_kernel")),
                      _wi(attn.get("kv_kernel")), act_item,
                      is_resident_leaf(attn.get("q_kernel")),
                      is_resident_leaf(attn.get("kv_kernel")),
                      budget - lb) != 1:
            return (f"LoRA epilogue (rank {rk}) needs the no-grid fused "
                    f"QKV body with its per-row factors VMEM-resident — "
                    f"over the budget ({budget} B) at rows={rows}; {flag}")
        lb = rows * rk * (nqd + h) * 4
        if _out_tiles(h, nqd, rows, _wi(attn.get("out_kernel")),
                      act_item,
                      is_resident_leaf(attn.get("out_kernel")),
                      budget - lb) != 1:
            return (f"LoRA epilogue (rank {rk}) needs the no-grid fused "
                    f"out-proj body with its per-row factors "
                    f"VMEM-resident — over the budget ({budget} B) at "
                    f"rows={rows}; {flag}")
        ffn = cfg.ffn_hidden_size
        fc1_out = (2 if is_gated(cfg.activation) else 1) * ffn
        lb = rows * rk * (h + fc1_out + ffn + h) * 4
        if _mlp_tiles(h, ffn, is_gated(cfg.activation), rows,
                      _wi(mlp.get("fc1_kernel")),
                      _wi(mlp.get("fc2_kernel")), act_item,
                      is_resident_leaf(mlp.get("fc1_kernel")),
                      is_resident_leaf(mlp.get("fc2_kernel")),
                      budget - lb) is not None:
            return (f"LoRA epilogue (rank {rk}) needs the one-kernel "
                    f"fused MLP body with its per-row factors "
                    f"VMEM-resident — over the budget ({budget} B) at "
                    f"rows={rows}; {flag}")
    return None


# ---------------------------------------------------------------------------
# Batched-LoRA delta kernels (ISSUE 19): one decode batch, many adapters
# ---------------------------------------------------------------------------
# The device half of inference/lora.py: a decode batch carries a per-row
# bank-slot id (0 = the NULL adapter), and every LoRA-targeted matmul
# adds delta[b] = (x[b] @ A_{id[b]}) @ B_{id[b]} to its base output.
# Three interchangeable per-row-exact implementations:
#
#   - lora_delta_reference  the jnp oracle AND the eager fallback:
#                           gather the per-row factors, two einsums in
#                           fp32;
#   - lora_segmented_delta  the emitted Pallas kernel: rows grouped into
#                           adapter SEGMENTS in-trace, the segment's
#                           adapter id scalar-prefetched like a page
#                           table so each grid step DMAs exactly one
#                           adapter's [din, rank]/[rank, dout] factors
#                           from the bank (vs the reference's [rows, …]
#                           gathered copies);
#   - the megakernel epilogue (``lora=`` on the fused bodies above):
#                           per-row gathered factors ride into the
#                           no-grid fused kernels as extra operands.
#
# All three compute row b's delta from row b's x and factors ONLY —
# never from batch composition — which is what makes a mixed-tenant
# batch token-exact vs serving each tenant serially.


def lora_segment_info(row_adapter):
    """Group batch rows by adapter id, in-trace (no host sort, no
    dynamic shapes — O(B²) compares on a decode-batch-sized B).

    row_adapter [B] int32 bank slots → (seg_adapter [B], row_seg [B],
    nseg): segments are numbered by FIRST occurrence order;
    seg_adapter[s] is segment s's bank slot (0 for the unused tail
    s >= nseg, so padding grid steps DMA the NULL adapter's block);
    row_seg[b] is row b's segment."""
    ids = row_adapter.astype(jnp.int32)
    b = ids.shape[0]
    iota = jnp.arange(b, dtype=jnp.int32)
    first = jnp.argmax(ids[:, None] == ids[None, :], axis=1)  # [B]
    is_first = first == iota
    seg_of_first = jnp.cumsum(is_first.astype(jnp.int32)) - 1
    row_seg = seg_of_first[first]
    seg_adapter = jnp.zeros((b,), jnp.int32).at[row_seg].set(ids)
    nseg = jnp.sum(is_first.astype(jnp.int32))
    return seg_adapter, row_seg, nseg


def lora_delta_reference(x, a_bank, b_bank, row_adapter):
    """jnp oracle (and THE eager fallback for kernel-ineligible
    shapes): per-row gathered two-step product in fp32.

    x [B, din], a_bank [slots, din, rank], b_bank [slots, rank, dout],
    row_adapter [B] int32 → delta [B, dout] fp32 (callers cast when
    adding into the base matmul output)."""
    a = a_bank[row_adapter].astype(jnp.float32)       # [B, din, rank]
    b = b_bank[row_adapter].astype(jnp.float32)       # [B, rank, dout]
    t = jnp.einsum("bi,bir->br", x.astype(jnp.float32), a)
    return jnp.einsum("br,bro->bo", t, b)


def lora_kernel_ineligible_reason(din: int, dout: int, rank: int,
                                  rows: int) -> Optional[str]:
    """Why the segmented Pallas kernel may NOT serve this delta — None
    when eligible, else the FIRST failed predicate by name (the caller
    falls back to the eager gather, which is the oracle itself, so
    ineligible shapes lose speed, never correctness)."""
    if rank > min(din, dout):
        return (f"adapter rank {rank} exceeds min(din={din}, "
                f"dout={dout}) — a low-rank delta this fat is an eager "
                f"gather, not a segmented GEMM")
    budget = get_megakernel_vmem_budget()
    # One grid step holds x [rows, din], one adapter's factors, the
    # rank-space intermediate and the fp32 accumulator + row_seg.
    need = 4 * (rows * din + din * rank + rank * dout
                + rows * rank + rows * dout + rows)
    if need > budget:
        return (f"segmented-LoRA kernel operands ({need} B at "
                f"rows={rows}, din={din}, dout={dout}, rank={rank}) "
                f"exceed the VMEM budget ({budget} B) — raise "
                f"--megakernel-vmem-budget or take the eager fallback")
    return None


def lora_segmented_delta(x, a_bank, b_bank, row_adapter):
    """The emitted segmented batched-LoRA GEMM.

    Grid = one step per row-SEGMENT (rows sharing an adapter), with the
    segment's bank slot scalar-prefetched (PrefetchScalarGridSpec —
    exactly how the paged kernels prefetch page tables) so each step's
    BlockSpec index map DMAs ONE adapter's A [din, rank] and
    B [rank, dout] blocks from the HBM bank. The step computes the full
    batch's delta through that adapter and accumulates only its own
    rows (mask by row_seg) — per-row results never depend on which
    OTHER rows share the batch. Unused tail segments (the grid is sized
    B, the worst case of B distinct adapters) index the NULL slot-0
    block and mask to nothing.

    x [B, din], banks [slots, din, rank]/[slots, rank, dout],
    row_adapter [B] int32 → delta [B, dout] fp32 — bit-for-bit the
    jnp oracle's dtype contract (fp32 accumulate, caller casts)."""
    b, din = x.shape
    rank = a_bank.shape[-1]
    dout = b_bank.shape[-1]
    seg_adapter, row_seg, _ = lora_segment_info(row_adapter)

    def kernel(seg_ref, rs_ref, x_ref, a_ref, b_ref, o_ref):
        s = pl.program_id(0)

        @pl.when(s == 0)
        def _init():
            o_ref[...] = jnp.zeros_like(o_ref)

        xv = x_ref[...].astype(jnp.float32)
        a = a_ref[0].astype(jnp.float32)              # [din, rank]
        bf = b_ref[0].astype(jnp.float32)             # [rank, dout]
        t = jax.lax.dot_general(xv, a, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        d = jax.lax.dot_general(t, bf, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        mine = (rs_ref[...] == s)[:, None]            # [B, 1]
        o_ref[...] += jnp.where(mine, d, 0.0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((b, din), lambda s, *_: (0, 0)),
            pl.BlockSpec((1, din, rank),
                         lambda s, seg, rs: (seg[s], 0, 0)),
            pl.BlockSpec((1, rank, dout),
                         lambda s, seg, rs: (seg[s], 0, 0)),
        ],
        out_specs=pl.BlockSpec((b, dout), lambda s, *_: (0, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, dout), jnp.float32),
        interpret=_interpret(),
    )(seg_adapter, row_seg, x, a_bank, b_bank)


def lora_delta(x, a_bank, b_bank, row_adapter):
    """THE batched-LoRA delta entry point: the segmented kernel when
    eligible, else the eager gather fallback (= the oracle). Returns
    [B, dout] fp32."""
    b, din = x.shape
    rank = a_bank.shape[-1]
    dout = b_bank.shape[-1]
    if lora_kernel_ineligible_reason(din, dout, rank, b) is None:
        return lora_segmented_delta(x, a_bank, b_bank, row_adapter)
    return lora_delta_reference(x, a_bank, b_bank, row_adapter)


def _lora_rows_delta(x, bank_pair, row_adapter):
    """Delta for possibly-[B, S, din] x against one target's per-layer
    bank pair, broadcasting the per-SLOT adapter ids over S (the
    engine's batch dim is slots; every token row of a slot wears its
    slot's adapter). Returns x-shaped fp32 delta."""
    a_bank, b_bank = bank_pair
    if x.ndim == 2:
        return lora_delta(x, a_bank, b_bank, row_adapter)
    b, s, din = x.shape
    ids = jnp.repeat(row_adapter, s)
    flat = lora_delta(x.reshape(b * s, din), a_bank, b_bank, ids)
    return flat.reshape(b, s, -1)


def apply_lora_delta(y, x, lora, target):
    """Add ``target``'s adapter delta to base output y (computed from
    input x), when lora carries that target; no-op otherwise. The ONE
    call-site helper the unfused forward passes use — delta in fp32,
    cast into y's dtype at the add (zero-B adapters add an exact 0.0
    and leave y's token stream bitwise unchanged)."""
    if lora is None or target not in lora["banks"]:
        return y
    d = _lora_rows_delta(x, lora["banks"][target], lora["row_adapter"])
    return y + d.astype(y.dtype)
