"""Paged-attention kernel GENERATOR + fused (megakernel) decode kernels.

ISSUE 11 tentpole. Before this module, ops/pallas/paged_attention.py
hand-wrote four kernel variants (decode / multiquery × plain / tp) × two
KV dtypes (bf16, int8 dequant-in-register) — eight bodies that had to be
edited in lockstep. Every variant differed from the others along exactly
three axes, so the bodies are now EMITTED from a spec instead of copied:

  - ``ragged``     one query row per slot (decode) vs a per-request
                   ragged q_len ∈ [1, S_q] window (speculative verify /
                   chunked prefill) with the causal-tail mask and the
                   q_lens scalar-prefetch ref;
  - ``quantized``  bf16 pools vs int8 pools whose per-(row, kv-head)
                   fp32 scale blocks ride the SAME page-table BlockSpec
                   index map and dequantize in-register;
  - tp head-shard  plain single-device placement vs a FULL-MANUAL
                   shard_map over KV heads (``mesh=`` — each shard runs
                   the emitted kernel on its matched GQA groups against
                   its 1/tp slice of the pool).

``paged_attention`` is the one entry point; the legacy names in
paged_attention.py are thin wrappers over it. The emitted body is
op-for-op the legacy body (the ragged=False specialization collapses the
window transposes exactly the way the hand-written decode kernel did),
so generated kernels are BITWISE-identical to the variants they replace
— pinned in tests/test_kernel_gen.py against frozen copies of the old
bodies across {bf16, int8} × {tp1, tp2} × {q_len 1, ragged} ×
{GQA, MHA}. New variants (fp8 pools, MLA latent layouts, token-tree
masks) are parameters here, not new copies.

The second half of the module is the FUSED DECODE STEP (megakernel
direction, *Event Tensor* arXiv 2604.13327): at decode batch sizes the
per-token step is dispatch-dominated (PERF.md: 35.7% MFU full-step vs
63.6% one layer body), so the dispatch-heavy tail of the layer body is
folded into three fat Pallas kernels —

  - ``fused_qkv``      RMS/LayerNorm + QKV projection + (optional) QK
                       layernorm + rope, one kernel per layer entry;
  - ``fused_out_proj`` attention epilogue: GQA head-flatten + out
                       projection + bias + residual add;
  - ``fused_mlp``      pre-MLP norm + fc1 + activation (incl. gated) +
                       fc2 + bias + residual add.

``fused_layer_decode`` assembles them around the generated paged
attention kernel; transformer/block.py dispatches it for the s == 1
paged decode path when ``cfg.megakernel_decode`` is on
(DynamicInferenceEngine(fused_decode=True) / --megakernel-decode).
Greedy streams are pinned token-exact against the unfused engine; the
win is gated off the COMPILED module (utils/dispatch.py counts
executable fusions/custom-calls per decode step), not wall time — the
TPU tunnel is down, so on-chip wall numbers wait for the chip
(PERF.md round-15).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dequant_block(k, ks):
    """[bs, Hkv, D] int8 block × [bs, Hkv] fp32 scales → fp32 block (the
    in-register dequant of one DMA'd page)."""
    return k.astype(jnp.float32) * ks[..., None]


# ---------------------------------------------------------------------------
# The generator: one spec → one emitted ragged-paged-attention body
# ---------------------------------------------------------------------------


QUANT_DTYPES = {
    # THE canonical quantized-KV storage registry: quant_dtype axis of
    # PagedSpec → (page jnp dtype, TPU min tile (sublane, lane) for the
    # KV block windows, symmetric quantization range bound qmax). Both
    # 1-byte formats want the (32, 128) layout on-chip; bf16 pools tile
    # (16, 128). The tile is PARAMETERIZED (not hard-coded in the body)
    # so the fp8 (32, 128) layout can be flipped on and validated when
    # the chip returns — interpret mode (CPU) imposes no tiling, so the
    # same spec runs everywhere today. quantize_kv_rows derives its
    # range from qmax, and the serving-facing KV_CACHE_DTYPES registry
    # (inference/paged_cache.py) builds its quantized entries FROM this
    # map — one place to add a storage dtype end-to-end.
    "int8": (jnp.int8, (32, 128), 127.0),
    "fp8": (jnp.float8_e4m3fn, (32, 128), 448.0),
}


def quant_dtype_of(pages_dtype) -> Optional[str]:
    """Map a page pool's storage dtype to the PagedSpec quant_dtype axis
    (None = unquantized compute-dtype pool)."""
    for name, (dt, _, _) in QUANT_DTYPES.items():
        if jnp.dtype(pages_dtype) == jnp.dtype(dt):
            return name
    return None


def quant_qmax_of(pages_dtype) -> float:
    """Symmetric quantization range bound for a registered quantized
    page dtype (127 int8, 448 e4m3)."""
    name = quant_dtype_of(pages_dtype)
    if name is None:
        raise ValueError(
            f"{pages_dtype} is not a registered quantized KV storage "
            f"dtype ({sorted(QUANT_DTYPES)})")
    return QUANT_DTYPES[name][2]


def default_kv_tile(quant_dtype: Optional[str]):
    """Min TPU tile (sublane, lane) of the KV block windows for this
    storage dtype — the shape knob an on-chip tuning pass flips."""
    if quant_dtype is None:
        return (16, 128)
    return QUANT_DTYPES[quant_dtype][1]


@dataclasses.dataclass(frozen=True)
class PagedSpec:
    """Everything that selects a paged-attention kernel variant.

    ragged=False requires s_q == 1 (the decode shape); ragged=True adds
    the q_lens scalar-prefetch ref and the causal tail mask over the
    [1, S_q] window. quant_dtype ("int8" | "fp8" | None) adds the
    scale-block refs and the in-register dequant of each DMA'd block —
    the dequant body (cast to fp32 × per-(row, head) scale) is shared by
    both quantized formats, so a new storage dtype is a registry entry
    (QUANT_DTYPES), not a new body. kv_tile is the (sublane, lane) min
    tile of the KV block windows (dtype-dependent on TPU — fp8/int8 want
    (32, 128)); interpret mode ignores it, and paged_attention derives
    the per-dtype default, so it only needs touching for on-chip layout
    experiments. The tp head-shard axis is NOT part of the body spec —
    sharding is pure placement (``paged_attention(..., mesh=)`` wraps
    the same emitted kernel in a full-manual shard_map)."""

    ragged: bool
    quant_dtype: Optional[str]
    s_q: int
    block_size: int
    num_blocks_seq: int
    hkv: int
    group: int
    scale: float
    kv_tile: tuple = (16, 128)

    @property
    def quantized(self) -> bool:
        return self.quant_dtype is not None

    def __post_init__(self):
        if not self.ragged and self.s_q != 1:
            raise ValueError(
                f"non-ragged (decode) kernels are single-query: s_q="
                f"{self.s_q} requires ragged=True (pass q_lens)")
        if self.quant_dtype is not None \
                and self.quant_dtype not in QUANT_DTYPES:
            raise ValueError(
                f"quant_dtype must be one of {sorted(QUANT_DTYPES)} or "
                f"None, got {self.quant_dtype!r}")
        if len(self.kv_tile) != 2 or self.kv_tile[1] % 128:
            raise ValueError(
                f"kv_tile must be (sublane, lane) with lane a multiple "
                f"of 128, got {self.kv_tile!r}")


def emit_paged_kernel(spec: PagedSpec):
    """Emit the kernel body for `spec`.

    Grid (B, max_blocks_per_seq); block j of slot b is DMA'd from page
    table[b, j] (scalar-prefetched index map). Online softmax over the
    ragged valid range [0, lens[b]); fully-out-of-range blocks are
    skipped whole. Ragged kernels additionally mask each local query row
    i (absolute position kv_len - q_len + i) causally within the new
    tail; at q_len == 1 the math collapses to the decode body's exact
    block/accumulator order — the two legacy variants were the
    ragged=False / ragged=True points of this one template."""
    bs = spec.block_size
    mbs = spec.num_blocks_seq
    hkv, group, s_q = spec.hkv, spec.group, spec.s_q
    hq = hkv * group
    ragged, quantized = spec.ragged, spec.quantized
    scale = spec.scale

    def kernel(*refs):
        if ragged:
            table_ref, lens_ref, qlens_ref = refs[:3]
            rest = refs[3:]
        else:
            table_ref, lens_ref = refs[:2]
            rest = refs[2:]
        del table_ref  # indirection is consumed by the BlockSpec index maps
        q_ref, k_ref, v_ref = rest[:3]
        rest = rest[3:]
        if quantized:
            ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
        else:
            o_ref, acc, m_scr, l_scr = rest
        b = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            acc[:] = jnp.zeros_like(acc)
            m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr)

        kv_len = lens_ref[b]
        if ragged:
            q_len = qlens_ref[b]
            q_start = kv_len - q_len   # absolute position of local query 0

        @pl.when(j * bs < kv_len)
        def _compute():
            q = q_ref[0].astype(jnp.float32) * scale
            if quantized:
                k = _dequant_block(k_ref[0], ks_ref[0])   # [bs, Hkv, D]
                v = _dequant_block(v_ref[0], vs_ref[0])
            else:
                k = k_ref[0]                              # [bs, Hkv, D]
                v = v_ref[0]
            d = q.shape[-1]
            if ragged:
                # [Hkv, S_q*group, D] with inner index i = s*group + g
                # (row i's query position is i // group after unfolding
                # back through the [S_q, Hq] layout below).
                q3 = jnp.transpose(q.reshape(s_q, hkv, group, d),
                                   (1, 0, 2, 3)).reshape(hkv, s_q * group,
                                                         d)
            else:
                q3 = q.reshape(hkv, group, d)
            k3 = jnp.swapaxes(k, 0, 1)                    # [Hkv, bs, D]
            v3 = jnp.swapaxes(v, 0, 1)
            s = jax.lax.dot_general(                      # [Hkv, rows, bs]
                q3.astype(k3.dtype), k3,
                (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            pos = j * bs + jax.lax.broadcasted_iota(
                jnp.int32, (1, bs), 1)[0]
            if ragged:
                row_q = jax.lax.broadcasted_iota(
                    jnp.int32, (s_q * group, 1), 0)[:, 0] // group
                abs_q = q_start + row_q                   # [S_q*group]
                valid = ((pos[None, :] <= abs_q[:, None])
                         & (pos[None, :] < kv_len))       # [S_q*g, bs]
                s = jnp.where(valid[None], s, _NEG_INF)
                # [S_q*Hq, bs] with row = s*hq + h (h = kvh*group + g).
                s2 = jnp.transpose(
                    s.reshape(hkv, s_q, group, bs),
                    (1, 0, 2, 3)).reshape(s_q * hq, bs)
                p_mask = jnp.transpose(
                    jnp.broadcast_to(valid.reshape(1, s_q, group, bs),
                                     (hkv, s_q, group, bs)),
                    (1, 0, 2, 3)).reshape(s_q * hq, bs)
            else:
                valid = pos < kv_len                      # [bs]
                s = jnp.where(valid[None, None, :], s, _NEG_INF)
                s2 = s.reshape(hq, bs)
                p_mask = valid[None, :]

            m_prev = m_scr[:, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1))
            m_safe = jnp.maximum(m_new, _NEG_INF / 2)
            p = jnp.exp(s2 - m_safe[:, None])
            p = jnp.where(p_mask, p, 0.0)
            corr = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
            corr = jnp.where(m_prev <= _NEG_INF / 2, 0.0, corr)
            l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=1)
            if ragged:
                p3 = jnp.transpose(
                    p.reshape(s_q, hkv, group, bs),
                    (1, 0, 2, 3)).reshape(hkv, s_q * group, bs)
            else:
                p3 = p.reshape(hkv, group, bs)
            pv = jax.lax.dot_general(                     # [Hkv, rows, D]
                p3.astype(v3.dtype), v3,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            if ragged:
                pv2 = jnp.transpose(
                    pv.reshape(hkv, s_q, group, d),
                    (1, 0, 2, 3)).reshape(s_q * hq, d)
            else:
                pv2 = pv.reshape(hq, d)
            acc[:] = acc[:] * corr[:, None] + pv2
            m_scr[:, 0] = m_new

        @pl.when(j == mbs - 1)
        def _finalize():
            l = jnp.maximum(l_scr[:, 0], 1e-20)
            if ragged:
                a = acc[:]
                o_ref[0] = (a / l[:, None]).reshape(
                    s_q, hq, a.shape[-1]).astype(o_ref.dtype)
            else:
                o_ref[0] = (acc[:] / l[:, None]).astype(o_ref.dtype)

    return kernel


def paged_attention(q: jnp.ndarray, k_pages: jnp.ndarray,
                    v_pages: jnp.ndarray, page_table: jnp.ndarray,
                    kv_lens: jnp.ndarray,
                    q_lens: Optional[jnp.ndarray] = None,
                    softmax_scale: Optional[float] = None,
                    k_scales: Optional[jnp.ndarray] = None,
                    v_scales: Optional[jnp.ndarray] = None,
                    mesh=None) -> jnp.ndarray:
    """Ragged paged attention — the single generator entry point.

    q [B, Hq, D] (decode) or [B, S_q, Hq, D] with q_lens [B] (ragged
    multi-query); k_pages/v_pages [NB, bs, Hkv, D]; page_table [B, MB]
    int32; kv_lens [B]. k_scales/v_scales [NB, bs, Hkv] fp32 mark int8
    pools (dequant rides the same page-table indirection, in-register).
    mesh: head-shard the emitted kernel over the tp axis of this mesh
    (full-manual shard_map — q on heads, pools + scale pools on Hkv,
    table/lens replicated); callers gate on tp_paged_eligible. Returns
    q's shape."""
    ragged = q_lens is not None
    if mesh is not None:
        return _tp_place(q, k_pages, v_pages, page_table, kv_lens, q_lens,
                         softmax_scale, k_scales, v_scales, mesh)
    if ragged:
        b, s_q, hq, d = q.shape
    else:
        b, hq, d = q.shape
        s_q = 1
    nb, bs, hkv, _ = k_pages.shape
    mb = page_table.shape[1]
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    quantized = k_scales is not None
    quant_dtype = quant_dtype_of(k_pages.dtype) if quantized else None
    if quantized and quant_dtype is None:
        raise ValueError(
            f"scales passed but page dtype {k_pages.dtype} is not a "
            f"registered quantized storage format "
            f"({sorted(QUANT_DTYPES)})")
    spec = PagedSpec(ragged=ragged, quant_dtype=quant_dtype, s_q=s_q,
                     block_size=bs, num_blocks_seq=mb, hkv=hkv,
                     group=hq // hkv, scale=float(softmax_scale),
                     kv_tile=default_kv_tile(quant_dtype))

    kernel = emit_paged_kernel(spec)

    # Page-table indirection: the table and per-slot lengths (and ragged
    # q_lens) are scalar-prefetched so the index maps can DMA block
    # t[b, j] straight from HBM — int8 scale blocks ride the same map.
    kv_spec = pl.BlockSpec((1, bs, hkv, d),
                           lambda b_, j, t, *_: (t[b_, j], 0, 0, 0))
    if ragged:
        q_spec = pl.BlockSpec((1, s_q, hq, d),
                              lambda b_, j, *_: (b_, 0, 0, 0))
    else:
        q_spec = pl.BlockSpec((1, hq, d), lambda b_, j, *_: (b_, 0, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [q, k_pages, v_pages]
    if quantized:
        sc_spec = pl.BlockSpec((1, bs, hkv),
                               lambda b_, j, t, *_: (t[b_, j], 0, 0))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3 if ragged else 2,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((s_q * hq, d), jnp.float32),
            pltpu.VMEM((s_q * hq, 1), jnp.float32),
            pltpu.VMEM((s_q * hq, 1), jnp.float32),
        ],
    )
    prefetch = [page_table.astype(jnp.int32), kv_lens.astype(jnp.int32)]
    if ragged:
        prefetch.append(q_lens.astype(jnp.int32))
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(*prefetch, *operands)


def _tp_place(q, k_pages, v_pages, page_table, kv_lens, q_lens,
              softmax_scale, k_scales, v_scales, mesh):
    """Head-sharded placement of the emitted kernel: a FULL-MANUAL
    shard_map over the tp axis — q sharded on heads, pools (and int8
    scale pools) on Hkv, page table / lengths / q_lens replicated. Each
    shard owns matched GQA groups (contiguous slicing of both head dims
    preserves h // group), so the per-shard body is the UNMODIFIED
    emitted kernel; no collectives run inside. tp_paged_eligible callers
    gate on no ambient manual axes."""
    from jax.sharding import PartitionSpec as P

    from megatronapp_tpu.config.parallel_config import TP_AXIS
    from megatronapp_tpu.parallel.collectives import shard_map_compat

    ragged = q_lens is not None
    if softmax_scale is None:
        softmax_scale = 1.0 / (q.shape[-1] ** 0.5)
    head = (P(None, None, TP_AXIS, None) if ragged
            else P(None, TP_AXIS, None))
    pages = P(None, None, TP_AXIS, None)      # pools [NB, bs, Hkv, D]
    scales = P(None, None, TP_AXIS)           # scale pools [NB, bs, Hkv]
    rep2, rep1 = P(None, None), P(None)

    in_specs = [head, pages, pages, rep2, rep1]
    operands = [q, k_pages, v_pages, page_table, kv_lens]
    if ragged:
        in_specs.append(rep1)
        operands.append(q_lens)
    if k_scales is not None:
        in_specs += [scales, scales]
        operands += [k_scales, v_scales]

    def body(*args):
        q_, k_, v_, t_, l_ = args[:5]
        rest = args[5:]
        ql_ = None
        if ragged:
            ql_, rest = rest[0], rest[1:]
        ks_ = vs_ = None
        if rest:
            ks_, vs_ = rest
        return paged_attention(q_, k_, v_, t_, l_, q_lens=ql_,
                               softmax_scale=softmax_scale,
                               k_scales=ks_, v_scales=vs_)

    # manual-ok: full-manual kernel placement, no collectives in body;
    # tp_paged_eligible callers gate on no ambient manual axes.
    return shard_map_compat(body, mesh, in_specs=tuple(in_specs),
                            out_specs=head)(*operands)


# ---------------------------------------------------------------------------
# Fused (megakernel) decode-layer kernels
#
# One decode token's layer body is ~15 small XLA fusions (two norms, two
# projection matmuls + biases, rope, GQA reshapes, out-proj, fc1/act/
# fc2, two residual adds) — each a separate dispatch inside the scan
# body. The three kernels below fold that tail into fat single-program
# Pallas kernels around the generated paged-attention kernel. Math is
# op-for-op the unfused path's (same norm/rope/activation formulas, same
# dtypes/casts), so greedy streams stay token-exact — pinned in
# tests/test_kernel_gen.py. Shapes: decode x is [B, H] with B = a
# handful of slots, so whole-operand (no-grid) kernels are the right
# granularity; weights must fit the VMEM budget
# (megakernel_ineligible_reason gates "where shapes allow"; a
# grid-tiled variant for big models is the ROADMAP follow-up).
# ---------------------------------------------------------------------------

# Per-kernel operand budget for the no-grid fused kernels. Real TPU
# VMEM is ~16 MB/core; interpret mode (CPU) has no limit but keeps the
# same gate so eligibility is platform-independent. Operators can
# override via MEGAKERNEL_VMEM_BUDGET (bytes) — e.g. raise it on CPU
# engines or chips with more VMEM; the fallback log names the budget.
MEGAKERNEL_VMEM_BUDGET = int(os.environ.get(
    "MEGAKERNEL_VMEM_BUDGET", 12 * 1024 * 1024))


def _rope_rows(x, cos, sin):
    """Half-rotation RoPE on [B, H, D] rows with per-row tables
    [B, half] — elementwise-identical to ops.rotary.apply_rope on the
    [B, 1, H, D] decode shape (fp32 rotate, cast back)."""
    half = cos.shape[-1]
    rot = 2 * half
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    x1, x2 = x_rot[..., :half], x_rot[..., half:]
    c = cos[:, None, :]
    s = sin[:, None, :]
    out1 = x1.astype(jnp.float32) * c - x2.astype(jnp.float32) * s
    out2 = x2.astype(jnp.float32) * c + x1.astype(jnp.float32) * s
    out = jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
    if x_pass.shape[-1]:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out


def _fused_qkv(x, attn_p, cfg, cos, sin):
    """Norm + QKV projection + (optional) QK-layernorm + rope in ONE
    kernel — the attention kernel's entry, fused.

    x [B, H] (residual dtype); returns (q, k, v) as [B, nq, D] /
    [B, nkv, D] in compute dtype, exactly as the unfused
    layer_forward → attention_forward prologue produces them."""
    from megatronapp_tpu.config.transformer_config import NormKind
    from megatronapp_tpu.inference.quantization import resolve_param
    from megatronapp_tpu.ops.normalization import apply_norm, rms_norm

    b, h = x.shape
    nq, nkv, d = (cfg.num_attention_heads, cfg.num_query_groups,
                  cfg.head_dim)
    cdt = cfg.compute_dtype
    eps = cfg.layernorm_epsilon
    kind = cfg.normalization
    has_ln_bias = kind == NormKind.layernorm
    has_bias = "q_bias" in attn_p
    has_rope = cos is not None
    has_qk_ln = cfg.qk_layernorm

    operands = [x, attn_p["ln1_scale"]]
    if has_ln_bias:
        operands.append(attn_p["ln1_bias"])
    operands += [resolve_param(attn_p["q_kernel"]),
                 resolve_param(attn_p["kv_kernel"])]
    if has_bias:
        operands += [attn_p["q_bias"], attn_p["kv_bias"]]
    if has_rope:
        operands += [cos, sin]
    if has_qk_ln:
        operands += [attn_p["q_ln_scale"], attn_p["k_ln_scale"]]

    def kernel(*refs):
        it = iter(refs)
        x_ref = next(it)
        ln_s = next(it)
        ln_b = next(it) if has_ln_bias else None
        wq_ref, wkv_ref = next(it), next(it)
        qb_ref = next(it) if has_bias else None
        kvb_ref = next(it) if has_bias else None
        cos_ref = next(it) if has_rope else None
        sin_ref = next(it) if has_rope else None
        qln_ref = next(it) if has_qk_ln else None
        kln_ref = next(it) if has_qk_ln else None
        q_out, k_out, v_out = next(it), next(it), next(it)

        xn = apply_norm(kind, x_ref[...], ln_s[...],
                        ln_b[...] if ln_b is not None else None, eps)
        xn = xn.astype(cdt)
        q = xn @ wq_ref[...].astype(cdt)
        kv = xn @ wkv_ref[...].astype(cdt)
        if has_bias:
            q = q + qb_ref[...].astype(cdt)
            kv = kv + kvb_ref[...].astype(cdt)
        q = q.reshape(b, nq, d)
        k, v = jnp.split(kv.reshape(b, 2 * nkv, d), 2, axis=1)
        if has_qk_ln:
            q = rms_norm(q, qln_ref[...], eps)
            k = rms_norm(k, kln_ref[...], eps)
        if has_rope:
            q = _rope_rows(q, cos_ref[...], sin_ref[...])
            k = _rope_rows(k, cos_ref[...], sin_ref[...])
        q_out[...] = q
        k_out[...] = k
        v_out[...] = v

    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((b, nq, d), cdt),
                   jax.ShapeDtypeStruct((b, nkv, d), cdt),
                   jax.ShapeDtypeStruct((b, nkv, d), cdt)],
        interpret=_interpret(),
    )(*operands)


def _fused_out_proj(attn_flat, attn_p, cfg, residual):
    """Attention epilogue in ONE kernel: out projection + bias +
    residual add (the paged-attention output arrives head-flat
    [B, nq*D] — the GQA transpose/reshape is folded into the caller's
    free reshape). residual [B, H] keeps its dtype; returns [B, H]."""
    from megatronapp_tpu.inference.quantization import resolve_param

    b, h = residual.shape
    cdt = cfg.compute_dtype
    has_bias = "out_bias" in attn_p
    operands = [attn_flat, resolve_param(attn_p["out_kernel"]), residual]
    if has_bias:
        operands.append(attn_p["out_bias"])

    def kernel(*refs):
        if has_bias:
            a_ref, w_ref, r_ref, b_ref, o_ref = refs
        else:
            a_ref, w_ref, r_ref, o_ref = refs
        out = a_ref[...] @ w_ref[...].astype(cdt)
        if has_bias:
            out = out + b_ref[...].astype(cdt)
        r = r_ref[...]
        o_ref[...] = r + out.astype(r.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h), residual.dtype),
        interpret=_interpret(),
    )(*operands)


def _fused_mlp(x, p, cfg):
    """Pre-MLP norm + fc1 + activation (incl. gated) + fc2 + biases +
    residual add in ONE kernel. x [B, H] (residual dtype) → [B, H]."""
    from megatronapp_tpu.config.transformer_config import NormKind
    from megatronapp_tpu.inference.quantization import resolve_param
    from megatronapp_tpu.ops.activations import apply_activation, is_gated
    from megatronapp_tpu.ops.normalization import apply_norm

    b, h = x.shape
    cdt = cfg.compute_dtype
    eps = cfg.layernorm_epsilon
    kind = cfg.normalization
    act = cfg.activation
    gated = is_gated(act)
    has_ln_bias = kind == NormKind.layernorm
    mlp_p = p["mlp"]
    has_bias = "fc1_bias" in mlp_p

    operands = [x, p["ln2_scale"]]
    if has_ln_bias:
        operands.append(p["ln2_bias"])
    operands += [resolve_param(mlp_p["fc1_kernel"]),
                 resolve_param(mlp_p["fc2_kernel"])]
    if has_bias:
        operands += [mlp_p["fc1_bias"], mlp_p["fc2_bias"]]

    def kernel(*refs):
        it = iter(refs)
        x_ref, ln_s = next(it), next(it)
        ln_b = next(it) if has_ln_bias else None
        w1_ref, w2_ref = next(it), next(it)
        b1_ref = next(it) if has_bias else None
        b2_ref = next(it) if has_bias else None
        o_ref = next(it)

        xn = apply_norm(kind, x_ref[...], ln_s[...],
                        ln_b[...] if ln_b is not None else None, eps)
        xn = xn.astype(cdt)
        y = xn @ w1_ref[...].astype(cdt)
        if has_bias:
            y = y + b1_ref[...].astype(cdt)
        if gated:
            gate, val = jnp.split(y, 2, axis=-1)
            y = apply_activation(act, val, gate)
        else:
            y = apply_activation(act, y)
        out = y @ w2_ref[...].astype(cdt)
        if has_bias:
            out = out + b2_ref[...].astype(cdt)
        r = x_ref[...]
        o_ref[...] = r + out.astype(r.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h), x.dtype),
        interpret=_interpret(),
    )(*operands)


def fused_layer_decode(p, x, cfg, rope_cos, rope_sin, kv_cache,
                       cache_positions, page_table, active,
                       kv_scales=None):
    """One decode layer as fused kernels: [fused norm+QKV+rope] →
    [append scatter] → [generated paged-attention kernel] → [fused
    out-proj + residual] → [fused norm+MLP + residual].

    Drop-in for transformer/block.layer_forward's s == 1 paged decode
    path (cfg.megakernel_decode; DynamicInferenceEngine(fused_decode=
    True)): same arguments, same ((out, new_cache), aux) return, greedy
    streams token-exact vs the unfused body. MegaScope capture /
    disturbance sites are NOT traced here — megakernel_ineligible_reason
    gates the fused path off while hooks are active."""
    from megatronapp_tpu.ops.pallas.paged_attention import (
        append_token_pages, quantize_kv_rows,
    )
    b = x.shape[0]
    assert x.shape[1] == 1, "fused_layer_decode is the s == 1 decode body"
    nq, d = cfg.num_attention_heads, cfg.head_dim
    attn_p = p["attention"]
    x2 = x[:, 0]
    cos = rope_cos[:, 0] if rope_cos is not None else None
    sin = rope_sin[:, 0] if rope_sin is not None else None

    q, k, v = _fused_qkv(x2, {**attn_p, "ln1_scale": p["ln1_scale"],
                              **({"ln1_bias": p["ln1_bias"]}
                                 if "ln1_bias" in p else {})},
                         cfg, cos, sin)

    ck, cv = kv_cache
    if active is None:
        active = jnp.ones((b,), bool)
    if kv_scales is not None:
        cks, cvs = kv_scales
        k_q, k_s = quantize_kv_rows(k, dtype=ck.dtype)
        v_q, v_s = quantize_kv_rows(v, dtype=cv.dtype)
        ck = append_token_pages(ck, k_q, page_table, cache_positions,
                                active)
        cv = append_token_pages(cv, v_q, page_table, cache_positions,
                                active)
        cks = append_token_pages(cks, k_s, page_table, cache_positions,
                                 active)
        cvs = append_token_pages(cvs, v_s, page_table, cache_positions,
                                 active)
        new_cache = (ck, cv, cks, cvs)
        sc_kw = {"k_scales": cks, "v_scales": cvs}
    else:
        ck = append_token_pages(ck, k, page_table, cache_positions, active)
        cv = append_token_pages(cv, v, page_table, cache_positions, active)
        new_cache = (ck, cv)
        sc_kw = {}

    attn = paged_attention(q, ck, cv, page_table, cache_positions + 1,
                           **sc_kw)                       # [B, nq, D]
    x2 = _fused_out_proj(attn.reshape(b, nq * d), attn_p, cfg, x2)
    x2 = _fused_mlp(x2, p, cfg)
    return (x2[:, None], new_cache), None


def megakernel_ineligible_reason(cfg, *, batch, tp_paged=False,
                                 paged=True, params=None) -> Optional[str]:
    """Why the fused (megakernel) decode step may NOT run — None when
    eligible, otherwise the FIRST failed predicate by name (same
    loud-fallback contract as tp_paged_ineligible_reason). params: the
    engine's param pytree when available — resident int8 weights
    (--quantized-weights) are ineligible because resolve_param runs
    OUTSIDE the fused kernels, which would materialize dequantized
    bf16 weight copies as kernel operands every step and give back
    PR 10's halved kernel HBM (the unfused path fuses the per-channel
    scale multiply into each consuming matmul)."""
    if not paged:
        return "dense (non-paged) backend — the fused step is built " \
               "around the paged-attention kernel"
    if cfg.multi_latent_attention:
        return "multi_latent_attention: the MLA decode path gathers " \
               "the latent run dense (no fused prologue yet)"
    if cfg.is_moe:
        return "MoE layers: expert dispatch is not fused yet"
    if getattr(cfg, "hetero_block_specs", None):
        return "heterogeneous per-layer configs unroll their own bodies"
    if tp_paged:
        return "tp head-sharded serving mesh: fused prologue/epilogue " \
               "kernels are single-device (the tp engine keeps the " \
               "unfused body)"
    from megatronapp_tpu.scope import hooks
    from megatronapp_tpu.scope.disturbance import get_disturbance
    cap_sites = ("qkv_q", "qkv_k", "qkv_v", "context", "mlp1", "mlp2",
                 "between_layers")
    if any(hooks.is_enabled(s) for s in cap_sites):
        return "MegaScope capture hooks active (fused kernels do not " \
               "trace capture sites)"
    dist = get_disturbance()
    if any(dist.active(s) for s in ("weight", "calculation", "system")):
        return "MegaScope disturbance sites active (fused kernels do " \
               "not trace perturbations)"
    if params is not None:
        from megatronapp_tpu.inference.quantization import is_resident_leaf
        if any(is_resident_leaf(leaf) for leaf in jax.tree.leaves(
                params, is_leaf=is_resident_leaf)):
            return ("resident int8 weights (--quantized-weights): the "
                    "fused kernels would materialize dequantized "
                    "weight copies per step — in-kernel weight dequant "
                    "is the recorded follow-up")
    # "Where shapes allow": the no-grid fused kernels hold their whole
    # operand set in VMEM — big models need the grid-tiled follow-up.
    h = cfg.hidden_size
    nq, nkv, d = (cfg.num_attention_heads, cfg.num_query_groups,
                  cfg.head_dim)
    fc1_out = mlp_bytes = 0
    from megatronapp_tpu.ops.activations import is_gated
    fc1_out = (2 * cfg.ffn_hidden_size if is_gated(cfg.activation)
               else cfg.ffn_hidden_size)
    itemsize = jnp.dtype(cfg.params_dtype).itemsize
    act_itemsize = jnp.dtype(cfg.compute_dtype).itemsize
    qkv_bytes = (h * nq * d + h * 2 * nkv * d) * itemsize \
        + batch * (h + (nq + 2 * nkv) * d) * act_itemsize
    mlp_bytes = (h * fc1_out + cfg.ffn_hidden_size * h) * itemsize \
        + batch * (2 * h + fc1_out) * act_itemsize
    worst = max(qkv_bytes, mlp_bytes)
    if worst > MEGAKERNEL_VMEM_BUDGET:
        return (f"fused-kernel operands ({worst} B) exceed the VMEM "
                f"budget ({MEGAKERNEL_VMEM_BUDGET} B) — needs the "
                f"grid-tiled megakernel follow-up")
    return None
