"""Pallas TPU flash attention (forward + custom-VJP backward).

The reference gets fused attention from TransformerEngine/Apex CUDA kernels
(SURVEY §2.7 native-code inventory: "Pallas flash attention" is the TPU
replacement obligation). This kernel:

- blockwise online-softmax forward, O(S) memory (no [Sq,Skv] materialized),
  fp32 accumulators, bf16 matmul inputs on the MXU;
- causal masking with whole-block skip for fully-masked tiles;
- GQA: KV heads indexed as h // group via BlockSpec index maps, no repeat;
- custom VJP with two backward kernels (dq; dk/dv), log-sum-exp residuals —
  the FlashAttention-2 recipe;
- runs in interpret mode on CPU (tests) and compiled on TPU.

Layout: [B, H, S, D] per-head-contiguous (callers reshape from [B,S,H,D]).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _force_straight() -> bool:
    """FLASH_STRAIGHT_ORIENTATION=1 pins the straight-orientation
    kernels even for D<128 — the measurement knob for A/Bing the
    transposed orientation on real hardware (tools/bench_profile.py)."""
    import os
    return os.environ.get("FLASH_STRAIGHT_ORIENTATION") == "1"


def _cdiv(a, b):
    return (a + b - 1) // b



def _mask_rows(x, start, limit):
    """Zero rows >= limit. Padding may be NaN (interpret mode pads with NaN),
    so this must be a select, not a multiply (NaN*0 == NaN)."""
    idx = start + jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0)
    return jnp.where(idx < limit, x, jnp.zeros_like(x))

def _valid_mask(q_start, k_start, block_q, block_kv, seq_q, seq_kv,
                causal, bounded, qs_ref, ks_ref):
    """[bq, bkv] validity mask with only the statically-needed terms:
    bounds checks when the sequence doesn't divide the block, the causal
    triangle, and packed-segment equality."""
    rows = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0)
    cols = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1)
    if bounded:
        valid = (rows < seq_q) & (cols < seq_kv)
        if causal:
            valid = valid & (rows >= cols)
    else:
        valid = rows >= cols if causal else jnp.ones(
            (block_q, block_kv), jnp.bool_)
    if qs_ref is not None:
        # Packed sequences: attend within-segment only (segment ids
        # [bq,1] vs [1,bkv] broadcast to the score block).
        valid = valid & (qs_ref[0] == ks_ref[0])
    return valid


def _dispatch_tiles(compute, causal, edge_mask, q_start, k_start,
                    block_q, block_kv):
    """Shared tile dispatch for all three kernels: skip tiles entirely
    above the causal diagonal, and route interior tiles (strictly below
    the diagonal, in-bounds, no segment ids) to compute(masked=False) —
    skipping the iota/compare/select chain on [bq, bkv] is the kernels'
    main VPU saving."""
    if causal:
        if edge_mask:
            @pl.when(q_start + block_q - 1 >= k_start)
            def _():
                compute(True)
        else:
            interior = q_start >= k_start + block_kv

            @pl.when(interior)
            def _():
                compute(False)

            @pl.when(jnp.logical_not(interior)
                     & (q_start + block_q - 1 >= k_start))
            def _():
                compute(True)
    else:
        compute(edge_mask)


# ---------------------------------------------------------------------------
# BlockSpec builders shared by all kernels. Every kernel runs on a
# (b, h, major, minor) grid where (major, minor) is (iq, ik) for
# q-major kernels (forward, dq) and (ik, iq) for kv-major ones (dkv);
# `q_major` picks which grid slot indexes the q blocks. Segment-id and
# lse/delta specs come in straight ([bq,1] columns) and transposed
# ([1,bq] lane rows) orientations.
# ---------------------------------------------------------------------------


def _spec_q(block_q, d, q_major):
    if q_major:
        return pl.BlockSpec((1, 1, block_q, d),
                            lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    return pl.BlockSpec((1, 1, block_q, d),
                        lambda b_, h_, ik, iq: (b_, h_, iq, 0))


def _spec_kv(block_kv, d, group, q_major):
    if q_major:
        return pl.BlockSpec(
            (1, 1, block_kv, d),
            lambda b_, h_, iq, ik, g_=group: (b_, h_ // g_, ik, 0))
    return pl.BlockSpec(
        (1, 1, block_kv, d),
        lambda b_, h_, ik, iq, g_=group: (b_, h_ // g_, ik, 0))


def _spec_segs(block_q, block_kv, q_major, transposed):
    """(q_segs, kv_segs) specs. Straight orientation reads q ids as a
    [bq, 1] column from [B,Sq,1] and kv ids as a [1, bkv] row from
    [B,1,Skv]; the transposed kernels read q ids as a [1, bq] row and kv
    ids as a [bkv, 1] column (callers swap the arrays to match)."""
    if transposed:
        q_shape, q_idx = (1, 1, block_q), (lambda b_, m, n: (b_, 0, m))
        k_shape, k_idx = (1, block_kv, 1), (lambda b_, m, n: (b_, n, 0))
    else:
        q_shape, q_idx = (1, block_q, 1), (lambda b_, m, n: (b_, m, 0))
        k_shape, k_idx = (1, 1, block_kv), (lambda b_, m, n: (b_, 0, n))
    iq_of = (lambda mj, mn: mj) if q_major else (lambda mj, mn: mn)
    ik_of = (lambda mj, mn: mn) if q_major else (lambda mj, mn: mj)
    return [
        pl.BlockSpec(q_shape,
                     lambda b_, h_, mj, mn: q_idx(b_, iq_of(mj, mn),
                                                  ik_of(mj, mn))),
        pl.BlockSpec(k_shape,
                     lambda b_, h_, mj, mn: k_idx(b_, iq_of(mj, mn),
                                                  ik_of(mj, mn))),
    ]


def _spec_qcol(block_q, q_major):
    """[bq, 1] per-q-row scalars (straight-orientation lse/delta)."""
    if q_major:
        return pl.BlockSpec((1, 1, block_q, 1),
                            lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    return pl.BlockSpec((1, 1, block_q, 1),
                        lambda b_, h_, ik, iq: (b_, h_, iq, 0))


def _spec_qrow(block_q, q_major):
    """[1, bq] lane-row scalars (transposed-orientation lse/delta)."""
    if q_major:
        return pl.BlockSpec((1, 1, 1, block_q),
                            lambda b_, h_, iq, ik: (b_, h_, 0, iq))
    return pl.BlockSpec((1, 1, 1, block_q),
                        lambda b_, h_, ik, iq: (b_, h_, 0, iq))


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, scale, causal, block_q, block_kv,
                num_kv, seq_q, seq_kv, has_segs, bounded):
    if has_segs:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref,
         o_ref, lse_ref, acc, m_scr, l_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    q_start = iq * block_q
    k_start = ik * block_kv

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * scale   # [bq, D]
        k = k_ref[0, 0]                               # [bkv, D]
        v = v_ref[0, 0]                               # [bkv, D]
        if bounded:
            q = _mask_rows(q, q_start, seq_q)
            k = _mask_rows(k, k_start, seq_kv)
            v = _mask_rows(v, k_start, seq_kv)
        s = jax.lax.dot_general(
            q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bkv]
        if masked:
            valid = _valid_mask(q_start, k_start, block_q, block_kv,
                                seq_q, seq_kv, causal, bounded,
                                qs_ref, ks_ref)
            s = jnp.where(valid, s, _NEG_INF)

        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(s - m_safe[:, None])
        if masked:
            p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        corr = jnp.where(m_prev <= _NEG_INF / 2, 0.0, corr)
        l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr[:, None] + pv
        m_scr[:, 0] = m_new

    _dispatch_tiles(compute, causal, bounded or has_segs, q_start, k_start,
                    block_q, block_kv)

    @pl.when(ik == num_kv - 1)
    def _finalize():
        l = l_scr[:, 0]
        o_ref[0, 0] = (acc[:] / jnp.maximum(l, 1e-20)[:, None]).astype(
            o_ref.dtype)
        m = m_scr[:, 0]
        lse = jnp.where(
            l > 0, jnp.maximum(m, _NEG_INF / 2) + jnp.log(
                jnp.maximum(l, 1e-20)), _NEG_INF)
        lse_ref[0, 0] = lse[:, None]


def _fwd_kernel_t(*refs, scale, causal, block_q, block_kv,
                  num_kv, seq_q, seq_kv, has_segs, bounded):
    """Forward in transposed orientation for D < 128: scores as
    s^T = k·q^T [bkv, bq], accumulator o^T [D, bq] filled by
    (p·v)^T = v^T·p — full-width contraction (bkv) and output (bq) dims
    where the straight orientation's p@v has only D output lanes. The
    online-softmax running max/sum live as [1, bq] lane rows; reductions
    run over sublanes (axis 0)."""
    if has_segs:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref,
         ot_ref, lse_ref, acc, m_scr, l_scr) = refs
    else:
        q_ref, k_ref, v_ref, ot_ref, lse_ref, acc, m_scr, l_scr = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    q_start = iq * block_q
    k_start = ik * block_kv

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * scale   # [bq, D]
        k = k_ref[0, 0]                               # [bkv, D]
        v = v_ref[0, 0]
        if bounded:
            q = _mask_rows(q, q_start, seq_q)
            k = _mask_rows(k, k_start, seq_kv)
            v = _mask_rows(v, k_start, seq_kv)
        st = jax.lax.dot_general(                     # k·q^T = s^T
            k, q.astype(k.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bkv, bq]
        if masked:
            valid = _valid_mask_t(q_start, k_start, block_q, block_kv,
                                  seq_q, seq_kv, causal, bounded,
                                  qs_ref, ks_ref)
            st = jnp.where(valid, st, _NEG_INF)

        m_prev = m_scr[0]                             # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(st, axis=0))
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(st - m_safe[None, :])             # [bkv, bq]
        if masked:
            p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        corr = jnp.where(m_prev <= _NEG_INF / 2, 0.0, corr)
        l_scr[0] = l_scr[0] * corr + jnp.sum(p, axis=0)
        pvt = jax.lax.dot_general(                    # v^T·p = (p·v)^T
            v, p.astype(v.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [D, bq]
        acc[:] = acc[:] * corr[None, :] + pvt
        m_scr[0] = m_new

    _dispatch_tiles(compute, causal, bounded or has_segs, q_start, k_start,
                    block_q, block_kv)

    @pl.when(ik == num_kv - 1)
    def _finalize():
        l = l_scr[0]
        ot_ref[0, 0] = (acc[:] / jnp.maximum(l, 1e-20)[None, :]).astype(
            ot_ref.dtype)
        m = m_scr[0]
        lse = jnp.where(
            l > 0, jnp.maximum(m, _NEG_INF / 2) + jnp.log(
                jnp.maximum(l, 1e-20)), _NEG_INF)
        lse_ref[0, 0] = lse[None, :]


def _flash_forward_t(q, k, v, scale, causal, block_q, block_kv, nq, nk,
                     bounded, group, segs):
    """D<128 forward: transposed-orientation kernel; output comes out as
    [B,H,D,Sq] and is swapped back here, lse as [B,H,1,Sq] rows."""
    b, h, sq, d = q.shape
    skv = k.shape[2]
    has_segs = segs is not None

    kernel = functools.partial(
        _fwd_kernel_t, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, num_kv=nk, seq_q=sq, seq_kv=skv,
        has_segs=has_segs, bounded=bounded)

    in_specs = [_spec_q(block_q, d, q_major=True),
                _spec_kv(block_kv, d, group, q_major=True),
                _spec_kv(block_kv, d, group, q_major=True)]
    inputs = [q, k, v]
    if has_segs:
        q_segs, kv_segs = segs                # [B,Sq,1] / [B,1,Skv]
        qs_row = jnp.swapaxes(q_segs, 1, 2)   # [B,1,Sq]
        ks_col = jnp.swapaxes(kv_segs, 1, 2)  # [B,Skv,1]
        in_specs += _spec_segs(block_q, block_kv, q_major=True,
                               transposed=True)
        inputs += [qs_row, ks_col]

    ot, lse_row = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, d, block_q),
                         lambda b_, h_, iq, ik: (b_, h_, 0, iq)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda b_, h_, iq, ik: (b_, h_, 0, iq)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, d, sq), q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((d, block_q), jnp.float32),
            pltpu.VMEM((1, block_q), jnp.float32),
            pltpu.VMEM((1, block_q), jnp.float32),
        ],
        interpret=_interpret(),
    )(*inputs)
    return jnp.swapaxes(ot, -1, -2), lse_row[:, :, 0, :]


def _flash_forward(q, k, v, scale, causal, block_q, block_kv, segs=None):
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    group = h // hkv
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)
    nq = _cdiv(sq, block_q)
    nk = _cdiv(skv, block_kv)

    bounded = (sq % block_q != 0) or (skv % block_kv != 0)
    if d < 128 and not _force_straight():
        return _flash_forward_t(q, k, v, scale, causal, block_q, block_kv,
                                nq, nk, bounded, group, segs)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, num_kv=nk, seq_q=sq, seq_kv=skv,
        has_segs=segs is not None, bounded=bounded)

    in_specs = [_spec_q(block_q, d, q_major=True),
                _spec_kv(block_kv, d, group, q_major=True),
                _spec_kv(block_kv, d, group, q_major=True)]
    inputs = [q, k, v]
    if segs is not None:
        q_segs, kv_segs = segs  # [B,Sq,1] / [B,1,Skv] int32
        in_specs += _spec_segs(block_q, block_kv, q_major=True,
                               transposed=False)
        inputs += [q_segs, kv_segs]

    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(*inputs)
    return out, lse[..., 0]


# ---------------------------------------------------------------------------
# Backward kernels
#
# Two orientations. For D >= 128 the straightforward one: accumulators
# [block, D] and the output-producing matmuls (dq = ds@k, dk = ds^T@q,
# dv = p^T@do) have N = D output lanes. At D = 64 that leaves half the
# MXU's 128 output columns (and half of every 128-lane vreg row of the
# accumulator) idle — PERF.md's main backward-kernel lever. The
# transposed orientation used when D < 128 computes dq^T = k^T·ds^T,
# dk^T = q^T·ds, dv^T = do^T·p instead: contraction and output dims are
# both the 512-wide sequence blocks (full MXU), the [D, block]
# accumulators fill whole vregs, and only the D-contracted score matmuls
# (s, dp) keep the intrinsic K=D underfill. Outputs land as [B,H,D,S]
# and are swapped back outside (one XLA transpose, O(bytes)).
# ---------------------------------------------------------------------------


def _valid_mask_t(q_start, k_start, block_q, block_kv, seq_q, seq_kv,
                  causal, bounded, qs_ref, ks_ref):
    """Transposed-orientation [bkv, bq] validity mask (rows = kv
    positions, cols = q positions) for the dq^T kernel."""
    rows = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_kv, block_q), 0)
    cols = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_kv, block_q), 1)
    if bounded:
        valid = (rows < seq_kv) & (cols < seq_q)
        if causal:
            valid = valid & (cols >= rows)
    else:
        valid = cols >= rows if causal else jnp.ones(
            (block_kv, block_q), jnp.bool_)
    if qs_ref is not None:
        # qs_ref[0]: [1, bq] lane row; ks_ref[0]: [bkv, 1] column.
        valid = valid & (ks_ref[0] == qs_ref[0])
    return valid


def _bwd_dq_kernel_t(*refs, scale, causal, block_q, block_kv, num_kv,
                     seq_q, seq_kv, has_segs, bounded):
    """dq in transposed orientation: scores as s^T = k·q^T [bkv, bq],
    accumulator dq^T [D, bq], final matmul k^T·ds^T with full-width
    contraction (bkv) and output (bq) dims."""
    if has_segs:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref, delta_ref,
         dqt_ref, dqt_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dqt_ref, dqt_acc) = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dqt_acc[:] = jnp.zeros_like(dqt_acc)

    q_start = iq * block_q
    k_start = ik * block_kv

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * scale   # [bq, D]
        k = k_ref[0, 0]                               # [bkv, D]
        v = v_ref[0, 0]
        if bounded:
            k = _mask_rows(k, k_start, seq_kv)
            v = _mask_rows(v, k_start, seq_kv)
        do = do_ref[0, 0].astype(jnp.float32)         # [bq, D]
        lse = lse_ref[0, 0]                           # [1, bq]
        delta = delta_ref[0, 0]                       # [1, bq]

        st = jax.lax.dot_general(                     # k·q^T = s^T
            k, q.astype(k.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bkv, bq]
        dpt = jax.lax.dot_general(                    # v·do^T = dp^T
            v, do.astype(v.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bkv, bq]
        if masked:
            valid = _valid_mask_t(q_start, k_start, block_q, block_kv,
                                  seq_q, seq_kv, causal, bounded,
                                  qs_ref, ks_ref)
            pt = jnp.where(valid, jnp.exp(st - lse), 0.0)
            dst = jnp.where(valid, pt * (dpt - delta), 0.0)
        else:
            pt = jnp.exp(st - lse)
            dst = pt * (dpt - delta)
        dqt_acc[:] += jax.lax.dot_general(            # k^T·ds^T = dq^T
            k, dst.astype(k.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [D, bq]

    _dispatch_tiles(compute, causal, bounded or has_segs, q_start, k_start,
                    block_q, block_kv)

    @pl.when(ik == num_kv - 1)
    def _finalize():
        dqt_ref[0, 0] = dqt_acc[:].astype(dqt_ref.dtype)


def _bwd_dkv_kernel_t(*refs, scale, causal,
                      block_q, block_kv, num_q, seq_q, seq_kv, has_segs,
                      bounded):
    """dk/dv in transposed orientation: scores stay [bq, bkv] (so the
    standard mask applies), but the accumulating matmuls contract over
    bq with D-row outputs: dv^T = do^T·p, dk^T = q^T·ds — full-width
    contraction and output dims, [D, bkv] accumulators."""
    if has_segs:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref, delta_ref,
         dkt_ref, dvt_ref, dkt_acc, dvt_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dkt_ref, dvt_ref, dkt_acc, dvt_acc) = refs
        qs_ref = ks_ref = None
    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dkt_acc[:] = jnp.zeros_like(dkt_acc)
        dvt_acc[:] = jnp.zeros_like(dvt_acc)

    q_start = iq * block_q
    k_start = ik * block_kv

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        if bounded:
            q = _mask_rows(q, q_start, seq_q)
            k = _mask_rows(k, k_start, seq_kv)
            v = _mask_rows(v, k_start, seq_kv)
            do = _mask_rows(do, q_start, seq_q)
        lse = lse_ref[0, 0][:, 0]
        delta = delta_ref[0, 0][:, 0]

        s = jax.lax.dot_general(q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do.astype(v.dtype), v,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if masked:
            valid = _valid_mask(q_start, k_start, block_q, block_kv,
                                seq_q, seq_kv, causal, bounded,
                                qs_ref, ks_ref)
            p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
            ds = jnp.where(valid, p * (dp - delta[:, None]), 0.0)
        else:
            p = jnp.exp(s - lse[:, None])          # [bq, bkv]
            ds = p * (dp - delta[:, None])         # [bq, bkv]
        # dv^T += do^T @ p   (contract bq; [D, bkv])
        dvt_acc[:] += jax.lax.dot_general(
            do.astype(v.dtype), p.astype(v.dtype),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dk^T += q^T @ ds (q already has scale folded in)
        dkt_acc[:] += jax.lax.dot_general(
            q.astype(k.dtype), ds.astype(k.dtype),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch_tiles(compute, causal, bounded or has_segs, q_start, k_start,
                    block_q, block_kv)

    @pl.when(iq == num_q - 1)
    def _finalize():
        dkt_ref[0, 0] = dkt_acc[:].astype(dkt_ref.dtype)
        dvt_ref[0, 0] = dvt_acc[:].astype(dvt_ref.dtype)

# ---------------------------------------------------------------------------
# Head-fold backward kernels (PERF.md lever 1, ISSUE 11): at D = 64 the
# straight kernels' [block, D] refs/accumulators fill only half of every
# 128-lane vreg row. Folding a PAIR of q heads into the trailing block
# dim ([B, H, S, D] → [B, H/2, S, 2D]) makes every q/do load, the dq /
# dk / dv accumulators, and the gradient stores full 128-lane rows, and
# halves the grid's head extent (half the per-tile dispatch overhead).
# The score matmuls stay per-head (two D-contracted dots per tile — the
# intrinsic K = D underfill is untouched, same as the transposed
# orientation). GQA: a pair must share its kv head, so eligibility is
# group even (pair inside one group) or group == 1 with hkv even (kv
# folds alongside q). Opt-in via flash_attention(head_fold=True) /
# --flash-head-fold; grad parity vs the unfolded kernels is pinned
# ≤ 1e-5 in tests/test_kernel_gen.py. On-chip A/B queued behind the
# tunnel; the CPU evidence is the fwd+bwd wall ratio + cost model in
# tools/megakernel_benchmark.py.
# ---------------------------------------------------------------------------


def _fold_heads(x):
    """[B, H, S, D] → [B, H/2, S, 2D] (head pair side by side in the
    trailing dim)."""
    b, h, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h // 2, 2, s, d), 2, 3).reshape(
        b, h // 2, s, 2 * d)


def _unfold_heads(x):
    """Inverse of _fold_heads."""
    b, hp, s, d2 = x.shape
    d = d2 // 2
    return jnp.swapaxes(x.reshape(b, hp, s, 2, d), 2, 3).reshape(
        b, 2 * hp, s, d)


def _fold_rows(x):
    """[B, H, S] per-row scalars (lse/delta) → [B, H/2, S, 2]."""
    b, h, s = x.shape
    return jnp.transpose(x.reshape(b, h // 2, 2, s), (0, 1, 3, 2))


def _bwd_dq_kernel_fold(*refs, scale, causal, block_q, block_kv, num_kv,
                        seq_q, seq_kv, bounded, kv_folded, d):
    """dq with a folded head pair: q/do/lse/delta/dq refs carry both
    heads ([bq, 2D] / [bq, 2]); the two per-head score chains share one
    [bq, bkv] validity mask and accumulate into the [bq, 2D] dq rows."""
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
     dq_ref, dq_acc) = refs
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = iq * block_q
    k_start = ik * block_kv

    def compute(masked):
        valid = None
        if masked:
            valid = _valid_mask(q_start, k_start, block_q, block_kv,
                                seq_q, seq_kv, causal, bounded,
                                None, None)
        for half in (0, 1):
            sl = slice(half * d, (half + 1) * d)
            q = q_ref[0, 0][:, sl].astype(jnp.float32) * scale
            do = do_ref[0, 0][:, sl].astype(jnp.float32)
            k = k_ref[0, 0][:, sl] if kv_folded else k_ref[0, 0]
            v = v_ref[0, 0][:, sl] if kv_folded else v_ref[0, 0]
            if bounded:
                k = _mask_rows(k, k_start, seq_kv)
                v = _mask_rows(v, k_start, seq_kv)
            lse = lse_ref[0, 0][:, half]
            delta = delta_ref[0, 0][:, half]

            s = jax.lax.dot_general(
                q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if masked:
                p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
                ds = jnp.where(valid, p * (dp - delta[:, None]), 0.0)
            else:
                p = jnp.exp(s - lse[:, None])
                ds = p * (dp - delta[:, None])
            dq_acc[:, sl] += jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

    _dispatch_tiles(compute, causal, bounded, q_start, k_start,
                    block_q, block_kv)

    @pl.when(ik == num_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel_fold(*refs, scale, causal, block_q, block_kv, num_q,
                         seq_q, seq_kv, bounded, kv_folded, d):
    """dk/dv with a folded q-head pair. kv_folded (MHA, hkv even): the
    kv pair folds alongside and the accumulators are [bkv, 2D]. Shared
    kv head (GQA, group even): both halves accumulate into one [bkv, D]
    dk/dv — the in-kernel half of the group reduction the caller
    finishes over pairs."""
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
     dk_ref, dv_ref, dk_acc, dv_acc) = refs
    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = iq * block_q
    k_start = ik * block_kv

    def compute(masked):
        valid = None
        if masked:
            valid = _valid_mask(q_start, k_start, block_q, block_kv,
                                seq_q, seq_kv, causal, bounded,
                                None, None)
        for half in (0, 1):
            sl = slice(half * d, (half + 1) * d)
            acc_sl = sl if kv_folded else slice(None)
            q = q_ref[0, 0][:, sl].astype(jnp.float32) * scale
            do = do_ref[0, 0][:, sl].astype(jnp.float32)
            k = k_ref[0, 0][:, sl] if kv_folded else k_ref[0, 0]
            v = v_ref[0, 0][:, sl] if kv_folded else v_ref[0, 0]
            if bounded:
                q = _mask_rows(q, q_start, seq_q)
                k = _mask_rows(k, k_start, seq_kv)
                v = _mask_rows(v, k_start, seq_kv)
                do = _mask_rows(do, q_start, seq_q)
            lse = lse_ref[0, 0][:, half]
            delta = delta_ref[0, 0][:, half]

            s = jax.lax.dot_general(
                q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if masked:
                s = jnp.where(valid, s, _NEG_INF)
                p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
                ds = jnp.where(valid, p * (dp - delta[:, None]), 0.0)
            else:
                p = jnp.exp(s - lse[:, None])          # [bq, bkv]
                ds = p * (dp - delta[:, None])         # [bq, bkv]
            # dv += p^T @ do ; dk += ds^T @ q (scale already in q)
            dv_acc[:, acc_sl] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_acc[:, acc_sl] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    _dispatch_tiles(compute, causal, bounded, q_start, k_start,
                    block_q, block_kv)

    @pl.when(iq == num_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def head_fold_eligible(h: int, hkv: int, d: int, segs=None) -> bool:
    """May the backward fold head pairs? 2D must fit the 128-lane vreg
    row, the q heads must pair evenly, every pair must share one kv head
    (group even) or fold its kv pair alongside (MHA, hkv even), and
    packed segments keep the unfolded kernels (their id specs are
    per-head-agnostic but the folded kernels don't thread them)."""
    group = h // hkv
    if segs is not None or 2 * d > 128 or h % 2:
        return False
    return (group % 2 == 0) or (group == 1 and hkv % 2 == 0)


def _flash_backward_fold(q, k, v, g, lse, delta, scale, causal,
                         block_q, block_kv, nq, nk, bounded, group):
    """Head-fold backward dispatch: fold pairs outside (one O(bytes)
    transpose per operand), run the folded kernels, unfold the
    gradients. GQA (group even) reduces dk/dv over pairs-per-group."""
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    kv_folded = group == 1

    qf = _fold_heads(q)
    dof = _fold_heads(g)
    lsef = _fold_rows(lse)
    deltaf = _fold_rows(delta)
    if kv_folded:
        kf, vf = _fold_heads(k), _fold_heads(v)
        kv_dim = 2 * d
        kv_idx_q = lambda b_, h_, iq, ik: (b_, h_, ik, 0)  # noqa: E731
        kv_idx_k = lambda b_, h_, ik, iq: (b_, h_, ik, 0)  # noqa: E731
    else:
        kf, vf = k, v
        kv_dim = d
        kv_idx_q = (lambda b_, h_, iq, ik,
                    g_=group: (b_, (2 * h_) // g_, ik, 0))
        kv_idx_k = (lambda b_, h_, ik, iq,
                    g_=group: (b_, (2 * h_) // g_, ik, 0))

    qp_spec = pl.BlockSpec((1, 1, block_q, 2 * d),
                           lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 2),
                            lambda b_, h_, iq, ik: (b_, h_, iq, 0))

    dqf = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_fold, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv, num_kv=nk,
                          seq_q=sq, seq_kv=skv, bounded=bounded,
                          kv_folded=kv_folded, d=d),
        grid=(b, h // 2, nq, nk),
        in_specs=[qp_spec,
                  pl.BlockSpec((1, 1, block_kv, kv_dim), kv_idx_q),
                  pl.BlockSpec((1, 1, block_kv, kv_dim), kv_idx_q),
                  qp_spec, row_spec, row_spec],
        out_specs=qp_spec,
        out_shape=jax.ShapeDtypeStruct((b, h // 2, sq, 2 * d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, 2 * d), jnp.float32)],
        interpret=_interpret(),
    )(qf, kf, vf, dof, lsef, deltaf)

    qp_spec_k = pl.BlockSpec((1, 1, block_q, 2 * d),
                             lambda b_, h_, ik, iq: (b_, h_, iq, 0))
    row_spec_k = pl.BlockSpec((1, 1, block_q, 2),
                              lambda b_, h_, ik, iq: (b_, h_, iq, 0))
    dkv_out_spec = pl.BlockSpec((1, 1, block_kv, kv_dim),
                                lambda b_, h_, ik, iq: (b_, h_, ik, 0))
    dkf, dvf = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_fold, scale=scale,
                          causal=causal, block_q=block_q,
                          block_kv=block_kv, num_q=nq, seq_q=sq,
                          seq_kv=skv, bounded=bounded,
                          kv_folded=kv_folded, d=d),
        grid=(b, h // 2, nk, nq),
        in_specs=[qp_spec_k,
                  pl.BlockSpec((1, 1, block_kv, kv_dim), kv_idx_k),
                  pl.BlockSpec((1, 1, block_kv, kv_dim), kv_idx_k),
                  qp_spec_k, row_spec_k, row_spec_k],
        out_specs=[dkv_out_spec, dkv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h // 2, skv, kv_dim), k.dtype),
            jax.ShapeDtypeStruct((b, h // 2, skv, kv_dim), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, kv_dim), jnp.float32),
            pltpu.VMEM((block_kv, kv_dim), jnp.float32),
        ],
        interpret=_interpret(),
    )(qf, kf, vf, dof, lsef, deltaf)

    dq = _unfold_heads(dqf)
    if kv_folded:
        dk, dv = _unfold_heads(dkf), _unfold_heads(dvf)
    else:
        # Each pair already summed its two halves into the shared kv
        # head; finish the GQA reduction over the group's pairs.
        dk = dkf.reshape(b, hkv, group // 2, skv, d).sum(axis=2)
        dv = dvf.reshape(b, hkv, group // 2, skv, d).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd_dq_kernel(*refs, scale, causal, block_q, block_kv, num_kv,
                   seq_q, seq_kv, has_segs, bounded):
    if has_segs:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = iq * block_q
    k_start = ik * block_kv

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        if bounded:
            k = _mask_rows(k, k_start, seq_kv)
            v = _mask_rows(v, k_start, seq_kv)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0]
        delta = delta_ref[0, 0][:, 0]

        s = jax.lax.dot_general(q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do.astype(v.dtype), v,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if masked:
            valid = _valid_mask(q_start, k_start, block_q, block_kv,
                                seq_q, seq_kv, causal, bounded,
                                qs_ref, ks_ref)
            s = jnp.where(valid, s, _NEG_INF)
            p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
            ds = jnp.where(valid, p * (dp - delta[:, None]), 0.0)
        else:
            p = jnp.exp(s - lse[:, None])
            ds = p * (dp - delta[:, None])
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    _dispatch_tiles(compute, causal, bounded or has_segs, q_start, k_start,
                    block_q, block_kv)

    @pl.when(ik == num_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal,
                    block_q, block_kv, num_q, seq_q, seq_kv, has_segs,
                    bounded):
    if has_segs:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qs_ref = ks_ref = None
    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = iq * block_q
    k_start = ik * block_kv

    def compute(masked):
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        if bounded:
            q = _mask_rows(q, q_start, seq_q)
            k = _mask_rows(k, k_start, seq_kv)
            v = _mask_rows(v, k_start, seq_kv)
            do = _mask_rows(do, q_start, seq_q)
        lse = lse_ref[0, 0][:, 0]
        delta = delta_ref[0, 0][:, 0]

        s = jax.lax.dot_general(q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do.astype(v.dtype), v,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if masked:
            valid = _valid_mask(q_start, k_start, block_q, block_kv,
                                seq_q, seq_kv, causal, bounded,
                                qs_ref, ks_ref)
            s = jnp.where(valid, s, _NEG_INF)
            p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
            ds = jnp.where(valid, p * (dp - delta[:, None]), 0.0)
        else:
            p = jnp.exp(s - lse[:, None])          # [bq, bkv]
            ds = p * (dp - delta[:, None])         # [bq, bkv]
        # dv += p^T @ do
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dk += ds^T @ q * scale (q already has scale folded in → use raw q)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch_tiles(compute, causal, bounded or has_segs, q_start, k_start,
                    block_q, block_kv)

    @pl.when(iq == num_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(res, g, scale, causal, block_q, block_kv, segs=None,
                    head_fold: bool = False):
    q, k, v, out, lse = res
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    group = h // hkv
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)
    nq = _cdiv(sq, block_q)
    nk = _cdiv(skv, block_kv)
    bounded = (sq % block_q != 0) or (skv % block_kv != 0)

    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # [B,H,Sq]
    if head_fold and head_fold_eligible(h, hkv, d, segs):
        return _flash_backward_fold(
            q, k, v, g, lse, delta, scale, causal, block_q, block_kv,
            nq, nk, bounded, group)
    if d < 128 and not _force_straight():
        return _flash_backward_t(
            q, k, v, g, lse, delta, scale, causal, block_q, block_kv,
            nq, nk, bounded, group, segs)
    lse4 = lse[..., None]
    delta4 = delta[..., None]

    dq_in_specs = [_spec_q(block_q, d, q_major=True),
                   _spec_kv(block_kv, d, group, q_major=True),
                   _spec_kv(block_kv, d, group, q_major=True)]
    dq_inputs = [q, k, v]
    if segs is not None:
        q_segs, kv_segs = segs
        dq_in_specs += _spec_segs(block_q, block_kv, q_major=True,
                                  transposed=False)
        dq_inputs += [q_segs, kv_segs]
    dq_in_specs += [_spec_q(block_q, d, q_major=True),
                    _spec_qcol(block_q, q_major=True),
                    _spec_qcol(block_q, q_major=True)]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv, num_kv=nk,
                          seq_q=sq, seq_kv=skv, has_segs=segs is not None,
                          bounded=bounded),
        grid=(b, h, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, iq, ik: (b_, h_, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*dq_inputs, g, lse4, delta4)

    # dk/dv computed at q-head granularity [B, H, Skv, D]; grouped heads are
    # reduced outside (GQA) — simple and correct; a fused variant can
    # accumulate in-kernel later.
    dkv_in_specs = [_spec_q(block_q, d, q_major=False),
                    _spec_kv(block_kv, d, group, q_major=False),
                    _spec_kv(block_kv, d, group, q_major=False)]
    dkv_inputs = [q, k, v]
    if segs is not None:
        q_segs, kv_segs = segs
        dkv_in_specs += _spec_segs(block_q, block_kv, q_major=False,
                                   transposed=False)
        dkv_inputs += [q_segs, kv_segs]
    dkv_in_specs += [_spec_q(block_q, d, q_major=False),
                     _spec_qcol(block_q, q_major=False),
                     _spec_qcol(block_q, q_major=False)]

    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv, num_q=nq,
                          seq_q=sq, seq_kv=skv, has_segs=segs is not None,
                          bounded=bounded),
        grid=(b, h, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, ik, iq: (b_, h_, ik, 0)),
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, ik, iq: (b_, h_, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, skv, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, skv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*dkv_inputs, g, lse4, delta4)

    if group > 1:
        dk = dk_full.reshape(b, hkv, group, skv, d).sum(axis=2)
        dv = dv_full.reshape(b, hkv, group, skv, d).sum(axis=2)
    else:
        dk, dv = dk_full, dv_full
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_backward_t(q, k, v, g, lse, delta, scale, causal,
                      block_q, block_kv, nq, nk, bounded, group, segs):
    """D<128 backward: transposed-orientation kernels (full MXU lanes —
    see the orientation note above). Gradients come out as [B,H,D,S] and
    are swapped back here."""
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    has_segs = segs is not None

    # lse/delta as [B,H,1,Sq] lane rows for the dq^T kernel.
    lse_row = lse[:, :, None, :]
    delta_row = delta[:, :, None, :]

    dq_in_specs = [_spec_q(block_q, d, q_major=True),
                   _spec_kv(block_kv, d, group, q_major=True),
                   _spec_kv(block_kv, d, group, q_major=True)]
    dq_inputs = [q, k, v]
    if has_segs:
        q_segs, kv_segs = segs              # [B,Sq,1] / [B,1,Skv]
        qs_row = jnp.swapaxes(q_segs, 1, 2)   # [B,1,Sq]
        ks_col = jnp.swapaxes(kv_segs, 1, 2)  # [B,Skv,1]
        dq_in_specs += _spec_segs(block_q, block_kv, q_major=True,
                                  transposed=True)
        dq_inputs += [qs_row, ks_col]
    dq_in_specs += [_spec_q(block_q, d, q_major=True),
                    _spec_qrow(block_q, q_major=True),
                    _spec_qrow(block_q, q_major=True)]

    dqt = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_t, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv, num_kv=nk,
                          seq_q=sq, seq_kv=skv, has_segs=has_segs,
                          bounded=bounded),
        grid=(b, h, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, d, block_q),
                               lambda b_, h_, iq, ik: (b_, h_, 0, iq)),
        out_shape=jax.ShapeDtypeStruct((b, h, d, sq), q.dtype),
        scratch_shapes=[pltpu.VMEM((d, block_q), jnp.float32)],
        interpret=_interpret(),
    )(*dq_inputs, g, lse_row, delta_row)

    lse4 = lse[..., None]
    delta4 = delta[..., None]
    dkv_in_specs = [_spec_q(block_q, d, q_major=False),
                    _spec_kv(block_kv, d, group, q_major=False),
                    _spec_kv(block_kv, d, group, q_major=False)]
    dkv_inputs = [q, k, v]
    if has_segs:
        q_segs, kv_segs = segs
        dkv_in_specs += _spec_segs(block_q, block_kv, q_major=False,
                                   transposed=False)
        dkv_inputs += [q_segs, kv_segs]
    dkv_in_specs += [_spec_q(block_q, d, q_major=False),
                     _spec_qcol(block_q, q_major=False),
                     _spec_qcol(block_q, q_major=False)]

    dkt_full, dvt_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_t, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv, num_q=nq,
                          seq_q=sq, seq_kv=skv, has_segs=has_segs,
                          bounded=bounded),
        grid=(b, h, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, d, block_kv),
                         lambda b_, h_, ik, iq: (b_, h_, 0, ik)),
            pl.BlockSpec((1, 1, d, block_kv),
                         lambda b_, h_, ik, iq: (b_, h_, 0, ik)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, d, skv), k.dtype),
            jax.ShapeDtypeStruct((b, h, d, skv), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((d, block_kv), jnp.float32),
            pltpu.VMEM((d, block_kv), jnp.float32),
        ],
        interpret=_interpret(),
    )(*dkv_inputs, g, lse4, delta4)

    dq = jnp.swapaxes(dqt, -1, -2)
    if group > 1:
        dk = jnp.swapaxes(
            dkt_full.reshape(b, hkv, group, d, skv).sum(axis=2), -1, -2)
        dv = jnp.swapaxes(
            dvt_full.reshape(b, hkv, group, d, skv).sum(axis=2), -1, -2)
    else:
        dk = jnp.swapaxes(dkt_full, -1, -2)
        dv = jnp.swapaxes(dvt_full, -1, -2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_bhsd(q, k, v, scale, causal, block_q, block_kv,
                          head_fold=False):
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_kv)
    return out


def _fwd_rule(q, k, v, scale, causal, block_q, block_kv, head_fold):
    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_kv)
    return out, (q, k, v, out, lse)


def _bwd_rule(scale, causal, block_q, block_kv, head_fold, res, g):
    return _flash_backward(res, g, scale, causal, block_q, block_kv,
                           head_fold=head_fold)


_flash_attention_bhsd.defvjp(_fwd_rule, _bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_attention_seg_bhsd(q, k, v, q_segs, kv_segs, scale, causal,
                              block_q, block_kv):
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_kv,
                            segs=(q_segs, kv_segs))
    return out


def _seg_fwd_rule(q, k, v, q_segs, kv_segs, scale, causal, block_q,
                  block_kv):
    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_kv,
                              segs=(q_segs, kv_segs))
    return out, (q, k, v, out, lse, q_segs, kv_segs)


def _seg_bwd_rule(scale, causal, block_q, block_kv, res, g):
    q, k, v, out, lse, q_segs, kv_segs = res
    dq, dk, dv = _flash_backward((q, k, v, out, lse), g, scale, causal,
                                 block_q, block_kv, segs=(q_segs, kv_segs))
    # Integer segment ids take float0 cotangents.
    import numpy as np
    f0 = jax.dtypes.float0
    return (dq, dk, dv, np.zeros(q_segs.shape, f0),
            np.zeros(kv_segs.shape, f0))


_flash_attention_seg_bhsd.defvjp(_seg_fwd_rule, _seg_bwd_rule)


def flash_attention(q, k, v, causal: bool = True,
                    softmax_scale: Optional[float] = None,
                    block_q: int = 512, block_kv: int = 512,
                    segment_ids: Optional[jnp.ndarray] = None,
                    head_fold: bool = False):
    """Flash attention on [B, S, H, D] tensors (GQA-aware).

    Returns [B, Sq, H, D]. Drop-in for ops.attention.dot_product_attention's
    causal/bidirectional paths.

    segment_ids: optional [B, S] int packing map — attention is restricted
    to within-segment (packed sequences, reference THD/packed_seq_params
    semantics) with the same O(S) memory profile; segment masking composes
    with the causal block-skip.

    head_fold: fold q-head pairs into the trailing block dim in the
    BACKWARD kernels (D=64 → full 128-lane rows; PERF.md lever 1,
    --flash-head-fold). Silently keeps the standard kernels when
    ineligible (head_fold_eligible: 2D > 128, odd head counts, packed
    segments). Forward math is unchanged; grads parity-pinned ≤ 1e-5.
    """
    b, sq, h, d = q.shape
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    qt = jnp.swapaxes(q, 1, 2)   # [B,H,S,D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if segment_ids is None:
        out = _flash_attention_bhsd(qt, kt, vt, float(softmax_scale),
                                    causal, block_q, block_kv,
                                    bool(head_fold))
    else:
        segs = segment_ids.astype(jnp.int32)
        out = _flash_attention_seg_bhsd(
            qt, kt, vt, segs[:, :, None], segs[:, None, :],
            float(softmax_scale), causal, block_q, block_kv)
    return jnp.swapaxes(out, 1, 2)
