"""Dot-product attention (reference jnp implementation).

Parity with /root/reference/megatron/core/transformer/dot_product_attention.py
(the 'local' CUDA-free impl): scaled QK^T → (scaled/masked) softmax in fp32 →
context matmul, with GQA (num_query_groups < num_heads; attention.py:88) and
causal masking. On TPU, XLA fuses the mask+softmax chain; the Pallas flash
kernel (ops/pallas/flash_attention.py) is the memory-efficient production
path selected via TransformerConfig.attention_impl.

Shapes follow the TPU-friendly [batch, seq, heads, head_dim] layout
(reference uses [s, b, h, d]; batch-major is better for TPU tiling).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import AttnMaskType


def repeat_kv(k: jnp.ndarray, num_heads: int) -> jnp.ndarray:
    """Broadcast KV heads to query heads for GQA ([B,S,Hkv,D] → [B,S,H,D])."""
    n_kv = k.shape[2]
    if n_kv == num_heads:
        return k
    reps = num_heads // n_kv
    return jnp.repeat(k, reps, axis=2)


def dot_product_attention(
    q: jnp.ndarray,  # [B, Sq, H, D]
    k: jnp.ndarray,  # [B, Skv, Hkv, D]
    v: jnp.ndarray,  # [B, Skv, Hkv, D]
    mask_type: AttnMaskType = AttnMaskType.causal,
    attention_mask: Optional[jnp.ndarray] = None,  # [B, 1, Sq, Skv] True=keep
    softmax_scale: Optional[float] = None,
    softmax_in_fp32: bool = True,
    q_offset: int = 0,
    layer_id=None,
) -> jnp.ndarray:
    """Returns context [B, Sq, H, D].

    q_offset: absolute position of q[0] relative to k[0] (used for decode
    steps and for ring-attention block offsets).
    layer_id: MegaScope capture attribution for the 'attention_probs'
    site (reference RawAttentionScore flag).
    """
    b, sq, h, d = q.shape
    skv = k.shape[1]
    if softmax_scale is None:
        softmax_scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    k = repeat_kv(k, h)
    v = repeat_kv(v, h)

    # [B,H,Sq,Skv]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores * softmax_scale

    if mask_type == AttnMaskType.causal:
        q_pos = jnp.arange(sq) + q_offset
        kv_pos = jnp.arange(skv)
        causal = q_pos[:, None] >= kv_pos[None, :]
        scores = jnp.where(causal[None, None], scores, -1e30)
    if attention_mask is not None:
        scores = jnp.where(attention_mask, scores, -1e30)

    if softmax_in_fp32:
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    else:
        probs = jax.nn.softmax(scores, axis=-1)
    # MegaScope RawAttentionScore site ([B,H,Sq,Skv] probabilities).
    # Gated on layer_id: only the transformer self-attention path
    # threads it, so context-parallel per-block partial softmaxes, T5
    # cross-attention, retro, and MLA callers (layer_id=None here) do
    # not emit misattributed payloads into the site.
    if layer_id is not None:
        from megatronapp_tpu.scope.hooks import scope_capture
        probs = scope_capture("attention_probs", probs, layer_id)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
