"""Context parallelism: ring attention, Ulysses all-to-all, allgather.

Parity with the reference's CP modes, which it delegates to TransformerEngine
(cp_comm_type per layer: 'p2p' ring / 'a2a' Ulysses head-parallel /
'allgather' — /root/reference/megatron/core/transformer/transformer_config.py
:458-462, extensions/transformer_engine.py:631-680). The reference has no
kernel of its own; here each mode is implemented natively on the 'cp' mesh
axis (SURVEY §5.7: "must implement ring attention + all-to-all head-parallel
attention natively ... collective permute over ICI").

All impl functions run INSIDE a shard_map manual over 'cp' with sequence
sharded [B, S/cp, H, D] per shard; `context_attention` is the outer wrapper
that sets up the shard_map. The wrapper is FULLY manual over every mesh axis
(parallel/collectives.shard_map_compat): batch threads over (dp, ep), heads
over tp when divisible, pp rides replicated — on the jax 0.4.x builds this
image ships, partial-auto manual regions lower ppermute/axis_index through
an SPMD path XLA:CPU aborts on (parallel/overlap.py design notes).

Ring attention = blockwise online-softmax attention (flash-attention style
running max/sum in fp32) with K,V blocks rotated around the cp ring via
ppermute. The rings are LATENCY-HIDING: every hop is issued BEFORE the
dependent block's attention compute, so on hardware with an async collective
engine (TPU ICI) the permute of block s+1 rides under the flash update of
block s (T3-style fine-grained overlap, arXiv:2401.16677; XLA:CPU runs the
hop synchronously, so CPU-mesh wins come from the causal block skip below).

Causal ring comes in two layouts:
- contiguous (`ring_attention`): rank i holds sequence chunk i. Blocks from
  ranks src > i are entirely masked under causal attention and are SKIPPED
  (lax.cond) — per-rank cost ranges from S²/cp² (rank 0) to S²/cp (rank
  cp-1), total S²/2cp on average but imbalanced across ranks.
  When `overlap=True` and no segment ids, this path carries a
  ``jax.custom_vjp`` whose backward runs the symmetric reverse ring FUSED:
  one ring pass rotates (K, V, dK, dV) together — each rank adds its dK/dV
  contribution for the block it holds while the next K/V hop is already in
  flight, and after cp hops the accumulated dK/dV land back on their home
  rank. dQ accumulates locally (no extra pass).
- zigzag (`zigzag_ring_attention`): rank i holds chunks (i, 2cp-1-i) of a
  2cp-way split (the reference's TE ring layout). Each non-diagonal round
  computes exactly half the score block — the visible half is known from
  (rank, src) alone — so per-rank cost is ~S²/(2cp), balanced across ranks.
  Callers permute the sequence into zigzag order first (`zigzag_indices`);
  models do this transparently (models/gpt.py). Hops are pre-issued the
  same way.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from megatronapp_tpu.config.parallel_config import (
    CP_AXIS, DP_AXIS, EP_AXIS, TP_AXIS,
)
from megatronapp_tpu.ops.attention import repeat_kv
from megatronapp_tpu.parallel.collectives import (
    axis_size, full_like_vma, ring_span, shard_map_compat, zeros_like_vma,
)

_NEG_INF = -1e30

# MegaScan span names (trace/tracer.py GRANULARITY_EVENTS 'collective').
CP_OVERLAP_COMPUTE_EVENT = "cp-overlap-compute"
CP_OVERLAP_PERMUTE_EVENT = "cp-overlap-permute"

# Activation batch dims shard over (dp, ep) — mesh.py batch_spec.
_BATCH = (DP_AXIS, EP_AXIS)


def _block_scores(q, k, scale):
    # q [B,Sq,H,D], k [B,Skv,H,D] → scores [B,H,Sq,Skv] fp32.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    return s * scale


def _mark(ph: str, kind: str, dep, axis_name, *, op: str, step: int):
    name = (CP_OVERLAP_COMPUTE_EVENT if kind == "compute"
            else CP_OVERLAP_PERMUTE_EVENT)
    ring_span(name, ph, dep, axis_name, step=step, op=op)


# ---------------------------------------------------------------------------
# Contiguous ring, overlapped custom_vjp path (causal/bidirectional, no
# segment ids): pre-issued hops + fused reverse-ring backward.
# ---------------------------------------------------------------------------

def _softmax_block_update(o, m, l, s, v_blk, h):
    """One online-softmax update with UNnormalized state (o, m, l) and
    pre-masked scores s [B,H,Sq,Skv]; v_blk [B,Skv,Hkv,Dv]."""
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    m_safe = jnp.maximum(m_new, _NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    corr = jnp.exp(jnp.minimum(m - m_new, 0.0))
    corr = jnp.where(m <= _NEG_INF / 2, 0.0, corr)
    l = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v_blk.dtype),
                    repeat_kv(v_blk, h),
                    preferred_element_type=jnp.float32)
    o = o * corr[..., None] + pv
    return o, m_new, l


def _ring_overlap_fwd_impl(axis_name, causal, scale, q, k, v):
    cp = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    dv = v.shape[-1]
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    op = "ring-attention"

    o = zeros_like_vma((b, h, sq, dv), jnp.float32, q)
    m = full_like_vma((b, h, sq), _NEG_INF, jnp.float32, q)
    l = zeros_like_vma((b, h, sq), jnp.float32, q)
    k_blk, v_blk = k, v
    for step in range(cp):
        nxt = None
        if step + 1 < cp:
            # Issue the hop BEFORE the dependent flash update so it rides
            # under the compute (TPU async collectives; XLA:CPU is sync).
            _mark("B", "permute", k_blk, axis_name, op=op, step=step)
            nxt = (lax.ppermute(k_blk, axis_name, perm),
                   lax.ppermute(v_blk, axis_name, perm))

        def update(o, m, l, k_blk=k_blk, v_blk=v_blk, step=step):
            s = _block_scores(q, repeat_kv(k_blk, h), scale)
            if causal and step == 0:
                # Diagonal block: causal mask within the chunk. Off-diagonal
                # causal blocks are either fully visible (src < me) or
                # skipped entirely below.
                within = (jnp.arange(sq)[:, None]
                          >= jnp.arange(k_blk.shape[1])[None, :])
                s = jnp.where(within[None, None], s, _NEG_INF)
            return _softmax_block_update(o, m, l, s, v_blk, h)

        _mark("B", "compute", k_blk, axis_name, op=op, step=step)
        if causal and step > 0:
            # After `step` rotations this rank holds the block originally
            # from src = me - step; src > me ⇒ entirely in the future ⇒
            # skip the whole block's FLOPs (cond, not select).
            src = (me - step) % cp
            o, m, l = lax.cond(src > me, lambda o, m, l: (o, m, l), update,
                               o, m, l)
        else:
            o, m, l = update(o, m, l)
        _mark("E", "compute", o, axis_name, op=op, step=step)
        if nxt is not None:
            _mark("E", "permute", nxt[0], axis_name, op=op, step=step)
            k_blk, v_blk = nxt

    lse = m + jnp.log(jnp.maximum(l, 1e-20))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    out = jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,Sq,H,Dv]
    return out, lse


def _ring_overlap_bwd_impl(axis_name, causal, scale, res, do):
    """Fused reverse ring: ONE pass rotates (k, v, dk, dv) together.

    Each rank adds its dK/dV contribution for the block it currently holds
    (the K/V hop for the NEXT block is pre-issued before the compute, so it
    rides underneath); the accumulators hop with their blocks and after cp
    hops land back home. dQ accumulates locally."""
    q, k, v, out, lse = res
    cp = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    hkv, dv = k.shape[2], v.shape[-1]
    g = h // hkv
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    op = "ring-attention-bwd"

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    # D_i = sum_e do_ie * out_ie (rowwise), the flash-backward correction.
    delta = jnp.einsum("bqhe,bqhe->bhq", do32, out.astype(jnp.float32))

    dq = zeros_like_vma((b, sq, h, d), jnp.float32, q)
    dk_blk = zeros_like_vma(k.shape, jnp.float32, q)
    dv_blk = zeros_like_vma(v.shape, jnp.float32, q)
    k_blk, v_blk = k, v
    for step in range(cp):
        nxt = None
        if step + 1 < cp:
            _mark("B", "permute", k_blk, axis_name, op=op, step=step)
            nxt = (lax.ppermute(k_blk, axis_name, perm),
                   lax.ppermute(v_blk, axis_name, perm))

        def update(dq, dk_blk, dv_blk, k_blk=k_blk, v_blk=v_blk, step=step):
            skv = k_blk.shape[1]
            s = _block_scores(q, repeat_kv(k_blk, h), scale)
            if causal and step == 0:
                within = (jnp.arange(sq)[:, None]
                          >= jnp.arange(skv)[None, :])
                s = jnp.where(within[None, None], s, _NEG_INF)
            # lse-normalized probabilities (rows always have ≥1 visible
            # key on the un-skipped blocks, so lse is finite).
            p = jnp.exp(s - lse[..., None])                    # [B,H,Sq,Skv]
            dv_rep = jnp.einsum("bhqk,bqhe->bkhe", p, do32)
            dp = jnp.einsum("bqhe,bkhe->bhqk", do32,
                            repeat_kv(v_blk, h).astype(jnp.float32))
            ds = p * (dp - delta[..., None]) * scale
            dq_add = jnp.einsum("bhqk,bkhd->bqhd", ds,
                                repeat_kv(k_blk, h).astype(jnp.float32))
            dk_rep = jnp.einsum("bhqk,bqhd->bkhd", ds, q32)
            # GQA: fold the repeated query-head groups back onto kv heads.
            dk_add = dk_rep.reshape(b, skv, hkv, g, d).sum(3)
            dv_add = dv_rep.reshape(b, skv, hkv, g, dv).sum(3)
            return dq + dq_add, dk_blk + dk_add, dv_blk + dv_add

        _mark("B", "compute", k_blk, axis_name, op=op, step=step)
        if causal and step > 0:
            src = (me - step) % cp
            dq, dk_blk, dv_blk = lax.cond(
                src > me, lambda a, b_, c: (a, b_, c), update,
                dq, dk_blk, dv_blk)
        else:
            dq, dk_blk, dv_blk = update(dq, dk_blk, dv_blk)
        _mark("E", "compute", dq, axis_name, op=op, step=step)
        # The accumulators travel WITH their block: after this hop the
        # next rank holds (block, partial dK/dV) together; the cp-th hop
        # returns them to the block's home rank.
        dk_blk = lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = lax.ppermute(dv_blk, axis_name, perm)
        if nxt is not None:
            _mark("E", "permute", nxt[0], axis_name, op=op, step=step)
            k_blk, v_blk = nxt

    return (dq.astype(q.dtype), dk_blk.astype(k.dtype),
            dv_blk.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ring_overlap(axis_name, causal, scale, q, k, v):
    return _ring_overlap_fwd_impl(axis_name, causal, scale, q, k, v)[0]


def _ring_overlap_fwd(axis_name, causal, scale, q, k, v):
    out, lse = _ring_overlap_fwd_impl(axis_name, causal, scale, q, k, v)
    return out, (q, k, v, out, lse)


_ring_overlap.defvjp(_ring_overlap_fwd, _ring_overlap_bwd_impl)


def ring_attention(q, k, v, axis_name: str = CP_AXIS, causal: bool = True,
                   softmax_scale: Optional[float] = None,
                   segment_ids=None, overlap: bool = True):
    """Ring attention over the cp axis (inside shard_map).

    q,k,v: local [B, S/cp, H(q)/H(kv), D]. Returns [B, S/cp, H, D].
    segment_ids: local [B, S/cp] packed map — kv segment ids ride the ring
    with the k/v blocks and mask cross-segment scores.

    overlap=True (and no segment ids): the latency-hiding custom_vjp path
    (pre-issued hops, fused reverse-ring backward, causal block skip).
    Segment ids route through the general unrolled ring below, which
    pre-issues its hops the same way but differentiates through the loop.
    """
    cp = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    if overlap and segment_ids is None:
        return _ring_overlap(axis_name, causal, float(softmax_scale),
                             q, k, v)
    # GQA: K/V ride the ring un-repeated (fewer bytes per ppermute hop);
    # heads are broadcast per block at the matmul.
    dv = v.shape[-1]  # may differ from d (MLA: nope+rope keys vs values)

    # fp32 online-softmax state; varying-manual-axes type inherited from q
    # (cp here, plus pp when nested inside the pipeline shard_map — parent
    # axis names cannot be referenced directly in a nested manual region).
    o = zeros_like_vma((b, h, sq, dv), jnp.float32, q)
    m = full_like_vma((b, h, sq), _NEG_INF, jnp.float32, q)
    l = zeros_like_vma((b, h, sq), jnp.float32, q)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def block_update(o, m, l, k_blk, v_blk, src, kv_seg_blk=None):
        s = _block_scores(q, repeat_kv(k_blk, h), softmax_scale)
        blk_mask = None
        if causal:
            # Block-level: src > my → entirely masked; src == my → causal
            # within block; src < my → fully visible.
            q_pos = jnp.arange(sq)
            kv_pos = jnp.arange(k_blk.shape[1])
            within = q_pos[:, None] >= kv_pos[None, :]
            blk_mask = jnp.where(
                src == my, within,
                jnp.broadcast_to(src < my, within.shape))
            blk_mask = jnp.broadcast_to(blk_mask[None, None],
                                        s.shape)
        if kv_seg_blk is not None:
            seg = (segment_ids[:, None, :, None]
                   == kv_seg_blk[:, None, None, :])
            blk_mask = seg if blk_mask is None else blk_mask & seg
        if blk_mask is not None:
            s = jnp.where(blk_mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # Guard fully-masked rows (m_new == -inf): keep exp argument finite.
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(s - m_safe[..., None])
        if blk_mask is not None:
            p = jnp.where(blk_mask, p, 0.0)
        corr = jnp.exp(jnp.minimum(m - m_new, 0.0))
        corr = jnp.where(m <= _NEG_INF / 2, 0.0, corr)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v_blk.dtype),
                        repeat_kv(v_blk, h),
                        preferred_element_type=jnp.float32)
        o = o * corr[..., None] + pv
        return o, m_new, l

    # Unrolled ring with pre-issued hops: the hop for block s+1 is issued
    # before block s's flash update, so it can ride underneath. The final
    # rotation (returning blocks home) would be wasted traffic — skipped.
    carry = (k, v) if segment_ids is None else (k, v, segment_ids)
    nxt = None
    for step in range(cp):
        if step + 1 < cp:
            nxt = tuple(lax.ppermute(x, axis_name, perm) for x in carry)
        src = (my - step) % cp
        o, m, l = block_update(o, m, l, carry[0], carry[1], src,
                               carry[2] if segment_ids is not None else None)
        if nxt is not None:
            carry, nxt = nxt, None
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,Sq,H,D]


def zigzag_indices(seq_len: int, cp: int):
    """Permutation taking a contiguous sequence to zigzag layout.

    The sequence splits into 2cp chunks; rank i's contiguous S/cp shard of
    the PERMUTED sequence holds original chunks (i, 2cp-1-i) — the
    reference's causal-balanced ring layout (TE cp_comm_type='p2p').
    Returns an int32 index array `idx` with permuted[j] = original[idx[j]];
    `idx` doubles as the per-token original positions of the permuted
    sequence (for rope tables).
    """
    import numpy as np
    if seq_len % (2 * cp):
        raise ValueError(
            f"zigzag context parallelism needs seq_len divisible by "
            f"2*cp={2*cp} (got {seq_len})")
    c = seq_len // (2 * cp)
    order = []
    for i in range(cp):
        order += [i, 2 * cp - 1 - i]
    return np.concatenate(
        [np.arange(ch * c, (ch + 1) * c, dtype=np.int32) for ch in order])


def zigzag_inverse_indices(seq_len: int, cp: int):
    """Inverse permutation: unpermuted[i] = permuted[inv[i]]."""
    import numpy as np
    idx = zigzag_indices(seq_len, cp)
    inv = np.empty_like(idx)
    inv[idx] = np.arange(seq_len, dtype=np.int32)
    return inv


def zigzag_ring_attention(q, k, v, axis_name: str = CP_AXIS,
                          causal: bool = True,
                          softmax_scale: Optional[float] = None,
                          segment_ids=None):
    """Causal-balanced ring attention over zigzag-laid-out sequences.

    q,k,v: local [B, S/cp, H, D] where the local block is [chunk_my ;
    chunk_{2cp-1-my}] of a 2cp-way split. For each rotated KV block from
    rank `src`, the visible region is known statically from (my, src):

      src == my : diagonal round — full block with position mask.
      src <  my : only kv chunk `src` (first half) is visible; all q rows
                  attend it fully (both q chunks sit later in time).
      src >  my : only q chunk `2cp-1-my` (second half) attends; it sees
                  both kv chunks fully.

    The two off-diagonal cases each compute a half-size score block of
    EQUAL flop count, selected with lax.cond — every rank does the same
    work every round (~S²/(2cp) total vs the contiguous ring's S²/cp).
    The ring is unrolled with every hop issued BEFORE the round it feeds,
    so the permute rides under the previous round's half-block compute.
    Reference: TE ring P2P zigzag (transformer_config.py:458-462 cp_comm_
    type='p2p'); layout produced by get_batch_on_this_cp_rank-style
    permutation (training/utils.py).
    """
    assert segment_ids is None, (
        "packed sequences route through the contiguous ring "
        "(zigzag_active excludes segment_ids)")
    if not causal:
        # Bidirectional attention has no imbalance; plain ring is optimal.
        return ring_attention(q, k, v, axis_name, causal=False,
                              softmax_scale=softmax_scale)
    cp = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    dv = v.shape[-1]
    c = sq // 2  # one global chunk
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    op = "zigzag-ring-attention"

    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def positions(rank):
        # Global positions of a rank's local rows [chunk_rank; mirror].
        r = jnp.arange(c)
        return jnp.concatenate([rank * c + r, (2 * cp - 1 - rank) * c + r])

    def softmax_update(o, m, l, s, v_rep, rows):
        """Online-softmax update of rows [rows] with scores s
        [B,H,nrows,Skv] and values v_rep [B,Skv,H,D]."""
        o_r = jax.lax.dynamic_slice_in_dim(o, rows[0], rows[1], axis=2)
        m_r = jax.lax.dynamic_slice_in_dim(m, rows[0], rows[1], axis=2)
        l_r = jax.lax.dynamic_slice_in_dim(l, rows[0], rows[1], axis=2)
        m_new = jnp.maximum(m_r, jnp.max(s, axis=-1))
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(s - m_safe[..., None])
        corr = jnp.exp(jnp.minimum(m_r - m_new, 0.0))
        corr = jnp.where(m_r <= _NEG_INF / 2, 0.0, corr)
        l_r = l_r * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v_rep.dtype), v_rep,
                        preferred_element_type=jnp.float32)
        o_r = o_r * corr[..., None] + pv
        return (jax.lax.dynamic_update_slice_in_dim(o, o_r, rows[0], axis=2),
                jax.lax.dynamic_update_slice_in_dim(m, m_new, rows[0], axis=2),
                jax.lax.dynamic_update_slice_in_dim(l, l_r, rows[0], axis=2))

    # Hop 1 is issued BEFORE the diagonal round so it rides under it.
    k_blk, v_blk = k, v
    nxt = None
    if cp > 1:
        _mark("B", "permute", k_blk, axis_name, op=op, step=0)
        nxt = (lax.ppermute(k_blk, axis_name, perm),
               lax.ppermute(v_blk, axis_name, perm))
        _mark("E", "permute", nxt[0], axis_name, op=op, step=0)

    # Diagonal round (src == my): full local block with the zigzag position
    # mask (half the scores are masked; only paid once).
    q_pos = positions(my)
    s0 = _block_scores(q, repeat_kv(k, h), softmax_scale)
    mask0 = q_pos[:, None] >= q_pos[None, :]
    s0 = jnp.where(mask0[None, None], s0, _NEG_INF)
    p0 = jnp.exp(s0 - jnp.maximum(jnp.max(s0, -1), _NEG_INF / 2)[..., None])
    p0 = jnp.where(mask0[None, None], p0, 0.0)
    m = jnp.max(s0, -1)
    l = jnp.sum(p0, -1)
    o = jnp.einsum("bhqk,bkhd->bhqd", p0.astype(v.dtype), repeat_kv(v, h),
                   preferred_element_type=jnp.float32)

    for step in range(1, cp):
        k_blk, v_blk = nxt
        nxt = None
        if step + 1 < cp:
            _mark("B", "permute", k_blk, axis_name, op=op, step=step)
            nxt = (lax.ppermute(k_blk, axis_name, perm),
                   lax.ppermute(v_blk, axis_name, perm))
            _mark("E", "permute", nxt[0], axis_name, op=op, step=step)
        src = (my - step) % cp

        def lower(o, m, l, k_blk=k_blk, v_blk=v_blk):
            # src < my: kv chunk `src` (first half) fully visible to all q.
            k_lo = repeat_kv(k_blk[:, :c], h)
            v_lo = repeat_kv(v_blk[:, :c], h)
            s = _block_scores(q, k_lo, softmax_scale)  # [B,H,2c,c]
            return softmax_update(o, m, l, s, v_lo, (0, sq))

        def upper(o, m, l, k_blk=k_blk, v_blk=v_blk):
            # src > my: q chunk `2cp-1-my` (second half) sees both kv
            # chunks fully.
            k_all = repeat_kv(k_blk, h)
            v_all = repeat_kv(v_blk, h)
            s = _block_scores(q[:, c:], k_all, softmax_scale)  # [B,H,c,2c]
            return softmax_update(o, m, l, s, v_all, (c, c))

        _mark("B", "compute", k_blk, axis_name, op=op, step=step)
        o, m, l = jax.lax.cond(src < my, lower, upper, o, m, l)
        _mark("E", "compute", o, axis_name, op=op, step=step)

    out = o / jnp.maximum(l, 1e-20)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = CP_AXIS, causal: bool = True,
                      softmax_scale: Optional[float] = None,
                      segment_ids=None):
    """Ulysses-style all-to-all head-parallel attention (inside shard_map).

    Local [B, S/cp, H, D] → all-to-all → [B, S, H/cp, D] (full sequence,
    head subset) → plain attention → all-to-all back. Requires both q-heads
    and kv-heads divisible by cp (reference a2a mode has the same
    constraint).
    """
    from megatronapp_tpu.ops.attention import dot_product_attention
    from megatronapp_tpu.config.transformer_config import AttnMaskType

    cp = axis_size(axis_name)

    def scatter_heads(x):
        # [B, S/cp, H, D] → [B, S, H/cp, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def gather_heads(x):
        # [B, S, H/cp, D] → [B, S/cp, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    attention_mask = None
    if segment_ids is not None:
        # Heads are sharded but the sequence is full after the a2a: gather
        # the full segment map and densify (the a2a mode has no blockwise
        # kernel to mask inside).
        segs_full = jax.lax.all_gather(segment_ids, axis_name, axis=1,
                                       tiled=True)
        attention_mask = (segs_full[:, None, :, None]
                          == segs_full[:, None, None, :])
    ctx = dot_product_attention(
        qh, kh, vh,
        mask_type=(AttnMaskType.causal if causal
                   else AttnMaskType.bidirectional),
        attention_mask=attention_mask,
        softmax_scale=softmax_scale)
    return gather_heads(ctx)


def allgather_attention(q, k, v, axis_name: str = CP_AXIS,
                        causal: bool = True,
                        softmax_scale: Optional[float] = None,
                        segment_ids=None):
    """All-gather K/V over cp, local q attends the full sequence (reference
    cp_comm_type='allgather')."""
    from megatronapp_tpu.ops.attention import dot_product_attention
    from megatronapp_tpu.config.transformer_config import AttnMaskType

    cp = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    sq = q.shape[1]
    k_full = jax.lax.all_gather(k, axis_name, axis=1, tiled=True)
    v_full = jax.lax.all_gather(v, axis_name, axis=1, tiled=True)
    attention_mask = None
    if segment_ids is not None:
        segs_full = jax.lax.all_gather(segment_ids, axis_name, axis=1,
                                       tiled=True)
        attention_mask = (segment_ids[:, None, :, None]
                          == segs_full[:, None, None, :])
    return dot_product_attention(
        q, k_full, v_full,
        mask_type=(AttnMaskType.causal if causal
                   else AttnMaskType.bidirectional),
        attention_mask=attention_mask,
        softmax_scale=softmax_scale,
        q_offset=my * sq)


def hierarchical_attention(q, k, v, axis_name: str = CP_AXIS,
                           causal: bool = True,
                           softmax_scale: Optional[float] = None,
                           segment_ids=None, a2a_size: int = 2):
    """Hierarchical CP (reference cp_comm_type='a2a+p2p',
    transformer_config.py:458-462 + hierarchical CP groups
    parallel_state.py:100-121): Ulysses head-scatter WITHIN inner groups of
    `a2a_size` adjacent ranks (cheap links), ring P2P ACROSS the
    ring_size = cp/a2a_size outer groups (one KV span per hop, pre-issued
    before the round it feeds like the flat rings).

    After the inner all-to-all each rank holds its inner group's contiguous
    sequence span [g*S/ring, (g+1)*S/ring) with H/a2a_size heads; the outer
    ring rotates K/V spans with group-granular causal skipping (diagonal
    span gets the within-span causal mask, earlier spans are fully
    visible). Requires heads % a2a_size == 0 and contiguous cp sharding.

    segment_ids (packed/THD): the local [B, S/cp] ids are all-gathered to
    the inner group's span (positions, not heads, so no head scatter) and
    the K/V spans' ids ride the outer ring with them; the within-segment
    equality mask composes with the group-granular causal mask per block.
    """
    cp = axis_size(axis_name)
    assert cp % a2a_size == 0, (cp, a2a_size)
    ring_size = cp // a2a_size
    my = lax.axis_index(axis_name)
    my_group = my // a2a_size
    inner_groups = [[g * a2a_size + i for i in range(a2a_size)]
                    for g in range(ring_size)]

    def scatter_heads(x):
        # [B, S/cp, H, D] → [B, S/ring, H/a2a, D] within the inner group.
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True,
                                  axis_index_groups=inner_groups)

    def gather_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True,
                                  axis_index_groups=inner_groups)

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    segs = None
    if segment_ids is not None:
        # Segment ids are per-position: gather the inner group's span
        # ([B, S/cp] → [B, S/ring]) instead of head-scattering.
        segs = jax.lax.all_gather(segment_ids, axis_name, axis=1,
                                  tiled=True,
                                  axis_index_groups=inner_groups)
    b, sq, h, d = q.shape
    dv = v.shape[-1]
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    # Ring across outer groups: rank r exchanges with r+a2a_size (same
    # inner position, next group) — each hop moves one sequence span.
    perm = [(r, (r + a2a_size) % cp) for r in range(cp)]

    def block_update(o, m, l, k_blk, v_blk, src_group, kv_segs_blk):
        s_ = _block_scores(q, repeat_kv(k_blk, h), softmax_scale)
        blk_mask = None                      # [sq, skv] or [B, sq, skv]
        if causal:
            q_pos = jnp.arange(sq)
            kv_pos = jnp.arange(k_blk.shape[1])
            within = q_pos[:, None] >= kv_pos[None, :]
            blk_mask = jnp.where(
                src_group == my_group, within,
                jnp.broadcast_to(src_group < my_group, within.shape))
        if kv_segs_blk is not None:
            seg_m = segs[:, :, None] == kv_segs_blk[:, None, :]
            blk_mask = (seg_m if blk_mask is None
                        else seg_m & blk_mask[None])
        if blk_mask is not None:
            mask_b = blk_mask if blk_mask.ndim == 3 else blk_mask[None]
            s_ = jnp.where(mask_b[:, None], s_, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s_, axis=-1))
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        pr = jnp.exp(s_ - m_safe[..., None])
        if blk_mask is not None:
            pr = jnp.where(mask_b[:, None], pr, 0.0)
        corr = jnp.exp(jnp.minimum(m - m_new, 0.0))
        corr = jnp.where(m <= _NEG_INF / 2, 0.0, corr)
        l = l * corr + jnp.sum(pr, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", pr.astype(v_blk.dtype),
                        repeat_kv(v_blk, h),
                        preferred_element_type=jnp.float32)
        o = o * corr[..., None] + pv
        return o, m_new, l

    o = zeros_like_vma((b, h, sq, dv), jnp.float32, q)
    m = full_like_vma((b, h, sq), _NEG_INF, jnp.float32, q)
    l = zeros_like_vma((b, h, sq), jnp.float32, q)

    # Pre-issue the first outer-ring hop so it rides under the diagonal
    # span's compute (same discipline as the flat rings).
    carry = (k, v) if segs is None else (k, v, segs)
    nxt = None
    if ring_size > 1:
        nxt = tuple(lax.ppermute(x, axis_name, perm) for x in carry)
    o, m, l = block_update(o, m, l, k, v, my_group, segs)

    for step in range(1, ring_size):
        carry, nxt = nxt, None
        if step + 1 < ring_size:
            nxt = tuple(lax.ppermute(x, axis_name, perm) for x in carry)
        src_group = (my_group - step) % ring_size
        o, m, l = block_update(o, m, l, carry[0], carry[1], src_group,
                               carry[2] if segs is not None else None)
    out = o / jnp.maximum(l, 1e-20)[..., None]
    out = jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
    return gather_heads(out)


_CP_IMPLS = {
    "p2p": ring_attention,
    "p2p_zigzag": zigzag_ring_attention,
    "a2a": ulysses_attention,
    "allgather": allgather_attention,
    "a2a+p2p": hierarchical_attention,
}
# Authoritative set of valid cp_comm_type CONFIG values (reference names;
# 'p2p' auto-upgrades to the zigzag impl for causal attention when
# TransformerConfig.cp_zigzag — the internal 'p2p_zigzag' key is not a
# user-facing config value).
CP_COMM_TYPES = frozenset({"p2p", "a2a", "allgather", "a2a+p2p"})


def zigzag_active(cfg, ctx) -> bool:
    """True when the config+mesh allow the zigzag ring. Models that permute
    their sequences use this to decide; the kernel dispatch
    (transformer/attention.py) additionally requires the caller-provided
    `zigzag` layout flag, so models that DON'T permute (t5, mamba hybrid)
    safely keep the contiguous ring."""
    from megatronapp_tpu.config.transformer_config import AttnMaskType
    return (ctx is not None and ctx.cp > 1 and cfg.cp_comm_type == "p2p"
            and cfg.cp_zigzag and not cfg.multi_latent_attention
            # MTP depth modules roll tokens/labels in natural order; the
            # zigzag permutation would misalign h with emb(t_{i+k}).
            and not cfg.mtp_num_layers
            and cfg.attn_mask_type == AttnMaskType.causal)


def context_attention(q, k, v, mesh, cp_comm_type: str = "p2p",
                      causal: bool = True,
                      softmax_scale: Optional[float] = None,
                      segment_ids=None, a2a_size: int = 2,
                      overlap_ring: bool = True):
    """Outer wrapper: FULL-MANUAL shard_map over every mesh axis.

    q,k,v: GLOBAL [B, S, H, D] arrays with S sharded over cp. Returns global
    [B, S, H, D] with the same sharding. segment_ids: GLOBAL [B, S] packed
    map (sharded over cp alongside the sequence).

    The manual region threads batch over (dp, ep) and heads over tp when
    they divide evenly (replicating them otherwise — identical math,
    redundant compute, exactly what GSPMD would emit for an unshardable
    dim); pp rides replicated. Partial-auto regions (cp manual, rest auto)
    abort XLA:CPU on this jax build — see the module docstring.

    S not divisible by cp is zero-padded to the next multiple and the pad
    masked out via synthetic segment ids (pad tokens get segment 0, real
    tokens segment ids shifted up by 1), so every mode stays exact;
    the padded rows are sliced off on return.

    overlap_ring: route the contiguous ring through the latency-hiding
    custom_vjp path (TransformerConfig.cp_comm_overlap)."""
    if cp_comm_type not in _CP_IMPLS:
        raise ValueError(
            f"cp_comm_type must be one of {sorted(_CP_IMPLS)}, got "
            f"{cp_comm_type!r}")
    impl = _CP_IMPLS[cp_comm_type]
    extra = ({"a2a_size": a2a_size} if cp_comm_type == "a2a+p2p" else {})
    if cp_comm_type == "p2p":
        extra["overlap"] = overlap_ring
    fn = functools.partial(impl, causal=causal, softmax_scale=softmax_scale,
                           **extra)

    # If 'cp' is ALREADY manual in the ambient context (we're inside the
    # pp(+cp) pipeline shard_map — nested shard_maps are unreliable in this
    # JAX build), q/k/v are already local seq blocks: call the impl directly.
    from megatronapp_tpu.parallel.collectives import current_manual_axes
    if CP_AXIS in current_manual_axes():
        return fn(q, k, v, segment_ids=segment_ids)

    cp = mesh.shape[CP_AXIS]
    b, s, h, d = q.shape
    hkv = k.shape[2]

    pad = (-s) % cp
    if pad:
        if cp_comm_type == "p2p_zigzag":
            raise ValueError(
                "zigzag layout requires seq divisible by 2*cp; callers "
                "(zigzag_indices) enforce this before permuting")
        if segment_ids is None:
            segment_ids = jnp.ones((b, s), jnp.int32)
        else:
            segment_ids = segment_ids + 1  # keep 0 free for the pad
        segment_ids = jnp.pad(segment_ids, ((0, 0), (0, pad)))
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))

    # Heads shard over tp when every per-shard constraint holds; otherwise
    # they stay replicated across tp (redundant compute, exact math).
    tp = mesh.shape[TP_AXIS]
    heads_tp = tp > 1 and h % tp == 0 and hkv % tp == 0
    if heads_tp and cp_comm_type == "a2a":
        heads_tp = (h // tp) % cp == 0 and (hkv // tp) % cp == 0
    if heads_tp and cp_comm_type == "a2a+p2p":
        heads_tp = (h // tp) % a2a_size == 0 and (hkv // tp) % a2a_size == 0
    head_spec = TP_AXIS if heads_tp else None
    # Batch threads over the (dp, ep) shards when it divides evenly.
    dpep = mesh.shape[DP_AXIS] * mesh.shape[EP_AXIS]
    batch_spec = _BATCH if b % dpep == 0 else None

    qkv_spec = P(batch_spec, CP_AXIS, head_spec, None)
    seg_spec = P(batch_spec, CP_AXIS)
    if segment_ids is None:
        sm = jax.jit(shard_map_compat(
            lambda q, k, v: fn(q, k, v),
            mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec))
        out = sm(q, k, v)
    else:
        sm = jax.jit(shard_map_compat(
            lambda q, k, v, s: fn(q, k, v, segment_ids=s),
            mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
            out_specs=qkv_spec))
        out = sm(q, k, v, segment_ids)
    return out[:, :s] if pad else out
