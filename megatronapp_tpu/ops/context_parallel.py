"""Context parallelism: ring attention, Ulysses all-to-all, allgather.

Parity with the reference's CP modes, which it delegates to TransformerEngine
(cp_comm_type per layer: 'p2p' ring / 'a2a' Ulysses head-parallel /
'allgather' — /root/reference/megatron/core/transformer/transformer_config.py
:458-462, extensions/transformer_engine.py:631-680). The reference has no
kernel of its own; here each mode is implemented natively on the 'cp' mesh
axis (SURVEY §5.7: "must implement ring attention + all-to-all head-parallel
attention natively ... collective permute over ICI").

All functions run INSIDE a shard_map manual over 'cp' with sequence sharded
[B, S/cp, H, D] per shard; `context_attention` is the outer wrapper that
sets up the shard_map (auto for every other axis).

Ring attention = blockwise online-softmax attention (flash-attention style
running max/sum in fp32) with K,V blocks rotated around the cp ring via
ppermute — each hop rides a single ICI neighbor link. Causal masking skips
future blocks entirely (their contribution is zero), matching the reference
ring's P2P schedule.

TODO(perf): causal ring currently uses contiguous sequence sharding, so rank
i does i+1 unmasked blocks while the scan runs cp lock-step rounds — the last
rank sets wall-clock (~2x balanced cost). The reference balances this with
the zigzag chunk assignment (rank i holds chunks i and 2cp-1-i); adopt that
layout here in a perf pass.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatronapp_tpu.config.parallel_config import CP_AXIS
from megatronapp_tpu.ops.attention import repeat_kv

_NEG_INF = -1e30


def _block_scores(q, k, scale):
    # q [B,Sq,H,D], k [B,Skv,H,D] → scores [B,H,Sq,Skv] fp32.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    return s * scale


def ring_attention(q, k, v, axis_name: str = CP_AXIS, causal: bool = True,
                   softmax_scale: Optional[float] = None):
    """Ring attention over the cp axis (inside shard_map).

    q,k,v: local [B, S/cp, H(q)/H(kv), D]. Returns [B, S/cp, H, D].
    """
    cp = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    if softmax_scale is None:
        softmax_scale = 1.0 / (d ** 0.5)
    # GQA: K/V ride the ring un-repeated (fewer bytes per ppermute hop);
    # heads are broadcast per block at the matmul.

    # fp32 online-softmax state; varying-manual-axes type inherited from q
    # (cp here, plus pp when nested inside the pipeline shard_map — parent
    # axis names cannot be referenced directly in a nested manual region).
    from megatronapp_tpu.parallel.collectives import (
        full_like_vma, zeros_like_vma,
    )
    o = zeros_like_vma((b, h, sq, d), jnp.float32, q)
    m = full_like_vma((b, h, sq), _NEG_INF, jnp.float32, q)
    l = zeros_like_vma((b, h, sq), jnp.float32, q)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def block_update(o, m, l, k_blk, v_blk, src):
        s = _block_scores(q, repeat_kv(k_blk, h), softmax_scale)  # [B,H,Sq,Skv]
        if causal:
            # Block-level: src > my → entirely masked; src == my → causal
            # within block; src < my → fully visible.
            q_pos = jnp.arange(sq)
            kv_pos = jnp.arange(k_blk.shape[1])
            within = q_pos[:, None] >= kv_pos[None, :]
            blk_mask = jnp.where(
                src == my, within,
                jnp.broadcast_to(src < my, within.shape))
            s = jnp.where(blk_mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # Guard fully-masked rows (m_new == -inf): keep exp argument finite.
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(s - m_safe[..., None])
        if causal:
            p = jnp.where(blk_mask[None, None], p, 0.0)
        corr = jnp.exp(jnp.minimum(m - m_new, 0.0))
        corr = jnp.where(m <= _NEG_INF / 2, 0.0, corr)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v_blk.dtype),
                        repeat_kv(v_blk, h),
                        preferred_element_type=jnp.float32)
        o = o * corr[..., None] + pv
        return o, m_new, l

    # Local block first, then cp-1 rotate-then-compute steps — the final
    # rotation (returning blocks home) would be wasted ICI traffic.
    o, m, l = block_update(o, m, l, k, v, my)

    def body(carry, step):
        o, m, l, k_blk, v_blk = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        # After `step` rotations my shard holds the block originally from
        # rank (my - step) mod cp.
        src = (my - step) % cp
        o, m, l = block_update(o, m, l, k_blk, v_blk, src)
        return (o, m, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = jax.lax.scan(body, (o, m, l, k, v),
                                      jnp.arange(1, cp))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,Sq,H,D]


def ulysses_attention(q, k, v, axis_name: str = CP_AXIS, causal: bool = True,
                      softmax_scale: Optional[float] = None):
    """Ulysses-style all-to-all head-parallel attention (inside shard_map).

    Local [B, S/cp, H, D] → all-to-all → [B, S, H/cp, D] (full sequence,
    head subset) → plain attention → all-to-all back. Requires both q-heads
    and kv-heads divisible by cp (reference a2a mode has the same
    constraint).
    """
    from megatronapp_tpu.ops.attention import dot_product_attention
    from megatronapp_tpu.config.transformer_config import AttnMaskType

    cp = jax.lax.axis_size(axis_name)

    def scatter_heads(x):
        # [B, S/cp, H, D] → [B, S, H/cp, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def gather_heads(x):
        # [B, S, H/cp, D] → [B, S/cp, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    ctx = dot_product_attention(
        qh, kh, vh,
        mask_type=(AttnMaskType.causal if causal
                   else AttnMaskType.bidirectional),
        softmax_scale=softmax_scale)
    return gather_heads(ctx)


def allgather_attention(q, k, v, axis_name: str = CP_AXIS,
                        causal: bool = True,
                        softmax_scale: Optional[float] = None):
    """All-gather K/V over cp, local q attends the full sequence (reference
    cp_comm_type='allgather')."""
    from megatronapp_tpu.ops.attention import dot_product_attention
    from megatronapp_tpu.config.transformer_config import AttnMaskType

    cp = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    sq = q.shape[1]
    k_full = jax.lax.all_gather(k, axis_name, axis=1, tiled=True)
    v_full = jax.lax.all_gather(v, axis_name, axis=1, tiled=True)
    return dot_product_attention(
        q, k_full, v_full,
        mask_type=(AttnMaskType.causal if causal
                   else AttnMaskType.bidirectional),
        softmax_scale=softmax_scale,
        q_offset=my * sq)


_CP_IMPLS = {
    "p2p": ring_attention,
    "a2a": ulysses_attention,
    "allgather": allgather_attention,
}
# Authoritative set of valid cp_comm_type values (TransformerConfig
# validation derives from this).
CP_COMM_TYPES = frozenset(_CP_IMPLS)


def context_attention(q, k, v, mesh, cp_comm_type: str = "p2p",
                      causal: bool = True,
                      softmax_scale: Optional[float] = None):
    """Outer wrapper: shard_map over 'cp' (auto for all other axes).

    q,k,v: GLOBAL [B, S, H, D] arrays with S sharded over cp. Returns global
    [B, S, H, D] with the same sharding.
    """
    if cp_comm_type not in _CP_IMPLS:
        raise ValueError(
            f"cp_comm_type must be one of {sorted(_CP_IMPLS)}, got "
            f"{cp_comm_type!r}")
    impl = _CP_IMPLS[cp_comm_type]
    fn = functools.partial(impl, causal=causal, softmax_scale=softmax_scale)

    # If 'cp' is ALREADY manual in the ambient context (we're inside the
    # pp(+cp) pipeline shard_map — nested shard_maps are unreliable in this
    # JAX build), q/k/v are already local seq blocks: call the impl directly.
    from megatronapp_tpu.parallel.collectives import current_manual_axes
    if CP_AXIS in current_manual_axes():
        return fn(q, k, v)

    sm = jax.shard_map(
        lambda q, k, v: fn(q, k, v),
        mesh=mesh,
        in_specs=(P(None, CP_AXIS), P(None, CP_AXIS), P(None, CP_AXIS)),
        out_specs=P(None, CP_AXIS),
        axis_names={CP_AXIS})
    return sm(q, k, v)
