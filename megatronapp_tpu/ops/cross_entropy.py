"""Vocab-parallel cross entropy.

Parity with /root/reference/megatron/core/tensor_parallel/cross_entropy.py:123
(VocabParallelCrossEntropy) — computes softmax cross entropy against a
vocab-sharded logits tensor without materializing the full-vocab softmax on
any one device.

Two forms:
- ``cross_entropy_loss``: plain jnp on a logits array; under jit with vocab
  sharded over 'tp', XLA keeps the reductions local and emits one scalar
  all-reduce per term (max / sumexp / target-pick), which is exactly the
  reference algorithm (cross_entropy.py:30-80) — no hand-written collectives
  required.
- ``shard_map_cross_entropy``: explicit axis-name version for use inside
  ``shard_map`` code paths (pipeline stages), same math with explicit psum.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray,
                       loss_mask: Optional[jnp.ndarray] = None,
                       z_loss_coeff: float = 0.0):
    """Token-mean CE. logits [B,S,V] (any dtype; upcast to fp32), targets
    [B,S] int32, loss_mask [B,S] (1=count). Returns (loss, per_token_loss)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    per_token = logz - target_logit
    if z_loss_coeff:
        # z-loss (softmax normalizer regularization), parity with
        # moe_utils.py z_loss / fused CE z-term.
        per_token = per_token + z_loss_coeff * jnp.square(logz)
    if loss_mask is None:
        loss = jnp.mean(per_token)
    else:
        loss_mask = loss_mask.astype(jnp.float32)
        loss = jnp.sum(per_token * loss_mask) / jnp.maximum(
            jnp.sum(loss_mask), 1.0)
    return loss, per_token


def shard_map_cross_entropy(local_logits: jnp.ndarray, targets: jnp.ndarray,
                            vocab_start: jnp.ndarray, axis_name: str = "tp"):
    """CE against vocab-sharded logits inside shard_map.

    local_logits: [B,S,V/tp] this shard's slice; targets: [B,S] global ids;
    vocab_start: scalar int, first vocab id owned by this shard. Implements
    the exact reference recipe (cross_entropy.py:30-80): local max → psum-max,
    masked target pick → psum, local sumexp → psum.
    """
    local_logits = local_logits.astype(jnp.float32)
    vocab_local = local_logits.shape[-1]
    local_max = jnp.max(local_logits, axis=-1)
    global_max = jax.lax.pmax(local_max, axis_name)
    shifted = local_logits - global_max[..., None]
    local_sumexp = jnp.sum(jnp.exp(shifted), axis=-1)
    global_sumexp = jax.lax.psum(local_sumexp, axis_name)

    local_idx = targets.astype(jnp.int32) - vocab_start
    in_range = (local_idx >= 0) & (local_idx < vocab_local)
    safe_idx = jnp.clip(local_idx, 0, vocab_local - 1)
    picked = jnp.take_along_axis(shifted, safe_idx[..., None], axis=-1)[..., 0]
    picked = jnp.where(in_range, picked, 0.0)
    target_shifted = jax.lax.psum(picked, axis_name)

    return jnp.log(global_sumexp) - target_shifted
