"""LayerNorm / RMSNorm.

Parity with the reference's fused norms (/root/reference/megatron/core/fusions/
fused_layer_norm.py — Apex-backed) — on TPU, XLA fuses the reduction+scale
chain natively, so a plain jnp implementation compiles to a fused kernel; no
hand-written Pallas needed for the norm itself.
Computation runs in fp32 regardless of input dtype (parity with Apex fused LN).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import NormKind


def layer_norm(x, scale, bias, eps: float = 1e-5):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    out = out * scale.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)


def rms_norm(x, scale, eps: float = 1e-5):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    out = x * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    return out.astype(dtype)


def apply_norm(kind: NormKind, x, scale, bias=None, eps: float = 1e-5):
    if kind == NormKind.rmsnorm:
        return rms_norm(x, scale, eps)
    return layer_norm(x, scale, bias, eps)
