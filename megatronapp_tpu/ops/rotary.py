"""Rotary position embeddings (RoPE) + YaRN scaling.

Parity with the reference's rotary implementations
(/root/reference/megatron/core/models/common/embeddings/rotary_pos_embedding.py
and yarn_rotary_pos_embedding.py). The reference caches cos/sin on device per
forward; here frequencies are computed inside the jit (cheap, fused by XLA) or
passed in precomputed for inference decode steps.

Uses the interleaved="false" (half-rotation / GPT-NeoX) layout which matches
the reference default ``rotary_interleaved=False``.
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp


def rope_frequencies(head_dim: int, base: float = 10000.0,
                     rotary_percent: float = 1.0) -> jnp.ndarray:
    """Inverse frequencies [rot_dim/2] in fp32."""
    rot_dim = int(head_dim * rotary_percent)
    rot_dim -= rot_dim % 2
    exponent = jnp.arange(0, rot_dim, 2, dtype=jnp.float32) / rot_dim
    return 1.0 / (base ** exponent)


def yarn_frequencies(head_dim: int, base: float = 10000.0,
                     scaling_factor: float = 1.0,
                     original_max_position: int = 4096,
                     beta_fast: float = 32.0, beta_slow: float = 1.0,
                     rotary_percent: float = 1.0) -> jnp.ndarray:
    """YaRN NTK-by-parts interpolation of RoPE frequencies.

    Semantics of yarn_rotary_pos_embedding.py (find_correction_range +
    linear_ramp_mask): low-frequency dims are interpolated by
    1/scaling_factor, high-frequency dims keep extrapolation, with a linear
    ramp between correction bounds.
    """
    rot_dim = int(head_dim * rotary_percent)
    rot_dim -= rot_dim % 2
    freq_extra = rope_frequencies(head_dim, base, rotary_percent)
    freq_inter = freq_extra / scaling_factor

    def correction_dim(num_rotations):
        return (rot_dim * math.log(original_max_position /
                                   (num_rotations * 2 * math.pi))) / \
               (2 * math.log(base))

    low = math.floor(correction_dim(beta_fast))
    high = math.ceil(correction_dim(beta_slow))
    low = max(low, 0)
    high = min(high, rot_dim - 1)
    ramp = (jnp.arange(rot_dim // 2, dtype=jnp.float32) - low) / max(high - low, 1)
    ramp = jnp.clip(ramp, 0.0, 1.0)
    # ramp==0 → extrapolation (high freq); ramp==1 → interpolation.
    return freq_extra * (1 - ramp) + freq_inter * ramp


def yarn_mscale(scaling_factor: float, mscale_coeff: float = 0.1) -> float:
    if scaling_factor <= 1.0:
        return 1.0
    return 1.0 + mscale_coeff * math.log(scaling_factor)


def rope_cos_sin(positions: jnp.ndarray, inv_freq: jnp.ndarray):
    """cos/sin tables for given positions.

    positions: [...seq] int32; returns cos,sin of shape [...seq, rot_dim/2].
    """
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               mscale: float = 1.0) -> jnp.ndarray:
    """Apply half-rotation RoPE.

    x: [batch, seq, heads, head_dim]; cos/sin: [seq, rot_dim/2] or
    [batch, seq, rot_dim/2]. Rotates the first rot_dim features, passes the
    rest through (rotary_percent < 1 parity).
    """
    half = cos.shape[-1]
    rot_dim = 2 * half
    x_rot, x_pass = x[..., :rot_dim], x[..., rot_dim:]
    x1, x2 = x_rot[..., :half], x_rot[..., half:]
    if cos.ndim == 2:  # [seq, half] → broadcast over batch and heads
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
    else:  # [batch, seq, half]
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]
    if mscale != 1.0:
        c = c * mscale
        s = s * mscale
    out1 = x1.astype(jnp.float32) * c - x2.astype(jnp.float32) * s
    out2 = x2.astype(jnp.float32) * c + x1.astype(jnp.float32) * s
    out = jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
    if x_pass.shape[-1]:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out
