"""ctypes binding for the C++ shared-memory staging ring.

Host-side inter-process tensor hand-off (see runtime/native/shm_ring.cpp for
the MegaDPP-transport lineage). Single-producer single-consumer; numpy
arrays are framed with a tiny header carrying dtype/shape.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libshm_ring.so")
_LIB = None
_LOAD_FAILED = False
_LOCK = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LOAD_FAILED
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_FAILED:
            return None
        src = os.path.join(_NATIVE_DIR, "shm_ring.cpp")
        if not os.path.exists(_SO_PATH) or (
                os.path.exists(src) and
                os.path.getmtime(_SO_PATH) < os.path.getmtime(src)):
            if not os.path.exists(src):
                _LOAD_FAILED = True
                return None
            tmp = _SO_PATH + f".tmp.{os.getpid()}"
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, src,
                     "-lrt"],
                    check=True, capture_output=True)
                os.replace(tmp, _SO_PATH)
            except Exception:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                _LOAD_FAILED = True
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            _LOAD_FAILED = True
            return None
        lib.shm_ring_create.restype = ctypes.c_void_p
        lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shm_ring_open.restype = ctypes.c_void_p
        lib.shm_ring_open.argtypes = [ctypes.c_char_p]
        lib.shm_ring_push.restype = ctypes.c_uint64
        lib.shm_ring_push.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint8),
                                      ctypes.c_uint64]
        lib.shm_ring_pop.restype = ctypes.c_uint64
        lib.shm_ring_pop.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint8),
                                     ctypes.c_uint64]
        lib.shm_ring_used.restype = ctypes.c_uint64
        lib.shm_ring_used.argtypes = [ctypes.c_void_p]
        lib.shm_ring_close.argtypes = [ctypes.c_void_p]
        lib.shm_ring_unlink.argtypes = [ctypes.c_char_p]
        _LIB = lib
        return _LIB


def native_available() -> bool:
    return _load() is not None


_UINT64_MAX = 2 ** 64 - 1


class ShmRing:
    """SPSC byte/tensor ring in /dev/shm."""

    def __init__(self, name: str, capacity: int = 1 << 24,
                 create: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError("libshm_ring.so unavailable (g++ missing?)")
        self._lib = lib
        self.name = name.encode()
        if create:
            self._h = lib.shm_ring_create(self.name, capacity)
        else:
            self._h = lib.shm_ring_open(self.name)
        if not self._h:
            raise OSError(f"failed to map shm ring {name!r}")

    _U8P = ctypes.POINTER(ctypes.c_uint8)

    def _np_ptr(self, arr: np.ndarray):
        return arr.ctypes.data_as(self._U8P)

    # -- raw bytes ---------------------------------------------------------
    def push_bytes(self, data) -> bool:
        arr = np.frombuffer(data, dtype=np.uint8)
        return self._lib.shm_ring_push(self._h, self._np_ptr(arr),
                                       len(arr)) == len(arr)

    def pop_bytes(self, max_len: int = 1 << 22) -> Optional[bytes]:
        arr = self._pop_np(max_len)
        return None if arr is None else arr.tobytes()

    def _pop_np(self, max_len: int) -> Optional[np.ndarray]:
        # Reuse one receive buffer across calls (allocated/grown lazily).
        buf = getattr(self, "_rx", None)
        if buf is None or len(buf) < max_len:
            buf = self._rx = np.empty(max_len, np.uint8)
        n = self._lib.shm_ring_pop(self._h, self._np_ptr(buf), max_len)
        if n == 0:
            return None
        if n == _UINT64_MAX:
            raise ValueError("message larger than max_len")
        return buf[:n]

    # -- numpy tensors -----------------------------------------------------
    def push_array(self, arr: np.ndarray) -> bool:
        arr = np.ascontiguousarray(arr)
        meta = json.dumps({"dtype": arr.dtype.str,
                           "shape": arr.shape}).encode()
        flat = arr.view(np.uint8).ravel()
        frame = np.empty(4 + len(meta) + flat.nbytes, np.uint8)
        frame[:4] = np.frombuffer(
            len(meta).to_bytes(4, "little"), np.uint8)
        frame[4: 4 + len(meta)] = np.frombuffer(meta, np.uint8)
        frame[4 + len(meta):] = flat
        return self._lib.shm_ring_push(self._h, self._np_ptr(frame),
                                       len(frame)) == len(frame)

    def pop_array(self, max_len: int = 1 << 26) -> Optional[np.ndarray]:
        frame = self._pop_np(max_len)
        if frame is None:
            return None
        mlen = int.from_bytes(frame[:4].tobytes(), "little")
        meta = json.loads(frame[4: 4 + mlen].tobytes())
        payload = frame[4 + mlen:]
        return payload.view(np.dtype(meta["dtype"])).reshape(
            meta["shape"]).copy()

    @property
    def used_bytes(self) -> int:
        return int(self._lib.shm_ring_used(self._h))

    def close(self):
        if self._h:
            self._lib.shm_ring_close(self._h)
            self._h = None

    def unlink(self):
        self._lib.shm_ring_unlink(self.name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
