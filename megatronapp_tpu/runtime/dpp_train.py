"""MegaDPP in the real training path: a host-driven fwd+bwd GPT step.

The reference initializes its dynamic transport inside ``pretrain_body``
(/root/reference/megatron/training/training.py:746-783) — MegaDPP is a
property of training runs, not a sidecar benchmark. This module gives the
TPU framework the same: ``make_dpp_train_step`` builds a drop-in
``step(state, batch) -> (state, metrics)`` whose pipeline-parallel
execution runs through ``DppPipelineRunner.run_train`` — per-stage
devices, readiness-driven transfer ordering, and a real backward sweep
through the same scheduler (reference backward_send,
shm_tensor_new_rdma.cpp:1550-1646) — instead of the jitted SPMD schedule.

Layouts: pp over stage devices, optionally × dp — each data-parallel
replica runs its own host pipeline over its own pp devices on its shard
of every microbatch. Combine weights ride the cotangent seeds (CE:
w_r/W mask-token shares — exactly the SPMD path's global masked-mean
decomposition; aux: 1/dp), so gradient trees plain-sum and a fully
masked shard still backprops its aux losses. MoE aux terms use
PER-REPLICA batch statistics — the reference's own DDP semantics (each
rank's router sees its tokens), approximately equal to the SPMD path's
global-batch statistics for the nonlinear load-balance term. Still guarded with actionable errors: tp = cp = ep = 1
(the host runner places one stage per device), no MTP, no packed
segments. Embedding runs on each replica's first stage device and the
LM head + loss on its last, the reference's stage placement. Numerics
match ``gpt_pipeline_loss`` + ``spmd_pipeline`` (layer offset
(chunk*pp + stage)*Lc, per-injection compute-dtype cast, aux summed over
stage-chunk-mb then /M) — pinned by the golden-parity tests in
tests/test_dpp_runtime.py.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.runtime.dpp import DppPipelineRunner


def _device_grid(devices) -> List[List[Any]]:
    """Normalize to [pp][dp]: a flat sequence means dp=1."""
    arr = np.asarray(devices, dtype=object)
    if arr.ndim == 1:
        arr = arr[:, None]
    if arr.ndim != 2:
        raise ValueError(f"devices must be [pp] or [pp][dp], got shape "
                         f"{arr.shape}")
    return [list(row) for row in arr]


def make_dpp_gpt_value_and_grad(cfg, devices, vpp: int = 1,
                                policy: str = "dfc", dynamic: bool = True,
                                n_buffers: int = 4,
                                jitter=None):
    """Build vg(params, batch_mb) -> (loss, grads, metrics, runners).

    batch_mb: {'tokens','labels','loss_mask': [M, mb, S]}. params is the
    full GPT pytree with params['block'] stacked [pp, vpp, Lc, ...]
    (models/gpt.py reshape convention). devices: [pp] stage devices, or
    [pp][dp] for data-parallel replicas (each column runs one pipeline
    on its batch shard). The returned callable reuses its jitted
    chunk/head/embed closures across steps and replicas, so
    steady-state calls do not recompile.
    """
    from megatronapp_tpu.models.gpt import (
        gpt_embed, gpt_head, gpt_rope_tables,
    )
    from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
    from megatronapp_tpu.transformer.block import block_forward

    if getattr(cfg, "mtp_num_layers", 0):
        raise NotImplementedError(
            "the DPP runtime step does not support multi-token prediction "
            "yet; drop --mtp-num-layers or --use-dpp")
    grid = _device_grid(devices)
    pp, dp = len(grid), len(grid[0])

    # One jitted forward per (stage, chunk, seq) — the layer offset is
    # baked in, matching spmd_pipeline's (chunk*pp + stage)*Lc indexing;
    # replicas share the callables (jit re-specializes per device).
    chunk_fwd_cache: Dict[Tuple[int, int, int], Callable] = {}
    rope_cache: Dict[int, Tuple[Any, Any]] = {}

    def chunk_fwd(stage: int, chunk: int, lc: int, s: int) -> Callable:
        # Keyed on s as well: the closure bakes in the rope tables for
        # one sequence length, and a shape change (rampup, eval stream)
        # must re-derive them rather than reuse stale tables.
        key = (stage, chunk, s)
        if key not in chunk_fwd_cache:
            offset = (chunk * pp + stage) * lc
            if s not in rope_cache:
                rope_cache[s] = gpt_rope_tables(cfg, s)
            cos, sin = rope_cache[s]

            @jax.jit
            def f(pc, x, _off=offset, _cos=cos, _sin=sin):
                return block_forward(pc, x, cfg, _cos, _sin, None,
                                     layer_offset=_off, ctx=None)

            chunk_fwd_cache[key] = f
        return chunk_fwd_cache[key]

    @jax.jit
    def f_embed(params, tokens_flat):
        return gpt_embed(params, tokens_flat, cfg, dtype=jnp.float32)

    @jax.jit
    def f_head(params, out_stack, targets_mb, loss_mask_mb):
        logits = gpt_head(params, out_stack, cfg)
        ce, _ = cross_entropy_loss(logits, targets_mb, loss_mask_mb)
        return ce

    def _replica_vg(params, tokens_mb, targets_mb, loss_mask_mb,
                    rdevs, ce_seed: float, aux_seed: float, replica: int):
        """One data-parallel replica's full fwd+bwd on its pp devices.

        Returns (ce, aux, grads-on-rdevs[0], runner). The combine
        weights ride the cotangent SEEDS — ce_seed = w_r/W (this
        replica's mask-token share) on the head loss, aux_seed = 1/dp
        on every chunk's aux output — so the caller combines gradient
        trees by PLAIN SUM and the aux gradients survive even a fully
        masked shard (w_r = 0 zeroes only the CE part, exactly like the
        SPMD step)."""
        m, mb, s = tokens_mb.shape
        pipe = params["block"]
        lc = jax.tree.leaves(pipe)[0].shape[2]
        compute_dtype = cfg.compute_dtype

        # Slice + place per-(stage, chunk) params (the executor's
        # distribution step; on a pod this is the per-stage weight
        # residency the reference gets from per-rank ownership).
        placed = [[jax.device_put(
            jax.tree.map(lambda x, s_=st, c_=c: x[s_, c_], pipe),
            rdevs[st]) for c in range(vpp)] for st in range(pp)]

        # Embed/head touch only the non-block params; place those copies
        # explicitly (params may arrive mesh-sharded from the SPMD-layout
        # train state — a single jit must not see mixed assignments).
        light = {k: v for k, v in params.items() if k != "block"}
        light_first = jax.device_put(light, rdevs[0])
        light_last = jax.device_put(light, rdevs[-1])

        # Embedding on the first stage device.
        with jax.default_device(rdevs[0]):
            h_flat, embed_vjp = jax.vjp(
                f_embed, light_first,
                jax.device_put(tokens_mb, rdevs[0]).reshape(m * mb, s))
        h_mb = h_flat.reshape(m, mb, s, -1)

        aux_parts = []

        def chunk_vjp_fn(stage, c, h, m_idx):
            if jitter and (stage, c) in jitter:
                # A/B instrumentation: injected per-(stage, chunk) delay
                # modeling a straggling stage (tools/dpp_ab_benchmark.py).
                import time as _time
                _time.sleep(jitter[(stage, c)])
            f = chunk_fwd(stage, c, lc, s)
            (y, a), vjp = jax.vjp(f, placed[stage][c], h)
            aux_parts.append(a)

            def wrapped(g_y, _vjp=vjp):
                # Each chunk's aux loss enters the total as
                # aux_sum / (M · dp) — the seed carries the replica
                # weighting (see docstring).
                return _vjp((g_y, jnp.asarray(aux_seed / m,
                                              jnp.float32)))

            return y, wrapped

        loss_box = {}

        def seed_grads_fn(outputs):
            out_stack = jnp.stack(
                [jax.device_put(o, rdevs[-1]) for o in outputs])
            # Head runs on the last stage device: co-locate its operands.
            targets_last = jax.device_put(targets_mb, rdevs[-1])
            mask_last = (None if loss_mask_mb is None
                         else jax.device_put(loss_mask_mb, rdevs[-1]))
            with jax.default_device(rdevs[-1]):
                ce, head_vjp = jax.vjp(
                    f_head, light_last, out_stack, targets_last,
                    mask_last)
                g_params_head, g_out, _, _ = head_vjp(
                    jnp.asarray(ce_seed, ce.dtype))
            loss_box["ce"] = ce
            loss_box["g_params_head"] = g_params_head
            return [g_out[i] for i in range(m)], None

        runner = DppPipelineRunner(
            None, rdevs, pp, vpp, m, policy=policy, dynamic=dynamic,
            n_buffers=n_buffers)
        _, block_grads, input_grads, _ = runner.run_train(
            [h_mb[i].astype(compute_dtype) for i in range(m)],
            chunk_vjp_fn, seed_grads_fn)

        # Assemble the stacked [pp, vpp, Lc, ...] block gradient.
        def on0(t):
            return jax.tree.map(lambda x: jax.device_put(x, rdevs[0]), t)

        per_stage = [
            jax.tree.map(lambda *cs: jnp.stack(cs),
                         *[on0(block_grads[(st, c)]) for c in range(vpp)])
            if vpp > 1 else
            jax.tree.map(lambda x: x[None], on0(block_grads[(st, 0)]))
            for st in range(pp)
        ]
        g_block = jax.tree.map(lambda *ss: jnp.stack(ss), *per_stage)

        # Embedding grad: the runner consumed h.astype(compute_dtype), so
        # chain the cast back to fp32 by hand.
        dh_mb = jnp.stack([jax.device_put(g, rdevs[0])
                           for g in input_grads]).astype(jnp.float32)
        g_params_embed, _ = embed_vjp(dh_mb.reshape(m * mb, s, -1))

        g_params_head = jax.tree.map(
            lambda x: jax.device_put(x, rdevs[0]),
            loss_box["g_params_head"])
        grads = jax.tree.map(lambda a, b: a + b,
                             g_params_embed, g_params_head)
        grads = dict(grads)
        grads["block"] = g_block

        aux_total = sum(jax.device_get(a) for a in aux_parts)
        aux = jnp.asarray(aux_total, jnp.float32) / m
        return loss_box["ce"], aux, grads, runner

    def vg(params, batch_mb):
        tokens_mb = jnp.asarray(batch_mb["tokens"])
        targets_mb = jnp.asarray(batch_mb["labels"])
        loss_mask_mb = batch_mb.get("loss_mask")
        if loss_mask_mb is not None:
            loss_mask_mb = jnp.asarray(loss_mask_mb)
        if batch_mb.get("segment_ids") is not None:
            raise NotImplementedError(
                "the DPP runtime step does not support packed segments "
                "yet; unpack the batch or drop --use-dpp")
        m, mb, s = tokens_mb.shape
        if mb % dp:
            raise ValueError(
                f"per-microbatch batch {mb} not divisible by dp={dp} "
                "under the DPP runtime")
        shard = mb // dp
        sls = [slice(r * shard, (r + 1) * shard) for r in range(dp)]
        # Mask-token weights: the SPMD path's CE is a masked mean over
        # the GLOBAL batch, which decomposes exactly as
        # sum_r w_r*ce_r / sum_r w_r with w_r the replica's mask sum.
        if loss_mask_mb is not None:
            w = [float(jnp.sum(loss_mask_mb[:, sl])) for sl in sls]
        else:
            w = [float(m * shard * s)] * dp
        W = sum(w) or 1.0

        results: List[Any] = [None] * dp
        errors: List[BaseException] = []

        def run_replica(r):
            try:
                results[r] = _replica_vg(
                    params, tokens_mb[:, sls[r]], targets_mb[:, sls[r]],
                    None if loss_mask_mb is None
                    else loss_mask_mb[:, sls[r]],
                    [grid[st][r] for st in range(pp)],
                    ce_seed=w[r] / W, aux_seed=1.0 / dp, replica=r)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(RuntimeError(
                    f"DPP replica {r} failed: {e!r}"))
                errors[-1].__cause__ = e

        if dp == 1:
            run_replica(0)
        else:
            ts = [threading.Thread(target=run_replica, args=(r,),
                                   daemon=True) for r in range(dp)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        if errors:
            raise errors[0]

        dev0 = grid[0][0]
        ce = sum((w[r] / W) * jax.device_put(results[r][0], dev0)
                 for r in range(dp))
        aux = sum(jax.device_put(results[r][1], dev0)
                  for r in range(dp)) / dp
        # Plain sum: the combine weights already rode the cotangent
        # seeds (ce_seed/aux_seed), so loss and gradients stay
        # consistent even for a fully masked shard.
        grads = jax.tree.map(
            lambda *gs: sum(jax.device_put(g, dev0) for g in gs),
            *[results[r][2] for r in range(dp)])
        runners = [results[r][3] for r in range(dp)]
        loss = ce + aux
        metrics = {"lm_loss": ce, "moe_aux_loss": aux}
        return loss, grads, metrics, runners

    return vg


def make_dpp_train_step(optimizer, opt_cfg, cfg, devices, train_iters: int,
                        vpp: int = 1, policy: str = "dfc",
                        dynamic: bool = True, check_nan: bool = True,
                        state_shardings=None, jitter=None):
    """Drop-in for make_train_step when the DPP runtime drives pp (×dp):
    the value-and-grad half runs host-driven through the dynamic
    scheduler; the optimizer half is one jitted update (same NaN gate,
    grad norm, lr schedule and metrics contract as
    training/train_step.py).

    state_shardings: when given (the train driver's mesh shardings), the
    update step keeps the state in that layout across iterations so the
    surrounding machinery (eval step, checkpointing, resharding) sees
    the same state it would under the SPMD step."""
    from megatronapp_tpu.training.optimizer import (
        global_grad_norm, lr_schedule,
    )

    grid = _device_grid(devices)
    sched = lr_schedule(opt_cfg, train_iters)
    vg = make_dpp_gpt_value_and_grad(cfg, devices, vpp=vpp, policy=policy,
                                     dynamic=dynamic, jitter=jitter)

    def apply(state, grads, loss):
        params = state["params"]
        grad_norm = global_grad_norm(grads)
        finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)

        def do_update(_):
            updates, new_opt = optimizer.update(
                grads, state["opt_state"], params)
            if hasattr(optimizer, "apply_updates"):
                # ZeRO-1 wrapper with master weights: params are the
                # rounded image of the fp32 master shard (same contract
                # as train_step's GSPMD/manual paths).
                new_params = optimizer.apply_updates(params, updates,
                                                     new_opt)
            else:
                new_params = jax.tree.map(
                    lambda p, u: (p + u.astype(p.dtype)), params, updates)
            return new_params, new_opt

        def skip(_):
            return params, state["opt_state"]

        if check_nan:
            new_params, new_opt = jax.lax.cond(finite, do_update, skip,
                                               operand=None)
            skipped = jnp.where(finite, 0, 1).astype(jnp.int32)
        else:
            new_params, new_opt = do_update(None)
            skipped = jnp.zeros((), jnp.int32)
        new_state = {"step": state["step"] + 1, "params": new_params,
                     "opt_state": new_opt}
        return new_state, grad_norm, skipped

    if state_shardings is not None:
        param_sh = state_shardings["params"]
        mesh = jax.tree.leaves(state_shardings)[0].mesh
        from jax.sharding import NamedSharding, PartitionSpec
        scalar_sh = NamedSharding(mesh, PartitionSpec())
        apply = jax.jit(apply,
                        in_shardings=(state_shardings, param_sh, scalar_sh),
                        out_shardings=(state_shardings, None, None))
    else:
        param_sh = scalar_sh = None
        apply = jax.jit(apply)

    def step(state, batch):
        import time as _time

        from megatronapp_tpu.trace.tracer import get_tracer
        tracer = get_tracer()
        tracing = tracer.enabled and tracer.active
        t0 = _time.perf_counter()
        anchor = tracer.now_in_iteration_us() if tracing else None
        loss, grads, aux, runners = vg(state["params"], batch)
        if tracing:
            # Per-(chunk, mb) compute/transfer spans on per-stage
            # timelines — MegaScan sees the DPP transport like the
            # reference's tracer sees its shm/RDMA sends. Replica r's
            # stage rows land on pids 5000+100r+stage.
            for r, runner in enumerate(runners):
                tracer.add_collective_records(
                    runner.trace_events(t0, pid_base=5000 + 100 * r),
                    offset_us=anchor)
        # The loss lands on the first replica's lead device and grads
        # with it; re-lay them out for the update step (which keeps the
        # state in the driver's mesh layout when given).
        loss = jax.device_put(
            loss, scalar_sh if scalar_sh is not None else grid[0][0])
        if param_sh is not None:
            grads = jax.device_put(grads, param_sh)
        new_state, grad_norm, skipped = apply(state, grads, loss)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "lr": sched(state["step"]),
            "skipped": skipped,
            **aux,
            # Scheduler observables (PERF.md's DPP A/B metrics), per
            # phase, summed over replicas: downstream input wait is the
            # stall DPP ordering removes.
            "dpp_fwd_compute_wait_s": sum(
                sum(ru.fwd_metrics["compute_wait_s"][1:])
                for ru in runners),
            "dpp_bwd_compute_wait_s": sum(
                sum(ru.bwd_metrics["compute_wait_s"][:-1])
                for ru in runners),
        }
        return new_state, metrics

    return step
