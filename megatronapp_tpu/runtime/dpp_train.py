"""MegaDPP in the real training path: a host-driven fwd+bwd GPT step.

The reference initializes its dynamic transport inside ``pretrain_body``
(/root/reference/megatron/training/training.py:746-783) — MegaDPP is a
property of training runs, not a sidecar benchmark. This module gives the
TPU framework the same: ``make_dpp_train_step`` builds a drop-in
``step(state, batch) -> (state, metrics)`` whose pipeline-parallel
execution runs through ``DppPipelineRunner.run_train`` — per-stage
devices, readiness-driven transfer ordering, and a real backward sweep
through the same scheduler (reference backward_send,
shm_tensor_new_rdma.cpp:1550-1646) — instead of the jitted SPMD schedule.

Scope (guarded with actionable errors): pure pipeline parallelism
(dp = tp = cp = ep = 1 — the host runner places one stage per device),
no MTP, no packed segments. Embedding runs on the first stage device and
the LM head + loss on the last, the reference's stage placement.
Numerics match ``gpt_pipeline_loss`` + ``spmd_pipeline`` (layer offset
(chunk*pp + stage)*Lc, per-injection compute-dtype cast, aux summed over
stage-chunk-mb then /M) — pinned by the golden-parity test in
tests/test_dpp_runtime.py.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.runtime.dpp import DppPipelineRunner


def make_dpp_gpt_value_and_grad(cfg, devices, vpp: int = 1,
                                policy: str = "dfc", dynamic: bool = True,
                                n_buffers: int = 4,
                                jitter=None):
    """Build vg(params, batch_mb) -> (loss, grads, metrics, runner).

    batch_mb: {'tokens','labels','loss_mask': [M, mb, S]}. params is the
    full GPT pytree with params['block'] stacked [pp, vpp, Lc, ...]
    (models/gpt.py reshape convention). The returned callable reuses its
    jitted chunk/head/embed closures across steps, so steady-state calls
    do not recompile.
    """
    from megatronapp_tpu.models.gpt import (
        gpt_embed, gpt_head, gpt_rope_tables,
    )
    from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
    from megatronapp_tpu.transformer.block import block_forward

    if getattr(cfg, "mtp_num_layers", 0):
        raise NotImplementedError(
            "the DPP runtime step does not support multi-token prediction "
            "yet; drop --mtp-num-layers or --dpp-runtime")
    pp = len(devices)

    # One jitted forward per (stage, chunk) — the layer offset is baked
    # in, matching spmd_pipeline's (chunk*pp + stage)*Lc indexing.
    chunk_fwd_cache: Dict[Tuple[int, int], Callable] = {}
    rope_cache: Dict[int, Tuple[Any, Any]] = {}

    def chunk_fwd(stage: int, chunk: int, lc: int, s: int) -> Callable:
        # Keyed on s as well: the closure bakes in the rope tables for
        # one sequence length, and a shape change (rampup, eval stream)
        # must re-derive them rather than reuse stale tables.
        key = (stage, chunk, s)
        if key not in chunk_fwd_cache:
            offset = (chunk * pp + stage) * lc
            if s not in rope_cache:
                rope_cache[s] = gpt_rope_tables(cfg, s)
            cos, sin = rope_cache[s]

            @jax.jit
            def f(pc, x, _off=offset, _cos=cos, _sin=sin):
                return block_forward(pc, x, cfg, _cos, _sin, None,
                                     layer_offset=_off, ctx=None)

            chunk_fwd_cache[key] = f
        return chunk_fwd_cache[key]

    @jax.jit
    def f_embed(params, tokens_flat):
        return gpt_embed(params, tokens_flat, cfg, dtype=jnp.float32)

    @jax.jit
    def f_head(params, out_stack, targets_mb, loss_mask_mb):
        logits = gpt_head(params, out_stack, cfg)
        ce, _ = cross_entropy_loss(logits, targets_mb, loss_mask_mb)
        return ce

    def vg(params, batch_mb):
        tokens_mb = jnp.asarray(batch_mb["tokens"])
        targets_mb = jnp.asarray(batch_mb["labels"])
        loss_mask_mb = batch_mb.get("loss_mask")
        if loss_mask_mb is not None:
            loss_mask_mb = jnp.asarray(loss_mask_mb)
        if batch_mb.get("segment_ids") is not None:
            raise NotImplementedError(
                "the DPP runtime step does not support packed segments "
                "yet; unpack the batch or drop --dpp-runtime")
        m, mb, s = tokens_mb.shape
        pipe = params["block"]
        lc = jax.tree.leaves(pipe)[0].shape[2]
        compute_dtype = cfg.compute_dtype

        # Slice + place per-(stage, chunk) params (the executor's
        # distribution step; on a pod this is the per-stage weight
        # residency the reference gets from per-rank ownership).
        placed = [[jax.device_put(
            jax.tree.map(lambda x, s_=st, c_=c: x[s_, c_], pipe),
            devices[st]) for c in range(vpp)] for st in range(pp)]

        # Embed/head touch only the non-block params; place those copies
        # explicitly (params may arrive mesh-sharded from the SPMD-layout
        # train state — a single jit must not see mixed assignments).
        light = {k: v for k, v in params.items() if k != "block"}
        light_first = jax.device_put(light, devices[0])
        light_last = jax.device_put(light, devices[-1])

        # Embedding on the first stage device.
        with jax.default_device(devices[0]):
            h_flat, embed_vjp = jax.vjp(
                f_embed, light_first,
                jax.device_put(tokens_mb, devices[0]).reshape(m * mb, s))
        h_mb = h_flat.reshape(m, mb, s, -1)

        aux_parts = []

        def chunk_vjp_fn(stage, c, h, m_idx):
            if jitter and (stage, c) in jitter:
                # A/B instrumentation: injected per-(stage, chunk) delay
                # modeling a straggling stage (tools/dpp_ab_benchmark.py).
                import time as _time
                _time.sleep(jitter[(stage, c)])
            f = chunk_fwd(stage, c, lc, s)
            (y, a), vjp = jax.vjp(f, placed[stage][c], h)
            aux_parts.append(a)

            def wrapped(g_y, _vjp=vjp):
                # Each chunk's aux loss enters the total as aux_sum / M.
                return _vjp((g_y, jnp.asarray(1.0 / m, jnp.float32)))

            return y, wrapped

        loss_box = {}

        def seed_grads_fn(outputs):
            out_stack = jnp.stack(
                [jax.device_put(o, devices[-1]) for o in outputs])
            # Head runs on the last stage device: co-locate its operands.
            targets_last = jax.device_put(targets_mb, devices[-1])
            mask_last = (None if loss_mask_mb is None
                         else jax.device_put(loss_mask_mb, devices[-1]))
            with jax.default_device(devices[-1]):
                ce, head_vjp = jax.vjp(
                    f_head, light_last, out_stack, targets_last,
                    mask_last)
                g_params_head, g_out, _, _ = head_vjp(
                    jnp.ones((), ce.dtype))
            loss_box["ce"] = ce
            loss_box["g_params_head"] = g_params_head
            return [g_out[i] for i in range(m)], None

        runner = DppPipelineRunner(
            None, devices, pp, vpp, m, policy=policy, dynamic=dynamic,
            n_buffers=n_buffers)
        _, block_grads, input_grads, _ = runner.run_train(
            [h_mb[i].astype(compute_dtype) for i in range(m)],
            chunk_vjp_fn, seed_grads_fn)

        # Assemble the stacked [pp, vpp, Lc, ...] block gradient.
        def on0(t):
            return jax.tree.map(lambda x: jax.device_put(x, devices[0]), t)

        per_stage = [
            jax.tree.map(lambda *cs: jnp.stack(cs),
                         *[on0(block_grads[(st, c)]) for c in range(vpp)])
            if vpp > 1 else
            jax.tree.map(lambda x: x[None], on0(block_grads[(st, 0)]))
            for st in range(pp)
        ]
        g_block = jax.tree.map(lambda *ss: jnp.stack(ss), *per_stage)

        # Embedding grad: the runner consumed h.astype(compute_dtype), so
        # chain the cast back to fp32 by hand.
        dh_mb = jnp.stack([jax.device_put(g, devices[0])
                           for g in input_grads]).astype(jnp.float32)
        g_params_embed, _ = embed_vjp(dh_mb.reshape(m * mb, s, -1))

        g_params_head = jax.tree.map(
            lambda x: jax.device_put(x, devices[0]),
            loss_box["g_params_head"])
        grads = jax.tree.map(lambda a, b: a + b,
                             g_params_embed, g_params_head)
        grads = dict(grads)
        grads["block"] = g_block

        aux_total = sum(jax.device_get(a) for a in aux_parts)
        aux = jnp.asarray(aux_total, jnp.float32) / m
        ce = loss_box["ce"]
        loss = ce + aux
        metrics = {"lm_loss": ce, "moe_aux_loss": aux}
        return loss, grads, metrics, runner

    return vg


def make_dpp_train_step(optimizer, opt_cfg, cfg, devices, train_iters: int,
                        vpp: int = 1, policy: str = "dfc",
                        dynamic: bool = True, check_nan: bool = True,
                        state_shardings=None, jitter=None):
    """Drop-in for make_train_step when the DPP runtime drives pp: the
    value-and-grad half runs host-driven through the dynamic scheduler;
    the optimizer half is one jitted update (same NaN gate, grad norm,
    lr schedule and metrics contract as training/train_step.py).

    state_shardings: when given (the train driver's mesh shardings), the
    update step keeps the state in that layout across iterations so the
    surrounding machinery (eval step, checkpointing, resharding) sees
    the same state it would under the SPMD step."""
    from megatronapp_tpu.training.optimizer import (
        global_grad_norm, lr_schedule,
    )

    sched = lr_schedule(opt_cfg, train_iters)
    vg = make_dpp_gpt_value_and_grad(cfg, devices, vpp=vpp, policy=policy,
                                     dynamic=dynamic, jitter=jitter)

    def apply(state, grads, loss):
        params = state["params"]
        grad_norm = global_grad_norm(grads)
        finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)

        def do_update(_):
            updates, new_opt = optimizer.update(
                grads, state["opt_state"], params)
            new_params = jax.tree.map(
                lambda p, u: (p + u.astype(p.dtype)), params, updates)
            return new_params, new_opt

        def skip(_):
            return params, state["opt_state"]

        if check_nan:
            new_params, new_opt = jax.lax.cond(finite, do_update, skip,
                                               operand=None)
            skipped = jnp.where(finite, 0, 1).astype(jnp.int32)
        else:
            new_params, new_opt = do_update(None)
            skipped = jnp.zeros((), jnp.int32)
        new_state = {"step": state["step"] + 1, "params": new_params,
                     "opt_state": new_opt}
        return new_state, grad_norm, skipped

    if state_shardings is not None:
        param_sh = state_shardings["params"]
        mesh = jax.tree.leaves(state_shardings)[0].mesh
        from jax.sharding import NamedSharding, PartitionSpec
        scalar_sh = NamedSharding(mesh, PartitionSpec())
        apply = jax.jit(apply,
                        in_shardings=(state_shardings, param_sh, scalar_sh),
                        out_shardings=(state_shardings, None, None))
    else:
        param_sh = scalar_sh = None
        apply = jax.jit(apply)

    def step(state, batch):
        import time as _time

        from megatronapp_tpu.trace.tracer import get_tracer
        tracer = get_tracer()
        tracing = tracer.enabled and tracer.active
        t0 = _time.perf_counter()
        anchor = tracer.now_in_iteration_us() if tracing else None
        loss, grads, aux, runner = vg(state["params"], batch)
        if tracing:
            # Per-(chunk, mb) compute/transfer spans on per-stage
            # timelines — MegaScan sees the DPP transport like the
            # reference's tracer sees its shm/RDMA sends.
            tracer.add_collective_records(runner.trace_events(t0),
                                          offset_us=anchor)
        # The loss lands on the last stage device (head placement) and
        # grads on the first; re-lay them out for the update step (which
        # keeps the state in the driver's mesh layout when given).
        loss = jax.device_put(
            loss, scalar_sh if scalar_sh is not None else devices[0])
        if param_sh is not None:
            grads = jax.device_put(grads, param_sh)
        new_state, grad_norm, skipped = apply(state, grads, loss)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "lr": sched(state["step"]),
            "skipped": skipped,
            **aux,
            # Scheduler observables (PERF.md's DPP A/B metrics), per
            # phase: downstream input wait is the stall DPP ordering
            # removes.
            "dpp_fwd_compute_wait_s": sum(
                runner.fwd_metrics["compute_wait_s"][1:]),
            "dpp_bwd_compute_wait_s": sum(
                runner.bwd_metrics["compute_wait_s"][:-1]),
        }
        return new_state, metrics

    return step
