// POSIX shared-memory ring buffer for host-side staging, ctypes ABI.
//
// TPU-native analogue of the reference MegaDPP shm transport
// (/root/reference/megatron/shm_tensor_new_rdma/shm_tensor_new_rdma.cpp:
// /dev/shm segments + semaphores per neighbor pair, background send/recv
// threads; pre-alloc variant shm_tensor_new_rdma_pre_alloc.cpp). On TPU the
// device-to-device activation traffic itself rides ICI via XLA collectives
// (SURVEY §2.7), so the host staging role that remains is inter-PROCESS
// tensor hand-off on one host: data loaders feeding trainer processes,
// checkpoint shards staged for async upload, trace buffers. This is that
// staging ring: a single-producer single-consumer lock-free byte ring in
// /dev/shm with atomic head/tail, plus a standalone bandwidth benchmark
// entry (profiling/shm_benchmark.cpp parity via tools/shm_benchmark.py).
//
// Build: g++ -O3 -shared -fPIC -o libshm_ring.so shm_ring.cpp -lrt

#include <atomic>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct RingHeader {
    std::atomic<uint64_t> head;  // next write offset (producer)
    std::atomic<uint64_t> tail;  // next read offset (consumer)
    uint64_t capacity;           // data bytes
    uint64_t magic;
};

constexpr uint64_t kMagic = 0x4d544152494e4721ull;  // "MTARING!"

struct Ring {
    RingHeader* hdr;
    uint8_t* data;
    size_t map_size;
    int fd;
};

Ring* map_ring(const char* name, uint64_t capacity, bool create) {
    int flags = create ? (O_CREAT | O_RDWR) : O_RDWR;
    int fd = shm_open(name, flags, 0600);
    if (fd < 0) return nullptr;
    size_t map_size = sizeof(RingHeader) + capacity;
    if (create && ftruncate(fd, map_size) != 0) {
        close(fd);
        return nullptr;
    }
    if (!create) {
        struct stat st;
        if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(RingHeader)) {
            close(fd);
            return nullptr;
        }
        map_size = st.st_size;
    }
    void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                     fd, 0);
    if (mem == MAP_FAILED) {
        close(fd);
        return nullptr;
    }
    Ring* ring = new Ring;
    ring->hdr = reinterpret_cast<RingHeader*>(mem);
    ring->data = reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader);
    ring->map_size = map_size;
    ring->fd = fd;
    if (create) {
        ring->hdr->head.store(0, std::memory_order_relaxed);
        ring->hdr->tail.store(0, std::memory_order_relaxed);
        ring->hdr->capacity = capacity;
        ring->hdr->magic = kMagic;
    } else if (ring->hdr->magic != kMagic) {
        munmap(mem, map_size);
        close(fd);
        delete ring;
        return nullptr;
    }
    return ring;
}

}  // namespace

extern "C" {

void* shm_ring_create(const char* name, uint64_t capacity) {
    return map_ring(name, capacity, true);
}

void* shm_ring_open(const char* name) {
    return map_ring(name, 0, false);
}

// Returns bytes written (len or 0 if insufficient space). Message framing:
// u64 length prefix, payload, both possibly wrapping the ring.
uint64_t shm_ring_push(void* handle, const uint8_t* buf, uint64_t len) {
    if (len == 0) return 0;  // zero-length frames are indistinguishable
                             // from "ring empty" on the pop side
    Ring* r = static_cast<Ring*>(handle);
    uint64_t cap = r->hdr->capacity;
    uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    uint64_t used = head - tail;
    uint64_t need = len + 8;
    if (used + need > cap) return 0;

    uint64_t pos = head % cap;
    uint8_t hdr8[8];
    std::memcpy(hdr8, &len, 8);
    for (int i = 0; i < 8; ++i) r->data[(pos + i) % cap] = hdr8[i];
    uint64_t dpos = (pos + 8) % cap;
    uint64_t first = cap - dpos < len ? cap - dpos : len;
    std::memcpy(r->data + dpos, buf, first);
    if (first < len) std::memcpy(r->data, buf + first, len - first);
    r->hdr->head.store(head + need, std::memory_order_release);
    return len;
}

// Returns the message length (and copies up to buf_len bytes into buf), or
// 0 if the ring is empty, or UINT64_MAX if buf_len is too small (message is
// left in place).
uint64_t shm_ring_pop(void* handle, uint8_t* buf, uint64_t buf_len) {
    Ring* r = static_cast<Ring*>(handle);
    uint64_t cap = r->hdr->capacity;
    uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
    uint64_t head = r->hdr->head.load(std::memory_order_acquire);
    if (head == tail) return 0;

    uint64_t pos = tail % cap;
    uint8_t hdr8[8];
    for (int i = 0; i < 8; ++i) hdr8[i] = r->data[(pos + i) % cap];
    uint64_t len;
    std::memcpy(&len, hdr8, 8);
    if (len > buf_len) return UINT64_MAX;
    uint64_t dpos = (pos + 8) % cap;
    uint64_t first = cap - dpos < len ? cap - dpos : len;
    std::memcpy(buf, r->data + dpos, first);
    if (first < len) std::memcpy(buf + first, r->data, len - first);
    r->hdr->tail.store(tail + len + 8, std::memory_order_release);
    return len;
}

uint64_t shm_ring_used(void* handle) {
    Ring* r = static_cast<Ring*>(handle);
    return r->hdr->head.load(std::memory_order_acquire) -
           r->hdr->tail.load(std::memory_order_acquire);
}

void shm_ring_close(void* handle) {
    Ring* r = static_cast<Ring*>(handle);
    munmap(r->hdr, r->map_size);
    close(r->fd);
    delete r;
}

void shm_ring_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
