"""MegaDPP dynamic runtime: readiness-driven transfer ordering.

Parity with the reference's dynamic half of MegaDPP (paper §5.2): the
static schedules in parallel/pipeline.py pick a *compile-time* send order
(dfc/bfc); the reference additionally runs background sender threads that
scan a pool of finished tensors and ship whichever (chunk, microbatch) is
ready first in DFC/BFC priority order
(/root/reference/megatron/shm_tensor_new_rdma/shm_tensor_new_rdma.cpp:1478-1646
forward_send/backward_send traversal), through a pre-allocated bounded
buffer pool with ready/expired queues
(/root/reference/megatron/shm_tensor_new_rdma_pre_alloc/shm_tensor_new_rdma_pre_alloc.cpp:126-205
NUM_GPU_BUFFERS=4 + ready_buffers/expired_buffers + condition variables).

TPU-first reinterpretation: per-(stage, chunk) computations are separate
XLA executables dispatched asynchronously per stage device; the host
runtime watches completion (readiness) and *initiates inter-stage
transfers in priority order among the tensors that are actually ready*,
holding a slot from a bounded TransferPool for the duration of each
transfer. The transfer itself is one `jax.device_put` — PJRT DMA (ICI on
a pod, host staging on the tunneled chip) — so the runtime only
*sequences* transfers; Python threads are fine because dispatch,
block_until_ready and device_put all release the GIL. The static baseline
(`dynamic=False`) ships strictly in schedule order, blocking on each
index in turn even when later tensors are already finished — exactly the
stall DPP exists to remove.

The backward direction of the reference (backward_send, mirrored
priority) is symmetric; the FBD executor (parallel/fbd.py) already ships
vjp residuals fwd→bwd, so this runtime exposes the forward direction and
the generic scheduler both halves share.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

__all__ = [
    "send_priority", "static_order", "TransferPool", "DppPipelineRunner",
]


def send_priority(chunk: int, mb: int, pp: int, vpp: int,
                  policy: str = "dfc") -> Tuple[int, ...]:
    """Priority key for a finished (chunk, microbatch) activation — lower
    ships first. Mirrors the reference forward_send traversal of the
    (chunk, microbatch) matrix (shm_tensor_new_rdma.cpp:1487-1510):

    - 'dfc' (depth-first-chunk): rounds of pp microbatches; within a
      round, all chunks before the next round — the interleaved-schedule
      order (round, chunk, position).
    - 'bfc' (breadth-first-chunk): all microbatches of chunk c before
      chunk c+1 (chunk, mb).
    """
    if policy == "dfc":
        return (mb // pp, chunk, mb % pp)
    if policy == "bfc":
        return (chunk, mb)
    raise ValueError(f"unknown DPP order policy {policy!r}")


def static_order(pp: int, vpp: int, num_microbatches: int,
                 policy: str = "dfc") -> List[Tuple[int, int]]:
    """The full (chunk, mb) send order a static scheduler commits to."""
    items = [(c, m) for c in range(vpp) for m in range(num_microbatches)]
    items.sort(key=lambda cm: send_priority(cm[0], cm[1], pp, vpp, policy))
    return items


class TransferPool:
    """Bounded pool of transfer slots (the reference's NUM_GPU_BUFFERS
    pre-allocated staging buffers with ready/expired queues,
    shm_tensor_new_rdma_pre_alloc.cpp:126-205). A sender must hold a slot
    for the duration of a transfer; acquisition stall time is recorded —
    it is the backpressure signal the dynamic scheduler reacts to."""

    def __init__(self, n_buffers: int = 4):
        self._sem = threading.Semaphore(n_buffers)
        self._lock = threading.Lock()
        self.stall_s = 0.0
        self.acquisitions = 0

    def acquire(self) -> None:
        t0 = time.perf_counter()
        self._sem.acquire()
        dt = time.perf_counter() - t0
        with self._lock:
            self.stall_s += dt
            self.acquisitions += 1

    def release(self) -> None:
        self._sem.release()


class _Mailbox:
    """Arrival table keyed by (chunk, mb) with blocking pop."""

    def __init__(self):
        self._cv = threading.Condition()
        self._items: Dict[Tuple[int, int], Any] = {}

    def put(self, key: Tuple[int, int], value: Any) -> None:
        with self._cv:
            self._items[key] = value
            self._cv.notify_all()

    def pop(self, key: Tuple[int, int], timeout: float = 120.0) -> Any:
        with self._cv:
            ok = self._cv.wait_for(lambda: key in self._items, timeout)
            if not ok:
                raise TimeoutError(f"activation {key} never arrived")
            return self._items.pop(key)

    def pop_best(self, keyfn, timeout: float = 120.0) -> Tuple[Tuple[int, int], Any]:
        """Pop the minimum-priority available item (dynamic readiness
        scan, reference forward_send:1487-1520)."""
        with self._cv:
            ok = self._cv.wait_for(lambda: bool(self._items), timeout)
            if not ok:
                raise TimeoutError("no activation became ready")
            key = min(self._items, key=keyfn)
            return key, self._items.pop(key)


class DppPipelineRunner:
    """Host-driven interleaved pipeline with dynamic send ordering.

    chunk_fn(stage, chunk, h, mb) -> h' runs one model chunk of one
    microbatch (typically a jitted function closed over that stage's
    params, placed on ``devices[stage]``). The runner executes the full
    vpp-interleaved forward: (stage s, chunk c) feeds (s+1, c) or wraps
    (pp-1, c) → (0, c+1); chunk vpp-1 leaving stage pp-1 is an output.

    Per stage, a compute thread consumes arrivals and a sender thread
    ships finished activations — in readiness-first priority order
    (``dynamic=True``) or strict static order — through a bounded
    TransferPool per link. Metrics collected per run:
      transfer_order[stage]  — (chunk, mb) in actual ship order
      sender_stall_s[stage]  — time the sender spent waiting for work
      pool_stall_s[stage]    — time blocked on the bounded buffer pool
      compute_wait_s[stage]  — time the compute loop starved for inputs
                               (the downstream stall DPP reordering cuts)
    """

    def __init__(self, chunk_fn: Callable[[int, int, Any, int], Any],
                 devices: Sequence[Any], pp: int, vpp: int,
                 num_microbatches: int, policy: str = "dfc",
                 dynamic: bool = True, n_buffers: int = 4,
                 join_timeout_s: Optional[float] = None):
        if len(devices) < pp:
            raise ValueError(f"need {pp} devices, got {len(devices)}")
        self.chunk_fn = chunk_fn
        self.devices = list(devices[:pp])
        self.pp, self.vpp, self.M = pp, vpp, num_microbatches
        self.policy, self.dynamic = policy, dynamic
        self.n_buffers = n_buffers
        # Per-phase thread-join budget: constructor arg, else the
        # MEGATRON_DPP_JOIN_TIMEOUT_S env (big models on slow hosts
        # legitimately exceed the default), else 300 s.
        if join_timeout_s is None:
            join_timeout_s = float(os.environ.get(
                "MEGATRON_DPP_JOIN_TIMEOUT_S", "300"))
        self.join_timeout_s = join_timeout_s
        # Per-run state (populated by run()).
        self.transfer_order: List[List[Tuple[int, int]]] = []
        self.sender_stall_s: List[float] = []
        self.pool_stall_s: List[float] = []

    # -- topology -----------------------------------------------------

    def _next_hop(self, stage: int, chunk: int
                  ) -> Optional[Tuple[int, int]]:
        """(stage, chunk) an activation flows to next, or None if it is a
        pipeline output."""
        if stage < self.pp - 1:
            return stage + 1, chunk
        if chunk < self.vpp - 1:
            return 0, chunk + 1
        return None

    def _prev_hop(self, stage: int, chunk: int
                  ) -> Optional[Tuple[int, int]]:
        """Reverse topology for the backward pass: where the gradient of
        (stage, chunk)'s INPUT flows — the producer of that input — or
        None for (0, 0), whose dh is a grad w.r.t. the pipeline seed
        (reference backward_send direction,
        shm_tensor_new_rdma.cpp:1550-1646)."""
        if stage > 0:
            return stage - 1, chunk
        if chunk > 0:
            return self.pp - 1, chunk - 1
        return None

    # -- execution ----------------------------------------------------

    def _pipeline_phase(self, seeds: Dict[Tuple[int, int], Any],
                        seed_stage: int,
                        exec_fn: Callable[[int, int, Any, int], Any],
                        next_hop: Callable[[int, int],
                                           Optional[Tuple[int, int]]],
                        keyfn: Callable[[Tuple[int, int]], Tuple],
                        plan: List[Tuple[int, int]]) -> Dict[int, Any]:
        """One scheduled pipeline sweep (forward OR backward — the
        reference runs the same sender machinery in both directions).

        seeds {(chunk, mb): value} enter ``seed_stage``'s inbox;
        ``exec_fn(stage, chunk, value, mb)`` computes; finished values
        ship along ``next_hop`` — readiness-first under ``keyfn`` when
        dynamic, strict ``plan`` order otherwise — through a bounded
        TransferPool per link. Items whose hop is None are collected
        into the returned {mb: value}. Per-phase metrics land on
        ``self`` (transfer_order, ship_time_s, sender_stall_s,
        compute_wait_s, pool_stall_s, wall_s)."""
        pp, vpp, M = self.pp, self.vpp, self.M
        inboxes = [_Mailbox() for _ in range(pp)]       # compute inputs
        finished = [_Mailbox() for _ in range(pp)]      # awaiting send
        pools = [TransferPool(self.n_buffers) for _ in range(pp)]
        outputs: Dict[int, Any] = {}
        out_lock = threading.Lock()
        errors: List[BaseException] = []
        sender_stall = [0.0] * pp
        compute_wait = [0.0] * pp
        order_log: List[List[Tuple[int, int]]] = [[] for _ in range(pp)]
        # Per-(chunk, mb) ship timestamps relative to run start: the
        # direct observable for head-of-line blocking (a static sender
        # ships ready work late; see tests/test_dpp_runtime.py).
        ship_log: List[Dict[Tuple[int, int], float]] = [
            {} for _ in range(pp)]
        # Absolute (perf_counter) compute/transfer windows per
        # (chunk, mb) — the raw material for MegaScan trace events
        # (trace_events(); the reference's tracer sees its shm/RDMA
        # sends the same way).
        compute_spans: List[Dict[Tuple[int, int], Tuple[float, float]]] = [
            {} for _ in range(pp)]
        send_spans: List[Dict[Tuple[int, int], Tuple[float, float]]] = [
            {} for _ in range(pp)]
        t_run0 = time.perf_counter()

        for (c, m), h in seeds.items():
            inboxes[seed_stage].put(
                (c, m), jax.device_put(h, self.devices[seed_stage]))

        def compute_loop(stage: int):
            try:
                n_items = vpp * M
                for _ in range(n_items):
                    # Compute follows readiness in priority order too (the
                    # schedule order when nothing is late).
                    t0 = time.perf_counter()
                    (c, m), h = inboxes[stage].pop_best(keyfn)
                    t1 = time.perf_counter()
                    compute_wait[stage] += t1 - t0
                    h = exec_fn(stage, c, h, m)
                    jax.block_until_ready(h)
                    compute_spans[stage][(c, m)] = (
                        t1, time.perf_counter() - t1)
                    finished[stage].put((c, m), h)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        def sender_loop(stage: int):
            try:
                for i in range(len(plan)):
                    t0 = time.perf_counter()
                    if self.dynamic:
                        (c, m), h = finished[stage].pop_best(keyfn)
                    else:
                        c, m = plan[i]           # strict static order:
                        h = finished[stage].pop((c, m))  # block on it
                    sender_stall[stage] += time.perf_counter() - t0
                    order_log[stage].append((c, m))
                    ship_log[stage][(c, m)] = time.perf_counter() - t_run0
                    hop = next_hop(stage, c)
                    if hop is None:
                        with out_lock:
                            outputs[m] = h
                        continue
                    nxt_stage, nxt_chunk = hop
                    pools[stage].acquire()
                    t_send = time.perf_counter()
                    try:
                        h = jax.device_put(h, self.devices[nxt_stage])
                        jax.block_until_ready(h)
                    finally:
                        send_spans[stage][(c, m)] = (
                            t_send, time.perf_counter() - t_send)
                        pools[stage].release()
                    inboxes[nxt_stage].put((nxt_chunk, m), h)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = []
        for s in range(pp):
            threads.append(threading.Thread(target=compute_loop, args=(s,),
                                            daemon=True,
                                            name=f"dpp-compute-{s}"))
            threads.append(threading.Thread(target=sender_loop, args=(s,),
                                            daemon=True,
                                            name=f"dpp-sender-{s}"))
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        deadline = time.perf_counter() + self.join_timeout_s
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.perf_counter()))
        timed_out = [t.name for t in threads if t.is_alive()]
        self.wall_s = time.perf_counter() - t_start
        if errors:
            raise errors[0]
        if timed_out:
            # Distinct from "output genuinely missing" below: the phase is
            # still RUNNING (deadlock or slow host), not silently done-
            # but-short. Raise with the knob that widens the budget.
            raise RuntimeError(
                f"dpp pipeline phase exceeded join_timeout_s="
                f"{self.join_timeout_s:.0f}s with {len(timed_out)} "
                f"thread(s) still running ({', '.join(timed_out)}); "
                f"produced {len(outputs)}/{M} outputs so far — raise "
                "join_timeout_s (or MEGATRON_DPP_JOIN_TIMEOUT_S) if the "
                "host is just slow")
        if len(outputs) != M:
            raise RuntimeError(
                f"pipeline produced {len(outputs)}/{M} outputs although "
                "every phase thread exited cleanly — a schedule/topology "
                "bug dropped microbatches (NOT a timeout)")
        self.transfer_order = order_log
        self.ship_time_s = ship_log
        self.sender_stall_s = sender_stall
        self.compute_wait_s = compute_wait
        self.pool_stall_s = [p.stall_s for p in pools]
        self.compute_spans = compute_spans
        self.send_spans = send_spans
        return outputs

    def run(self, microbatch_inputs: Sequence[Any]) -> List[Any]:
        """Execute the forward pipeline over all microbatches. Returns
        outputs indexed by microbatch."""
        if len(microbatch_inputs) != self.M:
            raise ValueError("need one input per microbatch")
        pp, vpp, M = self.pp, self.vpp, self.M

        def keyfn(cm):
            return send_priority(cm[0], cm[1], pp, vpp, self.policy)

        seeds = {(0, m): h for m, h in enumerate(microbatch_inputs)}
        outputs = self._pipeline_phase(
            seeds, 0,
            lambda s, c, h, m: self.chunk_fn(s, c, h, m),
            self._next_hop, keyfn, static_order(pp, vpp, M, self.policy))
        return [outputs[m] for m in range(M)]

    def run_train(self, microbatch_inputs: Sequence[Any],
                  chunk_vjp_fn: Callable[[int, int, Any, int],
                                         Tuple[Any, Callable]],
                  seed_grads_fn: Callable[[List[Any]],
                                          Tuple[Sequence[Any], Any]],
                  ) -> Tuple[List[Any], Dict[Tuple[int, int], Any],
                             List[Any], Any]:
        """Full fwd+bwd through the dynamic scheduler (the reference's
        forward_send AND backward_send loops,
        shm_tensor_new_rdma.cpp:1478-1646 — not argued by symmetry: the
        backward pass executes through the same `_pipeline_phase`
        machinery in reverse topology with mirrored priority).

        chunk_vjp_fn(stage, chunk, h, mb) -> (h_out, vjp) where
        vjp(g_out) -> (dparams, dh). seed_grads_fn(outputs) ->
        (per-mb output grads, aux) runs the loss head after the forward
        sweep. Returns (outputs, param_grads {(stage, chunk): pytree
        summed over mbs}, input_grads per mb, aux).

        Metrics: after return, fwd_metrics/bwd_metrics hold each phase's
        (transfer_order, ship_time_s, sender_stall_s, compute_wait_s,
        pool_stall_s, wall_s).
        """
        if len(microbatch_inputs) != self.M:
            raise ValueError("need one input per microbatch")
        pp, vpp, M = self.pp, self.vpp, self.M
        residuals: Dict[Tuple[int, int, int], Callable] = {}

        def fwd_key(cm):
            return send_priority(cm[0], cm[1], pp, vpp, self.policy)

        def fwd_exec(stage, c, h, m):
            out, vjp = chunk_vjp_fn(stage, c, h, m)
            residuals[(stage, c, m)] = vjp
            return out

        seeds = {(0, m): h for m, h in enumerate(microbatch_inputs)}
        fwd_out = self._pipeline_phase(
            seeds, 0, fwd_exec, self._next_hop, fwd_key,
            static_order(pp, vpp, M, self.policy))
        self.fwd_metrics = self._phase_metrics()
        outputs = [fwd_out[m] for m in range(M)]

        out_grads, aux = seed_grads_fn(outputs)
        if len(out_grads) != M:
            raise ValueError("seed_grads_fn must return one grad per "
                             "microbatch")

        # Mirrored priority: the latest-forward item goes backward first
        # (the reference's backward traversal mirrors forward_send).
        def bwd_key(cm):
            return tuple(-x for x in fwd_key(cm))

        param_grads: Dict[Tuple[int, int], Any] = {}

        def bwd_exec(stage, c, g, m):
            dparams, dh = residuals.pop((stage, c, m))(g)
            acc = param_grads.get((stage, c))
            param_grads[(stage, c)] = (
                dparams if acc is None else jax.tree.map(
                    lambda a, b: a + b, acc, dparams))
            return dh

        bwd_seeds = {(vpp - 1, m): g for m, g in enumerate(out_grads)}
        bwd_out = self._pipeline_phase(
            bwd_seeds, pp - 1, bwd_exec, self._prev_hop, bwd_key,
            sorted([(c, m) for c in range(vpp) for m in range(M)],
                   key=bwd_key))
        self.bwd_metrics = self._phase_metrics()
        input_grads = [bwd_out[m] for m in range(M)]
        return outputs, param_grads, input_grads, aux

    def _phase_metrics(self) -> Dict[str, Any]:
        return {
            "transfer_order": self.transfer_order,
            "ship_time_s": self.ship_time_s,
            "sender_stall_s": self.sender_stall_s,
            "compute_wait_s": self.compute_wait_s,
            "pool_stall_s": self.pool_stall_s,
            "wall_s": self.wall_s,
            "compute_spans": self.compute_spans,
            "send_spans": self.send_spans,
        }

    def trace_events(self, t0: float,
                     pid_base: int = 5000) -> List[Dict[str, Any]]:
        """MegaScan records for the last run_train: per-(chunk, mb)
        compute and transfer spans on per-stage timelines (pid
        pid_base+stage — default 5000, disjoint from process pids and
        the profiler-device 1000-range; dp replicas pass distinct
        bases), ts/dur in microseconds relative to ``t0`` (a
        perf_counter taken at step entry). The reference's tracer shows
        its shm/RDMA transport activity the same way (its SendOp/RecvOp
        rows); feed through Tracer.add_collective_records."""
        events: List[Dict[str, Any]] = []
        for phase, metrics in (
                ("forward", getattr(self, "fwd_metrics", None)),
                ("backward", getattr(self, "bwd_metrics", None))):
            if not metrics:
                continue
            for kind, tid, per_stage in (
                    ("dpp-compute", 0, metrics["compute_spans"]),
                    ("dpp-send", 1, metrics["send_spans"])):
                for stage, spans in enumerate(per_stage):
                    for (c, m), (t_abs, dur) in spans.items():
                        events.append({
                            "name": kind, "ph": "X",
                            "pid": pid_base + stage, "tid": tid,
                            "ts": (t_abs - t0) * 1e6,
                            "dur": dur * 1e6,
                            "args": {"stage": stage, "chunk": c,
                                     "mb": m, "dir": phase},
                        })
        return events
