"""megatronapp_tpu package init.

Pin ``jax_threefry_partitionable=True`` (the default on newer jax, but
False on the jax 0.4.x this image ships): with it False, ``jax.random``
values under jit depend on the MESH the init runs under, so the same seed
produces different params on different tp/cp/pp layouts — breaking every
cross-layout loss-parity contract (cp=1 vs cp=2 training parity, golden
loss curves, A/B benchmarks that share an init). Partitionable threefry is
sharding-invariant by construction.
"""

import jax as _jax

try:
    _jax.config.update("jax_threefry_partitionable", True)
except Exception:  # pragma: no cover — flag retired on newer jax
    pass
