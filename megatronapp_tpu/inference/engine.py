"""Static inference engine: KV-cached autoregressive generation.

Parity with /root/reference/megatron/core/inference/engines/static_engine.py
(StaticInferenceEngine), text_generation_controllers/text_generation_
controller.py (prefill + decode loop, sampling) and
megatron/inference/text_generation/{generation.py,sampling}: greedy,
temperature, top-k, top-p sampling; static preallocated KV cache
(contexts/static_context.py analogue).

TPU-first: prefill is one jit over the prompt; decode is one jitted step
(donated cache) driven by lax.while-free host loop — token-by-token outputs
stream to a callback (the MegaScope per-token streaming contract).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.models.gpt import (
    gpt_embed, gpt_head, gpt_rope_tables,
)
from megatronapp_tpu.transformer.block import layer_forward
from megatronapp_tpu.scope.hooks import scope_capture


@dataclasses.dataclass
class SamplingParams:
    """Reference common_inference_params/SamplingParams."""
    temperature: float = 1.0
    top_k: int = 0          # 0 = disabled
    top_p: float = 0.0      # 0 = disabled
    greedy: bool = False
    seed: int = 0


def _decode_loop(cfg, prompt_tokens, raw_logits_last, step_fn,
                 max_new_tokens, sampling, eod_id, token_callback):
    """Shared autoregressive sampling loop (one copy for the static,
    mamba, and convenience paths): sampling, padded-vocab masking, eod
    early stop, MegaScope per-token callback. step_fn(next_tok [B]) →
    raw logits [B, V] for the next position."""
    sampling = sampling or SamplingParams()
    b = prompt_tokens.shape[0]
    rng = jax.random.PRNGKey(sampling.seed)
    logits_last = mask_padded_vocab(raw_logits_last, cfg)
    out = [prompt_tokens]
    finished = np.zeros((b,), bool)
    for step in range(max_new_tokens):
        rng, krng = jax.random.split(rng)
        next_tok = sample_logits(logits_last, krng, sampling)
        next_tok = next_tok.astype(jnp.int32)
        tok_host = np.asarray(jax.device_get(next_tok))
        if token_callback is not None:
            token_callback(step, tok_host,
                           np.asarray(jax.device_get(logits_last)))
        if eod_id is not None:
            finished |= tok_host == eod_id
        out.append(next_tok[:, None])
        if eod_id is not None and finished.all():
            break
        if step == max_new_tokens - 1:
            break
        logits_last = mask_padded_vocab(step_fn(next_tok), cfg)
    return np.asarray(jax.device_get(jnp.concatenate(out, axis=1)))


def _generate_text(engine, prompts, max_new_tokens, sampling,
                   token_callback):
    """Shared string-level API (api.py generate_and_post_process parity).

    Prompts of different lengths run as separate batches (no padding
    leaks into causal attention / recurrent state)."""
    assert engine.tokenizer is not None, "tokenizer required"
    eod = getattr(engine.tokenizer, "eod", None)
    texts = []
    for prompt in prompts:
        ids = np.asarray([engine.tokenizer.tokenize(prompt)], np.int32)
        out = engine.generate(ids, max_new_tokens, sampling, eod_id=eod,
                              token_callback=token_callback)
        new_ids = out[0, ids.shape[1]:].tolist()
        if eod is not None and eod in new_ids:
            new_ids = new_ids[: new_ids.index(eod)]
        texts.append(engine.tokenizer.detokenize(new_ids))
    return texts


def mask_padded_vocab(logits: jnp.ndarray, cfg: TransformerConfig
                      ) -> jnp.ndarray:
    """Mask logits for vocab rows beyond the tokenizer's true vocab to -inf.

    Converted checkpoints pad the embedding to a TP-friendly vocab size with
    zero rows; with tied embeddings those ids get logit exactly 0 — often
    above the mean of real logits — and would otherwise be sampleable
    (advisor finding r1)."""
    true_v = cfg.true_vocab_size
    if true_v is None or true_v >= logits.shape[-1]:
        return logits
    ids = jnp.arange(logits.shape[-1])
    return jnp.where(ids < true_v, logits, -1e30)


def sample_logits(logits: jnp.ndarray, rng, params: SamplingParams):
    """logits [B,V] → token ids [B] (generation.py sampling parity)."""
    if params.greedy:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(params.temperature, 1e-6)
    if params.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -params.top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if params.top_p > 0.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative prob >= top_p.
        cutoff_idx = jnp.sum(cum < params.top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int):
    """Per-layer decode cache (static_context.py analogue).

    Standard attention: K and V [L, B, S_max, Hkv, D]. MLA: the COMPRESSED
    cache — latent [L, B, S_max, kv_lora_rank] + shared roped key
    [L, B, S_max, qk_pos_emb_head_dim] (reference MLA's storage win:
    klat+dpe floats per token instead of 2*Hkv*D)."""
    if cfg.multi_latent_attention:
        return (jnp.zeros((cfg.num_layers, batch, max_len,
                           cfg.kv_lora_rank), cfg.compute_dtype),
                jnp.zeros((cfg.num_layers, batch, max_len,
                           cfg.qk_pos_emb_head_dim), cfg.compute_dtype))
    shape = (cfg.num_layers, batch, max_len, cfg.num_query_groups,
             cfg.head_dim)
    return (jnp.zeros(shape, cfg.compute_dtype),
            jnp.zeros(shape, cfg.compute_dtype))


def _forward_with_cache(p, tokens, cache, cache_index,
                        cfg: TransformerConfig):
    """tokens [B,S_step] starting at position cache_index →
    (logits [B,S_step,V], cache). Layer loop unrolled (stacked params are
    indexed per layer; caches updated in place via dynamic_update_slice)."""
    b, s = tokens.shape
    h = gpt_embed(p, tokens, cfg, position_offset=cache_index)
    max_len = cache[0].shape[2]
    inv_cos, inv_sin = gpt_rope_tables(cfg, max_len)
    # Slice rope tables for the current positions.
    if inv_cos is not None:
        cos = jax.lax.dynamic_slice_in_dim(inv_cos, cache_index, s)
        sin = jax.lax.dynamic_slice_in_dim(inv_sin, cache_index, s)
    else:
        cos = sin = None

    ck, cv = cache

    def body(carry, inputs):
        hh = carry
        layer_p, k_l, v_l, lid = inputs
        (hh, new_cache), _ = layer_forward(
            layer_p, hh, cfg, cos, sin, None, layer_id=lid,
            kv_cache=(k_l, v_l), cache_index=cache_index)
        return hh, new_cache

    h, new_caches = jax.lax.scan(
        body, h,
        (p["block"], ck, cv, jnp.arange(cfg.num_layers)))
    logits = gpt_head(p, h, cfg)
    return logits, new_caches


class StaticInferenceEngine:
    """generate() over a fixed-shape batch with a preallocated cache."""

    def __init__(self, params, cfg: TransformerConfig,
                 tokenizer=None, max_seq_len: Optional[int] = None):
        self.params = params
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len or cfg.max_position_embeddings

        self._build_jits()

    def _build_jits(self):
        self._prefill = jax.jit(
            functools.partial(_forward_with_cache, cfg=self.cfg),
            static_argnames=(), donate_argnums=(2,))
        self._decode = jax.jit(
            functools.partial(_forward_with_cache, cfg=self.cfg),
            donate_argnums=(2,))

    def reset_compilation(self):
        """Drop the jitted prefill/decode so the next call re-traces —
        required after toggling MegaScope capture hooks, whose enablement
        is baked in at trace time (scope/hooks.py NOTE)."""
        self._build_jits()

    def generate(self, prompt_tokens: np.ndarray, max_new_tokens: int,
                 sampling: Optional[SamplingParams] = None,
                 eod_id: Optional[int] = None,
                 token_callback: Optional[Callable] = None) -> np.ndarray:
        """prompt_tokens [B, S_prompt] int32 → [B, S_prompt+max_new]."""
        prompt_tokens = jnp.asarray(prompt_tokens, jnp.int32)
        b, s_prompt = prompt_tokens.shape
        total = s_prompt + max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(f"prompt+new ({total}) exceeds max_seq_len "
                             f"({self.max_seq_len})")
        cache = init_kv_cache(self.cfg, b, self.max_seq_len)
        logits, cache = self._prefill(self.params, prompt_tokens, cache, 0)
        state = {"cache": cache, "pos": s_prompt}

        def step_fn(next_tok):
            logits, state["cache"] = self._decode(
                self.params, next_tok[:, None], state["cache"],
                state["pos"])
            state["pos"] += 1
            return logits[:, -1]

        return _decode_loop(self.cfg, prompt_tokens, logits[:, -1],
                            step_fn, max_new_tokens, sampling, eod_id,
                            token_callback)

    def generate_text(self, prompts, max_new_tokens: int,
                      sampling: Optional[SamplingParams] = None,
                      token_callback: Optional[Callable] = None):
        return _generate_text(self, prompts, max_new_tokens, sampling,
                              token_callback)


class MambaInferenceEngine:
    """Server-compatible generation engine for Mamba models — pure-M
    stacks decode with O(1) recurrent state; hybrid (M/attention) stacks
    additionally carry a KV cache sized max_seq_len for the '*' layers
    (reference: the mamba text-generation server under tools/).

    Exposes the same generate/generate_text surface the
    TextGenerationServer drives on StaticInferenceEngine."""

    def __init__(self, params, cfg, mcfg, tokenizer=None,
                 max_seq_len: Optional[int] = None):
        from megatronapp_tpu.models.mamba import (
            mamba_decode_step, mamba_prefill,
        )
        self.params = params
        self.cfg = cfg
        self.mcfg = mcfg
        self.tokenizer = tokenizer
        # Mamba has no positional embeddings — an operator may serve
        # beyond the training context via --max-seq-len. Hybrid stacks
        # contain rope attention layers, so there the trained position
        # range is a hard bound.
        self.max_seq_len = max_seq_len or cfg.max_position_embeddings
        pattern = mcfg.hybrid_pattern or ""
        if set(pattern) - {"M"} and (
                self.max_seq_len > cfg.max_position_embeddings):
            raise ValueError(
                f"hybrid mamba stack: max_seq_len ({self.max_seq_len}) "
                "exceeds the attention layers' trained position range "
                f"({cfg.max_position_embeddings})")
        # jit once per engine — per-request lambdas would re-trace and
        # recompile every call.
        self._build_jits()

    def _build_jits(self):
        from megatronapp_tpu.models.mamba import (
            mamba_decode_step, mamba_prefill,
        )
        cfg, mcfg = self.cfg, self.mcfg
        self._prefill = jax.jit(
            lambda p, t: mamba_prefill(p, t, cfg, mcfg,
                                       max_len=self.max_seq_len))
        self._step = jax.jit(
            lambda p, s, t, i: mamba_decode_step(p, s, t, cfg, mcfg,
                                                 cache_index=i),
            donate_argnums=(1,))

    def reset_compilation(self):
        """Re-trace on next call (after MegaScope hook toggles — see
        StaticInferenceEngine.reset_compilation)."""
        self._build_jits()

    def generate(self, prompt_tokens: np.ndarray, max_new_tokens: int,
                 sampling: Optional[SamplingParams] = None,
                 eod_id: Optional[int] = None,
                 token_callback: Optional[Callable] = None) -> np.ndarray:
        """Same contract as StaticInferenceEngine.generate: full sampling
        (greedy/temperature/top-k/top-p), padded-vocab masking, eod early
        stop, max_seq_len bound."""
        prompt_tokens = jnp.asarray(prompt_tokens, jnp.int32)
        s_prompt = prompt_tokens.shape[1]
        if s_prompt + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt+new ({s_prompt + max_new_tokens}) exceeds "
                f"max_seq_len ({self.max_seq_len})")
        logits, states = self._prefill(self.params, prompt_tokens)
        box = {"states": states, "pos": s_prompt}

        def step_fn(next_tok):
            logits_last, box["states"] = self._step(
                self.params, box["states"], next_tok,
                jnp.int32(box["pos"]))
            box["pos"] += 1
            return logits_last

        return _decode_loop(self.cfg, prompt_tokens, logits[:, -1],
                            step_fn, max_new_tokens, sampling, eod_id,
                            token_callback)

    def generate_text(self, prompts, max_new_tokens: int,
                      sampling: Optional[SamplingParams] = None,
                      token_callback: Optional[Callable] = None):
        return _generate_text(self, prompts, max_new_tokens, sampling,
                              token_callback)


def beam_search(engine: StaticInferenceEngine, prompt_tokens: np.ndarray,
                max_new_tokens: int, beam_width: int = 4,
                length_penalty: float = 1.0,
                eod_id: Optional[int] = None) -> np.ndarray:
    """Beam search decode (reference generation.py beam_search parity) for a
    single prompt [1, S]."""
    cfg = engine.cfg
    prompt = jnp.asarray(prompt_tokens, jnp.int32)
    assert prompt.shape[0] == 1, "beam search takes a single prompt"
    s_prompt = prompt.shape[1]

    # Expand prompt to beam_width rows; run one shared prefill.
    beams = jnp.tile(prompt, (beam_width, 1))
    cache = init_kv_cache(cfg, beam_width, engine.max_seq_len)
    logits, cache = engine._prefill(engine.params, beams, cache, 0)
    logp = jax.nn.log_softmax(
        mask_padded_vocab(logits[:, -1], cfg).astype(jnp.float32), axis=-1)

    # First step: take top beam_width continuations of the single prompt.
    top_logp, top_idx = jax.lax.top_k(logp[0], beam_width)
    scores = np.asarray(top_logp, np.float64)
    beams = np.concatenate([np.asarray(beams),
                            np.asarray(top_idx)[:, None]], axis=1)
    finished = np.zeros((beam_width,), bool)
    pos = s_prompt

    for _ in range(max_new_tokens - 1):
        if eod_id is not None and finished.all():
            break
        tok = jnp.asarray(beams[:, -1:], jnp.int32)
        logits, cache = engine._decode(engine.params, tok, cache, pos)
        pos += 1
        logp = np.asarray(jax.nn.log_softmax(
            mask_padded_vocab(logits[:, -1], cfg).astype(jnp.float32),
            axis=-1))
        vocab = logp.shape[-1]
        cand = scores[:, None] + np.where(finished[:, None], -1e9, logp)
        if eod_id is not None:
            # Finished beams keep their score on a dummy continuation.
            cand[finished, 0] = scores[finished]
        flat = cand.ravel()
        best = np.argsort(flat)[::-1][:beam_width]
        parents, toks = best // vocab, best % vocab
        scores = flat[best]
        beams = np.concatenate([beams[parents], toks[:, None]], axis=1)
        finished = finished[parents] | (
            (toks == eod_id) if eod_id is not None else False)
        # Reorder the cache rows to follow the surviving beams.
        cache = jax.tree.map(lambda c: c[:, parents], cache)

    lengths = (beams.shape[1] - s_prompt) * np.ones(beam_width)
    final = scores / (lengths ** length_penalty)
    return beams[int(np.argmax(final))][None]
