"""Paged KV-cache block pool: allocator, prefix cache, preemption support.

vLLM-style block management for the continuous-batching engine
(inference/dynamic_engine.py `paged=True`): KV storage is a shared pool
shaped [L, num_blocks, block_size, Hkv, D] (MLA: the compressed latent
[L, num_blocks, block_size, kv_lora_rank] + shared roped key
[..., qk_pos_emb_head_dim] pair), and each slot owns an ordered page
table row [max_blocks_per_seq] int32. Capacity is admitted per block, so
a 6-token request costs one block, not an S_max row.

Prefix caching: full blocks are keyed by a rolling hash of the token
prefix they complete (hash chains over whole prefixes, so a hit
guarantees exact token equality up to the block boundary) and
refcounted. Blocks whose refcount drops to zero stay resident on an LRU
list and remain hittable until the allocator evicts them for fresh
demand. A request whose prompt fully hits still needs the last
position's logits, so its final block is **copy-on-write**: the shared
block's rows are copied into a private block and only the diverging row
is recomputed — shared blocks are never written.

All bookkeeping is host-side (numpy/python); the page DATA lives in jnp
arrays on `self.pages` and is only touched by jit-able scatter/gather
helpers (ops/pallas/paged_attention.py) plus the small copy-on-write
block copy here.

Quantized pools (ISSUE 10, ``kv_cache_dtype="int8"``): pages store int8
with a per-(row, kv-head) fp32 scale pool [L, NB, bs, Hkv] on
`self.scales` — rows quantize independently on insert
(quantize_kv_rows), so every page-table operation here (CoW, refcounts,
prefix hashing, transfer, rewind) is UNCHANGED: block identity and
sharing semantics never depend on the storage dtype. Capacity
accounting (`bytes_total`, `bytes_per_block`) reads the addressable
arrays, so it is dtype-aware by construction. MLA pools (ISSUE 17)
quantize the same way: the latent row [bs, klat] and roped-key row
[bs, dpe] have no kv-head axis, so their scale pools are per-row
SCALARS [L, NB, bs] — `quantize_kv_rows` over the trailing dim yields
exactly that layout, and every pool-shaped operation here is generic
over the per-pool trailing dims.

fp8 pools (ISSUE 13, ``kv_cache_dtype="fp8"``): same scale-pool layout
as int8 but the pages store e4m3 — quantize_kv_rows maps each row's
absmax to the e4m3 range bound (448) and saturate-casts, dropping the
integer rounding step; dequant stays the same cast-and-scale in-kernel
path. The storage dtypes, their CLI choices, and every validation
message derive from the one KV_CACHE_DTYPES registry below."""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict, deque
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.utils import chaos
from megatronapp_tpu.utils import metrics as telemetry


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def prefix_block_keys(tokens, block_size: int, limit: int) -> List[bytes]:
    """Rolling hash per FULL block of tokens[:limit]: key i commits to
    the whole prefix through block i, so a table hit is an exact prefix
    match. The ONE hashing implementation shared by the pool's prefix
    cache and the fleet router's affinity map (inference/fleet.py) — a
    hash mismatch between them would silently zero the affinity signal,
    so neither side rolls its own."""
    tokens = np.asarray(tokens, np.int32)
    keys: List[bytes] = []
    digest = b""
    for i in range(limit // block_size):
        digest = hashlib.sha1(
            digest + np.ascontiguousarray(
                tokens[i * block_size:(i + 1) * block_size],
                dtype=np.int32).tobytes()
        ).digest()
        keys.append(digest)
    return keys


@dataclasses.dataclass(frozen=True)
class KvDtypeSpec:
    """One KV-cache storage dtype (the SHARED registry entry): the pool
    check, the CLI choices/help, and the server-side validation all
    derive from KV_CACHE_DTYPES so adding a dtype cannot leave them
    disagreeing (ISSUE 13 satellite). Quantized entries take their
    page dtype and range bound from the KERNEL registry
    (ops/pallas/kernel_gen.QUANT_DTYPES — the same map quantize_kv_rows
    and the PagedSpec quant-dtype axis consume), so a new storage
    format lands there once and flows to the CLI/pool/kernels
    together."""
    name: str
    page_dtype: object          # jnp dtype of the page pools (None = compute)
    quantized: bool             # per-(row, kv-head) fp32 scale pool present
    qmax: Optional[float]       # symmetric quantization range bound
    help: str                   # one-line CLI help fragment


def _quantized_spec(name: str, help_text: str) -> KvDtypeSpec:
    from megatronapp_tpu.ops.pallas.kernel_gen import QUANT_DTYPES
    dtype, _tile, qmax = QUANT_DTYPES[name]
    return KvDtypeSpec(name, dtype, True, qmax, help_text)


KV_CACHE_DTYPES = {
    "bf16": KvDtypeSpec("bf16", None, False, None,
                        "compute-dtype pages (the baseline)"),
    "int8": _quantized_spec(
        "int8",
        "int8 pages + per-(row, kv-head) fp32 scales, rounded "
        "symmetric [-127, 127], dequantized in-kernel per DMA'd block"),
    "fp8": _quantized_spec(
        "fp8",
        "fp8 (e4m3) pages + per-(row, kv-head) fp32 scales — same "
        "bytes as int8 but saturating float rounding (no integer "
        "rounding step), dequantized in-kernel per DMA'd block"),
}


def kv_cache_dtype_help() -> str:
    """CLI help text for --kv-cache-dtype, derived from the registry."""
    return "; ".join(f"{n}: {s.help}" for n, s in KV_CACHE_DTYPES.items())


def validate_kv_cache_dtype(name: str, *, paged: bool = True,
                            mla: bool = False) -> KvDtypeSpec:
    """Single source of truth for kv_cache_dtype validation: the pool
    constructor, the engine, and the parse-time CLI check all raise
    THESE messages (ValueError; entry points wrap in SystemExit)."""
    spec = KV_CACHE_DTYPES.get(name)
    if spec is None:
        raise ValueError(
            f"kv_cache_dtype must be one of "
            f"{sorted(KV_CACHE_DTYPES)}, got {name!r}")
    if spec.quantized and not paged:
        raise ValueError(
            f"kv_cache_dtype={spec.name} requires the paged backend "
            "(the per-block quantization scales live alongside the "
            "block pool; the dense slot cache has no block structure) "
            "— pass paged=True / --paged-kv-cache")
    # mla is accepted (and kept in the signature) so call sites document
    # the layout they validate for; quantized MLA pools are supported
    # since ISSUE 17 (per-row scalar scales on the latent/pe pools).
    del mla
    return spec


@dataclasses.dataclass
class AdmitPlan:
    """Result of admitting a token sequence into a slot."""
    blocks: List[int]        # page-table row, sequence order
    cached_tokens: int       # leading tokens whose KV is already resident
    cow: bool                # last block was copy-on-write'd (full hit)


class PagedKVCache:
    """Block pool + page tables + refcounted prefix cache."""

    def __init__(self, cfg: TransformerConfig, max_batch: int,
                 max_seq_len: int, num_blocks: Optional[int] = None,
                 block_size: int = 16, enable_prefix_caching: bool = True,
                 extra_slots: int = 0, kv_cache_dtype: str = "bf16"):
        dtype_spec = validate_kv_cache_dtype(
            kv_cache_dtype, paged=True, mla=cfg.multi_latent_attention)
        self.cfg = cfg
        self.kv_cache_dtype = kv_cache_dtype
        self.dtype_spec = dtype_spec
        self.quantized = dtype_spec.quantized
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        self.block_size = block_size
        self.max_blocks_per_seq = cdiv(max_seq_len, block_size)
        # Default pool = dense capacity (max_batch full sequences); size
        # it down for the actual workload to realize the memory win.
        self.num_blocks = (num_blocks if num_blocks is not None
                           else max_batch * self.max_blocks_per_seq)
        self.enable_prefix_caching = enable_prefix_caching
        # extra_slots: staging page-table rows past the engine's decode
        # slots — the disaggregated prefill side (inference/disagg.py)
        # admits in-flight prefills there and hands finished ones to a
        # decode slot via transfer_slot (pure bookkeeping, no KV copy).
        self.num_slots = max_batch + extra_slots

        l = cfg.num_layers
        nb, bs = self.num_blocks, self.block_size
        # scales: per-(row, kv-head) fp32 quantization scales for int8
        # pools (None for bf16) — scattered/copied exactly like the data
        # pools (same leading [L, NB, bs] dims).
        self.scales: Optional[Tuple[jnp.ndarray, ...]] = None
        if cfg.multi_latent_attention:
            dt = (dtype_spec.page_dtype if self.quantized
                  else cfg.compute_dtype)
            self.pages: Tuple[jnp.ndarray, ...] = (
                jnp.zeros((l, nb, bs, cfg.kv_lora_rank), dt),
                jnp.zeros((l, nb, bs, cfg.qk_pos_emb_head_dim), dt))
            if self.quantized:
                # The latent/pe rows have no kv-head axis — the scales
                # are one SCALAR per (layer, block, row).
                self.scales = (jnp.ones((l, nb, bs), jnp.float32),
                               jnp.ones((l, nb, bs), jnp.float32))
        else:
            shape = (l, nb, bs, cfg.num_query_groups, cfg.head_dim)
            dt = (dtype_spec.page_dtype if self.quantized
                  else cfg.compute_dtype)
            self.pages = (jnp.zeros(shape, dt), jnp.zeros(shape, dt))
            if self.quantized:
                sshape = (l, nb, bs, cfg.num_query_groups)
                self.scales = (jnp.ones(sshape, jnp.float32),
                               jnp.ones(sshape, jnp.float32))

        self.page_table = np.zeros((self.num_slots, self.max_blocks_per_seq),
                                   np.int32)
        self._free: deque = deque(range(nb))
        self._refcount = np.zeros((nb,), np.int32)
        self._table: dict = {}            # prefix hash -> block id
        self._hash_of: dict = {}          # block id -> prefix hash
        self._lru: OrderedDict = OrderedDict()  # rc==0 hashed blocks
        self._slot_blocks: List[List[int]] = [
            [] for _ in range(self.num_slots)]
        self.stats = {"prefix_hit_tokens": 0, "prefill_tokens": 0,
                      "cow_copies": 0, "evictions": 0, "preemptions": 0,
                      "peak_blocks_in_use": 0, "handoff_transfers": 0,
                      "slot_exports": 0, "slot_imports": 0,
                      "prefix_block_exports": 0, "prefix_block_imports": 0}
        # Fleet-router hooks (inference/fleet.py): prefix_listener(keys)
        # fires with every batch of NEWLY registered prefix-block hashes
        # (the router's hash→replica affinity map is fed from these
        # events); flush_listener() fires when the prefix cache is
        # flushed (rolling reload — the router must drop this replica's
        # affinity entries, or it would keep steering sessions to it for
        # stale-weight "hits"). Both default to None (zero cost).
        self.prefix_listener = None
        self.flush_listener = None

    # ---- placement -------------------------------------------------------
    def place_pages(self, sharding, scales_sharding=None):
        """Commit the page pools to an explicit device placement (tp
        serving mesh: sharded on the Hkv dim — MLA: latent columns —
        so each device holds 1/tp of the pool; disaggregated serving:
        the decode sub-mesh). Quantized pools place their scale pools
        alongside (scales_sharding). `sharding` / `scales_sharding` may
        each be a single sharding applied to every pool, OR a sequence
        with one entry per pool (the MLA tp layout shards the latent
        pool but replicates the pe pool). Later jnp updates (CoW copy,
        the engine's scatter/append jits) preserve the committed
        sharding by propagation."""
        import jax

        def _per_pool(sh, n):
            if isinstance(sh, (list, tuple)):
                assert len(sh) == n, (len(sh), n)
                return tuple(sh)
            return (sh,) * n

        data_sh = _per_pool(sharding, len(self.pages))
        # manual-ok: host-side pool placement, no manual region
        self.pages = tuple(jax.device_put(p, s)
                           for p, s in zip(self.pages, data_sh))
        if self.scales is not None:
            sc_sh = _per_pool(scales_sharding if scales_sharding is not None
                              else sharding, len(self.scales))
            self.scales = tuple(
                # manual-ok: host-side pool placement, no manual region
                jax.device_put(s, sh)
                for s, sh in zip(self.scales, sc_sh))

    # ---- sizing ----------------------------------------------------------
    def _arrays(self):
        return self.pages + (self.scales or ())

    @property
    def bytes_total(self) -> int:
        """Resident pool bytes, dtype-aware: int8 data + fp32 scales for
        quantized pools, compute-dtype data otherwise — always read off
        the addressable arrays, never derived from the param dtype."""
        return sum(p.size * p.dtype.itemsize for p in self._arrays())

    @property
    def bytes_per_block(self) -> int:
        return self.bytes_total // self.num_blocks

    def blocks_in_use(self) -> int:
        """Blocks with live references (excludes free + evictable)."""
        return self.num_blocks - len(self._free) - len(self._lru)

    def available_blocks(self) -> int:
        return len(self._free) + len(self._lru)

    def free_blocks(self) -> int:
        return len(self._free)

    def evictable_blocks(self) -> int:
        return len(self._lru)

    def refcount(self, block: int) -> int:
        return int(self._refcount[block])

    def slot_blocks(self, slot: int) -> List[int]:
        return list(self._slot_blocks[slot])

    # ---- low-level block lifecycle --------------------------------------
    def _take_free(self) -> Optional[int]:
        if self._free:
            return self._free.popleft()
        if self._lru:
            # Chaos site fires BEFORE the eviction mutates anything, so
            # an injected fault leaves the allocator consistent and the
            # caller's rollback (admit/_rollback) owns the cleanup.
            chaos.fire("paged-evict")
            blk, _ = self._lru.popitem(last=False)   # least recently used
            key = self._hash_of.pop(blk, None)
            if key is not None and self._table.get(key) == blk:
                del self._table[key]
            self.stats["evictions"] += 1
            telemetry.inc("paged_evictions")
            return blk
        return None

    def _acquire_cached(self, blk: int):
        self._refcount[blk] += 1
        self._lru.pop(blk, None)

    def _release_block(self, blk: int):
        self._refcount[blk] -= 1
        assert self._refcount[blk] >= 0, f"block {blk} over-released"
        if self._refcount[blk] == 0:
            if blk in self._hash_of:
                self._lru[blk] = None    # evictable, still hittable
            else:
                self._free.append(blk)

    def _copy_block(self, src: int, dst: int):
        # Chaos site fires before the copy: pages/stats untouched, the
        # caller's rollback returns src's ref and dst to the pool.
        chaos.fire("paged-cow")
        self.pages = tuple(p.at[:, dst].set(p[:, src]) for p in self.pages)
        if self.scales is not None:
            # Rows quantize independently, so CoW copies scales verbatim
            # alongside the int8 rows — no re-quantization.
            self.scales = tuple(s.at[:, dst].set(s[:, src])
                                for s in self.scales)
        self.stats["cow_copies"] += 1
        telemetry.inc("paged_cow_copies")

    def _note_usage(self):
        self.stats["peak_blocks_in_use"] = max(
            self.stats["peak_blocks_in_use"], self.blocks_in_use())

    # ---- prefix hashing --------------------------------------------------
    def _block_keys(self, tokens: np.ndarray, limit: int) -> List[bytes]:
        """Rolling hash per FULL block of tokens[:limit] (delegates to
        the module-level prefix_block_keys — the implementation shared
        with the fleet router's affinity map)."""
        return prefix_block_keys(tokens, self.block_size, limit)

    # ---- engine-facing API ----------------------------------------------
    def admit(self, slot: int, tokens: np.ndarray) -> Optional[AdmitPlan]:
        """Install blocks covering `tokens` into `slot`'s page table,
        reusing cached prefix blocks. Returns None (state rolled back)
        when the pool cannot supply the fresh blocks."""
        assert not self._slot_blocks[slot], f"slot {slot} still holds blocks"
        p_len = len(tokens)
        need_total = cdiv(p_len, self.block_size)

        hits: List[int] = []
        if self.enable_prefix_caching:
            for key in self._block_keys(tokens, p_len):
                blk = self._table.get(key)
                if blk is None:
                    break
                hits.append(blk)
        cached = len(hits) * self.block_size
        cow = cached >= p_len        # full hit: recompute the last token
        if cow:
            cached = p_len - 1

        for blk in hits:
            self._acquire_cached(blk)
        fresh_needed = need_total - len(hits) + (1 if cow else 0)
        fresh: List[int] = []

        def _rollback():
            for b in fresh:
                self._refcount[b] = 0
                self._free.append(b)
            for b in hits:
                self._release_block(b)

        # Exception-safe allocation: _take_free (eviction) and
        # _copy_block (CoW) are fault-injection sites — a failure there
        # must return every acquired ref/block, not leak them (the
        # paged-evict / paged-cow drills audit() exactly this).
        try:
            for _ in range(fresh_needed):
                blk = self._take_free()
                if blk is None:
                    _rollback()
                    return None
                self._refcount[blk] = 1
                fresh.append(blk)
            if cow:
                src = hits[-1]
                dst = fresh[0]
                self._copy_block(src, dst)
        except Exception:
            _rollback()
            raise

        if cow:
            self._release_block(src)
            blocks = hits[:-1] + [dst] + fresh[1:]
        else:
            blocks = hits + fresh

        self._slot_blocks[slot] = blocks
        self.page_table[slot, :] = 0
        self.page_table[slot, :len(blocks)] = blocks
        self.stats["prefix_hit_tokens"] += cached
        self.stats["prefill_tokens"] += p_len - cached
        telemetry.inc("paged_prefix_hit_tokens", cached)
        telemetry.inc("paged_prefill_tokens", p_len - cached)
        self._note_usage()
        return AdmitPlan(blocks, cached, cow)

    def ensure_capacity(self, slot: int, position: int) -> bool:
        """Make sure `slot` owns the block covering `position` (decode
        appends grow one block at a time)."""
        idx = position // self.block_size
        owned = self._slot_blocks[slot]
        if idx < len(owned):
            return True
        assert idx == len(owned), (
            f"slot {slot} skipped a block: position {position} needs block "
            f"{idx}, owns {len(owned)}")
        blk = self._take_free()
        if blk is None:
            return False
        self._refcount[blk] = 1
        owned.append(blk)
        self.page_table[slot, idx] = blk
        self._note_usage()
        return True

    def extend_capacity(self, slot: int, position: int, span: int) -> int:
        """Best-effort growth for a multi-token (speculative) append:
        allocate blocks so `slot` covers positions
        [position, position + span), WITHOUT preempting anyone. Returns
        the span actually covered (>= 0); the caller shrinks its
        speculation to fit. Partially-granted blocks stay owned — a
        later rewind() or release() returns them."""
        granted = 0
        for p in range(position, position + span):
            if p >= self.max_seq_len:
                break
            if not self.ensure_capacity(slot, p):
                break
            granted += 1
        return granted

    def flush_prefix_cache(self):
        """Invalidate every cached prefix (rolling engine reload: blocks
        hold KV computed with the OLD weights — a post-swap request
        hitting them would decode new-weight logits over old-weight KV).
        Evictable blocks return to the free list; blocks still
        referenced by live slots merely lose their hash, so they free
        (not LRU-park) on release."""
        self._table.clear()
        self._hash_of.clear()
        for blk in self._lru:
            self._free.append(blk)
        self._lru.clear()
        if self.flush_listener is not None:
            # Structural invalidation (ISSUE 14 satellite): ANY flush —
            # however set_params was reached — drops the fleet router's
            # affinity entries for this replica, so the router cannot
            # keep steering sessions at stale-weight "hits".
            self.flush_listener()

    def transfer_slot(self, src: int, dst: int):
        """Move block ownership from slot `src` to slot `dst` (which
        must be empty): the prefill→decode KV handoff of the
        disaggregated engine. PURE bookkeeping — the page-table row and
        the block list move, refcounts and the page DATA are untouched,
        so adoption never copies KV (the no-dense-copy pin in
        tests/test_disagg.py)."""
        assert not self._slot_blocks[dst], (
            f"transfer_slot: destination slot {dst} still holds blocks")
        self._slot_blocks[dst] = self._slot_blocks[src]
        self._slot_blocks[src] = []
        self.page_table[dst, :] = self.page_table[src, :]
        self.page_table[src, :] = 0
        self.stats["handoff_transfers"] += 1

    def export_slot(self, slot: int, valid_len: int) -> dict:
        """READ-ONLY export of a slot's written KV rows for CROSS-POOL
        live session migration (inference/fleet.py — the PR-8/10 disagg
        handoff generalized; `transfer_slot` above stays the intra-pool
        fast path). Gathers the first `valid_len` rows of every pool
        tensor to host arrays IN THE STORED DTYPE: quantized pools ship
        their int8/fp8 rows + fp32 scales VERBATIM — no dequantize/
        re-quantize round trip, so an import on the destination is
        copy-exact and the migrated stream stays token-exact. Nothing
        here mutates the source pool: a migration that fails after the
        export (the "fleet-migrate" chaos site) leaves the source slot
        fully intact."""
        import jax
        from megatronapp_tpu.ops.pallas.paged_attention import (
            gather_prefix_pages,
        )
        assert valid_len > 0, "export_slot: nothing written yet"
        nblocks = cdiv(valid_len, self.block_size)
        owned = self._slot_blocks[slot]
        assert nblocks <= len(owned), (
            f"export_slot: slot {slot} owns {len(owned)} blocks but "
            f"{valid_len} rows need {nblocks}")
        table_row = jnp.asarray(self.page_table[slot])

        def grab(pools):
            return tuple(
                np.asarray(jax.device_get(
                    gather_prefix_pages(p, table_row, nblocks)
                ))[:, :valid_len] for p in pools)

        rows = grab(self.pages)
        scales = grab(self.scales) if self.scales is not None else None
        nbytes = sum(r.nbytes for r in rows)
        if scales is not None:
            nbytes += sum(s.nbytes for s in scales)
        self.stats["slot_exports"] += 1
        telemetry.inc("fleet_kv_exported_bytes", nbytes)
        return {"kv_cache_dtype": self.kv_cache_dtype, "rows": rows,
                "scales": scales, "valid_len": valid_len,
                "nbytes": nbytes}

    def import_slot(self, slot: int, payload: dict) -> bool:
        """Install an `export_slot` payload into empty slot `slot`:
        allocate fresh blocks covering valid_len rows and scatter the
        exported rows (+ scales) into them verbatim. ALL-OR-NOTHING:
        returns False with every allocated block returned to the pool
        when capacity is short, and rolls the allocation back on any
        scatter fault — `audit()` passes either way. The storage dtype
        must match (rows are stored bytes, never converted): fleet
        replicas share one --kv-cache-dtype by construction."""
        if payload["kv_cache_dtype"] != self.kv_cache_dtype:
            raise ValueError(
                f"cannot import {payload['kv_cache_dtype']!r} KV rows "
                f"into a {self.kv_cache_dtype!r} pool — migration ships "
                "the stored rows verbatim; every fleet replica must run "
                "the same --kv-cache-dtype")
        assert not self._slot_blocks[slot], (
            f"import_slot: destination slot {slot} still holds blocks")
        valid_len = payload["valid_len"]
        need = cdiv(valid_len, self.block_size)
        fresh: List[int] = []

        def _rollback():
            for b in fresh:
                self._refcount[b] = 0
                self._free.append(b)

        try:
            for _ in range(need):
                blk = self._take_free()
                if blk is None:
                    _rollback()
                    return False
                self._refcount[blk] = 1
                fresh.append(blk)
        except Exception:
            _rollback()
            raise
        self._slot_blocks[slot] = fresh
        self.page_table[slot, :] = 0
        self.page_table[slot, :need] = fresh
        from megatronapp_tpu.ops.pallas.paged_attention import (
            write_prompt_pages,
        )
        table_row = jnp.asarray(self.page_table[slot])
        try:
            self.pages = tuple(
                write_prompt_pages(p, jnp.asarray(r), table_row, 0,
                                   valid_len)
                for p, r in zip(self.pages, payload["rows"]))
            if self.scales is not None:
                self.scales = tuple(
                    write_prompt_pages(p, jnp.asarray(r), table_row, 0,
                                       valid_len)
                    for p, r in zip(self.scales, payload["scales"]))
        except Exception:
            # Partially-scattered rows are dead data in returned blocks
            # that the next writer overwrites — bookkeeping stays clean.
            self._slot_blocks[slot] = []
            self.page_table[slot, :] = 0
            _rollback()
            raise
        self.stats["slot_imports"] += 1
        telemetry.inc("fleet_kv_imported_bytes", payload["nbytes"])
        self._note_usage()
        return True

    def rewind(self, slot: int, valid_len: int):
        """Roll back a slot to `valid_len` written positions: release the
        tail blocks past ceil(valid_len / block_size) — the rejected-
        speculation path (and the cleanup for over-granted
        extend_capacity blocks). Only privately-owned tail blocks may be
        dropped; a refcounted/hashed block here would mean speculation
        wrote into a shared prefix block (never legal — CoW guarantees
        the writable tail is private), so that asserts rather than
        corrupting the prefix cache. Rewinding never splits a block:
        KV rows past valid_len inside the kept tail block are simply
        overwritten by the next append."""
        keep = cdiv(max(valid_len, 1), self.block_size)
        owned = self._slot_blocks[slot]
        while len(owned) > keep:
            blk = owned.pop()
            assert self._refcount[blk] == 1 and blk not in self._hash_of, (
                f"rewind would drop shared/hashed block {blk} "
                f"(rc={int(self._refcount[blk])}) — speculative tail "
                "blocks must be private")
            self.page_table[slot, len(owned)] = 0
            self._release_block(blk)

    def audit(self):
        """Consistency check (tests): every block is exactly one of
        free / LRU-evictable / slot-referenced, and each block's
        refcount equals the number of slot page-table references to it.
        Raises AssertionError on double-free, leak, or refcount skew."""
        nb = self.num_blocks
        refs = np.zeros((nb,), np.int64)
        for blocks in self._slot_blocks:
            for blk in blocks:
                refs[blk] += 1
        assert np.array_equal(refs, self._refcount), (
            f"refcount skew: table={self._refcount.tolist()} "
            f"actual={refs.tolist()}")
        free = set(self._free)
        assert len(free) == len(self._free), (
            "duplicate block on the free list (double-free)")
        lru = set(self._lru)
        held = {b for b in range(nb) if refs[b] > 0}
        assert not (free & lru) and not (free & held) and not (lru & held), (
            "block in two states: "
            f"free∩lru={free & lru} free∩held={free & held} "
            f"lru∩held={lru & held}")
        assert len(free) + len(lru) + len(held) == nb, (
            f"leaked blocks: free={len(free)} lru={len(lru)} "
            f"held={len(held)} != {nb}")
        for blk in lru:
            assert blk in self._hash_of, f"unhashed block {blk} on LRU"
        return True

    def register_prefix(self, slot: int, tokens: np.ndarray, valid_len: int):
        """Hash this slot's full blocks over tokens[:valid_len] so later
        same-prefix requests hit them (only rows actually written count —
        the engine passes valid_len excluding the pending last token)."""
        if not self.enable_prefix_caching:
            return
        owned = self._slot_blocks[slot]
        inserted: List[bytes] = []
        for i, key in enumerate(self._block_keys(tokens, valid_len)):
            if i >= len(owned):
                break
            blk = owned[i]
            if blk not in self._hash_of and key not in self._table:
                self._table[key] = blk
                self._hash_of[blk] = key
                inserted.append(key)
        if inserted and self.prefix_listener is not None:
            # Per-replica prefix-insert event: the fleet router's
            # affinity map learns which replica holds which prefix.
            self.prefix_listener(inserted)

    def release(self, slot: int, tokens: np.ndarray, valid_len: int,
                preempted: bool = False):
        """Return a slot's blocks to the pool. Full blocks get registered
        in the prefix cache first (so a preempted request can re-hit its
        own KV on resume, and finished prompts stay warm for followers),
        then every block is de-referenced — rc==0 hashed blocks park on
        the LRU list, unhashed ones go straight to the free list."""
        self.register_prefix(slot, tokens, valid_len)
        for blk in self._slot_blocks[slot]:
            self._release_block(blk)
        self._slot_blocks[slot] = []
        self.page_table[slot, :] = 0
        if preempted:
            self.stats["preemptions"] += 1
            telemetry.inc("paged_preemptions")

    # ---- per-block prefix export/import (fleet prefix store) -------------
    def has_prefix(self, key: bytes) -> bool:
        """Whether a prefix-block hash is currently hittable in this
        pool (the fleet router probes this before serving a store
        payload — a locally-present block never crosses the wire)."""
        return key in self._table

    def export_prefix_block(self, key: bytes) -> Optional[dict]:
        """READ-ONLY export of ONE cached prefix block's stored rows
        (+ scales) for the FLEET-GLOBAL PREFIX STORE (ISSUE 20): the
        block is shipped in export_slot discipline — verbatim stored
        bytes in the storage dtype, exact nbytes off the addressable
        arrays — so an import on any same-dtype pool is copy-exact.
        Returns None when the hash is no longer hittable (evicted or
        flushed between the insert event and the export). Nothing here
        mutates the pool."""
        import jax
        blk = self._table.get(key)
        if blk is None:
            return None
        rows = tuple(np.asarray(jax.device_get(p[:, blk]))
                     for p in self.pages)
        scales = (tuple(np.asarray(jax.device_get(s[:, blk]))
                        for s in self.scales)
                  if self.scales is not None else None)
        nbytes = sum(r.nbytes for r in rows)
        if scales is not None:
            nbytes += sum(s.nbytes for s in scales)
        self.stats["prefix_block_exports"] += 1
        return {"kv_cache_dtype": self.kv_cache_dtype, "rows": rows,
                "scales": scales, "block_size": self.block_size,
                "nbytes": nbytes}

    def import_prefix_block(self, key: bytes, payload: dict) -> bool:
        """Install an `export_prefix_block` payload as a HITTABLE prefix
        block: one fresh block is filled with the stored rows verbatim
        and registered under `key` with refcount 0 on the LRU list —
        exactly the state a locally-prefilled block reaches after its
        last owner releases, so a subsequent admit() hits it like any
        local prefix and the prefill starts past it (the
        prefill-chunks-avoided win). ALL-OR-NOTHING: returns True when
        the key is already present (idempotent), False when the pool
        cannot supply a block, and rolls the allocation back on any
        scatter fault — audit() passes either way."""
        if payload["kv_cache_dtype"] != self.kv_cache_dtype:
            raise ValueError(
                f"cannot import a {payload['kv_cache_dtype']!r} prefix "
                f"block into a {self.kv_cache_dtype!r} pool — the store "
                "ships stored rows verbatim; every fleet replica must "
                "run the same --kv-cache-dtype")
        if payload["block_size"] != self.block_size:
            raise ValueError(
                f"prefix-block size mismatch: payload block_size="
                f"{payload['block_size']} vs pool {self.block_size} — "
                "prefix hashes only align across equal block sizes")
        if not self.enable_prefix_caching:
            return False
        if key in self._table:
            return True
        blk = self._take_free()
        if blk is None:
            return False
        try:
            self.pages = tuple(p.at[:, blk].set(jnp.asarray(r))
                               for p, r in zip(self.pages,
                                               payload["rows"]))
            if self.scales is not None:
                self.scales = tuple(
                    s.at[:, blk].set(jnp.asarray(r))
                    for s, r in zip(self.scales, payload["scales"]))
        except Exception:
            # Partially-written rows are dead data in a returned block
            # the next writer overwrites — bookkeeping stays clean.
            self._free.append(blk)
            raise
        self._table[key] = blk
        self._hash_of[blk] = key
        self._lru[blk] = None       # rc==0, evictable, hittable
        self.stats["prefix_block_imports"] += 1
        telemetry.inc("fleet_prefix_blocks_imported")
        return True


class HostSpillTier:
    """Host-RAM spill tier for PARKED sessions (ISSUE 20): a strict
    byte-budgeted dict of `export_slot`-format payloads (numpy rows +
    scales — already host-resident, exact nbytes off the serialized
    arrays) keyed by request id. The tier never evicts: a parked
    session is LIVE state, so `put` past the budget is refused and the
    engine falls back to preemption (spill preferred, never forced).
    Insertion order is the engine's unpark order (FIFO — the
    least-recently-parked session resumes first)."""

    def __init__(self, budget_bytes: int):
        assert budget_bytes > 0, "spill tier needs a positive byte budget"
        self.budget_bytes = int(budget_bytes)
        self.bytes_used = 0
        self._entries: OrderedDict = OrderedDict()   # rid -> payload
        self.counters = {"parks": 0, "unparks": 0, "park_bytes": 0,
                         "unpark_bytes": 0, "rejects": 0,
                         "peak_bytes": 0, "peak_parked": 0}

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, rid) -> bool:
        return rid in self._entries

    def would_fit(self, nbytes: int) -> bool:
        return self.bytes_used + nbytes <= self.budget_bytes

    def put(self, rid, payload: dict) -> bool:
        """Park a payload. False (tier untouched, reject counted) when
        the exact serialized bytes would exceed the budget."""
        assert rid not in self._entries, f"request {rid} already parked"
        nbytes = payload["nbytes"]
        if not self.would_fit(nbytes):
            self.counters["rejects"] += 1
            return False
        self._entries[rid] = payload
        self.bytes_used += nbytes
        self.counters["parks"] += 1
        self.counters["park_bytes"] += nbytes
        self.counters["peak_bytes"] = max(self.counters["peak_bytes"],
                                          self.bytes_used)
        self.counters["peak_parked"] = max(self.counters["peak_parked"],
                                           len(self._entries))
        telemetry.inc("kv_spill_parks")
        telemetry.inc("kv_spill_park_bytes", nbytes)
        return True

    def get(self, rid) -> Optional[dict]:
        return self._entries.get(rid)

    def pop(self, rid, unpark: bool = True) -> Optional[dict]:
        """Remove a parked payload (unpark=False for aborts/expiry —
        only genuine resumes count as unparks)."""
        payload = self._entries.pop(rid, None)
        if payload is None:
            return None
        self.bytes_used -= payload["nbytes"]
        if unpark:
            self.counters["unparks"] += 1
            self.counters["unpark_bytes"] += payload["nbytes"]
            telemetry.inc("kv_spill_unparks")
        return payload

    def rids(self) -> List:
        """Parked request ids, oldest (next to unpark) first."""
        return list(self._entries)

    def stats(self) -> dict:
        return {"parked": len(self._entries),
                "budget_bytes": self.budget_bytes,
                "bytes_used": self.bytes_used, **self.counters}


class FleetPrefixStore:
    """Fleet-global prefix store (ISSUE 20): `export_prefix_block`
    payloads keyed by the SAME rolling `prefix_block_keys` hashes the
    pool's prefix cache and the routers' affinity maps use — so a store
    hit is an exact-prefix match by construction. Bounded by bytes with
    LRU eviction (a prefix block is derived state — unlike the spill
    tier it may always be dropped and re-prefilled), with per-fleet
    hit/byte counters. Both routers (inference/fleet.py in-process,
    inference/fleet_rpc.py cross-process via the prefix_put/prefix_get
    verbs) populate it from prefix-insert events and serve admissions
    from it."""

    def __init__(self, capacity_bytes: int):
        assert capacity_bytes > 0, "prefix store needs a positive capacity"
        self.capacity_bytes = int(capacity_bytes)
        self.bytes_used = 0
        self._entries: OrderedDict = OrderedDict()   # key -> payload
        self.counters = {"puts": 0, "put_bytes": 0, "hits": 0,
                         "hit_bytes": 0, "misses": 0, "evictions": 0,
                         "flushes": 0, "peak_bytes": 0}

    def __len__(self) -> int:
        return len(self._entries)

    def has(self, key: bytes) -> bool:
        return key in self._entries

    def put(self, key: bytes, payload: dict) -> bool:
        """Insert a block payload, evicting LRU entries to fit. A
        payload larger than the whole store is refused (never counted
        as resident)."""
        if key in self._entries:
            return True
        nbytes = payload["nbytes"]
        if nbytes > self.capacity_bytes:
            return False
        while self.bytes_used + nbytes > self.capacity_bytes:
            _, old = self._entries.popitem(last=False)
            self.bytes_used -= old["nbytes"]
            self.counters["evictions"] += 1
        self._entries[key] = payload
        self.bytes_used += nbytes
        self.counters["puts"] += 1
        self.counters["put_bytes"] += nbytes
        self.counters["peak_bytes"] = max(self.counters["peak_bytes"],
                                          self.bytes_used)
        telemetry.inc("fleet_prefix_store_put_bytes", nbytes)
        return True

    def get(self, key: bytes) -> Optional[dict]:
        payload = self._entries.get(key)
        if payload is None:
            self.counters["misses"] += 1
            return None
        self._entries.move_to_end(key)
        self.counters["hits"] += 1
        self.counters["hit_bytes"] += payload["nbytes"]
        telemetry.inc("fleet_prefix_store_hits")
        return payload

    def clear(self):
        """Drop everything (params reload / replica death: stored
        blocks hold KV from weights no longer guaranteed fleet-wide)."""
        if self._entries:
            self.counters["flushes"] += 1
        self._entries.clear()
        self.bytes_used = 0

    def stats(self) -> dict:
        return {"entries": len(self._entries),
                "capacity_bytes": self.capacity_bytes,
                "bytes_used": self.bytes_used, **self.counters}
