"""Post-training weight quantization (int8, per-channel).

Parity with /root/reference/megatron/post_training/ quantization exports
(arguments.py --export-quant-cfg int8_sq/fp8 choices, model_provider.py
modelopt delegation): the reference hands quantization to the external
ModelOpt library; here it is implemented natively — symmetric per-output-
channel int8 for every matmul kernel in the params pytree, with
dequantize-on-load for serving and a quantization-error report.

TPU notes: XLA lowers int8 ops fine, but weight-only PTQ's win on TPU is
artifact size + host→device transfer (half of bf16, quarter of fp32);
matmuls stay bf16 after dequant, so accuracy loss is bounded by the
per-channel rounding error measured here.

Serving-resident int8 (ISSUE 10, ``--quantized-weights``): instead of
dequantize-on-load, `residentize_params` converts the quantized pytree
into a jit-able form — each supported matmul kernel becomes a two-leaf
dict {"qint8": int8, "qscale": fp32} — and the forward passes call
`resolve_param` at matmul entry, so XLA keeps the int8 weights resident
in HBM (param bytes ~halved vs bf16) and fuses the per-channel dequant
into the consuming matmul. Only kernels whose consumers are
resolve-aware stay resident (RESIDENT_KERNELS); anything else
dequantizes eagerly so unexpected model families keep working.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.utils import metrics as telemetry

logger = logging.getLogger(__name__)

# Leaves whose name ends with one of these are quantized (matmul kernels);
# everything else (norms, biases, embeddings' positional tables, routers)
# stays full precision — the reference int8_sq config makes the same
# linear-only choice.
QUANT_SUFFIXES = ("kernel", "dense", "head", "pooler", "attn_linear",
                  "mlp_linear")
# MoE routers are deliberately fp32 in the forward (moe.py _router);
# perturbing router logits flips top-k selection — keep them unquantized.
QUANT_EXCLUDE = ("router_kernel",)


def _should_quantize(path: Tuple[str, ...], leaf) -> bool:
    name = path[-1] if path else ""
    if any(name.endswith(s) for s in QUANT_EXCLUDE):
        return False
    return (hasattr(leaf, "ndim") and leaf.ndim >= 2 and
            any(name.endswith(s) for s in QUANT_SUFFIXES))


def _flatten_with_names(tree, prefix=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _flatten_with_names(v, prefix + (k,))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _flatten_with_names(v, prefix + (str(i),))
    else:
        yield prefix, tree


def quantize_leaf(w: jnp.ndarray) -> Dict[str, Any]:
    """Symmetric per-output-channel int8.

    Scales reduce over the INPUT axis only (axis -2): output features
    live on the last axis, and any leading axes are layer/expert stacks
    ([L,H,F], [L,E,H,F] from _stack_layers) whose slices are independent
    linears — each gets its own scales, matching the reference's
    per-linear int8 (each linear quantized independently)."""
    w32 = np.asarray(w, dtype=np.float32)
    absmax = np.max(np.abs(w32), axis=-2, keepdims=True)
    scale = np.maximum(absmax / 127.0, 1e-12)
    q = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
    return {"__quant__": "int8", "q": q,
            "scale": scale.astype(np.float32),
            "dtype": str(np.dtype(np.asarray(w).dtype))}


def dequantize_leaf(entry: Dict[str, Any]) -> np.ndarray:
    out = entry["q"].astype(np.float32) * entry["scale"]
    return out.astype(np.dtype(entry["dtype"]))


def is_quantized_leaf(x) -> bool:
    return isinstance(x, dict) and x.get("__quant__") == "int8"


def quantize_params(params, resident_only: bool = False
                    ) -> Tuple[Any, Dict[str, float]]:
    """Quantize every matmul kernel; returns (pytree with quantized
    leaves, report {path: max_abs_error}).

    resident_only: quantize ONLY the leaves residentize_params will
    keep int8-resident (startup PTQ for serving — anything else would
    eat int8 rounding error and then be dequantized eagerly anyway,
    accuracy loss with zero memory win). Artifact export keeps the
    default full selection: on-disk size benefits from every quantized
    kernel even when some dequantize on load."""
    report: Dict[str, float] = {}

    def want(prefix, tree):
        if not _should_quantize(prefix, tree):
            return False
        if not resident_only:
            return True
        name = prefix[-1] if prefix else ""
        return any(name.endswith(s) for s in RESIDENT_KERNELS)

    def walk(tree, prefix=()):
        if isinstance(tree, dict):
            return {k: walk(v, prefix + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [walk(v, prefix + (str(i),))
                    for i, v in enumerate(tree)]
        if isinstance(tree, tuple):
            return tuple(walk(v, prefix + (str(i),))
                         for i, v in enumerate(tree))
        if want(prefix, tree):
            entry = quantize_leaf(tree)
            err = float(np.max(np.abs(
                dequantize_leaf(entry).astype(np.float32)
                - np.asarray(tree, np.float32))))
            report["/".join(prefix)] = err
            return entry
        return tree

    return walk(params), report


def dequantize_params(tree):
    """Inverse of quantize_params (load path for serving)."""
    if is_quantized_leaf(tree):
        return jnp.asarray(dequantize_leaf(tree))
    if isinstance(tree, dict):
        return {k: dequantize_params(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [dequantize_params(v) for v in tree]
    if isinstance(tree, tuple):
        return tuple(dequantize_params(v) for v in tree)
    return tree


# Kernels whose forward-pass consumers call resolve_param at matmul
# entry (transformer/attention.py, transformer/mlp.py, transformer/
# mla.py out-proj, transformer/moe.py expert GEMMs) and may therefore
# stay int8-resident for serving. MoE expert stacks resolve at
# moe_forward matmul entry since ISSUE 13 — the old "moe" carve-out
# (the last non-resident tensor family) is gone; any remaining
# fallback dequantization is counted + logged by residentize_params.
RESIDENT_KERNELS = ("q_kernel", "kv_kernel", "out_kernel",
                    "fc1_kernel", "fc2_kernel")


def is_resident_leaf(x) -> bool:
    return (isinstance(x, dict) and "qint8" in x and "qscale" in x
            and len(x) == 2)


def resolve_param(w, dtype=None):
    """Matmul-entry hook: a resident-quantized leaf dequantizes here
    (int8 × per-channel fp32 scale — XLA fuses it into the consuming
    matmul, the int8 buffer is what lives in HBM); plain arrays pass
    through untouched, so every call site stays dtype/path agnostic."""
    if is_resident_leaf(w):
        w = w["qint8"].astype(jnp.float32) * w["qscale"]
    return w if dtype is None else w.astype(dtype)


def residentize_params(tree):
    """Convert a quantize_params pytree into the serving-resident form:
    RESIDENT_KERNELS leaves (incl. MoE expert stacks since ISSUE 13)
    become {"qint8", "qscale"} jnp-array pairs (kept int8 in HBM,
    dequantized at matmul entry by resolve_param); every other
    quantized leaf dequantizes eagerly. Idempotent on unquantized
    pytrees.

    Fallback observability (ISSUE 13 satellite): eager dequantization
    here is a silent loss of the resident-HBM win — every fallback's
    dequantized bytes are counted into the metrics registry
    (``quantized_weights_dequantized_bytes``) and logged ONCE per call,
    so a future carve-out regression shows up in /metrics instead of
    only in an HBM profile."""
    fallback = {"bytes": 0, "paths": []}

    def walk(tree, path):
        if is_quantized_leaf(tree):
            name = path[-1] if path else ""
            if any(name.endswith(s) for s in RESIDENT_KERNELS):
                return {"qint8": jnp.asarray(tree["q"]),
                        "qscale": jnp.asarray(tree["scale"], jnp.float32)}
            deq = jnp.asarray(dequantize_leaf(tree))
            fallback["bytes"] += int(deq.nbytes)
            fallback["paths"].append("/".join(path))
            return deq
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [walk(v, path + (str(i),))
                    for i, v in enumerate(tree)]
        if isinstance(tree, tuple):
            return tuple(walk(v, path + (str(i),))
                         for i, v in enumerate(tree))
        return tree

    out = walk(tree, ())
    if fallback["bytes"]:
        telemetry.inc("quantized_weights_dequantized_bytes",
                      fallback["bytes"])
        logger.warning(
            "residentize_params: %d quantized leaves have no "
            "resolve-aware consumer and were dequantized eagerly "
            "(%d bytes of the resident-HBM win given back): %s",
            len(fallback["paths"]), fallback["bytes"],
            ", ".join(fallback["paths"][:8]))
    return out


def resident_nbytes(tree) -> int:
    """Device bytes of a (possibly residentized) params pytree."""
    total = 0
    for _, leaf in _flatten_with_names(tree):
        if hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total


def quantized_nbytes(tree) -> int:
    total = 0
    for path, leaf in _flatten_with_names(tree):
        if path and path[-1] in ("q", "scale"):
            total += leaf.nbytes
        elif hasattr(leaf, "nbytes") and path[-1] != "dtype":
            total += leaf.nbytes
    return total
