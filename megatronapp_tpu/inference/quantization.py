"""Post-training weight quantization (int8, per-channel).

Parity with /root/reference/megatron/post_training/ quantization exports
(arguments.py --export-quant-cfg int8_sq/fp8 choices, model_provider.py
modelopt delegation): the reference hands quantization to the external
ModelOpt library; here it is implemented natively — symmetric per-output-
channel int8 for every matmul kernel in the params pytree, with
dequantize-on-load for serving and a quantization-error report.

TPU notes: XLA lowers int8 ops fine, but weight-only PTQ's win on TPU is
artifact size + host→device transfer (half of bf16, quarter of fp32);
matmuls stay bf16 after dequant, so accuracy loss is bounded by the
per-channel rounding error measured here.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Leaves whose name ends with one of these are quantized (matmul kernels);
# everything else (norms, biases, embeddings' positional tables, routers)
# stays full precision — the reference int8_sq config makes the same
# linear-only choice.
QUANT_SUFFIXES = ("kernel", "dense", "head", "pooler", "attn_linear",
                  "mlp_linear")
# MoE routers are deliberately fp32 in the forward (moe.py _router);
# perturbing router logits flips top-k selection — keep them unquantized.
QUANT_EXCLUDE = ("router_kernel",)


def _should_quantize(path: Tuple[str, ...], leaf) -> bool:
    name = path[-1] if path else ""
    if any(name.endswith(s) for s in QUANT_EXCLUDE):
        return False
    return (hasattr(leaf, "ndim") and leaf.ndim >= 2 and
            any(name.endswith(s) for s in QUANT_SUFFIXES))


def _flatten_with_names(tree, prefix=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _flatten_with_names(v, prefix + (k,))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _flatten_with_names(v, prefix + (str(i),))
    else:
        yield prefix, tree


def quantize_leaf(w: jnp.ndarray) -> Dict[str, Any]:
    """Symmetric per-output-channel int8.

    Scales reduce over the INPUT axis only (axis -2): output features
    live on the last axis, and any leading axes are layer/expert stacks
    ([L,H,F], [L,E,H,F] from _stack_layers) whose slices are independent
    linears — each gets its own scales, matching the reference's
    per-linear int8 (each linear quantized independently)."""
    w32 = np.asarray(w, dtype=np.float32)
    absmax = np.max(np.abs(w32), axis=-2, keepdims=True)
    scale = np.maximum(absmax / 127.0, 1e-12)
    q = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
    return {"__quant__": "int8", "q": q,
            "scale": scale.astype(np.float32),
            "dtype": str(np.dtype(np.asarray(w).dtype))}


def dequantize_leaf(entry: Dict[str, Any]) -> np.ndarray:
    out = entry["q"].astype(np.float32) * entry["scale"]
    return out.astype(np.dtype(entry["dtype"]))


def is_quantized_leaf(x) -> bool:
    return isinstance(x, dict) and x.get("__quant__") == "int8"


def quantize_params(params) -> Tuple[Any, Dict[str, float]]:
    """Quantize every matmul kernel; returns (pytree with quantized
    leaves, report {path: max_abs_error})."""
    report: Dict[str, float] = {}

    def walk(tree, prefix=()):
        if isinstance(tree, dict):
            return {k: walk(v, prefix + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [walk(v, prefix + (str(i),))
                    for i, v in enumerate(tree)]
        if isinstance(tree, tuple):
            return tuple(walk(v, prefix + (str(i),))
                         for i, v in enumerate(tree))
        if _should_quantize(prefix, tree):
            entry = quantize_leaf(tree)
            err = float(np.max(np.abs(
                dequantize_leaf(entry).astype(np.float32)
                - np.asarray(tree, np.float32))))
            report["/".join(prefix)] = err
            return entry
        return tree

    return walk(params), report


def dequantize_params(tree):
    """Inverse of quantize_params (load path for serving)."""
    if is_quantized_leaf(tree):
        return jnp.asarray(dequantize_leaf(tree))
    if isinstance(tree, dict):
        return {k: dequantize_params(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [dequantize_params(v) for v in tree]
    if isinstance(tree, tuple):
        return tuple(dequantize_params(v) for v in tree)
    return tree


def quantized_nbytes(tree) -> int:
    total = 0
    for path, leaf in _flatten_with_names(tree):
        if path and path[-1] in ("q", "scale"):
            total += leaf.nbytes
        elif hasattr(leaf, "nbytes") and path[-1] != "dtype":
            total += leaf.nbytes
    return total
