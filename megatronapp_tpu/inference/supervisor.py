"""Replica supervisor: ONE detection/relaunch code path (ISSUE 18).

PR 14's fleet carried replica death and replacement as two router
methods (`kill_replica`/`revive_replica`) that tests and operators
called "by hand" — and the cross-process fleet (inference/fleet_rpc.py)
needs a REAL supervisor: something that watches replica worker
processes through the long-carried `read_heartbeat` view
(training/ft_integration.py — the on-disk heartbeat written exactly so
an EXTERNAL supervisor can see a hung process from outside), SIGKILLs a
wedged or dead worker, and relaunches it. If those were two separate
code paths they would drift; this module is the single one.

`Supervisor` owns the POLICY (poll → detect → kill → relaunch →
account a restart) and delegates the MECHANISM to a backend object:

- ``FleetRouter.supervisor`` (inference/fleet.py) wires an in-process
  backend: alive = replica not DEAD, kill = the step-exception failover
  path (`_fail_replica` — zero lost sessions), relaunch = the
  engine_factory rebuild. Manual drills (`kill_replica`,
  `revive_replica`) route through the SAME Supervisor methods the poll
  loop uses, so "playing supervisor by hand" and the real watcher
  cannot diverge.
- ``ProcessFleetRouter`` (inference/fleet_rpc.py) wires a process
  backend: alive = worker pid running AND heartbeat fresh, kill =
  SIGKILL + router-side session failover, relaunch = respawn the worker
  entrypoint with a bumped incarnation (the router reattaches off the
  worker's addr file).
- ``python -m megatronapp_tpu.inference.supervisor --state-dir D``
  runs the same policy as a STANDALONE OS process against the state
  directory alone (addr/heartbeat files), so the router and the
  supervisor can live in different processes: the supervisor respawns,
  the router notices the incarnation bump and reconnects.

Restart accounting (`restarts` per replica) is persisted to
``<state_dir>/supervisor.json`` when a state dir is given, so the
router's /stats // /metrics aggregation reports supervisor restarts no
matter which process did the restarting.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

SUPERVISOR_FILE = "supervisor.json"


class Supervisor:
    """Detection/relaunch policy over a pluggable backend.

    Backend protocol (duck-typed):
      indices() -> List[int]          replicas under supervision
      alive(idx) -> bool              liveness probe
      kill(idx)                       force-fail (sessions fail over)
      relaunch(idx, **hints)          bring a replacement up
    """

    def __init__(self, backend, interval: float = 1.0,
                 state_dir: Optional[str] = None):
        self.backend = backend
        self.interval = interval
        self.state_dir = state_dir
        self.restarts: Dict[int, int] = {
            i: 0 for i in backend.indices()}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.RLock()
        self._load_state()

    # -- accounting ---------------------------------------------------------
    @property
    def total_restarts(self) -> int:
        return sum(self.restarts.values())

    def _load_state(self):
        """Adopt restart counts from a previous supervisor incarnation
        (router restart recovery keeps the counters monotonic)."""
        if not self.state_dir:
            return
        path = os.path.join(self.state_dir, SUPERVISOR_FILE)
        try:
            with open(path) as f:
                prev = json.load(f).get("restarts", {})
            for k, v in prev.items():
                self.restarts[int(k)] = max(
                    self.restarts.get(int(k), 0), int(v))
        except (OSError, ValueError):
            pass

    def _write_state(self):
        if not self.state_dir:
            return
        path = os.path.join(self.state_dir, SUPERVISOR_FILE)
        tmp = path + ".tmp"
        payload = {"pid": os.getpid(), "ts": time.time(),
                   "restarts": {str(k): v
                                for k, v in self.restarts.items()}}
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            logger.warning("supervisor state write failed", exc_info=True)

    # -- the one code path --------------------------------------------------
    def kill(self, idx: int):
        """Force-fail replica `idx` (manual drills and the poll loop
        both land here): the backend fails its sessions over — zero
        lost — and the replica is DEAD until `revive`."""
        with self._lock:
            self.backend.kill(idx)

    def revive(self, idx: int, **hints):
        """Bring a replacement for replica `idx` up through the
        backend's relaunch mechanism (engine_factory rebuild in-process;
        worker respawn cross-process). Counts a restart — a manual
        revive IS a restart, so drills and the poll loop report through
        the same accounting."""
        with self._lock:
            self.backend.relaunch(idx, **hints)
            self.restarts[idx] = self.restarts.get(idx, 0) + 1
        self._write_state()

    def poll_once(self) -> List[int]:
        """One detection round: every dead/wedged replica is killed
        (idempotent — failover already ran if the router saw the death
        first), relaunched, and counted. Returns recovered indices."""
        recovered: List[int] = []
        for idx in self.backend.indices():
            try:
                if self.backend.alive(idx):
                    continue
            except Exception:  # noqa: BLE001 — probe failure = dead
                pass
            logger.warning(
                "supervisor: replica %d dead/wedged — SIGKILL + "
                "relaunch", idx)
            with self._lock:
                try:
                    self.backend.kill(idx)
                except Exception:  # noqa: BLE001 — already dead is fine
                    logger.debug("supervisor kill(%d) raised", idx,
                                 exc_info=True)
                try:
                    self.backend.relaunch(idx)
                except Exception:  # noqa: BLE001 — retried next poll
                    logger.warning("supervisor relaunch(%d) failed — "
                                   "retrying next poll", idx,
                                   exc_info=True)
                    continue
                self.restarts[idx] = self.restarts.get(idx, 0) + 1
            recovered.append(idx)
        self._write_state()
        return recovered

    # -- thread mode --------------------------------------------------------
    def start(self) -> "Supervisor":
        """Run the poll loop in a daemon thread (the in-process
        supervisor mode; the standalone process mode runs main())."""
        self._thread = threading.Thread(
            target=self._run, name="fleet-supervisor", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 4)
            self._thread = None

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — supervisor must survive
                logger.warning("supervisor poll failed", exc_info=True)


class StateDirBackend:
    """Backend for the STANDALONE supervisor process: everything it
    knows comes from the fleet state directory (worker addr files +
    heartbeats), so it shares no memory with the router. Relaunched
    workers become children of the supervisor process; the router
    notices the addr file's incarnation bump and reconnects."""

    def __init__(self, state_dir: str, stale_after: float = 15.0):
        self.state_dir = state_dir
        self.stale_after = stale_after
        self._procs: Dict[int, object] = {}   # idx -> Popen we spawned

    def indices(self) -> List[int]:
        from megatronapp_tpu.inference.fleet_rpc import replica_dirs
        return replica_dirs(self.state_dir)

    def _addr(self, idx: int) -> Optional[dict]:
        from megatronapp_tpu.inference.fleet_rpc import read_addr
        return read_addr(self.state_dir, idx)

    def alive(self, idx: int) -> bool:
        from megatronapp_tpu.training.ft_integration import read_heartbeat
        addr = self._addr(idx)
        if addr is None:
            return False
        try:
            os.kill(addr["pid"], 0)
        except (OSError, ProcessLookupError):
            return False
        from megatronapp_tpu.inference.fleet_rpc import heartbeat_dir
        hb = read_heartbeat(heartbeat_dir(self.state_dir, idx),
                            stale_after=self.stale_after)
        return bool(hb["alive"])

    def kill(self, idx: int):
        addr = self._addr(idx)
        if addr is None:
            return
        try:
            os.kill(addr["pid"], signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass

    def relaunch(self, idx: int, **hints):
        from megatronapp_tpu.inference.fleet_rpc import (
            spawn_worker, wait_for_addr,
        )
        addr = self._addr(idx) or {"incarnation": -1}
        incarnation = addr["incarnation"] + 1
        proc = spawn_worker(self.state_dir, idx, incarnation)
        self._procs[idx] = proc
        wait_for_addr(self.state_dir, idx, incarnation)


def main(argv=None) -> int:
    """Standalone supervisor process entrypoint:

      python -m megatronapp_tpu.inference.supervisor --state-dir D
    """
    ap = argparse.ArgumentParser(
        description="fleet replica supervisor (ISSUE 18)")
    ap.add_argument("--state-dir", required=True)
    ap.add_argument("--stale-after", type=float, default=15.0,
                    help="heartbeat age past which a worker counts as "
                         "wedged (SIGKILL + relaunch)")
    ap.add_argument("--interval", type=float, default=1.0)
    args = ap.parse_args(argv)
    backend = StateDirBackend(args.state_dir,
                              stale_after=args.stale_after)
    sup = Supervisor(backend, interval=args.interval,
                     state_dir=args.state_dir)
    print(f"supervisor pid {os.getpid()} watching {args.state_dir} "
          f"(stale_after={args.stale_after}s)", flush=True)
    sup._write_state()
    try:
        while True:
            sup.poll_once()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
