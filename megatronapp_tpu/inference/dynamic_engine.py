"""Dynamic inference engine: continuous batching over a slot-based KV cache.

Parity with /root/reference/megatron/core/inference/engines/dynamic_engine.py
+ contexts/dynamic_context.py + scheduler.py: requests of different lengths
enter a waiting queue; the engine admits them into free cache slots
(prefill), decodes ONE token per step for every active slot, and retires
finished requests — new requests join mid-flight without draining the batch.

TPU-first: all shapes static. The shared cache is [L, max_batch, S_max,
Hkv, D] K/V for standard attention, or the compressed MLA pair
(latent [L, max_batch, S_max, kv_lora_rank] + shared roped key
[L, max_batch, S_max, dpe]); per-slot sequence lengths live in a
[max_batch] int32 array; the decode step is ONE jit for all slots
(per-row rope positions + per-row causal masks), and prefill runs
through length-bucketed jits (a handful of compilations instead of one
per prompt length).
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.inference.engine import (
    SamplingParams, init_kv_cache, mask_padded_vocab, sample_logits,
)
from megatronapp_tpu.models.gpt import gpt_embed, gpt_head, gpt_rope_tables
from megatronapp_tpu.transformer.block import layer_forward


@dataclasses.dataclass
class Request:
    """One generation request (reference inference_request.py analogue)."""
    request_id: int
    prompt: np.ndarray                  # [P] int32
    max_new_tokens: int
    sampling: SamplingParams
    eod_id: Optional[int] = None
    # Filled by the engine:
    slot: int = -1
    generated: list = dataclasses.field(default_factory=list)
    finished: bool = False

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])


def _decode_step(params, tokens, cache, lengths, active,
                 cfg: TransformerConfig):
    """One-token decode for every slot.

    tokens [B,1] (last token per slot), cache [L,B,Smax,...], lengths [B]
    (tokens already in cache per slot), active [B] bool. Returns
    (last_logits [B,V], new_cache)."""
    b = tokens.shape[0]
    max_len = cache[0].shape[2]
    h = gpt_embed(params, tokens, cfg, position_ids=lengths[:, None])
    cos_full, sin_full = gpt_rope_tables(cfg, max_len)
    if cos_full is not None:
        cos = jnp.take(cos_full, lengths, axis=0)[:, None]   # [B,1,half]
        sin = jnp.take(sin_full, lengths, axis=0)[:, None]
    else:
        cos = sin = None

    # Per-row causality: the new token (position lengths[b]) may attend
    # cache positions <= lengths[b]; inactive rows are fully masked except
    # self (keeps the softmax finite; results are discarded).
    kv_pos = jnp.arange(max_len)
    attend = kv_pos[None, :] <= lengths[:, None]             # [B,Smax]
    mask = attend[:, None, None, :]                          # [B,1,1,Smax]

    ck, cv = cache

    def body(carry, layer_in):
        hh = carry
        layer_p, k_l, v_l, lid = layer_in
        (hh, new_cache), _ = layer_forward(
            layer_p, hh, cfg, cos, sin, mask, layer_id=lid,
            kv_cache=(k_l, v_l), cache_index=None,
            cache_positions=lengths)
        return hh, new_cache

    h, new_caches = jax.lax.scan(
        body, h, (params["block"], ck, cv, jnp.arange(cfg.num_layers)))
    logits = gpt_head(params, h, cfg)[:, -1]
    return logits, new_caches


class DynamicInferenceEngine:
    """Continuous-batching engine (reference DynamicInferenceEngine).

    add_request() any time; step() decodes one token for every active
    request and admits waiting requests into free slots. Finished requests
    surface through the returned events and the optional token_callback.
    """

    def __init__(self, params, cfg: TransformerConfig, tokenizer=None,
                 max_batch: int = 4, max_seq_len: Optional[int] = None,
                 prefill_buckets: Tuple[int, ...] = (32, 128, 512)):
        self.params = params
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len or cfg.max_position_embeddings
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= self.max_seq_len
        ) or (self.max_seq_len,)

        self.cache = init_kv_cache(cfg, max_batch, self.max_seq_len)
        self.lengths = jnp.zeros((max_batch,), jnp.int32)
        self.last_tokens = np.zeros((max_batch, 1), np.int32)
        self.slots: List[Optional[Request]] = [None] * max_batch
        self.waiting: deque = deque()
        self._ids = itertools.count()
        self._build_jits()

    def _build_jits(self):
        cfg = self.cfg
        self._decode = jax.jit(
            lambda p, t, c, l, a: _decode_step(p, t, c, l, a, cfg))
        # Prefill reuses the static engine's whole-prompt forward on a
        # [1, bucket] batch, then scatters the kv rows into the slot.
        import functools

        from megatronapp_tpu.inference.engine import _forward_with_cache
        self._prefill = jax.jit(
            functools.partial(_forward_with_cache, cfg=cfg))

    def reset_compilation(self):
        """Re-trace on next call (after MegaScope hook toggles — see
        StaticInferenceEngine.reset_compilation)."""
        self._build_jits()

    # ---- request lifecycle ------------------------------------------------
    def add_request(self, prompt_tokens, max_new_tokens: int,
                    sampling: Optional[SamplingParams] = None,
                    eod_id: Optional[int] = None) -> int:
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new({max_new_tokens}) exceeds "
                f"max_seq_len({self.max_seq_len})")
        req = Request(next(self._ids), prompt, max_new_tokens,
                      sampling or SamplingParams(), eod_id=eod_id)
        self.waiting.append(req)
        return req.request_id

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or any(
            r is not None for r in self.slots)

    def _admit(self) -> List[Request]:
        admitted = []
        for slot in range(self.max_batch):
            if self.slots[slot] is not None or not self.waiting:
                continue
            req = self.waiting.popleft()
            req.slot = slot
            self.slots[slot] = req
            self._prefill_into_slot(req)
            admitted.append(req)
        return admitted

    def _prefill_into_slot(self, req: Request):
        p_len = len(req.prompt)
        bucket = next((b for b in self.prefill_buckets if b >= p_len),
                      self.max_seq_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p_len] = req.prompt
        tmp_cache = init_kv_cache(self.cfg, 1, self.max_seq_len)
        logits, tmp_cache = self._prefill(self.params,
                                          jnp.asarray(padded), tmp_cache, 0)
        # Scatter the prompt's kv rows into this slot of the shared cache.
        slot = req.slot
        self.cache = tuple(
            c.at[:, slot, :].set(t[:, 0, :]) for c, t in
            zip(self.cache, tmp_cache))
        self.lengths = self.lengths.at[slot].set(p_len)
        # First generated token comes from the last PROMPT position.
        logits_last = mask_padded_vocab(logits[0, p_len - 1], self.cfg)
        tok = self._sample(logits_last[None], req)
        self._record_token(req, int(tok[0]))

    def _sample(self, logits, req: Request):
        rng = jax.random.PRNGKey(
            req.sampling.seed + len(req.generated) * 7919 + req.request_id)
        return jax.device_get(sample_logits(logits, rng, req.sampling))

    def _record_token(self, req: Request, tok: int):
        req.generated.append(tok)
        self.last_tokens[req.slot, 0] = tok
        if (tok == req.eod_id or
                len(req.generated) >= req.max_new_tokens):
            req.finished = True

    def _retire(self) -> List[Request]:
        done = []
        for slot, req in enumerate(self.slots):
            if req is not None and req.finished:
                done.append(req)
                self.slots[slot] = None
                self.lengths = self.lengths.at[slot].set(0)
        return done

    # ---- main loop --------------------------------------------------------
    def step(self) -> Dict[str, List]:
        """Admit → decode one token for all active slots → retire.

        Returns {"admitted": [ids], "tokens": [(id, tok)], "finished":
        [ids]} for this step."""
        admitted = self._admit()
        events = {"admitted": [r.request_id for r in admitted],
                  "tokens": [(r.request_id, r.generated[-1])
                             for r in admitted],
                  "finished": []}

        active = [r for r in self.slots
                  if r is not None and not r.finished]
        if active:
            active_mask = jnp.asarray(
                [self.slots[i] is not None and not self.slots[i].finished
                 for i in range(self.max_batch)])
            logits, self.cache = self._decode(
                self.params, jnp.asarray(self.last_tokens), self.cache,
                self.lengths, active_mask)
            # The decode wrote each active row's kv at lengths[slot].
            self.lengths = self.lengths + active_mask.astype(jnp.int32)
            logits = mask_padded_vocab(logits, self.cfg)
            for req in active:
                tok = self._sample(logits[req.slot][None], req)
                self._record_token(req, int(tok[0]))
                events["tokens"].append((req.request_id, int(tok[0])))

        events["finished"] = [r.request_id for r in self._retire()]
        return events

    def run_to_completion(self,
                          token_callback: Optional[Callable] = None
                          ) -> Dict[int, np.ndarray]:
        """Drive step() until every request finishes; returns
        {request_id: full token array}."""
        results: Dict[int, np.ndarray] = {}
        finished_reqs: Dict[int, Request] = {}
        known: Dict[int, Request] = {}
        while self.has_work:
            for r in list(self.waiting) + [r for r in self.slots if r]:
                known[r.request_id] = r
            ev = self.step()
            if token_callback is not None:
                for rid, tok in ev["tokens"]:
                    token_callback(rid, tok)
            for rid in ev["finished"]:
                finished_reqs[rid] = known[rid]
        for rid, req in finished_reqs.items():
            results[rid] = req.tokens
        return results

    def generate_text(self, prompts, max_new_tokens: int,
                      sampling: Optional[SamplingParams] = None,
                      token_callback: Optional[Callable] = None):
        """String-level API (drop-in for StaticInferenceEngine
        .generate_text — lets the REST/WS server run on the dynamic
        engine)."""
        assert self.tokenizer is not None, "tokenizer required"
        eod = getattr(self.tokenizer, "eod", None)
        rids = []
        for prompt in prompts:
            ids = np.asarray(self.tokenizer.tokenize(prompt), np.int32)
            rids.append(self.add_request(ids, max_new_tokens, sampling,
                                         eod_id=eod))
        cb = None
        if token_callback is not None:
            def cb(rid, tok):
                token_callback(rid, np.asarray([tok]), None)
        results = self.run_to_completion(token_callback=cb)
        texts = []
        for prompt, rid in zip(prompts, rids):
            n_prompt = len(self.tokenizer.tokenize(prompt))
            new_ids = results[rid][n_prompt:].tolist()
            if eod is not None and eod in new_ids:
                new_ids = new_ids[: new_ids.index(eod)]
            texts.append(self.tokenizer.detokenize(new_ids))
        return texts
