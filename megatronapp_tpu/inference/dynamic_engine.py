"""Dynamic inference engine: continuous batching over slot or paged KV.

Parity with /root/reference/megatron/core/inference/engines/dynamic_engine.py
+ contexts/dynamic_context.py + scheduler.py: requests of different lengths
enter a waiting queue; the engine admits them into free cache slots
(prefill), decodes ONE token per step for every active slot, and retires
finished requests — new requests join mid-flight without draining the batch.

Two cache backends:

- dense (default): the shared cache is [L, max_batch, S_max, Hkv, D] K/V
  (MLA: the compressed latent + shared roped key pair) — every slot pays
  for S_max regardless of actual length. Kept bit-exact as the parity
  oracle for the paged backend.
- ``paged=True``: KV lives in a shared block pool
  [L, num_blocks, block_size, Hkv, D] with per-request page tables
  (inference/paged_cache.py — vLLM-style): admission is by block
  availability rather than whole slots, identical prompt prefixes are
  served from the refcounted prefix cache instead of recomputed,
  exhaustion preempts the lowest-priority running request back to the
  waiting queue (it resumes by re-prefilling prompt+generated, usually
  re-hitting its own cached blocks), and decode attends through the
  ragged paged-attention Pallas kernel
  (ops/pallas/paged_attention.py).

TPU-first: all shapes static; the decode step is ONE jit for all slots
(per-row rope positions + per-row masking), prefill runs through
length-bucketed jits, and sampling is ONE batched on-device jit per step
(per-request streams stay reproducible via fold_in key chains —
PRNGKey(seed) ∘ request_id ∘ step — independent of batch composition).

Speculative decoding (ISSUE 4, inference/speculative.py): with
``spec_method`` set ("draft"/"mtp"/"ngram") on a paged engine, every
decode round proposes up to spec_k draft tokens per request, verifies
them in ONE batched multi-query forward (`_paged_multiquery_step`, the
unified prefill/decode primitive of arXiv 2604.15464), and exact
rejection sampling keeps greedy streams bit-identical to plain decode
and sampled streams distributed exactly like the target model. Rejected
tokens' KV is rolled back (PagedKVCache.rewind). The same multi-query
step prefills the uncached prompt tail in fixed-size chunks, so prefill
traces once per chunk shape instead of once per (bucket, cached-length)
pair.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.inference.engine import (
    SamplingParams, init_kv_cache, mask_padded_vocab,
)
from megatronapp_tpu.inference.paged_cache import (
    HostSpillTier, PagedKVCache, cdiv,
)
from megatronapp_tpu.models.gpt import gpt_embed, gpt_head, gpt_rope_tables
from megatronapp_tpu.trace.request_trace import get_request_tracer
from megatronapp_tpu.transformer.block import layer_forward
from megatronapp_tpu.utils import chaos
from megatronapp_tpu.utils import metrics as telemetry

logger = logging.getLogger(__name__)


class DeadlineExceeded(RuntimeError):
    """A request's deadline passed: rejected at admission, or aborted
    mid-flight by the engine/stepper (its pool blocks are reclaimed on
    the retire path like any finished request)."""


def validate_admission(prompt_tokens, max_new_tokens: int,
                       max_seq_len: int, pool=None,
                       deadline_s=None) -> np.ndarray:
    """Shared admission validation (single source of truth for the
    plain engine AND the disaggregated coordinator — the two must
    accept/reject identically): deadline, non-empty prompt, sequence
    bound, and pool-capacity bound. Returns the normalized int32
    prompt."""
    import time as _time
    if deadline_s is not None and _time.monotonic() >= deadline_s:
        raise DeadlineExceeded(
            "request deadline already expired at admission")
    prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
    if len(prompt) == 0:
        raise ValueError(
            "empty prompt: prefill samples the first token from the "
            "last PROMPT position, so at least one token (e.g. BOS/"
            "eod) is required")
    if len(prompt) + max_new_tokens > max_seq_len:
        raise ValueError(
            f"prompt({len(prompt)}) + max_new({max_new_tokens}) exceeds "
            f"max_seq_len({max_seq_len})")
    if pool is not None:
        need = cdiv(len(prompt) + max_new_tokens, pool.block_size)
        if need > pool.num_blocks:
            raise ValueError(
                f"request needs {need} blocks "
                f"(prompt {len(prompt)} + max_new {max_new_tokens} at "
                f"block_size {pool.block_size}) but the pool has "
                f"only {pool.num_blocks}")
    return prompt


@dataclasses.dataclass
class Request:
    """One generation request (reference inference_request.py analogue).

    priority: lower = more important; the paged backend preempts the
    highest (priority, request_id) running request when the block pool
    is exhausted.

    deadline_s: absolute time.monotonic() deadline; overdue requests are
    aborted by step()'s expiry sweep (event key "expired") and their
    cache/pool resources reclaimed.

    adapter_id/tenant: multi-tenant LoRA serving (inference/lora.py,
    ISSUE 19) — adapter_id names the tenant's low-rank adapter in the
    engine's AdapterCache registry (None = the base model); tenant is a
    free-form accounting label for per-tenant telemetry/SLO classes.
    Both ride the Request itself, so fleet migration carries them and a
    migrated stream stays token-exact under the same adapter."""
    request_id: int
    prompt: np.ndarray                  # [P] int32
    max_new_tokens: int
    sampling: SamplingParams
    eod_id: Optional[int] = None
    priority: int = 0
    deadline_s: Optional[float] = None
    adapter_id: Optional[str] = None
    tenant: Optional[str] = None
    # Filled by the engine:
    slot: int = -1
    generated: list = dataclasses.field(default_factory=list)
    finished: bool = False
    # Wall-clock admission time (time.monotonic()) — time-to-first-token
    # telemetry measures from here (first admission only; a preempted
    # request's resume is not a first token).
    admit_t: float = 0.0
    # When the request last ENTERED a queue (admission or re-queue after
    # preemption/rollback) — queue-wait telemetry measures from here, so
    # a resumed request's second wait doesn't include its first life.
    queued_t: float = 0.0
    # Speculative-decoding stats (spec_method engines):
    spec_proposed: int = 0
    spec_accepted: int = 0

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])


def _decode_step(params, tokens, cache, lengths, active,
                 cfg: TransformerConfig):
    """One-token decode for every slot (dense backend).

    tokens [B,1] (last token per slot), cache [L,B,Smax,...], lengths [B]
    (tokens already in cache per slot), active [B] bool. Returns
    (last_logits [B,V], new_cache)."""
    b = tokens.shape[0]
    max_len = cache[0].shape[2]
    h = gpt_embed(params, tokens, cfg, position_ids=lengths[:, None])
    cos_full, sin_full = gpt_rope_tables(cfg, max_len)
    if cos_full is not None:
        cos = jnp.take(cos_full, lengths, axis=0)[:, None]   # [B,1,half]
        sin = jnp.take(sin_full, lengths, axis=0)[:, None]
    else:
        cos = sin = None

    # Per-row causality: the new token (position lengths[b]) may attend
    # cache positions <= lengths[b]; inactive rows are fully masked except
    # self (keeps the softmax finite; results are discarded).
    kv_pos = jnp.arange(max_len)
    attend = kv_pos[None, :] <= lengths[:, None]             # [B,Smax]
    mask = attend[:, None, None, :]                          # [B,1,1,Smax]

    ck, cv = cache

    def body(carry, layer_in):
        hh = carry
        layer_p, k_l, v_l, lid = layer_in
        (hh, new_cache), _ = layer_forward(
            layer_p, hh, cfg, cos, sin, mask, layer_id=lid,
            kv_cache=(k_l, v_l), cache_index=None,
            cache_positions=lengths)
        return hh, new_cache

    h, new_caches = jax.lax.scan(
        body, h, (params["block"], ck, cv, jnp.arange(cfg.num_layers)),
        unroll=cfg.scan_unroll)
    logits = gpt_head(params, h, cfg)[:, -1]
    return logits, new_caches


def _paged_decode_step(params, tokens, pages, page_table, lengths, active,
                       cfg: TransformerConfig, max_seq_len: int, ctx=None,
                       scales=None, fused: bool = False, lora=None):
    """One-token decode for every slot against the paged block pool.

    pages: ([L, NB, bs, Hkv, D], same) K/V pools (MLA: latent + k_pe
    pools); page_table [B, max_blocks_per_seq] int32; lengths [B] append
    positions; active [B] bool (inactive rows' writes are dropped and
    their outputs discarded). scales: ([L, NB, bs, Hkv] fp32, same) for
    an int8 pool — the step then quantizes the appended rows in-jit and
    returns the updated scale pools alongside. fused: megakernel layer
    body (ISSUE 11) — each scanned layer runs the fused Pallas kernels
    of kernel_gen.fused_layer_decode instead of the unfused op tail
    (callers gate on megakernel_ineligible_reason; streams token-exact).
    lora: batched adapter deltas (inference/lora.py) — {"row_adapter":
    [B] int32 bank slots, "banks": {target: (a [L, slots, din, r],
    b [L, slots, r, dout])}}; the banks join the layer scan's xs (the
    leading L dim slices per layer) and each projection matmul grows a
    per-row low-rank delta (slot 0 = the all-zero null adapter, so the
    trace is identical whether or not any row has a real adapter).
    The layer scan honors cfg.scan_unroll (PERF lever 3: unrolling
    removes the while-loop dispatch overhead and lets XLA fuse across
    layer boundaries). Returns (last_logits [B,V], new pages[, new
    scales] as one stacked tuple)."""
    h = gpt_embed(params, tokens, cfg, position_ids=lengths[:, None])
    cos_full, sin_full = gpt_rope_tables(cfg, max_seq_len)
    if cos_full is not None:
        cos = jnp.take(cos_full, lengths, axis=0)[:, None]
        sin = jnp.take(sin_full, lengths, axis=0)[:, None]
    else:
        cos = sin = None

    # The ragged kernels mask by per-row kv length themselves (MLA
    # included since ISSUE 17 — the latent kernel attends through the
    # page table, no dense gather and no host-built mask).
    mask = None

    pa, pb = pages
    lids = jnp.arange(cfg.num_layers)

    # xs layout: block params, kv pools, [kv scale pools,] [lora factor
    # banks (a, b per target, sorted),] layer ids. The body re-parses by
    # the same flags so one body covers all four pool/lora combinations.
    xs = [params["block"], pa, pb]
    if scales is not None:
        xs += list(scales)
    lora_targets = tuple(sorted(lora["banks"])) if lora is not None else ()
    for t in lora_targets:
        xs += [lora["banks"][t][0], lora["banks"][t][1]]
    xs.append(lids)

    def body(carry, layer_in):
        hh = carry
        it = iter(layer_in)
        layer_p, a_l, b_l = next(it), next(it), next(it)
        kvs = (next(it), next(it)) if scales is not None else None
        ll = None
        if lora is not None:
            ll = {"row_adapter": lora["row_adapter"],
                  "banks": {t: (next(it), next(it))
                            for t in lora_targets}}
        lid = next(it)
        (hh, new_cache), _ = layer_forward(
            layer_p, hh, cfg, cos, sin, mask, layer_id=lid,
            kv_cache=(a_l, b_l), cache_index=None,
            cache_positions=lengths, page_table=page_table,
            active=active, ctx=ctx, kv_scales=kvs,
            fused_decode=fused, lora=ll)
        return hh, new_cache

    h, new_pages = jax.lax.scan(body, h, tuple(xs),
                                unroll=cfg.scan_unroll)
    logits = gpt_head(params, h, cfg)[:, -1]
    return logits, new_pages


def _paged_multiquery_step(params, tokens, pages, page_table, starts,
                           q_lens, active, cfg: TransformerConfig,
                           max_seq_len: int, ctx=None, scales=None,
                           fused: bool = False, lora=None):
    """Ragged multi-token step against the paged pool — the UNIFIED
    prefill/decode primitive (speculative verify + chunked prefill).

    tokens [B, S]; starts [B] per-row append positions; q_lens [B] valid
    token counts in [1, S] (rows past a row's count are padding whose
    outputs are garbage); active [B] bool. Row b's token i lands at
    position starts[b] + i and attends the paged context plus the new
    tail causally. Returns (logits [B, S, V], hidden [B, S, H] pre-head,
    new pages) — hidden feeds the MTP self-draft proposer. fused: run
    each layer as kernel_gen.fused_layer_multiquery (megakernel verify/
    chunked-prefill; callers gate on megakernel_ineligible_reason)."""
    b, s = tokens.shape
    positions = starts[:, None] + jnp.arange(s)[None, :]       # [B, S]
    positions = jnp.minimum(positions, max_seq_len - 1)
    h = gpt_embed(params, tokens, cfg, position_ids=positions)
    cos_full, sin_full = gpt_rope_tables(cfg, max_seq_len)
    if cos_full is not None:
        cos = jnp.take(cos_full, positions, axis=0)            # [B,S,half]
        sin = jnp.take(sin_full, positions, axis=0)
    else:
        cos = sin = None

    # The multi-query ragged kernels mask themselves (MLA included since
    # ISSUE 17 — the latent kernel's scalar-prefetched q_lens carries
    # the causal tail mask).
    mask = None

    pa, pb = pages
    lids = jnp.arange(cfg.num_layers)

    # Same xs layout as _paged_decode_step: optional scale pools then
    # optional lora factor banks, parsed back by the closed-over flags.
    xs = [params["block"], pa, pb]
    if scales is not None:
        xs += list(scales)
    lora_targets = tuple(sorted(lora["banks"])) if lora is not None else ()
    for t in lora_targets:
        xs += [lora["banks"][t][0], lora["banks"][t][1]]
    xs.append(lids)

    def body(carry, layer_in):
        hh = carry
        it = iter(layer_in)
        layer_p, a_l, b_l = next(it), next(it), next(it)
        kvs = (next(it), next(it)) if scales is not None else None
        ll = None
        if lora is not None:
            ll = {"row_adapter": lora["row_adapter"],
                  "banks": {t: (next(it), next(it))
                            for t in lora_targets}}
        lid = next(it)
        (hh, new_cache), _ = layer_forward(
            layer_p, hh, cfg, cos, sin, mask, layer_id=lid,
            kv_cache=(a_l, b_l), cache_index=None,
            cache_positions=starts, page_table=page_table,
            active=active, chunk_counts=q_lens, ctx=ctx,
            kv_scales=kvs, fused_decode=fused, lora=ll)
        return hh, new_cache

    h, new_pages = jax.lax.scan(body, h, tuple(xs),
                                unroll=cfg.scan_unroll)
    logits = gpt_head(params, h, cfg)
    return logits, h, new_pages


def _request_keys(seeds, rids, steps):
    """Per-row PRNG keys: PRNGKey(seed) ∘ fold_in(request_id) ∘
    fold_in(step). The previous additive scheme
    (seed + step*7919 + request_id) collided across requests/steps —
    e.g. (rid, step) and (rid + 7919, step - 1) shared a key."""
    def one(s, r, t):
        return jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(s), r), t)
    return jax.vmap(one)(seeds, rids, steps)


def _warp_logits(logits, temps, top_ks, top_ps):
    """Per-row temperature → top-k → top-p filtering ([N, V] → [N, V],
    filtered entries at -1e30). Single source of truth for the sampling
    semantics: `_sample_batched` (plain decode) and the speculative
    rejection-sampling verifier (inference/speculative.py) both warp
    through here, so speculation preserves the target distribution wrt
    the EXACT sampler plain decode uses."""
    v = logits.shape[-1]
    x = logits / jnp.maximum(temps[:, None], 1e-6)
    sorted_desc = jnp.sort(x, axis=-1)[:, ::-1]
    k_idx = jnp.clip(top_ks - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    x = jnp.where((top_ks[:, None] > 0) & (x < kth), -1e30, x)
    sorted2 = jnp.sort(x, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted2, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < top_ps[:, None], axis=-1)
    cutoff = jnp.take_along_axis(sorted2, cutoff_idx[:, None], axis=-1)
    return jnp.where((top_ps[:, None] > 0.0) & (x < cutoff), -1e30, x)


def _sample_batched(logits, seeds, rids, steps, temps, top_ks, top_ps,
                    greedys):
    """Batched on-device sampling, one jit for all slots (replaces the
    per-request device_get loop). Per-row params; rows mirror
    engine.sample_logits semantics exactly: temperature → top-k →
    top-p → categorical, greedy bypasses all. logits [B,V] → [B]."""
    keys = _request_keys(seeds, rids, steps)
    x = _warp_logits(logits, temps, top_ks, top_ps)
    sampled = jax.vmap(jax.random.categorical)(keys, x)
    return jnp.where(greedys, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)


class DynamicInferenceEngine:
    """Continuous-batching engine (reference DynamicInferenceEngine).

    add_request() any time; step() decodes one token for every active
    request and admits waiting requests into free slots. Finished requests
    surface through the returned events and the optional token_callback.

    paged=True switches to the block-pool backend (see module docstring):
    block_size/num_blocks size the pool (num_blocks defaults to dense
    capacity — pass less to run oversubscribed with preemption), and
    enable_prefix_caching turns shared-prefix block reuse on/off.

    spec_method ("draft"/"mtp"/"ngram", paged only) turns on speculative
    decoding with up to spec_k drafts per round (see module docstring);
    "draft" additionally needs draft_params/draft_cfg (a small model
    sharing the target vocab, e.g. from models/presets.py). When the
    requested proposer is unavailable (no MTP heads, no draft model) the
    engine warns and falls back to plain decode.
    """

    def __init__(self, params, cfg: TransformerConfig, tokenizer=None,
                 max_batch: int = 4, max_seq_len: Optional[int] = None,
                 prefill_buckets: Tuple[int, ...] = (32, 128, 512),
                 paged: bool = False, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 enable_prefix_caching: bool = True,
                 spec_method: Optional[str] = None, spec_k: int = 4,
                 draft_params=None, draft_cfg=None,
                 prefill_chunk: int = 32, ctx=None, pool=None,
                 kv_cache_dtype: str = "bf16",
                 fused_decode: bool = False,
                 adapter_cache=None,
                 spill_host_mb: float = 0.0,
                 spill_watermark_blocks: int = 0):
        self.params = params
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len or cfg.max_position_embeddings
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= self.max_seq_len
        ) or (self.max_seq_len,)
        self.prefill_chunk = min(prefill_chunk, self.max_seq_len)
        # Rolling reload (DynamicBatchingDriver.request_reload): while
        # True, _admit leaves the waiting queue untouched so running
        # requests drain and the params swap lands on an empty batch.
        self.pause_admission = False

        self.paged = paged
        if paged:
            # An injected pool (disagg) carries its own kv_cache_dtype.
            self.pool = pool if pool is not None else PagedKVCache(
                cfg, max_batch, self.max_seq_len, num_blocks=num_blocks,
                block_size=block_size,
                enable_prefix_caching=enable_prefix_caching,
                kv_cache_dtype=kv_cache_dtype)
            self.cache = None
        else:
            assert pool is None, "pool injection requires paged=True"
            from megatronapp_tpu.inference.paged_cache import (
                validate_kv_cache_dtype,
            )
            validate_kv_cache_dtype(kv_cache_dtype, paged=False,
                                    mla=cfg.multi_latent_attention)
            self.pool = None
            self.cache = init_kv_cache(cfg, max_batch, self.max_seq_len)

        # TP serving mesh (ISSUE 9): with a MeshContext whose tp > 1 and
        # a tp-eligible paged config, params replicate over the mesh and
        # the pool pages shard on their Hkv dim — the one-jit-per-step
        # then runs the paged kernels head-sharded (per-shard KV pools,
        # replicated page tables; see ops/pallas/paged_attention.py).
        self.ctx = ctx
        self.tp_paged = False
        if ctx is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            # manual-ok: engine construction runs outside any manual
            # region — mesh-level placement of params/pool is GSPMD by
            # design here.
            self._params_sharding = NamedSharding(ctx.mesh, P())
            self.params = jax.device_put(params, self._params_sharding)  # manual-ok: see above
            if paged:
                from megatronapp_tpu.config.parallel_config import TP_AXIS
                from megatronapp_tpu.ops.pallas.paged_attention import (
                    tp_paged_ineligible_reason,
                )
                reason = tp_paged_ineligible_reason(cfg, ctx)
                self.tp_paged = reason is None
                if not self.tp_paged and ctx.tp > 1:
                    # Name the SPECIFIC failed predicate instead of a
                    # generic ineligible-fallback line (ISSUE 11
                    # satellite).
                    logger.warning(
                        "paged kernels stay single-device on a tp=%d "
                        "mesh: %s", ctx.tp, reason)
                # Pages [L, NB, bs, Hkv, D]: shard Hkv when eligible so
                # each device holds 1/tp of the pool; otherwise just
                # commit them to this mesh (disagg decode sub-mesh). An
                # int8 pool's scale pools [L, NB, bs, Hkv] shard on the
                # same Hkv dim (their last). MLA pools are rank-4 with
                # no head axis — the latent pool [L, NB, bs, klat]
                # shards on its COLUMN dim (kernel_gen._tp_place_latent
                # contracts per-shard columns and psums the logits), the
                # tiny pe pool and the per-row scalar scale pools
                # replicate.
                if not self.tp_paged:
                    pages_spec = scales_spec = P()
                elif cfg.multi_latent_attention:
                    pages_spec = [P(None, None, None, TP_AXIS), P()]
                    scales_spec = P()
                else:
                    pages_spec = P(None, None, None, TP_AXIS, None)
                    scales_spec = P(None, None, None, TP_AXIS)

                def _sh(spec):
                    if isinstance(spec, list):
                        # manual-ok: constructor-time placement, no manual region
                        return [NamedSharding(ctx.mesh, s) for s in spec]
                    return NamedSharding(ctx.mesh, spec)  # manual-ok: see above

                # manual-ok: constructor-time placement, no manual region
                self.pool.place_pages(
                    _sh(pages_spec),    # manual-ok: see above
                    _sh(scales_spec))   # manual-ok: see above
            else:
                # manual-ok: constructor-time placement, no manual region
                self.cache = jax.device_put(self.cache,
                                            self._params_sharding)
        else:
            self._params_sharding = None
        # Telemetry (ISSUE 12): per-request lifecycle spans go to the
        # singleton ring tracer (every call is one enabled check when
        # tracing is off); counters/histograms to utils/metrics.
        self._rt = get_request_tracer()
        self._last_round_t: Optional[float] = None
        # Private always-on decode-interval histogram (the disagg
        # coordinator keeps the same) — the PER-REPLICA SLO signal the
        # fleet router scores off (inference/fleet.py): the router's
        # own round timing would measure the whole serial fleet round,
        # not this replica's decode cadence. Live even when the global
        # metrics registry is off.
        from megatronapp_tpu.utils.metrics import Histogram
        self.interval_hist = Histogram(lo=1e-2, hi=1e6, growth=1.25)
        # Multi-tenant LoRA serving (inference/lora.py, ISSUE 19):
        # adapter_cache is an AdapterCache pinning each running slot's
        # low-rank factors resident in HBM banks. row_adapter maps each
        # engine slot to its adapter's BANK slot (0 = the permanent
        # all-zero null adapter, so the step trace is identical whether
        # or not any row carries a real adapter). Acquire/release rides
        # the slot lifecycle: _admit acquires, _free_slot releases — an
        # in-use adapter can never be evicted.
        self.adapters = adapter_cache
        if adapter_cache is not None and not paged:
            raise ValueError(
                "adapter_cache requires the paged backend (batched LoRA "
                "serves over the paged decode step) — pass paged=True")
        self.row_adapter = np.zeros((max_batch,), np.int32)
        # Optional lora.TenantSLO: the serving driver composes each
        # submit's (priority, deadline) through it when set.
        self.tenant_slo = None
        # Per-tenant serving counters (bounded cardinality: at most
        # _TENANT_LABEL_CAP distinct tenants get their own label; the
        # rest fold into "_other" — same discipline as the fleet's
        # per-replica /metrics labels).
        self._tenant_stats: Dict[str, Dict[str, int]] = {}
        self.lengths = np.zeros((max_batch,), np.int32)
        self.last_tokens = np.zeros((max_batch, 1), np.int32)
        self.slots: List[Optional[Request]] = [None] * max_batch
        self.waiting: deque = deque()
        self.requests: Dict[int, Request] = {}
        self._aborted: List[Request] = []   # aborted mid-admission
        self._ids = itertools.count()

        # Host-RAM KV spill tier (ISSUE 20): parked sessions hold their
        # written KV as export_slot payloads in host memory instead of
        # pool blocks — resume imports the bytes back (copy-exact, so
        # the stream continues token-exact) rather than re-prefilling
        # like a preemption. _parked maps rid -> payload in FIFO
        # (= unpark) order; _held rids stay parked until the client
        # asks for the next token (resume_request); _no_repark guards
        # one step's unparked sessions from bouncing straight back out.
        self.spill: Optional[HostSpillTier] = None
        self.spill_watermark = int(spill_watermark_blocks)
        if spill_host_mb:
            if not paged:
                raise ValueError(
                    "spill_host_mb requires the paged backend (the "
                    "spill tier parks pool blocks) — pass paged=True / "
                    "--paged-kv-cache")
            self.spill = HostSpillTier(int(spill_host_mb * (1 << 20)))
        elif spill_watermark_blocks:
            raise ValueError(
                "spill_watermark_blocks without a spill budget does "
                "nothing — set spill_host_mb / --kv-spill-host-mb too")
        self._parked: "OrderedDict[int, dict]" = OrderedDict()
        self._held: set = set()
        self._no_repark: set = set()

        # Speculative decoding (inference/speculative.py).
        self.spec_method: Optional[str] = None
        self.spec_k = int(spec_k)
        self.proposer = None
        self.spec_stats = {"rounds": 0, "proposed": 0, "accepted": 0,
                           "emitted_tokens": 0, "model_steps": 0}
        # Pre-head hidden state at each slot's last verified position —
        # feeds the MTP self-draft proposer.
        self._h_last = np.zeros((max_batch, cfg.hidden_size), np.float32)
        self._h_valid = np.zeros((max_batch,), bool)
        if spec_method and spec_method != "none":
            if not paged:
                raise ValueError(
                    "speculative decoding runs over the paged-KV engine "
                    "(multi-token append + rollback need the block pool) "
                    "— pass paged=True")
            from megatronapp_tpu.inference.speculative import make_proposer
            self.proposer = make_proposer(spec_method, self,
                                          draft_params=draft_params,
                                          draft_cfg=draft_cfg)
            if self.proposer is not None:
                self.spec_method = spec_method

        # Megakernel decode (ISSUE 11): requested via fused_decode=True /
        # --megakernel-decode; eligibility is re-checked on every jit
        # build (reset_compilation re-gates after MegaScope hook
        # toggles). Ineligible requests keep the unfused step with a
        # loud log naming the SPECIFIC failed predicate.
        self._fused_requested = bool(fused_decode)
        self.megakernel = False
        if fused_decode and not paged:
            raise ValueError(
                "fused_decode=True requires the paged backend (the "
                "fused step is built around the paged-attention "
                "kernel) — pass paged=True / --paged-kv-cache")

        # Trace counter for the unified multi-query step (chunked prefill
        # + speculative verify): increments ONLY when jax re-traces, so
        # tests can assert chunked prefill stops retracing per
        # (bucket, cached-length) pair. decode_traces mirrors it for the
        # plain decode step (the /stats jit-count satellite).
        self.mq_traces = 0
        self.decode_traces = 0
        # Compiled decode-step dispatch accounting, cached per jit build
        # (utils/dispatch.py; computed lazily — it costs one AOT
        # compile at the engine's shapes).
        self._dispatch_stats = None
        self._build_jits()

    def _build_jits(self):
        cfg = self.cfg
        import functools

        from megatronapp_tpu.inference.engine import _forward_with_cache
        self._prefill = jax.jit(
            functools.partial(_forward_with_cache, cfg=cfg))
        self._sample_b = jax.jit(_sample_batched)
        self._dispatch_stats = None
        if self.paged:
            msl = self.max_seq_len
            # ctx rides into the step only on a tp-paged mesh (it then
            # dispatches the head-sharded kernel placement inside
            # attention_forward); otherwise the trace stays identical to
            # the single-device engine.
            step_ctx = self.ctx if self.tp_paged else None
            # Megakernel decode eligibility (re-checked per build so
            # MegaScope hook toggles + reset_compilation re-gate it).
            self.megakernel = False
            if self._fused_requested:
                from megatronapp_tpu.ops.pallas.kernel_gen import (
                    megakernel_ineligible_reason,
                )
                # Tile plans are sized for the widest flattened row
                # count any fused step sees: decode runs [B, 1],
                # chunked prefill [1, prefill_chunk], speculative
                # verify [B, K+1] — the mq rows flatten to B·S.
                mq_rows = max(
                    self.max_batch, self.prefill_chunk,
                    self.max_batch * (self.spec_k + 1)
                    if self.spec_method else 0)
                reason = megakernel_ineligible_reason(
                    cfg, batch=self.max_batch, tp_paged=self.tp_paged,
                    params=self.params, mq_rows=mq_rows,
                    lora_rank=(self.adapters.rank
                               if self.adapters is not None else None))
                if reason is None:
                    self.megakernel = True
                else:
                    logger.warning(
                        "megakernel decode requested but ineligible — "
                        "keeping the unfused decode step: %s", reason)
            fused = self.megakernel

            # `scales` is the int8 pool's fp32 scale-pool pair (None for
            # bf16 pools — an empty pytree, so the same jit signature
            # serves both dtypes and donation is a no-op there). `lora`
            # follows the same trick: None without an adapter cache,
            # else {"row_adapter", "banks"} (the banks are NOT donated —
            # they are the cache's resident HBM arrays and outlive the
            # step).
            def _decode_traced(p, t, pages, scales, tbl, l, a, lora):
                # Python side-effect: runs only while TRACING.
                self.decode_traces += 1
                return _paged_decode_step(p, t, pages, tbl, l, a, cfg,
                                          msl, ctx=step_ctx,
                                          scales=scales, fused=fused,
                                          lora=lora)

            self._decode = jax.jit(_decode_traced, donate_argnums=(2, 3))

            def _mq_traced(p, t, pages, scales, tbl, starts, qlens, act,
                           lora):
                # Python side-effect: runs only while TRACING.
                self.mq_traces += 1
                return _paged_multiquery_step(p, t, pages, tbl, starts,
                                              qlens, act, cfg, msl,
                                              ctx=step_ctx, scales=scales,
                                              fused=fused, lora=lora)

            self._mq_step = jax.jit(_mq_traced, donate_argnums=(2, 3))
            if self.spec_method:
                from megatronapp_tpu.inference.speculative import (
                    build_verify_sampler,
                )
                self._verify_sample = build_verify_sampler(
                    point_mass=self.proposer.point_mass)
                self.proposer.reset_compilation()
        else:
            def _decode_traced_dense(p, t, c, l, a):
                self.decode_traces += 1
                return _decode_step(p, t, c, l, a, cfg)

            self._decode = jax.jit(_decode_traced_dense)

    def reset_compilation(self):
        """Re-trace on next call (after MegaScope hook toggles — see
        StaticInferenceEngine.reset_compilation). Rebuilds the paged
        decode/scatter/gather jits too, so toggled capture hooks cannot
        pin stale traces in the paged backend."""
        self._build_jits()

    def _commit_pools(self, new):
        """Install a step's updated pool arrays: bf16 pools return
        (k, v); int8 pools return (k, v, k_scales, v_scales) — the scale
        pools updated by the in-jit quantize ride the same scan."""
        if self.pool.quantized:
            self.pool.pages = tuple(new[:2])
            self.pool.scales = tuple(new[2:])
        else:
            self.pool.pages = tuple(new)

    # Bounded per-tenant label cardinality (/metrics + /stats): beyond
    # this many distinct tenants, new ones fold into "_other".
    _TENANT_LABEL_CAP = 32

    def _tenant_label(self, tenant: Optional[str]) -> Optional[str]:
        if tenant is None:
            return None
        if tenant in self._tenant_stats:
            return tenant
        if len(self._tenant_stats) >= self._TENANT_LABEL_CAP:
            return "_other"
        return tenant

    def _tenant_inc(self, tenant: Optional[str], key: str, n: int = 1):
        """Per-tenant serving counters, mirrored to labeled /metrics
        counters at bounded cardinality."""
        label = self._tenant_label(tenant)
        if label is None:
            return
        st = self._tenant_stats.setdefault(
            label, {"requests": 0, "tokens": 0, "finished": 0,
                    "expired": 0})
        st[key] = st.get(key, 0) + n
        telemetry.inc(telemetry.labeled(f"serving_tenant_{key}",
                                        tenant=label), n)

    def _lora_args(self, rows: Optional[np.ndarray] = None):
        """The step jits' `lora` operand: None without an adapter cache
        (an empty pytree — same jit signature), else the per-slot bank
        slots + the cache's resident factor banks. `rows` overrides the
        full per-slot map for single-row calls (chunked prefill)."""
        if self.adapters is None:
            return None
        if rows is None:
            rows = self.row_adapter
        return {"row_adapter": jnp.asarray(np.asarray(rows, np.int32)),
                "banks": self.adapters.banks}

    # ---- request lifecycle ------------------------------------------------
    def add_request(self, prompt_tokens, max_new_tokens: int,
                    sampling: Optional[SamplingParams] = None,
                    eod_id: Optional[int] = None,
                    priority: int = 0,
                    deadline_s: Optional[float] = None,
                    request_id: Optional[int] = None,
                    adapter_id: Optional[str] = None,
                    tenant: Optional[str] = None) -> int:
        prompt = validate_admission(prompt_tokens, max_new_tokens,
                                    self.max_seq_len,
                                    pool=self.pool if self.paged else None,
                                    deadline_s=deadline_s)
        # Unknown adapters are a PERMANENT submit-time error (the
        # registry names what it knows) — transient all-slots-pinned
        # pressure is handled at admission instead.
        if adapter_id is not None:
            if self.adapters is None:
                raise ValueError(
                    "adapter_id requires an engine adapter cache — "
                    "construct with adapter_cache= / --lora-dir")
            if adapter_id not in self.adapters.registry:
                raise KeyError(
                    f"unknown adapter {adapter_id!r}; known: "
                    f"{sorted(self.adapters.registry.ids())}")
        now = time.monotonic()
        # An explicit request_id is the cross-process fleet's admission
        # shape (inference/fleet_rpc.py): the ROUTER owns the one rid
        # space spanning every replica worker, so the engine must accept
        # a caller-minted id — the sampler's fold_in chain keys off it,
        # which is what makes a stream's tokens placement-independent.
        if request_id is None:
            request_id = next(self._ids)
        elif request_id in self.requests:
            raise ValueError(f"request id {request_id} already admitted")
        req = Request(request_id, prompt, max_new_tokens,
                      sampling or SamplingParams(), eod_id=eod_id,
                      priority=priority, deadline_s=deadline_s,
                      adapter_id=adapter_id, tenant=tenant,
                      admit_t=now, queued_t=now)
        self.waiting.append(req)
        self.requests[req.request_id] = req
        telemetry.inc("serving_requests_admitted")
        self._tenant_inc(tenant, "requests")
        rt = self._rt
        if rt.enabled:
            rt.instant("admit", req.request_id,
                       prompt_tokens=len(prompt), priority=priority)
            rt.begin("request", req.request_id)
            rt.begin("queue-wait", req.request_id)
        return req.request_id

    def pop_request(self, request_id: int) -> Optional[Request]:
        """Remove and return a finished request (server-side consumers)."""
        return self.requests.pop(request_id, None)

    def abort_request(self, request_id: int) -> Optional[str]:
        """Cancel a request. Returns 'waiting' if it was dequeued before
        running (no finish event will fire), 'running' if it was marked
        to retire on the next step, or None if unknown/already done."""
        req = self.requests.get(request_id)
        if req is None:
            return None
        if req in self.waiting:
            try:
                self.waiting.remove(req)
            except ValueError:
                pass    # raced with admission: treat as running below
            else:
                req.finished = True
                self._rt.finish(request_id, "abort")
                return "waiting"
        if not req.finished:
            # Running — or mid-admission on the stepper thread (slot not
            # yet assigned): either way, marking finished retires it on
            # the next step, releasing its cache.
            req.finished = True
            self._rt.instant("abort", request_id)
            return "running"
        return None

    def expire_overdue(self, now: Optional[float] = None) -> List[int]:
        """Abort every request whose deadline passed (per-request SLO
        enforcement): waiting ones leave the queue immediately; running
        ones are marked finished, so the SAME step's retire pass
        releases their slot and pool blocks. Returns the expired request
        ids — step() reports them under events["expired"] so the server
        driver can hand each a clean deadline error frame."""
        import time as _time
        if now is None:
            now = _time.monotonic()
        expired: List[int] = []

        def overdue(r: Request) -> bool:
            return (r.deadline_s is not None and not r.finished
                    and now >= r.deadline_s)

        # Snapshot the waiting deque tolerantly: the sweep runs on the
        # stepper thread while submit() may append concurrently (deque
        # iteration raises RuntimeError on mutation). Expiry is
        # re-checked every step, so skipping one contended sweep is
        # harmless — turning the race into a step failure is not.
        for _ in range(4):
            try:
                overdue_waiting = [r for r in self.waiting if overdue(r)]
                break
            except RuntimeError:
                continue
        else:
            overdue_waiting = []
        for req in overdue_waiting:
            try:
                self.waiting.remove(req)
            except ValueError:
                # cancel()/abort_request on the driver thread removed it
                # between the snapshot and here (same race guard as
                # abort_request) — it is already being retired.
                continue
            req.finished = True
            self._aborted.append(req)    # finish event fires this step
            expired.append(req.request_id)
            self._tenant_inc(req.tenant, "expired")
            self._rt.finish(req.request_id, "expire")
        for req in self.slots:
            if req is not None and overdue(req):
                req.finished = True      # retired (blocks released) below
                expired.append(req.request_id)
                self._tenant_inc(req.tenant, "expired")
                # Spans close when the same step's retire pass reclaims
                # the slot (the one finish funnel).
                self._rt.instant("expire", req.request_id)
        for rid in list(self._parked):
            req = self._parked[rid]["req"]
            if overdue(req):
                # Parked sessions hold no slot — marking finished lets
                # the SAME step's _spill_policy sweep drop the spill
                # entry and fire the finished event.
                req.finished = True
                expired.append(req.request_id)
                self._tenant_inc(req.tenant, "expired")
                self._rt.instant("expire", req.request_id)
        if expired:
            telemetry.inc("serving_deadline_expired", len(expired))
        return expired

    def abort_all(self):
        """Drop ALL queued and running requests (server error recovery).

        Paged blocks are released through the pool so capacity is
        reclaimed and the slot bookkeeping stays consistent — clearing
        slots without releasing would trip PagedKVCache.admit's
        slot-still-holds-blocks assert on the next request. Best-effort
        if the failure left pool bookkeeping itself inconsistent."""
        # A crashed round never reached the point that refreshes
        # _last_round_t — without this reset the first post-recovery
        # round would observe the crash + backoff gap as a "token
        # interval" and poison the histogram's tail.
        self._last_round_t = None
        for req in list(self.waiting):
            self.requests.pop(req.request_id, None)
            self._rt.finish(req.request_id, "abort")
        self.waiting.clear()
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            if self.paged:
                try:
                    self.pool.release(slot, np.asarray(req.tokens),
                                      int(self.lengths[slot]))
                except Exception:  # noqa: BLE001 — best-effort reclaim
                    pass
            self._free_slot(slot)
            self.requests.pop(req.request_id, None)
            self._rt.finish(req.request_id, "abort")
        for rid in list(self._parked):
            req = self._parked[rid]["req"]
            self._drop_parked(rid)
            self.requests.pop(req.request_id, None)
            self._rt.finish(req.request_id, "abort")

    def _free_slot(self, slot: int):
        """Clear every per-slot engine resource (request ref, length,
        proposer state, MTP hidden) — the ONE place to extend when a new
        per-slot resource is added; pool blocks are released by the
        caller (release semantics differ per path)."""
        self.slots[slot] = None
        self.lengths[slot] = 0
        self._h_valid[slot] = False
        if self.adapters is not None:
            # Unpin the slot's adapter (slot 0 = null adapter, a no-op);
            # rc==0 residents park in the cache's LRU, still hittable.
            self.adapters.release(int(self.row_adapter[slot]))
            self.row_adapter[slot] = 0
        if self.proposer is not None:
            self.proposer.on_release(slot)

    @property
    def has_work(self) -> bool:
        return (bool(self.waiting) or bool(self._parked)
                or any(r is not None for r in self.slots))

    def set_params(self, params):
        """Install new model params (rolling engine reload). Same pytree
        structure/shapes as the old ones, so every jit trace stays valid
        — the driver drains running requests first and swaps on an empty
        batch, then re-admits the waiting queue against the new
        weights. The prefix cache is flushed: its blocks hold KV from
        the OLD weights."""
        if self._params_sharding is not None:
            # manual-ok: host-side reload path, no manual region
            params = jax.device_put(params, self._params_sharding)
        self.params = params
        if self.pool is not None:
            self.pool.flush_prefix_cache()

    def free_decode_slots(self) -> int:
        return sum(1 for r in self.slots if r is None)

    def drained_for_reload(self) -> bool:
        """True when a rolling params swap is safe: no occupied slots
        (waiting requests keep their position and run on new weights)."""
        return all(r is None for r in self.slots)

    def adopt_request(self, req: Request, src_slot: int, length: int
                      ) -> int:
        """Adopt a prefilled request from the disaggregated prefill side
        (inference/disagg.py): move its pool blocks from staging slot
        `src_slot` into a free decode slot via the pool's page-table
        transfer — NO KV copy — and resume decoding at `length` (the
        prompt KV rows written by prefill; the first generated token was
        already sampled prefill-side with the identical fold_in chain).
        Returns the decode slot."""
        assert self.paged, "adoption requires the paged backend"
        slot = next(i for i in range(self.max_batch)
                    if self.slots[i] is None)
        if self.adapters is not None:
            self.row_adapter[slot] = self.adapters.acquire(req.adapter_id)
        self.pool.transfer_slot(src_slot, slot)
        req.slot = slot
        self.slots[slot] = req
        self.requests[req.request_id] = req
        self.lengths[slot] = length
        self.last_tokens[slot, 0] = req.generated[-1]
        if self.proposer is not None:
            self.proposer.on_admit(slot, req)
        rt = self._rt
        if rt.enabled:
            rt.instant("adopt", req.request_id, slot=slot, length=length)
            rt.begin("decode", req.request_id)
        return slot

    # ---- live session migration (ISSUE 14, inference/fleet.py) -----------
    def export_request(self, rid: int) -> Optional[dict]:
        """READ-ONLY snapshot of a RUNNING request's migratable state:
        the pool's exported KV rows (+ scales, verbatim bytes) plus the
        Request object itself — which carries the sampler fold_in chain
        position (request_id + len(generated)) and every admission
        field, so the destination continues the EXACT stream (greedy
        and sampled alike: the key chain PRNGKey(seed)∘rid∘step never
        references which replica computes the step). Returns None when
        the request is not currently decoding in a slot — waiting /
        mid-prefill requests own no resumable KV and migrate by simple
        requeue instead. Nothing is mutated here: the source rolls
        nothing back if the migration dies between export and import
        (the "fleet-migrate" chaos site)."""
        assert self.paged, "session export requires the paged backend"
        req = self.requests.get(rid)
        if req is not None and not req.finished and rid in self._parked:
            # A PARKED session migrates too (a drained/reloading replica
            # must not strand its parked sessions): the spill payload IS
            # the export_slot snapshot, handed over as-is — read-only
            # here; release_exported drops the spill entry on commit.
            return dict(self._parked[rid])
        if (req is None or req.finished or req.slot < 0
                or self.slots[req.slot] is not req or not req.generated):
            return None
        valid_len = int(self.lengths[req.slot])
        payload = self.pool.export_slot(req.slot, valid_len)
        payload["req"] = req
        return payload

    def import_request(self, payload: dict) -> bool:
        """Install a migrated session from an `export_request` payload:
        the pool scatters the exported rows into fresh blocks
        (copy-exact — see PagedKVCache.import_slot) and the request
        resumes decoding at its exact position. Returns False with the
        destination untouched when no decode slot is free or the pool
        cannot host the rows. The MTP proposer's pre-head hidden is not
        shipped (proposal-quality-only, same note as the disagg adopt
        path); ngram/draft proposers are unaffected."""
        assert self.paged, "session import requires the paged backend"
        req: Request = payload["req"]
        slot = next((i for i in range(self.max_batch)
                     if self.slots[i] is None), None)
        if slot is None:
            return False
        aslot = 0
        if self.adapters is not None:
            from megatronapp_tpu.inference.lora import AdapterSlotsPinned
            try:
                # The adapter ID rides the Request in the payload — the
                # destination re-acquires from ITS registry/cache, so a
                # migrated stream decodes under the same factors
                # (token-exact; drilled in tests).
                aslot = self.adapters.acquire(req.adapter_id)
            except (AdapterSlotsPinned, KeyError):
                # Can't host the adapter here (pinned-full / not in this
                # replica's registry): refuse with nothing touched — the
                # router treats False like a full destination.
                return False
        if not self.pool.import_slot(slot, payload):
            if self.adapters is not None:
                self.adapters.release(aslot)
            return False
        self.row_adapter[slot] = aslot
        valid_len = payload["valid_len"]
        req.slot = slot
        self.slots[slot] = req
        self.requests[req.request_id] = req
        self.lengths[slot] = valid_len
        self.last_tokens[slot, 0] = req.generated[-1]
        # Followers on THIS replica hit the migrated prompt blocks like
        # any locally-prefilled ones.
        self.pool.register_prefix(slot, np.asarray(req.tokens), valid_len)
        if self.proposer is not None:
            self.proposer.on_admit(slot, req)
        self._rt.instant("migrate-in", req.request_id, slot=slot,
                         length=valid_len)
        return True

    def release_exported(self, rid: int):
        """Source-side completion of a migration: the destination has
        imported the KV copy, so this replica's slot releases. The
        prompt prefix registers first (release() does) — the KV stays
        weight-valid, so followers on THIS replica keep hitting it. The
        request itself now lives in the destination engine's books."""
        req = self.requests.pop(rid)
        if rid in self._parked:
            # A PARKED session migrated: its KV never re-entered this
            # pool (export handed the spill payload over verbatim), so
            # completion just drops the spill entry. Not an unpark —
            # the session resumes on the destination, not here.
            self._drop_parked(rid)
            self._rt.instant("migrate-out", rid, slot=-1)
            return
        # req.slot already points at the DESTINATION slot (import set
        # it) — find the source slot by identity.
        slot = next(i for i, r in enumerate(self.slots) if r is req)
        self.pool.release(slot, np.asarray(req.tokens),
                          int(self.lengths[slot]))
        self._free_slot(slot)
        self._rt.instant("migrate-out", rid, slot=slot)

    # ---- host-RAM KV spill tier (ISSUE 20) -------------------------------
    def _park(self, req: Request, hold: bool = False) -> bool:
        """Move a RUNNING request's written KV to the host spill tier
        and release its slot + pool blocks. The copy is the SAME
        export_slot payload a migration ships (verbatim stored rows +
        scales), so the resume path (_unpark → import_slot) restores
        the pool bytes exactly and the stream continues token-exact for
        every KV dtype — unlike preemption, which re-prefills. Returns
        False with NOTHING mutated when the session is not parkable or
        the tier's byte budget refuses the payload (the caller falls
        back to preemption). `hold` marks a client-requested park
        (tools/loadgen.py long-idle phases): the session stays parked
        until resume_request, excluded from the auto-unpark pass."""
        if (self.spill is None or req.finished or req.slot < 0
                or self.slots[req.slot] is not req or not req.generated):
            return False
        rid = req.request_id
        slot = req.slot
        valid_len = int(self.lengths[slot])
        payload = self.pool.export_slot(slot, valid_len)   # read-only
        if not self.spill.would_fit(payload["nbytes"]):
            self.spill.counters["rejects"] += 1
            return False
        # Chaos site "kv-spill" (park window): fires between the
        # read-only host copy above and the page-table release below —
        # nothing has mutated yet, so the rollback is "do nothing": the
        # session keeps decoding in its slot, audit() passes, and the
        # stream is unaffected (tests/test_resilience.py drill).
        chaos.fire("kv-spill")
        payload["req"] = req
        assert self.spill.put(rid, payload)     # would_fit checked above
        # Not preempted=True: full blocks stay prefix-cached while
        # evictable (same as a retirement) and the preemption counters
        # keep meaning "KV thrown away", which a park is not.
        self.pool.release(slot, np.asarray(req.tokens), valid_len)
        self._free_slot(slot)
        req.slot = -1
        self._parked[rid] = payload
        if hold:
            self._held.add(rid)
        rt = self._rt
        if rt.enabled:
            rt.end("decode", rid)
            rt.instant("park", rid, bytes=payload["nbytes"])
        return True

    def _unpark(self, rid: int) -> bool:
        """Re-enter a parked session through the pool (import_slot) so
        the next decode step continues its stream token-exact. Returns
        False with the session STILL PARKED (and the pool untouched)
        when no slot is free, the adapter bank is pinned full, or the
        pool cannot host the rows right now — the policy retries next
        step."""
        payload = self._parked.get(rid)
        if payload is None:
            return False
        req: Request = payload["req"]
        slot = next((i for i in range(self.max_batch)
                     if self.slots[i] is None), None)
        if slot is None:
            return False
        aslot = 0
        if self.adapters is not None:
            from megatronapp_tpu.inference.lora import AdapterSlotsPinned
            try:
                aslot = self.adapters.acquire(req.adapter_id)
            except AdapterSlotsPinned:
                return False
        if not self.pool.import_slot(slot, payload):
            if self.adapters is not None:
                self.adapters.release(aslot)
            return False
        try:
            # Chaos site "kv-spill" (unpark mirror): fires between the
            # pool import and the spill-entry release — the rollback
            # returns the imported blocks to the pool and the session
            # stays parked (its payload was never dropped), so audit()
            # passes and a later resume is still token-exact.
            chaos.fire("kv-spill")
        except Exception:
            self.pool.release(slot, np.asarray(req.tokens),
                              int(payload["valid_len"]))
            if self.adapters is not None:
                self.adapters.release(aslot)
            raise
        self.row_adapter[slot] = aslot
        valid_len = int(payload["valid_len"])
        req.slot = slot
        self.slots[slot] = req
        self.lengths[slot] = valid_len
        self.last_tokens[slot, 0] = req.generated[-1]
        # Followers hit the resumed prompt blocks like locally-prefilled
        # ones (mirror of import_request).
        self.pool.register_prefix(slot, np.asarray(req.tokens), valid_len)
        if self.proposer is not None:
            self.proposer.on_admit(slot, req)
        self.spill.pop(rid)                       # counts the unpark
        del self._parked[rid]
        self._held.discard(rid)
        self._no_repark.add(rid)   # no park/unpark thrash within a step
        rt = self._rt
        if rt.enabled:
            rt.instant("unpark", rid, slot=slot, length=valid_len)
            rt.begin("decode", rid)
        return True

    def _drop_parked(self, rid: int):
        """Remove a parked session's spill entry WITHOUT counting an
        unpark (aborts, expiry, migration-out): only genuine resumes
        count."""
        self._parked.pop(rid, None)
        self._held.discard(rid)
        if self.spill is not None:
            self.spill.pop(rid, unpark=False)

    def park_request(self, rid: int) -> bool:
        """Client-requested park of a long-idle session (held until
        resume_request). True when the session is parked (or already
        was)."""
        req = self.requests.get(rid)
        if req is None or req.finished:
            return False
        if rid in self._parked:
            self._held.add(rid)
            return True
        return self._park(req, hold=True)

    def resume_request(self, rid: int) -> bool:
        """Unpark-on-next-token: the client wants this session's next
        token, so clear its hold and try to re-enter the pool now (the
        step policy retries if capacity refuses). True when the session
        is known (parked or running)."""
        if rid not in self._parked:
            return rid in self.requests
        self._held.discard(rid)
        self._unpark(rid)     # best-effort now; _spill_policy retries
        return True

    def _park_for_pressure(self) -> bool:
        """Park the lowest-priority running session (same victim order
        as preemption: highest (priority, request_id) first). False when
        nobody is parkable — the caller falls back to preemption."""
        runners = sorted(
            (r for r in self.slots
             if r is not None and not r.finished and r.slot >= 0
             and r.request_id not in self._no_repark),
            key=lambda r: (r.priority, r.request_id))
        for victim in reversed(runners):
            if self._park(victim):
                return True
        return False

    def _spill_policy(self):
        """Per-step spill housekeeping, run after the expiry sweep and
        before admission: (1) drop parked sessions finished by
        abort/expiry so their finished events fire this step; (2)
        auto-unpark (FIFO = park order) the non-held parked sessions
        capacity allows — forced when the engine is otherwise idle so a
        parked session can never stall forever; (3) watermark parking:
        while available_blocks() sits below --kv-spill-watermark-blocks,
        park lowest-priority sessions to keep decode/admission
        headroom."""
        if self.spill is None:
            return
        for rid in list(self._parked):
            req = self._parked[rid]["req"]
            if req.finished:
                self._drop_parked(rid)
                self._aborted.append(req)    # finished event this step
        for rid in [r for r in self._parked if r not in self._held]:
            payload = self._parked[rid]
            need = cdiv(int(payload["valid_len"]) + 1,
                        self.pool.block_size)
            idle = (not self.waiting and
                    all(r is None for r in self.slots))
            if (not idle and self.pool.available_blocks() - need
                    < self.spill_watermark):
                break    # below-watermark unpark would thrash right back
            if not self._unpark(rid):
                break    # no slot / pool full; FIFO — don't skip ahead
        if self.spill_watermark > 0:
            while (self.pool.available_blocks() < self.spill_watermark
                   and self._park_for_pressure()):
                pass

    def _admit(self) -> List[Request]:
        admitted = []
        if self.pause_admission:
            return admitted
        for slot in range(self.max_batch):
            if self.slots[slot] is not None or not self.waiting:
                continue
            # Pop FIRST (re-appended on failure): a peek-then-pop window
            # would race a concurrent abort_request removing the head —
            # popleft would then silently drop the NEXT request.
            req = self.waiting.popleft()
            if req.finished:          # aborted while queued (racy path)
                self._aborted.append(req)
                continue
            plan = None
            if self.paged:
                # Admission by block availability: if the pool cannot
                # host this prompt now, keep FIFO order and wait for
                # retirements/preemptions to free blocks.
                plan = self.pool.admit(slot, req.tokens)
                if plan is None and self.spill is not None:
                    # Pressure path, spill preferred over waiting: park
                    # idle-priority sessions (KV kept byte-exact in host
                    # RAM) until the prompt fits — this is what lifts
                    # concurrent sessions-at-budget past the HBM block
                    # count.
                    while plan is None and self._park_for_pressure():
                        plan = self.pool.admit(slot, req.tokens)
                if plan is None:
                    self.waiting.appendleft(req)
                    break
            if self.adapters is not None:
                from megatronapp_tpu.inference.lora import (
                    AdapterSlotsPinned)
                try:
                    aslot = self.adapters.acquire(req.adapter_id)
                except AdapterSlotsPinned:
                    # Every adapter bank slot is pinned by running
                    # requests — a transient capacity condition exactly
                    # like pool-full admit: keep FIFO order and wait for
                    # a retirement to unpin one.
                    if self.paged:
                        self.pool.release(slot, np.asarray(req.tokens), 0)
                    self.waiting.appendleft(req)
                    break
                except Exception:
                    # Load fault (the "lora-load" chaos drill): the
                    # cache mutated nothing — release the admitted
                    # blocks, requeue at the head, re-raise for the
                    # stepper watchdog. The retry costs one step.
                    if self.paged:
                        self.pool.release(slot, np.asarray(req.tokens), 0)
                    req.queued_t = time.monotonic()
                    self.waiting.appendleft(req)
                    raise
                self.row_adapter[slot] = aslot
            req.slot = slot
            self.slots[slot] = req
            rid = req.request_id
            first_life = not req.generated   # vs resumed after preempt
            self._rt.end("queue-wait", rid)
            telemetry.observe("serving_queue_wait_ms",
                              (time.monotonic() - req.queued_t) * 1e3)
            self._rt.begin("prefill", rid, prompt_tokens=len(req.tokens))
            try:
                self._prefill_into_slot(req, plan)
            except Exception:
                # Exception-safe rollback (the "kv-quant-write" chaos
                # drill fires between quantize and page-table commit in
                # the chunk-scatter path): return every admitted block
                # (valid_len=0 — partially-written rows are stale data
                # the retry overwrites, never registered prefixes),
                # clear the slot, and requeue the request at the head so
                # a transient fault costs one step. Re-raised for the
                # stepper watchdog's accounting.
                if self.paged:
                    self.pool.release(slot, np.asarray(req.tokens), 0)
                self._free_slot(slot)
                req.slot = -1
                req.queued_t = time.monotonic()
                self.waiting.appendleft(req)
                self._rt.end("prefill", rid, error=True)
                self._rt.begin("queue-wait", rid)   # requeued at the head
                raise
            self._rt.end("prefill", rid)
            if first_life:
                # TTFT is a first-token metric: a preempted request's
                # resume prefill emits its Nth token, not its first —
                # re-observing would inflate the percentiles the fleet
                # router scores replicas by.
                telemetry.observe("serving_ttft_ms",
                                  (time.monotonic() - req.admit_t) * 1e3)
            self._rt.begin("decode", rid)
            admitted.append(req)
        return admitted

    def _prefill_into_slot(self, req: Request, plan=None):
        # req.tokens (prompt + any pre-preemption generated tokens): a
        # resumed request re-prefills its full history and samples the
        # NEXT token, exactly like a fresh admission.
        tokens = req.tokens
        p_len = len(tokens)
        if self.paged:
            # Chunked prefill through the unified multi-query step: ONE
            # trace per chunk shape instead of one per
            # (bucket, cached-length) pair, and prefix-cache hits are
            # attended directly through the page table (no dense gather;
            # MLA rides the same path since ISSUE 17 — the latent kernel
            # handles the ragged chunk, and quantized latent rows
            # quantize inside the same _mq_step jit).
            logits_last = self._paged_prefill_chunked(req, tokens, p_len,
                                                      plan)
        else:
            bucket = next((b for b in self.prefill_buckets if b >= p_len),
                          self.max_seq_len)
            if bucket < p_len:
                raise AssertionError(
                    f"no prefill bucket covers length {p_len} (buckets "
                    f"{self.prefill_buckets}, max_seq_len "
                    f"{self.max_seq_len})")
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :p_len] = tokens
            tmp_cache = init_kv_cache(self.cfg, 1, bucket)
            logits, tmp_cache = self._prefill(
                self.params, jnp.asarray(padded), tmp_cache, 0)
            # Scatter the kv rows into this slot of the shared cache.
            slot = req.slot
            self.cache = tuple(
                c.at[:, slot, :bucket].set(t[:, 0]) for c, t in
                zip(self.cache, tmp_cache))
            logits_last = logits[0, p_len - 1]
        self.lengths[req.slot] = p_len
        # First generated token comes from the last PROMPT position.
        logits_last = mask_padded_vocab(logits_last, self.cfg)
        tok = self._sample(logits_last[None], req)
        self._record_token(req, int(tok[0]))
        if self.proposer is not None:
            self.proposer.on_admit(req.slot, req)

    def _paged_prefill_chunked(self, req: Request, tokens, p_len: int,
                               plan) -> jnp.ndarray:
        """Prefill the uncached prompt tail in fixed-size chunks against
        the page table (the ROADMAP chunked-prefill follow-up): each
        chunk is one `_mq_step` call at shape [1, prefill_chunk], so the
        compiler sees ONE program for every (prompt length, cached
        length) combination. Returns the last prompt position's logits
        [V] and records the pre-head hidden for the MTP proposer."""
        assert plan is not None
        slot = req.slot
        pool = self.pool
        cached = plan.cached_tokens
        c = self.prefill_chunk
        table_row = jnp.asarray(pool.page_table[slot][None])     # [1, MB]
        pos, count = cached, 0
        logits = hid = None
        while pos < p_len:
            count = min(c, p_len - pos)
            chunk = np.zeros((1, c), np.int32)
            chunk[0, :count] = tokens[pos:pos + count]
            if pool.quantized:
                # Chaos site "kv-quant-write": fires between staging the
                # chunk and committing its quantized rows + scales to
                # the pool — the admit caller (_admit) rolls the slot's
                # blocks back and requeues the request, so a transient
                # fault costs one step and audit() stays clean (the
                # tests/test_resilience.py drill).
                chaos.fire("kv-quant-write")
            logits, hid, new = self._mq_step(
                self.params, jnp.asarray(chunk), self.pool.pages,
                self.pool.scales,
                table_row, jnp.asarray([pos], jnp.int32),
                jnp.asarray([count], jnp.int32), jnp.ones((1,), bool),
                self._lora_args(rows=self.row_adapter[slot:slot + 1]))
            self._commit_pools(new)
            pos += count
        # Register the prompt's full blocks so concurrent same-prefix
        # requests hit them immediately.
        pool.register_prefix(slot, np.asarray(tokens), p_len)
        if self.proposer is not None and self.proposer.needs_hidden:
            self._h_last[slot] = np.asarray(
                jax.device_get(hid[0, count - 1]), np.float32)
            self._h_valid[slot] = True
        return logits[0, count - 1]

    def _sample(self, logits, req: Request):
        """Single-row sampling (prefill). Same fold_in key chain as the
        batched decode sampler, so a request's sample stream is
        reproducible and independent of batch composition."""
        s = req.sampling
        tok = self._sample_b(
            logits,
            jnp.asarray([s.seed], jnp.int32),
            jnp.asarray([req.request_id], jnp.int32),
            jnp.asarray([len(req.generated)], jnp.int32),
            jnp.asarray([s.temperature], jnp.float32),
            jnp.asarray([s.top_k], jnp.int32),
            jnp.asarray([s.top_p], jnp.float32),
            jnp.asarray([s.greedy], bool))
        return jax.device_get(tok)

    def _sampling_rows(self) -> Dict[str, np.ndarray]:
        """Per-slot sampling parameters + key-chain inputs for every
        non-finished slot (inactive rows keep neutral defaults; their
        outputs are ignored). Single source for the plain sampler, the
        speculative verifier, and the draft proposer — one place to
        thread a future sampling field through."""
        b = self.max_batch
        rows = {"seeds": np.zeros(b, np.int32),
                "rids": np.zeros(b, np.int32),
                "steps": np.zeros(b, np.int32),
                "temps": np.ones(b, np.float32),
                "top_ks": np.zeros(b, np.int32),
                "top_ps": np.zeros(b, np.float32),
                "greedys": np.zeros(b, bool)}
        for i, r in enumerate(self.slots):
            if r is None or r.finished:
                continue
            s = r.sampling
            rows["seeds"][i], rows["rids"][i] = s.seed, r.request_id
            rows["steps"][i] = len(r.generated)
            rows["temps"][i], rows["top_ks"][i] = s.temperature, s.top_k
            rows["top_ps"][i], rows["greedys"][i] = s.top_p, s.greedy
        return rows

    def _sample_all(self, logits) -> np.ndarray:
        """Batched on-device sampling for every slot. ONE device
        round-trip per decode step instead of one per request."""
        r = self._sampling_rows()
        toks = self._sample_b(
            logits, jnp.asarray(r["seeds"]), jnp.asarray(r["rids"]),
            jnp.asarray(r["steps"]), jnp.asarray(r["temps"]),
            jnp.asarray(r["top_ks"]), jnp.asarray(r["top_ps"]),
            jnp.asarray(r["greedys"]))
        return np.asarray(jax.device_get(toks))

    def _record_token(self, req: Request, tok: int):
        req.generated.append(tok)
        self.last_tokens[req.slot, 0] = tok
        self._tenant_inc(req.tenant, "tokens")
        if (tok == req.eod_id or
                len(req.generated) >= req.max_new_tokens):
            req.finished = True

    # ---- paged-backend pressure handling ---------------------------------
    def _preempt(self, req: Request, out: List[Request]):
        """Push a running request back to the waiting queue, releasing its
        blocks (full blocks stay prefix-cached while evictable, so the
        resume prefill usually re-hits its own KV)."""
        slot = req.slot
        self.pool.release(slot, np.asarray(req.tokens),
                          int(self.lengths[slot]), preempted=True)
        self._free_slot(slot)
        req.slot = -1
        req.queued_t = time.monotonic()
        self.waiting.appendleft(req)
        out.append(req)
        rt = self._rt
        if rt.enabled:
            rt.end("decode", req.request_id)
            rt.instant("preempt", req.request_id)
            rt.begin("queue-wait", req.request_id)

    def _ensure_decode_capacity(self) -> List[Request]:
        """Before a decode step, every active slot needs the block that
        covers its append position. Exhaustion preempts the
        lowest-priority running request (highest (priority, request_id));
        the needy request preempts ITSELF when it is the lowest."""
        preempted: List[Request] = []
        runners = sorted(
            (r for r in self.slots if r is not None and not r.finished),
            key=lambda r: (r.priority, r.request_id))
        for req in runners:
            if req.slot < 0:
                continue                 # preempted earlier this step
            while not self.pool.ensure_capacity(
                    req.slot, int(self.lengths[req.slot])):
                victim = next(r for r in reversed(runners)
                              if r.slot >= 0)
                if (victim is not req and self.spill is not None
                        and victim.request_id not in self._no_repark
                        and self._park(victim)):
                    # Spill preferred over preemption: the victim's KV
                    # moved to host RAM byte-exact instead of being
                    # thrown away — its resume costs an import, not a
                    # re-prefill. Falls through to preemption when the
                    # tier's budget refuses the payload.
                    continue
                self._preempt(victim, preempted)
                if victim is req:
                    break
        return preempted

    def _retire(self) -> List[Request]:
        done = []
        for slot, req in enumerate(self.slots):
            if req is not None and req.finished:
                done.append(req)
                if self.paged:
                    # The cache holds tokens[:-1] (the final sampled
                    # token's KV was never written) — register/release
                    # only the written rows.
                    self.pool.release(slot, np.asarray(req.tokens),
                                      int(self.lengths[slot]))
                self._free_slot(slot)
                telemetry.inc("serving_requests_retired")
                self._tenant_inc(req.tenant, "finished")
                self._rt.finish(req.request_id, "retire",
                                generated=len(req.generated))
        return done

    # ---- main loop --------------------------------------------------------
    def step(self) -> Dict[str, List]:
        """Admit → decode (one token, or a speculate+verify round) for
        all active slots → retire.

        Returns {"admitted": [ids], "tokens": [(id, tok)], "finished":
        [ids], "preempted": [ids], "expired": [ids]} for this step
        (expired ⊆ finished: deadline-overdue requests aborted by this
        step's expiry sweep)."""
        expired = self.expire_overdue()
        if self.spill is not None:
            self._no_repark.clear()
            self._spill_policy()
        admitted = self._admit()
        events = {"admitted": [r.request_id for r in admitted],
                  "tokens": [(r.request_id, r.generated[-1])
                             for r in admitted],
                  "finished": [], "preempted": [], "expired": expired}

        if self.paged:
            events["preempted"] = [
                r.request_id for r in self._ensure_decode_capacity()]

        active = [r for r in self.slots
                  if r is not None and not r.finished]
        if active:
            # Token-interval telemetry: back-to-back decode rounds only
            # (an idle gap is not a token interval — same rule as the
            # disagg coordinator's SLO accounting).
            t_round = time.monotonic()
            if self._last_round_t is not None:
                iv_ms = (t_round - self._last_round_t) * 1e3
                telemetry.observe("decode_interval_ms", iv_ms)
                self.interval_hist.observe(iv_ms)
            if self.spec_method:
                self._spec_round(active, events)
            else:
                self._plain_round(active, events)
            self._last_round_t = time.monotonic()
        else:
            self._last_round_t = None

        events["finished"] = [r.request_id for r in self._retire()]
        events["finished"] += [r.request_id for r in self._aborted]
        self._aborted = []
        return events

    def _plain_round(self, active: List[Request], events: Dict):
        """One-token decode for every active slot (non-speculative)."""
        # try/finally like _spec_round's span: a failing step must not
        # leak an orphan B that mis-pairs with a later round's E.
        self._rt.begin("decode-step", None, batch=len(active))
        try:
            active_np = np.array(
                [self.slots[i] is not None and not self.slots[i].finished
                 for i in range(self.max_batch)])
            active_mask = jnp.asarray(active_np)
            lengths = jnp.asarray(self.lengths)
            if self.paged:
                logits, new = self._decode(
                    self.params, jnp.asarray(self.last_tokens),
                    self.pool.pages, self.pool.scales,
                    jnp.asarray(self.pool.page_table[:self.max_batch]),
                    lengths, active_mask, self._lora_args())
                self._commit_pools(new)
            else:
                logits, self.cache = self._decode(
                    self.params, jnp.asarray(self.last_tokens), self.cache,
                    lengths, active_mask)
            # The decode wrote each active row's kv at lengths[slot].
            self.lengths += active_np.astype(np.int32)
            logits = mask_padded_vocab(logits, self.cfg)
            toks = self._sample_all(logits)
            self.spec_stats["model_steps"] += 1
            self.spec_stats["emitted_tokens"] += len(active)
            telemetry.inc("serving_tokens_emitted", len(active))
            for req in active:
                tok = int(toks[req.slot])
                self._record_token(req, tok)
                events["tokens"].append((req.request_id, tok))
        finally:
            self._rt.end("decode-step", None)

    def _spec_round(self, active: List[Request], events: Dict):
        """One speculate+verify round: propose up to spec_k drafts per
        slot, verify all of them in ONE batched multi-query forward, and
        accept by exact rejection sampling (greedy: bit-identical argmax
        chain; sampled: target distribution preserved). Rejected tokens'
        KV is rolled back via PagedKVCache.rewind."""
        b, k = self.max_batch, self.spec_k

        # Opportunistic capacity for the speculative tail: span-1 is
        # already guaranteed by _ensure_decode_capacity; under pressure
        # speculation SHRINKS instead of preempting.
        k_caps = np.zeros((b,), np.int32)
        for req in active:
            slot = req.slot
            length = int(self.lengths[slot])
            want = min(k, req.max_new_tokens - len(req.generated) - 1,
                       self.max_seq_len - 1 - length)
            if want > 0:
                k_caps[slot] = self.pool.extend_capacity(
                    slot, length + 1, want)

        self._rt.begin("spec-round", None, batch=len(active))
        try:
            self._spec_round_inner(active, events, k_caps)
        except Exception:
            # Leave the pool consistent on ANY mid-round failure (the
            # "spec-verify" chaos drill): every surviving slot rewinds
            # to its last VERIFIED length (+1 for this step's guaranteed
            # append block) — written-but-unaccepted draft KV becomes
            # stale rows that the retried round overwrites, and the
            # over-granted speculative tail blocks go back to the pool.
            # Slots already advanced by this round keep their accepted
            # tokens (their rewind is a no-op). audit() passes either
            # way.
            for req in active:
                if req.slot >= 0:
                    self.pool.rewind(req.slot,
                                     int(self.lengths[req.slot]) + 1)
            raise
        finally:
            self._rt.end("spec-round", None)

    def _spec_round_inner(self, active: List[Request], events: Dict,
                          k_caps: np.ndarray):
        b, k = self.max_batch, self.spec_k
        drafts, counts, q_probs = self.proposer.propose(k_caps)
        if not counts.any():
            # Nothing proposed anywhere (e.g. n-gram on non-repetitive
            # text): the (K+1)-wide verify would pay ~K+1× decode cost
            # to emit one token per row — take the plain 1-token step
            # instead (streams are identical by construction). Drop the
            # over-granted spec blocks first, keeping the one covering
            # this step's append position.
            for req in active:
                self.pool.rewind(req.slot,
                                 int(self.lengths[req.slot]) + 1)
            self._plain_round(active, events)
            return

        q_lens = np.ones((b,), np.int32)
        tokens = np.zeros((b, k + 1), np.int32)
        active_np = np.zeros((b,), bool)
        for req in active:
            slot = req.slot
            active_np[slot] = True
            tokens[slot, 0] = self.last_tokens[slot, 0]
            n = int(counts[slot])
            tokens[slot, 1:1 + n] = drafts[slot, :n]
            q_lens[slot] = 1 + n
        rows = self._sampling_rows()

        logits, hidden, new = self._mq_step(
            self.params, jnp.asarray(tokens), self.pool.pages,
            self.pool.scales,
            jnp.asarray(self.pool.page_table[:self.max_batch]),
            jnp.asarray(self.lengths),
            jnp.asarray(q_lens), jnp.asarray(active_np),
            self._lora_args())
        self._commit_pools(new)
        logits = mask_padded_vocab(logits, self.cfg)
        # Chaos site "spec-verify": fires at the WORST point — the
        # multi-query step already wrote every draft token's KV, nothing
        # is accepted yet — so the drill proves _spec_round's rollback
        # (rewind to the last verified length) keeps the pool auditable
        # and the stream exact.
        chaos.fire("spec-verify")
        accepts, out_toks = self._verify_sample(
            logits, jnp.asarray(drafts), jnp.asarray(q_lens), q_probs,
            jnp.asarray(rows["seeds"]), jnp.asarray(rows["rids"]),
            jnp.asarray(rows["steps"]), jnp.asarray(rows["temps"]),
            jnp.asarray(rows["top_ks"]), jnp.asarray(rows["top_ps"]),
            jnp.asarray(rows["greedys"]))
        accepts = np.asarray(jax.device_get(accepts))
        out_toks = np.asarray(jax.device_get(out_toks))
        h_sel = None
        if self.proposer.needs_hidden:
            h_sel = np.asarray(jax.device_get(jnp.take_along_axis(
                hidden, jnp.asarray(accepts)[:, None, None], axis=1)[:, 0]),
                np.float32)

        self.spec_stats["rounds"] += 1
        self.spec_stats["model_steps"] += 1
        for req in active:
            slot = req.slot
            n = int(counts[slot])
            a = min(int(accepts[slot]), n)
            emitted = [int(t) for t in drafts[slot, :a]]
            emitted.append(int(out_toks[slot]))
            len_before = int(self.lengths[slot])
            m = 0
            for tok in emitted:
                self._record_token(req, tok)
                events["tokens"].append((req.request_id, tok))
                m += 1
                if req.finished:
                    break   # eod/budget: drop the rest of the window
            # Valid KV = [last_token, accepted drafts] — rewind the
            # written-but-rejected tail (and over-granted blocks).
            self.lengths[slot] = len_before + m
            self.pool.rewind(slot, len_before + m)
            if h_sel is not None:
                self._h_last[slot] = h_sel[slot]
                self._h_valid[slot] = True
            req.spec_proposed += n
            req.spec_accepted += a
            self.spec_stats["proposed"] += n
            self.spec_stats["accepted"] += a
            self.spec_stats["emitted_tokens"] += m
            # Acceptance histogram (ISSUE 12): accepted drafts per
            # verify round, per request row — /metrics percentiles show
            # the acceptance DISTRIBUTION, not just the mean rate.
            telemetry.observe("spec_accepted_per_round", a,
                              lo=0.5, hi=64, growth=1.5)
            telemetry.inc("spec_proposed_tokens", n)
            telemetry.inc("spec_accepted_tokens", a)
            telemetry.inc("serving_tokens_emitted", m)
            self.proposer.on_verified(slot, a)

    def run_to_completion(self,
                          token_callback: Optional[Callable] = None
                          ) -> Dict[int, np.ndarray]:
        """Drive step() until every request finishes; returns
        {request_id: full token array}."""
        results: Dict[int, np.ndarray] = {}
        finished_reqs: Dict[int, Request] = {}
        while self.has_work:
            ev = self.step()
            if token_callback is not None:
                for rid, tok in ev["tokens"]:
                    token_callback(rid, tok)
            for rid in ev["finished"]:
                finished_reqs[rid] = self.requests[rid]
        for rid, req in finished_reqs.items():
            results[rid] = req.tokens
            self.requests.pop(rid, None)
        return results

    # ---- observability ----------------------------------------------------
    def dispatch_stats(self, force: bool = False) -> Optional[Dict]:
        """Compiled decode-step dispatch accounting (ISSUE 11): lowers +
        compiles the decode jit AOT at the engine's shapes and counts
        executable fusions / custom-calls / while-loops per step
        (utils/dispatch.py). Cached per jit build — the first call pays
        one extra compile; /stats serves the cached value afterwards.
        The megakernel fusion win is gated off THESE counts (the
        compiled module), not wall time."""
        if self._dispatch_stats is not None and not force:
            return self._dispatch_stats
        if not self.paged:
            return None
        from megatronapp_tpu.utils.dispatch import (
            compiled_stats, launch_stats,
        )
        spec = lambda a: jax.ShapeDtypeStruct(  # noqa: E731
            a.shape, a.dtype)
        p_spec = jax.tree.map(spec, self.params)
        pages_spec = jax.tree.map(spec, self.pool.pages)
        scales_spec = jax.tree.map(spec, self.pool.scales)
        mb = self.pool.page_table.shape[1]
        args = (p_spec,
                jax.ShapeDtypeStruct((self.max_batch, 1), jnp.int32),
                pages_spec, scales_spec,
                jax.ShapeDtypeStruct((self.max_batch, mb), jnp.int32),
                jax.ShapeDtypeStruct((self.max_batch,), jnp.int32),
                jax.ShapeDtypeStruct((self.max_batch,), jnp.bool_),
                jax.tree.map(spec, self._lora_args()))
        try:
            # Gate metric: estimated kernel launches per executed step
            # off the traced module (pallas_call == ONE TPU custom
            # call; scan bodies × length; unroll credits loop steps).
            stats = launch_stats(self._decode, *args)
            # Record metrics: what THIS backend actually compiled (on
            # CPU the interpret-mode kernels inline into plain HLO) +
            # the XLA cost-model totals.
            stats["compiled"] = compiled_stats(self._decode, *args)
        except Exception as e:  # noqa: BLE001 — observability must not
            # take the serving loop down with it (backend-specific
            # lowering quirks degrade to a reported error).
            logger.warning("decode dispatch accounting failed: %s", e)
            stats = {"error": str(e)}
        stats["megakernel"] = self.megakernel
        stats["scan_unroll"] = self.cfg.scan_unroll
        self._dispatch_stats = stats
        # MegaScan: the fusion win is a monitored metric — emit it into
        # the trace stream when a tracer is configured.
        try:
            from megatronapp_tpu.trace.tracer import get_tracer
            tr = get_tracer()
            if getattr(tr, "enabled", False):
                tr.instant("decode-dispatch", **{
                    k: v for k, v in stats.items()
                    if isinstance(v, (int, float, bool))})
        except Exception:  # noqa: BLE001 — tracing is best-effort
            pass
        return stats

    def stats_snapshot(self, include_dispatch: bool = False) -> Dict:
        """JSON-ready serving stats (the server's GET /stats payload):
        pool occupancy, prefix-cache hit rate, speculative acceptance,
        active batch size — serving is observable without log scraping.

        include_dispatch=True adds the compiled decode-step dispatch
        accounting (dispatch_stats; the first call pays one AOT compile
        — /stats opts in, /healthz stays cheap)."""
        out = {
            "engine": "dynamic",
            "paged": self.paged,
            "max_batch": self.max_batch,
            "active": sum(1 for r in self.slots if r is not None),
            "waiting": len(self.waiting),
            "multiquery_traces": self.mq_traces,
            "decode_traces": self.decode_traces,
            "megakernel": self.megakernel,
        }
        if include_dispatch and self.paged:
            out["decode_dispatch"] = self.dispatch_stats()
        if self.paged:
            pool = self.pool
            st = dict(pool.stats)
            seen = st["prefix_hit_tokens"] + st["prefill_tokens"]
            # Byte accounting reads the ADDRESSABLE pool arrays (int8
            # data + fp32 scales for quantized pools), never a dtype
            # assumption — /stats and /healthz stay honest when the pool
            # dtype differs from the param dtype. resident_bytes counts
            # blocks whose data is live (in use + LRU-parked, still
            # hittable); pool_bytes_total is the full allocation.
            bpb = pool.bytes_per_block
            resident_blocks = pool.num_blocks - pool.free_blocks()
            out["pool"] = {
                "num_blocks": pool.num_blocks,
                "block_size": pool.block_size,
                "kv_cache_dtype": pool.kv_cache_dtype,
                "bytes_per_block": bpb,
                "pool_bytes_total": pool.bytes_total,
                "resident_bytes": resident_blocks * bpb,
                "blocks_in_use": pool.blocks_in_use(),
                "blocks_free": pool.free_blocks(),
                "blocks_evictable": pool.evictable_blocks(),
                "prefix_hit_rate": (
                    round(st["prefix_hit_tokens"] / seen, 4) if seen
                    else 0.0),
                **st,
            }
        if self.spill is not None:
            out["spill"] = {"watermark_blocks": self.spill_watermark,
                            "held": len(self._held),
                            **self.spill.stats()}
        if self.adapters is not None:
            out["lora"] = self.adapters.stats_snapshot()
        if self._tenant_stats:
            # Per-tenant serving counters (bounded cardinality, see
            # _tenant_inc). slo_attainment = finished / closed requests
            # (deadline expiries are the misses).
            tenants = {}
            for t, st in self._tenant_stats.items():
                closed = st["finished"] + st["expired"]
                tenants[t] = dict(
                    st, slo_attainment=(round(st["finished"] / closed, 4)
                                        if closed else 1.0))
            out["tenants"] = tenants
        if self.spec_method:
            ss = dict(self.spec_stats)
            out["speculative"] = {
                "method": self.spec_method,
                "k": self.spec_k,
                "acceptance_rate": (
                    round(ss["accepted"] / ss["proposed"], 4)
                    if ss["proposed"] else 0.0),
                "tokens_per_step": (
                    round(ss["emitted_tokens"] / ss["model_steps"], 4)
                    if ss["model_steps"] else 0.0),
                **ss,
            }
        return out

    def generate_text(self, prompts, max_new_tokens: int,
                      sampling: Optional[SamplingParams] = None,
                      token_callback: Optional[Callable] = None):
        """String-level API (drop-in for StaticInferenceEngine
        .generate_text — lets the REST/WS server run on the dynamic
        engine)."""
        assert self.tokenizer is not None, "tokenizer required"
        eod = getattr(self.tokenizer, "eod", None)
        rids = []
        for prompt in prompts:
            ids = np.asarray(self.tokenizer.tokenize(prompt), np.int32)
            rids.append(self.add_request(ids, max_new_tokens, sampling,
                                         eod_id=eod))
        cb = None
        if token_callback is not None:
            def cb(rid, tok):
                token_callback(rid, np.asarray([tok]), None)
        results = self.run_to_completion(token_callback=cb)
        texts = []
        for prompt, rid in zip(prompts, rids):
            n_prompt = len(self.tokenizer.tokenize(prompt))
            new_ids = results[rid][n_prompt:].tolist()
            if eod is not None and eod in new_ids:
                new_ids = new_ids[: new_ids.index(eod)]
            texts.append(self.tokenizer.detokenize(new_ids))
        return texts
