"""Cross-process fleet serving: replica RPC workers + process router
(ISSUE 18).

PR 14's `FleetRouter` proved the fleet story — KV-affinity admission,
live token-exact migration, zero-lost failover, rolling reloads —
inside ONE Python process. This module promotes the replica boundary to
a real OS process boundary, the way the reference's multi-node pieces
are actually deployed (MegaDPP's background sender/receiver processes,
MegaScan's per-rank trace collection; the MPMD pipeline work in
PAPERS.md runs every stage as its own communicating program):

- **Wire protocol**: serialized, length-prefixed frames over a TCP
  socket (stdlib only — an 8-byte big-endian length prefix + a pickle
  payload; both ends count messages AND exact frame bytes, so the
  benchmark's RPC accounting gates read off real serialized frames,
  not estimates).
- **`ReplicaServer` / worker entrypoint**: wraps an UNCHANGED
  `DynamicInferenceEngine` behind verbs — submit / step / abort / pop /
  export / import / release / evict / set_params / sessions / healthz /
  stats / audit / trace / shutdown. `python -m
  megatronapp_tpu.inference.fleet_rpc --state-dir D --idx I` builds the
  engine from the replica's spec file, binds an ephemeral port, writes
  `addr.json` (host/port/pid/incarnation), and heartbeats through
  `training/ft_integration.HeartbeatMonitor` — the SAME on-disk
  heartbeat the training supervisor story has carried since ISSUE 6,
  now read by the serving supervisor.
- **`ProcessFleetRouter`**: speaks the protocol to N worker processes.
  Same rid space (the router's counter rides in every submit),
  message-shaped admission with the in-process router's scoring
  (affinity − queue·load − pressure + SLO·attainment — affinity fed by
  prefix-insert keys riding step replies, attainment by each worker's
  interval-histogram state), and live migration that ships the EXACT
  `export_slot` bytes `PagedKVCache` already serializes — a migrated
  stream continues token-exact across processes because the sampler's
  fold_in chain (seed ∘ rid ∘ step) never references which process
  computes the step.
- **Failure domains**: a dead worker's sessions re-enter a survivor
  with prompt+generated intact (the preemption-resume path — zero
  sessions lost, greedy streams exact); a dead ROUTER recovers by
  interrogating worker `sessions` over RPC (`ProcessFleetRouter
  .attach`) and rebuilding owner + affinity tables from the live
  engine state — zero lost in both directions. The supervisor
  (inference/supervisor.py) owns detect → SIGKILL → relaunch.

Chaos site ``fleet-rpc`` fires in `ReplicaClient.call` AFTER the reply
frame is deserialized and BEFORE the router commits it — the
lost-acknowledgement window. Every router operation is exception-safe
against it: submit rolls back with an idempotent `evict` and resubmits;
migration evicts the half-imported destination copy (the session keeps
decoding on the source, both pools audit-clean); a lost step reply
resyncs the router's shadow books from the worker's authoritative
`sessions` state, so no emitted token is dropped.

The router presents the single-engine facade
(`add_request`/`step`/`abort_request`/`pop_request`/`has_work`/
`stats_snapshot`), so `DynamicBatchingDriver` and the /stats /healthz
/metrics endpoints serve a cross-process fleet unchanged; /metrics
aggregation (per-replica labels + supervisor restart counts) rides
`export_fleet_gauges`.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from megatronapp_tpu.utils import chaos
from megatronapp_tpu.utils import metrics as telemetry
from megatronapp_tpu.utils.metrics import Histogram

logger = logging.getLogger(__name__)

# Replica lifecycle states (shared vocabulary with inference/fleet.py).
ACTIVE = "active"
DEAD = "dead"

_LEN = struct.Struct("!Q")
MAX_FRAME = 1 << 32     # 4 GiB — far above any KV export at test scale


# ---------------------------------------------------------------------------
# Wire protocol: length-prefixed pickle frames. Pickle is the right
# trust model here — router, supervisor, and workers are ONE operator's
# co-located processes on a loopback socket (the payloads carry live
# numpy KV rows and Request objects); this is an internal fabric, not a
# public API surface.
# ---------------------------------------------------------------------------
def send_msg(sock: socket.socket, obj) -> int:
    """Serialize + frame + send; returns exact bytes put on the wire."""
    blob = pickle.dumps(obj, protocol=4)
    frame = _LEN.pack(len(blob)) + blob
    sock.sendall(frame)
    return len(frame)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("fleet-rpc peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket) -> Tuple[object, int]:
    """Receive one frame; returns (object, exact bytes off the wire)."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise ValueError(f"fleet-rpc frame of {n} bytes exceeds "
                         f"MAX_FRAME ({MAX_FRAME})")
    return pickle.loads(_recv_exact(sock, n)), _LEN.size + n


# ---------------------------------------------------------------------------
# Engine specs: a JSON-able recipe both the worker entrypoint and the
# in-process baseline build engines from, so a process fleet and an
# in-process fleet on the same spec hold BIT-IDENTICAL params (PRNG
# init is deterministic in the seed) — the foundation of every
# cross-process token-exactness gate.
# ---------------------------------------------------------------------------
def default_engine_spec(**overrides) -> dict:
    spec = {
        "preset": None,             # models/presets.py name, or dims:
        "num_layers": 2, "hidden_size": 64, "num_attention_heads": 4,
        "num_query_groups": 2, "vocab_size": 128,
        "max_position_embeddings": 64,
        "seed": 7,                  # params init PRNGKey
        "max_batch": 2, "max_seq_len": 48,
        "prefill_buckets": [16],
        "block_size": 8, "num_blocks": None,
        "kv_cache_dtype": "bf16",
        "prefill_chunk": 32,
        # Host-RAM KV spill tier (ISSUE 20): parked sessions per worker.
        "kv_spill_host_mb": 0.0,
        "kv_spill_watermark_blocks": 0,
        "platform": "cpu",          # worker JAX_PLATFORMS
        # Multi-tenant LoRA serving (ISSUE 19): a lora_dir of .npz
        # adapters gives every worker an AdapterCache over the same
        # on-disk registry — cross-process fleets serve adapters with
        # identical banks because the npz bytes are the shared truth.
        "lora_dir": None,
        "lora_rank": 8,
        "max_resident_adapters": 8,
    }
    spec.update(overrides)
    return spec


def build_engine_from_spec(spec: dict):
    """Deterministic engine construction (worker entrypoint AND the
    benchmark's in-process parity leg — one build path, exact params)."""
    import jax
    import jax.numpy as jnp

    from megatronapp_tpu.config.transformer_config import TransformerConfig
    from megatronapp_tpu.inference.dynamic_engine import (
        DynamicInferenceEngine,
    )
    from megatronapp_tpu.models.gpt import init_gpt_params

    if spec.get("preset"):
        from megatronapp_tpu.models.presets import PRESETS
        cfg = PRESETS[spec["preset"]]()
    else:
        cfg = TransformerConfig(
            num_layers=spec["num_layers"],
            hidden_size=spec["hidden_size"],
            num_attention_heads=spec["num_attention_heads"],
            num_query_groups=spec["num_query_groups"],
            vocab_size=spec["vocab_size"],
            max_position_embeddings=spec["max_position_embeddings"],
            compute_dtype=jnp.float32, remat_policy="none")
    params, _ = init_gpt_params(
        jax.random.PRNGKey(spec.get("seed", 0)), cfg)
    adapter_cache = None
    if spec.get("lora_dir"):
        from megatronapp_tpu.inference.lora import (
            AdapterCache, AdapterRegistry,
        )
        adapter_cache = AdapterCache(
            cfg, AdapterRegistry(spec["lora_dir"]),
            max_resident=spec.get("max_resident_adapters", 8),
            rank=spec.get("lora_rank", 8))
    return DynamicInferenceEngine(
        params, cfg, max_batch=spec["max_batch"],
        max_seq_len=spec["max_seq_len"],
        prefill_buckets=tuple(spec.get("prefill_buckets") or (16,)),
        paged=True, block_size=spec["block_size"],
        num_blocks=spec.get("num_blocks"),
        kv_cache_dtype=spec.get("kv_cache_dtype", "bf16"),
        prefill_chunk=spec.get("prefill_chunk", 32),
        adapter_cache=adapter_cache,
        spill_host_mb=spec.get("kv_spill_host_mb", 0.0) or 0.0,
        spill_watermark_blocks=(
            spec.get("kv_spill_watermark_blocks", 0) or 0))


# ---------------------------------------------------------------------------
# Fleet state directory layout (the supervisor/recovery rendezvous):
#   <state_dir>/replica-<i>/spec.json        engine recipe (router writes)
#   <state_dir>/replica-<i>/addr.json        host/port/pid/incarnation
#                                            (the WORKER writes, atomic)
#   <state_dir>/replica-<i>/heartbeat.json   HeartbeatMonitor (worker)
#   <state_dir>/supervisor.json              restart accounting
# ---------------------------------------------------------------------------
def replica_dir(state_dir: str, idx: int) -> str:
    return os.path.join(state_dir, f"replica-{idx}")


def heartbeat_dir(state_dir: str, idx: int) -> str:
    return replica_dir(state_dir, idx)


def replica_dirs(state_dir: str) -> List[int]:
    out = []
    try:
        for name in os.listdir(state_dir):
            if name.startswith("replica-"):
                try:
                    out.append(int(name.split("-", 1)[1]))
                except ValueError:
                    continue
    except OSError:
        pass
    return sorted(out)


def _write_json_atomic(path: str, payload: dict):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def write_spec(state_dir: str, idx: int, spec: dict):
    d = replica_dir(state_dir, idx)
    os.makedirs(d, exist_ok=True)
    _write_json_atomic(os.path.join(d, "spec.json"), spec)


def read_spec(state_dir: str, idx: int) -> dict:
    with open(os.path.join(replica_dir(state_dir, idx),
                           "spec.json")) as f:
        return json.load(f)


def _host_is_local(host: str) -> bool:
    """True for loopback/any-local names — the only hosts the spawn +
    SIGKILL supervision model can actually manage."""
    if host in ("", "localhost", "0.0.0.0", "::", "::1"):
        return True
    return host.startswith("127.")


def read_addr(state_dir: str, idx: int) -> Optional[dict]:
    path = os.path.join(replica_dir(state_dir, idx), "addr.json")
    try:
        with open(path) as f:
            addr = json.load(f)
    except (OSError, ValueError):
        return None
    host = str(addr.get("host", ""))
    if not _host_is_local(host):
        # Fail LOUDLY at parse/attach time instead of silently assuming
        # loopback: worker supervision is os.kill-based (SIGKILL +
        # pid liveness) and spawn launches subprocesses on THIS machine,
        # so a remote host in addr.json can neither be supervised nor
        # respawned — the fleet would "work" until the first failure.
        raise RuntimeError(
            f"replica-{idx} addr.json lists non-local host {host!r}: "
            "multi-host spawn not yet supported (worker spawn and "
            "SIGKILL supervision assume every replica runs on this "
            "machine). Run one fleet per host behind a front-end "
            "instead.")
    return addr


def spawn_worker(state_dir: str, idx: int, incarnation: int,
                 extra_env: Optional[dict] = None) -> subprocess.Popen:
    """Launch one replica worker process (router.launch and the
    supervisor's relaunch share this — one spawn path)."""
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    log_path = os.path.join(replica_dir(state_dir, idx),
                            f"worker-{incarnation}.log")
    os.makedirs(replica_dir(state_dir, idx), exist_ok=True)
    log = open(log_path, "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "-m",
             "megatronapp_tpu.inference.fleet_rpc",
             "--state-dir", state_dir, "--idx", str(idx),
             "--incarnation", str(incarnation)],
            stdout=log, stderr=subprocess.STDOUT, env=env)
    finally:
        log.close()      # the child holds its own descriptor


def wait_for_addr(state_dir: str, idx: int, incarnation: int,
                  timeout: float = 120.0) -> dict:
    """Block until the worker's addr file shows `incarnation` (a fresh
    worker pays the jax import + engine build before binding)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        addr = read_addr(state_dir, idx)
        if addr is not None and addr.get("incarnation") == incarnation:
            return addr
        time.sleep(0.05)
    raise TimeoutError(
        f"replica {idx} incarnation {incarnation} never published its "
        f"address within {timeout}s (see worker-{incarnation}.log in "
        f"{replica_dir(state_dir, idx)})")


# ---------------------------------------------------------------------------
# Server side: one engine behind the verb table.
# ---------------------------------------------------------------------------
class ReplicaServer:
    """Serve one UNCHANGED engine over the fleet RPC protocol.

    Runs identically as a subprocess entrypoint (worker_main) and as an
    in-process thread (tests / the benchmark's thread-backed mode) —
    the wire frames, verb handlers, chaos window, and byte accounting
    are the same either way; only the process boundary differs."""

    def __init__(self, engine, idx: int = 0,
                 heartbeat: Optional[object] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.engine = engine
        self.idx = idx
        self.heartbeat = heartbeat
        self.steps = 0
        self.msgs_recv = 0
        self.msgs_sent = 0
        self.bytes_recv = 0
        self.bytes_sent = 0
        self._lock = threading.RLock()       # engine ops serialized
        self._stop = threading.Event()
        self._busy_since: Optional[float] = None
        # Prefix-insert events buffer: the in-process router wires pool
        # listeners directly; cross-process they ride step replies.
        self._prefix_buf: List[bytes] = []
        self._flushed = False
        pool = getattr(engine, "pool", None)
        if pool is not None:
            pool.prefix_listener = self._note_prefixes
            pool.flush_listener = self._note_flush
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.addr = self._sock.getsockname()

    def _note_prefixes(self, keys: List[bytes]):
        self._prefix_buf.extend(keys)

    def _note_flush(self):
        self._flushed = True
        self._prefix_buf.clear()

    # -- liveness ----------------------------------------------------------
    def _beat(self):
        if self.heartbeat is not None:
            self.heartbeat.beat()

    def _beat_loop(self, interval: float):
        """Background heartbeat: beats while the worker is responsive.
        A handler wedged longer than `interval*4` stops the beats —
        that wedge is exactly what the supervisor's staleness check
        must see, so the ticker refuses to mask it."""
        while not self._stop.wait(interval):
            busy = self._busy_since
            if busy is not None and time.monotonic() - busy > interval * 4:
                continue
            self._beat()

    # -- serve loops -------------------------------------------------------
    def start(self) -> "ReplicaServer":
        """Accept-loop in a daemon thread (in-process mode)."""
        threading.Thread(target=self.serve_forever,
                         name=f"replica-rpc-{self.idx}",
                         daemon=True).start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def serve_forever(self, beat_interval: Optional[float] = None):
        if beat_interval and self.heartbeat is not None:
            threading.Thread(target=self._beat_loop,
                             args=(beat_interval,), daemon=True).start()
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                msg, nbytes = recv_msg(conn)
                self.msgs_recv += 1
                self.bytes_recv += nbytes
                reply = self._dispatch(msg)
                self.bytes_sent += send_msg(conn, reply)
                self.msgs_sent += 1
                if msg.get("verb") == "shutdown":
                    self.stop()
                    break
        except (ConnectionError, EOFError, OSError):
            pass      # router went away; next connection re-accepts
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, msg: dict) -> dict:
        verb = msg.get("verb")
        handler = getattr(self, f"_do_{verb}", None)
        if handler is None:
            return {"ok": False, "kind": "ValueError",
                    "error": f"unknown fleet-rpc verb {verb!r}"}
        self._busy_since = time.monotonic()
        try:
            with self._lock:
                value = handler(msg)
            return {"ok": True, "value": value}
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            logger.warning("fleet-rpc verb %s failed", verb,
                           exc_info=True)
            return {"ok": False, "kind": type(e).__name__,
                    "error": str(e)}
        finally:
            self._busy_since = None
            self._beat()

    # -- verbs -------------------------------------------------------------
    def _do_ping(self, msg):
        return {"idx": self.idx, "pid": os.getpid()}

    def _do_submit(self, msg):
        """Admit a request under the ROUTER'S rid (one rid space spans
        the fleet). `generated` non-empty is the failover/resume shape:
        the request re-enters the waiting queue carrying its emitted
        tokens, exactly like the in-process router's `_requeue_on` —
        the engine re-prefills prompt+generated and the fold_in chain
        continues the stream token-exact."""
        from megatronapp_tpu.inference.dynamic_engine import Request
        from megatronapp_tpu.inference.engine import SamplingParams
        eng = self.engine
        rid = msg["rid"]
        generated = msg.get("generated") or []
        if rid in eng.requests:
            raise ValueError(f"rid {rid} already present on replica "
                             f"{self.idx}")
        if not generated:
            got = eng.add_request(
                msg["prompt"], msg["max_new_tokens"],
                msg.get("sampling"), eod_id=msg.get("eod_id"),
                priority=msg.get("priority", 0),
                deadline_s=msg.get("deadline_s"),
                request_id=rid,
                adapter_id=msg.get("adapter_id"),
                tenant=msg.get("tenant"))
            assert got == rid
            return {"rid": rid}
        now = time.monotonic()
        req = Request(
            rid, np.asarray(msg["prompt"], np.int32).reshape(-1),
            msg["max_new_tokens"],
            msg.get("sampling") or SamplingParams(),
            eod_id=msg.get("eod_id"),
            priority=msg.get("priority", 0),
            deadline_s=msg.get("deadline_s"),
            adapter_id=msg.get("adapter_id"),
            tenant=msg.get("tenant"),
            admit_t=now, queued_t=now)
        req.generated = list(generated)
        req.slot = -1
        eng.requests[rid] = req
        eng.waiting.append(req)
        return {"rid": rid, "resumed": len(generated)}

    def _do_step(self, msg):
        eng = self.engine
        if eng.has_work:
            ev = eng.step()
            self.steps += 1
        else:
            ev = {"admitted": [], "tokens": [], "finished": [],
                  "preempted": [], "expired": []}
        prefix = self._prefix_buf
        self._prefix_buf = []
        flushed = self._flushed
        self._flushed = False
        hist = getattr(eng, "interval_hist", None)
        return {
            "events": ev,
            "prefix_keys": prefix,
            "flushed": flushed,
            "waiting": len(eng.waiting),
            "active": sum(1 for s in eng.slots if s is not None),
            "free_slots": eng.free_decode_slots(),
            "pressure": (eng.pool.blocks_in_use() / eng.pool.num_blocks
                         if getattr(eng, "pool", None) is not None
                         else 0.0),
            "hist": hist.state() if hist is not None else None,
            "steps": self.steps,
        }

    def _do_abort(self, msg):
        return self.engine.abort_request(msg["rid"])

    def _do_pop(self, msg):
        return self.engine.pop_request(msg["rid"])

    def _do_export(self, msg):
        return self.engine.export_request(msg["rid"])

    def _do_import(self, msg):
        return self.engine.import_request(msg["payload"])

    def _do_release(self, msg):
        self.engine.release_exported(msg["rid"])
        return True

    def _do_evict(self, msg):
        """Idempotent un-admit (the router's rollback verb for a lost
        acknowledgement): drop `rid` from this replica's books and
        release any slot/pool resources it holds. Safe to call when the
        rid never landed (returns False)."""
        eng = self.engine
        inner = getattr(eng, "engine", eng)
        rid = msg["rid"]
        req = eng.requests.pop(rid, None)
        if req is None:
            return False
        try:
            eng.waiting.remove(req)
        except ValueError:
            pass
        slot = next((i for i, r in enumerate(inner.slots) if r is req),
                    None)
        if slot is not None:
            pool = getattr(eng, "pool", None)
            if pool is not None:
                try:
                    pool.release(slot, np.asarray(req.tokens),
                                 int(inner.lengths[slot]))
                except Exception:  # noqa: BLE001 — best-effort reclaim
                    logger.warning("evict pool release failed for rid "
                                   "%d", rid, exc_info=True)
            inner._free_slot(slot)
        return True

    def _do_park(self, msg):
        """Client/loadgen-requested park of a long-idle session into
        this worker's host spill tier (False when spill is off)."""
        fn = getattr(self.engine, "park_request", None)
        return bool(fn and fn(msg["rid"]))

    def _do_resume(self, msg):
        fn = getattr(self.engine, "resume_request", None)
        return bool(fn and fn(msg["rid"]))

    def _do_prefix_put(self, msg):
        """Seed one fleet-store prefix block into this worker's pool
        (rc==0 LRU entry, hittable by the next admit). `dup` tells the
        router the worker already held it — no bytes re-imported, and
        the router's chunks-avoided accounting counts it as local."""
        pool = getattr(self.engine, "pool", None)
        if pool is None:
            return {"ok": False, "dup": False}
        key = msg["key"]
        if pool.has_prefix(key):
            return {"ok": True, "dup": True}
        return {"ok": pool.import_prefix_block(key, msg["payload"]),
                "dup": False}

    def _do_prefix_get(self, msg):
        """Export one prefix block's payload for the fleet store (None
        when this pool no longer holds the key — it may have been
        LRU-evicted between the step reply and this fetch)."""
        pool = getattr(self.engine, "pool", None)
        if pool is None:
            return None
        return pool.export_prefix_block(msg["key"])

    def _do_set_params(self, msg):
        self.engine.set_params(msg["params"])
        return True

    def _do_sessions(self, msg):
        """Authoritative session table (router restart recovery + the
        router's lost-step-reply resync): every Request this replica
        holds, with its emitted tokens."""
        return dict(self.engine.requests)

    def _do_healthz(self, msg):
        eng = self.engine
        return {"ok": True, "idx": self.idx, "pid": os.getpid(),
                "steps": self.steps,
                "active": sum(1 for s in eng.slots if s is not None),
                "waiting": len(eng.waiting)}

    def _do_stats(self, msg):
        eng = self.engine
        out = eng.stats_snapshot() if hasattr(eng, "stats_snapshot") \
            else {}
        hist = getattr(eng, "interval_hist", None)
        out["hist"] = hist.state() if hist is not None else None
        out["rpc"] = {"msgs_recv": self.msgs_recv,
                      "msgs_sent": self.msgs_sent,
                      "bytes_recv": self.bytes_recv,
                      "bytes_sent": self.bytes_sent}
        out["pid"] = os.getpid()
        out["steps"] = self.steps
        if telemetry.enabled():
            out["metrics"] = telemetry.snapshot()
        return out

    def _do_audit(self, msg):
        pool = getattr(self.engine, "pool", None)
        if pool is not None:
            pool.audit()
        return True

    def _do_trace(self, msg):
        from megatronapp_tpu.trace.request_trace import get_request_tracer
        rt = get_request_tracer()
        return {"records": rt.dump(), "pid_names": dict(rt._pid_names),
                "pid": os.getpid()}

    def _do_shutdown(self, msg):
        return True


# ---------------------------------------------------------------------------
# Client side.
# ---------------------------------------------------------------------------
class ReplicaRpcError(RuntimeError):
    """A verb failed on the replica side (the error crossed the wire)."""


class ReplicaClient:
    """One socket to one replica worker, with exact frame accounting.

    The ``fleet-rpc`` chaos site fires AFTER a reply frame is received
    and deserialized, BEFORE the caller (the router) can commit it —
    the lost-acknowledgement window every router operation must be
    exception-safe against."""

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 connect_retries: int = 40):
        self.msgs_sent = 0
        self.msgs_recv = 0
        self.bytes_sent = 0
        self.bytes_recv = 0
        self._lock = threading.Lock()
        last: Optional[Exception] = None
        for _ in range(connect_retries):
            try:
                self.sock = socket.create_connection(
                    (host, port), timeout=timeout)
                break
            except OSError as e:
                last = e
                time.sleep(0.05)
        else:
            raise ConnectionError(
                f"fleet-rpc connect to {host}:{port} failed: {last}")
        self.sock.settimeout(timeout)

    def call(self, verb: str, **kw):
        with self._lock:
            self.bytes_sent += send_msg(self.sock, dict(kw, verb=verb))
            self.msgs_sent += 1
            reply, nbytes = recv_msg(self.sock)
            self.bytes_recv += nbytes
            self.msgs_recv += 1
        # The drill window: reply deserialized, router not yet
        # committed. (Outside the lock so rollback verbs can reuse
        # this client from the except handler.)
        chaos.fire("fleet-rpc")
        if not reply["ok"]:
            raise ReplicaRpcError(
                f"{verb} failed on replica: [{reply['kind']}] "
                f"{reply['error']}")
        return reply["value"]

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Router-side shadow bookkeeping.
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class _Session:
    """The router's shadow of one request: enough to fail it over with
    nothing lost (prompt + emitted tokens + admission fields) and to
    serve results for a dead replica."""
    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    sampling: object
    eod_id: Optional[int] = None
    priority: int = 0
    deadline_s: Optional[float] = None
    adapter_id: Optional[str] = None
    tenant: Optional[str] = None
    generated: list = dataclasses.field(default_factory=list)
    finished: bool = False
    running: bool = False


@dataclasses.dataclass
class _ProcReplica:
    """Router-side handle for one worker process."""
    idx: int
    client: Optional[ReplicaClient] = None
    proc: Optional[subprocess.Popen] = None
    incarnation: int = 0
    state: str = ACTIVE
    steps: int = 0
    waiting: int = 0
    active: int = 0
    free_slots: int = 1
    pressure: float = 0.0
    hist: Optional[Histogram] = None

    def attainment(self, slo_ms: Optional[float]) -> float:
        if self.hist is None or slo_ms is None or not self.hist.count:
            return 1.0
        return self.hist.fraction_below(slo_ms)


class ProcessFleetRouter:
    """The in-process `FleetRouter`'s stepping surface over N replica
    WORKER PROCESSES (module docstring). Construct with `launch()` to
    spawn a fresh fleet, or `attach()` to recover a router over already
    -running workers (router restart: zero lost sessions)."""

    def __init__(self, state_dir: str, spec: Optional[dict] = None,
                 num_replicas: int = 2, policy: str = "affinity",
                 slo_ms: Optional[float] = None,
                 affinity_capacity: int = 8192,
                 supervise: Optional[str] = None,
                 stale_after: float = 15.0,
                 base_port: int = 0,
                 spawn: bool = True,
                 extra_env: Optional[dict] = None,
                 prefix_store_mb: float = 0.0):
        assert policy in ("affinity", "round_robin"), policy
        assert supervise in (None, "off", "thread", "process"), supervise
        self.state_dir = state_dir
        self.policy = policy
        self.slo_ms = slo_ms
        self.affinity_capacity = affinity_capacity
        self.stale_after = stale_after
        self.base_port = base_port
        self._extra_env = dict(extra_env or {})
        self._affinity: OrderedDict = OrderedDict()
        # Tenant/adapter→replica steering (same bounded-map machinery
        # as the in-process FleetRouter): keeping one tenant's requests
        # on the worker whose AdapterCache already holds its adapter
        # avoids a bank write per admission.
        self._tenant_affinity: OrderedDict = OrderedDict()
        self.tenant_affinity_capacity = 1024
        self._owner: Dict[int, Optional[int]] = {}
        self._sessions: Dict[int, _Session] = {}
        self._lock = threading.RLock()
        self._rr = 0
        self.pause_admission = False        # driver-facade compat
        self.paged = True
        self.tokenizer = None
        # Fleet-global prefix store (ISSUE 20): the router pulls newly
        # inserted prefix blocks off step replies (prefix_get) and
        # pushes them into an admission target that misses locally
        # (prefix_put) — the cross-process flavor of FleetRouter's
        # in-process store, same payloads, same counters.
        if prefix_store_mb:
            from megatronapp_tpu.inference.paged_cache import (
                FleetPrefixStore,
            )
            self.prefix_store = FleetPrefixStore(
                int(prefix_store_mb * (1 << 20)))
        else:
            self.prefix_store = None
        self.router_stats = {
            "admissions": 0, "affinity_admissions": 0,
            "migrations": 0, "migration_failures": 0,
            "migrated_kv_bytes": 0, "failovers": 0,
            "replica_deaths": 0, "reattaches": 0,
            "rpc_rollbacks": 0, "resyncs": 0,
            "prefix_store_admission_hits": 0,
            "prefix_store_seeded_blocks": 0,
            "prefix_store_seeded_bytes": 0,
            "prefill_chunks_avoided": 0,
        }
        self.supervisor = None
        self._supervisor_proc: Optional[subprocess.Popen] = None
        if spawn:
            assert spec is not None, "spawn=True needs an engine spec"
            self.spec = dict(spec)
            os.makedirs(state_dir, exist_ok=True)
            self._reps = []
            for i in range(num_replicas):
                s = dict(spec)
                if base_port:
                    s["port"] = base_port + i
                write_spec(state_dir, i, s)
                proc = spawn_worker(state_dir, i, 0,
                                    extra_env=self._extra_env)
                self._reps.append(_ProcReplica(idx=i, proc=proc))
            for rep in self._reps:
                addr = wait_for_addr(state_dir, rep.idx, 0)
                rep.client = ReplicaClient(addr["host"], addr["port"])
            self._ids = itertools.count()
        else:
            idxs = replica_dirs(state_dir)
            assert idxs, f"no replicas under {state_dir} to attach to"
            self.spec = read_spec(state_dir, idxs[0])
            self._reps = []
            for i in idxs:
                rep = _ProcReplica(idx=i)
                addr = read_addr(state_dir, i)
                if addr is None:
                    rep.state = DEAD
                else:
                    rep.incarnation = addr["incarnation"]
                    try:
                        rep.client = ReplicaClient(addr["host"],
                                                   addr["port"],
                                                   connect_retries=4)
                    except ConnectionError:
                        rep.state = DEAD
                self._reps.append(rep)
            self._recover_sessions()
        self.max_batch = self.spec["max_batch"] * len(self._reps)
        if supervise in ("thread", "process"):
            self.start_supervisor(mode=supervise)

    # -- construction fronts -----------------------------------------------
    @classmethod
    def launch(cls, state_dir: str, spec: dict, num_replicas: int = 2,
               **kw) -> "ProcessFleetRouter":
        return cls(state_dir, spec=spec, num_replicas=num_replicas,
                   spawn=True, **kw)

    @classmethod
    def attach(cls, state_dir: str, **kw) -> "ProcessFleetRouter":
        """Router restart recovery: connect to already-running workers
        and rebuild owner + session + affinity tables by interrogating
        replica state over RPC — zero sessions lost across a router
        death."""
        return cls(state_dir, spawn=False, **kw)

    def _recover_sessions(self):
        """Interrogate every live replica's authoritative books and
        rebuild the router's shadow: sessions/owners come back verbatim
        (Request objects carry prompt + generated + sampling), the rid
        counter resumes past the max in flight, and affinity entries
        are recomputed from each session's prompt hash chain — the same
        `prefix_block_keys` the pools hash with."""
        from megatronapp_tpu.inference.paged_cache import (
            prefix_block_keys,
        )
        max_rid = -1
        block_size = self.spec["block_size"]
        for rep in self._reps:
            if rep.state == DEAD or rep.client is None:
                continue
            sess_map = rep.client.call("sessions")
            for rid, req in sess_map.items():
                self._sessions[rid] = _Session(
                    rid=rid, prompt=np.asarray(req.prompt, np.int32),
                    max_new_tokens=req.max_new_tokens,
                    sampling=req.sampling, eod_id=req.eod_id,
                    priority=req.priority, deadline_s=req.deadline_s,
                    adapter_id=getattr(req, "adapter_id", None),
                    tenant=getattr(req, "tenant", None),
                    generated=list(req.generated),
                    finished=bool(req.finished),
                    running=req.slot >= 0)
                self._owner[rid] = rep.idx
                self._note_tenant(
                    getattr(req, "adapter_id", None)
                    or getattr(req, "tenant", None), rep.idx)
                max_rid = max(max_rid, rid)
                for key in prefix_block_keys(
                        np.asarray(req.prompt, np.int32), block_size,
                        len(req.prompt)):
                    self._note_prefix(key, rep.idx)
        self._ids = itertools.count(max_rid + 1)

    # -- supervision ---------------------------------------------------------
    def start_supervisor(self, mode: str = "thread",
                         interval: float = 0.5):
        from megatronapp_tpu.inference.supervisor import Supervisor
        if mode == "process":
            repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            env = dict(os.environ)
            env["PYTHONPATH"] = (repo_root + os.pathsep
                                 + env.get("PYTHONPATH", ""))
            self._supervisor_proc = subprocess.Popen(
                [sys.executable, "-m",
                 "megatronapp_tpu.inference.supervisor",
                 "--state-dir", self.state_dir,
                 "--stale-after", str(self.stale_after),
                 "--interval", str(interval)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=env)
            return self._supervisor_proc
        self.supervisor = Supervisor(
            _ProcessBackend(self), interval=interval,
            state_dir=self.state_dir).start()
        return self.supervisor

    def supervisor_restarts(self) -> Dict[int, int]:
        """Restart accounting regardless of which process supervises:
        the in-router thread supervisor's live counters, else the
        state-dir file the standalone supervisor process writes."""
        if self.supervisor is not None:
            return dict(self.supervisor.restarts)
        try:
            with open(os.path.join(self.state_dir,
                                   "supervisor.json")) as f:
                return {int(k): v for k, v in
                        json.load(f).get("restarts", {}).items()}
        except (OSError, ValueError):
            return {}

    # -- affinity -------------------------------------------------------------
    def _note_prefix(self, key: bytes, idx: int):
        self._affinity[key] = idx
        self._affinity.move_to_end(key)
        while len(self._affinity) > self.affinity_capacity:
            self._affinity.popitem(last=False)

    def _drop_affinity(self, idx: int):
        stale = [k for k, v in self._affinity.items() if v == idx]
        for k in stale:
            del self._affinity[k]

    def _note_tenant(self, key: Optional[str], idx: int):
        if key is None:
            return
        self._tenant_affinity[key] = idx
        self._tenant_affinity.move_to_end(key)
        while len(self._tenant_affinity) > self.tenant_affinity_capacity:
            self._tenant_affinity.popitem(last=False)

    def _drop_tenant_replica(self, idx: int):
        stale = [k for k, v in self._tenant_affinity.items() if v == idx]
        for k in stale:
            del self._tenant_affinity[k]

    # -- admission ------------------------------------------------------------
    def _live(self) -> List[_ProcReplica]:
        return [r for r in self._reps if r.state == ACTIVE]

    def _admit_target(self, prompt: np.ndarray,
                      affinity_key: Optional[str] = None) -> _ProcReplica:
        from megatronapp_tpu.inference.paged_cache import (
            prefix_block_keys,
        )
        live = self._live()
        if not live:
            raise RuntimeError("process fleet has no live replica to "
                               "admit into")
        if self.policy == "round_robin":
            rep = live[self._rr % len(live)]
            self._rr += 1
            return rep
        block_size = self.spec["block_size"]
        keys = prefix_block_keys(prompt, block_size, len(prompt))
        owners = [self._affinity.get(k) for k in keys]
        tenant_home = (None if affinity_key is None
                       else self._tenant_affinity.get(affinity_key))
        # The in-process router's scoring, off last-step-reply signals.
        queue_w, pressure_w, slo_w, tenant_w = (2.0 * block_size,
                                                4.0 * block_size,
                                                2.0 * block_size,
                                                8.0 * block_size)
        best = best_key = None
        best_aff = 0.0
        for rep in live:
            aff = 0.0
            for o in owners:
                if o != rep.idx:
                    break
                aff += block_size
            taff = tenant_w if tenant_home == rep.idx else 0.0
            load = rep.waiting + rep.active
            score = (aff + taff - queue_w * load
                     - pressure_w * rep.pressure
                     + slo_w * rep.attainment(self.slo_ms))
            key = (score, -load, -rep.idx)
            if best_key is None or key > best_key:
                best, best_key, best_aff = rep, key, aff
        if best_aff > 0:
            self.router_stats["affinity_admissions"] += 1
        return best

    def _submit_to(self, rep: _ProcReplica, sess: _Session):
        """One exception-safe submit: a lost acknowledgement (the
        fleet-rpc chaos window, or a worker death mid-call) rolls back
        with an idempotent evict, and the session re-enters admission —
        the rid was reserved router-side, so the retry is the SAME
        request and the stream it eventually emits is unchanged."""
        self._seed_from_store(rep, sess.prompt)
        try:
            rep.client.call(
                "submit", rid=sess.rid, prompt=sess.prompt,
                max_new_tokens=sess.max_new_tokens,
                sampling=sess.sampling, eod_id=sess.eod_id,
                priority=sess.priority, deadline_s=sess.deadline_s,
                adapter_id=sess.adapter_id, tenant=sess.tenant,
                generated=list(sess.generated) or None)
            rep.waiting += 1
            self._owner[sess.rid] = rep.idx
            self._note_tenant(sess.adapter_id or sess.tenant, rep.idx)
            return
        except chaos.ChaosFault:
            # Ack lost AFTER the worker may have committed: undo
            # (idempotent), then retry through admission.
            self.router_stats["rpc_rollbacks"] += 1
            telemetry.inc("fleet_rpc_rollbacks")
            try:
                rep.client.call("evict", rid=sess.rid)
            except Exception:  # noqa: BLE001 — replica may be dying
                self._fail_rep(rep)
        except (ConnectionError, EOFError, OSError, socket.timeout):
            self._fail_rep(rep, reassign=False)
        # Retry on the (possibly different) best live replica.
        self._submit_to(self._admit_target(
            sess.prompt, affinity_key=sess.adapter_id or sess.tenant),
            sess)

    def _seed_from_store(self, rep: _ProcReplica, prompt: np.ndarray):
        """Push this prompt's leading prefix blocks from the fleet
        store into the target worker's pool (prefix_put) before the
        submit, so its admit() hits them instead of re-prefilling.
        Best-effort and idempotent: a dup reply means the worker
        already held the block (counts as local, not seeded), any
        fault just stops the seeding — the submit path's own error
        handling owns worker death. Chunks-avoided follows the engine's
        chunked-prefill arithmetic exactly (leading cached blocks *
        block_size, capped at p_len - 1)."""
        store = self.prefix_store
        if store is None:
            return
        from megatronapp_tpu.inference.paged_cache import (
            cdiv, prefix_block_keys,
        )
        block_size = self.spec["block_size"]
        keys = prefix_block_keys(prompt, block_size, len(prompt))
        local = chain = seeded = 0
        leading_local = True
        for k in keys:
            payload = store.get(k)          # counts the hit/miss
            if payload is None:
                break                       # only a LEADING run helps
            try:
                reply = rep.client.call("prefix_put", key=k,
                                        payload=payload)
            except chaos.ChaosFault:
                break     # put may have landed (idempotent) — stop here
            except (ConnectionError, EOFError, OSError, socket.timeout):
                return    # submit's failover owns the dying worker
            if not reply["ok"]:
                break                       # worker pool full
            if reply["dup"] and leading_local:
                local += 1
            else:
                leading_local = False
                seeded += 1
                self.router_stats["prefix_store_seeded_blocks"] += 1
                self.router_stats["prefix_store_seeded_bytes"] += (
                    payload["nbytes"])
            chain += 1
            self._note_prefix(k, rep.idx)
        if not seeded:
            return
        p_len = len(prompt)
        chunk = int(self.spec.get("prefill_chunk", 32))

        def chunks_at(blocks_cached: int) -> int:
            cached = min(blocks_cached * block_size, p_len - 1)
            return cdiv(p_len - cached, chunk)

        avoided = chunks_at(local) - chunks_at(chain)
        self.router_stats["prefix_store_admission_hits"] += 1
        self.router_stats["prefill_chunks_avoided"] += avoided
        telemetry.inc("fleet_prefill_chunks_avoided", avoided)

    def add_request(self, prompt_tokens, max_new_tokens: int,
                    sampling=None, eod_id: Optional[int] = None,
                    priority: int = 0,
                    deadline_s: Optional[float] = None,
                    adapter_id: Optional[str] = None,
                    tenant: Optional[str] = None) -> int:
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        with self._lock:
            rid = next(self._ids)
            sess = _Session(rid=rid, prompt=prompt,
                            max_new_tokens=max_new_tokens,
                            sampling=sampling, eod_id=eod_id,
                            priority=priority, deadline_s=deadline_s,
                            adapter_id=adapter_id, tenant=tenant)
            self._sessions[rid] = sess
            self._submit_to(
                self._admit_target(prompt,
                                   affinity_key=adapter_id or tenant),
                sess)
        self.router_stats["admissions"] += 1
        telemetry.inc("fleet_admissions")
        return rid

    # -- per-request forwarding ------------------------------------------------
    def _rep_of(self, rid: int) -> Optional[_ProcReplica]:
        idx = self._owner.get(rid)
        if idx is None:
            return None
        rep = next((r for r in self._reps if r.idx == idx), None)
        if rep is None or rep.state == DEAD or rep.client is None:
            return None
        return rep

    def abort_request(self, rid: int) -> Optional[str]:
        sess = self._sessions.get(rid)
        if sess is None or sess.finished:
            return None
        rep = self._rep_of(rid)
        if rep is None:
            sess.finished = True
            return "waiting"
        try:
            out = rep.client.call("abort", rid=rid)
        except chaos.ChaosFault:
            out = "running"   # worker marked it; finish event follows
        except (ConnectionError, EOFError, OSError, socket.timeout):
            self._fail_rep(rep)
            return self.abort_request(rid)
        if out == "waiting":
            sess.finished = True
        return out

    def pop_request(self, rid: int):
        """Remove + return the finished request. Serves from the
        worker's books when the owner is alive, and from the router's
        shadow when it is not (a finished-but-unfetched result must
        survive its replica's death — same transplant contract as the
        in-process router)."""
        from megatronapp_tpu.inference.dynamic_engine import Request
        from megatronapp_tpu.inference.engine import SamplingParams
        sess = self._sessions.pop(rid, None)
        rep = self._rep_of(rid)
        self._owner.pop(rid, None)
        if rep is not None:
            try:
                req = rep.client.call("pop", rid=rid)
                if req is not None:
                    return req
            except chaos.ChaosFault:
                pass          # worker popped; serve the shadow below
            except (ConnectionError, EOFError, OSError, socket.timeout):
                self._fail_rep(rep)
        if sess is None:
            return None
        req = Request(rid, sess.prompt, sess.max_new_tokens,
                      sess.sampling or SamplingParams(),
                      eod_id=sess.eod_id, priority=sess.priority,
                      deadline_s=sess.deadline_s,
                      adapter_id=sess.adapter_id, tenant=sess.tenant)
        req.generated = list(sess.generated)
        req.finished = sess.finished
        return req

    def park_request(self, rid: int) -> bool:
        """Forward a client park to the owning worker's spill tier
        (`park` verb). A lost ack counts as parked — the verb is
        engine-side idempotent and resume_request tolerates both
        states."""
        rep = self._rep_of(rid)
        if rep is None:
            return False
        try:
            return bool(rep.client.call("park", rid=rid))
        except chaos.ChaosFault:
            return True
        except (ConnectionError, EOFError, OSError, socket.timeout):
            self._fail_rep(rep)
            return False

    def resume_request(self, rid: int) -> bool:
        rep = self._rep_of(rid)
        if rep is None:
            return False
        try:
            return bool(rep.client.call("resume", rid=rid))
        except chaos.ChaosFault:
            return True
        except (ConnectionError, EOFError, OSError, socket.timeout):
            self._fail_rep(rep)
            return False

    # -- live migration --------------------------------------------------------
    def migrate_request(self, rid: int,
                        dst_idx: Optional[int] = None) -> bool:
        """Cross-process live migration: the EXACT `export_slot` bytes
        the source pool serializes travel the wire and scatter into the
        destination pool — `import_slot` is the same all-or-nothing
        call the in-process router uses, so the migrated stream
        continues token-exact. Exception-safe: a fault after import's
        ack is lost evicts the destination copy (idempotent) and the
        session keeps decoding on the source, both pools audit-clean."""
        with self._lock:
            src = self._rep_of(rid)
            if src is None:
                return False
            cands = [r for r in self._live() if r is not src
                     and (dst_idx is None or r.idx == dst_idx)
                     and r.free_slots > 0]
            if not cands:
                return False
            dst = min(cands, key=lambda r: (r.waiting + r.active,
                                            r.idx))
            payload = None
            try:
                payload = src.client.call("export", rid=rid)
                if payload is None:
                    return False
                if not dst.client.call("import", payload=payload):
                    self.router_stats["migration_failures"] += 1
                    return False
            except Exception as e:  # noqa: BLE001 — rollback + stay put
                self.router_stats["migration_failures"] += 1
                telemetry.inc("fleet_migration_failures")
                if payload is not None:
                    # The import MAY have landed before its ack was
                    # lost — evict the destination copy (idempotent;
                    # False when it never arrived). Export was
                    # read-only, so the source needs no rollback.
                    try:
                        dst.client.call("evict", rid=rid)
                        self.router_stats["rpc_rollbacks"] += 1
                    except Exception:  # noqa: BLE001 — dst dying
                        logger.warning("migration rollback evict "
                                       "failed", exc_info=True)
                logger.warning(
                    "cross-process migration of rid %d (replica %d -> "
                    "%d) failed — session stays on the source: %s",
                    rid, src.idx, dst.idx, e)
                return False
            try:
                src.client.call("release", rid=rid)
            except chaos.ChaosFault:
                pass          # worker released; ack lost is harmless
            except (ConnectionError, EOFError, OSError, socket.timeout):
                self._fail_rep(src, skip_rid=rid)
            self._owner[rid] = dst.idx
            self.router_stats["migrations"] += 1
            self.router_stats["migrated_kv_bytes"] += payload["nbytes"]
            telemetry.inc("fleet_migrations")
        return True

    # -- failure handling ------------------------------------------------------
    def _fail_rep(self, rep: _ProcReplica, reassign: bool = True,
                  skip_rid: Optional[int] = None):
        """A worker died under the router (socket error / supervisor
        kill): mark it DEAD, drop its affinity entries, and fail every
        session it owned over to survivors with prompt+generated intact
        (the preemption-resume shape — zero sessions lost, streams
        exact). Finished-but-unfetched results stay servable from the
        router's shadow."""
        if rep.state == DEAD:
            return
        logger.warning("fleet-rpc replica %d DIED — failing its "
                       "sessions over", rep.idx)
        rep.state = DEAD
        if rep.client is not None:
            rep.client.close()
        self._drop_affinity(rep.idx)
        self._drop_tenant_replica(rep.idx)
        self.router_stats["replica_deaths"] += 1
        telemetry.inc("fleet_replica_deaths")
        if not reassign:
            # Caller re-admits the in-flight rid itself; orphans still
            # need failover below.
            pass
        orphans = [rid for rid, o in self._owner.items()
                   if o == rep.idx and rid != skip_rid]
        for rid in sorted(orphans):
            sess = self._sessions.get(rid)
            if sess is None:
                self._owner.pop(rid, None)
                continue
            if sess.finished:
                self._owner[rid] = None    # shadow serves the result
                continue
            sess.running = False
            self._owner.pop(rid, None)
            self._submit_to(self._admit_target(
                sess.prompt,
                affinity_key=sess.adapter_id or sess.tenant), sess)
            self.router_stats["failovers"] += 1
            telemetry.inc("fleet_failovers")

    def _try_reattach(self, rep: _ProcReplica) -> bool:
        """A DEAD replica rejoins when the supervisor's relaunched
        worker publishes a NEWER incarnation. It comes back empty (its
        sessions already failed over) — reattaching restores capacity,
        not state."""
        addr = read_addr(self.state_dir, rep.idx)
        if addr is None or addr["incarnation"] <= rep.incarnation:
            return False
        try:
            client = ReplicaClient(addr["host"], addr["port"],
                                   connect_retries=2)
            client.call("ping")
        except (ConnectionError, ReplicaRpcError, OSError):
            return False
        rep.client = client
        rep.incarnation = addr["incarnation"]
        rep.state = ACTIVE
        rep.waiting = rep.active = 0
        rep.free_slots = self.spec["max_batch"]
        rep.pressure = 0.0
        rep.hist = None
        self.router_stats["reattaches"] += 1
        telemetry.inc("fleet_reattaches")
        logger.warning("fleet-rpc replica %d reattached "
                       "(incarnation %d)", rep.idx, rep.incarnation)
        return True

    def _resync(self, rep: _ProcReplica, events: Dict[str, List]):
        """A step reply was lost (chaos window): the worker stepped but
        the router never saw the events. Re-read the worker's
        authoritative session table and emit the missing tokens/finish
        transitions into this round's events — nothing is dropped."""
        self.router_stats["resyncs"] += 1
        telemetry.inc("fleet_rpc_resyncs")
        sess_map = rep.client.call("sessions")
        for rid, req in sess_map.items():
            sess = self._sessions.get(rid)
            if sess is None:
                continue
            new = list(req.generated[len(sess.generated):])
            for tok in new:
                sess.generated.append(int(tok))
                events["tokens"].append((rid, int(tok)))
            if req.finished and not sess.finished:
                sess.finished = True
                events["finished"].append(rid)

    # -- main loop --------------------------------------------------------------
    def _fan_out_steps(self, live: List[_ProcReplica]) -> List:
        """Issue the per-step RPCs to every live replica CONCURRENTLY
        (one thread per in-flight verb) and return each reply or the
        exception it raised, in replica order. N workers step in
        parallel instead of serializing behind one socket round-trip
        each — fleet step latency is max(replica step), not sum. The
        byte accounting is untouched: each `ReplicaClient.call` counts
        its own frames under the client's lock, and exactly one step
        frame per replica goes on the wire either way (pinned by
        tests/test_fleet_rpc.py). Replies are PROCESSED serially by the
        caller under the router lock, so the failure handling
        (resync / fail over) is byte-for-byte the sequential path's."""
        results: List = [None] * len(live)

        def run(i: int, rep: _ProcReplica):
            try:
                results[i] = rep.client.call("step")
            except Exception as e:  # noqa: BLE001 — re-handled serially
                results[i] = e

        if len(live) == 1:
            run(0, live[0])
            return results
        threads = [threading.Thread(target=run, args=(i, rep),
                                    daemon=True)
                   for i, rep in enumerate(live)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results

    def step(self) -> Dict[str, List]:
        events: Dict[str, List] = {"admitted": [], "tokens": [],
                                   "finished": [], "preempted": [],
                                   "expired": []}
        with self._lock:
            for rep in self._reps:
                if rep.state == DEAD:
                    self._try_reattach(rep)
            live = [rep for rep in self._reps
                    if rep.state != DEAD and rep.client is not None]
            replies = self._fan_out_steps(live)
            for rep, r in zip(live, replies):
                if isinstance(r, chaos.ChaosFault):
                    self._resync(rep, events)
                    continue
                if isinstance(r, (ConnectionError, EOFError, OSError,
                                  socket.timeout, ReplicaRpcError)):
                    if isinstance(r, ReplicaRpcError):
                        logger.warning("replica %d step raised: %s",
                                       rep.idx, r)
                    self._fail_rep(rep)
                    continue
                if isinstance(r, Exception):
                    raise r
                rep.steps = r["steps"]
                rep.waiting = r["waiting"]
                rep.active = r["active"]
                rep.free_slots = r["free_slots"]
                rep.pressure = r["pressure"]
                if r["hist"] is not None:
                    rep.hist = Histogram.from_state(r["hist"])
                for key in r["prefix_keys"]:
                    self._note_prefix(key, rep.idx)
                    if (self.prefix_store is not None
                            and not self.prefix_store.has(key)):
                        # Pull each NEW block's payload once (prefix_get
                        # is read-only + idempotent, so a lost reply
                        # just refetches on the next insert event).
                        try:
                            payload = rep.client.call("prefix_get",
                                                      key=key)
                        except chaos.ChaosFault:
                            continue
                        except (ConnectionError, EOFError, OSError,
                                socket.timeout):
                            self._fail_rep(rep)
                            break
                        if payload is not None:
                            self.prefix_store.put(key, payload)
                if rep.state == DEAD:
                    continue
                if r["flushed"]:
                    self._drop_affinity(rep.idx)
                    if self.prefix_store is not None:
                        # A worker-side flush means a params swap: the
                        # store's blocks may hold KV from the OLD
                        # weights — drop everything, fleet-wide.
                        self.prefix_store.clear()
                ev = r["events"]
                for rid in ev["admitted"]:
                    sess = self._sessions.get(rid)
                    if sess is not None:
                        sess.running = True
                for rid in ev["preempted"]:
                    sess = self._sessions.get(rid)
                    if sess is not None:
                        sess.running = False
                for rid, tok in ev["tokens"]:
                    sess = self._sessions.get(rid)
                    if sess is not None:
                        sess.generated.append(int(tok))
                for rid in ev["finished"] + ev["expired"]:
                    sess = self._sessions.get(rid)
                    if sess is not None:
                        sess.finished = True
                for key in events:
                    events[key] += ev.get(key, [])
        return events

    @property
    def has_work(self) -> bool:
        return any(not s.finished for s in self._sessions.values())

    # Facade compat: shadow-derived views (the server's health snapshot
    # reads len()/occupancy off these).
    @property
    def slots(self) -> List:
        return [s.rid for s in self._sessions.values()
                if s.running and not s.finished]

    @property
    def waiting(self) -> List:
        return [s.rid for s in self._sessions.values()
                if not s.running and not s.finished]

    @property
    def requests(self) -> Dict:
        return dict(self._sessions)

    def free_decode_slots(self) -> int:
        return sum(r.free_slots for r in self._live())

    def expire_overdue(self, now=None) -> List[int]:
        return []    # deadlines are enforced worker-side (step events)

    def abort_all(self):
        for sess in list(self._sessions.values()):
            if not sess.finished:
                self.abort_request(sess.rid)

    def run_to_completion(self, token_callback=None
                          ) -> Dict[int, np.ndarray]:
        results: Dict[int, np.ndarray] = {}
        while self.has_work:
            ev = self.step()
            if token_callback is not None:
                for rid, tok in ev["tokens"]:
                    token_callback(rid, tok)
        for rid in [r for r, s in self._sessions.items() if s.finished]:
            req = self.pop_request(rid)
            if req is not None:
                results[rid] = req.tokens
        return results

    # -- server-facade compat ----------------------------------------------------
    def set_params(self, params):
        """Fan new weights out to every live worker (`set_params` verb;
        the swap is atomic per worker under its engine lock). The
        serving driver's generic reload path pauses admission, waits
        for `drained_for_reload`, then calls this."""
        for rep in self._live():
            rep.client.call("set_params", params=params)

    def drained_for_reload(self) -> bool:
        return not self.has_work

    def reset_compilation(self):
        pass    # workers own their engines; nothing is cached here

    def generate_text(self, prompts, max_new_tokens: int, sampling=None,
                      token_callback=None):
        """String-level API (mirrors FleetRouter.generate_text)."""
        assert self.tokenizer is not None, "tokenizer required"
        eod = getattr(self.tokenizer, "eod", None)
        rids = []
        for prompt in prompts:
            ids = np.asarray(self.tokenizer.tokenize(prompt), np.int32)
            rids.append(self.add_request(ids, max_new_tokens, sampling,
                                         eod_id=eod))
        cb = None
        if token_callback is not None:
            def cb(rid, tok):
                token_callback(rid, np.asarray([tok]), None)
        results = self.run_to_completion(token_callback=cb)
        texts = []
        for prompt, rid in zip(prompts, rids):
            n_prompt = len(self.tokenizer.tokenize(prompt))
            new_ids = results[rid][n_prompt:].tolist()
            if eod is not None and eod in new_ids:
                new_ids = new_ids[: new_ids.index(eod)]
            texts.append(self.tokenizer.detokenize(new_ids))
        return texts

    # -- observability -----------------------------------------------------------
    def rpc_totals(self) -> Dict[str, int]:
        out = {"msgs_sent": 0, "msgs_recv": 0,
               "bytes_sent": 0, "bytes_recv": 0}
        for rep in self._reps:
            if rep.client is None:
                continue
            out["msgs_sent"] += rep.client.msgs_sent
            out["msgs_recv"] += rep.client.msgs_recv
            out["bytes_sent"] += rep.client.bytes_sent
            out["bytes_recv"] += rep.client.bytes_recv
        return out

    def stats_snapshot(self, include_dispatch: bool = False) -> Dict:
        restarts = self.supervisor_restarts()
        live = self._live()
        replicas = []
        for rep in self._reps:
            entry = {
                "idx": rep.idx, "state": rep.state,
                "params_version": 0, "reloads": 0,
                "incarnation": rep.incarnation,
                "steps": rep.steps,
                "attainment": round(rep.attainment(self.slo_ms), 4),
                "restarts": restarts.get(rep.idx, 0),
            }
            if rep.state != DEAD:
                entry.update({"active": rep.active,
                              "waiting": rep.waiting,
                              "pressure": round(rep.pressure, 4)})
            if rep.hist is not None and rep.hist.count:
                entry["interval_p50_ms"] = round(
                    rep.hist.percentile(50), 3)
                entry["interval_p99_ms"] = round(
                    rep.hist.percentile(99), 3)
            replicas.append(entry)
        out = {
            "engine": "fleet",
            "paged": True,
            "max_batch": self.max_batch,
            "active": sum(r.get("active", 0) for r in replicas),
            "waiting": sum(r.get("waiting", 0) for r in replicas),
            "fleet": {
                "replicas": replicas,
                "num_replicas": len(self._reps),
                "live_replicas": len(live),
                "policy": self.policy,
                "migrate": True,
                "autoscale": False,
                "slo_ms": self.slo_ms,
                "params_version": 0,
                "reload_pending": False,
                "process_backed": True,
                "affinity_entries": len(self._affinity),
                "tenant_affinity_entries": len(self._tenant_affinity),
                "supervisor_restarts": sum(restarts.values()),
                "rpc": self.rpc_totals(),
                **self.router_stats,
            },
        }
        if self.prefix_store is not None:
            out["fleet"]["prefix_store"] = self.prefix_store.stats()
        return out

    def export_fleet_gauges(self, registry=telemetry):
        """Server /metrics hook: per-replica labeled gauges + the
        supervisor restart counter — one scrape covers the fleet."""
        restarts = self.supervisor_restarts()
        lab = registry.labeled
        for rep in self._reps:
            r = str(rep.idx)
            registry.set_gauge(lab("fleet_replica_up", replica=r),
                               int(rep.state != DEAD))
            registry.set_gauge(
                lab("fleet_replica_attainment", replica=r),
                round(rep.attainment(self.slo_ms), 4))
            registry.set_gauge(
                lab("fleet_replica_active_slots", replica=r),
                rep.active if rep.state != DEAD else 0)
            registry.set_gauge(
                lab("fleet_replica_waiting", replica=r),
                rep.waiting if rep.state != DEAD else 0)
            registry.set_gauge(
                lab("fleet_supervisor_restarts", replica=r),
                restarts.get(rep.idx, 0))
        registry.set_gauge("fleet_supervisor_restarts_total",
                           sum(restarts.values()))
        if self.prefix_store is not None:
            st = self.prefix_store.stats()
            registry.set_gauge("fleet_prefix_store_entries",
                               st["entries"])
            registry.set_gauge("fleet_prefix_store_bytes",
                               st["bytes_used"])
            registry.set_gauge("fleet_prefix_store_hit_total",
                               st["hits"])

    def merged_trace(self) -> dict:
        """ONE Chrome trace across every replica process + the router:
        each worker's request-trace ring is pulled over RPC and merged
        with per-process pid offsets (the MegaScan per-rank-merge
        story, applied to serving)."""
        from megatronapp_tpu.trace.request_trace import (
            get_request_tracer, merge_process_traces,
        )
        rt = get_request_tracer()
        procs = [("router", rt.dump(), dict(rt._pid_names))]
        for rep in self._reps:
            if rep.state == DEAD or rep.client is None:
                continue
            try:
                t = rep.client.call("trace")
            except Exception:  # noqa: BLE001 — trace is best-effort
                continue
            procs.append((f"replica-{rep.idx}", t["records"],
                          t["pid_names"]))
        return merge_process_traces(procs)

    def audit(self):
        """Pool audit on every live replica (drill gate)."""
        for rep in self._live():
            rep.client.call("audit")

    # -- teardown -----------------------------------------------------------------
    def shutdown(self):
        if self.supervisor is not None:
            self.supervisor.stop()
            self.supervisor = None
        if self._supervisor_proc is not None:
            self._supervisor_proc.kill()
            self._supervisor_proc.wait(timeout=10)
            self._supervisor_proc = None
        for rep in self._reps:
            if rep.client is not None:
                try:
                    rep.client.call("shutdown")
                except Exception:  # noqa: BLE001 — dying anyway
                    pass
                rep.client.close()
                rep.client = None
            if rep.proc is not None:
                try:
                    rep.proc.kill()
                    rep.proc.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    pass


class _ProcessBackend:
    """Supervisor backend over a ProcessFleetRouter's worker table:
    alive = pid running AND heartbeat fresh; kill = SIGKILL + router
    failover; relaunch = respawn with a bumped incarnation (the router
    reattaches off the addr file). The in-process FleetRouter's backend
    lives in inference/fleet.py — both feed the SAME Supervisor."""

    def __init__(self, router: ProcessFleetRouter):
        self.router = router

    def indices(self) -> List[int]:
        return [r.idx for r in self.router._reps]

    def _rep(self, idx: int) -> _ProcReplica:
        return next(r for r in self.router._reps if r.idx == idx)

    def alive(self, idx: int) -> bool:
        from megatronapp_tpu.training.ft_integration import read_heartbeat
        rep = self._rep(idx)
        addr = read_addr(self.router.state_dir, idx)
        if addr is None:
            return False
        if rep.proc is not None and rep.incarnation == addr.get(
                "incarnation") and rep.proc.poll() is not None:
            return False
        try:
            os.kill(addr["pid"], 0)
        except (OSError, ProcessLookupError):
            return False
        hb = read_heartbeat(heartbeat_dir(self.router.state_dir, idx),
                            stale_after=self.router.stale_after)
        return bool(hb["alive"])

    def kill(self, idx: int):
        import signal
        addr = read_addr(self.router.state_dir, idx)
        if addr is not None:
            try:
                os.kill(addr["pid"], signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass
        with self.router._lock:
            self.router._fail_rep(self._rep(idx))

    def relaunch(self, idx: int, **hints):
        rep = self._rep(idx)
        incarnation = rep.incarnation + 1
        addr = read_addr(self.router.state_dir, idx)
        if addr is not None:
            incarnation = max(incarnation, addr["incarnation"] + 1)
        rep.proc = spawn_worker(self.router.state_dir, idx, incarnation,
                                extra_env=self.router._extra_env)
        wait_for_addr(self.router.state_dir, idx, incarnation)
        # The router's step loop reattaches on the incarnation bump.


def launch_threaded(state_dir: str, spec: dict, num_replicas: int = 2,
                    **router_kw):
    """Thread-backed fleet: the SAME wire frames, verbs, chaos window,
    and byte accounting over real loopback sockets, with the replica
    servers in daemon threads instead of OS processes — the fast tier-1
    smoke and the benchmark's cheap mode (subprocess workers each pay a
    full jax import). Returns (router, servers); callers stop the
    servers via router.shutdown()."""
    os.makedirs(state_dir, exist_ok=True)
    servers = []
    for i in range(num_replicas):
        write_spec(state_dir, i, spec)
        engine = build_engine_from_spec(spec)
        srv = ReplicaServer(engine, idx=i).start()
        _write_json_atomic(
            os.path.join(replica_dir(state_dir, i), "addr.json"),
            {"host": srv.addr[0], "port": srv.addr[1],
             "pid": os.getpid(), "incarnation": 0})
        servers.append(srv)
    router = ProcessFleetRouter.attach(state_dir, **router_kw)
    return router, servers


# ---------------------------------------------------------------------------
# Worker entrypoint.
# ---------------------------------------------------------------------------
def worker_main(argv=None) -> int:
    ap = __import__("argparse").ArgumentParser(
        description="fleet replica RPC worker (ISSUE 18)")
    ap.add_argument("--state-dir", required=True)
    ap.add_argument("--idx", type=int, required=True)
    ap.add_argument("--incarnation", type=int, default=0)
    args = ap.parse_args(argv)
    spec = read_spec(args.state_dir, args.idx)
    # Platform pin BEFORE any jax import (the image's sitecustomize
    # would otherwise select the tunneled TPU and hang a CPU drill).
    os.environ.setdefault("JAX_PLATFORMS",
                          spec.get("platform") or "cpu")
    from megatronapp_tpu.training.ft_integration import (
        FTConfig, HeartbeatMonitor,
    )
    hb = HeartbeatMonitor(FTConfig(
        heartbeat_dir=heartbeat_dir(args.state_dir, args.idx),
        heartbeat_write_interval=0.2))
    hb.start_section("setup")
    engine = build_engine_from_spec(spec)
    hb.start_section("step")
    server = ReplicaServer(engine, idx=args.idx, heartbeat=hb,
                           port=int(spec.get("port", 0)))
    _write_json_atomic(
        os.path.join(replica_dir(args.state_dir, args.idx),
                     "addr.json"),
        {"host": server.addr[0], "port": server.addr[1],
         "pid": os.getpid(), "incarnation": args.incarnation})
    print(f"replica {args.idx} incarnation {args.incarnation} serving "
          f"on {server.addr[0]}:{server.addr[1]} (pid {os.getpid()})",
          flush=True)
    server.serve_forever(beat_interval=0.25)
    return 0


if __name__ == "__main__":
    sys.exit(worker_main())
